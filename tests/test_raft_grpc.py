"""Raft over the real wire: FileStorage durability + a 3-node gRPC cluster."""

import asyncio
import os

import grpc
import pytest

from distributed_lms_raft_llm_tpu.proto import lms_pb2, rpc
from distributed_lms_raft_llm_tpu.raft import (
    Entry,
    FileStorage,
    RaftConfig,
    RaftNode,
    decode_command,
)
from distributed_lms_raft_llm_tpu.raft.grpc_transport import (
    GrpcTransport,
    RaftServicer,
)

FAST = RaftConfig(
    election_timeout_min=0.11, election_timeout_max=0.22, heartbeat_interval=0.05
)


def test_file_storage_roundtrip_and_truncate(tmp_path):
    path = str(tmp_path / "wal.jsonl")
    s = FileStorage(path, fsync=False)
    s.save_meta(3, 2)
    s.append_entries(1, [Entry(1, "a"), Entry(1, "b")])
    s.append_entries(3, [Entry(2, "c")])
    s.truncate_from(2)
    s.append_entries(2, [Entry(3, "d")])
    s.close()

    s2 = FileStorage(path, fsync=False)
    term, voted, entries, _, _ = s2.load()
    assert (term, voted) == (3, 2)
    assert [(e.term, e.command) for e in entries] == [(1, "a"), (3, "d")]
    s2.close()


def test_file_storage_survives_torn_tail(tmp_path):
    path = str(tmp_path / "wal.jsonl")
    s = FileStorage(path, fsync=False)
    s.save_meta(1, None)
    s.append_entries(1, [Entry(1, "a")])
    s.close()
    with open(path, "a") as f:
        f.write('{"t": "entry", "i": 2, "ter')  # crash mid-write
    s2 = FileStorage(path, fsync=False)
    term, voted, entries, _, _ = s2.load()
    assert term == 1 and len(entries) == 1
    # Records written after the torn tail must survive the NEXT restart too
    # (the torn line is truncated, not appended onto).
    s2.save_meta(7, 3)
    s2.append_entries(2, [Entry(7, "b")])
    s2.close()
    s3 = FileStorage(path, fsync=False)
    term, voted, entries, _, _ = s3.load()
    assert (term, voted) == (7, 3)
    assert [e.command for e in entries] == ["a", "b"]
    s3.close()


def test_file_storage_compaction(tmp_path):
    path = str(tmp_path / "wal.jsonl")
    s = FileStorage(path, fsync=False, compact_every_bytes=2000)
    for i in range(1, 60):
        s.append_entries(i, [Entry(1, f"cmd-{i}" * 5)])
    size = os.path.getsize(path)
    assert size < 20000  # compaction kept it bounded
    s2 = FileStorage(path, fsync=False)
    _, _, entries, _, _ = s2.load()
    assert len(entries) == 59
    s.close()
    s2.close()


@pytest.fixture()
def grpc_cluster(tmp_path):
    """Three RaftNodes, each behind a real aio gRPC server on localhost."""

    async def build():
        ids = [1, 2, 3]
        servers, nodes, servicers, addresses = {}, {}, {}, {}
        # First pass: bind ports.
        for i in ids:
            servers[i] = grpc.aio.server()
            port = servers[i].add_insecure_port("127.0.0.1:0")
            addresses[i] = f"127.0.0.1:{port}"
        for i in ids:
            storage = FileStorage(str(tmp_path / f"wal{i}.jsonl"), fsync=False)
            transport = GrpcTransport(addresses)
            kv = {}

            def make_cb(kv=kv):
                def cb(index, entry):
                    op, args = decode_command(entry.command)
                    if op == "SetVal":
                        kv[args["key"]] = args["value"]
                return cb

            node = RaftNode(i, ids, storage, transport, apply_cb=make_cb(),
                            config=FAST, tick_interval=0.01, seed=i)
            servicer = RaftServicer(node, addresses, kv=kv)
            rpc.add_RaftServiceServicer_to_server(servicer, servers[i])
            nodes[i] = node
            servicers[i] = servicer
            await servers[i].start()
            await node.start()
        return servers, nodes, servicers, addresses

    return build


def test_grpc_cluster_elects_and_replicates_setval(grpc_cluster):
    async def run():
        servers, nodes, servicers, addresses = await grpc_cluster()
        try:
            # Wait for a leader.
            leader = None
            for _ in range(300):
                leaders = [n for n in nodes.values() if n.is_leader]
                if leaders:
                    leader = leaders[0]
                    break
                await asyncio.sleep(0.02)
            assert leader is not None, "no leader over gRPC"

            # Client path: WhoIsLeader on a follower names the leader.
            follower_id = next(i for i in nodes if i != leader.node_id)
            async with grpc.aio.insecure_channel(addresses[follower_id]) as ch:
                stub = rpc.RaftServiceStub(ch)
                who = await stub.WhoIsLeader(lms_pb2.Empty(), timeout=5)
                assert who.leader_id == leader.node_id
                gl = await stub.GetLeader(lms_pb2.GetLeaderRequest(), timeout=5)
                assert gl.nodeAddress == addresses[leader.node_id]

            # SetVal against the leader commits and applies on a quorum.
            async with grpc.aio.insecure_channel(addresses[leader.node_id]) as ch:
                stub = rpc.RaftServiceStub(ch)
                setr = await stub.SetVal(
                    lms_pb2.SetValRequest(key="course", value="AOS"), timeout=10
                )
                assert setr.verdict
                getr = await stub.GetVal(lms_pb2.GetValRequest(key="course"), timeout=5)
                assert getr.verdict and getr.value == "AOS"
            await asyncio.sleep(0.3)
            applied_on = [i for i, s in servicers.items() if s.kv.get("course") == "AOS"]
            assert len(applied_on) == 3  # heartbeats propagate commit to all
        finally:
            for n in nodes.values():
                await n.stop()
            for s in servers.values():
                await s.stop(None)

    asyncio.run(run())
