"""Telemetry timeline (utils/timeline.py + utils/scrape.py), Prometheus
exposition, the continuous SLO burn-rate engine, the capacity-model
fitter, and the shared quantile helper.

Tier-1 guards here are deliberately cheap (the ~870 s budget is tight):
the sampler-overhead bound runs ~0.6 s of wall clock, everything else is
synthetic-time unit work. The end-to-end continuous-SLO acceptance run
rides the existing module-scoped semester-sim fixture in
tests/test_semester_sim.py instead of booting a second cluster.
"""

import asyncio
import importlib.util
import json
import re
import time
from pathlib import Path

import pytest

from distributed_lms_raft_llm_tpu.config import SimConfig, TelemetryConfig
from distributed_lms_raft_llm_tpu.sim.slo import (
    ContinuousSloEngine,
    evaluate_slos,
    stage_breakdown,
)
from distributed_lms_raft_llm_tpu.utils import metrics_registry
from distributed_lms_raft_llm_tpu.utils.healthz import HealthServer
from distributed_lms_raft_llm_tpu.utils.metrics import (
    LatencyHistogram,
    Metrics,
    percentile_of_sorted,
)
from distributed_lms_raft_llm_tpu.utils.scrape import ClusterScraper
from distributed_lms_raft_llm_tpu.utils.timeline import (
    Timeline,
    TimelineSampler,
    render_prometheus,
    snap_counter,
    snap_gauge,
    snap_hist,
    timeline_admin_get,
)

REPO = Path(__file__).resolve().parent.parent


def _load_script(name: str):
    spec = importlib.util.spec_from_file_location(
        name, REPO / "scripts" / f"{name}.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------- quantile helper


def test_percentile_of_sorted_small_n_agrees_everywhere():
    """Satellite: ONE index formula. p50 of two samples is the FIRST
    sample (the old snapshot() formula returned the max), and the
    histogram's percentile(), snapshot(), and stage_breakdown all agree
    with the helper at small n."""
    assert percentile_of_sorted([1.0, 2.0], 50) == 1.0
    assert percentile_of_sorted([1.0, 2.0], 95) == 2.0
    assert percentile_of_sorted([3.0], 99) == 3.0
    with pytest.raises(ValueError):
        percentile_of_sorted([], 50)

    h = LatencyHistogram()
    h.observe(2.0)
    h.observe(1.0)
    snap = h.snapshot()
    assert snap["p50_s"] == 1.0 == h.percentile(50)
    assert snap["p95_s"] == 2.0 == h.percentile(95)

    stages = stage_breakdown([{
        "spans": [
            {"name": "s", "duration_s": 1.0, "children": []},
            {"name": "s", "duration_s": 2.0, "children": []},
        ]
    }])
    assert stages["s"]["p50_s"] == 1.0
    assert stages["s"]["count"] == 2


def test_percentile_matches_nearest_rank_at_scale():
    vals = sorted(float(i) for i in range(1, 101))
    assert percentile_of_sorted(vals, 95) == 95.0
    assert percentile_of_sorted(vals, 50) == 50.0
    assert percentile_of_sorted(vals, 99) == 99.0


def test_window_percentile_is_sliding_window():
    """The recent ring answers windowed quantiles a cumulative reservoir
    can't: an old spike ages out."""
    h = LatencyHistogram()
    h._recent.append((time.monotonic() - 100.0, 9.0))  # aged-out spike
    h.observe(0.1)
    h.observe(0.2)
    assert h.window_percentile(10.0, 95) == 0.2  # spike outside window
    assert h.percentile(95) == 9.0 or h.percentile(95) == 0.2
    assert h.window_percentile(10.0, 95, now=time.monotonic() + 1000) is None


# ------------------------------------------------------------- timeline


def _snap(counters=None, gauges=None, hists=None):
    out = {"counters": counters or {}}
    if gauges:
        out["gauges"] = gauges
    if hists:
        out["latency"] = hists
    return out


def test_timeline_window_queries_and_reset():
    tl = Timeline()
    t = 1000.0
    tl.append(_snap({"reqs": 10}), t=t)           # baseline
    tl.append(_snap({"reqs": 30}), t=t + 1)       # +20
    tl.append(_snap({"reqs": 40},
                    gauges={"depth": 3.0},
                    hists={"lat": {"count": 2, "p95_s": 0.5,
                                   "mean_s": 0.3}}), t=t + 2)  # +10
    # Counter reset (restart): 40 -> 5 contributes 5, never -35.
    tl.append(_snap({"reqs": 5}), t=t + 3)
    assert tl.counter_delta("reqs", 1.5, now=t + 3) == 15
    rate = tl.counter_rate("reqs", 2.5, now=t + 3)
    assert rate is not None and rate > 0
    assert tl.counter_rate("reqs", 10.0, now=t + 500) is None
    assert tl.gauge_last("depth") == 3.0
    assert tl.hist_p95("lat", 10.0, now=t + 3) == 0.5
    assert tl.gauge_percentile("depth", 10.0, 95, now=t + 3) == 3.0
    # dcount: histogram observations attributed to the sample interval.
    point = tl.points()[2]
    assert point.hists["lat"]["dcount"] == 2.0

    tl.record_event("boom", "it happened", t=t + 2, level="fast")
    assert tl.events()[0]["kind"] == "boom"

    # Export -> rehydrate round trip preserves windowed rates.
    doc = tl.to_dict()
    back = Timeline.from_dict(doc)
    assert len(back.points()) == len(tl.points())
    assert back.events()[0]["detail"] == "it happened"
    r0 = tl.points()[1].rates()["reqs"]
    assert back.points()[1].rates()["reqs"] == pytest.approx(r0, rel=0.01)


def test_timeline_first_sample_seeds_baselines_only():
    """A timeline started against an already-warm process must not read
    the boot-era totals as a rate spike in its first window (the
    two-samples-for-a-rate rule)."""
    tl = Timeline()
    t = 1000.0
    tl.append(_snap({"reqs": 100000},
                    hists={"lat": {"count": 5000, "p95_s": 0.1}}), t=t)
    assert tl.counter_delta("reqs", 60.0, now=t) == 0
    assert tl.points()[0].hists["lat"]["dcount"] == 0.0
    tl.append(_snap({"reqs": 100003},
                    hists={"lat": {"count": 5002, "p95_s": 0.1}}), t=t + 1)
    assert tl.counter_delta("reqs", 60.0, now=t + 1) == 3
    assert tl.hist_rate("lat", 60.0, now=t + 1) == pytest.approx(2.0)


def test_snapshot_readers():
    snap = _snap({"a": 2}, gauges={"g": 1.5},
                 hists={"h": {"count": 1, "p95_s": 0.2}})
    assert snap_counter(snap, "a") == 2
    assert snap_counter(snap, "zzz") == 0
    assert snap_gauge(snap, "g") == 1.5
    assert snap_hist(snap, "h")["p95_s"] == 0.2
    assert snap_hist(snap, "zzz") == {}


def test_timeline_sampler_overhead_bound():
    """The watcher must stay ~free: ~25 samples of a realistically sized
    Metrics cost well under 100 ms of sampling work, and the wall budget
    of this whole test is ~1 s."""
    t0 = time.monotonic()
    m = Metrics()
    for i in range(20):
        m.inc(f"c{i}", i)
        m.set_gauge(f"g{i}", float(i))
    for i in range(8):
        h = m.hist(f"h{i}")
        for j in range(50):
            h.observe(0.001 * j)
    sampler = TimelineSampler(m, interval_s=0.02, max_points=64).start()
    time.sleep(0.55)
    sampler.stop()
    assert sampler.samples >= 10
    assert len(sampler.timeline.points()) == min(sampler.samples, 64)
    per_sample = sampler.overhead_s / sampler.samples
    assert per_sample < 0.005, (
        f"sampling cost {per_sample * 1e3:.2f} ms/sample — the telemetry "
        "plane is supposed to be invisible next to what it watches"
    )
    assert time.monotonic() - t0 < 5.0, "wall budget: keep this test cheap"


def test_sampler_rejects_bad_interval():
    with pytest.raises(ValueError):
        TimelineSampler(Metrics(), interval_s=0.0)


# --------------------------------------------------- prometheus round trip


_SAMPLE_RE = re.compile(
    r"^([A-Za-z_:][A-Za-z0-9_:]*)(?:\{([^}]*)\})?\s+(-?[0-9.eE+]+)$"
)


def parse_prometheus(text: str):
    """Minimal text-exposition parser: families {name: kind}, helps
    {name: help}, samples {(name, labels): value}. Raises on any line
    that is neither a comment nor a well-formed sample."""
    kinds, helps, samples = {}, {}, {}
    for line in text.splitlines():
        if not line.strip():
            continue
        if line.startswith("# HELP "):
            name, help_text = line[len("# HELP "):].split(" ", 1)
            helps[name] = help_text
        elif line.startswith("# TYPE "):
            name, kind = line[len("# TYPE "):].split(" ", 1)
            kinds[name] = kind
        else:
            m = _SAMPLE_RE.match(line)
            assert m, f"unparseable exposition line: {line!r}"
            samples[(m.group(1), m.group(2) or "")] = float(m.group(3))
    return kinds, helps, samples


def _declared_metrics():
    m = Metrics()
    m.inc("llm_requests", 7)
    m.set_gauge("storage_recovering", 1.0)
    h = m.hist("ttft")
    for v in (0.1, 0.2, 0.3, 0.4):
        h.observe(v)
    m.inc("scratch_adhoc_series")  # undeclared: TYPE yes, HELP no
    return m


def test_render_prometheus_round_trip():
    m = _declared_metrics()
    snap = m.snapshot()
    kinds, helps, samples = parse_prometheus(render_prometheus(snap))
    assert kinds["llm_requests"] == "counter"
    assert samples[("llm_requests", "")] == 7
    assert kinds["storage_recovering"] == "gauge"
    assert samples[("storage_recovering", "")] == 1.0
    # Histograms expose as Prometheus summaries: quantile samples +
    # _count/_sum, values matching the JSON snapshot exactly.
    assert kinds["ttft"] == "summary"
    assert samples[("ttft", 'quantile="0.95"')] == snap["latency"]["ttft"][
        "p95_s"
    ]
    assert samples[("ttft_count", "")] == 4
    assert samples[("ttft_sum", "")] == pytest.approx(1.0)
    # Name/help come from the registry declarations (single source).
    assert helps["llm_requests"] == metrics_registry.spec(
        "llm_requests"
    ).help
    # Undeclared ad-hoc series still export, but carry no HELP — only
    # registry-declared series are documented (and only they pass lint).
    assert kinds["scratch_adhoc_series"] == "counter"
    assert "scratch_adhoc_series" not in helps


def test_metrics_prom_endpoint_and_admin_timeline():
    """GET /metrics.prom serves text-plain exposition that parses, and
    GET /admin/timeline serves the sampler's ring; both on the same
    HealthServer the servers already run."""
    m = _declared_metrics()
    tl = Timeline()
    tl.append(m.snapshot(), t=time.time())

    async def admin_get(path):
        return timeline_admin_get(path, tl)

    async def run():
        hs = HealthServer(m, admin_get=admin_get)
        port = await hs.start()
        try:
            reader, writer = await asyncio.open_connection("127.0.0.1",
                                                           port)
            writer.write(b"GET /metrics.prom HTTP/1.1\r\nHost: x\r\n\r\n")
            await writer.drain()
            raw = await reader.read()
            writer.close()
            await writer.wait_closed()
            head, _, body = raw.partition(b"\r\n\r\n")
            assert b" 200 " in head.splitlines()[0]
            assert b"text/plain" in head
            kinds, _, samples = parse_prometheus(body.decode())
            assert samples[("llm_requests", "")] == 7
            assert kinds["ttft"] == "summary"

            reader, writer = await asyncio.open_connection("127.0.0.1",
                                                           port)
            writer.write(b"GET /admin/timeline HTTP/1.1\r\nHost: x\r\n\r\n")
            await writer.drain()
            raw = await reader.read()
            writer.close()
            await writer.wait_closed()
            head, _, body = raw.partition(b"\r\n\r\n")
            doc = json.loads(body)
            assert doc["ok"] and len(doc["timeline"]["points"]) == 1
            point = doc["timeline"]["points"][0]
            assert point["hists"]["ttft"]["p95_s"] == pytest.approx(0.4)
        finally:
            await hs.stop()

    asyncio.run(run())


# ------------------------------------------------------ cluster scraper


def test_cluster_scraper_merges_deltas_and_survives_restarts():
    node_a = {"counters": {"llm_requests": 100, "tutoring_degraded": 0}}
    node_b = {"counters": {"llm_requests": 50},
              "gauges": {"serving_queue_depth": 2.0},
              "latency": {"llm_ttft": {"count": 3, "p95_s": 0.9}}}
    snaps = {"a": node_a, "b": node_b}
    down = set()

    def src(name):
        return lambda: None if name in down else snaps[name]

    scraper = ClusterScraper(sources={"a": src("a"), "b": src("b")})
    t = 2000.0
    # First sight seeds baselines: boot-era counts are NOT a rate spike.
    scraper.poll(now=t)
    assert scraper.cluster.counter_delta("llm_requests", 60.0, now=t) == 0

    node_a["counters"]["llm_requests"] = 110      # +10
    node_b["counters"]["llm_requests"] = 55       # +5
    scraper.poll(now=t + 1)
    assert scraper.cluster.counter_delta("llm_requests", 1.5,
                                         now=t + 1) == 15

    # b restarts: unreachable one round, then counters wiped.
    down.add("b")
    node_a["counters"]["llm_requests"] = 120      # +10
    scraper.poll(now=t + 2)
    down.clear()
    node_b["counters"]["llm_requests"] = 4        # reset; contributes 4
    scraper.poll(now=t + 3)
    assert scraper.unreachable["b"] == 1
    assert scraper.cluster.counter_delta("llm_requests", 1.5,
                                         now=t + 3) == 14
    # Gauges merge worst-of; histograms merge worst-p95.
    assert scraper.cluster.gauge_last("serving_queue_depth") == 2.0
    assert scraper.cluster.hist_p95("llm_ttft", 60.0, now=t + 3) == 0.9
    export = scraper.export()
    assert export["node_count"] == 2
    assert set(export["nodes"]) == {"a", "b"}


def test_cluster_scraper_hist_count_stays_monotonic_across_worst_flips():
    """The merged block carries the worst node's percentiles but a
    cluster-cumulative count: when the slowest node flips between polls,
    dcount must reflect real new observations, not the count jump
    between two different nodes' reservoirs."""
    a = {"counters": {}, "latency": {"lat": {"count": 1000, "p95_s": 0.1}}}
    b = {"counters": {}, "latency": {"lat": {"count": 10, "p95_s": 0.9}}}
    scraper = ClusterScraper(sources={"a": lambda: a, "b": lambda: b})
    t = 4000.0
    scraper.poll(now=t)  # baseline (worst = b)
    # 2 new observations on a, 1 on b; worst flips to a.
    a["latency"]["lat"] = {"count": 1002, "p95_s": 2.0}
    b["latency"]["lat"] = {"count": 11, "p95_s": 0.9}
    scraper.poll(now=t + 1)
    # worst flips back to b; 1 more observation on each.
    a["latency"]["lat"] = {"count": 1003, "p95_s": 0.1}
    b["latency"]["lat"] = {"count": 12, "p95_s": 3.0}
    scraper.poll(now=t + 2)
    points = scraper.cluster.points()
    assert points[1].hists["lat"]["dcount"] == 3.0
    assert points[2].hists["lat"]["dcount"] == 2.0
    assert scraper.cluster.hist_rate("lat", 1.5, now=t + 2) == \
        pytest.approx(5.0 / 2.0)  # 5 real observations over 2 s of span
    # Percentile merge is still worst-of.
    assert points[1].hists["lat"]["p95_s"] == 2.0
    assert points[2].hists["lat"]["p95_s"] == 3.0


# ------------------------------------------- continuous burn-rate engine


def _engine(cfg=None, **kw):
    cfg = cfg or SimConfig(duration_s=16.0)
    cluster = Timeline()
    sim_metrics = Metrics()
    harness_metrics = Metrics()
    kw.setdefault("fast_window_s", 1.0)
    kw.setdefault("slow_window_s", 4.0)
    eng = ContinuousSloEngine(cfg, cluster, sim_metrics,
                              metrics=harness_metrics, **kw)
    return eng, cluster, sim_metrics, harness_metrics


def test_burn_engine_raises_and_clears_on_degraded_burst():
    """The multi-window state machine: a healthy phase stays silent, a
    full blackout raises the fast alert after `sustain` consecutive
    over-threshold windows, recovery clears it; fault classification
    separates expected alerts from false alarms."""
    eng, cluster, sim_metrics, harness_metrics = _engine()
    sim_metrics.hist("sim_ask_latency").observe(0.05)
    base = 3000.0
    req = deg = 0

    def tick(i, dreq, ddeg):
        nonlocal req, deg
        req += dreq
        deg += ddeg
        t = base + i * 0.25
        cluster.append(
            {"counters": {"llm_requests": req, "tutoring_degraded": deg,
                          "gate_reject": 0, "raft_tick_stalls": 0}}, t=t
        )
        eng.evaluate(at_s=i * 0.25, now=t)

    tick(0, 0, 0)                      # baseline
    for i in range(1, 9):              # healthy: traffic, no degrades
        tick(i, 2, 0)
    assert not eng.alerts
    for i in range(9, 17):             # blackout: everything degrades
        tick(i, 2, 2)
    fast = [a for a in eng.alerts if a.window == "fast"]
    assert fast, "a full blackout must raise the fast-window alert"
    assert fast[0].peak_burn >= 1.5
    assert fast[0].raised_at_s >= 0.25 * 10, "sustain: never on one sample"
    for i in range(17, 34):            # recovery: healthy again
        tick(i, 2, 0)
    assert fast[0].cleared_at_s is not None, "recovery must clear it"
    assert harness_metrics.snapshot()["counters"]["sim_burn_alerts"] >= 1
    events = [e["kind"] for e in cluster.events()]
    assert "slo_alert_raised" in events and "slo_alert_cleared" in events
    # Every SLO was evaluated in at least one window.
    assert all(eng.windows_evaluated[s] >= 1
               for s in ("answer_p95", "degraded_rate", "tick_stalls"))

    # Fault classification drives the verdict check both ways.
    blackout_window = (0.25 * 9, 0.25 * 17)
    eng.finish([blackout_window])
    assert all(a.during_fault for a in eng.alerts)
    ledger = {"losses": [], "acked_writes": 1, "ryw_violations": []}
    report = evaluate_slos(eng.cfg, {}, {}, sim_metrics.snapshot(), ledger,
                           continuous=eng.report())
    by_name = {c.name: c for c in report.checks}
    assert by_name["no_false_alarms"].ok
    assert by_name["burn_windows_evaluated"].ok

    eng.finish([])                     # no faults planned -> false alarm
    assert eng.false_alarms()
    report = evaluate_slos(eng.cfg, {}, {}, sim_metrics.snapshot(), ledger,
                           continuous=eng.report())
    assert not report.ok
    assert not {c.name: c for c in report.checks}["no_false_alarms"].ok


def test_burn_engine_quiet_window_holds_no_evidence():
    """No traffic in the window => no evaluation (None), never a spurious
    0-burn clear or raise."""
    eng, cluster, _, _ = _engine()
    assert eng._burn("degraded_rate", 1.0, now=5000.0) is None
    cluster.append({"counters": {"llm_requests": 0,
                                 "tutoring_degraded": 0}}, t=5000.0)
    cluster.append({"counters": {"llm_requests": 0,
                                 "tutoring_degraded": 0}}, t=5000.5)
    assert eng._burn("degraded_rate", 1.0, now=5000.5) == 0.0


def test_telemetry_config_validation():
    with pytest.raises(ValueError):
        TelemetryConfig(sample_interval_s=0.0)
    with pytest.raises(ValueError):
        TelemetryConfig(fast_window_s=60.0, slow_window_s=30.0)
    with pytest.raises(ValueError):
        SimConfig(telemetry_sample_s=0.0)


# ------------------------------------------------------- capacity model


def _capacity_export(saturate=True, tokens=True):
    points = []
    for i in range(1, 31):
        req = float(i)
        p95 = 0.2 if (not saturate or req <= 20) else 9.0
        gauges = {"serving_queue_depth": 0.0 if req <= 20 else req - 20}
        if tokens:
            gauges["serving_tokens_per_s"] = req * 128.0
        points.append({
            "t": 100.0 + i, "dt": 1.0,
            "rates": {"llm_requests": req},
            "gauges": gauges,
            "hists": {"answer_latency": {"count": i, "p95_s": p95}},
        })
    return {
        "node_count": 3,
        "cluster": {"points": [], "events": []},
        "nodes": {"tutoring": {"points": points, "events": []}},
    }


def test_fit_capacity_finds_the_slo_knee():
    telemetry = _load_script("telemetry")
    model = telemetry.fit_capacity(
        _capacity_export(), slo_p95_s=6.0, ceiling_tokens_per_s=61500.0
    )
    assert model["metric"] == "capacity_req_s_per_node_at_slo"
    assert model["source"] == "tutoring"
    assert model["slo_saturated"] is True
    # The knee is at 20 req/s; bin granularity may shave the top bin.
    assert 15.0 <= model["value"] <= 22.0
    assert model["p95_at_capacity_s"] <= 6.0
    util = model["utilization"]
    assert util is not None
    assert util["tokens_per_req"] == pytest.approx(128.0, rel=0.05)
    assert util["token_limited_req_s"] == pytest.approx(61500.0 / 128.0,
                                                        rel=0.05)
    assert model["queue_depth_p95"] > 0


def test_fit_capacity_unsaturated_is_a_lower_bound():
    telemetry = _load_script("telemetry")
    model = telemetry.fit_capacity(
        _capacity_export(saturate=False, tokens=False),
        slo_p95_s=6.0, ceiling_tokens_per_s=61500.0,
    )
    assert model["slo_saturated"] is False
    assert model["value"] == pytest.approx(30.0, rel=0.05)
    assert model["utilization"] is None


def test_capacity_cli_over_bench_record(tmp_path, capsys):
    """The acceptance path: a (synthetic) BENCH record with an embedded
    timeline -> `telemetry.py --capacity` -> one capacity-model JSON
    line with req/s-per-node-at-SLO."""
    telemetry = _load_script("telemetry")
    record = {
        "metric": "semester_sim_ask_p95_s",
        "timeline": _capacity_export(),
        "slos": {"stage_p95s": {"engine.batch": {"count": 5,
                                                 "p95_s": 0.012}}},
    }
    path = tmp_path / "record.json"
    path.write_text(json.dumps(record))
    rc = telemetry.main(["--capacity", str(path), "--slo-p95", "6.0"])
    assert rc == 0
    model = json.loads(capsys.readouterr().out.strip())
    assert model["metric"] == "capacity_req_s_per_node_at_slo"
    assert model["value"] > 0
    assert model["unit"] == "req/s/node"
    assert model["service_time_p95_s"] == pytest.approx(0.012)


# ------------------------------------------------------ trace_report diff


def test_trace_report_stage_diff(tmp_path, capsys):
    """Satellite: --diff renders a side-by-side per-stage p95 diff from
    two exports (BENCH record shape and bare mapping shape)."""
    trace_report = _load_script("trace_report")
    a = {"slos": {"stage_p95s": {
        "queue.wait": {"count": 10, "p50_s": 0.01, "p95_s": 0.05,
                       "max_s": 0.06},
        "engine.batch": {"count": 10, "p50_s": 0.02, "p95_s": 0.04,
                         "max_s": 0.05},
        "gate.check": {"count": 10, "p50_s": 0.001, "p95_s": 0.002,
                       "max_s": 0.01},
    }}}
    b = {
        "queue.wait": {"count": 12, "p50_s": 0.01, "p95_s": 0.40,
                       "max_s": 0.50},
        "engine.batch": {"count": 12, "p50_s": 0.02, "p95_s": 0.04,
                         "max_s": 0.05},
        "raft.commit": {"count": 12, "p50_s": 0.003, "p95_s": 0.004,
                        "max_s": 0.01},
    }
    pa, pb = tmp_path / "a.json", tmp_path / "b.json"
    pa.write_text(json.dumps(a))
    pb.write_text(json.dumps(b))
    rc = trace_report.main(["--diff", str(pa), str(pb)])
    assert rc == 0
    out = capsys.readouterr().out
    lines = out.splitlines()
    # Worst regression first; one-sided stages stay visible.
    assert "queue.wait" in lines[1]
    assert "+350.0ms" in lines[1] or "+" in lines[1]
    assert any("raft.commit" in ln and "new" in ln for ln in lines)
    assert any("gate.check" in ln and "gone" in ln for ln in lines)
    # Saved-trace shape: breakdown computed from spans.
    trace_doc = {"trace": {"spans": [
        {"name": "client.ask", "duration_s": 1.0,
         "children": [{"name": "queue.wait", "duration_s": 0.3,
                       "children": []}]},
    ]}}
    pt = tmp_path / "t.json"
    pt.write_text(json.dumps(trace_doc))
    stages = trace_report.load_stage_p95s(str(pt))
    assert stages["queue.wait"]["p95_s"] == pytest.approx(0.3)
