"""Speculative decoding inside the paged continuous-batching engine.

The unification safety property mirrors tests/test_spec.py: speculation
changes WHEN tokens are computed, never WHICH distribution they come from.
Greedy paged+spec streams must be bit-identical to the non-spec paged
engine AND to the bucketed `engine.generate` path (any transcript, ragged
window-scatter, or seen-mask bug shows up within a few tokens); the first
token of a verify window must be distribution-identical to the plain
step's sampled token. On top of exactness: mid-decode admission still
works while another slot is mid-verify-window, the step program compiles
once per (S, k, width) configuration across a multi-request session, and
the serving queue surfaces acceptance metrics.
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_lms_raft_llm_tpu.engine import (
    EngineConfig,
    PagedEngine,
    PagedQueue,
    SamplingParams,
    TutoringEngine,
)
from distributed_lms_raft_llm_tpu.engine.paged import (
    SlotState,
    _spec_step_program,
    _step_program,
)
from distributed_lms_raft_llm_tpu.engine.sampling import seen_mask_from_ids
from distributed_lms_raft_llm_tpu.models import registry
from distributed_lms_raft_llm_tpu.utils.guards import compile_count_guard
from distributed_lms_raft_llm_tpu.utils.metrics import Metrics

MAX_NEW = 8

PROMPTS = ["what is raft?", "hello world", "explain paging", "k"]


def make_config(**kw):
    kw.setdefault("sampling", SamplingParams.greedy(max_new_tokens=MAX_NEW))
    kw.setdefault("length_buckets", (16,))
    kw.setdefault("spec_tokens", 3)
    return EngineConfig(
        model="tiny",
        batch_buckets=(1, 2, 4),
        dtype=jnp.float32,
        **kw,
    )


class TestGreedyBitEquality:
    @pytest.mark.parametrize("spec_tokens", [1, 3])
    def test_matches_plain_paged_and_bucketed(self, spec_tokens):
        """Same params/seed, greedy: the spec paged engine must emit exactly
        what the plain paged engine and the bucketed engine emit."""
        plain_cfg = make_config(spec_tokens=0)
        expected = TutoringEngine(plain_cfg).answer_batch(list(PROMPTS))
        plain = PagedEngine(plain_cfg, slots=4)
        pr = [plain.submit(p) for p in PROMPTS]
        out_plain = plain.drain()
        assert [out_plain[r] for r in pr] == expected

        spec = PagedEngine(make_config(spec_tokens=spec_tokens), slots=4)
        sr = [spec.submit(p) for p in PROMPTS]
        out_spec = spec.drain()
        assert [out_spec[r] for r in sr] == expected

    def test_with_repetition_penalty(self):
        # Penalty 1.2 exercises the hypothetical seen-stack inside the
        # shared verifier THROUGH the paged transcript plumbing: a token
        # accepted mid-window must penalize the rest of the window.
        sp = SamplingParams(temperature=0.0, top_k=50, top_p=1.0,
                            repetition_penalty=1.2, max_new_tokens=12)
        cfg = make_config(sampling=sp, spec_tokens=0)
        expected = TutoringEngine(cfg).answer_batch(list(PROMPTS))
        spec = PagedEngine(make_config(sampling=sp), slots=4)
        rids = [spec.submit(p) for p in PROMPTS]
        out = spec.drain()
        assert [out[r] for r in rids] == expected

    def test_with_prompt_buckets_and_slot_churn(self):
        """Per-prompt prefill buckets + slot reuse: 5 requests churn through
        2 slots, transcripts from evicted occupants must not leak into the
        next occupant's drafts (stale-anchor regression)."""
        cfg = make_config(length_buckets=(4, 8, 16), spec_tokens=0)
        prompts = list(PROMPTS) + ["k v"]
        expected = TutoringEngine(cfg).answer_batch(prompts)
        spec = PagedEngine(
            make_config(length_buckets=(4, 8, 16)), slots=2, chunk=2
        )
        rids = [spec.submit(p) for p in prompts]
        out = spec.drain()
        assert [out[r] for r in rids] == expected

    def test_with_kv_quant(self):
        cfg = make_config(spec_tokens=0, kv_quant=True)
        expected = TutoringEngine(cfg).answer_batch(list(PROMPTS[:2]))
        spec = PagedEngine(make_config(kv_quant=True), slots=2)
        rids = [spec.submit(p) for p in PROMPTS[:2]]
        out = spec.drain()
        assert [out[r] for r in rids] == expected

    def test_pipelined_outputs_match_serialized(self):
        """inflight=2 (dispatch N+1 before reading N) with ragged per-slot
        window advances must still produce byte-identical answers."""
        cfg = make_config()
        ser = PagedEngine(cfg, slots=2, inflight=1, chunk=2)
        rs = [ser.submit(p) for p in PROMPTS]
        out_ser = ser.drain()
        pipe = PagedEngine(cfg, slots=2, inflight=2, chunk=2)
        rp = [pipe.submit(p) for p in PROMPTS]
        out_pipe = pipe.drain()
        assert [out_pipe[r] for r in rp] == [out_ser[r] for r in rs]


def test_mid_verify_window_admission_completes_without_waiting():
    """A request submitted while another slot is mid-verify-window joins at
    the next chunk boundary and finishes within its own budget."""
    paged = PagedEngine(make_config(), slots=2, chunk=2)
    paged.submit("a long question about distributed consensus and logs")
    for _ in range(2):
        paged.step()  # A is now mid-decode, between verify windows
    b = paged.submit("b")
    finished = {}
    steps_after_b = 0
    while paged.has_work and steps_after_b < 3 * MAX_NEW:
        steps_after_b += 1
        for rid, _ in paged.step():
            finished.setdefault(rid, steps_after_b)
        if steps_after_b == 1:
            in_slots = {r.rid for r in paged._slot_req if r is not None}
            assert b in in_slots or b in finished
    assert b in finished
    # Each chunk=2 dispatch advances >= 2 windows of >= 1 token each, so B
    # needs at most ceil(MAX_NEW / 2) decode dispatches (+ admission +
    # pipelined-reap slack) — it did not wait for A's remaining decode.
    assert finished[b] <= MAX_NEW // 2 + 3


def test_first_window_token_matches_plain_step_distribution():
    """Distribution identity through the paged integration (mirrors
    tests/test_spec.py's verifier test, but through the transcript ->
    drafts -> ragged forward -> verify pipeline): over S identical slots,
    the FIRST token a verify window emits must be distributed exactly like
    the plain step's sampled token for the same prefix."""
    family, cfg = registry.resolve("tiny", jnp.float32)
    params = family.init_params(jax.random.key(0), cfg)
    sampling = SamplingParams(temperature=0.7, top_k=16, top_p=0.9,
                              repetition_penalty=1.2, max_new_tokens=8)
    s_slots, t0, width, k = 1500, 6, 16, 3
    rng = np.random.default_rng(0)
    row = rng.integers(1, cfg.vocab_size, t0)
    row[3:5] = row[0:2]  # a repeated bigram so the drafter finds anchors
    ids = jnp.asarray(np.tile(row, (s_slots, 1)), jnp.int32)
    pending = jnp.asarray(int(row[1]), jnp.int32)  # plausible next token

    cache = family.init_cache(cfg, s_slots, width, dtype=cfg.dtype)
    _, cache = family.forward(params, cfg, ids, cache=cache)
    cache = cache._replace(length=jnp.full((s_slots,), t0, jnp.int32))
    seen = seen_mask_from_ids(
        ids, jnp.ones((s_slots, t0), bool), cfg.vocab_size
    )
    seen = seen | jax.nn.one_hot(
        jnp.full((s_slots,), pending), cfg.vocab_size, dtype=jnp.bool_
    )
    transcript = jnp.zeros((s_slots, width), jnp.int32)
    transcript = transcript.at[:, :t0].set(ids)
    transcript = transcript.at[:, t0].set(pending)
    key_shape = jax.random.key_data(jax.random.key(0)).shape
    state = SlotState(
        cache=cache,
        tok=jnp.full((s_slots,), pending, jnp.int32),
        active=jnp.ones((s_slots,), bool),
        seen=seen,
        transcript=transcript,
        staged=jnp.zeros((s_slots,), bool),
        stage_cursor=jnp.zeros((s_slots,), jnp.int32),
        stage_len=jnp.ones((s_slots,), jnp.int32),
        stage_seq=jnp.zeros((s_slots,), jnp.int32),
        stage_rng=jnp.zeros((s_slots,) + key_shape, jnp.uint32),
    )

    statics = dict(cfg=cfg, sampling=sampling, eos_id=-1, pad_id=-1,
                   model=family, chunk=1)
    _, toks, _ = _step_program(params, state, jax.random.key(7), **statics)
    ref = np.asarray(toks)[0]  # [S] plain-step samples
    _, emitted, counts, _ = _spec_step_program(
        params, state, jax.random.key(8), spec_tokens=k, **statics
    )
    counts = np.asarray(counts)[0]
    assert (counts >= 1).all()
    got = np.asarray(emitted)[0, :, 0]  # [S] first window emission

    support = sorted(set(ref.tolist()) | set(got.tolist()))
    f_ref = np.array([(ref == s).mean() for s in support])
    f_got = np.array([(got == s).mean() for s in support])
    # 1500 trials/side: binomial std <= ~0.013 per bin; allow ~5 sigma.
    np.testing.assert_allclose(f_got, f_ref, atol=0.065)


def test_stochastic_session_plausible_and_observable():
    """A stochastic multi-request session completes, stays within budget,
    and reports acceptance stats (windows >= 1 token each, ceiling k+1)."""
    sp = SamplingParams.reference_defaults(max_new_tokens=MAX_NEW)
    eng = PagedEngine(make_config(sampling=sp), slots=2, chunk=2)
    rids = [eng.submit(f"the the the question {i}") for i in range(5)]
    out = eng.drain()
    assert all(isinstance(out[r], str) for r in rids)
    windows, emitted = eng.pop_spec_stats()
    assert windows > 0
    assert windows <= emitted <= windows * (eng.spec + 1)
    assert eng.pop_spec_stats() == (0, 0)  # drained


def test_step_program_compiles_once_per_width():
    """No silent per-step recompiles: the spec step program compiles
    exactly once per (S, k, width) — S and k are fixed per engine, so once
    per width — during warmup, and a live session that churns slots,
    rebuilds at both widths, and grows the cache mid-batch adds ZERO
    compilations (historically the spelling of replicated shardings
    differed between the install/grow/step producers, so warmup's compile
    did not cover the live handoffs — see paged._state_spec)."""
    eng = PagedEngine(
        make_config(length_buckets=(4, 16)), slots=2, chunk=2
    )
    assert len(eng.widths) == 2
    eng.warmup()
    programs = (eng._step, eng._install, eng._prefill, eng._grow)
    assert programs[0]._cache_size() == len(eng.widths)
    short, lng = "k v", "a long question about raft elections and logs"
    # The reusable runtime guard (utils/guards.py) generalizes this
    # assertion: zero new programs across the whole live session.
    with compile_count_guard(*programs, what="live paged session"):
        eng.submit(short)
        eng.step()       # running at the narrow width
        eng.submit(lng)  # grows the live cache mid-batch
        eng.drain()
        for prompt in (short, lng, short):  # idle rebuilds at both widths
            eng.submit(prompt)
        eng.drain()


def test_dead_slot_emits_no_filler_when_pad_differs_from_eos():
    """A slot inactive from admission (first sampled token is eos) emits
    zero-count windows — the spec reap must return an empty answer even
    when pad != eos (no filler misread as content)."""
    paged = PagedEngine(make_config(), slots=2)
    paged.tokenizer.pad_id = 0
    assert paged.tokenizer.eos_id != 0
    real_prefill = paged._prefill

    def eos_first(params, ids, true_len, rng):
        cache, _first, seen = real_prefill(params, ids, true_len, rng)
        return cache, jnp.asarray(paged.tokenizer.eos_id, jnp.int32), seen

    paged._prefill = eos_first
    rid = paged.submit("anything at all")
    out = paged.drain()
    assert out[rid] == paged.tokenizer.decode([])


def test_paged_queue_reports_spec_metrics():
    """The default server path surfaces speculation: PagedQueue feeds the
    spec_tokens_per_window gauge and spec_accepted_tokens counter from the
    engine's reap-time stats."""
    metrics = Metrics()
    engine = PagedEngine(make_config(), slots=2, chunk=2)

    async def run():
        q = PagedQueue(engine, metrics=metrics)
        await q.start()
        answers = await asyncio.gather(
            *[q.submit(f"query number {i}") for i in range(4)]
        )
        await q.close()
        return answers

    answers = asyncio.run(run())
    assert len(answers) == 4
    snap = metrics.snapshot()
    tpw = snap["gauges"]["spec_tokens_per_window"]
    assert 1.0 <= tpw <= engine.spec + 1
    assert snap["counters"]["spec_accepted_tokens"] >= 0
    assert metrics.hist("ttft").snapshot()["count"] == 4


def test_spec_overhang_respects_position_table():
    # tiny's position table is 64. With max_new=50 and k=4 the prompt
    # bucket must shrink by the window's k-1 overhang so the widest
    # verify window stays inside the table; a budget leaving no prompt
    # room at all is rejected loudly.
    eng = PagedEngine(
        make_config(sampling=SamplingParams.greedy(max_new_tokens=50),
                    spec_tokens=4),
        slots=2,
    )
    assert eng.bucket == 64 - 50 - 3
    assert eng.widths[-1] == eng.bucket + 50 + 3 <= 64
    rid = eng.submit("a prompt much longer than eleven byte-tokens")
    assert isinstance(eng.drain()[rid], str)
    with pytest.raises(ValueError, match="no room"):
        PagedEngine(
            make_config(sampling=SamplingParams.greedy(max_new_tokens=62),
                        spec_tokens=4),
            slots=2,
        )
