"""Resumable streamed tutoring (StreamLLMAnswer) + session prefix pins.

The streaming contract under test, end to end:

- chunk offsets count tokens and are monotone and gap-free from offset 0
  (or the resume offset) through the final chunk;
- the final chunk's digest is the sha256 of the STRIPPED full answer —
  byte-identical to what the unary GetLLMAnswer returns for the same
  query, so a client can verify a spliced transcript no matter how many
  failovers produced it;
- a mid-stream node loss makes the pool RESUME at the delivered offset
  on the next candidate (never restart, never fork): zero duplicate and
  zero dropped tokens across the failover;
- a session turn publishes its transcript into the radix prefix cache
  and session-pins it, so turn N+1 admits with a shared-prefix hit; the
  pin survives eviction pressure while live and becomes ordinary LRU
  content once its TTL lapses or the session is released.
"""

import asyncio
import hashlib

import grpc
import jax.numpy as jnp
import pytest

from distributed_lms_raft_llm_tpu.engine import (
    BatchingQueue,
    EngineConfig,
    PagedEngine,
    PagedQueue,
    SamplingParams,
)
from distributed_lms_raft_llm_tpu.engine.batcher import split_stream_tokens
from distributed_lms_raft_llm_tpu.engine.prefix_cache import PrefixCache
from distributed_lms_raft_llm_tpu.lms.tutoring_pool import (
    TutoringPool,
    affinity_key,
)
from distributed_lms_raft_llm_tpu.proto import lms_pb2, rpc
from distributed_lms_raft_llm_tpu.serving.tutoring_server import (
    TutoringService,
)
from distributed_lms_raft_llm_tpu.sim.cluster import EchoEngine
from distributed_lms_raft_llm_tpu.utils.faults import FaultInjector
from distributed_lms_raft_llm_tpu.utils.metrics import Metrics


# ------------------------------------------------- prefix-cache session pins


def ints(n, start=0):
    return list(range(start, start + n))


def test_session_pin_survives_eviction_pressure():
    """Tier order under pressure: the unpinned LRU leaf goes first; a
    live session pin holds its path resident even though it is older."""
    pc = PrefixCache(block_tokens=2, max_blocks=4)
    pc.insert(ints(4), lambda i: ("a", i))          # 2 blocks (oldest)
    assert pc.pin_session("sess", ints(4), ttl_s=60.0, now=0.0) == 2
    pc.insert(ints(4, 100), lambda i: ("b", i))     # 2 blocks
    pc.insert(ints(4, 200), lambda i: ("c", i))     # 2 blocks -> 6 > 4
    freed = pc.evict_to_budget(now=1.0)
    assert freed == 2 and pc.blocks_used == 4
    assert pc.lookup(ints(4) + [9]).tokens == 4, "pinned path evicted"
    assert pc.lookup(ints(4, 100) + [9]).tokens == 0, "LRU leaf survived"
    assert pc.session_count == 1
    assert pc.session_pinned_blocks() == 2


def test_ttl_expired_session_pin_is_evictable():
    """Once the TTL lapses the transcript is ordinary LRU content: the
    same pressure that spared it live now evicts it first."""
    pc = PrefixCache(block_tokens=2, max_blocks=4)
    pc.insert(ints(4), lambda i: ("a", i))
    assert pc.pin_session("sess", ints(4), ttl_s=5.0, now=0.0) == 2
    pc.insert(ints(4, 100), lambda i: ("b", i))
    pc.lookup(ints(4, 100) + [9])  # touch b: the expired pin is LRU
    pc.insert(ints(4, 200), lambda i: ("c", i))
    freed = pc.evict_to_budget(now=10.0)  # past the pin's expiry
    assert freed == 2 and pc.session_count == 0
    assert pc.lookup(ints(4) + [9]).tokens == 0, (
        "TTL-expired session path must evict under pressure"
    )
    assert pc.lookup(ints(4, 100) + [9]).tokens == 4


def test_all_pinned_forces_release_of_soonest_expiry():
    """Tier 3: when every evictable leaf is session-pinned, the session
    nearest its TTL loses its residency guarantee — never the one with
    the most life left."""
    pc = PrefixCache(block_tokens=2, max_blocks=2)
    pc.insert(ints(4), lambda i: ("a", i))
    pc.insert(ints(4, 100), lambda i: ("b", i))
    assert pc.pin_session("long", ints(4), ttl_s=600.0, now=0.0) == 2
    assert pc.pin_session("short", ints(4, 100), ttl_s=5.0, now=0.0) == 2
    assert pc.evict_to_budget(now=1.0) == 2
    assert pc.lookup(ints(4) + [9]).tokens == 4
    assert pc.lookup(ints(4, 100) + [9]).tokens == 0
    assert pc.session_count == 1


def test_release_and_repin_move_the_pin():
    pc = PrefixCache(block_tokens=2, max_blocks=64)
    pc.insert(ints(8), lambda i: ("a", i))
    # Turn 1 pins the short transcript; turn 2 re-pins the longer one
    # (same session), moving the pin and refreshing the TTL.
    assert pc.pin_session("s", ints(4), ttl_s=60.0, now=0.0) == 2
    assert pc.pin_session("s", ints(8), ttl_s=60.0, now=1.0) == 4
    assert pc.session_count == 1
    assert pc.release_session("s")
    assert not pc.release_session("s")  # already gone
    assert pc.session_pinned_blocks() == 0


# --------------------------------------------------------- real-gRPC helpers


async def _start_tutoring(node_id, delay_s=0.002):
    metrics = Metrics()
    queue = BatchingQueue(EchoEngine(delay_s), max_batch=4,
                          max_wait_ms=1.0, metrics=metrics)
    await queue.start()
    server = grpc.aio.server()
    service = TutoringService(queue, metrics, node_id=node_id)
    rpc.add_TutoringServicer_to_server(service, server)
    port = server.add_insecure_port("127.0.0.1:0")
    await server.start()
    return {
        "server": server, "queue": queue, "metrics": metrics,
        "service": service, "address": f"127.0.0.1:{port}",
    }


async def _stop_tutoring(rec):
    await rec["server"].stop(None)
    await rec["queue"].close()


def _check_contract(chunks, start=0):
    """Assert monotone gap-free offsets from `start` and exactly one
    final chunk; returns (assembled text, final digest)."""
    assert chunks, "stream yielded nothing"
    delivered = start
    for ch in chunks:
        assert ch.success
        assert ch.offset == delivered, (
            f"offset gap: chunk at {ch.offset}, delivered {delivered}"
        )
        delivered += ch.count
    assert [c.final for c in chunks].count(True) == 1
    assert chunks[-1].final
    return "".join(c.text for c in chunks), chunks[-1].digest


def test_streamed_answer_equals_unary_over_grpc():
    """Wire-level parity: the assembled stream is byte-identical to the
    unary answer for the same query, the final digest commits to it, and
    a resume_offset=K call replays exactly the token suffix [K:]."""
    async def run():
        node = await _start_tutoring("solo")
        channel = grpc.aio.insecure_channel(node["address"])
        stub = rpc.TutoringStub(channel)
        q = "what is a resumable stream?"
        try:
            unary = await stub.GetLLMAnswer(
                lms_pb2.QueryRequest(token="tok", query=q), timeout=10.0
            )
            assert unary.success
            chunks = []
            async for ch in stub.StreamLLMAnswer(
                lms_pb2.StreamRequest(token="tok", query=q), timeout=10.0
            ):
                chunks.append(ch)
            full, digest = _check_contract(chunks)
            assert full.strip() == unary.response
            assert digest == hashlib.sha256(
                full.strip().encode()).hexdigest()
            # Deterministic regeneration: resuming at offset 2 delivers
            # exactly the token suffix, same digest (same full answer).
            toks = split_stream_tokens(full)
            assert len(toks) > 2, "answer too short to exercise resume"
            resumed = []
            async for ch in stub.StreamLLMAnswer(
                lms_pb2.StreamRequest(token="tok", query=q,
                                      resume_offset=2),
                timeout=10.0,
            ):
                resumed.append(ch)
            tail, rdigest = _check_contract(resumed, start=2)
            assert tail == "".join(toks[2:])
            assert rdigest == digest
            # A session turn registers in the node's transcript store
            # (the session_active gauge the dashboard rows read).
            async for ch in stub.StreamLLMAnswer(
                lms_pb2.StreamRequest(token="tok", query=q,
                                      session_id="sess-e2e"),
                timeout=10.0,
            ):
                pass
            snap = node["metrics"].snapshot()["gauges"]
            assert snap["session_active"] == 1.0
        finally:
            await channel.close()
            await _stop_tutoring(node)

    asyncio.run(run())


def test_mid_stream_kill_resumes_at_offset_over_grpc():
    """Chaos `error` fault on the affinity node: the stream breaks AFTER
    its first delivered chunk (too late to hedge or restart), and the
    pool resumes on the second node at the delivered offset — the client
    sees one monotone gap-free stream whose digest still matches the
    unary answer, with zero duplicated and zero dropped tokens."""
    async def run():
        nodes = [await _start_tutoring("tutA"),
                 await _start_tutoring("tutB")]
        metrics = Metrics()
        injector = FaultInjector()
        pool = TutoringPool([n["address"] for n in nodes],
                            metrics=metrics, fault_injector=injector,
                            hedge_after_s=0.0)
        try:
            q = "explain the raft election protocol in detail please?"
            winner = pool.rendezvous_order(affinity_key(q))[0]
            injector.configure(winner.fault_target(), error=1.0)
            chunks = []
            async for ch in pool.forward_stream(q, "tok"):
                chunks.append(ch)
            full, digest = _check_contract(chunks)
            snap = metrics.snapshot()["counters"]
            assert snap.get("stream_resumes", 0) >= 1, (
                "mid-stream loss must be survived by resuming, "
                "not by luck"
            )
            # Parity with the unary path once the fault is gone (the
            # echo engine regenerates the same answer on any node).
            injector.clear(winner.fault_target())
            answer, _served = await pool.forward(q, "tok")
            assert full.strip() == answer.response
            assert digest == hashlib.sha256(
                full.strip().encode()).hexdigest()
        finally:
            await pool.close()
            for n in nodes:
                await _stop_tutoring(n)

    asyncio.run(run())


# -------------------------------------------- paged engine: greedy + session


def _tiny_paged(metrics, **kw):
    cfg = EngineConfig(
        model="tiny",
        sampling=SamplingParams.greedy(max_new_tokens=8),
        # 56 = the tiny position table (64) minus max_new: the largest
        # bucket the engine admits without tail-truncating the prompt.
        # The 32 bucket gives plan_partial a suffix window a turn-2
        # splice fits into (prefix_used + suffix_bucket <= bucket).
        length_buckets=(16, 32, 56), batch_buckets=(1, 2, 4),
        dtype=jnp.float32,
    )
    kw.setdefault("prefix_cache_blocks", 64)
    engine = PagedEngine(cfg, slots=2, chunk=2, prefix_cache=True,
                         prefix_block_tokens=4, **kw)
    return engine, PagedQueue(engine, metrics=metrics)


def test_paged_stream_is_bit_equal_to_unary():
    """The real serving shape (tiny paged engine, greedy): incremental
    token-yield streaming assembles to the byte-exact unary answer for
    the same query, and the final digest commits to it."""
    metrics = Metrics()
    engine, queue = _tiny_paged(metrics)

    async def run():
        await queue.start()
        service = TutoringService(queue, metrics, node_id="paged")
        try:
            q = "what is paging?"
            unary = await service.GetLLMAnswer(
                lms_pb2.QueryRequest(token="tok", query=q), None
            )
            assert unary.success
            chunks = []
            async for ch in service.StreamLLMAnswer(
                lms_pb2.StreamRequest(token="tok", query=q), None
            ):
                chunks.append(ch)
            full, digest = _check_contract(chunks)
            assert full.strip() == unary.response, (
                "greedy streamed answer must be bit-equal to unary"
            )
            assert digest == hashlib.sha256(
                full.strip().encode()).hexdigest()
        finally:
            await queue.close()

    asyncio.run(run())


def test_session_turn2_admits_with_pinned_prefix_hit():
    """Conversational acceptance at the queue level, where prompts fit
    the tiny engine's 56-token window un-truncated (the service's full
    prompt template overflows it — at that scale the session mechanism
    is exercised by the sim via verbatim repeats instead): turn 1's
    transcript is published and session-pinned, and turn 2 — whose
    prompt extends it exactly the way the server frames follow-ups —
    admits with a shared-prefix cache hit."""
    metrics = Metrics()
    engine, queue = _tiny_paged(metrics)

    async def stream(prompt):
        return [d async for d in queue.submit_stream(
            prompt, session=("sess-1", 30.0)
        )]

    async def run():
        await queue.start()
        try:
            t1 = "Q: what is raft consensus?\nA:"
            deltas = await stream(t1)
            assert deltas and deltas[-1].final
            ans1 = deltas[-1].full_text
            assert ans1
            count, blocks = engine.session_pin_stats()
            assert count == 1 and blocks > 0, (
                "turn 1 must leave its transcript session-pinned"
            )
            before = metrics.snapshot()["counters"].get(
                "prefix_cache_hit_tokens", 0)
            # Follow-up framing, exactly like the server: the new
            # question appends to the verbatim turn-1 prompt + answer.
            deltas2 = await stream(t1 + ans1 + "\nQ: why leaders?\nA:")
            assert deltas2 and deltas2[-1].final
            snap = metrics.snapshot()
            assert snap["counters"]["prefix_cache_hit_tokens"] > before, (
                "turn 2 must admit with a prefix-cache hit on the "
                "pinned turn-1 transcript"
            )
            assert snap["gauges"]["session_pinned_blocks"] > 0
        finally:
            await queue.close()

    asyncio.run(run())
