"""The background bulk-scoring tenant (engine/scoring.py + the queue
co-scheduler in engine/batcher.py).

Claims pinned here:

- score numerics are pad/batch-invariant: per-text logprobs are equal
  batched-vs-singleton across batch AND length buckets, and on the sp>1
  ring-attention path (CPU mesh);
- `score()` reports truncation per item (and the manager counts it in
  `score_truncated_texts`) instead of silently scoring prefixes;
- the score program is a first-class inventoried program: a warmed
  scoring-enabled session runs a bulk job with ZERO live compiles and
  `expected_from_inventory` exact equality holds (both engines); a
  scoring-disabled bucketed engine is still rejected loudly;
- the co-scheduler admits quanta only while nothing interactive is
  pending, and an interactive request arriving mid-quantum waits at most
  ONE quantum before its prefill dispatches — measured and recorded as
  `score_preempt_wait_ms`;
- the fleet router's background route places bulk jobs OFF the hot
  affinity nodes.
"""

import asyncio
import time

import jax.numpy as jnp
import numpy as np
import pytest

from distributed_lms_raft_llm_tpu.engine import (
    BatchingQueue,
    EngineConfig,
    PagedEngine,
    PagedQueue,
    SamplingParams,
    ScoringManager,
    TutoringEngine,
)
from distributed_lms_raft_llm_tpu.engine.scoring import score_admin_get
from distributed_lms_raft_llm_tpu.utils.guards import (
    InventoryMismatchError,
    compile_count_guard,
    expected_from_inventory,
)
from distributed_lms_raft_llm_tpu.utils.metrics import Metrics


def tiny_tutoring(**kw):
    kw.setdefault("model", "tiny")
    kw.setdefault("sampling", SamplingParams(max_new_tokens=4))
    kw.setdefault("length_buckets", (16, 32))
    kw.setdefault("batch_buckets", (1, 2))
    kw.setdefault("dtype", jnp.float32)
    kw.setdefault("param_dtype", jnp.float32)
    return TutoringEngine(EngineConfig(**kw))


def tiny_paged(**kw):
    kw.setdefault("model", "tiny")
    kw.setdefault("sampling", SamplingParams.greedy(max_new_tokens=4))
    kw.setdefault("length_buckets", (4, 16))
    kw.setdefault("batch_buckets", (1, 2))
    kw.setdefault("dtype", jnp.float32)
    kw.setdefault("scoring", True)
    return PagedEngine(EngineConfig(**kw), slots=2, chunk=2)


# ---------------------------------------------------------- numerics


class TestScoreNumerics:
    def test_batched_equals_singleton_across_buckets(self):
        """Pad invariance: a text's logprob must not depend on which
        (batch, length) bucket its companions forced it into."""
        eng = tiny_tutoring()
        texts = [
            "a",                                     # 16-bucket, short
            "the raft consensus algorithm elects a leader and "
            "replicates a log across the cluster members",  # 32-bucket
            "a quorum is a majority of the members",
            "logs",
        ]
        batched = eng.score(texts)  # mixed lengths -> widest bucket
        for text, got in zip(texts, batched):
            [alone] = eng.score([text])  # smallest admissible buckets
            assert alone["tokens"] == got["tokens"]
            np.testing.assert_allclose(got["logprob"], alone["logprob"],
                                       rtol=1e-4, atol=1e-4)

    def test_ring_sharded_score_matches_dense_with_truncation(self):
        """The sp>1 ring-attention path on the CPU mesh agrees with the
        dense forward, truncation flags included."""
        dense = tiny_tutoring()
        ring = tiny_tutoring(sp=2)
        assert ring.mesh.shape["sp"] == 2
        long_text = " ".join(["leader election term"] * 40)  # > 32 toks
        texts = ["the leader replicates logs", long_text]
        a = dense.score(texts)
        b = ring.score(texts)
        for ra, rb in zip(a, b):
            assert ra["truncated"] == rb["truncated"]
            assert ra["tokens"] == rb["tokens"]
            np.testing.assert_allclose(ra["logprob"], rb["logprob"],
                                       rtol=1e-4, atol=1e-4)
        assert a[0]["truncated"] is False
        assert a[1]["truncated"] is True

    def test_truncated_flag_marks_prefix_scores(self):
        eng = tiny_tutoring(length_buckets=(8,))
        long_text = " ".join(["raft"] * 30)
        short_text = "raft"  # under the 8-token bucket in any tokenizer
        res = eng.score([short_text, long_text])
        assert res[0]["truncated"] is False
        assert res[1]["truncated"] is True
        # The truncated score really is the prefix's score.
        limit_toks = eng.tokenizer.encode(long_text)[:8]
        [prefix] = eng.score([eng.tokenizer.decode(limit_toks)])
        assert prefix["tokens"] == res[1]["tokens"]
        np.testing.assert_allclose(res[1]["logprob"], prefix["logprob"],
                                   rtol=1e-4, atol=1e-4)


# ------------------------------------------------- inventory / compiles


class TestScoreInventory:
    def test_warmed_paged_scoring_session_zero_live_compiles(self):
        """The acceptance path: scoring enabled, warmup covers the score
        domain, `expected_from_inventory` exact equality holds, and a
        live session interleaving generation and a bulk score job adds
        ZERO programs."""
        eng = tiny_paged()
        eng.warmup()
        expectation = expected_from_inventory(eng)
        assert expectation.mismatches() == {}
        assert expectation.expected["_score"] == len(eng.score_shapes) > 0
        with compile_count_guard(expectation) as guard:
            eng.submit("what is raft?")
            eng.drain()
            eng.score(["the leader replicates logs", "a quorum votes",
                       "terms increase monotonically"])  # > one quantum
        assert guard.new_compiles() == 0

    def test_warmed_bucketed_scoring_session_zero_live_compiles(self):
        eng = tiny_tutoring(scoring=True)
        eng.warmup(batch=2, bucket=16)
        expectation = expected_from_inventory(eng)
        assert expectation.mismatches() == {}
        with compile_count_guard(expectation.fns["_score"]) as guard:
            eng.score(["one", "two tokens here", "three"])
        assert guard.new_compiles() == 0

    def test_scoring_disabled_bucketed_engine_still_rejected(self):
        eng = tiny_tutoring()  # scoring off
        with pytest.raises(InventoryMismatchError, match="warmup-covered"):
            expected_from_inventory(eng)

    def test_paged_without_scoring_expects_zero_score_programs(self):
        eng = tiny_paged(scoring=False)
        eng.warmup()
        expectation = expected_from_inventory(eng)
        assert expectation.expected["_score"] == 0
        assert expectation.mismatches() == {}


# ------------------------------------------------------ the job manager


class SlowScoreEngine:
    """Deterministic scoring-contract stand-in with a controllable
    quantum wall, for co-scheduler timing tests."""

    score_batch_cap = 2

    def __init__(self, quantum_s: float = 0.0, fail_at: int = -1):
        self.quantum_s = quantum_s
        self.fail_at = fail_at
        self.calls = 0

    def answer_batch(self, prompts):
        return [f"ans:{p}" for p in prompts]

    def score(self, texts):
        self.calls += 1
        if self.calls == self.fail_at:
            raise RuntimeError("injected score failure")
        if self.quantum_s:
            time.sleep(self.quantum_s)
        return [
            {"logprob": -2.0 * max(1, len(t.split())),
             "tokens": max(1, len(t.split())), "ppl": 7.389,
             "truncated": t.startswith("LONG")}
            for t in texts
        ]


class TestScoringManager:
    def test_jobs_chunk_resume_and_complete(self):
        metrics = Metrics()
        mgr = ScoringManager(SlowScoreEngine(), metrics=metrics)
        job = mgr.submit(["a b", "c", "d e f", "g", "LONG x"],
                         purpose="grading", job_id="j1")
        assert job["status"] == "queued" and job["texts"] == 5
        # Idempotent: a retried POST returns the same job, no re-queue.
        again = mgr.submit(["ignored"], job_id="j1")
        assert again["job_id"] == "j1" and again["texts"] == 5
        quanta = 0
        while mgr.has_work:
            assert mgr.run_quantum()
            quanta += 1
        assert quanta == 3  # ceil(5 / cap 2)
        detail = mgr.job("j1")
        assert detail["status"] == "done"
        assert len(detail["results"]) == 5
        assert detail["truncated_texts"] == 1
        snap = metrics.snapshot()["counters"]
        assert snap["scoring_quanta"] == 3
        assert snap["scoring_jobs_completed"] == 1
        assert snap["score_truncated_texts"] == 1
        assert snap["scoring_scored_tokens"] == detail["scored_tokens"] > 0
        assert not mgr.run_quantum()  # drained

    def test_job_failure_fails_the_job_not_the_tenant(self):
        metrics = Metrics()
        mgr = ScoringManager(SlowScoreEngine(fail_at=1), metrics=metrics)
        mgr.submit(["a", "b"], job_id="bad")
        mgr.submit(["c"], job_id="good")
        assert mgr.run_quantum()      # fails the first job internally
        assert mgr.job("bad")["status"] == "failed"
        while mgr.has_work:
            mgr.run_quantum()
        assert mgr.job("good")["status"] == "done"
        snap = metrics.snapshot()["counters"]
        assert snap["scoring_jobs_failed"] == 1
        assert snap["scoring_jobs_completed"] == 1

    def test_admission_caps_and_validation(self):
        mgr = ScoringManager(SlowScoreEngine(), max_job_texts=3)
        with pytest.raises(ValueError, match="admission cap"):
            mgr.submit(["x"] * 4)
        with pytest.raises(ValueError, match="non-empty"):
            mgr.submit(["", "  "])

    def test_admin_get_surface(self):
        mgr = ScoringManager(SlowScoreEngine())
        mgr.submit(["a"], job_id="jj")
        doc = score_admin_get("/admin/score", mgr)
        assert doc["ok"] and doc["jobs"][0]["job_id"] == "jj"
        assert doc["stats"]["backlog_texts"] == 1
        got = score_admin_get("/admin/score/jj", mgr)
        assert got["status"] == "queued" and got["results"] is None
        with pytest.raises(KeyError):
            score_admin_get("/admin/score/nope", mgr)
        with pytest.raises(KeyError):
            score_admin_get("/admin/score", None)  # tenant disabled


# ------------------------------------------------- queue co-scheduling


class TestCoScheduling:
    def test_preemption_wait_bounded_by_one_quantum(self):
        """Satellite pin: an interactive request arriving mid-quantum
        dispatches after at most ONE quantum, and the wait is recorded in
        score_preempt_wait_ms."""
        async def run():
            metrics = Metrics()
            eng = SlowScoreEngine(quantum_s=0.4)
            scorer = ScoringManager(eng, metrics=metrics)
            q = BatchingQueue(eng, max_batch=2, max_wait_ms=1.0,
                              metrics=metrics, scorer=scorer)
            await q.start()
            scorer.submit(["t one", "t two", "t three", "t four"])
            await asyncio.sleep(0.1)  # first quantum is in flight
            t0 = time.monotonic()
            answer = await q.submit("hello")
            wait_s = time.monotonic() - t0
            while not scorer.done():
                await asyncio.sleep(0.01)
            await q.close()
            return answer, wait_s, metrics.snapshot(), scorer, q

        answer, wait_s, snap, scorer, q = asyncio.run(run())
        assert answer == "ans:hello"
        # Arrived ~0.1 s into a 0.4 s quantum: served after that quantum
        # finishes, never after the whole job.
        assert wait_s < 0.4 + 0.35, f"waited {wait_s:.3f}s"
        assert snap["counters"]["score_preempt_wait_ms"] >= 1
        assert q.max_preempt_wait_s <= scorer.max_quantum_wall_s + 0.05
        # The policy witness: no quantum was ever admitted while
        # interactive work waited.
        assert scorer.stats()["quanta_with_pending"] == 0
        assert scorer.stats()["jobs_completed"] == 1

    def test_paged_queue_harvests_idle_lanes_real_engine(self):
        """End-to-end through the real paged engine: interactive answers
        resolve, the bulk job completes in the idle gaps, zero quanta
        run while anything interactive is pending, and the whole session
        compiles nothing live."""
        eng = tiny_paged()
        eng.warmup()
        expectation = expected_from_inventory(eng)

        async def run():
            metrics = Metrics()
            scorer = ScoringManager(eng, metrics=metrics)
            q = PagedQueue(eng, metrics=metrics, scorer=scorer)
            await q.start()
            scorer.submit([f"course text number {i} about raft logs"
                           for i in range(5)], purpose="relevance")
            answers = await asyncio.gather(
                q.submit("what is a term?"),
                q.submit("who votes?"),
            )
            while not scorer.done():
                await asyncio.sleep(0.01)
            await q.close()
            return answers, scorer, metrics.snapshot()

        with compile_count_guard(expectation) as guard:
            answers, scorer, snap = asyncio.run(run())
        assert guard.new_compiles() == 0
        assert all(isinstance(a, str) for a in answers)
        stats = scorer.stats()
        assert stats["jobs_completed"] == 1
        assert stats["quanta"] == 3  # ceil(5 / batch cap 2)
        assert stats["quanta_with_pending"] == 0
        assert snap["counters"]["scoring_scored_tokens"] > 0

    def test_scorer_wake_starts_idle_server(self):
        """A job submitted to an IDLE queue starts scoring without any
        interactive traffic to kick the runner."""
        async def run():
            metrics = Metrics()
            eng = SlowScoreEngine()
            scorer = ScoringManager(eng, metrics=metrics)
            q = BatchingQueue(eng, metrics=metrics, scorer=scorer)
            await q.start()
            await asyncio.sleep(0.05)  # runner parked on the idle wait
            scorer.submit(["a", "b", "c"])
            for _ in range(200):
                if scorer.done():
                    break
                await asyncio.sleep(0.01)
            await q.close()
            return scorer.stats()

        stats = asyncio.run(run())
        assert stats["jobs_completed"] == 1


# --------------------------------------------------- background routing


def test_background_route_avoids_hot_nodes():
    """Bulk jobs place OFF the hot affinity nodes: deepest-queue and
    most-routed nodes sort last."""
    from distributed_lms_raft_llm_tpu.lms.tutoring_pool import TutoringPool

    pool = TutoringPool(
        ["127.0.0.1:1", "127.0.0.1:2", "127.0.0.1:3"],
        health_addresses=["127.0.0.1:11", "127.0.0.1:12", "127.0.0.1:13"],
    )
    hot, warm, cold = pool.nodes
    hot.routes = 50
    hot.queued, hot.queued_at = 9, pool._clock()
    warm.routes = 10
    order = pool.plan_background()
    assert [n.index for n in order] == [cold.index, warm.index, hot.index]
    # A draining node is not a background candidate either.
    cold.draining = True
    order = pool.plan_background()
    assert [n.index for n in order] == [warm.index, hot.index]
