"""Tutoring fleet router (lms/tutoring_pool.py).

Ring properties first — deterministic placement, the minimal-remap bound
on membership change (only the departed/arrived node's keys move),
warm-up weighting — then spill ordering, budget-aware hedging with
loser cancellation, per-node chaos targets, single-node back-compat,
and the drain -> eject -> rejoin lifecycle over real gRPC + the real
healthz/drain admin plane.
"""

import asyncio
import time

import grpc
import pytest

from distributed_lms_raft_llm_tpu.engine import BatchingQueue
from distributed_lms_raft_llm_tpu.lms.tutoring_pool import (
    TutoringPool,
    TutoringUnavailable,
    affinity_key,
)
from distributed_lms_raft_llm_tpu.proto import rpc
from distributed_lms_raft_llm_tpu.serving.tutoring_server import (
    TutoringService,
    make_tutoring_admin,
    make_tutoring_health,
)
from distributed_lms_raft_llm_tpu.sim.cluster import EchoEngine
from distributed_lms_raft_llm_tpu.utils.faults import FaultInjector
from distributed_lms_raft_llm_tpu.utils.healthz import HealthServer
from distributed_lms_raft_llm_tpu.utils.metrics import Metrics
from distributed_lms_raft_llm_tpu.utils.resilience import (
    CircuitBreaker,
    Deadline,
)

ADDRS = ["10.0.0.1:50054", "10.0.0.2:50054", "10.0.0.3:50054"]
KEYS = [
    affinity_key(f"course{i % 40} assignment context: question {i}")
    for i in range(400)
]


def _pool(addresses, **kw):
    kw.setdefault("metrics", Metrics())
    return TutoringPool(addresses, **kw)


def _owners(pool):
    return {k: pool.rendezvous_order(k)[0].address for k in KEYS}


# ----------------------------------------------------------- ring maths


def test_placement_is_deterministic():
    """Same membership + same key => same node, across pool instances
    (the ring is pure hash, no per-process seed)."""
    assert _owners(_pool(ADDRS)) == _owners(_pool(ADDRS))


def test_remove_moves_only_the_departed_nodes_keys():
    """Rendezvous property: scores are per-(node, key), so removing a
    node reassigns exactly its own keys (~1/N) — the survivors' prefix
    caches keep every key they had."""
    before = _owners(_pool(ADDRS))
    after = _owners(_pool(ADDRS[:2]))
    moved = [k for k in KEYS if before[k] != after[k]]
    owned_by_removed = [k for k in KEYS if before[k] == ADDRS[2]]
    assert set(moved) == set(owned_by_removed)
    # The departed share is ~1/3 of the keys, not a reshuffle.
    assert 0.15 * len(KEYS) < len(moved) < 0.55 * len(KEYS)


def test_add_steals_at_most_a_fair_share():
    """Adding a node moves only the keys the NEW node wins (~1/(N+1));
    every moved key lands on it."""
    before = _owners(_pool(ADDRS))
    grown = _pool(ADDRS + ["10.0.0.4:50054"])
    after = _owners(grown)
    moved = [k for k in KEYS if before[k] != after[k]]
    assert moved, "a new node must take some share"
    assert all(after[k] == "10.0.0.4:50054" for k in moved)
    assert len(moved) < 0.45 * len(KEYS)  # expected ~1/4


def test_warmup_weight_shrinks_then_restores_the_key_share():
    """A warming node takes a reduced key share (its prefix cache is
    cold); once the ramp ends its placement is bit-identical to the
    steady state."""
    steady = _pool(ADDRS)
    warming = _pool(ADDRS, warmup_weight=0.25, warmup_s=60.0)
    node = warming.nodes[2]
    node.warming_until = warming._clock() + 60.0
    share_steady = sum(
        1 for k in KEYS if _owners(steady)[k] == node.address
    )
    share_warm = sum(
        1 for k, a in _owners(warming).items() if a == node.address
    )
    assert share_warm < 0.6 * share_steady
    node.warming_until = 0.0  # ramp over
    assert _owners(warming) == _owners(steady)


def test_affinity_key_normalizes_prompt_heads():
    assert affinity_key("  What   is\nRaft? ") == "what is raft?"
    long = "course0 assignment context: " + "x" * 200
    assert len(affinity_key(long)) == 64
    # Same course context prefix => same key, regardless of the tail.
    assert affinity_key(long + " A") == affinity_key(long + " B")


# -------------------------------------------------------- spill ordering


def test_queue_depth_spills_to_second_choice():
    pool = _pool(ADDRS, queue_spill_depth=8)
    key = KEYS[0]
    order = pool.rendezvous_order(key)
    now = pool._clock()
    order[0].queued, order[0].queued_at = 50, now
    order[1].queued, order[1].queued_at = 0, now
    routed, reason, affinity = pool.plan_route(key)
    assert reason == "spill:queue"
    assert routed[0] is order[1]
    assert affinity is order[0], "affinity reports the ring winner"
    # Both deep: no point spilling — stay on affinity.
    order[1].queued = 50
    _, reason, _ = pool.plan_route(key)
    assert reason == "affinity"
    # Stale reading: a depth observed longer than queue_ttl_s ago is
    # treated as drained — a node spilled around receives no trailers,
    # so a non-expiring burst reading would lock out its key share
    # (and its prefix-cache affinity) forever.
    order[0].queued_at = now - pool.queue_ttl_s - 1.0
    order[1].queued = 0
    _, reason, _ = pool.plan_route(key)
    assert reason == "affinity"


def test_budget_spills_when_affinity_ewma_exceeds_remaining():
    pool = _pool(ADDRS)
    key = KEYS[1]
    order = pool.rendezvous_order(key)
    order[0].ewma_s = 5.0
    order[1].ewma_s = 0.02
    routed, reason, affinity = pool.plan_route(key, Deadline.after(1.0))
    assert reason == "spill:budget"
    assert routed[0] is order[1]
    assert affinity is order[0]
    # Plenty of budget: affinity keeps the send.
    _, reason, _ = pool.plan_route(key, Deadline.after(30.0))
    assert reason == "affinity"


def test_hedging_is_budget_aware():
    pool = _pool(ADDRS, hedge_after_s=0.2, deadline_floor_s=0.25)
    assert pool._can_hedge(None)
    assert pool._can_hedge(Deadline.after(10.0))
    assert not pool._can_hedge(Deadline.after(0.3))
    assert not _pool(ADDRS, hedge_after_s=0.0)._can_hedge(None)


def test_empty_and_ejected_pools_raise_typed_unavailable():
    async def run():
        with pytest.raises(TutoringUnavailable) as none_exc:
            await _pool([]).forward("q", "tok")
        assert none_exc.value.kind == "none"
        pool = _pool(ADDRS)
        for node in pool.nodes:
            node.ejected = True
        with pytest.raises(TutoringUnavailable) as ej_exc:
            await pool.forward("q", "tok")
        assert ej_exc.value.kind == "ejected"

    asyncio.run(run())


# ------------------------------------------------------- real-gRPC fleet


async def _start_tutoring(node_id, delay_s=0.002, with_health=False):
    metrics = Metrics()
    queue = BatchingQueue(EchoEngine(delay_s), max_batch=4,
                          max_wait_ms=1.0, metrics=metrics)
    await queue.start()
    server = grpc.aio.server()
    service = TutoringService(queue, metrics, node_id=node_id)
    rpc.add_TutoringServicer_to_server(service, server)
    port = server.add_insecure_port("127.0.0.1:0")
    await server.start()
    rec = {
        "server": server, "queue": queue, "metrics": metrics,
        "service": service, "address": f"127.0.0.1:{port}",
        "health": None, "health_address": None, "node_id": node_id,
    }
    if with_health:
        health = HealthServer(
            metrics,
            health=make_tutoring_health(service, queue, "EchoEngine", 64),
            admin=make_tutoring_admin(service),
        )
        hport = await health.start()
        rec["health"] = health
        rec["health_address"] = f"127.0.0.1:{hport}"
    return rec


async def _stop_tutoring(rec):
    if rec["health"] is not None:
        await rec["health"].stop()
    await rec["server"].stop(None)
    await rec["queue"].close()


def _query_with_affinity(pool, want_address):
    """A query string the ring places on `want_address` first."""
    for i in range(200):
        q = f"probe question variant {i}?"
        if pool.rendezvous_order(affinity_key(q))[0].address == \
                want_address:
            return q
    raise AssertionError("no key found for node")


def test_forward_routes_by_affinity_and_reports_served_by():
    async def run():
        nodes = [await _start_tutoring("tutA"),
                 await _start_tutoring("tutB")]
        metrics = Metrics()
        pool = TutoringPool([n["address"] for n in nodes],
                            metrics=metrics, hedge_after_s=1.0)
        ids = {n["address"]: n["node_id"] for n in nodes}
        try:
            for i in range(6):
                q = f"what is consensus, variant {i}?"
                expected = pool.rendezvous_order(affinity_key(q))[0]
                answer, served = await pool.forward(q, "tok")
                assert answer.success and "Echo tutor" in answer.response
                # x-served-by trailing metadata names the fleet member
                # the ring predicted.
                assert served == ids[expected.address]
            snap = metrics.snapshot()["counters"]
            assert snap.get("tutoring_spills", 0) == 0
            assert snap.get("tutoring_hedges", 0) == 0
        finally:
            await pool.close()
            for n in nodes:
                await _stop_tutoring(n)

    asyncio.run(run())


def test_hedge_fires_wins_and_cancels_the_slow_primary():
    """Brownout the affinity node (injected per-node delay): the hedge
    to the second choice must win well before the primary's delay, the
    loser is cancelled (the forward returns fast), and the counters
    record one hedge + one win + one spill (served off-affinity)."""
    async def run():
        nodes = [await _start_tutoring("tutA"),
                 await _start_tutoring("tutB")]
        metrics = Metrics()
        injector = FaultInjector()
        pool = TutoringPool([n["address"] for n in nodes],
                            metrics=metrics, fault_injector=injector,
                            hedge_after_s=0.05)
        ids = {n["address"]: n["node_id"] for n in nodes}
        try:
            slow = pool.nodes[0]
            q = _query_with_affinity(pool, slow.address)
            injector.configure(slow.fault_target(), delay_s=0.8)
            t0 = time.monotonic()
            answer, served = await pool.forward(
                q, "tok", deadline=Deadline.after(5.0)
            )
            elapsed = time.monotonic() - t0
            assert answer.success
            other = next(n for n in pool.nodes if n is not slow)
            assert served == ids[other.address]
            assert elapsed < 0.6, (
                f"loser not cancelled: forward took {elapsed:.2f}s"
            )
            snap = metrics.snapshot()["counters"]
            assert snap.get("tutoring_hedges", 0) == 1
            assert snap.get("tutoring_hedge_wins", 0) == 1
            assert snap.get("tutoring_spills", 0) == 1
        finally:
            await pool.close()
            for n in nodes:
                await _stop_tutoring(n)

    asyncio.run(run())


def test_blackout_of_one_node_spills_and_recovers():
    async def run():
        nodes = [await _start_tutoring("tutA"),
                 await _start_tutoring("tutB")]
        metrics = Metrics()
        injector = FaultInjector()
        pool = TutoringPool([n["address"] for n in nodes],
                            metrics=metrics, fault_injector=injector,
                            hedge_after_s=0.0,
                            breaker_failure_threshold=2,
                            breaker_recovery_s=0.1)
        try:
            dead = pool.nodes[0]
            q = _query_with_affinity(pool, dead.address)
            injector.configure(dead.fault_target(), drop=1.0)
            answer, _served = await pool.forward(q, "tok")
            assert answer.success, "the spill must serve the answer"
            snap = metrics.snapshot()["counters"]
            assert snap.get("tutoring_spills", 0) >= 1
            assert snap.get("tutoring_failures", 0) >= 1
            # Fault cleared: affinity routing resumes (give the breaker
            # its half-open window).
            injector.clear(dead.fault_target())
            await asyncio.sleep(0.15)
            answer, served = await pool.forward(q, "tok")
            assert answer.success and served == "tutA"
        finally:
            await pool.close()
            for n in nodes:
                await _stop_tutoring(n)

    asyncio.run(run())


def test_single_node_breaker_backcompat_and_legacy_fault_target():
    """A bare one-address fleet behaves like the pre-fleet forward: the
    injected legacy target "tutoring" still faults it (hierarchical
    spec fallback), consecutive failures open the injected breaker, and
    an open circuit raises kind="breaker" without dialing."""
    async def run():
        node = await _start_tutoring("solo")
        metrics = Metrics()
        injector = FaultInjector()
        breaker = CircuitBreaker(failure_threshold=2, recovery_s=30.0)
        pool = TutoringPool([node["address"]], metrics=metrics,
                            fault_injector=injector, breakers=[breaker],
                            hedge_after_s=0.0)
        try:
            injector.configure("tutoring", drop=1.0)
            for _ in range(2):
                with pytest.raises(TutoringUnavailable) as exc:
                    await pool.forward("q?", "tok")
                assert exc.value.kind == "rpc"
            assert breaker.state == CircuitBreaker.OPEN
            before = node["metrics"].snapshot()["counters"].get(
                "llm_requests", 0
            )
            with pytest.raises(TutoringUnavailable) as exc:
                await pool.forward("q?", "tok")
            assert exc.value.kind == "breaker"
            after = node["metrics"].snapshot()["counters"].get(
                "llm_requests", 0
            )
            assert after == before, "open circuit must not dial"
        finally:
            await pool.close()
            await _stop_tutoring(node)

    asyncio.run(run())


def test_duplicate_fault_delivers_twice_on_the_faulted_node():
    async def run():
        node = await _start_tutoring("solo")
        metrics = Metrics()
        injector = FaultInjector()
        pool = TutoringPool([node["address"]], metrics=metrics,
                            fault_injector=injector, hedge_after_s=0.0)
        try:
            injector.configure("tutoring:0", duplicate=1.0)
            answer, _ = await pool.forward("q?", "tok")
            assert answer.success
            assert node["metrics"].snapshot()["counters"][
                "llm_requests"
            ] == 2
            assert metrics.snapshot()["counters"][
                "tutoring_duplicates"
            ] == 1
        finally:
            await pool.close()
            await _stop_tutoring(node)

    asyncio.run(run())


def test_drain_ejects_rejoins_with_warmup_and_restores_affinity():
    """The elastic-membership lifecycle over the real admin plane: a
    draining node is ejected by the health poller (traffic keeps
    flowing via the second choice, with a draining refusal never
    counted as a breaker failure), the drain's end re-admits it with a
    warm-up ramp, and once the ramp ends the ring places its old keys
    back on it."""
    async def run():
        nodes = [await _start_tutoring("tutA", with_health=True),
                 await _start_tutoring("tutB", with_health=True)]
        metrics = Metrics()
        pool = TutoringPool(
            [n["address"] for n in nodes],
            metrics=metrics,
            health_addresses=[n["health_address"] for n in nodes],
            hedge_after_s=0.0, warmup_s=0.2, health_poll_s=0.03,
        )
        pool.start()

        async def wait_for(pred, what, timeout=5.0):
            end = time.monotonic() + timeout
            while time.monotonic() < end:
                if pred():
                    return
                await asyncio.sleep(0.02)
            raise AssertionError(f"timed out waiting for {what}")

        try:
            victim = pool.nodes[0]
            q = _query_with_affinity(pool, victim.address)
            nodes[0]["service"].set_draining(True)
            await wait_for(lambda: not victim.routable(),
                           "poller to eject the draining node")
            answer, served = await pool.forward(q, "tok")
            assert answer.success and served == "tutB"
            assert victim.breaker.state == CircuitBreaker.CLOSED, (
                "draining must never count as a breaker failure"
            )
            nodes[0]["service"].set_draining(False)
            await wait_for(lambda: victim.routable(),
                           "poller to re-admit the node")
            assert victim.warming(time.monotonic()), (
                "rejoin must start a warm-up ramp"
            )
            await wait_for(
                lambda: not victim.warming(time.monotonic()),
                "warm-up to finish",
            )
            order = pool.rendezvous_order(affinity_key(q))
            assert order[0] is victim, "affinity must be restored"
            answer, served = await pool.forward(q, "tok")
            assert answer.success and served == "tutA"
            counters = metrics.snapshot()["counters"]
            assert counters["tutoring_node_ejections"] == 1
            assert counters["tutoring_node_rejoins"] == 1
        finally:
            await pool.close()
            for n in nodes:
                await _stop_tutoring(n)

    asyncio.run(run())
