"""Mixture-of-Experts GPT-2 + expert parallelism (models/moe.py).

The reference serves one dense architecture (GUI_RAFT_LLM_SourceCode/
tutoring_server.py:10-12); MoE is a beyond-reference capability, so the
correctness bar is internal: the static dispatch/combine einsum layer must
match a brute-force per-token reference exactly, ep-sharded execution must
match single-device execution, and the full serving engine must drive the
family through the standard generate path (the trunk IS gpt2.forward, so
cache/decode/speculation come along for free — asserted here too).
"""

import dataclasses

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_lms_raft_llm_tpu.engine.sampling import SamplingParams
from distributed_lms_raft_llm_tpu.models import moe, registry
from distributed_lms_raft_llm_tpu.parallel import make_mesh, partition


def _layer0(params):
    return jax.tree.map(lambda a: a[0], params["blocks"]["moe"])


def _brute_force(x, mp, cfg):
    """Per-token loop with float64 math: top-k, renormalize, weighted sum."""
    x = np.asarray(x, np.float64)
    wr = np.asarray(mp["wr"], np.float64)
    logits = x @ wr
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p /= p.sum(-1, keepdims=True)

    def gelu(v):
        return 0.5 * v * (
            1 + np.tanh(np.sqrt(2 / np.pi) * (v + 0.044715 * v**3))
        )

    out = np.zeros_like(x)
    for s in range(x.shape[0]):
        order = np.argsort(-p[s])[: cfg.experts_per_token]
        w = p[s][order]
        w = w / w.sum()
        for wi, e in zip(w, order):
            mid = gelu(
                x[s] @ np.asarray(mp["wi"][e], np.float64)
                + np.asarray(mp["bi"][e], np.float64)
            )
            out[s] += wi * (
                mid @ np.asarray(mp["wo"][e], np.float64)
                + np.asarray(mp["bo"][e], np.float64)
            )
    return out


class TestMoELayer:
    @pytest.mark.parametrize("k", [1, 2])
    def test_matches_brute_force_without_drops(self, k):
        cfg = moe.GPT2MoEConfig.tiny(
            capacity_factor=100.0, experts_per_token=k
        )
        params = moe.init_params(jax.random.key(0), cfg)
        mp = _layer0(params)
        h = jax.random.normal(jax.random.key(1), (2, 5, cfg.hidden_size),
                              jnp.float32)
        y = np.asarray(moe.moe_mlp(h, mp, cfg)).reshape(-1, cfg.hidden_size)
        ref = _brute_force(
            np.asarray(h).reshape(-1, cfg.hidden_size), mp, cfg
        )
        np.testing.assert_allclose(y, ref, atol=2e-4)

    def test_capacity_drops_route_to_zero(self):
        # C=1: at most E slots across the whole batch carry tokens; every
        # dropped token contributes exactly 0 (residual passthrough).
        cfg = moe.GPT2MoEConfig.tiny(capacity_factor=1e-9)
        params = moe.init_params(jax.random.key(0), cfg)
        mp = _layer0(params)
        h = jax.random.normal(jax.random.key(2), (4, 8, cfg.hidden_size),
                              jnp.float32)
        assert moe.capacity(cfg, 32) == 1
        y = np.asarray(moe.moe_mlp(h, mp, cfg)).reshape(-1, cfg.hidden_size)
        nonzero = np.sum(np.any(np.abs(y) > 0, axis=1))
        assert 0 < nonzero <= cfg.num_experts

    def test_slot_priority_is_first_choice_first(self):
        # Crafted collision at capacity 1: token A prefers E0 then E1,
        # token B prefers E1 then E0. Slot-major priority means BOTH get
        # their FIRST choice (all first picks outrank any second pick) and
        # both second picks are dropped — so each token's output is its
        # renormalized-first-choice expert alone. An inverted priority
        # would hand each token its SECOND choice instead, which this
        # assertion distinguishes.
        cfg = moe.GPT2MoEConfig.tiny(capacity_factor=1e-9)  # C = 1
        params = moe.init_params(jax.random.key(0), cfg)
        mp = dict(_layer0(params))
        d, e = cfg.hidden_size, cfg.num_experts
        wr = np.full((d, e), -30.0, np.float32)
        wr[0, 0], wr[0, 1] = 3.0, 2.0   # token A = e_0: E0 > E1
        wr[1, 1], wr[1, 0] = 3.0, 2.0   # token B = e_1: E1 > E0
        mp["wr"] = jnp.asarray(wr)
        h = np.zeros((1, 2, d), np.float32)
        h[0, 0, 0] = 1.0  # token A
        h[0, 1, 1] = 1.0  # token B
        assert moe.capacity(cfg, 2) == 1
        y = np.asarray(moe.moe_mlp(jnp.asarray(h), mp, cfg))[0]

        def expert(x, idx):
            wi = np.asarray(mp["wi"][idx], np.float64)
            bi = np.asarray(mp["bi"][idx], np.float64)
            wo = np.asarray(mp["wo"][idx], np.float64)
            bo = np.asarray(mp["bo"][idx], np.float64)
            v = x @ wi + bi
            g = 0.5 * v * (1 + np.tanh(np.sqrt(2 / np.pi)
                                       * (v + 0.044715 * v**3)))
            return g @ wo + bo

        # Renormalized first-choice weight: softmax(3,2) over the top-2.
        w1 = float(np.exp(3.0) / (np.exp(3.0) + np.exp(2.0)))
        exp_a = w1 * expert(np.asarray(h[0, 0], np.float64), 0)
        exp_b = w1 * expert(np.asarray(h[0, 1], np.float64), 1)
        np.testing.assert_allclose(y[0], exp_a, atol=2e-4)
        np.testing.assert_allclose(y[1], exp_b, atol=2e-4)

    def test_load_balance_loss_positive_and_bounded(self):
        cfg = moe.GPT2MoEConfig.tiny()
        params = moe.init_params(jax.random.key(0), cfg)
        h = jax.random.normal(jax.random.key(4), (2, 8, cfg.hidden_size),
                              jnp.float32)
        loss = float(moe.load_balance_loss(params, cfg, h, layer=0))
        # Perfectly balanced -> 1.0; worst case -> E. Must lie in [1, E].
        assert 0.9 <= loss <= cfg.num_experts + 1e-3


class TestExpertParallel:
    def test_ep_sharded_matches_single_device(self):
        cfg = moe.GPT2MoEConfig.tiny(dtype=jnp.float32,
                                     param_dtype=jnp.float32)
        params = moe.init_params(jax.random.key(0), cfg)
        ids = jax.random.randint(jax.random.key(5), (2, 12), 0,
                                 cfg.vocab_size)
        dense_logits, _ = moe.forward(params, cfg, ids)

        mesh = make_mesh({"ep": 4, "dp": -1})
        assert mesh.shape["ep"] == 4
        rules = partition.RULES_FOR["gpt2_moe"]
        sharded = partition.shard_tree(params, mesh, rules)
        with mesh:
            ep_logits, _ = jax.jit(
                lambda p, i: moe.forward(p, cfg, i)
            )(sharded, ids)
        np.testing.assert_allclose(
            np.asarray(dense_logits), np.asarray(ep_logits),
            rtol=2e-5, atol=2e-5,
        )

    def test_ep_composes_with_tp(self):
        cfg = moe.GPT2MoEConfig.tiny(dtype=jnp.float32,
                                     param_dtype=jnp.float32)
        params = moe.init_params(jax.random.key(0), cfg)
        ids = jax.random.randint(jax.random.key(6), (2, 8), 0,
                                 cfg.vocab_size)
        dense_logits, _ = moe.forward(params, cfg, ids)
        mesh = make_mesh({"ep": 2, "tp": 2, "dp": -1})
        sharded = partition.shard_tree(
            params, mesh, partition.RULES_FOR["gpt2_moe"]
        )
        with mesh:
            out, _ = jax.jit(lambda p, i: moe.forward(p, cfg, i))(
                sharded, ids
            )
        np.testing.assert_allclose(
            np.asarray(dense_logits), np.asarray(out), rtol=2e-5, atol=2e-5
        )


class TestTraining:
    def test_ep_sharded_train_step_loss_decreases(self):
        from distributed_lms_raft_llm_tpu.train import (
            TrainConfig,
            make_sharded_train_step,
        )

        cfg = moe.GPT2MoEConfig.tiny(dtype=jnp.float32,
                                     param_dtype=jnp.float32)
        mesh = make_mesh({"ep": 2, "tp": 2, "dp": -1})
        step, state, batch_shardings = make_sharded_train_step(
            mesh, cfg,
            TrainConfig(learning_rate=1e-2, warmup_steps=1, remat=True),
            jax.random.key(0),
        )
        seq = np.tile(np.arange(16, dtype=np.int32), (8, 2))
        batch = {
            "input_ids": jax.device_put(seq, batch_shardings["input_ids"]),
            "loss_mask": jax.device_put(
                np.ones_like(seq, np.float32), batch_shardings["loss_mask"]
            ),
        }
        losses, balances = [], []
        with mesh:
            for _ in range(8):
                state, metrics = step(state, batch)
                losses.append(float(metrics["loss"]))
                balances.append(float(metrics["moe_balance"]))
        assert np.isfinite(losses).all()
        assert losses[-1] < losses[0] * 0.8, losses
        # The Switch aux metric lives in [1, E] (1 = perfectly balanced).
        assert all(0.9 <= b <= cfg.num_experts + 1e-3 for b in balances)
        # Expert stacks actually sharded over ep.
        wi_shard = state["params"]["blocks"]["moe"]["wi"].sharding
        assert "ep" in (wi_shard.spec[1],), wi_shard.spec

    def test_forward_with_aux_matches_forward_logits(self):
        cfg = moe.GPT2MoEConfig.tiny(dtype=jnp.float32,
                                     param_dtype=jnp.float32)
        params = moe.init_params(jax.random.key(0), cfg)
        ids = jax.random.randint(jax.random.key(8), (2, 10), 0,
                                 cfg.vocab_size)
        ref, _ = moe.forward(params, cfg, ids)
        got, aux = moe.forward_with_aux(params, cfg, ids)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                                   rtol=2e-5, atol=2e-5)
        assert 0.9 <= float(aux) <= cfg.num_experts

    def test_train_export_serves_through_engine(self, tmp_path):
        # The full loop: ep-sharded train step -> native-layout export ->
        # TutoringEngine loads it via the standard checkpoint path.
        from distributed_lms_raft_llm_tpu.engine import (
            EngineConfig,
            TutoringEngine,
        )
        from distributed_lms_raft_llm_tpu.train import (
            TrainConfig,
            make_sharded_train_step,
        )
        from distributed_lms_raft_llm_tpu.train.checkpoint import (
            export_model,
        )

        cfg = moe.GPT2MoEConfig.tiny()
        mesh = make_mesh({"ep": 2, "dp": -1})
        step, state, shardings = make_sharded_train_step(
            mesh, cfg, TrainConfig(warmup_steps=1), jax.random.key(0)
        )
        seq = np.tile(np.arange(16, dtype=np.int32), (4, 2))
        batch = {
            "input_ids": jax.device_put(seq, shardings["input_ids"]),
            "loss_mask": jax.device_put(
                np.ones_like(seq, np.float32), shardings["loss_mask"]
            ),
        }
        with mesh:
            state, _ = step(state, batch)
        path = str(tmp_path / "moe.safetensors")
        export_model(path, state)

        eng = TutoringEngine(EngineConfig(
            model="moe-tiny", checkpoint=path,
            sampling=SamplingParams.reference_defaults(max_new_tokens=8),
            length_buckets=(16,), batch_buckets=(1,),
        ))
        # Trained weights actually loaded (not random init): compare one
        # leaf against the exported state.
        got = np.asarray(eng.params["blocks"]["moe"]["wr"], np.float32)
        want = np.asarray(
            jax.device_get(state["params"]["blocks"]["moe"]["wr"]),
            np.float32,
        )
        np.testing.assert_allclose(got, want, rtol=1e-2, atol=1e-2)
        assert isinstance(eng.answer_batch(["q"])[0], str)

    def test_moe_refuses_pp(self):
        import pytest as _pytest

        from distributed_lms_raft_llm_tpu.train import (
            TrainConfig,
            make_sharded_train_step,
        )

        cfg = moe.GPT2MoEConfig.tiny()
        with _pytest.raises(ValueError, match="pp and MoE"):
            make_sharded_train_step(
                make_mesh({"pp": 2, "dp": -1}), cfg,
                TrainConfig(warmup_steps=1), jax.random.key(0),
            )

    def test_ring_attention_composes_with_moe_and_aux(self):
        # sp x ep x dp: the ring-routed full-sequence forward and its aux
        # channel must match the dense single-device forward exactly.
        import dataclasses

        cfg = moe.GPT2MoEConfig.tiny(dtype=jnp.float32,
                                     param_dtype=jnp.float32)
        params = moe.init_params(jax.random.key(0), cfg)
        ids = jax.random.randint(jax.random.key(5), (4, 16), 0,
                                 cfg.vocab_size)
        ref, aux_ref = moe.forward_with_aux(params, cfg, ids)
        mesh = make_mesh({"sp": 2, "ep": 2, "dp": -1})
        ring_cfg = dataclasses.replace(cfg, ring_mesh=mesh)
        sharded = partition.shard_tree(
            params, mesh, partition.RULES_FOR["gpt2_moe"]
        )
        with mesh:
            got, aux = jax.jit(
                lambda p, i: moe.forward_with_aux(p, ring_cfg, i)
            )(sharded, ids)
        np.testing.assert_allclose(np.asarray(ref), np.asarray(got),
                                   rtol=2e-5, atol=2e-5)
        assert abs(float(aux) - float(aux_ref)) < 1e-4


class TestServing:
    def test_engine_serves_moe_with_ep(self):
        from distributed_lms_raft_llm_tpu.engine import (
            EngineConfig,
            TutoringEngine,
        )

        eng = TutoringEngine(EngineConfig(
            model="moe-tiny",
            sampling=SamplingParams.reference_defaults(max_new_tokens=10),
            length_buckets=(16,), batch_buckets=(1, 2), ep=4,
        ))
        assert eng.mesh.shape["ep"] == 4
        answers = eng.answer_batch(["what is a quorum?", "explain logs"])
        assert len(answers) == 2 and all(isinstance(a, str) for a in answers)

    def test_moe_composes_with_speculative_decoding(self):
        # The trunk is gpt2.forward, so the spec verify window (ragged
        # multi-token cache writes) must run unchanged: greedy streams
        # bit-equal with and without speculation. capacity_factor >= E
        # disables dropping, making the layer per-token independent —
        # with drops enabled a token's output depends on what else is in
        # the forward (batch-context dependence inherent to Switch-style
        # capacity), so the window and step forwards may legitimately
        # disagree (documented in models/moe.py).
        from distributed_lms_raft_llm_tpu.engine.generate import (
            decode,
            prefill,
        )
        from distributed_lms_raft_llm_tpu.engine.spec import decode_spec

        cfg = moe.GPT2MoEConfig.tiny(capacity_factor=4.0)
        fam = registry.MOE_FAMILY
        params = fam.init_params(jax.random.key(0), cfg)
        ids = jax.random.randint(jax.random.key(7), (2, 8), 1,
                                 cfg.vocab_size)
        mask = jnp.ones((2, 8), jnp.bool_)
        sp = SamplingParams.greedy(max_new_tokens=12)
        st = prefill(params, cfg, ids, mask, jax.random.key(1), sp, 0, 0,
                     model=fam)
        ref, _ = decode(params, st, cfg, sp, 0, 0, model=fam)
        st2 = prefill(params, cfg, ids, mask, jax.random.key(1), sp, 0, 0,
                      model=fam)
        spec, _ = decode_spec(params, st2, ids, cfg, sp, 0, 0, model=fam,
                              spec_tokens=3)
        np.testing.assert_array_equal(
            np.asarray(ref.tokens), np.asarray(spec.tokens)
        )

    def test_paged_engine_serves_moe(self):
        # Continuous batching over the MoE family: per-slot ragged decode
        # + the dispatch einsums under one chunked step program, with the
        # expert stacks ep-sharded.
        from distributed_lms_raft_llm_tpu.engine import (
            EngineConfig,
            PagedEngine,
        )

        eng = PagedEngine(EngineConfig(
            model="moe-tiny",
            sampling=SamplingParams.reference_defaults(max_new_tokens=8),
            length_buckets=(16,), batch_buckets=(1, 2), ep=4,
        ), slots=2, chunk=4)
        assert eng.mesh.shape["ep"] == 4
        rids = [eng.submit("what is a log?"), eng.submit("quorum?")]
        out = eng.drain()
        assert all(isinstance(out[r], str) for r in rids)

    def test_engine_rejects_ep_for_dense_family(self):
        from distributed_lms_raft_llm_tpu.engine import (
            EngineConfig,
            TutoringEngine,
        )

        with pytest.raises(ValueError, match="requires an MoE family"):
            TutoringEngine(EngineConfig(model="tiny", ep=2))

    def test_paged_engine_rejects_sp(self):
        from distributed_lms_raft_llm_tpu.engine import (
            EngineConfig,
            PagedEngine,
        )

        with pytest.raises(ValueError, match="sp applies to"):
            PagedEngine(EngineConfig(model="tiny", sp=2))

    def test_engine_rejects_spec_with_dropping_moe(self):
        # Default capacity_factor (1.25) drops tokens, which breaks the
        # spec verifier's exactness contract — must fail loudly.
        from distributed_lms_raft_llm_tpu.engine import (
            EngineConfig,
            TutoringEngine,
        )

        with pytest.raises(ValueError, match="capacity_factor"):
            TutoringEngine(EngineConfig(model="moe-tiny", spec_tokens=4))

    def test_quantized_trunk_serves(self):
        from distributed_lms_raft_llm_tpu.engine import (
            EngineConfig,
            TutoringEngine,
        )

        eng = TutoringEngine(EngineConfig(
            model="moe-tiny",
            sampling=SamplingParams.reference_defaults(max_new_tokens=8),
            length_buckets=(16,), batch_buckets=(1,),
            quant="int8", kv_quant=True,
        ))
        assert eng.answer_batch(["hello"])[0] is not None

    def test_int8_experts_stay_close_to_dense(self):
        # Weight-only int8 on the expert stacks (and trunk): the forward
        # must track the full-precision one closely — same bar as the
        # dense-model quant tests (top-1 agreement on most positions).
        from distributed_lms_raft_llm_tpu.models import quant

        cfg = moe.GPT2MoEConfig.tiny(dtype=jnp.float32,
                                     param_dtype=jnp.float32)
        params = moe.init_params(jax.random.key(0), cfg)
        ids = jax.random.randint(jax.random.key(9), (2, 12), 0,
                                 cfg.vocab_size)
        ref, _ = moe.forward(params, cfg, ids)
        qparams = quant.quantize_params(params, "gpt2_moe")
        assert isinstance(qparams["blocks"]["moe"]["wi"], dict)  # quantized
        got, _ = moe.forward(qparams, cfg, ids)
        ref_np, got_np = np.asarray(ref), np.asarray(got)
        agree = np.mean(
            np.argmax(ref_np, axis=-1) == np.argmax(got_np, axis=-1)
        )
        assert agree >= 0.9, agree
        # And the int8 expert stacks still shard over ep.
        mesh = make_mesh({"ep": 4, "dp": -1})
        sharded = partition.shard_tree(
            qparams, mesh, partition.RULES_FOR["gpt2_moe"]
        )
        with mesh:
            ep_logits, _ = jax.jit(lambda p, i: moe.forward(p, cfg, i))(
                sharded, ids
            )
        np.testing.assert_allclose(got_np, np.asarray(ep_logits),
                                   rtol=2e-5, atol=2e-5)
