"""sp/pp reach the PRODUCTION model and training paths (round-4 A7 gap).

parallel/ring.py and parallel/pipeline.py were parity-proven primitives no
production code path could invoke. These tests pin the wiring: GPT-2 and
Llama full-sequence forwards route through ring attention when
cfg.ring_mesh has sp > 1; the training step runs the REAL stacked trunk
through pipeline_trunk when the mesh has pp > 1 — both bit-compatible
(up to float tolerance) with the dense single-path forwards.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_lms_raft_llm_tpu.models import gpt2, llama
from distributed_lms_raft_llm_tpu.parallel import mesh as mesh_lib


def _tiny_gpt2(**kw):
    return dataclasses.replace(
        gpt2.GPT2Config(dtype=jnp.float32, param_dtype=jnp.float32),
        hidden_size=64, num_layers=4, num_heads=8,
        vocab_size=512, max_position_embeddings=64, **kw,
    )


def test_gpt2_forward_ring_matches_dense():
    """Full-sequence GPT-2 forward with ring_mesh (sp=4) == dense forward."""
    cfg = _tiny_gpt2()
    params = gpt2.init_params(jax.random.key(0), cfg)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(0, cfg.vocab_size, (2, 32)),
        jnp.int32,
    )
    dense_logits, _ = gpt2.forward(params, cfg, ids)

    mesh = mesh_lib.make_mesh({"sp": 4, "dp": -1})
    ring_cfg = dataclasses.replace(cfg, ring_mesh=mesh)
    with mesh:
        ring_logits, _ = jax.jit(
            lambda p, i: gpt2.forward(p, ring_cfg, i)
        )(params, ids)
    err = float(jnp.max(jnp.abs(dense_logits - ring_logits)))
    assert err < 2e-4, f"ring-wired forward diverges from dense: {err}"


def test_gpt2_ring_rejects_masked_or_custom_positions():
    cfg = _tiny_gpt2(ring_mesh=mesh_lib.make_mesh({"sp": 4, "dp": -1}))
    params = gpt2.init_params(jax.random.key(0), cfg)
    ids = jnp.ones((2, 16), jnp.int32)
    with pytest.raises(ValueError, match="full causal"):
        gpt2.forward(params, cfg, ids, kv_mask=jnp.ones((2, 16), bool))
    with pytest.raises(ValueError, match="full causal"):
        gpt2.forward(
            params, cfg, ids,
            positions=jnp.zeros((2, 16), jnp.int32),
        )


def test_llama_forward_ring_matches_dense():
    """Llama (GQA: 8 q heads over 4 kv heads) ring forward == dense."""
    cfg = dataclasses.replace(
        llama.LlamaConfig.tiny(dtype=jnp.float32, param_dtype=jnp.float32),
        hidden_size=64, num_layers=3, num_heads=8, num_kv_heads=4,
        intermediate_size=128,
    )
    params = llama.init_params(jax.random.key(1), cfg)
    ids = jnp.asarray(
        np.random.default_rng(1).integers(0, cfg.vocab_size, (2, 32)),
        jnp.int32,
    )
    dense_logits, _ = llama.forward(params, cfg, ids)

    mesh = mesh_lib.make_mesh({"sp": 4, "dp": -1})
    ring_cfg = dataclasses.replace(cfg, ring_mesh=mesh)
    with mesh:
        ring_logits, _ = jax.jit(
            lambda p, i: llama.forward(p, ring_cfg, i)
        )(params, ids)
    err = float(jnp.max(jnp.abs(dense_logits - ring_logits)))
    assert err < 2e-4, f"ring-wired llama diverges from dense: {err}"


def test_gpt2_forward_pipelined_matches_forward():
    """The REAL gpt2 trunk through pipeline_trunk (pp=2, 2 microbatches)
    reproduces the sequential scan's logits."""
    cfg = _tiny_gpt2()
    params = gpt2.init_params(jax.random.key(2), cfg)
    ids = jnp.asarray(
        np.random.default_rng(2).integers(0, cfg.vocab_size, (4, 16)),
        jnp.int32,
    )
    want, _ = gpt2.forward(params, cfg, ids)
    mesh = mesh_lib.make_mesh({"pp": 2, "dp": -1})
    with mesh:
        got = jax.jit(
            lambda p, i: gpt2.forward_pipelined(p, cfg, i, mesh, n_micro=2)
        )(params, ids)
    err = float(jnp.max(jnp.abs(want - got)))
    assert err < 2e-4, f"pipelined forward diverges: {err}"


def test_train_step_pp_matches_dp_loss():
    """One REAL train step with the trunk pipeline-sharded (pp=2 x dp=4,
    layer weights stage-sharded, 2 microbatches) produces the same loss and
    gradients (via the updated params' effect) as the plain dp step."""
    from distributed_lms_raft_llm_tpu.train import (
        TrainConfig, make_sharded_train_step,
    )

    cfg = _tiny_gpt2()
    tc = TrainConfig(warmup_steps=1, remat=False, pp_micro=2)
    batch_np = {
        "input_ids": np.random.default_rng(3).integers(
            0, cfg.vocab_size, (8, 16)
        ).astype(np.int32),
        "loss_mask": np.ones((8, 16), np.float32),
    }

    def run(axes):
        mesh = mesh_lib.make_mesh(axes)
        step, state, shardings = make_sharded_train_step(
            mesh, cfg, tc, jax.random.key(4)
        )
        batch = {
            k: jax.device_put(v, shardings[k]) for k, v in batch_np.items()
        }
        with mesh:
            state, metrics = step(state, batch)
        return float(metrics["loss"]), float(metrics["grad_norm"])

    loss_dp, gn_dp = run({"dp": -1})
    loss_pp, gn_pp = run({"pp": 2, "dp": -1})
    assert loss_pp == pytest.approx(loss_dp, rel=1e-5)
    assert gn_pp == pytest.approx(gn_dp, rel=1e-4)


def test_train_step_rejects_unimplemented_pp_combos():
    """pp+sp and pp+tp fail loudly instead of silently dropping ring
    attention / tensor sharding inside the pipeline stage body."""
    from distributed_lms_raft_llm_tpu.train import (
        TrainConfig, make_sharded_train_step,
    )

    cfg = _tiny_gpt2()
    tc = TrainConfig(warmup_steps=1, remat=False, pp_micro=2)
    with pytest.raises(ValueError, match="pp and sp"):
        make_sharded_train_step(
            mesh_lib.make_mesh({"pp": 2, "sp": 2, "dp": -1}), cfg, tc,
            jax.random.key(0),
        )
    with pytest.raises(ValueError, match="pp and tp"):
        make_sharded_train_step(
            mesh_lib.make_mesh({"pp": 2, "tp": 2, "dp": -1}), cfg, tc,
            jax.random.key(0),
        )


def test_train_step_sp_ring_matches_dp_loss():
    """One REAL train step with the sequence sharded over sp=2 (ring
    attention in the loss forward) matches the plain dp step's loss."""
    from distributed_lms_raft_llm_tpu.train import (
        TrainConfig, make_sharded_train_step,
    )

    cfg = _tiny_gpt2()
    tc = TrainConfig(warmup_steps=1, remat=False)
    batch_np = {
        "input_ids": np.random.default_rng(5).integers(
            0, cfg.vocab_size, (8, 32)
        ).astype(np.int32),
        "loss_mask": np.ones((8, 32), np.float32),
    }

    def run(axes):
        mesh = mesh_lib.make_mesh(axes)
        step, state, shardings = make_sharded_train_step(
            mesh, cfg, tc, jax.random.key(6)
        )
        batch = {
            k: jax.device_put(v, shardings[k]) for k, v in batch_np.items()
        }
        with mesh:
            state, metrics = step(state, batch)
        return float(metrics["loss"]), float(metrics["grad_norm"])

    loss_dp, gn_dp = run({"dp": -1})
    loss_sp, gn_sp = run({"sp": 2, "tp": 2, "dp": -1})
    assert loss_sp == pytest.approx(loss_dp, rel=1e-5)
    assert gn_sp == pytest.approx(gn_dp, rel=1e-4)
