"""Speculative decoding (engine/spec.py): exactness + engine wiring.

The safety property is that speculation changes WHEN tokens are computed,
never WHICH distribution they come from: greedy streams must be
bit-identical to the non-speculative decoder (any cache corruption or
verification bug shows up within a few tokens), and the stochastic
verifier's accept/resample rule must reproduce the processed sampling
distribution exactly (checked against analytic probabilities on a fixed
logit row). Reference behavior being replaced: the strictly one-token-
per-model-call HF generate loop (GUI_RAFT_LLM_SourceCode/
tutoring_server.py:21-29).
"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from distributed_lms_raft_llm_tpu.engine.generate import decode, prefill
from distributed_lms_raft_llm_tpu.engine.sampling import (
    SamplingParams,
    sample_step,
    seen_mask_from_ids,
)
from distributed_lms_raft_llm_tpu.engine.spec import (
    build_drafts,
    decode_spec,
    verify_window,
)
from distributed_lms_raft_llm_tpu.models import gpt2, llama, registry


def _prompt(cfg, b=3, t=8, seed=2, ragged=True):
    ids = np.asarray(
        jax.random.randint(jax.random.key(seed), (b, t), 1, cfg.vocab_size),
        np.int32,
    )
    mask = np.ones((b, t), bool)
    if ragged:
        mask[1, :3] = False
    return jnp.asarray(ids), jnp.asarray(mask)


def _run_both(cfg, family, sampling, *, eos=0, spec_tokens=4, seed=1,
              b=3, t=8, quant_kv=False):
    import dataclasses

    if quant_kv:
        cfg = dataclasses.replace(cfg, quant_kv=True)
    params = family.init_params(jax.random.key(0), cfg)
    ids, mask = _prompt(cfg, b=b, t=t)
    rng = jax.random.key(seed)
    st = prefill(params, cfg, ids, mask, rng, sampling, eos, 0, model=family)
    ref, _ = decode(params, st, cfg, sampling, eos, 0, model=family)
    st2 = prefill(params, cfg, ids, mask, rng, sampling, eos, 0, model=family)
    spec, _ = decode_spec(
        params, st2, ids, cfg, sampling, eos, 0, model=family,
        spec_tokens=spec_tokens,
    )
    return jax.device_get(ref), jax.device_get(spec)


class TestGreedyBitEquality:
    """temperature=0 makes every sampling decision deterministic, so the
    speculative and sequential decoders must emit IDENTICAL streams —
    the sharpest possible check of window verification, ragged cache
    writes, seen-mask evolution, and budget/EOS bookkeeping."""

    def test_gpt2_matches(self):
        ref, spec = _run_both(
            gpt2.GPT2Config.tiny(), registry.GPT2_FAMILY,
            SamplingParams.greedy(max_new_tokens=16),
        )
        np.testing.assert_array_equal(ref.tokens, spec.tokens)
        np.testing.assert_array_equal(ref.lengths, spec.lengths)

    def test_gpt2_with_repetition_penalty(self):
        # Penalty 1.2 exercises the seen-mask path inside the verifier:
        # a token accepted mid-window must penalize the rest of the window.
        sp = SamplingParams(temperature=0.0, top_k=50, top_p=1.0,
                            repetition_penalty=1.2, max_new_tokens=20)
        ref, spec = _run_both(gpt2.GPT2Config.tiny(), registry.GPT2_FAMILY, sp)
        np.testing.assert_array_equal(ref.tokens, spec.tokens)
        np.testing.assert_array_equal(ref.lengths, spec.lengths)

    def test_gpt2_int8_kv(self):
        ref, spec = _run_both(
            gpt2.GPT2Config.tiny(), registry.GPT2_FAMILY,
            SamplingParams.greedy(max_new_tokens=16), quant_kv=True,
        )
        np.testing.assert_array_equal(ref.tokens, spec.tokens)

    def test_llama_matches(self):
        ref, spec = _run_both(
            llama.LlamaConfig.tiny(), registry.LLAMA_FAMILY,
            SamplingParams.greedy(max_new_tokens=16),
        )
        np.testing.assert_array_equal(ref.tokens, spec.tokens)
        np.testing.assert_array_equal(ref.lengths, spec.lengths)

    def test_eos_stops_rows(self):
        # Force frequent EOS by making it a likely token: pick the model's
        # actual greedy argmax after a few steps as the eos id.
        cfg = gpt2.GPT2Config.tiny()
        fam = registry.GPT2_FAMILY
        sp = SamplingParams.greedy(max_new_tokens=16)
        params = fam.init_params(jax.random.key(0), cfg)
        ids, mask = _prompt(cfg)
        rng = jax.random.key(1)
        st = prefill(params, cfg, ids, mask, rng, sp, 0, 0, model=fam)
        probe, _ = decode(params, st, cfg, sp, 0, 0, model=fam)
        eos = int(np.asarray(probe.tokens)[0, 4])  # a token greedy WILL hit
        ref, spec = _run_both(cfg, fam, sp, eos=eos)
        np.testing.assert_array_equal(ref.tokens, spec.tokens)
        np.testing.assert_array_equal(ref.lengths, spec.lengths)
        assert int(spec.lengths[0]) < 16  # actually stopped early

    def test_spec_width_spans_budget_boundary(self):
        # max_new not divisible by the window width: the budget clamp
        # drops the tail of the last window.
        for k in (1, 3, 5):
            ref, spec = _run_both(
                gpt2.GPT2Config.tiny(), registry.GPT2_FAMILY,
                SamplingParams.greedy(max_new_tokens=7), spec_tokens=k,
            )
            np.testing.assert_array_equal(ref.tokens, spec.tokens)


class TestRaggedMultiTokenCacheWrite:
    """The per-row scatter write (models/*.forward, offset.ndim==1, T>1)
    must agree with the scalar dynamic_update_slice path when every row
    sits at the same offset."""

    @pytest.mark.parametrize("family,cfg", [
        (registry.GPT2_FAMILY, gpt2.GPT2Config.tiny()),
        (registry.LLAMA_FAMILY, llama.LlamaConfig.tiny()),
    ])
    @pytest.mark.parametrize("quant_kv", [False, True])
    def test_matches_scalar_path(self, family, cfg, quant_kv):
        import dataclasses

        cfg = dataclasses.replace(cfg, quant_kv=quant_kv)
        params = family.init_params(jax.random.key(0), cfg)
        b, t0, tw = 2, 6, 4
        prompt = jax.random.randint(jax.random.key(3), (b, t0), 1,
                                    cfg.vocab_size)
        window = jax.random.randint(jax.random.key(4), (b, tw), 1,
                                    cfg.vocab_size)
        cache = family.init_cache(cfg, b, t0 + tw, dtype=cfg.dtype)
        _, cache = family.forward(params, cfg, prompt, cache=cache)

        lg_s, c_s = family.forward(params, cfg, window, cache=cache)
        ragged = cache._replace(
            length=jnp.full((b,), t0, jnp.int32)
        )
        lg_r, c_r = family.forward(params, cfg, window, cache=ragged)

        np.testing.assert_allclose(np.asarray(lg_s), np.asarray(lg_r),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_array_equal(
            np.asarray(c_s.k, np.float32), np.asarray(c_r.k, np.float32)
        )
        np.testing.assert_array_equal(
            np.asarray(c_s.v, np.float32), np.asarray(c_r.v, np.float32)
        )

    def test_rows_at_different_offsets(self):
        # Row r's window lands at its own offset; other rows' slots are
        # untouched. Directly validates cross-row isolation of the scatter.
        cfg = gpt2.GPT2Config.tiny()
        fam = registry.GPT2_FAMILY
        params = fam.init_params(jax.random.key(0), cfg)
        b, tw, width = 2, 3, 12
        offs = jnp.asarray([2, 5], jnp.int32)
        window = jax.random.randint(jax.random.key(5), (b, tw), 1,
                                    cfg.vocab_size)
        cache = fam.init_cache(cfg, b, width, dtype=cfg.dtype)
        marker = cache._replace(
            k=jnp.full_like(cache.k, 7.0), v=jnp.full_like(cache.v, 7.0),
            length=offs,
        )
        _, out = fam.forward(params, cfg, window, cache=marker)
        k = np.asarray(out.k, np.float32)
        for r, o in enumerate([2, 5]):
            touched = np.any(k[:, r] != 7.0, axis=(0, 1, 3))  # [width] per slot
            assert touched[o : o + tw].all()
            assert not touched[:o].any() and not touched[o + tw :].any()


class TestVerifierDistribution:
    """The accept/resample rule must reproduce the processed sampling
    distribution exactly. With a point-mass draft q=δ(d), speculative
    sampling accepts with p(d) and otherwise resamples from p restricted
    to V∖{d} — whose mixture is p itself. Checked empirically against
    sample_step's analytic distribution on a fixed logit row."""

    def _empirical(self, logits_row, draft, sampling, trials=4000):
        b = trials
        drafts = jnp.full((b, 1), draft, jnp.int32)
        logits = jnp.broadcast_to(
            logits_row, (b, 2, logits_row.shape[-1])
        )
        seen = jnp.zeros((b, logits_row.shape[-1]), jnp.bool_)
        emitted, valid, _, _ = verify_window(
            jax.random.key(9), logits, drafts, seen,
            jnp.ones((b,), jnp.bool_), sampling, eos_id=-1, pad_id=-1,
        )
        emitted = np.asarray(emitted)
        valid = np.asarray(valid)
        assert valid[:, 0].all()
        return emitted[:, 0]

    def test_first_position_matches_sample_step(self):
        v = 64
        rng = np.random.default_rng(0)
        logits_row = jnp.asarray(rng.normal(0, 2.0, (v,)), jnp.float32)
        sampling = SamplingParams(temperature=0.7, top_k=16, top_p=0.9,
                                  repetition_penalty=1.0, max_new_tokens=4)
        draft = int(jnp.argsort(logits_row)[-2])  # a plausible draft

        got = self._empirical(logits_row, draft, sampling)

        # Analytic processed distribution via sample_step on a huge batch
        # of fresh keys (its own correctness is golden-tested vs HF).
        b = 4000
        seen = jnp.zeros((b, v), jnp.bool_)
        ref = sample_step(
            jax.random.key(123),
            jnp.broadcast_to(logits_row, (b, v)), seen, sampling,
        )
        ref = np.asarray(ref)

        # Compare frequency tables over the nucleus support.
        support = sorted(set(ref.tolist()) | set(got.tolist()))
        f_got = np.array([(got == s).mean() for s in support])
        f_ref = np.array([(ref == s).mean() for s in support])
        # 4000 trials: binomial std ≤ ~0.008; allow 5 sigma.
        np.testing.assert_allclose(f_got, f_ref, atol=0.04)

    def test_rejected_draft_never_reemitted_when_p_zero(self):
        # A draft outside the top-k support has p=0 under the processed
        # distribution: it must never be emitted.
        v = 64
        rng = np.random.default_rng(1)
        logits_row = jnp.asarray(rng.normal(0, 2.0, (v,)), jnp.float32)
        sampling = SamplingParams(temperature=0.7, top_k=8, top_p=1.0,
                                  repetition_penalty=1.0, max_new_tokens=4)
        draft = int(jnp.argsort(logits_row)[0])  # the WORST token
        got = self._empirical(logits_row, draft, sampling, trials=1000)
        assert (got != draft).all()


class TestDrafts:
    def test_bigram_preferred_over_unigram(self):
        # transcript: ... 5 9 ... 7 9 ... [7 9] → bigram (7,9) matches at
        # the second 9; proposal continues from there, not from the first.
        tr = jnp.asarray([[5, 9, 1, 2, 7, 9, 3, 4, 7, 9, 0, 0]], jnp.int32)
        # The current bigram is slots 8-9; match_valid (as decode_spec
        # builds it) anchors only earlier slots.
        valid = jnp.asarray([[True] * 9 + [False] * 3])
        d = build_drafts(tr, valid, jnp.asarray([7]), jnp.asarray([9]), 3)
        np.testing.assert_array_equal(np.asarray(d), [[3, 4, 7]])

    def test_unigram_fallback_and_recency(self):
        tr = jnp.asarray([[9, 1, 2, 9, 3, 4, 0, 0]], jnp.int32)
        valid = jnp.asarray([[True] * 6 + [False, False]])
        # prev token 8 matches nowhere → unigram on 9, most recent (idx 3).
        d = build_drafts(tr, valid, jnp.asarray([8]), jnp.asarray([9]), 2)
        np.testing.assert_array_equal(np.asarray(d), [[3, 4]])

    def test_no_match_repeats_last(self):
        tr = jnp.asarray([[1, 2, 3, 4]], jnp.int32)
        valid = jnp.ones((1, 4), jnp.bool_)
        d = build_drafts(tr, valid, jnp.asarray([6]), jnp.asarray([7]), 2)
        np.testing.assert_array_equal(np.asarray(d), [[7, 7]])


class TestEngineWiring:
    def test_engine_spec_roundtrip(self):
        from distributed_lms_raft_llm_tpu.engine import (
            EngineConfig,
            TutoringEngine,
        )

        eng = TutoringEngine(EngineConfig(
            model="tiny",
            sampling=SamplingParams.reference_defaults(max_new_tokens=12),
            length_buckets=(16,), batch_buckets=(1, 2), spec_tokens=4,
        ))
        answers = eng.answer_batch(["what is a raft quorum?"])
        assert len(answers) == 1 and isinstance(answers[0], str)

    def test_engine_spec_composes_with_tp(self):
        # The verify window's ragged multi-token scatter must partition
        # over a tp-sharded cache (heads axis untouched by the indices).
        from distributed_lms_raft_llm_tpu.engine import (
            EngineConfig,
            TutoringEngine,
        )

        eng = TutoringEngine(EngineConfig(
            model="tiny",
            sampling=SamplingParams.reference_defaults(max_new_tokens=12),
            length_buckets=(16,), batch_buckets=(1, 2), spec_tokens=4,
            tp=2, quant="int8", kv_quant=True,
        ))
        answers = eng.answer_batch(["explain quorums", "what is a log?"])
        assert len(answers) == 2
        assert all(isinstance(a, str) for a in answers)

    def test_engine_reports_tokens_per_window(self):
        from distributed_lms_raft_llm_tpu.engine import (
            EngineConfig,
            TutoringEngine,
        )

        eng = TutoringEngine(EngineConfig(
            model="tiny",
            sampling=SamplingParams.greedy(max_new_tokens=12),
            length_buckets=(16,), batch_buckets=(1,), spec_tokens=4,
        ))
        assert eng.last_spec_tokens_per_window is None
        eng.answer_batch(["the the the the"])
        tpw = eng.last_spec_tokens_per_window
        # Prefill token excluded: the ceiling is exactly spec_tokens + 1.
        assert tpw is not None and 0.0 < tpw <= 5.0

    def test_warmup_caps_bucket_inside_position_budget(self):
        # tiny has max_position_embeddings=64: an uncapped warmup at
        # length_buckets[0]=48 with max_new=16 + k=4 would oversubscribe
        # the position table (48+16+4-1=67 > 64) and trip decode_spec's
        # new budget validation on a shape real traffic can never reach
        # (encode_prompts caps at _max_prompt_len). warmup must cap too.
        from distributed_lms_raft_llm_tpu.engine import (
            EngineConfig,
            TutoringEngine,
        )

        eng = TutoringEngine(EngineConfig(
            model="tiny",
            sampling=SamplingParams.greedy(max_new_tokens=16),
            length_buckets=(48,), batch_buckets=(1,), spec_tokens=4,
        ))
        eng.warmup(batch=1)  # must not raise
        answers = eng.answer_batch(["a question after warmup"])
        assert len(answers) == 1

    def test_decode_spec_rejects_oversubscribed_position_budget(self):
        # Direct decode_spec callers get a loud error, not silently
        # clamped (wrong) position embeddings (ADVICE round 5): prefill's
        # own guard passes (t + max_new == mpe) but the spec window's
        # k-1 overhang does not fit.
        from distributed_lms_raft_llm_tpu.engine import generate as gen_lib
        from distributed_lms_raft_llm_tpu.engine.spec import decode_spec
        from distributed_lms_raft_llm_tpu.models import registry

        family, cfg = registry.resolve("tiny", jnp.float32)
        params = family.init_params(jax.random.PRNGKey(0), cfg)
        t = 8
        sampling = SamplingParams.greedy(
            max_new_tokens=cfg.max_position_embeddings - t
        )
        ids = jnp.ones((1, t), jnp.int32)
        mask = jnp.ones((1, t), bool)
        state = gen_lib.prefill(params, cfg, ids, mask,
                                jax.random.PRNGKey(1), sampling,
                                eos_id=0, pad_id=0, model=family)
        with pytest.raises(ValueError, match="max_position_embeddings"):
            decode_spec(params, state, ids, cfg, sampling, eos_id=0,
                        pad_id=0, model=family, spec_tokens=4)

    def test_engine_rejects_spec_with_fused_attention(self):
        from distributed_lms_raft_llm_tpu.engine import (
            EngineConfig,
            TutoringEngine,
        )

        with pytest.raises(ValueError, match="spec_tokens"):
            TutoringEngine(EngineConfig(
                model="tiny", spec_tokens=4, fused_attention=True,
            ))
