"""Multi-chip paged serving: the slot cache and every KV-adjacent plane
shard over tp (heads axis), and a sharded engine is bit-identical to the
single-chip one.

Runs on the virtual 8-device CPU mesh (conftest forces
``xla_force_host_platform_device_count=8``), so tp=2 / ep=2 meshes are
real multi-device shardings even without accelerator hardware. Greedy
decode decomposes exactly under head-sharding (the only cross-head
reduce is the row-parallel output-projection psum), so the parity bar is
byte equality, not tolerance.
"""

import asyncio

import jax
import jax.numpy as jnp
import pytest

from distributed_lms_raft_llm_tpu.engine import (
    EngineConfig,
    PagedEngine,
    PagedQueue,
    SamplingParams,
)
from distributed_lms_raft_llm_tpu.parallel import partition
from distributed_lms_raft_llm_tpu.utils.guards import (
    compile_count_guard,
    expected_from_inventory,
)
from distributed_lms_raft_llm_tpu.utils.metrics import Metrics

P = jax.sharding.PartitionSpec

MAX_NEW = 8

PROMPTS = ["what is raft?", "hello world", "explain paging", "k"]


def make_config(tp=1, **kw):
    kw.setdefault("sampling", SamplingParams.greedy(max_new_tokens=MAX_NEW))
    kw.setdefault("length_buckets", (4, 16))
    kw.setdefault("model", "tiny")
    return EngineConfig(
        batch_buckets=(1, 2),
        dtype=jnp.float32,
        tp=tp,
        **kw,
    )


def answers(cfg, prompts=PROMPTS, **engine_kw):
    engine_kw.setdefault("slots", 2)
    engine_kw.setdefault("chunk", 2)
    eng = PagedEngine(cfg, **engine_kw)
    # warmup() consumes request ids of its own, so live rids must come
    # from submit() — never assume the first live request is rid 0.
    rids = [eng.submit(p) for p in prompts]
    out = eng.drain()
    return [out[r] for r in rids]


# Every serving configuration whose step/admission programs touch the KV
# planes differently: plain chunked decode, speculative verify windows,
# device-resident megasteps, fused (staged) admission, shared-prefix
# splice/publish, and the int8 KV layout with its extra scale planes.
CONFIGS = [
    ("plain", {}, {}),
    ("spec", {"spec_tokens": 2}, {}),
    ("megastep", {}, {"megastep": 2, "megastep_max": 4}),
    ("fused_admission", {},
     {"megastep": 2, "megastep_max": 4, "prefill_chunk_tokens": 4}),
    ("prefix_hit", {},
     {"prefix_cache": True, "prefix_cache_blocks": 64,
      "prefix_block_tokens": 4}),
    ("kv_quant", {"kv_quant": True}, {}),
]


@pytest.mark.parametrize(
    "name,cfg_kw,eng_kw", CONFIGS, ids=[c[0] for c in CONFIGS]
)
def test_tp2_bit_identical_to_tp1(name, cfg_kw, eng_kw):
    """tp=2 must emit byte-for-byte what tp=1 emits, in every serving
    configuration — resharding the KV planes is a layout change, never a
    numerics change."""
    base = answers(make_config(tp=1, **cfg_kw), **dict(eng_kw))
    sharded = answers(make_config(tp=2, **cfg_kw), **dict(eng_kw))
    assert sharded == base


def test_kv_planes_shard_over_tp_and_halve_per_chip_bytes():
    """The slot KV cache lands under the plane table's P(None, None, 'tp')
    — heads split across shards — so each chip holds 1/tp of the KV
    bytes (the acceptance metric for multi-chip serving)."""
    eng = PagedEngine(make_config(tp=2), slots=2, chunk=2)
    rid = eng.submit(PROMPTS[0])
    eng.step()
    spec = P(None, None, "tp")
    for plane in ("k", "v"):
        arr = getattr(eng.state.cache, plane)
        assert arr.sharding.spec == spec, (plane, arr.sharding.spec)
    # length is host-logical bookkeeping: replicated, canonical P().
    assert eng.state.cache.length.sharding.spec == P()
    assert eng.tp == 2
    assert eng.kv_bytes_per_chip == eng.kv_bytes_total // 2
    assert isinstance(eng.drain()[rid], str)


def test_tp2_warmup_covers_inventory_and_live_traffic_compiles_nothing():
    """compile-once under tp: warmup on the tp=2 mesh compiles exactly
    the (mesh-keyed) inventoried domain and a live session with slot
    churn across both widths adds zero compiles."""
    eng = PagedEngine(make_config(tp=2), slots=2, chunk=2)
    eng.warmup()
    expectation = expected_from_inventory(eng)
    assert expectation.mismatches() == {}
    with compile_count_guard(expectation) as guard:
        eng.submit("k v")
        eng.step()
        eng.submit("a longer question about raft elections and logs")
        eng.drain()
    assert guard.new_compiles() == 0


def test_prefix_cache_hits_under_tp():
    """Shared-prefix reuse across the mesh: exported KVBlocks are
    per-shard device-resident runs under the same plane sharding, so a
    second same-course request splices cached blocks and still matches
    an unshared engine byte-for-byte."""
    ctx = "the raft leader election protocol works by "
    # An exact repeat guarantees a deep block hit regardless of how the
    # prompt bucket truncates the byte-fallback token stream; the third
    # prompt shares only the course context.
    prompts = [ctx + "choosing a leader", ctx + "choosing a leader",
               ctx + "counting votes"]
    cfg_kw = dict(length_buckets=(16, 32))
    eng_kw = dict(slots=2, chunk=2, prefix_cache=True,
                  prefix_cache_blocks=64, prefix_block_tokens=4)

    def serve_sequentially(tp):
        # One request at a time so the first request's published blocks
        # are in the cache before the second is admitted (concurrent
        # admission would race the publish and hit nothing).
        eng = PagedEngine(make_config(tp=tp, **cfg_kw), **eng_kw)
        out = []
        for p in prompts:
            rid = eng.submit(p)
            out.append(eng.drain()[rid])
        return eng, out

    _, base = serve_sequentially(tp=1)
    eng, sharded = serve_sequentially(tp=2)
    assert sharded == base
    hits = eng.pop_prefix_hits()
    # The second request shares the ctx prefix: at least one block hit.
    assert any(v > 0 for v in hits.values()), hits
    # Cached blocks live under the KV plane sharding, split over tp.
    spec = P(None, None, "tp")
    blocks = [b for n in eng.prefix_cache._iter_nodes() for b in n.blocks]
    assert blocks
    for blk in blocks:
        assert blk.k.sharding.spec == spec
        assert blk.v.sharding.spec == spec


def test_moe_tp_ep_paged_queue_smoke():
    """tp=2 x ep=2 on the MoE preset through the full async serving
    stack: expert planes shard over ep, KV over tp, and the queue
    serves concurrent requests and reports per-chip KV residency."""
    metrics = Metrics()
    engine = PagedEngine(
        make_config(tp=2, model="moe-tiny", ep=2), slots=2, chunk=2
    )
    assert engine.tp == 2 and engine.ep == 2

    async def run():
        q = PagedQueue(engine, metrics=metrics)
        await q.start()
        out = await asyncio.gather(
            *[q.submit(f"query number {i}") for i in range(3)]
        )
        await q.close()
        return out

    out = asyncio.run(run())
    assert len(out) == 3 and all(isinstance(a, str) for a in out)
    snap = metrics.snapshot()
    assert snap["gauges"]["serving_tp"] == 2.0
    assert snap["gauges"]["serving_kv_bytes_per_chip"] == float(
        engine.kv_bytes_per_chip
    )


# ------------------------------------------------- uneven-head rejection


def test_supported_tp_is_the_divisor_ladder():
    assert partition.supported_tp(20) == [1, 2, 4, 5, 10, 20]
    assert partition.supported_tp(12) == [1, 2, 3, 4, 6, 12]
    assert partition.supported_tp(4) == [1, 2, 4]
    assert partition.supported_tp(1) == [1]


def test_validate_tp_heads_accepts_divisors_rejects_ragged():
    for tp in partition.supported_tp(20):
        partition.validate_tp_heads(20, tp, "gpt2-large")  # no raise
    # gpt2-large's 20 heads at tp=8 would leave ragged head shards:
    # reject loudly with the exact supported ways in the message.
    with pytest.raises(ValueError, match=r"\[1, 2, 4, 5, 10, 20\]"):
        partition.validate_tp_heads(20, 8, "gpt2-large")
    with pytest.raises(ValueError, match="does not divide"):
        partition.validate_tp_heads(12, 5, "gpt2")


def test_engine_rejects_uneven_tp_at_construction():
    """The reject happens at PagedEngine construction (tiny has 4 heads;
    tp=3 is ragged), not as a jit shape error mid-serve."""
    with pytest.raises(ValueError, match=r"supported tp ways.*\[1, 2, 4\]"):
        PagedEngine(make_config(tp=3), slots=2, chunk=2)
