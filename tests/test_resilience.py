"""Unit tests for the resilience layer (utils/resilience.py, utils/faults.py)
and its batcher integration: deadline arithmetic, breaker state machine,
jittered backoff, seeded fault injection, bounded admission, and
expired-before-prefill shedding.
"""

import asyncio
import random
import time

import pytest

from distributed_lms_raft_llm_tpu.engine.batcher import BatchingQueue, PagedQueue
from distributed_lms_raft_llm_tpu.utils.faults import (
    FaultInjected,
    FaultInjector,
    FaultyTransport,
)
from distributed_lms_raft_llm_tpu.utils.metrics import Metrics
from distributed_lms_raft_llm_tpu.utils.resilience import (
    DEADLINE_METADATA_KEY,
    CircuitBreaker,
    Deadline,
    DeadlineExpired,
    Overloaded,
    jittered_backoff,
)


class FakeClock:
    def __init__(self, t=0.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt


# ------------------------------------------------------------------ Deadline


def test_deadline_remaining_and_expiry():
    clock = FakeClock()
    d = Deadline.after(5.0, clock=clock)
    assert d.remaining() == pytest.approx(5.0)
    assert not d.expired
    clock.advance(4.0)
    assert d.remaining() == pytest.approx(1.0)
    clock.advance(2.0)
    assert d.expired
    assert d.remaining() == 0.0  # never negative
    with pytest.raises(DeadlineExpired):
        d.raise_if_expired()


def test_deadline_timeout_cap():
    clock = FakeClock()
    d = Deadline.after(10.0, clock=clock)
    assert d.timeout(cap=3.0) == pytest.approx(3.0)
    assert d.timeout() == pytest.approx(10.0)
    clock.advance(9.0)
    assert d.timeout(cap=3.0) == pytest.approx(1.0)


def test_deadline_metadata_roundtrip():
    clock = FakeClock()
    d = Deadline.after(2.5, clock=clock)
    md = d.to_metadata()
    assert md == [(DEADLINE_METADATA_KEY, "2500")]
    d2 = Deadline.from_metadata(md, clock=clock)
    assert d2.remaining() == pytest.approx(2.5, abs=0.01)
    # Malformed / absent headers decode to None, not an error.
    assert Deadline.from_metadata([(DEADLINE_METADATA_KEY, "bogus")]) is None
    assert Deadline.from_metadata([("other", "1")]) is None
    assert Deadline.from_metadata(None) is None


def test_deadline_from_grpc_context_prefers_tighter_budget():
    clock = FakeClock()

    class Ctx:
        def time_remaining(self):
            return 9.0

        def invocation_metadata(self):
            return [(DEADLINE_METADATA_KEY, "3000")]

    d = Deadline.from_grpc_context(Ctx(), clock=clock)
    assert d.remaining() == pytest.approx(3.0, abs=0.01)

    class NoBudget:
        def time_remaining(self):
            return None

        def invocation_metadata(self):
            return []

    assert Deadline.from_grpc_context(NoBudget(), clock=clock) is None


# ------------------------------------------------------------------- backoff


def test_jittered_backoff_bounds_and_growth():
    rng = random.Random(7)
    for attempt in range(8):
        for _ in range(50):
            d = jittered_backoff(attempt, base_s=0.1, cap_s=1.0, rng=rng)
            assert 0.0 <= d <= min(1.0, 0.1 * 2.0 ** attempt) + 1e-9
    # Deterministic under a fixed seed.
    a = [jittered_backoff(i, rng=random.Random(3)) for i in range(4)]
    b = [jittered_backoff(i, rng=random.Random(3)) for i in range(4)]
    assert a == b


# ------------------------------------------------------------------- breaker


def test_breaker_state_machine():
    clock = FakeClock()
    changes = []
    br = CircuitBreaker(
        failure_threshold=3, recovery_s=5.0, clock=clock,
        on_state_change=lambda old, new: changes.append((old, new)),
    )
    assert br.state == CircuitBreaker.CLOSED
    assert br.allow()
    br.record_failure()
    br.record_failure()
    assert br.state == CircuitBreaker.CLOSED  # below threshold
    br.record_failure()
    assert br.state == CircuitBreaker.OPEN
    assert not br.allow()  # open: reject in O(1)
    clock.advance(5.1)
    assert br.state == CircuitBreaker.HALF_OPEN
    assert br.allow()       # the probe slot
    assert not br.allow()   # only one probe at a time (half_open_max=1)
    br.record_failure()     # probe failed: re-open, recovery clock restarts
    assert br.state == CircuitBreaker.OPEN
    clock.advance(5.1)
    assert br.allow()
    br.record_success()     # probe succeeded: closed again
    assert br.state == CircuitBreaker.CLOSED
    assert br.allow()
    assert ("closed", "open") in changes and ("open", "half_open") in changes
    snap = br.snapshot()
    assert snap["opened"] == 2 and snap["state"] == "closed"


def test_breaker_heals_leaked_half_open_probe():
    """A caller that takes the probe slot and dies before recording must
    not wedge the breaker half-open with no capacity forever."""
    clock = FakeClock()
    br = CircuitBreaker(failure_threshold=1, recovery_s=5.0, clock=clock)
    br.record_failure()
    clock.advance(5.1)
    assert br.allow()          # probe taken...
    assert not br.allow()      # ...and never recorded (caller died)
    clock.advance(5.1)         # another recovery window re-arms the probe
    assert br.allow()
    br.record_success()
    assert br.state == CircuitBreaker.CLOSED


def test_breaker_success_resets_consecutive_failures():
    br = CircuitBreaker(failure_threshold=2, clock=FakeClock())
    br.record_failure()
    br.record_success()
    br.record_failure()
    assert br.state == CircuitBreaker.CLOSED  # never 2 consecutive


# ------------------------------------------------------------- fault injector


def test_fault_injector_deterministic_and_targeted():
    a = FaultInjector(seed=42)
    b = FaultInjector(seed=42)
    a.configure("raft:1", drop=0.5)
    b.configure("raft:1", drop=0.5)
    plans_a = [a.plan("raft:1").drop for _ in range(64)]
    plans_b = [b.plan("raft:1").drop for _ in range(64)]
    assert plans_a == plans_b          # same seed, same faults
    assert any(plans_a) and not all(plans_a)
    # Unconfigured targets never fault (and don't consume RNG state).
    assert not a.plan("raft:2").any
    # Wildcard fallback applies to any target without its own spec.
    a.configure("*", drop=1.0)
    assert a.plan("raft:9").drop
    a.clear("*")
    assert not a.plan("raft:9").any
    with pytest.raises(ValueError):
        a.configure("raft:1", nonsense=1.0)


def test_fault_injector_snapshot_and_reset():
    inj = FaultInjector(seed=0)
    inj.configure("tutoring", error=1.0)
    snap = inj.snapshot()
    assert snap["targets"]["tutoring"]["error"] == 1.0
    inj.clear()
    assert inj.snapshot()["targets"] == {}
    assert not inj.active


class _FakeInner:
    """Transport double: counts sends, returns a canned response."""

    def __init__(self):
        self.sent = []
        self.addresses = {1: "a", 2: "b"}

    async def send(self, peer, message):
        self.sent.append((peer, message))
        return ("resp", peer)

    async def close(self):
        self.closed = True


def test_faulty_transport_drop_error_duplicate():
    async def run():
        inner = _FakeInner()
        inj = FaultInjector(seed=1)
        t = FaultyTransport(inner, inj)
        # No spec: passthrough.
        assert await t.send(1, "m") == ("resp", 1)
        # 100% drop: raises BEFORE delivery.
        inj.configure("raft:1", drop=1.0)
        with pytest.raises(FaultInjected):
            await t.send(1, "m2")
        assert len(inner.sent) == 1  # m2 never delivered
        # 100% error: delivered, then the response is lost.
        inj.configure("raft:1", error=1.0)
        with pytest.raises(FaultInjected):
            await t.send(1, "m3")
        assert inner.sent[-1] == (1, "m3")
        # 100% duplicate: delivered twice.
        inj.configure("raft:1", duplicate=1.0)
        await t.send(1, "m4")
        assert [m for _, m in inner.sent].count("m4") == 2
        # addresses proxies to the wrapped transport (RaftNode syncs it).
        assert t.addresses is inner.addresses

    asyncio.run(run())


# --------------------------------------------------- bounded batcher admission


class SlowEngine:
    """answer_batch blocks long enough for queue pressure to build."""

    def __init__(self, delay_s=0.2):
        self.delay_s = delay_s
        self.batches = []

    def answer_batch(self, prompts):
        self.batches.append(list(prompts))
        time.sleep(self.delay_s)
        return [f"ans:{p}" for p in prompts]


def test_batching_queue_sheds_on_overload():
    async def run():
        engine = SlowEngine(delay_s=0.3)
        metrics = Metrics()
        q = BatchingQueue(engine, max_batch=1, max_wait_ms=1,
                          metrics=metrics, max_queue=1)
        await q.start()
        try:
            t1 = asyncio.ensure_future(q.submit("a"))  # runner picks this up
            await asyncio.sleep(0.1)                   # a is now in-flight
            t2 = asyncio.ensure_future(q.submit("b"))  # occupies the 1 slot
            await asyncio.sleep(0.05)
            with pytest.raises(Overloaded):
                await q.submit("c")                    # bounded: refused
            assert await t1 == "ans:a"
            assert await t2 == "ans:b"
        finally:
            await q.close()
        snap = metrics.snapshot()
        assert snap["counters"]["shed_overload"] == 1
        assert snap["counters"]["engine_batches"] == 2
        assert ["c"] not in engine.batches

    asyncio.run(run())


def test_batching_queue_drops_expired_before_prefill():
    async def run():
        engine = SlowEngine(delay_s=0.25)
        metrics = Metrics()
        q = BatchingQueue(engine, max_batch=1, max_wait_ms=1, metrics=metrics)
        await q.start()
        try:
            t1 = asyncio.ensure_future(q.submit("a"))
            await asyncio.sleep(0.1)  # "a" holds the engine for ~0.25s
            # "b" will expire while queued behind "a".
            t2 = asyncio.ensure_future(
                q.submit("b", deadline=Deadline.after(0.05))
            )
            assert await t1 == "ans:a"
            with pytest.raises(DeadlineExpired):
                await t2
            # An already-expired submit is refused before even enqueueing.
            with pytest.raises(DeadlineExpired):
                await q.submit("c", deadline=Deadline.after(0.0))
        finally:
            await q.close()
        snap = metrics.snapshot()
        # ZERO prefills for expired requests: only "a" reached the engine.
        assert engine.batches == [["a"]]
        assert snap["counters"]["engine_batches"] == 1
        assert snap["counters"]["shed_expired"] == 2

    asyncio.run(run())


class FakePagedEngine:
    """Paged-engine double mirroring the real pending/slot split: submit()
    backlogs, step() admits ONE request per call (slots=1), prefill
    happens at admission."""

    def __init__(self, step_delay_s=0.02):
        self.step_delay_s = step_delay_s
        self.prefilled = []          # prompts whose prefill actually ran
        self._next = 0
        self._pending = []           # (rid, prompt) awaiting a slot
        self._active = {}

    @property
    def has_work(self):
        return bool(self._pending or self._active)

    @property
    def backlog(self):
        return len(self._pending)

    def cancel_pending(self, rid):
        for i, (r, _) in enumerate(self._pending):
            if r == rid:
                del self._pending[i]
                return True
        return False

    def submit(self, prompt):
        self._next += 1
        self._pending.append((self._next, prompt))
        return self._next

    def step(self):
        if not self._active and self._pending:
            rid, prompt = self._pending.pop(0)
            self.prefilled.append(prompt)  # admission = prefill
            self._active[rid] = prompt
        time.sleep(self.step_delay_s)
        done = [(rid, f"ans:{p}") for rid, p in self._active.items()]
        self._active.clear()
        return done

    def pop_ttfts(self):
        return {}

    def reset(self):
        self._pending.clear()
        self._active.clear()


def test_paged_queue_sheds_expired_before_admission():
    async def run():
        engine = FakePagedEngine()
        metrics = Metrics()
        q = PagedQueue(engine, metrics=metrics)
        await q.start()
        try:
            with pytest.raises(DeadlineExpired):
                await q.submit("x", deadline=Deadline.after(0.0))
            assert await q.submit("y") == "ans:y"
        finally:
            await q.close()
        assert engine.prefilled == ["y"]  # "x" never reached the engine
        assert metrics.snapshot()["counters"]["shed_expired"] == 1

    asyncio.run(run())


def test_paged_queue_sheds_engine_backlogged_expired_before_prefill():
    """A request that expires while waiting in the ENGINE's pending list
    (no free slot) is cancelled before its prefill dispatches."""
    async def run():
        engine = FakePagedEngine(step_delay_s=0.15)
        metrics = Metrics()
        q = PagedQueue(engine, metrics=metrics)
        await q.start()
        try:
            t1 = asyncio.ensure_future(q.submit("slow"))
            await asyncio.sleep(0.05)  # "slow" admitted to the only slot
            t2 = asyncio.ensure_future(
                q.submit("doomed", deadline=Deadline.after(0.02))
            )
            assert await t1 == "ans:slow"
            with pytest.raises(DeadlineExpired):
                await t2
        finally:
            await q.close()
        assert engine.prefilled == ["slow"]  # "doomed" never prefilled
        assert metrics.snapshot()["counters"]["shed_expired"] == 1

    asyncio.run(run())


def test_paged_queue_counts_engine_backlog_toward_bound():
    """Backpressure accounts for the engine's pre-slot pending list, not
    just the (eagerly drained) incoming queue."""
    async def run():
        engine = FakePagedEngine(step_delay_s=0.2)
        metrics = Metrics()
        q = PagedQueue(engine, metrics=metrics, max_queue=1)
        await q.start()
        try:
            t1 = asyncio.ensure_future(q.submit("a"))  # takes the slot
            await asyncio.sleep(0.05)
            t2 = asyncio.ensure_future(q.submit("b"))  # engine backlog = 1
            await asyncio.sleep(0.05)
            with pytest.raises(Overloaded):
                await q.submit("c")
            assert await t1 == "ans:a"
            assert await t2 == "ans:b"
        finally:
            await q.close()
        assert metrics.snapshot()["counters"]["shed_overload"] == 1
        assert "c" not in engine.prefilled

    asyncio.run(run())
