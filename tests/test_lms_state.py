"""LMS state machine + persistence + PDF unit tests."""

from distributed_lms_raft_llm_tpu.lms import (
    BlobStore,
    LMSState,
    SnapshotCorruption,
    SnapshotStore,
    hash_password,
)
from distributed_lms_raft_llm_tpu.utils import pdf

import pytest


def test_register_login_logout_flow():
    s = LMSState()
    s.apply("Register", {"username": "ana", "password_hash": hash_password("pw"),
                         "role": "student"})
    assert s.check_password("ana", "pw")
    assert not s.check_password("ana", "wrong")
    s.apply("Login", {"username": "ana", "token": "tok1"})
    assert s.user_of_token("tok1") == "ana"
    s.apply("Logout", {"token": "tok1"})
    assert s.user_of_token("tok1") is None


def test_register_is_first_writer_wins():
    s = LMSState()
    s.apply("Register", {"username": "bo", "password_hash": "h1", "role": "student"})
    s.apply("Register", {"username": "bo", "password_hash": "h2", "role": "instructor"})
    assert s.data["users"]["bo"]["password"] == "h1"
    assert s.role_of("bo") == "student"


def test_assignment_grade_query_lifecycle():
    s = LMSState()
    s.apply("Register", {"username": "st", "password_hash": "h", "role": "student"})
    s.apply("PostAssignment", {"student": "st", "filename": "hw1.pdf",
                               "filepath": "assignments/st/hw1.pdf",
                               "text": "trees"})
    s.apply("PostAssignment", {"student": "st", "filename": "hw2.pdf",
                               "filepath": "assignments/st/hw2.pdf",
                               "text": "graphs"})
    assert [a["grade"] for a in s.assignments_of("st")] == [None, None]
    # Reference semantics: grade applies to all the student's assignments.
    s.apply("GradeAssignment", {"student": "st", "grade": "A"})
    assert [a["grade"] for a in s.assignments_of("st")] == ["A", "A"]

    s.apply("AskQuery", {"username": "st", "query": "what is a B-tree?"})
    s.apply("AskQuery", {"username": "st", "query": "and an LSM?"})
    assert len(s.unanswered_queries()) == 2
    # Responds to the oldest unanswered query first.
    s.apply("RespondToQuery", {"instructor": "in", "student": "st",
                               "response": "a balanced tree"})
    unanswered = s.unanswered_queries()
    assert len(unanswered) == 1 and unanswered[0]["query"] == "and an LSM?"
    answered = s.answered_queries_of("st")
    assert answered == [{"query": "what is a B-tree?",
                         "response": "a balanced tree"}]


def test_unknown_op_raises():
    with pytest.raises(ValueError):
        LMSState().apply("DropTables", {})


def test_snapshot_roundtrip(tmp_path):
    store = SnapshotStore(str(tmp_path / "snap.json"))
    s = LMSState()
    s.apply("Register", {"username": "u", "password_hash": "h", "role": "student"})
    store.save(s, applied_index=7)
    s2, idx = store.load()
    assert idx == 7
    assert "u" in s2.data["users"]


def test_snapshot_missing_and_corrupt(tmp_path):
    store = SnapshotStore(str(tmp_path / "none.json"))
    s, idx = store.load()
    assert idx == 0 and s.data["users"] == {}
    # Corruption is NOT absence: loading a damaged snapshot as an empty
    # state at index 0 would silently discard every applied command the
    # compacted WAL no longer holds (PR-5; recovery happens in lms.node).
    (tmp_path / "bad.json").write_text("{not json")
    store2 = SnapshotStore(str(tmp_path / "bad.json"))
    with pytest.raises(SnapshotCorruption):
        store2.load()


def test_blob_store_confines_paths(tmp_path):
    blobs = BlobStore(str(tmp_path / "uploads"))
    blobs.put("materials/a.pdf", b"data")
    assert blobs.get("materials/a.pdf") == b"data"
    with pytest.raises(ValueError):
        blobs.put("../escape.pdf", b"x")
    with pytest.raises(ValueError):
        blobs.get("../../etc/passwd")


def test_blob_writer_replaces_not_appends(tmp_path):
    blobs = BlobStore(str(tmp_path / "uploads"))
    for _ in range(2):  # resend the same file (reference D5 duplicated it)
        w = blobs.open_writer("materials/m.pdf")
        w.write(b"12345")
        w.write(b"67890")
        w.commit()
    assert blobs.get("materials/m.pdf") == b"1234567890"


def test_pdf_roundtrip_multiline():
    data = pdf.make_pdf("line one\nline two (with parens)")
    text = pdf.extract_text(data)
    assert "line one" in text and "with parens" in text
    assert pdf.extract_text(b"not a pdf") == ""


def test_pdf_escaped_backslash_sequences():
    # A backslash followed by n/t must survive the escape decoder.
    for text in ["C:\\new\\table", "a\\b", "octal \x01 ok"]:
        data = pdf.make_pdf(text)
        assert pdf.extract_text(data) == text.replace("\x01", "\x01")
