"""Llama model: golden parity vs HF transformers + engine integration.

Mirrors tests/test_models_golden.py's GPT-2/BERT strategy (SURVEY.md §4d)
for the Llama family: same tiny config in both frameworks, same weights via
the HF conversion path, logits must agree. Covers RoPE, RMSNorm, GQA
(num_kv_heads < num_heads), SwiGLU, and the KV-cache decode path.
"""

import dataclasses

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax
import jax.numpy as jnp

from distributed_lms_raft_llm_tpu.engine import (
    EngineConfig,
    SamplingParams,
    TutoringEngine,
)
from distributed_lms_raft_llm_tpu.models import convert, llama

HF_CFG = dict(
    vocab_size=211,
    hidden_size=32,
    num_hidden_layers=2,
    num_attention_heads=4,
    num_key_value_heads=2,  # GQA: groups of 2
    intermediate_size=64,
    max_position_embeddings=64,
    rope_theta=10000.0,
    rms_norm_eps=1e-5,
    tie_word_embeddings=False,
)


@pytest.fixture(scope="module")
def hf_and_ours():
    hf_cfg = transformers.LlamaConfig(**HF_CFG)
    torch.manual_seed(0)
    hf_model = transformers.LlamaForCausalLM(hf_cfg).eval()
    cfg = convert.llama_config_from_hf(hf_cfg.to_dict())
    cfg = dataclasses.replace(cfg, dtype=jnp.float64, param_dtype=jnp.float64)
    params = convert.llama_params_from_hf(hf_model.state_dict(), cfg)
    return hf_model, cfg, params


def test_llama_logits_match_hf(hf_and_ours):
    hf_model, cfg, params = hf_and_ours
    ids = np.array([[3, 77, 140, 9, 201, 55, 18, 4]], np.int32)
    ours, _ = llama.forward(params, cfg, jnp.asarray(ids))
    with torch.no_grad():
        theirs = hf_model(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=1e-4, rtol=1e-4)


def test_llama_cached_decode_matches_full(hf_and_ours):
    """Prefill + single-token decode steps == one full forward."""
    _, cfg, params = hf_and_ours
    ids = np.array([[5, 9, 101, 44, 7, 63]], np.int32)
    full, _ = llama.forward(params, cfg, jnp.asarray(ids))

    cache = llama.init_cache(cfg, 1, ids.shape[1], dtype=cfg.dtype)
    pre, cache = llama.forward(params, cfg, jnp.asarray(ids[:, :3]), cache=cache)
    np.testing.assert_allclose(
        np.asarray(pre), np.asarray(full[:, :3]), atol=1e-6, rtol=1e-6
    )
    for i in range(3, 6):
        step, cache = llama.forward(params, cfg, jnp.asarray(ids[:, i : i + 1]),
                                    cache=cache)
        np.testing.assert_allclose(
            np.asarray(step[:, 0]), np.asarray(full[:, i]), atol=1e-6, rtol=1e-6
        )


def test_llama_tiny_engine_generates():
    """EngineConfig(model='llama-tiny') generates on the (virtual) mesh —
    the BASELINE config-5 path wired end-to-end (VERDICT round-1 item 5)."""
    engine = TutoringEngine(
        EngineConfig(
            model="llama-tiny",
            sampling=SamplingParams.reference_defaults(max_new_tokens=8),
            length_buckets=(16,),
            batch_buckets=(1, 2),
            tp=2,
            dtype=jnp.float32,
        )
    )
    answers = engine.answer_batch(["what is a lease?", "define quorum"])
    assert len(answers) == 2
    assert all(isinstance(a, str) for a in answers)


def test_llama_gqa_cache_is_grouped():
    cfg = llama.LlamaConfig.tiny()
    cache = llama.init_cache(cfg, 2, 16)
    assert cache.k.shape == (cfg.num_layers, 2, cfg.num_kv_heads, 16,
                             cfg.head_dim)
