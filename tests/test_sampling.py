"""Sampling ops vs HF transformers LogitsProcessors (golden parity)."""

import numpy as np
import pytest

import jax.numpy as jnp

from distributed_lms_raft_llm_tpu.engine import sampling

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


@pytest.fixture()
def logits():
    rng = np.random.default_rng(0)
    return rng.normal(size=(3, 64)).astype(np.float32) * 3


def test_top_k_matches_hf(logits):
    ours = np.asarray(sampling.apply_top_k(jnp.asarray(logits), 10))
    proc = transformers.TopKLogitsWarper(top_k=10, filter_value=sampling.NEG_INF)
    ref = proc(None, torch.tensor(logits)).numpy()
    kept_ours = ours > sampling.NEG_INF / 2
    kept_ref = ref > sampling.NEG_INF / 2
    np.testing.assert_array_equal(kept_ours, kept_ref)
    np.testing.assert_allclose(np.where(kept_ours, ours, 0), np.where(kept_ref, ref, 0), rtol=1e-6)


def test_top_p_matches_hf(logits):
    ours = np.asarray(sampling.apply_top_p(jnp.asarray(logits), 0.9))
    proc = transformers.TopPLogitsWarper(top_p=0.9, filter_value=sampling.NEG_INF)
    ref = proc(None, torch.tensor(logits)).numpy()
    np.testing.assert_array_equal(ours > sampling.NEG_INF / 2, ref > sampling.NEG_INF / 2)


def test_repetition_penalty_matches_hf(logits):
    rng = np.random.default_rng(1)
    prompt = rng.integers(0, 64, size=(3, 12))
    seen = np.zeros((3, 64), bool)
    for b in range(3):
        seen[b, prompt[b]] = True
    ours = np.asarray(
        sampling.apply_repetition_penalty(jnp.asarray(logits), jnp.asarray(seen), 1.2)
    )
    proc = transformers.RepetitionPenaltyLogitsProcessor(penalty=1.2)
    ref = proc(torch.tensor(prompt), torch.tensor(logits)).numpy()
    np.testing.assert_allclose(ours, ref, rtol=1e-5)


def test_greedy_and_temperature_paths():
    logits = jnp.asarray([[1.0, 5.0, 2.0], [4.0, 0.0, -1.0]])
    seen = jnp.zeros((2, 3), bool)
    greedy = sampling.sample_step(
        jnp.zeros(2, jnp.uint32), logits, seen, sampling.SamplingParams.greedy()
    )
    np.testing.assert_array_equal(np.asarray(greedy), [1, 0])

    import jax

    params = sampling.SamplingParams(temperature=0.7, top_k=2, top_p=0.95)
    toks = sampling.sample_step(jax.random.key(0), logits, seen, params)
    assert toks.shape == (2,)
    # top_k=2 restricts row 0 to {1, 2}, row 1 to {0, 1}.
    assert int(toks[0]) in (1, 2) and int(toks[1]) in (0, 1)


def test_seen_mask_roundtrip():
    ids = jnp.asarray([[3, 5, 3], [1, 0, 2]])
    valid = jnp.asarray([[True, True, True], [True, False, True]])
    mask = sampling.seen_mask_from_ids(ids, valid, 8)
    expect = np.zeros((2, 8), bool)
    expect[0, [3, 5]] = True
    expect[1, [1, 2]] = True  # id 0 in row 1 is padding
    np.testing.assert_array_equal(np.asarray(mask), expect)
    mask2 = sampling.update_seen(mask, jnp.asarray([7, 0]))
    assert bool(mask2[0, 7]) and bool(mask2[1, 0])


def test_approx_top_k_samples_from_plausible_set():
    """approx_top_k=True (serving opt-in, ~0.95 recall) still samples only
    high-logit tokens; exact parity is not promised, membership near the
    top is."""
    import numpy as np

    import jax
    import jax.numpy as jnp

    from distributed_lms_raft_llm_tpu.engine.sampling import (
        SamplingParams, sample_step,
    )

    rng = np.random.default_rng(0)
    logits = jnp.asarray(rng.normal(size=(4, 5000)).astype(np.float32))
    seen = jnp.zeros((4, 5000), bool)
    params = SamplingParams(approx_top_k=True, max_new_tokens=4)
    toks = sample_step(jax.random.key(0), logits, seen, params)
    # Every sample lands within the exact top-2k (k=50 with generous slack
    # for the approximate bins).
    _, exact_idx = jax.lax.top_k(logits, 100)
    for row in range(4):
        assert int(toks[row]) in np.asarray(exact_idx[row]), row
