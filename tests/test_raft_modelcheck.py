"""Bounded model checking of the Raft core: exhaustive interleavings.

The chaos soaks sample random schedules; this explores EVERY reachable
schedule of a bounded scenario — all orders of message delivery, message
loss (modeled by never delivering), and election timeouts — and asserts
Raft's safety invariants in every reachable state:

- **Election safety**: at most one leader per term, ever.
- **Log matching**: two logs agreeing on (index, term) agree on the
  command at that index and on the whole prefix.
- **State-machine safety**: two nodes agree on every index both have
  committed.

This is possible because raft/core.py is sans-IO: a transition is a plain
method call with an explicit `now`, outbound messages land in an outbox
list, and MemoryStorage keeps durability in-process — so a scheduler can
snapshot, branch, and deep-copy whole clusters. BFS with state-hash
memoization keeps the bounded space tractable (tens of thousands of
distinct states in seconds). The reference's Raft cannot be tested this
way at all: its transitions race across a ticker thread and gRPC handler
threads with no seam to schedule through (SURVEY.md §2.5 D10).
"""

import copy
import itertools

from distributed_lms_raft_llm_tpu.raft import MemoryStorage, RaftConfig
from distributed_lms_raft_llm_tpu.raft.core import RaftCore, Role
from distributed_lms_raft_llm_tpu.raft.messages import (
    AppendRequest,
    AppendResponse,
    TimeoutNowRequest,
    TimeoutNowResponse,
    VoteRequest,
    VoteResponse,
)

NOW = 1_000.0  # fixed virtual time: timeouts fire only via explicit action


def make_cluster(n=3):
    cfg = RaftConfig()
    return {
        i: RaftCore(i, list(range(1, n + 1)), MemoryStorage(), cfg,
                    now=0.0, seed=i)
        for i in range(1, n + 1)
    }


def drain(cores, node, pending):
    for dst, msg in cores[node].drain_outbox():
        pending.append((node, dst, msg))


def deliver(cores, src, dst, msg, pending):
    """Process `msg` at dst and enqueue its response back to src (response
    delivery is itself a schedulable action — responses reorder/delay like
    any other message)."""
    core = cores[dst]
    if isinstance(msg, VoteRequest):
        resp = core.on_vote_request(msg, NOW)
        pending.append((dst, src, resp))
    elif isinstance(msg, AppendRequest):
        resp = core.on_append_request(msg, NOW)
        pending.append((dst, src, (msg, resp)))  # pair: responder context
    elif isinstance(msg, TimeoutNowRequest):
        resp = core.on_timeout_now(msg, NOW)
        pending.append((dst, src, resp))
    elif isinstance(msg, VoteResponse):
        core.on_vote_response(src, msg, NOW)
    elif isinstance(msg, tuple):  # (AppendRequest, AppendResponse)
        core.on_append_response(src, msg[1], NOW)
    elif isinstance(msg, TimeoutNowResponse):
        core.on_timeout_now_response(msg, NOW)
    else:  # pragma: no cover
        raise TypeError(type(msg))
    drain(cores, dst, pending)


def state_key(cores, pending):
    core_keys = tuple(
        (
            c.current_term,
            c.voted_for,
            c.role.value,
            c.leader_id,
            c.commit_index,
            tuple((e.term, e.command) for e in c.log),
            tuple(sorted(c.votes)),
        )
        for c in cores.values()
    )
    # Pending is order-insensitive for exploration purposes (every order
    # is explored anyway); sort for canonical form.
    return core_keys, tuple(sorted(map(repr, pending)))


def check_invariants(cores, leaders_seen):
    """Per-STATE safety (branches are alternative universes — two branches
    may legally elect different leaders for the same term, so history-
    style invariants are phrased as state predicates, which still catch
    every real violation: a historical double-leader that matters shows
    up as split-brain, divergent committed prefixes, or broken log
    matching in some reachable state)."""
    # Election safety: no split-brain — two live leaders sharing a term.
    leaders = [
        (c.current_term, c.node_id)
        for c in cores.values() if c.role is Role.LEADER
    ]
    terms = [t for t, _ in leaders]
    assert len(terms) == len(set(terms)), f"split brain: {leaders}"
    for t, n in leaders:
        leaders_seen.add((t, n))
    # Log matching: agreement at (index, term) => equal prefixes.
    logs = [c.log for c in cores.values()]
    for la, lb in itertools.combinations(logs, 2):
        for idx in range(min(len(la), len(lb)) - 1, -1, -1):
            if la[idx].term == lb[idx].term:
                assert la[: idx + 1] == lb[: idx + 1], "log matching broken"
                break
    # State-machine safety: any two nodes agree on every index both have
    # committed.
    for ca, cb in itertools.combinations(cores.values(), 2):
        upto = min(ca.commit_index, cb.commit_index)
        for idx in range(1, upto + 1):
            ea = (ca.entry_at(idx).term, ca.entry_at(idx).command)
            eb = (cb.entry_at(idx).term, cb.entry_at(idx).command)
            assert ea == eb, f"committed divergence at {idx}: {ea} vs {eb}"


def explore(initial_actions, max_timeouts=1, max_states=60_000,
            pending_cap=5):
    """BFS every schedule: actions are (deliver pending[i]) ∪ (timeout n).

    Message loss needs no explicit action: a message that is never
    delivered within the horizon is a lost message — BFS covers every
    subset by covering every prefix order.
    """
    cores0 = make_cluster()
    pending0 = []
    for act in initial_actions:
        act(cores0, pending0)
    leaders_seen = set()
    seen = set()
    frontier = [(cores0, pending0, 0)]
    explored = 0
    while frontier:
        cores, pending, n_timeouts = frontier.pop()
        key = state_key(cores, pending)
        if key in seen:
            continue
        seen.add(key)
        explored += 1
        assert explored <= max_states, "state space exceeded bound"
        check_invariants(cores, leaders_seen)
        # Branch: deliver any pending message.
        for i in range(len(pending)):
            c2 = copy.deepcopy(cores)
            p2 = copy.deepcopy(pending)
            src, dst, msg = p2.pop(i)
            deliver(c2, src, dst, msg, p2)
            # Bound the pending queue so replication streaming can't run
            # away; exceeding it just truncates that branch.
            if len(p2) <= pending_cap:
                frontier.append((c2, p2, n_timeouts))
        # Branch: any follower/candidate times out (new election).
        if n_timeouts < max_timeouts:
            for nid, core in cores.items():
                if core.role is Role.LEADER or core.removed:
                    continue
                c2 = copy.deepcopy(cores)
                p2 = copy.deepcopy(pending)
                c2[nid].start_election(NOW)
                drain(c2, nid, p2)
                if len(p2) <= pending_cap:
                    frontier.append((c2, p2, n_timeouts + 1))
    return explored, leaders_seen


def test_exhaustive_election_schedules():
    """Every interleaving of up to 2 competing elections on 3 nodes (the
    kicked-off one plus one spurious timeout; all
    delivery orders, including lost messages): election safety and log
    matching hold in every reachable state, and at least one schedule
    actually elects a leader."""

    def kickoff(cores, pending):
        cores[1].start_election(NOW)
        drain(cores, 1, pending)

    explored, leaders = explore([kickoff], max_timeouts=1,
                                pending_cap=4)
    assert explored > 1000, explored  # genuinely explored a space
    assert leaders, "no schedule elected any leader"


def test_exhaustive_replication_schedules():
    """A leader with one proposed entry, a competing election allowed at
    any point, all delivery orders: no committed entry is ever lost or
    replaced, and commit never diverges across schedules."""

    def kickoff(cores, pending):
        # Deterministically elect node 1 first (synchronous votes).
        cores[1].start_election(NOW)
        for dst, msg in cores[1].drain_outbox():
            deliver(cores, 1, dst, msg, pending)
        for src, dst, msg in list(pending):
            if isinstance(msg, VoteResponse):
                pending.remove((src, dst, msg))
                deliver(cores, src, dst, msg, pending)
        assert cores[1].role is Role.LEADER
        pending.clear()  # drop the initial heartbeats: fresh horizon
        cores[1].propose("w1", NOW)
        drain(cores, 1, pending)

    explored, leaders = explore([kickoff], max_timeouts=1)
    assert explored > 500, explored


def test_exhaustive_transfer_schedules():
    """Leadership transfer interleaved with every delivery order and a
    spurious timeout: the sanctioned TimeoutNow campaign never produces
    two leaders in a term and never loses the committed no-op barrier."""

    def kickoff(cores, pending):
        cores[1].start_election(NOW)
        for dst, msg in cores[1].drain_outbox():
            deliver(cores, 1, dst, msg, pending)
        for src, dst, msg in list(pending):
            if isinstance(msg, VoteResponse):
                pending.remove((src, dst, msg))
                deliver(cores, src, dst, msg, pending)
        assert cores[1].role is Role.LEADER
        # Commit the term barrier everywhere (synchronous round).
        for src, dst, msg in list(pending):
            pending.remove((src, dst, msg))
            deliver(cores, src, dst, msg, pending)
        pending.clear()
        cores[1].transfer_leadership(NOW, target=2)
        drain(cores, 1, pending)

    explored, leaders = explore([kickoff], max_timeouts=1)
    assert explored > 200, explored
