"""Wire interop with the REFERENCE's own generated protobuf schema.

The north star (BASELINE.json) is byte-compatible interop: a reference
client or Raft peer must be able to talk to this framework unchanged.
These tests load the serialized FileDescriptorProto embedded in the
reference's generated `lms_pb2.py` (read-only; loaded into a PRIVATE
descriptor pool so the two `lms.proto` registrations don't collide) and
round-trip real messages in both directions between the reference's
message classes and ours.
"""

import re

import pytest

from google.protobuf import descriptor_pb2, descriptor_pool, message_factory

from distributed_lms_raft_llm_tpu.proto import lms_pb2 as ours

REF_PB2 = "/root/reference/GUI_RAFT_LLM_SourceCode/lms_pb2.py"


@pytest.fixture(scope="module")
def ref_pool():
    try:
        src = open(REF_PB2, "rb").read().decode()
    except OSError:
        pytest.skip("reference tree not mounted")
    m = re.search(r"AddSerializedFile\(\s*(b'(?:[^'\\]|\\.)*')", src, re.S)
    assert m, "reference lms_pb2.py has no serialized descriptor"
    pool = descriptor_pool.DescriptorPool()
    pool.Add(descriptor_pb2.FileDescriptorProto.FromString(eval(m.group(1))))
    return pool


def ref_class(pool, name):
    return message_factory.GetMessageClass(
        pool.FindMessageTypeByName(f"lms.{name}")
    )


def test_lms_messages_cross_parse_both_directions(ref_pool):
    cases = [
        ("RegisterRequest",
         dict(username="ana", password="pw", role="student")),
        ("LoginRequest", dict(username="ana", password="pw")),
        ("PostRequest",
         dict(token="t", type="assignment", file=b"%PDF",
              filename="hw.pdf", data="", studentId="")),
        ("GetRequest", dict(token="t", type="course_material")),
        ("GradeRequest", dict(token="t", studentId="ana", grade="A")),
        ("QueryRequest", dict(token="t", query="what is raft?")),
        ("QueryResponse", dict(success=True, response="leader election...")),
        ("LeaderResponse", dict(leader_id=3)),
    ]
    for name, fields in cases:
        mine = getattr(ours, name)(**fields)
        theirs = ref_class(ref_pool, name).FromString(
            mine.SerializeToString()
        )
        for key, value in fields.items():
            assert getattr(theirs, key) == value, (name, key)
        # And back: reference-serialized bytes parse into our classes.
        back = getattr(ours, name).FromString(theirs.SerializeToString())
        assert back == mine, name


def test_raft_wire_messages_cross_parse(ref_pool):
    """The Raft RPCs a reference peer would exchange with our cluster."""
    RefVote = ref_class(ref_pool, "RequestVoteRequest")
    v = RefVote()
    v.candidate.term = 7
    v.candidate.candidateID = 2
    v.lastLogIndex = 41
    v.lastLogTerm = 6
    mine = ours.RequestVoteRequest.FromString(v.SerializeToString())
    assert mine.candidate.term == 7 and mine.lastLogIndex == 41

    RefAppend = ref_class(ref_pool, "AppendEntriesRequest")
    a = RefAppend()
    a.leader.leaderID = 1
    a.leader.term = 7
    a.prevLogIndex = 41
    a.prevLogTerm = 6
    a.leaderCommit = 40
    entry = a.entries.add()
    entry.term = 7
    entry.command = '{"operation": "Register", "args": {}}'
    mine = ours.AppendEntriesRequest.FromString(a.SerializeToString())
    assert mine.leader.leaderID == 1
    assert mine.entries[0].command == entry.command

    # Response in the reference's quirky shape: verdict inside the
    # TermResultPair (SURVEY §7 hard part 5).
    resp = ours.AppendEntriesResponse()
    resp.result.term = 7
    resp.result.verdict = True
    theirs = ref_class(ref_pool, "AppendEntriesResponse").FromString(
        resp.SerializeToString()
    )
    assert theirs.result.verdict is True and theirs.result.term == 7


def test_service_method_sets_match(ref_pool):
    """Every RPC the reference's LMS/Tutoring/Raft/FileTransfer services
    declare exists with identical request/response types in our contract."""
    fdp = descriptor_pb2.FileDescriptorProto()
    ref_pool.FindFileByName("lms.proto").CopyToProto(fdp)
    ours_fdp = descriptor_pb2.FileDescriptorProto()
    ours.DESCRIPTOR.CopyToProto(ours_fdp)
    ref_services = {
        s.name: {(m.name, m.input_type, m.output_type) for m in s.method}
        for s in fdp.service
    }
    our_services = {
        s.name: {(m.name, m.input_type, m.output_type) for m in s.method}
        for s in ours_fdp.service
    }
    for sname, methods in ref_services.items():
        assert sname in our_services, f"service {sname} missing"
        missing = methods - our_services[sname]
        assert not missing, f"{sname} lacks reference methods {missing}"
