"""Tier-1 gate: the tree is lint-clean under the full rule set.

The `tests/test_marker_audit.py` pattern generalized: every rule in the
catalog runs over the package, scripts, and tests, and any unsuppressed
finding fails the suite — so the bug classes the rules encode (the PR-2
silent-recompile spelling bug above all) cannot be reintroduced without a
visible, attributable `# lint: disable=` comment in the diff.
"""

from distributed_lms_raft_llm_tpu.analysis import all_rules, run_lint


def test_tree_is_lint_clean():
    rules = all_rules()
    assert len(rules) >= 6, "the catalog must keep at least six active rules"
    findings = run_lint(rules=rules)
    assert not findings, (
        f"{len(findings)} unsuppressed lint finding(s):\n"
        + "\n".join(f.format() for f in findings)
        + "\n\nFix the code, or suppress an intentional case with "
        "`# lint: disable=<rule>` and a reason (see README: dlrl-lint)."
    )


def test_rule_set_covers_the_demonstrated_bug_classes():
    """The PR acceptance list: each demonstrated bug class has a live rule.
    Removing or renaming one must be a conscious, reviewed act."""
    names = {r.name for r in all_rules()}
    for required in (
        "canonical-pspec",           # PR-2: P() vs P(None, None) recompiles
        "no-host-sync-in-dispatch",  # paged-engine readback stalls
        "no-blocking-in-async",      # raft/serving loop stalls
        "no-orphan-task",            # dropped task handles (grpc_transport)
        "guarded-by",                # lock-guarded state (PR-1 review class)
        "tracer-hygiene",            # python control flow on tracers
        "slow-marker",               # tier-1 timeout protection
    ):
        assert required in names, f"rule {required} missing from the catalog"
