"""Tier-1 gate: the tree is lint-clean under the full rule set.

The `tests/test_marker_audit.py` pattern generalized: every rule in the
catalog — per-file lexical AND whole-repo semantic (call graph, metrics
registry, config consistency) — runs over the package, scripts, and
tests, and any unsuppressed finding fails the suite; the bug classes the
rules encode cannot be reintroduced without a visible, attributable
`# lint: disable=` comment in the diff. Reversion pins below prove the
expensive acceptance cases stay caught: un-deriving either request-path
RPC timeout, or emitting an unregistered metric name, fails lint again.
"""

from pathlib import Path

from distributed_lms_raft_llm_tpu.analysis import all_rules, run_lint
from distributed_lms_raft_llm_tpu.analysis.core import (
    Source,
    iter_sources,
    repo_root,
)
from distributed_lms_raft_llm_tpu.analysis.project import Project
from distributed_lms_raft_llm_tpu.analysis.rules.atomicity_across_await import (
    AtomicityAcrossAwaitRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.await_under_lock import (
    AwaitUnderLockRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.cancellation_safety import (
    CancellationSafetyRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.deadline_flow import (
    DeadlineFlowRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.lock_order import (
    LockOrderRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.metrics_registry import (
    MetricsRegistryRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.trace_propagation import (
    TracePropagationRule,
)
from distributed_lms_raft_llm_tpu.utils import metrics_registry

REPO = Path(__file__).resolve().parent.parent
SERVICE = "distributed_lms_raft_llm_tpu/lms/service.py"
POOL = "distributed_lms_raft_llm_tpu/lms/tutoring_pool.py"


def test_tree_is_lint_clean():
    rules = all_rules()
    assert len(rules) >= 6, "the catalog must keep at least six active rules"
    findings = run_lint(rules=rules)
    assert not findings, (
        f"{len(findings)} unsuppressed lint finding(s):\n"
        + "\n".join(f.format() for f in findings)
        + "\n\nFix the code, or suppress an intentional case with "
        "`# lint: disable=<rule>` and a reason (see README: dlrl-lint)."
    )


def test_rule_set_covers_the_demonstrated_bug_classes():
    """The PR acceptance list: each demonstrated bug class has a live rule.
    Removing or renaming one must be a conscious, reviewed act."""
    names = {r.name for r in all_rules()}
    for required in (
        "canonical-pspec",           # PR-2: P() vs P(None, None) recompiles
        "no-host-sync-in-dispatch",  # paged-engine readback stalls
        "no-blocking-in-async",      # raft/serving loop stalls
        "no-orphan-task",            # dropped task handles (grpc_transport)
        "guarded-by",                # lock-guarded state (PR-1 review class)
        "tracer-hygiene",            # python control flow on tracers
        "slow-marker",               # tier-1 timeout protection
        "deadline-flow",             # PR-4: budget-dropping RPC timeouts
        "metrics-registry",          # PR-4: typo'd/undocumented series
        "config-consistency",        # PR-4: dead knobs, typo'd TOML keys
        "guarded-by-flow",           # PR-4: executor escape via call graph
        "durable-rename",            # PR-5: rename outliving its contents
        "pspec-flow",                # PR-6: semantic sharding divergence
        "donation-safety",           # PR-6: use-after-donate
        "dtype-flow",                # PR-6: silent hot-path widening
        "program-inventory",         # PR-6: jit entry points vs manifest
        "state-machine-determinism",  # PR-18: replica-diverging appliers
        "wire-taint",                # PR-18: unverified wire input at sinks
        "lock-order",                # PR-13: breaker-callback self-deadlock
        "atomicity-across-await",    # event-loop TOCTOU (shutdown races)
        "await-under-lock",          # threading lock held across a yield
        "cancellation-safety",       # teardown that loses CancelledError
    ):
        assert required in names, f"rule {required} missing from the catalog"


# ------------------------------------------------------- reversion pins


def _project_with_patch(rel: str, *edits) -> Project:
    """The real repo tree, with textual edits to one file — exactly what
    `git revert` of a sweep fix would produce."""
    root = repo_root()
    sources = iter_sources(None, root=root)
    patched = []
    for src in sources:
        if src.rel == rel:
            text = src.text
            for old, new in edits:
                assert old in text, f"pin is stale: {old!r} not in {rel}"
                text = text.replace(old, new, 1)
            src = type(src)(src.path, root=root, text=text)
        patched.append(src)
    return Project(patched, root=root)


def _project_with_patched_service(old: str, new: str) -> Project:
    return _project_with_patch(SERVICE, (old, new))


def test_reverting_blob_fetch_timeout_fix_fails_lint():
    project = _project_with_patched_service(
        "timeout=attempt_timeout,", "timeout=5,"
    )
    findings = [
        f for f in DeadlineFlowRule().check_project(project)
        if f.path == SERVICE
    ]
    assert findings, "a re-hardcoded FetchFile timeout must fail deadline-flow"


def test_reverting_replicate_timeout_fix_fails_lint():
    project = _project_with_patched_service(
        "SendFile(chunks(), timeout=attempt_timeout,",
        "SendFile(chunks(), timeout=30,",
    )
    findings = [
        f for f in DeadlineFlowRule().check_project(project)
        if f.path == SERVICE
    ]
    assert findings, "a re-hardcoded SendFile timeout must fail deadline-flow"


def test_metadata_dropping_egress_fails_lint():
    """PR 8 acceptance pin: strip trace_metadata() off the blob-fetch
    egress (what reverting the instrumentation sweep would do) and the
    x-trace-context chain breaks — trace-propagation must catch it."""
    project = _project_with_patched_service(
        "metadata=trace_metadata(),", ""
    )
    findings = [
        f for f in TracePropagationRule().check_project(project)
        if f.path == SERVICE and "FetchFile" in f.message
    ]
    assert findings, (
        "an egress that drops the trace metadata must fail trace-propagation"
    )


def test_bare_metadata_egress_fails_lint():
    """The subtler break: metadata still flows (the deadline budget), but
    without the wrapper the trace context is silently dropped. The
    GetLLMAnswer forward now lives in the fleet router
    (lms/tutoring_pool.py) — the pool is an egress-root module, so the
    same revert fails lint there."""
    project = _project_with_patch(
        POOL, (
            "\n                    metadata=trace_metadata(md),",
            "\n                    metadata=md,",
        )
    )
    findings = [
        f for f in TracePropagationRule().check_project(project)
        if f.path == POOL and "GetLLMAnswer" in f.message
    ]
    assert findings, (
        "an egress whose metadata bypasses trace_metadata() must fail "
        "trace-propagation"
    )


def test_pool_metadata_dropping_egress_fails_lint():
    """Fleet-router pin: strip the metadata= keyword off the pool's
    tutoring forward entirely and trace-propagation must catch it (the
    x-served-by/waterfall chain would silently break)."""
    project = _project_with_patch(
        POOL, ("\n                    metadata=trace_metadata(md),", "")
    )
    findings = [
        f for f in TracePropagationRule().check_project(project)
        if f.path == POOL and "GetLLMAnswer" in f.message
    ]
    assert findings, (
        "a pool egress that drops metadata= must fail trace-propagation"
    )


def test_pool_literal_timeout_fails_lint():
    """Fleet-router pin: re-hardcoding the forward's timeout (dropping
    the live Deadline budget) in tutoring_pool.py must fail
    deadline-flow — the pool's async functions are rule roots even
    though the call graph can't see `self.pool.forward`."""
    project = _project_with_patch(
        POOL, ("timeout=self._attempt_timeout(deadline),", "timeout=30,")
    )
    findings = [
        f for f in DeadlineFlowRule().check_project(project)
        if f.path == POOL
    ]
    assert findings, (
        "a re-hardcoded pool forward timeout must fail deadline-flow"
    )


ROUTER = "distributed_lms_raft_llm_tpu/lms/group_router.py"


def test_router_literal_timeout_fails_lint():
    """PR 16 acceptance pin: the group router's leader forwards derive
    their timeout from the caller's live Deadline budget. Re-hardcoding
    one (what reverting the sweep would do) must fail deadline-flow —
    the router is an egress-root module like the tutoring pool."""
    project = _project_with_patch(ROUTER, (
        "stub.Register(request, timeout=timeout, "
        "metadata=trace_metadata(md))",
        "stub.Register(request, timeout=30, "
        "metadata=trace_metadata(md))",
    ))
    findings = [
        f for f in DeadlineFlowRule().check_project(project)
        if f.path == ROUTER
    ]
    assert findings, (
        "a re-hardcoded router forward timeout must fail deadline-flow"
    )


def test_router_metadata_bypass_fails_lint():
    """PR 16 acceptance pin: the router's cross-node forwards carry the
    trace context (plus group/hops/deadline metadata) through
    trace_metadata(). Bypassing the wrapper on one forward must fail
    trace-propagation."""
    project = _project_with_patch(ROUTER, (
        "stub.Register(request, timeout=timeout, "
        "metadata=trace_metadata(md))",
        "stub.Register(request, timeout=timeout, metadata=md)",
    ))
    findings = [
        f for f in TracePropagationRule().check_project(project)
        if f.path == ROUTER and "Register" in f.message
    ]
    assert findings, (
        "a router egress whose metadata bypasses trace_metadata() must "
        "fail trace-propagation"
    )


def test_stream_forward_metadata_drop_fails_lint():
    """PR 20 acceptance pin: the router's server-streaming forward is
    held to the same trace contract as its unary forwards — the
    async-for egress shape. Stripping trace_metadata() off the
    StreamLLMAnswer forward (what reverting the streaming sweep would
    do) must fail trace-propagation, and dropping its timeout must fail
    deadline-flow even though the call is never awaited directly."""
    project = _project_with_patch(ROUTER, (
        "stub.StreamLLMAnswer(\n"
        "                request, timeout=timeout, "
        "metadata=trace_metadata(md)\n"
        "            )",
        "stub.StreamLLMAnswer(\n"
        "                request, timeout=timeout, metadata=md\n"
        "            )",
    ))
    findings = [
        f for f in TracePropagationRule().check_project(project)
        if f.path == ROUTER and "StreamLLMAnswer" in f.message
    ]
    assert findings, (
        "a metadata-dropping StreamLLMAnswer forward must fail "
        "trace-propagation"
    )
    project = _project_with_patch(ROUTER, (
        "stub.StreamLLMAnswer(\n"
        "                request, timeout=timeout, "
        "metadata=trace_metadata(md)\n"
        "            )",
        "stub.StreamLLMAnswer(\n"
        "                request, metadata=trace_metadata(md)\n"
        "            )",
    ))
    findings = [
        f for f in DeadlineFlowRule().check_project(project)
        if f.path == ROUTER and "StreamLLMAnswer" in f.message
    ]
    assert findings, (
        "a timeout-less StreamLLMAnswer forward must fail deadline-flow"
    )


def test_unregistered_metric_name_fails_lint():
    project = _project_with_patched_service(
        '"tutoring_degraded"', '"tutoring_degarded"'
    )
    findings = [
        f for f in MetricsRegistryRule().check_project(project)
        if f.path == SERVICE and "tutoring_degarded" in f.message
    ]
    assert findings, "a typo'd metric name must fail metrics-registry"


SLO = "distributed_lms_raft_llm_tpu/sim/slo.py"


def test_slo_read_of_undeclared_series_fails_lint():
    """PR-11 acceptance pin: SLO bounds read metric names through the
    registry constants + shared snapshot readers, and the
    metrics-registry rule checks the READ sites — reverting a constant
    back to a (typo'd) literal makes the bound silently read 0 forever,
    and must fail lint."""
    project = _project_with_patch(SLO, (
        "snap_counter(s, metric.TUTORING_DEGRADED)",
        'snap_counter(s, "tutoring_degarded")',
    ))
    findings = [
        f for f in MetricsRegistryRule().check_project(project)
        if f.path == SLO and "tutoring_degarded" in f.message
    ]
    assert findings, "an SLO read of an undeclared series must fail " \
        "metrics-registry"


def test_slo_windowed_read_of_undeclared_series_fails_lint():
    """Same class at the timeline window queries: a burn-rate evaluator
    bound to a never-declared series must fail lint."""
    project = _project_with_patch(SLO, (
        "self.cluster.counter_rate(metric.RAFT_TICK_STALLS,\n"
        "                                             window_s, now)",
        'self.cluster.counter_rate("raft_tick_stals",\n'
        "                                             window_s, now)",
    ))
    findings = [
        f for f in MetricsRegistryRule().check_project(project)
        if f.path == SLO and "raft_tick_stals" in f.message
    ]
    assert findings, "a windowed read of an undeclared series must fail " \
        "metrics-registry"


# ------------------------------------------- reversion pins (absint, PR 6)


PAGED = "distributed_lms_raft_llm_tpu/engine/paged.py"


def test_semantically_divergent_state_plane_spec_fails_lint():
    """Re-introducing a state-plane spec that differs in MEANING (both
    spellings individually canonical, so `canonical-pspec` stays silent)
    must fail pspec-flow — the class behind the PR-2 recompile. Since the
    plane table took over the policy, the divergence is a producer that
    stops consulting the table: _canon_state respelling every plane onto
    dp disagrees with the table's declared specs."""
    from distributed_lms_raft_llm_tpu.analysis.rules.pspec_flow import (
        PSpecFlowRule,
    )

    project = _project_with_patch(PAGED, (
        "sh = jax.sharding.NamedSharding(self.mesh, _plane_spec(name))",
        'sh = jax.sharding.NamedSharding(self.mesh, '
        'jax.sharding.PartitionSpec("dp"))',
    ))
    findings = [
        f for f in PSpecFlowRule().check_project(project) if f.path == PAGED
    ]
    assert findings, "a dispatch-boundary respell under a different " \
        "sharding must fail pspec-flow"
    assert any("plane table" in f.message for f in findings), \
        "the finding must name the plane table the producer disagrees with"


def test_unrebound_donated_state_fails_lint():
    """Donating the live SlotState without rebinding `self.state` in the
    same statement leaves the engine pointing at deleted buffers — the
    exact failure PagedEngine.reset documents."""
    from distributed_lms_raft_llm_tpu.analysis.rules.donation_safety import (
        DonationSafetyRule,
    )

    project = _project_with_patch(PAGED, (
        "self.state, toks, active = self._step(\n"
        "                        self.params, self.state, rng\n"
        "                    )",
        "toks, active = self._step(\n"
        "                        self.params, self.state, rng\n"
        "                    )[1:]",
    ))
    findings = [
        f for f in DonationSafetyRule().check_project(project)
        if f.path == PAGED
    ]
    assert findings, "a donated self.state with no rebinding must fail " \
        "donation-safety"


def test_removing_warmup_coverage_fails_lint():
    """Gutting warmup's step coverage (the direct step AND the drain that
    reaches step through the call graph) must fail program-inventory —
    the static half; partial removals that static reachability cannot see
    are the runtime guard's half (tests/test_program_inventory.py)."""
    from distributed_lms_raft_llm_tpu.analysis.rules.program_inventory import (
        ProgramInventoryRule,
    )

    project = _project_with_patch(PAGED, (
        "self.state = self._step(self.params, self.state, rng)[0]",
        "pass",
    ), (
        'rid = self.submit("warmup")\n        self.drain()',
        "rid = 0",
    ))
    findings = [
        f for f in ProgramInventoryRule().check_project(project)
        if "warmup no longer covers" in f.message
    ]
    assert findings, "a warmup that cannot reach _step must fail " \
        "program-inventory"


def test_donating_a_shared_prefix_block_fails_lint():
    """PR-10 acceptance pin: shared-prefix tree blocks are immutable
    shared structure — an in-place write (donation) to a shared block
    plane would free KV other admissions still splice from. Donating the
    block argument of the splice program must fail donation-safety."""
    from distributed_lms_raft_llm_tpu.analysis.rules.donation_safety import (
        DonationSafetyRule,
    )

    project = _project_with_patch(PAGED, (
        "partial(_load_block_program), donate_argnums=(0,),",
        "partial(_load_block_program), donate_argnums=(0, 1),",
    ))
    findings = [
        f for f in DonationSafetyRule().check_project(project)
        if f.path == PAGED and "blk" in f.message
    ]
    assert findings, "a donated shared block plane must fail " \
        "donation-safety"


def test_uninventoried_fused_admission_jit_entry_fails_lint():
    """PR-12 acceptance pin: the fused-admission program family
    (_stage/_stage_block) is inventoried like every other jit entry — a
    new staged-admission program added without regenerating the manifest
    must fail program-inventory."""
    from distributed_lms_raft_llm_tpu.analysis.rules.program_inventory import (
        ProgramInventoryRule,
    )

    project = _project_with_patch(PAGED, (
        "self._stage = jax.jit(",
        "self._rogue_stage = jax.jit(\n"
        "            partial(_stage_program), donate_argnums=(0,),\n"
        "        )\n"
        "        self._stage = jax.jit(",
    ))
    findings = [
        f for f in ProgramInventoryRule().check_project(project)
        if "uninventoried" in f.message
    ]
    assert findings, "a staged-admission jit entry missing from the " \
        "manifest must fail program-inventory"


def test_host_readback_in_staged_reap_fails_lint():
    """PR-12 acceptance pin: the staged-admission reap learns flips from
    planes read INSIDE `with intended_transfer():` — the one sanctioned
    sync point. A host readback of the flipped plane outside it (what
    reverting the batched-reap design to an eager per-flip sync would
    look like) must fail no-host-sync-in-dispatch."""
    from distributed_lms_raft_llm_tpu.analysis.rules.host_sync import (
        HostSyncInDispatchRule,
    )

    project = _project_with_patch(PAGED, (
        "                col = (np.zeros((k_axis,), bool) if flipped is None\n"
        "                       else flipped[:, slot])",
        "                col = np.asarray(flipped_dev)[:, slot]",
    ))
    findings = HostSyncInDispatchRule().check(project.sources[PAGED])
    assert findings, "a host readback in the staged-admission reap " \
        "outside intended_transfer() must fail no-host-sync-in-dispatch"


SCORING = "distributed_lms_raft_llm_tpu/engine/scoring.py"


def test_host_sync_in_score_quantum_loop_fails_lint():
    """PR-15 acceptance pin: engine/scoring.py is a dispatch module — a
    bare `.item()` dropped into the quantum loop (a per-quantum device
    round trip on the serving chip) must fail no-host-sync-in-dispatch,
    same as it would in the decode path."""
    from distributed_lms_raft_llm_tpu.analysis.rules.host_sync import (
        HostSyncInDispatchRule,
    )

    project = _project_with_patch(SCORING, (
        'tokens = sum(int(r["tokens"]) for r in results)',
        "tokens = device_total.item()",
    ))
    findings = HostSyncInDispatchRule().check(project.sources[SCORING])
    assert findings, "a bare .item() in the scoring quantum loop must " \
        "fail no-host-sync-in-dispatch"


def test_uninventoried_score_jit_entry_fails_lint():
    """PR-15 acceptance pin: the score program is inventoried like every
    other jit entry — a second scoring program added without
    regenerating the manifest must fail program-inventory."""
    from distributed_lms_raft_llm_tpu.analysis.rules.program_inventory import (
        ProgramInventoryRule,
    )

    project = _project_with_patch(PAGED, (
        "self._score = jax.jit(",
        "self._rogue_score = jax.jit(\n"
        "            partial(_score_program, cfg=self.cfg, "
        "model=self.family)\n"
        "        )\n"
        "        self._score = jax.jit(",
    ))
    findings = [
        f for f in ProgramInventoryRule().check_project(project)
        if "uninventoried" in f.message
    ]
    assert findings, "a scoring jit entry missing from the manifest " \
        "must fail program-inventory"


def test_uninventoried_jit_entry_fails_lint():
    from distributed_lms_raft_llm_tpu.analysis.rules.program_inventory import (
        ProgramInventoryRule,
    )

    project = _project_with_patch(PAGED, (
        "self._grow = jax.jit(",
        "self._rogue = jax.jit(\n"
        "            _grow_state_program, static_argnums=(1,), "
        "donate_argnums=(0,)\n"
        "        )\n"
        "        self._grow = jax.jit(",
    ))
    findings = [
        f for f in ProgramInventoryRule().check_project(project)
        if "uninventoried" in f.message
    ]
    assert findings, "a new jit entry point missing from the manifest " \
        "must fail program-inventory"


# ------------------------------- reversion pins (effects & taint, PR 18)


STATE = "distributed_lms_raft_llm_tpu/lms/state.py"


def test_clock_read_in_applier_fails_lint():
    """PR 18 acceptance pin: a wall-clock read inside a replicated
    applier (each replica would stamp its OWN time and the state digests
    diverge) must fail state-machine-determinism. Timestamps are minted
    leader-side pre-propose and ride the Entry."""
    from distributed_lms_raft_llm_tpu.analysis.rules \
        .state_machine_determinism import StateMachineDeterminismRule

    project = _project_with_patch(STATE, (
        'assignment["grade"] = a["grade"]',
        'assignment["grade"] = a["grade"]\n'
        '            assignment["graded_at"] = time.time()',
    ))
    findings = [
        f for f in StateMachineDeterminismRule().check_project(project)
        if f.path == STATE and "reads-clock" in f.message
    ]
    assert findings, (
        "time.time() in _apply_gradeassignment must fail "
        "state-machine-determinism"
    )


def test_rng_read_in_applier_fails_lint():
    """Same class, RNG flavor: minting an id inside an applier gives
    every replica a different id for the same Entry. Ids come from
    lms/minting.py BEFORE propose."""
    from distributed_lms_raft_llm_tpu.analysis.rules \
        .state_machine_determinism import StateMachineDeterminismRule

    project = _project_with_patch(STATE, (
        'assignment["grade"] = a["grade"]',
        'assignment["grade"] = uuid.uuid4().int',
    ))
    findings = [
        f for f in StateMachineDeterminismRule().check_project(project)
        if f.path == STATE and "reads-rng" in f.message
    ]
    assert findings, (
        "uuid.uuid4() in _apply_gradeassignment must fail "
        "state-machine-determinism"
    )


def test_unordered_apply_iteration_fails_lint():
    """PR 18 sweep pin: the _apply_dropkeys bug class — iterating a set
    while building replicated structure makes insertion order depend on
    per-process hash randomization. Reverting the dict.fromkeys fix must
    fail state-machine-determinism."""
    from distributed_lms_raft_llm_tpu.analysis.rules \
        .state_machine_determinism import StateMachineDeterminismRule

    project = _project_with_patch(STATE, (
        'users = list(dict.fromkeys(a["users"]))',
        'users = set(a["users"])',
    ))
    findings = [
        f for f in StateMachineDeterminismRule().check_project(project)
        if f.path == STATE and "unordered-iter" in f.message
    ]
    assert findings, (
        "set iteration writing replicated state in _apply_dropkeys must "
        "fail state-machine-determinism"
    )


def test_unsigned_group_metadata_read_fails_lint():
    """PR 18 acceptance pin: routing trust decisions read x-lms-group
    through _signed_md (HMAC-verified). Bypassing the verifier with the
    raw metadata reader (what reverting PR 16's hardening would do) must
    fail wire-taint."""
    from distributed_lms_raft_llm_tpu.analysis.rules.wire_taint import (
        WireTaintRule,
    )

    project = _project_with_patch(ROUTER, (
        "raw = self._signed_md(context).get(GROUP_METADATA_KEY)",
        "raw = _metadata_get(context, GROUP_METADATA_KEY)",
    ))
    findings = [
        f for f in WireTaintRule().check_project(project)
        if f.path == ROUTER and "x-lms-group" in f.message
    ]
    assert findings, (
        "reading x-lms-group without _signed_md must fail wire-taint"
    )


def test_secret_equality_compare_fails_lint():
    """PR 18 sweep pin: password verification uses
    hmac.compare_digest — reverting to `==` reintroduces the
    timing-oracle compare and must fail wire-taint."""
    from distributed_lms_raft_llm_tpu.analysis.rules.wire_taint import (
        WireTaintRule,
    )

    project = _project_with_patch(STATE, (
        'return hmac.compare_digest(\n'
        '            user["password"], '
        'hash_password(password, user.get("salt", ""))\n'
        '        )',
        'return user["password"] == hash_password('
        'password, user.get("salt", ""))',
    ))
    findings = [
        f for f in WireTaintRule().check_project(project)
        if f.path == STATE and "compare_digest" in f.message
    ]
    assert findings, (
        "a == compare against the stored password hash must fail "
        "wire-taint"
    )


# ------------------------------------------- concurrency reversion pins


BATCHER = "distributed_lms_raft_llm_tpu/engine/batcher.py"
TRANSPORT = "distributed_lms_raft_llm_tpu/raft/grpc_transport.py"
RESILIENCE = "distributed_lms_raft_llm_tpu/utils/resilience.py"
METRICS_IMPL = "distributed_lms_raft_llm_tpu/utils/metrics.py"


def test_pr13_breaker_callback_deadlock_reconstruction_fails_lint():
    """The PR-13 incident, reconstructed: make _on_breaker_change read
    the live (locked) state_code() of a sibling breaker again instead of
    the cached code. The interprocedural chain — transition fires the
    callback under CircuitBreaker._lock, the callback's lockset (via the
    sibling's state property) re-enters the same declaration-site lock —
    must fail lock-order, with the dynamic callback invocation site
    among the findings."""
    project = _project_with_patch(POOL, (
        "self._breaker_codes[node.index] = CircuitBreaker._STATE_CODES[new]",
        "self._breaker_codes[node.index] = node.breaker.state_code()",
    ))
    findings = LockOrderRule().check_project(project)
    assert findings, (
        "re-reading live breaker state from the state-change callback "
        "must fail lock-order"
    )
    assert any(
        f.path == RESILIENCE and "cb(...)" in f.message for f in findings
    ), "the callback invocation under CircuitBreaker._lock must be flagged"


def test_await_under_threading_lock_fails_lint():
    """What a careless async refactor of Metrics would produce: a
    suspension point inside the `with self._lock:` critical section.
    Metrics._lock is a threading lock (OrderedLock), so the lock would
    stay held across the yield and every other task touching metrics
    blocks the loop thread."""
    project = _project_with_patch(METRICS_IMPL, (
        "    def set_gauge(self",
        "    async def render_async(self):\n"
        "        with self._lock:\n"
        "            await asyncio.sleep(0)\n"
        "            return dict(self._gauges)\n"
        "\n"
        "    def set_gauge(self",
    ))
    findings = [
        f for f in AwaitUnderLockRule().check_project(project)
        if f.path == METRICS_IMPL
    ]
    assert findings, (
        "an await inside a threading-lock critical section must fail "
        "await-under-lock"
    )


def test_forgotten_cancel_turns_absorb_into_swallow_fails_lint():
    """The canceller-absorb allowance is precise: drop the .cancel()
    call from the batcher's close() and the same `except CancelledError:
    pass` becomes a genuine cancellation swallow (awaiting a task it
    never cancelled), which must fail cancellation-safety."""
    root = repo_root()
    path = root / BATCHER
    text = path.read_text()
    old = "            self._runner.cancel()\n"
    assert old in text, "pin is stale: batcher close() no longer cancels"
    src = Source(path, root=root, text=text.replace(old, "", 1))
    rule = CancellationSafetyRule()
    findings = [
        f for f in rule.check(src)
        if not src.suppressed(f.rule, f.line) and "swallows" in f.message
    ]
    assert findings, (
        "an un-cancelled CancelledError absorb must fail "
        "cancellation-safety"
    )


def test_reverting_transport_close_snapshot_fix_fails_lint():
    """Revert the grpc transport's snapshot-then-clear shutdown fix
    (clear() back after the awaits) and the clear once again acts on a
    pre-await read of a live dict — atomicity-across-await must flag
    it."""
    project = _project_with_patch(TRANSPORT, (
        "        channels = list(self._channels.values())\n"
        "        self._channels.clear()\n"
        "        self._stubs.clear()\n"
        "        for channel in channels:\n"
        "            await channel.close()\n",
        "        for channel in self._channels.values():\n"
        "            await channel.close()\n"
        "        self._channels.clear()\n"
        "        self._stubs.clear()\n",
    ))
    findings = [
        f for f in AtomicityAcrossAwaitRule().check_project(project)
        if f.path == TRANSPORT and "_channels" in f.message
    ]
    assert findings, (
        "clearing the channel dict after awaiting closes must fail "
        "atomicity-across-await"
    )


# ------------------------------------------------------ lint wall budget


def test_full_lint_run_stays_within_wall_budget():
    """The suite runs the full rule set several times (here, the CLI
    test, fixture tests); the shared AST cache keeps that cheap. A cold
    full run measures low-20s on a loaded dev box (the interprocedural
    rules build a whole-tree call graph + concurrency engine); 30 s
    leaves noise headroom while an accidental O(files^2) regression —
    which blows past minutes — still fails loudly."""
    import time

    t0 = time.monotonic()
    findings = run_lint()
    dt = time.monotonic() - t0
    assert not findings
    assert dt < 30.0, f"full lint run took {dt:.1f}s (budget 30s)"


# --------------------------------------------------- registry <-> README


def test_metrics_registry_declarations_are_live():
    specs = metrics_registry.all_metrics()
    assert len(specs) >= 25
    kinds = {s.kind for s in specs}
    assert kinds <= {"counter", "gauge", "histogram"}
    # The names the rest of the suite depends on stay declared.
    for name in ("llm_ttft", "ttft", "shed_expired", "shed_overload",
                 "spec_tokens_per_window", "raft_tick_lag",
                 "blob_fetch_budget_exhausted", "replicate_budget_exhausted"):
        assert metrics_registry.is_declared(name), name


def test_readme_metrics_table_matches_registry():
    """README's metrics catalog is generated from the registry
    (scripts/gen_metrics_table.py --write); drift fails tier-1."""
    text = (REPO / "README.md").read_text()
    begin, end = "<!-- metrics-table:begin -->", "<!-- metrics-table:end -->"
    assert begin in text and end in text, "README lost the table markers"
    block = text[text.index(begin): text.index(end) + len(end)]
    want = f"{begin}\n{metrics_registry.render_markdown_table()}\n{end}"
    assert block == want, (
        "README metrics table is stale; run "
        "`python scripts/gen_metrics_table.py --write`"
    )
