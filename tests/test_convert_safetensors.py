"""End-to-end checkpoint path: HF state_dict -> .safetensors -> our loader
-> JAX params -> logits parity with the torch model.

This proves the real-weights serving path byte-for-byte: the exact file
format HF publishes checkpoints in flows through `save_safetensors` /
`load_safetensors` / `gpt2_params_from_hf` / `bert_params_from_hf` and the
resulting JAX model matches torch logits. (No pretrained weights exist on
this image — zero egress — so the state dicts come from HF-architecture
models with random weights, which exercises the identical code path.)
Reference analogue: GUI_RAFT_LLM_SourceCode/tutoring_server.py:10-12 and
lms_server.py:1258-1260 load the same architectures from the HF hub.
"""

import dataclasses

import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp

from distributed_lms_raft_llm_tpu.models import bert as bert_lib
from distributed_lms_raft_llm_tpu.models import convert
from distributed_lms_raft_llm_tpu.models import gpt2 as gpt2_lib


def _to_safetensors(path, model):
    sd = {k: v.detach().cpu().numpy() for k, v in model.state_dict().items()}
    # HF ties lm_head.weight to wte; safetensors rejects shared storage dupes.
    sd.pop("lm_head.weight", None)
    convert.save_safetensors(str(path), sd)


def test_gpt2_safetensors_roundtrip_matches_hf(tmp_path):
    hf_cfg = transformers.GPT2Config(
        vocab_size=211, n_positions=64, n_embd=48, n_layer=3, n_head=4
    )
    torch.manual_seed(0)
    hf_model = transformers.GPT2LMHeadModel(hf_cfg).eval()
    ckpt = tmp_path / "gpt2.safetensors"
    _to_safetensors(ckpt, hf_model)

    cfg = convert.gpt2_config_from_hf(hf_cfg.to_dict())
    cfg = dataclasses.replace(cfg, dtype=jnp.float32, param_dtype=jnp.float32)
    sd = convert.load_safetensors(str(ckpt))
    params = convert.gpt2_params_from_hf(sd, cfg)

    ids = np.array([[1, 7, 42, 5, 200, 3, 17, 9]], np.int32)
    ours, _ = gpt2_lib.forward(params, cfg, jnp.asarray(ids))
    with torch.no_grad():
        theirs = hf_model(torch.tensor(ids, dtype=torch.long)).logits.numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-4, rtol=2e-3)


def test_gpt2_safetensors_bf16_checkpoint(tmp_path):
    """BF16-stored checkpoints load through the same path (HF publishes
    bf16 checkpoints for large models)."""
    hf_cfg = transformers.GPT2Config(
        vocab_size=97, n_positions=32, n_embd=32, n_layer=2, n_head=2
    )
    torch.manual_seed(1)
    hf_model = transformers.GPT2LMHeadModel(hf_cfg).eval()
    sd = {
        k: v.detach().to(torch.bfloat16).float().numpy().astype(np.float32)
        for k, v in hf_model.state_dict().items()
        if k != "lm_head.weight"
    }
    # store as actual BF16 via jax arrays
    sd_bf16 = {k: jnp.asarray(v, jnp.bfloat16) for k, v in sd.items()}
    ckpt = tmp_path / "gpt2_bf16.safetensors"
    convert.save_safetensors(str(ckpt), sd_bf16)

    loaded = convert.load_safetensors(str(ckpt))
    for k, v in sd.items():
        np.testing.assert_allclose(loaded[k], v, atol=0, rtol=0)  # exact:
        # values were already bf16-rounded before the save/load cycle.


def test_bert_safetensors_roundtrip_matches_hf(tmp_path):
    hf_cfg = transformers.BertConfig(
        vocab_size=131,
        hidden_size=48,
        num_hidden_layers=2,
        num_attention_heads=4,
        intermediate_size=96,
        max_position_embeddings=64,
    )
    torch.manual_seed(2)
    hf_model = transformers.BertModel(hf_cfg).eval()
    ckpt = tmp_path / "bert.safetensors"
    _to_safetensors(ckpt, hf_model)

    cfg = convert.bert_config_from_hf(hf_cfg.to_dict())
    cfg = dataclasses.replace(cfg, dtype=jnp.float32, param_dtype=jnp.float32)
    sd = convert.load_safetensors(str(ckpt))
    params = convert.bert_params_from_hf(sd, cfg)

    ids = np.array([[2, 45, 99, 7, 130, 12]], np.int32)
    mask = np.ones_like(ids, bool)
    ours = bert_lib.forward(params, cfg, jnp.asarray(ids), jnp.asarray(mask))
    with torch.no_grad():
        theirs = hf_model(
            torch.tensor(ids, dtype=torch.long),
            attention_mask=torch.tensor(mask, dtype=torch.long),
        ).last_hidden_state.numpy()
    np.testing.assert_allclose(np.asarray(ours), theirs, atol=2e-4, rtol=2e-3)


def test_engine_loads_safetensors_checkpoint(tmp_path):
    """The serving engine boots from a checkpoint file + real BPE vocab and
    generates — the full real-weights path in one test."""
    tokenizers = pytest.importorskip("tokenizers")
    from distributed_lms_raft_llm_tpu.engine import (
        EngineConfig,
        SamplingParams,
        TutoringEngine,
    )

    # Real BPE vocab trained on the fly.
    corpus = tmp_path / "corpus.txt"
    corpus.write_text(
        "students ask questions instructors answer them\n" * 40, encoding="utf-8"
    )
    bpe = tokenizers.ByteLevelBPETokenizer()
    bpe.train([str(corpus)], vocab_size=384, min_frequency=1,
              special_tokens=["<|endoftext|>"])
    bpe.save_model(str(tmp_path))

    hf_cfg = transformers.GPT2Config(
        vocab_size=384, n_positions=64, n_embd=32, n_layer=2, n_head=4
    )
    torch.manual_seed(3)
    _to_safetensors(tmp_path / "m.safetensors",
                    transformers.GPT2LMHeadModel(hf_cfg).eval())

    engine = TutoringEngine(
        EngineConfig(
            model="tiny",
            checkpoint=str(tmp_path / "m.safetensors"),
            vocab_path=str(tmp_path / "vocab.json"),
            merges_path=str(tmp_path / "merges.txt"),
            sampling=SamplingParams.reference_defaults(max_new_tokens=8),
            length_buckets=(16,),
            batch_buckets=(1, 2),
        )
    )
    answers = engine.answer_batch(["what is an assignment?"])
    assert len(answers) == 1
    assert isinstance(answers[0], str)
