"""Ring attention (parallel/ring.py): exact parity with dense causal
attention while the sequence is sharded over the sp mesh axis."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

from distributed_lms_raft_llm_tpu.models.common import attend
from distributed_lms_raft_llm_tpu.parallel import make_mesh
from distributed_lms_raft_llm_tpu.parallel.ring import ring_attention


def _dense_causal(q, k, v):
    t = q.shape[2]
    pos = jnp.arange(t)
    mask = (pos[None, :] <= pos[:, None])[None, None]
    return attend(q, k, v, mask)


@pytest.mark.parametrize("sp", [2, 4, 8])
def test_ring_matches_dense_causal(sp):
    mesh = make_mesh({"sp": sp, "dp": -1})
    rng = np.random.default_rng(0)
    b, h, t, dh = 2, 4, 32, 16
    q = jnp.asarray(rng.normal(size=(b, h, t, dh)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(b, h, t, dh)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(b, h, t, dh)).astype(np.float32))
    dense = _dense_causal(q, k, v)
    with mesh:
        # Full-rank shard_map spec (rank documentation, never a jit cache
        # key).
        ring = ring_attention(
            q, k, v, mesh,
            spec=P(None, None, "sp", None),  # lint: disable=canonical-pspec
        )
    np.testing.assert_allclose(
        np.asarray(dense), np.asarray(ring), rtol=2e-5, atol=2e-5
    )


def test_ring_composes_with_dp_and_tp():
    """sp=2 x tp=2 x dp=2 on the 8-device mesh: batch, heads, and sequence
    all sharded at once."""
    mesh = make_mesh({"sp": 2, "tp": 2, "dp": -1})
    assert mesh.shape["dp"] == 2
    rng = np.random.default_rng(1)
    b, h, t, dh = 4, 4, 16, 8
    q = jnp.asarray(rng.normal(size=(b, h, t, dh)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(b, h, t, dh)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(b, h, t, dh)).astype(np.float32))
    dense = _dense_causal(q, k, v)
    with mesh:
        ring = ring_attention(q, k, v, mesh)
    np.testing.assert_allclose(
        np.asarray(dense), np.asarray(ring), rtol=2e-5, atol=2e-5
    )


def test_ring_under_jit_and_grad():
    """Differentiable + jittable: the training path can use it."""
    mesh = make_mesh({"sp": 4, "dp": -1})
    rng = np.random.default_rng(2)
    b, h, t, dh = 1, 2, 16, 8
    q = jnp.asarray(rng.normal(size=(b, h, t, dh)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(b, h, t, dh)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(b, h, t, dh)).astype(np.float32))

    spec = P(None, None, "sp", None)  # lint: disable=canonical-pspec

    def ring_loss(q, k, v):
        with mesh:
            return jnp.sum(ring_attention(q, k, v, mesh, spec=spec) ** 2)

    def dense_loss(q, k, v):
        return jnp.sum(_dense_causal(q, k, v) ** 2)

    g_ring = jax.jit(jax.grad(ring_loss))(q, k, v)
    g_dense = jax.grad(dense_loss)(q, k, v)
    np.testing.assert_allclose(
        np.asarray(g_ring), np.asarray(g_dense), rtol=1e-4, atol=1e-4
    )
