"""Runtime guards (utils/guards.py): the dynamic counterparts of the lint
rules — compile-count guard, strict-dispatch transfer guard wiring, and the
asyncio loop-stall watchdog on the Raft tick loop.
"""

import asyncio
import time

import jax
import jax.numpy as jnp
import pytest

from distributed_lms_raft_llm_tpu.utils.guards import (
    LoopWatchdog,
    RecompileError,
    compile_count_guard,
    intended_transfer,
    make_tick_watchdog,
    strict_dispatch,
)
from distributed_lms_raft_llm_tpu.utils.metrics import Metrics


# ------------------------------------------------------ compile-count guard


def test_compile_count_guard_passes_when_warm():
    fn = jax.jit(lambda x: x * 2)
    fn(jnp.ones((4,)))  # warm
    with compile_count_guard(fn) as guard:
        fn(jnp.ones((4,)))
        fn(jnp.zeros((4,)))  # same shape: cached program
    assert guard.new_compiles() == 0


def test_compile_count_guard_catches_recompiles():
    fn = jax.jit(lambda x: x * 2)
    fn(jnp.ones((4,)))
    with pytest.raises(RecompileError, match="1 new program"):
        with compile_count_guard(fn, what="shape change"):
            fn(jnp.ones((8,)))  # new shape: new program


def test_compile_count_guard_allowance_and_multiple_fns():
    f = jax.jit(lambda x: x + 1)
    g = jax.jit(lambda x: x - 1)
    with compile_count_guard(f, g, allow=2):
        f(jnp.ones((2,)))
        g(jnp.ones((2,)))


def test_compile_count_guard_rejects_unjitted():
    with pytest.raises(TypeError, match="not a jitted callable"):
        with compile_count_guard(lambda x: x):
            pass


# -------------------------------------------------------- transfer guards


def test_strict_dispatch_sets_and_restores_transfer_guard():
    """The scoped guard installs jax's device->host disallow mode and
    restores the previous mode on exit. (The CPU backend's readbacks are
    zero-copy and never trip the guard, so enforcement is exercised on
    real accelerators; here we pin the wiring.)"""
    before = jax.config.jax_transfer_guard_device_to_host
    with strict_dispatch():
        assert (jax.config.jax_transfer_guard_device_to_host == "disallow")
        # Marked sync points re-allow inside the strict scope.
        with intended_transfer():
            assert (jax.config.jax_transfer_guard_device_to_host == "allow")
            import numpy as np

            np.asarray(jnp.arange(3))  # sanctioned readback
        assert (jax.config.jax_transfer_guard_device_to_host == "disallow")
    assert jax.config.jax_transfer_guard_device_to_host == before


def test_strict_dispatch_warns_once_on_cpu_backend():
    """On the CPU backend the transfer guard is a physical no-op (CPU
    readbacks are zero-copy): strict dispatch must say so ONCE and point
    at the lint rule that enforces there — never silently pretend to
    guard."""
    import logging

    from distributed_lms_raft_llm_tpu.utils import guards

    assert jax.default_backend() == "cpu", "suite runs on the CPU backend"
    guards._warned_cpu_noop = False  # re-arm the one-time warning
    try:
        records = []
        handler = logging.Handler()
        handler.emit = records.append
        logger = logging.getLogger("distributed_lms_raft_llm_tpu.utils.guards")
        logger.addHandler(handler)
        try:
            with strict_dispatch():
                pass
            with strict_dispatch():  # second entry: no second warning
                pass
        finally:
            logger.removeHandler(handler)
        warnings = [
            r for r in records
            if r.levelno == logging.WARNING and "no-op on the CPU" in
            r.getMessage()
        ]
        assert len(warnings) == 1, [r.getMessage() for r in records]
        assert "no-host-sync-in-dispatch" in warnings[0].getMessage()
    finally:
        guards._warned_cpu_noop = True  # leave the suite quiet


def test_engine_hot_path_runs_under_strict_dispatch():
    """The paged engine's submit->step->reap loop completes under strict
    dispatch: every host sync on the path is wrapped in
    intended_transfer() (the same marker the lint rule checks)."""
    from distributed_lms_raft_llm_tpu.engine import EngineConfig, PagedEngine
    from distributed_lms_raft_llm_tpu.engine.sampling import SamplingParams

    eng = PagedEngine(
        EngineConfig(
            model="tiny",
            sampling=SamplingParams(max_new_tokens=4),
            length_buckets=(8,),
            batch_buckets=(1, 2),
            dtype=jnp.float32,
        ),
        slots=2,
        chunk=2,
    )
    with strict_dispatch():
        rid = eng.submit("a question")
        out = eng.drain()
    assert isinstance(out[rid], str)


# ---------------------------------------------------------- loop watchdog


def test_watchdog_records_lag_and_counts_stalls():
    clock = [0.0]
    metrics = Metrics()
    wd = LoopWatchdog(metrics, name="tick", warn_above_s=0.1,
                      clock=lambda: clock[0])
    wd.observe(0.01)   # healthy
    wd.observe(0.5)    # stall
    clock[0] += 100.0  # past the warn rate limit
    wd.observe(0.9)    # stall
    snap = metrics.snapshot()
    assert snap["latency"]["tick_lag"]["count"] == 3
    assert snap["counters"]["tick_stalls"] == 2
    assert wd.max_lag_s == pytest.approx(0.9)
    assert wd.stalls == 2


def test_watchdog_negative_lag_clamped():
    wd = LoopWatchdog(None, name="t", warn_above_s=1.0)
    wd.observe(-0.5)
    assert wd.max_lag_s == 0.0
    assert wd.stalls == 0


def test_make_tick_watchdog_thresholds():
    metrics = Metrics()
    wd = make_tick_watchdog(metrics, tick_interval=0.01)
    assert wd is not None
    assert wd.warn_above_s == pytest.approx(0.1)
    assert make_tick_watchdog(None, tick_interval=0.01) is None


def test_raft_tick_loop_feeds_the_watchdog():
    """RaftNode wiring: a blocking apply callback on the loop shows up as
    tick lag in /metrics (raft_tick_lag histogram + raft_tick_stalls)."""
    from distributed_lms_raft_llm_tpu.raft.node import MemNetwork, RaftNode
    from distributed_lms_raft_llm_tpu.raft.storage import MemoryStorage

    async def run():
        metrics = Metrics()
        net = MemNetwork()
        node = RaftNode(
            1, {1: ""}, MemoryStorage(), net.transport_for(1),
            tick_interval=0.005,
            watchdog=LoopWatchdog(metrics, name="raft_tick",
                                  warn_above_s=0.05),
        )
        net.register(node)
        await node.start()
        try:
            # Give the single-node cluster time to elect itself and tick.
            await asyncio.sleep(0.1)
            # Stall the LOOP (not the node): exactly what the watchdog is
            # for — a blocking call anywhere on the shared loop (and
            # exactly what the lint rule flags; here the block is the
            # point).  # lint: disable-next=no-blocking-in-async
            time.sleep(0.12)
            await asyncio.sleep(0.05)
        finally:
            await node.stop()
        return metrics.snapshot()

    snap = asyncio.run(run())
    assert snap["latency"]["raft_tick_lag"]["count"] > 0
    assert snap["latency"]["raft_tick_lag"]["max_s"] >= 0.1
    assert snap["counters"]["raft_tick_stalls"] >= 1


def test_lms_node_wires_watchdog_into_metrics(tmp_path):
    """LMSNode(metrics=...) hands the tick watchdog to its RaftNode; the
    lag series lands in the same Metrics object /metrics serves."""
    from distributed_lms_raft_llm_tpu.lms.node import LMSNode
    from distributed_lms_raft_llm_tpu.raft.node import MemNetwork

    metrics = Metrics()
    net = MemNetwork()
    node = LMSNode(1, {1: ""}, str(tmp_path / "n1"),
                   transport=net.transport_for(1), metrics=metrics)
    assert node.node.watchdog is not None
    assert node.node.watchdog.metrics is metrics
    # Without metrics the wiring degrades to no watchdog, not a crash.
    node2 = LMSNode(2, {2: ""}, str(tmp_path / "n2"),
                    transport=net.transport_for(2))
    assert node2.node.watchdog is None
