"""Seeded chaos soak: random partitions + message drops over a 5-node
cluster with continuous client writes, then heal and check Raft's safety
invariants held throughout.

The partition tests pin specific scenarios; this drives the same
`MemNetwork` fault surface with a seeded RNG for several simulated rounds
so schedule-dependent bugs (commit during reconfiguration of the partition
sets, elections racing drops, double-apply on retry) get a standing chance
to surface — deterministically reproducible by seed.
"""

import asyncio
import random

from distributed_lms_raft_llm_tpu.raft import (
    MemNetwork,
    MemoryStorage,
    NotLeader,
    RaftConfig,
    RaftNode,
    encode_command,
)

from test_raft_cluster import FAST, build_cluster, wait_for_leader


def test_chaos_partitions_and_drops_preserve_safety():
    async def run():
        rng = random.Random(0xC0FFEE)
        net = MemNetwork()
        applied = {}
        nodes, _ = build_cluster(net, 5, applied=applied)
        for n in nodes.values():
            await n.start()
        await wait_for_leader(nodes)

        acked = []  # commands the cluster ACKED committed
        seq = 0

        async def try_write():
            nonlocal seq
            leaders = [n for n in nodes.values() if n.is_leader]
            if not leaders:
                return
            cmd = encode_command("set", {"n": seq})
            seq += 1
            try:
                await asyncio.wait_for(leaders[0].propose(cmd), 0.6)
                acked.append(cmd)
            except (NotLeader, TimeoutError, asyncio.TimeoutError,
                    RuntimeError):
                pass  # unacked writes may or may not survive — both legal

        for round_no in range(12):
            fault = rng.random()
            ids = list(nodes)
            if fault < 0.4:  # random two-group partition
                rng.shuffle(ids)
                cut = rng.randint(1, 2)
                net.partition(set(ids[:cut]), set(ids[cut:]))
            elif fault < 0.7:  # random directed drops
                net.drop_pairs = {
                    (rng.choice(ids), rng.choice(ids)) for _ in range(4)
                }
            else:
                net.heal()
            for _ in range(rng.randint(1, 4)):
                await try_write()
                await asyncio.sleep(rng.uniform(0.01, 0.08))
            # Safety invariant, continuously: at most one leader per term.
            by_term = {}
            for n in nodes.values():
                if n.is_leader:
                    by_term.setdefault(n.core.current_term, []).append(
                        n.node_id
                    )
            for term, leaders in by_term.items():
                assert len(leaders) == 1, f"two leaders in term {term}"

        net.heal()
        # Converge: a leader exists and every acked write is applied on
        # every node, in the same order (state-machine safety).
        leader = await wait_for_leader(nodes)
        for _ in range(3):  # commit a barrier so all replicas catch up
            try:
                await asyncio.wait_for(leader.read_barrier(), 2.0)
                break
            except (NotLeader, TimeoutError, asyncio.TimeoutError):
                leader = await wait_for_leader(nodes)
        await asyncio.sleep(0.5)

        sequences = {
            i: [cmd for _, cmd in applied.get(i, [])] for i in nodes
        }
        reference_seq = sequences[leader.node_id]
        for i, cmds in sequences.items():
            # Prefix consistency: every replica's applied sequence is a
            # prefix of (or equal to) the leader's.
            assert cmds == reference_seq[: len(cmds)], f"divergence on {i}"
        # Durability: every ACKED write is present on the leader, once.
        for cmd in acked:
            assert reference_seq.count(cmd) == 1, f"acked write lost: {cmd}"
        assert len(acked) >= 3, "chaos schedule never committed anything"

        for n in nodes.values():
            await n.stop()

    asyncio.run(run())


def test_chaos_with_membership_changes_preserves_safety():
    """Chaos soak with ADD/REMOVE membership changes interleaved with
    partitions, drops, and writes: single-leader-per-term, prefix
    consistency, and acked-write durability must hold while the cluster
    itself grows and shrinks (the raft/core.py §4 machinery under the same
    fault surface as the plain soak)."""

    async def run():
        from distributed_lms_raft_llm_tpu.raft.core import (
            ConfigChangeInFlight,
        )

        rng = random.Random(0xFEED5EED)
        net = MemNetwork()
        applied = {}
        nodes, _ = build_cluster(net, 3, applied=applied)
        for n in nodes.values():
            await n.start()
        await wait_for_leader(nodes)

        def addr(i):
            return f"127.0.0.1:{9100 + i}"

        next_id = 4
        adds_landed = 0
        acked = []
        seq = 0

        async def try_write():
            nonlocal seq
            leaders = [n for n in nodes.values()
                       if n.is_leader and not n._stopped]
            if not leaders:
                return
            cmd = encode_command("set", {"n": seq})
            seq += 1
            try:
                await asyncio.wait_for(leaders[0].propose(cmd), 0.6)
                acked.append(cmd)
            except (NotLeader, TimeoutError, asyncio.TimeoutError,
                    RuntimeError):
                pass

        def nonlocal_adds():
            nonlocal adds_landed
            adds_landed += 1

        async def try_membership():
            nonlocal next_id
            leaders = [n for n in nodes.values()
                       if n.is_leader and not n._stopped]
            if not leaders:
                return
            leader = leaders[0]
            members = dict(leader.core.members)
            grow = len(members) < 4 or (len(members) < 6 and rng.random() < 0.6)
            try:
                if grow:
                    # Consume the id up front: a timed-out add may still
                    # commit later (Raft timeouts don't roll back), so the
                    # id must NEVER be reused for a second instance — two
                    # live nodes sharing one Raft identity would corrupt
                    # the very invariants this soak asserts.
                    nid, next_id = next_id, next_id + 1
                    storage = MemoryStorage()

                    def cb(i, e, nid=nid):
                        applied.setdefault(nid, []).append((i, e.command))

                    newborn = RaftNode(
                        nid, {**{k: addr(k) for k in members}, nid: addr(nid)},
                        storage, net.transport_for(nid),
                        apply_cb=cb,
                        config=FAST, tick_interval=0.01, seed=500 + nid,
                    )
                    net.register(newborn)
                    await newborn.start()
                    nodes[nid] = newborn
                    members[nid] = addr(nid)
                    await asyncio.wait_for(
                        leader.propose_config(members), 1.0
                    )
                    nonlocal_adds()
                else:
                    victim = rng.choice(
                        [i for i in members if i != leader.node_id]
                    )
                    members.pop(victim)
                    await asyncio.wait_for(
                        leader.propose_config(members), 1.0
                    )
            except (NotLeader, ConfigChangeInFlight, ValueError,
                    TimeoutError, asyncio.TimeoutError, RuntimeError):
                pass  # rejected/unacked changes may or may not land — legal

        for round_no in range(14):
            fault = rng.random()
            ids = [i for i in nodes if not nodes[i]._stopped]
            if fault < 0.3 and len(ids) > 2:
                rng.shuffle(ids)
                cut = rng.randint(1, max(1, len(ids) // 2 - 1))
                net.partition(set(ids[:cut]), set(ids[cut:]))
            elif fault < 0.55:
                net.drop_pairs = {
                    (rng.choice(ids), rng.choice(ids)) for _ in range(3)
                }
            else:
                net.heal()
            if rng.random() < 0.5:
                await try_membership()
            for _ in range(rng.randint(1, 3)):
                await try_write()
                await asyncio.sleep(rng.uniform(0.01, 0.06))
            by_term = {}
            for n in nodes.values():
                if n.is_leader and not n._stopped:
                    by_term.setdefault(n.core.current_term, []).append(
                        n.node_id
                    )
            for term, leaders in by_term.items():
                assert len(leaders) == 1, f"two leaders in term {term}"

        net.heal()
        leader = await wait_for_leader(nodes, timeout=8.0)
        for _ in range(3):
            try:
                await asyncio.wait_for(leader.read_barrier(), 2.0)
                break
            except (NotLeader, TimeoutError, asyncio.TimeoutError):
                leader = await wait_for_leader(nodes, timeout=8.0)
        await asyncio.sleep(0.6)

        member_ids = set(leader.core.members)
        reference_seq = [cmd for _, cmd in applied.get(leader.node_id, [])]
        for i in member_ids:
            cmds = [cmd for _, cmd in applied.get(i, [])]
            assert cmds == reference_seq[: len(cmds)], f"divergence on {i}"
        for cmd in acked:
            assert reference_seq.count(cmd) == 1, f"acked write lost: {cmd}"
        assert len(acked) >= 3, "chaos schedule never committed anything"
        # The membership machinery actually exercised growth/shrink.
        assert adds_landed > 0, "no add ever landed"

        for n in nodes.values():
            if not n._stopped:
                await n.stop()

    asyncio.run(run())


def test_chaos_with_leadership_transfers_preserves_safety():
    """Chaos soak with deliberate leadership transfers interleaved with
    partitions, drops, and writes: the §3.10 machinery (TimeoutNow,
    lease-bypassing transfer votes, proposal blocking, deadline aborts)
    must never violate single-leader-per-term, prefix consistency, or
    acked-write durability — even when the chosen target is partitioned
    away mid-transfer."""

    async def run():
        from distributed_lms_raft_llm_tpu.raft import TransferInFlight

        rng = random.Random(0x7A5F3A)
        net = MemNetwork()
        applied = {}
        nodes, _ = build_cluster(net, 5, applied=applied)
        for n in nodes.values():
            await n.start()
        await wait_for_leader(nodes)

        acked = []
        seq = 0
        transfers_ok = 0

        async def try_write():
            nonlocal seq
            leaders = [n for n in nodes.values() if n.is_leader]
            if not leaders:
                return
            cmd = encode_command("set", {"n": seq})
            seq += 1
            try:
                await asyncio.wait_for(leaders[0].propose(cmd), 0.6)
                acked.append(cmd)
            except (NotLeader, TransferInFlight, TimeoutError,
                    asyncio.TimeoutError, RuntimeError):
                pass

        async def try_transfer():
            nonlocal transfers_ok
            leaders = [n for n in nodes.values() if n.is_leader]
            if not leaders:
                return
            target = rng.choice(
                [i for i in nodes if i != leaders[0].node_id]
            )
            try:
                await leaders[0].transfer_leadership(target, timeout=1.0)
                transfers_ok += 1
            except (NotLeader, TransferInFlight, TimeoutError,
                    ValueError, RuntimeError):
                pass  # target unreachable / deposed meanwhile — both legal

        for round_no in range(12):
            fault = rng.random()
            ids = list(nodes)
            if fault < 0.3:
                rng.shuffle(ids)
                cut = rng.randint(1, 2)
                net.partition(set(ids[:cut]), set(ids[cut:]))
            elif fault < 0.55:
                net.drop_pairs = {
                    (rng.choice(ids), rng.choice(ids)) for _ in range(4)
                }
            else:
                net.heal()
            for _ in range(rng.randint(1, 3)):
                await try_write()
                await asyncio.sleep(rng.uniform(0.01, 0.06))
            await try_transfer()
            by_term = {}
            for n in nodes.values():
                if n.is_leader:
                    by_term.setdefault(n.core.current_term, []).append(
                        n.node_id
                    )
            for term, leaders in by_term.items():
                assert len(leaders) == 1, f"two leaders in term {term}"

        net.heal()
        leader = await wait_for_leader(nodes)
        for _ in range(3):
            try:
                await asyncio.wait_for(leader.read_barrier(), 2.0)
                break
            except (NotLeader, TimeoutError, asyncio.TimeoutError):
                leader = await wait_for_leader(nodes)
        await asyncio.sleep(0.5)

        sequences = {
            i: [cmd for _, cmd in applied.get(i, [])] for i in nodes
        }
        reference_seq = sequences[leader.node_id]
        for i, cmds in sequences.items():
            assert cmds == reference_seq[: len(cmds)], f"divergence on {i}"
        for cmd in acked:
            assert reference_seq.count(cmd) == 1, f"acked write lost: {cmd}"
        assert len(acked) >= 3, "chaos schedule never committed anything"
        assert transfers_ok >= 2, "no transfer ever completed under chaos"

        for n in nodes.values():
            await n.stop()

    asyncio.run(run())
