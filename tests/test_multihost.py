"""Multi-host plumbing (parallel/mesh.py): single-process behavior.

Real multi-host needs multiple processes + a coordinator, which this image
cannot spawn meaningfully; what IS testable locally is the contract: the
initializer no-ops for single-process runs, and the hybrid-mesh builder
degrades to the flat local mesh when no axis spans hosts — so the same
call sites work unchanged from 1 chip to a pod slice.
"""

import numpy as np

import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

from distributed_lms_raft_llm_tpu.parallel.mesh import (
    initialize_multihost,
    make_hybrid_mesh,
    make_mesh,
)


def test_initialize_multihost_noops_single_process(monkeypatch):
    monkeypatch.delenv("JAX_COORDINATOR_ADDRESS", raising=False)
    assert initialize_multihost() is False  # and jax still works
    assert len(jax.devices()) >= 8


def test_hybrid_mesh_degrades_to_flat_local_mesh():
    hybrid = make_hybrid_mesh({"dp": 4, "tp": 2})
    flat = make_mesh({"dp": 4, "tp": 2})
    assert dict(hybrid.shape) == dict(flat.shape)
    # A sharded computation runs on it like any other mesh.
    x = jnp.arange(8.0).reshape(4, 2)
    y = jax.device_put(x, NamedSharding(hybrid, P("dp", "tp")))
    assert float(jnp.sum(y)) == float(np.sum(np.arange(8.0)))


def test_hybrid_mesh_dcn_axis_merges_in_single_process():
    # dcn dp=1 explicitly + ici dp=2: still a well-formed 8-device mesh.
    mesh = make_hybrid_mesh({"dp": 2, "tp": 2, "sp": 2}, {"dp": 1})
    assert dict(mesh.shape)["dp"] == 2
    assert mesh.devices.size == 8


def test_hybrid_mesh_rejects_unknown_axis():
    import pytest

    with pytest.raises(ValueError, match="unknown mesh axes"):
        make_hybrid_mesh({"zz": 2})
