"""HTTP health/metrics endpoint (utils/healthz.py)."""

import asyncio
import json

from distributed_lms_raft_llm_tpu.utils.healthz import HealthServer
from distributed_lms_raft_llm_tpu.utils.metrics import Metrics


async def _get(port: int, path: str):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {path} HTTP/1.1\r\nHost: x\r\n\r\n".encode())
    await writer.drain()
    raw = await reader.read()
    writer.close()
    await writer.wait_closed()
    head, _, body = raw.partition(b"\r\n\r\n")
    status = int(head.split()[1])
    return status, json.loads(body)


def test_healthz_and_metrics_roundtrip():
    async def run():
        metrics = Metrics()
        metrics.inc("llm_requests", 3)
        metrics.hist("ttft").observe(0.123)
        hs = HealthServer(
            metrics, health=lambda: {"ok": True, "role": "leader"}
        )
        port = await hs.start()
        try:
            status, body = await _get(port, "/healthz")
            assert status == 200 and body["ok"] and body["role"] == "leader"
            status, body = await _get(port, "/metrics")
            assert status == 200
            assert body["counters"]["llm_requests"] == 3
            assert body["latency"]["ttft"]["count"] == 1
            status, body = await _get(port, "/nope")
            assert status == 404
        finally:
            await hs.stop()

    asyncio.run(run())


def test_tutoring_server_exposes_endpoint():
    """serve_async wires the endpoint; /metrics reflects served requests."""
    import grpc

    from distributed_lms_raft_llm_tpu.engine import (
        EngineConfig, SamplingParams, TutoringEngine,
    )
    from distributed_lms_raft_llm_tpu.proto import lms_pb2, rpc
    from distributed_lms_raft_llm_tpu.serving import tutoring_server

    async def run():
        engine = TutoringEngine(
            EngineConfig(
                model="tiny",
                sampling=SamplingParams.reference_defaults(max_new_tokens=8),
                length_buckets=(16,), batch_buckets=(1, 2),
            )
        )
        server = await tutoring_server.serve_async(0, engine, metrics_port=0)
        # serve_async binds the gRPC port before returning; for port 0 grab
        # the real one from the server object is not exposed — dial health.
        hport = server._health.port
        status, body = await _get(hport, "/healthz")
        assert status == 200 and body["ok"]
        assert body["engine"] == "TutoringEngine"
        status, body = await _get(hport, "/metrics")
        assert status == 200 and "counters" in body
        await server.stop(None)
        await server._health.stop()
        await server._queue.close()

    asyncio.run(run())
