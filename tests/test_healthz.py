"""HTTP health/metrics endpoint (utils/healthz.py)."""

import asyncio
import json

from distributed_lms_raft_llm_tpu.utils.healthz import HealthServer
from distributed_lms_raft_llm_tpu.utils.metrics import Metrics


async def _get(port: int, path: str):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    writer.write(f"GET {path} HTTP/1.1\r\nHost: x\r\n\r\n".encode())
    await writer.drain()
    raw = await reader.read()
    writer.close()
    await writer.wait_closed()
    head, _, body = raw.partition(b"\r\n\r\n")
    status = int(head.split()[1])
    return status, json.loads(body)


def test_healthz_and_metrics_roundtrip():
    async def run():
        metrics = Metrics()
        metrics.inc("llm_requests", 3)
        metrics.hist("ttft").observe(0.123)
        hs = HealthServer(
            metrics, health=lambda: {"ok": True, "role": "leader"}
        )
        port = await hs.start()
        try:
            status, body = await _get(port, "/healthz")
            assert status == 200 and body["ok"] and body["role"] == "leader"
            status, body = await _get(port, "/metrics")
            assert status == 200
            assert body["counters"]["llm_requests"] == 3
            assert body["latency"]["ttft"]["count"] == 1
            status, body = await _get(port, "/nope")
            assert status == 404
        finally:
            await hs.stop()

    asyncio.run(run())


def test_tutoring_server_exposes_endpoint():
    """serve_async wires the endpoint; /metrics reflects served requests."""
    import grpc

    from distributed_lms_raft_llm_tpu.engine import (
        EngineConfig, SamplingParams, TutoringEngine,
    )
    from distributed_lms_raft_llm_tpu.proto import lms_pb2, rpc
    from distributed_lms_raft_llm_tpu.serving import tutoring_server

    async def run():
        engine = TutoringEngine(
            EngineConfig(
                model="tiny",
                sampling=SamplingParams.reference_defaults(max_new_tokens=8),
                length_buckets=(16,), batch_buckets=(1, 2),
            )
        )
        server = await tutoring_server.serve_async(0, engine, metrics_port=0)
        # serve_async binds the gRPC port before returning; for port 0 grab
        # the real one from the server object is not exposed — dial health.
        hport = server._health.port
        status, body = await _get(hport, "/healthz")
        assert status == 200 and body["ok"]
        assert body["engine"] == "TutoringEngine"
        status, body = await _get(hport, "/metrics")
        assert status == 200 and "counters" in body
        await server.stop(None)
        await server._health.stop()
        await server._queue.close()

    asyncio.run(run())


async def _post(port: int, path: str, payload: dict):
    reader, writer = await asyncio.open_connection("127.0.0.1", port)
    body = json.dumps(payload).encode()
    writer.write(
        f"POST {path} HTTP/1.1\r\nHost: x\r\n"
        f"Content-Length: {len(body)}\r\n\r\n".encode() + body
    )
    await writer.drain()
    raw = await reader.read()
    writer.close()
    await writer.wait_closed()
    head, _, resp = raw.partition(b"\r\n\r\n")
    return int(head.split()[1]), json.loads(resp)


def test_admin_endpoint_roundtrip_and_errors():
    """POST /admin/* dispatches to the admin hook with the parsed JSON
    body; unknown paths 404, ValueErrors 400, other failures 500."""
    calls = []

    async def admin(path, body):
        if path != "/admin/membership":
            raise KeyError(path)
        if body.get("op") not in ("add", "remove"):
            raise ValueError("op must be 'add' or 'remove'")
        if body.get("boom"):
            raise RuntimeError("kaput")
        calls.append(body)
        return {"ok": True, "index": 7}

    async def run():
        hs = HealthServer(Metrics(), admin=admin)
        port = await hs.start()
        try:
            status, body = await _post(
                port, "/admin/membership",
                {"op": "add", "id": 6, "address": "127.0.0.1:9"},
            )
            assert status == 200 and body == {"ok": True, "index": 7}
            assert calls and calls[0]["id"] == 6
            status, body = await _post(port, "/admin/nope", {})
            assert status == 404
            status, body = await _post(port, "/admin/membership", {"op": "x"})
            assert status == 400 and "op must be" in body["error"]
            status, body = await _post(
                port, "/admin/membership", {"op": "add", "boom": True}
            )
            assert status == 500
            # GET to an admin path stays 404 when no read-only handler
            # is configured (mutations remain POST-only either way).
            status, _ = await _get(port, "/admin/membership")
            assert status == 404
        finally:
            await hs.stop()

    asyncio.run(run())


def test_admin_get_routes_read_only_introspection():
    """GET /admin/* dispatches to `admin_get` (read-only plane, e.g.
    GET /admin/faults); unknown paths 404, ValueErrors 400; POST still
    routes to the mutating handler."""
    posts = []

    async def admin(path, body):
        posts.append((path, body))
        return {"posted": True}

    async def admin_get(path):
        if path == "/admin/faults":
            return {"ok": True, "faults": {"targets": {}}}
        if path == "/admin/teapot":
            raise ValueError("short and stout")
        raise KeyError(path)

    async def run():
        hs = HealthServer(Metrics(), admin=admin, admin_get=admin_get)
        port = await hs.start()
        try:
            status, body = await _get(port, "/admin/faults")
            assert status == 200 and body["ok"] and "faults" in body
            status, body = await _get(port, "/admin/teapot")
            assert status == 400 and "stout" in body["error"]
            status, _ = await _get(port, "/admin/nope")
            assert status == 404
            # POST keeps hitting the mutating handler, not admin_get.
            status, body = await _post(port, "/admin/faults", {"x": 1})
            assert status == 200 and body == {"posted": True}
            assert posts == [("/admin/faults", {"x": 1})]
        finally:
            await hs.stop()

    asyncio.run(run())
