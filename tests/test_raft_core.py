"""Deterministic sans-IO Raft core tests: no clocks, no sockets, no sleeps."""

import pytest

from distributed_lms_raft_llm_tpu.raft import (
    AppendRequest,
    Entry,
    NotLeader,
    RaftConfig,
    RaftCore,
    Role,
    VoteRequest,
    MemoryStorage,
)
from distributed_lms_raft_llm_tpu.raft.messages import NOOP


CFG = RaftConfig(
    election_timeout_min=1.0, election_timeout_max=1.0, heartbeat_interval=0.4
)


def make(node_id=1, peers=(1, 2, 3), storage=None):
    return RaftCore(node_id, peers, storage or MemoryStorage(), CFG, now=0.0)


def test_follower_times_out_and_starts_election():
    c = make()
    c.tick(0.5)
    assert c.role is Role.FOLLOWER and not c.outbox
    c.tick(1.1)
    assert c.role is Role.CANDIDATE
    # Pre-vote semantics on the frozen wire: the candidate CAMPAIGNS with
    # term 1 but adopts (persists, self-votes) it only when a voter
    # acknowledges — disregarded campaigns never inflate terms.
    assert c.current_term == 0 and c._proposed_term == 1
    reqs = [(p, m) for p, m in c.outbox if isinstance(m, VoteRequest)]
    assert {p for p, _ in reqs} == {2, 3}
    assert all(m.term == 1 for _, m in reqs)
    from distributed_lms_raft_llm_tpu.raft.messages import VoteResponse

    c.on_vote_response(2, VoteResponse(term=1, granted=True), 1.2)
    assert c.current_term == 1 and c.voted_for == 1
    assert c.role is Role.LEADER  # self + peer 2 = quorum of 3


def test_vote_granted_once_per_term():
    c = make(node_id=2)
    req = VoteRequest(term=1, candidate_id=1, last_log_index=0, last_log_term=0)
    assert c.on_vote_request(req, 0.1).granted
    # Same candidate asks again (retry): still granted.
    assert c.on_vote_request(req, 0.2).granted
    # Different candidate, same term: denied.
    other = VoteRequest(term=1, candidate_id=3, last_log_index=0, last_log_term=0)
    assert not c.on_vote_request(other, 0.3).granted


def test_vote_denied_to_stale_log():
    storage = MemoryStorage()
    storage.entries = [Entry(term=2, command="x")]
    storage.term = 2
    c = make(node_id=2, storage=storage)
    stale = VoteRequest(term=3, candidate_id=1, last_log_index=0, last_log_term=0)
    assert not c.on_vote_request(stale, 0.1).granted
    fresh = VoteRequest(term=3, candidate_id=3, last_log_index=1, last_log_term=2)
    assert c.on_vote_request(fresh, 0.2).granted


def test_candidate_becomes_leader_on_quorum_and_appends_noop():
    c = make()
    c.tick(1.1)
    from distributed_lms_raft_llm_tpu.raft import VoteResponse

    c.on_vote_response(2, VoteResponse(term=1, granted=True), 1.2)
    assert c.role is Role.LEADER
    assert c.log[-1].command == NOOP
    # next_index points at the first entry each peer lacks — here the just-
    # appended noop (the reference's D2 off-by-one skipped the first entry).
    assert all(v == c.last_log_index for v in c.next_index.values())
    outgoing = [m for _, m in c.outbox if isinstance(m, AppendRequest)]
    assert outgoing and all(
        m.entries and m.entries[-1].command == NOOP for m in outgoing
    )


def test_append_rejects_stale_term_and_accepts_current():
    c = make(node_id=2)
    ok = c.on_append_request(
        AppendRequest(term=1, leader_id=1, prev_log_index=0, prev_log_term=0,
                      entries=(), leader_commit=0),
        0.1,
    )
    assert ok.success and c.leader_id == 1
    stale = c.on_append_request(
        AppendRequest(term=0, leader_id=3, prev_log_index=0, prev_log_term=0,
                      entries=(), leader_commit=0),
        0.2,
    )
    assert not stale.success and stale.term == 1


def test_append_conflict_truncates_and_reports_hint():
    c = make(node_id=2)
    # Install entries from term 1.
    c.on_append_request(
        AppendRequest(term=1, leader_id=1, prev_log_index=0, prev_log_term=0,
                      entries=(Entry(1, "a"), Entry(1, "b"), Entry(1, "c")),
                      leader_commit=0),
        0.1,
    )
    assert c.last_log_index == 3
    # New leader (term 3) has a different entry at index 2.
    resp = c.on_append_request(
        AppendRequest(term=3, leader_id=3, prev_log_index=2, prev_log_term=2,
                      entries=(), leader_commit=0),
        0.2,
    )
    assert not resp.success
    assert resp.conflict_index == 1  # whole term-1 run reported for fast skip
    resp = c.on_append_request(
        AppendRequest(term=3, leader_id=3, prev_log_index=1, prev_log_term=1,
                      entries=(Entry(3, "x"),), leader_commit=0),
        0.3,
    )
    assert resp.success
    assert [e.command for e in c.log] == ["a", "x"]


def test_commit_requires_majority_and_current_term():
    c = make()
    c.tick(1.1)
    from distributed_lms_raft_llm_tpu.raft import VoteResponse, AppendResponse

    c.on_vote_response(2, VoteResponse(term=1, granted=True), 1.2)
    assert c.role is Role.LEADER
    idx = c.propose("cmd", 1.3)  # index 2 (after the noop)
    assert c.commit_index == 0
    c.on_append_response(2, AppendResponse(term=1, success=True, match_index=idx), 1.4)
    assert c.commit_index == idx  # leader + one peer = quorum of 3
    applied = c.take_applies()
    assert [e.command for _, e in applied][-1] == "cmd"


def test_propose_on_follower_raises_not_leader():
    c = make()
    with pytest.raises(NotLeader):
        c.propose("cmd", 0.1)


def test_step_down_on_higher_term_response():
    c = make()
    c.tick(1.1)
    from distributed_lms_raft_llm_tpu.raft import VoteResponse

    c.on_vote_response(2, VoteResponse(term=5, granted=False), 1.2)
    assert c.role is Role.FOLLOWER
    assert c.current_term == 5


def test_restart_recovers_persistent_state():
    from distributed_lms_raft_llm_tpu.raft.messages import VoteResponse

    storage = MemoryStorage()
    c = make(storage=storage)
    c.tick(1.1)  # campaigns with proposed term 1
    c.on_vote_response(2, VoteResponse(term=1, granted=True), 1.2)
    assert c.current_term == 1  # adopted on acknowledgment, persisted
    incarnation2 = make(storage=storage)
    assert incarnation2.current_term == 1
    assert incarnation2.voted_for == 1
