"""Weight-only int8 + int8-KV-cache quantization: numeric bounds.

Serving quantization (models/quant.py, common.quantize_kv/attend_quant) is
near-lossless by construction — symmetric per-channel/per-slot scales —
and these tests pin that down numerically instead of trusting the label:
round-trip error is bounded by half a scale step, matmuls through the
quantized path stay within tight relative error of the dense path, and the
full forward/generate pipelines run and agree closely.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_lms_raft_llm_tpu.engine import generate as gen_lib
from distributed_lms_raft_llm_tpu.engine.sampling import SamplingParams
from distributed_lms_raft_llm_tpu.models import gpt2, quant, registry
from distributed_lms_raft_llm_tpu.models.common import (
    attend,
    attend_quant,
    quantize_kv,
)


def test_quantize_array_roundtrip_bounded():
    w = np.random.default_rng(0).normal(size=(64, 48)).astype(np.float32)
    qd = quant.quantize_array(jnp.asarray(w))
    assert qd["q"].dtype == jnp.int8
    back = np.asarray(qd["q"], np.float32) * np.asarray(qd["s"])[None, :]
    step = np.asarray(qd["s"])[None, :]
    assert np.all(np.abs(back - w) <= 0.5 * step + 1e-7)


def test_quantize_embedding_per_row_scales():
    w = np.random.default_rng(1).normal(size=(32, 16)).astype(np.float32)
    w[3] *= 50.0  # an outlier row must not damage other rows
    qd = quant.quantize_embedding(jnp.asarray(w))
    back = np.asarray(qd["q"], np.float32) * np.asarray(qd["s"])[:, None]
    rel = np.abs(back - w).max(axis=1) / (np.abs(w).max(axis=1) + 1e-9)
    assert np.all(rel < 0.005)


def test_dense_quant_close_to_full():
    from distributed_lms_raft_llm_tpu.models.common import dense

    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.normal(size=(4, 96)).astype(np.float32))
    w = jnp.asarray(rng.normal(size=(96, 64)).astype(np.float32))
    full = dense(x, w)
    q = dense(x, quant.quantize_array(w))
    cos = jnp.sum(full * q) / (jnp.linalg.norm(full) * jnp.linalg.norm(q))
    assert float(cos) > 0.9999


def test_attend_quant_close_to_full():
    rng = np.random.default_rng(3)
    q = jnp.asarray(rng.normal(size=(2, 4, 1, 32)).astype(np.float32))
    k = jnp.asarray(rng.normal(size=(2, 4, 24, 32)).astype(np.float32))
    v = jnp.asarray(rng.normal(size=(2, 4, 24, 32)).astype(np.float32))
    mask = jnp.ones((2, 1, 1, 24), bool).at[:, :, :, 20:].set(False)
    full = attend(q, k, v, mask)
    k8, ks = quantize_kv(k)
    v8, vs = quantize_kv(v)
    qq = attend_quant(q, k8, ks, v8, vs, mask)
    err = float(jnp.max(jnp.abs(full - qq)))
    scale = float(jnp.max(jnp.abs(full)))
    assert err / scale < 0.02


def test_forward_quant_weights_logits_close():
    cfg = gpt2.GPT2Config.tiny()
    params = gpt2.init_params(jax.random.key(0), cfg)
    ids = jnp.asarray(
        np.random.default_rng(4).integers(0, cfg.vocab_size, (2, 12)),
        jnp.int32,
    )
    full, _ = gpt2.forward(params, cfg, ids)
    qparams = quant.quantize_params(params, "gpt2")
    qlog, _ = gpt2.forward(qparams, cfg, ids)
    # Relative RMSE of the logits stays small (weight-only, per-channel).
    rmse = float(jnp.sqrt(jnp.mean((full - qlog) ** 2)))
    spread = float(jnp.std(full))
    assert rmse / spread < 0.05


@pytest.mark.parametrize("quant_kv", [False, True])
def test_generate_end_to_end_with_quant(quant_kv):
    cfg = gpt2.GPT2Config.tiny(quant_kv=quant_kv)
    params = quant.quantize_params(
        gpt2.init_params(jax.random.key(1), cfg), "gpt2"
    )
    b, t = 2, 8
    ids = jnp.asarray(
        np.random.default_rng(5).integers(1, cfg.vocab_size, (b, t)), jnp.int32
    )
    mask = jnp.ones((b, t), bool)
    sampling = SamplingParams.greedy(max_new_tokens=6)
    out = gen_lib.generate(
        params, cfg, ids, mask, jax.random.key(0), sampling,
        eos_id=0, pad_id=0, model=registry.GPT2_FAMILY,
    )
    assert out.tokens.shape == (b, 6)
    assert np.all(np.asarray(out.lengths) >= 1)
    # Deterministic: greedy decode twice gives identical tokens.
    out2 = gen_lib.generate(
        params, cfg, ids, mask, jax.random.key(7), sampling,
        eos_id=0, pad_id=0, model=registry.GPT2_FAMILY,
    )
    assert np.array_equal(np.asarray(out.tokens), np.asarray(out2.tokens))


def test_quant_kv_generate_close_to_full_cache():
    """Greedy decode with an int8 cache tracks the full-precision cache:
    compare the first-step logits (pre-divergence) directly."""
    cfg_full = gpt2.GPT2Config.tiny()
    cfg_q = gpt2.GPT2Config.tiny(quant_kv=True)
    params = gpt2.init_params(jax.random.key(2), cfg_full)
    b, t = 2, 10
    ids = jnp.asarray(
        np.random.default_rng(6).integers(1, cfg_full.vocab_size, (b, t)),
        jnp.int32,
    )
    mask = jnp.ones((b, t), bool)
    sampling = SamplingParams.greedy(max_new_tokens=4)

    def first_logits(cfg):
        state = gen_lib.prefill(
            params, cfg, ids, mask, jax.random.key(0), sampling,
            eos_id=0, pad_id=0, model=registry.GPT2_FAMILY,
        )
        return state.out[:, 0]

    full_tok = np.asarray(first_logits(cfg_full))
    q_tok = np.asarray(first_logits(cfg_q))
    # Greedy argmax over a 384-vocab random model: the int8 cache must not
    # flip the clear winner on most rows (allow at most one flip).
    assert np.sum(full_tok != q_tok) <= 1


def test_paged_engine_serves_quantized():
    """Continuous batching over int8 weights + int8 KV cache end to end."""
    from distributed_lms_raft_llm_tpu.engine import EngineConfig, PagedEngine

    eng = PagedEngine(
        EngineConfig(
            model="tiny", quant="int8", kv_quant=True,
            sampling=SamplingParams.reference_defaults(max_new_tokens=8),
            length_buckets=(16,), batch_buckets=(1, 2),
        ),
        slots=2,
    )
    rids = [eng.submit("what is raft?"), eng.submit("explain paxos")]
    out = eng.drain()
    assert set(out) == set(rids)
    assert all(isinstance(t, str) for t in out.values())


def test_quantized_partition_rules_cover_qs_pairs():
    """The {q, s} leaf pairs of a quantized tree match real tp rules, not
    the replicate-everything fallback: q shards like its dense leaf, scales
    follow their out-channel axis (BASELINE config 4 — int8 under tp —
    depends on these)."""
    from jax.sharding import PartitionSpec as P

    from distributed_lms_raft_llm_tpu.parallel import partition

    cfg = gpt2.GPT2Config.tiny()
    qparams = quant.quantize_params(gpt2.init_params(jax.random.key(0), cfg),
                                    "gpt2")
    specs = partition.match_partition_rules(partition.GPT2_RULES, qparams)
    # Expectations use the canonical trailing-None-free spelling the
    # canonical-pspec lint rule enforces (P() == replicated at any rank;
    # PartitionSpec pads missing trailing dims with None).
    assert specs["wte"]["q"] == P("tp")
    assert specs["wte"]["s"] == P("tp")
    blk = specs["blocks"]
    assert blk["attn"]["wqkv"]["q"] == P(None, None, "tp")
    assert blk["attn"]["wqkv"]["s"] == P(None, "tp")
    assert blk["attn"]["wo"]["q"] == P(None, "tp")
    assert blk["attn"]["wo"]["s"] == P()
    assert blk["mlp"]["wi"]["q"] == P(None, None, "tp")
    assert blk["mlp"]["wi"]["s"] == P(None, "tp")
    assert blk["mlp"]["wo"]["q"] == P(None, "tp")
    assert blk["mlp"]["wo"]["s"] == P()


def test_int8_tp_sharded_logits_match_unsharded():
    """int8 weights under tp=4: the sharded forward reproduces the
    single-device quantized forward (same quantized params, f32 math)."""
    import dataclasses

    from distributed_lms_raft_llm_tpu.parallel import mesh as mesh_lib
    from distributed_lms_raft_llm_tpu.parallel import partition

    cfg = dataclasses.replace(
        gpt2.GPT2Config(dtype=jnp.float32, param_dtype=jnp.float32),
        hidden_size=64, num_layers=3, num_heads=8,
        vocab_size=512, max_position_embeddings=64,
    )
    qparams = quant.quantize_params(gpt2.init_params(jax.random.key(3), cfg),
                                    "gpt2")
    ids = jnp.asarray(
        np.random.default_rng(9).integers(1, cfg.vocab_size, (2, 12)),
        jnp.int32,
    )
    ref, _ = gpt2.forward(qparams, cfg, ids)

    mesh = mesh_lib.make_mesh({"tp": 4, "dp": -1})
    sharded = partition.shard_tree(qparams, mesh, partition.GPT2_RULES)
    with mesh:
        got, _ = jax.jit(lambda p, i: gpt2.forward(p, cfg, i))(sharded, ids)
    err = float(jnp.max(jnp.abs(ref - got)))
    scale = float(jnp.max(jnp.abs(ref)))
    assert err / scale < 1e-5, f"tp=4 int8 logits diverge: {err}"


def test_int8_tp8_uneven_gpt2_large_topology_decode():
    """BASELINE config 4's production quant: int8 weights + int8 KV under
    tp=8 with GPT-2-large's uneven head topology (20 % 8 != 0)."""
    import dataclasses

    from distributed_lms_raft_llm_tpu.engine import generate as gen
    from distributed_lms_raft_llm_tpu.parallel import mesh as mesh_lib
    from distributed_lms_raft_llm_tpu.parallel import partition

    cfg = dataclasses.replace(
        gpt2.GPT2Config.large(dtype=jnp.float32, param_dtype=jnp.float32),
        hidden_size=80,   # 20 heads x 4 head_dim (true: 20 x 64)
        num_layers=4,
        vocab_size=512,
        max_position_embeddings=64,
        quant_kv=True,
    )
    qparams = quant.quantize_params(gpt2.init_params(jax.random.key(4), cfg),
                                    "gpt2")
    mesh = mesh_lib.make_mesh({"tp": 8, "dp": -1})
    sharded = partition.shard_tree(qparams, mesh, partition.GPT2_RULES)
    ids = np.ones((2, 16), np.int32)
    mask = np.ones((2, 16), bool)
    with mesh:
        result = jax.jit(
            lambda p, i, m, r: gen.generate(
                p, cfg, i, m, r,
                sampling=SamplingParams.reference_defaults(max_new_tokens=4),
                eos_id=0, pad_id=0,
            )
        )(sharded, jnp.asarray(ids), jnp.asarray(mask), jax.random.key(5))
    result = jax.device_get(result)
    assert result.tokens.shape == (2, 4)
    assert (result.tokens < cfg.vocab_size).all()
    assert np.isfinite(result.lengths).all()


def test_engine_int8_tp2_serves():
    """TutoringEngine with quant='int8', tp=2 boots and answers (the
    combination the round-4 guard rejected)."""
    from distributed_lms_raft_llm_tpu.engine import EngineConfig, TutoringEngine

    eng = TutoringEngine(
        EngineConfig(
            model="tiny", quant="int8", kv_quant=True, tp=2,
            sampling=SamplingParams.reference_defaults(max_new_tokens=6),
            length_buckets=(16,), batch_buckets=(1, 2),
        )
    )
    answers = eng.answer_batch(["what is raft?", "explain paxos"])
    assert len(answers) == 2
    assert all(isinstance(a, str) for a in answers)


def test_bert_gate_quantized_similarity_close():
    """int8 BERT gate: cosine similarities track full precision closely
    (the gate decision is a 0.6 threshold on cosine — scale-tolerant)."""
    from distributed_lms_raft_llm_tpu.engine.gate import (
        GateConfig, RelevanceGate,
    )

    full = RelevanceGate(GateConfig(model="tiny", dtype=jnp.float32))
    q = RelevanceGate(
        GateConfig(model="tiny", dtype=jnp.float32, quant="int8")
    )
    pairs = [
        ("how does raft elect a leader", "raft consensus and elections"),
        ("what is a matrix", "cooking with garlic butter"),
    ]
    for a, b in pairs:
        _, sim_full = full.check(a, b)
        _, sim_q = q.check(a, b)
        assert abs(float(sim_full) - float(sim_q)) < 0.05, (a, b)


def test_gate_context_cache_matches_joint_embedding():
    """The cached path (query embedded alone, cached context from the
    joint batch) must reproduce the joint-batch cosine: mask-weighted mean
    pooling makes embeddings bucket-independent, including when the short
    query would alone pick a narrower bucket than the long context."""
    from distributed_lms_raft_llm_tpu.engine.gate import (
        GateConfig, RelevanceGate,
    )

    gate = RelevanceGate(GateConfig(model="tiny", dtype=jnp.float32))
    q = "short query"
    ctx = "a much longer assignment context " * 12  # forces a wider bucket
    # Oracle: the pre-cache behavior — one joint [q, ctx] embed.
    emb = gate.embed_texts([q, ctx])
    sim_joint = float(
        np.dot(emb[0], emb[1])
        / (np.linalg.norm(emb[0]) * np.linalg.norm(emb[1]))
    )
    _, sim_first = gate.check(q, ctx)       # miss: joint embed + cache
    assert ctx in gate._ctx_cache
    _, sim_cached = gate.check(q, ctx)      # hit: query embedded ALONE
    assert sim_first == pytest.approx(sim_joint, abs=1e-5)
    assert sim_cached == pytest.approx(sim_joint, abs=1e-5)
