"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh so that every sharding / collective
path (tp/dp/sp ring attention, pjit train step) is exercised without TPU
hardware. These env vars must be set before JAX initializes its backends,
hence at conftest import time.
"""

import os

# Hard override: the ambient environment pins JAX_PLATFORMS to the real TPU
# ('axon'); tests must run on the virtual CPU mesh.
os.environ["JAX_PLATFORMS"] = "cpu"
xla_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in xla_flags:
    os.environ["XLA_FLAGS"] = (
        xla_flags + " --xla_force_host_platform_device_count=8"
    ).strip()
# Keep test-time compiles cheap and deterministic.
os.environ.setdefault("JAX_ENABLE_X64", "0")

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    import jax

    devices = jax.devices()
    assert len(devices) >= 8, f"expected >=8 virtual devices, got {len(devices)}"
    return devices[:8]
