"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh so that every sharding / collective
path (tp/dp/sp ring attention, pjit train step) is exercised without TPU
hardware.

The image's sitecustomize registers the experimental 'axon' TPU backend and
*overwrites* `jax_platforms` at interpreter start, so env vars alone
(JAX_PLATFORMS / XLA_FLAGS) are not enough — we must override the config
after importing jax, before any backend is touched.
"""

import os

# Harmless extra belt-and-braces for subprocesses spawned by tests.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")

# jax < 0.5 has no `jax_num_cpu_devices` config option; the XLA flag is the
# portable spelling and must be in the environment before the CPU backend is
# first touched (conftest imports before any test module, so this is early
# enough even when sitecustomize already imported jax).
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
try:
    jax.config.update("jax_num_cpu_devices", 8)
except AttributeError:
    pass  # older jax: the XLA_FLAGS spelling above already applies

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    devices = jax.devices()
    assert len(devices) >= 8, f"expected >=8 virtual devices, got {len(devices)}"
    return devices[:8]


@pytest.fixture
def strict_dispatch_guard():
    """Engine tests opt in to dispatch-hygiene assertion mode: any
    device->host readback outside `with intended_transfer():` raises on
    backends where readbacks are real transfers (utils/guards.py; the
    static rule no-host-sync-in-dispatch is the CPU-side enforcement)."""
    from distributed_lms_raft_llm_tpu.utils.guards import strict_dispatch

    with strict_dispatch():
        yield


@pytest.fixture
def ordered_locks():
    """Lock-order assertion mode: every OrderedLock acquisition during
    the test feeds the live acquisition graph (utils/locks.py), and the
    fixture asserts it acyclic — with no re-entry and no cycle-closing
    edge — on teardown. The runtime counterpart of the `lock-order`
    lint rule; the semester sim enables the same recording itself."""
    from distributed_lms_raft_llm_tpu.utils import locks

    locks.reset()
    with locks.recording():
        yield locks
    locks.assert_acyclic()


