"""Test harness configuration.

Tests run on a virtual 8-device CPU mesh so that every sharding / collective
path (tp/dp/sp ring attention, pjit train step) is exercised without TPU
hardware.

The image's sitecustomize registers the experimental 'axon' TPU backend and
*overwrites* `jax_platforms` at interpreter start, so env vars alone
(JAX_PLATFORMS / XLA_FLAGS) are not enough — we must override the config
after importing jax, before any backend is touched.
"""

import os

# Harmless extra belt-and-braces for subprocesses spawned by tests.
os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
jax.config.update("jax_num_cpu_devices", 8)

import pytest  # noqa: E402


@pytest.fixture(scope="session")
def eight_devices():
    devices = jax.devices()
    assert len(devices) >= 8, f"expected >=8 virtual devices, got {len(devices)}"
    return devices[:8]
