"""Radix shared-prefix KV cache: prefill each course context once.

The cache changes WHERE prompt KV comes from, never WHAT the device
computes: a cache-hit generation must equal the cold-prefill generation
token for token, across every engine configuration (plain, speculative,
kv-quant, megastep, megastep+spec). On top of exactness: the radix
tree's structure (longest-prefix lookup, insert-with-split, LRU
eviction) is pinned at the unit level, eviction under pressure never
frees a block a live slot references (ref-count pin), slot churn with
interleaved hits and misses stays correct, the whole partial-prefill
program domain is warmup-covered (`expected_from_inventory` equality),
the serving queue surfaces the new hit-rate/eviction/blocks gauges, and
the sim workload's same-course concentration knob produces the
deterministic shared prefixes the cache targets.
"""

import asyncio

import jax.numpy as jnp
import pytest

from distributed_lms_raft_llm_tpu.config import SimConfig
from distributed_lms_raft_llm_tpu.engine import (
    EngineConfig,
    PagedEngine,
    PagedQueue,
    SamplingParams,
    TutoringEngine,
)
from distributed_lms_raft_llm_tpu.engine.prefix_cache import (
    PrefixCache,
    plan_partial,
)
from distributed_lms_raft_llm_tpu.sim import workload as wl
from distributed_lms_raft_llm_tpu.sim.slo import evaluate_slos
from distributed_lms_raft_llm_tpu.utils.guards import (
    compile_count_guard,
    expected_from_inventory,
)
from distributed_lms_raft_llm_tpu.utils.metrics import Metrics

MAX_NEW = 8
BLOCK = 4

# A shared course context long enough to span several 4-token blocks
# (byte-fallback tokenizer on the tiny model: ~1 token per character),
# with distinct per-student suffixes — the same-course workload shape.
CTX = "the raft leader election protocol works by "
PROMPTS = [
    CTX + "choosing a leader",
    CTX + "replicating a log",
    "what is paging?",
    CTX + "electing nodes",
]


def make_config(**kw):
    kw.setdefault("sampling", SamplingParams.greedy(max_new_tokens=MAX_NEW))
    kw.setdefault("length_buckets", (16, 32))
    return EngineConfig(
        model="tiny",
        batch_buckets=(1, 2, 4),
        dtype=jnp.float32,
        **kw,
    )


def make_engine(cfg=None, **kw):
    kw.setdefault("slots", 2)
    kw.setdefault("chunk", 2)
    kw.setdefault("prefix_cache", True)
    kw.setdefault("prefix_cache_blocks", 64)
    kw.setdefault("prefix_block_tokens", BLOCK)
    return PagedEngine(cfg if cfg is not None else make_config(), **kw)


# --------------------------------------------------------- radix tree unit


def ints(n, start=0):
    return list(range(start, start + n))


def test_tree_lookup_insert_and_partial_hit():
    pc = PrefixCache(block_tokens=4, max_blocks=64)
    toks = ints(17)  # 4 full blocks + 1 spare token
    added = pc.insert(toks[:16], lambda i: f"blk{i}")
    assert added == 4 and pc.blocks_used == 4
    # Full-prompt lookup is usable-capped at len-1: 16 cached tokens but
    # only 3 blocks (12 tokens) are usable for an identical 16-token
    # prompt (the last position's logits must be recomputed).
    m = pc.lookup(toks[:16])
    assert m.tokens == 12
    # A longer prompt sharing the prefix uses all 4 blocks.
    m = pc.lookup(toks + ints(8, 100))
    assert m.tokens == 16
    assert m.blocks() == ["blk0", "blk1", "blk2", "blk3"]
    # Divergence mid-path: only the shared whole blocks match.
    m = pc.lookup(ints(8) + ints(12, 500))
    assert m.tokens == 8
    assert m.blocks() == ["blk0", "blk1"]
    # No hit at all.
    assert pc.lookup(ints(12, 900)).tokens == 0


def test_tree_insert_splits_and_dedups():
    pc = PrefixCache(block_tokens=2, max_blocks=64)
    pc.insert(ints(8), lambda i: ("a", i))
    # Shares 2 blocks then diverges: the shared edge must split, the new
    # tail gets fresh blocks, and NOTHING already cached is re-made.
    made = []

    def mk(i):
        made.append(i)
        return ("b", i)

    added = pc.insert(ints(4) + ints(6, 50), mk)
    assert added == 3 and made == [2, 3, 4]
    assert pc.blocks_used == 7
    # Both branches still fully resolvable after the split.
    assert pc.lookup(ints(8) + [99]).tokens == 8
    assert pc.lookup(ints(4) + ints(6, 50) + [99]).tokens == 10
    # Re-inserting an exact existing prefix adds nothing.
    assert pc.insert(ints(8), mk) == 0


def test_tree_lru_eviction_and_refcount_pin():
    pc = PrefixCache(block_tokens=2, max_blocks=4)
    pc.insert(ints(4), lambda i: ("a", i))         # 2 blocks
    pc.insert(ints(4, 100), lambda i: ("b", i))    # 2 blocks
    pin = pc.lookup(ints(4) + [9])                 # touch + pin branch a
    pc.acquire(pin)
    # Pressure: a third branch overruns the budget. The LRU unpinned
    # leaf (branch b) must go; the pinned branch a must survive even
    # though it is older than c.
    pc.insert(ints(4, 200), lambda i: ("c", i))
    freed = pc.evict_to_budget()
    assert freed == 2 and pc.blocks_used == 4
    assert pc.lookup(ints(4) + [9]).tokens == 4        # a survived
    assert pc.lookup(ints(4, 100) + [9]).tokens == 0   # b evicted
    # Everything pinned => budget overruns rather than freeing live
    # blocks.
    pin_c = pc.lookup(ints(4, 200) + [9])
    pc.acquire(pin_c)
    pc.insert(ints(4, 300), lambda i: ("d", i))
    pin_d = pc.lookup(ints(4, 300) + [9])
    pc.acquire(pin_d)
    assert pc.evict_to_budget() == 0 and pc.blocks_used == 6
    # Releasing makes the LRU leaf evictable again.
    pc.release(pin)
    assert pc.evict_to_budget() == 2 and pc.blocks_used == 4
    assert pc.evicted_blocks == 4  # cumulative


def test_tree_split_keeps_pin_on_deep_node():
    """A pinned node that later splits keeps its refcount on the deep
    (tail) node; the new upper node is protected structurally by having
    a child — no split may strand a pinned path evictable."""
    pc = PrefixCache(block_tokens=2, max_blocks=2)
    pc.insert(ints(8), lambda i: ("a", i))
    pin = pc.lookup(ints(8) + [9])
    pc.acquire(pin)
    pc.insert(ints(4) + ints(4, 50), lambda i: ("b", i))  # forces split
    # Budget 2 is far exceeded (6 blocks), but branch a's tail is pinned
    # and interior nodes have children: only branch b may go.
    pc.evict_to_budget()
    assert pc.lookup(ints(8) + [9]).tokens == 8


def test_plan_partial_fits_static_domain():
    buckets = (8, 16, 32)
    # Plain hit: block-aligned prefix, smallest suffix bucket that fits.
    assert plan_partial(8, 20, 32, buckets, 4) == (8, 16)
    # Smallest admissible suffix wins; the prefix shrinks to fit the
    # window (blocks are given back rather than overrunning).
    assert plan_partial(28, 32, 32, buckets, 4) == (24, 8)
    assert plan_partial(28, 32, 32, (16, 32), 4) == (16, 16)
    # Hit floor: less than one block of usable prefix => cold.
    assert plan_partial(3, 10, 16, buckets, 4) == (0, 0)
    # prefix_used never reaches true_len (>= 1 recomputed token).
    p, s = plan_partial(16, 16, 16, buckets, 4)
    assert p < 16 and (p == 0 or 16 - p <= s)
    # Returned prefix is always block-aligned and window-safe.
    for hit in (4, 8, 12, 16, 24, 28):
        for tl in (9, 15, 17, 29, 32):
            p, s = plan_partial(hit, tl, 32, buckets, 4)
            if p:
                assert p % 4 == 0 and p + s <= 32 and tl - p <= s


# ------------------------------------------------------- greedy bit-equality


class TestCacheHitBitEquality:
    def _expected(self, cfg, prompts):
        base = PagedEngine(cfg, slots=2, chunk=2)
        rids = [base.submit(p) for p in prompts]
        out = base.drain()
        return [out[r] for r in rids]

    def _assert_two_passes_match(self, eng, prompts, expected):
        """Pass 1 seeds the tree (later same-course requests already
        hit); pass 2 is fully warm. Both must equal the cold engine."""
        for pass_no in (1, 2):
            rids = [eng.submit(p) for p in prompts]
            out = eng.drain()
            assert [out[r] for r in rids] == expected, f"pass {pass_no}"
        hit, total, _ev, _blocks = eng.pop_prefix_stats()
        assert 0 < hit < total

    def test_plain_matches_cold_and_bucketed(self):
        cfg = make_config()
        expected = self._expected(cfg, PROMPTS)
        assert expected == TutoringEngine(cfg).answer_batch(list(PROMPTS))
        self._assert_two_passes_match(make_engine(cfg), PROMPTS, expected)

    @pytest.mark.parametrize("spec_tokens", [2])
    def test_spec_mode(self, spec_tokens):
        cfg = make_config(spec_tokens=spec_tokens)
        expected = self._expected(cfg, PROMPTS)
        self._assert_two_passes_match(make_engine(cfg), PROMPTS, expected)

    def test_kv_quant(self):
        cfg = make_config(kv_quant=True)
        expected = self._expected(cfg, PROMPTS)
        self._assert_two_passes_match(make_engine(cfg), PROMPTS, expected)

    def test_megastep(self):
        cfg = make_config()
        expected = self._expected(cfg, PROMPTS)
        eng = make_engine(cfg, megastep=4, megastep_max=4)
        self._assert_two_passes_match(eng, PROMPTS, expected)

    def test_megastep_with_spec(self):
        cfg = make_config(spec_tokens=2)
        expected = self._expected(cfg, PROMPTS)
        eng = make_engine(cfg, megastep=4, megastep_max=4)
        self._assert_two_passes_match(eng, PROMPTS, expected)


def test_slot_churn_interleaved_hits_and_misses():
    """More requests than slots, hits and misses interleaved: every
    stream must match the cache-off engine while the tree is being
    built, hit, split, and re-hit under churn."""
    cfg = make_config()
    prompts = [
        CTX + "choosing a leader",
        "completely unrelated question",
        CTX + "replicating a log entry",
        "another cold miss here",
        CTX + "choosing a leader",          # exact repeat: deep hit
        CTX + "counting votes",
        "what is paging?",
        CTX + "replicating a log entry",    # repeat again
    ]
    base = PagedEngine(cfg, slots=2, chunk=2)
    rb = [base.submit(p) for p in prompts]
    out_base = base.drain()

    eng = make_engine(cfg)
    re_ = [eng.submit(p) for p in prompts]
    out = eng.drain()
    assert [out[a] for a in re_] == [out_base[b] for b in rb]
    hits = eng.pop_prefix_hits()
    assert len(hits) == len(prompts)
    assert any(v > 0 for v in hits.values())
    assert any(v == 0 for v in hits.values())


def test_eviction_under_pressure_keeps_live_pins_and_stays_exact():
    """A tiny block budget under heavy distinct-prefix churn: evictions
    happen, pinned (in-flight) paths are never freed, and outputs still
    equal the cache-off engine."""
    cfg = make_config()
    # Budget = ONE prompt's blocks: every distinct publish overruns and
    # evicts; adjacent repeats hit (and pin) before churn can evict them.
    prompts = [f"unique course context number {i} question" for i in range(3)]
    prompts += [PROMPTS[0], PROMPTS[0]]
    prompts += [f"more cold churn number {i} ok" for i in range(3)]
    prompts += [PROMPTS[1], PROMPTS[1]]
    base = PagedEngine(cfg, slots=2, chunk=2)
    rb = [base.submit(p) for p in prompts]
    out_base = base.drain()

    eng = make_engine(cfg, prefix_cache_blocks=8)
    re_ = [eng.submit(p) for p in prompts]
    # Step (not drain) so we can observe live pins mid-flight.
    saw_pin = False
    out = {}
    while eng.has_work:
        for rid, text in eng.step():
            out[rid] = text
        for pin in eng._prefix_pins.values():
            saw_pin = True
            # The pinned path's deepest node must still be reachable in
            # the tree (eviction never freed a live slot's blocks).
            assert pin.nodes[-1].refs > 0
    assert [out[a] for a in re_] == [out_base[b] for b in rb]
    assert saw_pin
    assert not eng._prefix_pins  # all released at completion
    hit, total, evicted, blocks_used = eng.pop_prefix_stats()
    assert evicted > 0
    assert hit > 0


def test_reset_releases_pins_but_keeps_tree():
    eng = make_engine()
    eng.submit(PROMPTS[0])
    eng.step()  # admitted; publish happened, possibly pinned
    blocks_before = eng.prefix_cache.blocks_used
    assert blocks_before > 0
    eng.reset()
    assert not eng._prefix_pins
    assert all(
        n.refs == 0 for n in eng.prefix_cache._iter_nodes()
    )
    # Tree blocks were never donated: the cache survives an engine reset.
    assert eng.prefix_cache.blocks_used == blocks_before
    rid = eng.submit(PROMPTS[0])
    out = eng.drain()
    assert out[rid]
    assert eng.pop_prefix_stats()[0] > 0  # re-hit after reset


# ------------------------------------------------- compile-once acceptance


def test_partial_prefill_domain_is_warmup_covered():
    """The acceptance pin: warmup compiles exactly the inventoried
    program set (partial-prefill pairs, block export/load included), and
    a live session mixing cold misses, partial hits, deep repeats, and
    eviction pressure adds ZERO programs."""
    eng = make_engine(make_config(length_buckets=(8, 16)),
                      prefix_cache_blocks=8)
    eng.warmup()
    expectation = expected_from_inventory(eng)
    assert expectation.mismatches() == {}
    # Adjacent repeats hit before LRU churn (budget 8 blocks vs ~4 per
    # prompt) can evict them; the distinct prompts force evictions.
    workload = [p for prompt in PROMPTS for p in (prompt, prompt)]
    workload += ["one more cold miss"]
    with compile_count_guard(expectation) as guard:
        for p in workload:
            eng.submit(p)
        eng.drain()
    assert guard.new_compiles() == 0
    hit, _total, evicted, _blocks = eng.pop_prefix_stats()
    assert hit > 0 and evicted > 0


def test_disabled_prefix_cache_expects_zero_programs():
    """With the cache off, the partial/export/load wrappers exist but
    their expected (and actual) program counts are zero — the manifest
    stays exact in both modes."""
    eng = PagedEngine(make_config(length_buckets=(8,)), slots=2, chunk=2)
    eng.warmup()
    expectation = expected_from_inventory(eng)
    assert expectation.expected["_partial_prefill"] == 0
    assert expectation.expected["_export_block"] == 0
    assert expectation.expected["_load_block"] == 0
    assert expectation.mismatches() == {}


# ------------------------------------------------------------ serving queue


def test_paged_queue_reports_prefix_metrics():
    metrics = Metrics()
    engine = make_engine()

    async def run():
        q = PagedQueue(engine, metrics=metrics)
        await q.start()
        answers = await asyncio.gather(
            *[q.submit(p) for p in PROMPTS],
            *[q.submit(p) for p in PROMPTS],
        )
        await q.close()
        return answers

    answers = asyncio.run(run())
    assert len(answers) == 2 * len(PROMPTS)
    snap = metrics.snapshot()
    assert snap["counters"]["prefix_cache_hit_tokens"] > 0
    assert 0.0 < snap["gauges"]["prefix_cache_hit_rate"] < 1.0
    assert snap["gauges"]["prefix_cache_blocks_used"] > 0


# ------------------------------------------------------------- sim workload


def sim_cfg(**kw):
    kw.setdefault("seed", 7)
    kw.setdefault("students", 12)
    kw.setdefault("courses", 3)
    kw.setdefault("duration_s", 5.0)
    kw.setdefault("base_rate", 20.0)
    return SimConfig(**kw)


def test_concentration_zero_keeps_legacy_assignment_and_bare_prompts():
    gen = wl.WorkloadGenerator(sim_cfg(course_concentration=0.0))
    ops = gen.ops()
    asks = [o for o in ops if o.kind == wl.ASK_LLM_ON_TOPIC]
    assert asks
    assert all(o.payload["query"] in wl.ON_TOPIC_QUERIES for o in asks)
    # Legacy hash spread: with 12 students over 3 courses, more than one
    # course sees traffic.
    assert len({o.course for o in ops}) > 1


def test_concentration_shares_course_prefixes_deterministically():
    cfg = sim_cfg(course_concentration=0.6)
    gen = wl.WorkloadGenerator(cfg)
    ops = gen.ops()
    asks = [o for o in ops if o.kind == wl.ASK_LLM_ON_TOPIC]
    assert asks
    for o in asks:
        prefix = gen.course_context(o.course)
        assert o.payload["query"].startswith(prefix)
        assert o.payload["query"][len(prefix):] in wl.ON_TOPIC_QUERIES
    # Off-topic asks stay bare so the relevance gate still discriminates.
    for o in ops:
        if o.kind == wl.ASK_LLM_OFF_TOPIC:
            assert o.payload["query"] in wl.OFF_TOPIC_QUERIES
    # Deterministic: same seed, same trace (prefixes included).
    assert wl.trace_digest(ops) == wl.trace_digest(
        wl.WorkloadGenerator(cfg).ops()
    )


def test_concentration_skews_and_saturates():
    base = sim_cfg(course_concentration=0.0)
    skew = sim_cfg(course_concentration=0.9)
    full = sim_cfg(course_concentration=1.0)
    students = [f"student{i:03d}" for i in range(64)]

    def share0(cfg):
        gen = wl.WorkloadGenerator(cfg)
        return sum(
            1 for s in students if gen.course_of(s) == "course0"
        ) / len(students)

    assert share0(full) == 1.0
    assert share0(skew) > share0(base)


def test_slo_verdict_carries_prefix_hit_rate():
    report = evaluate_slos(
        sim_cfg(), node_metrics={}, node_health={}, sim_metrics={},
        ledger_report={"losses": [], "ryw_violations": [],
                       "acked_writes": 0},
        tutoring_metrics={"gauges": {"prefix_cache_hit_rate": 0.42}},
    )
    assert report.prefix_cache_hit_rate == 0.42
    assert report.to_dict()["prefix_cache_hit_rate"] == 0.42
    # Absent engine => carried as None, never fabricated.
    report = evaluate_slos(
        sim_cfg(), node_metrics={}, node_health={}, sim_metrics={},
        ledger_report={"losses": [], "ryw_violations": [],
                       "acked_writes": 0},
        tutoring_metrics={},
    )
    assert report.prefix_cache_hit_rate is None
