"""Semester simulator (sim/): the composed production scenario.

Tier-1 runs ONE seeded sim end-to-end (module-scoped fixture — every
assertion below reads the same run): >=1 TimeoutNow rolling restart, >=1
storage-recovery quarantine + rejoin, >=1 membership add/remove, and a
network-chaos campaign with a tutoring blackout, with SLOs asserted from
/metrics + /healthz and the acked-write ledger proving zero loss. A
scaled `slow`-marked soak runs the same harness harder; the wall-budget
guard keeps the tier-1 run inside its time box.
"""

import dataclasses
import time
from pathlib import Path

import pytest

from distributed_lms_raft_llm_tpu.config import SimConfig
from distributed_lms_raft_llm_tpu.sim import (
    SemesterSim,
    SimCluster,
    WorkloadGenerator,
    plan_events,
    trace_digest,
)

# Deliberately small but not trivial: ~90 ops across 12 actors, every
# event kind (fleet drills included — 3 tutoring nodes behind the
# cache-affinity router), and every SLO — in ~25 s of wall clock.
TIER1_CFG = SimConfig(
    seed=7, students=10, instructors=2, courses=2,
    duration_s=16.0, base_rate=6.0, workers=6, llm_budget_s=10.0,
    tutoring_nodes=3,
    slo_answer_p95_s=8.0, slo_degraded_rate_max=0.5,
    slo_tick_stalls_max=50,
)

# The tier-1 sim's time box (the fixture measures the WHOLE run: cluster
# boot, setup, workload, settle, audit, teardown). The workload phase is
# 16 s; everything around it has to fit in the remainder. Creeping past
# this means the sim no longer belongs in tier-1 — trim it or move it.
TIER1_WALL_BUDGET_S = 90.0


@pytest.fixture(scope="module")
def sim_run(tmp_path_factory):
    t0 = time.monotonic()
    record = SemesterSim(
        TIER1_CFG, str(tmp_path_factory.mktemp("semester"))
    ).run()
    return record, time.monotonic() - t0


def test_sim_end_to_end_slos_hold(sim_run):
    """The acceptance scenario: every SLO asserted from the cluster's
    /metrics + /healthz (and the ledger) holds across the full run."""
    record, _ = sim_run
    slos = record["slos"]
    assert slos["ok"], f"SLO failures: " + str({
        k: v for k, v in slos["checks"].items() if not v["ok"]
    })
    assert slos["checks"]["zero_acked_write_loss"]["ok"]
    assert record["acked_writes"] > 30, "the run must really write"
    assert record["ops_ok"] > 0.9 * record["ops_planned"], (
        "most ops must succeed despite the fault schedule"
    )


def test_sim_executed_every_event_kind(sim_run):
    """>=1 leadership transfer (rolling restart), >=1 storage-recovery
    quarantine+rejoin, >=1 membership add AND remove, >=1 chaos
    campaign — all executed through the real admin plane, none failed."""
    record, _ = sim_run
    failed = [e for e in record["events"] if not e["ok"]]
    assert not failed, f"events failed: {failed}"
    executed = record["events_executed"]
    for kind in ("rolling_restart", "quarantine", "membership_add",
                 "membership_remove", "chaos_campaign",
                 "tutoring_blackout", "tutoring_drain_rejoin",
                 "tutoring_autoscale", "bulk_grading_night",
                 "tutoring_stream_kill"):
        assert executed.get(kind, 0) >= 1, f"missing event kind {kind}"


def test_sim_bulk_grading_harvested_idle_lanes(sim_run):
    """PR-15 acceptance: the bulk-grading night's score job fanned to
    the tutoring fleet's background tenant via the LMS admin plane and
    COMPLETED in preemptible quanta while student traffic kept flowing —
    with interactive p95 untouched (the grading window is a NON-fault
    window, so a scoring-induced burn alert would have failed
    `no_false_alarms` above)."""
    record, _ = sim_run
    scoring = record["scoring"]
    assert scoring is not None
    assert scoring["jobs_completed"] >= 1, scoring
    assert scoring["jobs_failed"] == 0, scoring
    assert scoring["quanta"] >= 1 and scoring["scored_tokens"] > 0
    checks = record["slos"]["checks"]
    assert checks["bulk_scoring_completed"]["ok"], (
        checks["bulk_scoring_completed"]
    )


def test_sim_fleet_drills_spilled_hedged_and_restored_affinity(sim_run):
    """The tutoring-fleet acceptance: killing one of three tutoring
    nodes mid-traffic left measured evidence — >=1 router spill and >=1
    hedge win in the BENCH record — the drain-and-rejoin drill completed
    (ejection + warm-up rejoin counted), and no node ended the run out
    of the ring."""
    record, _ = sim_run
    fleet = record["tutoring_fleet"]
    assert fleet is not None and fleet["size"] == 3
    assert fleet["spills"] >= 1, fleet
    assert fleet["hedges"] >= 1 and fleet["hedge_wins"] >= 1, fleet
    assert fleet["ejections"] >= 1 and fleet["rejoins"] >= 1, fleet
    checks = record["slos"]["checks"]
    assert checks["fleet_spill_observed"]["ok"]
    assert checks["fleet_hedge_win_observed"]["ok"]
    assert checks["fleet_nodes_routable"]["ok"]
    # The per-node map survived to the verdict: every configured node
    # routable, with route/served attribution.
    states = {n["state"] for n in fleet["nodes"]}
    assert states <= {"ok", "warming"}, fleet["nodes"]


def test_sim_exercised_degraded_path(sim_run):
    """The tutoring blackout really produced degraded instructor-queue
    answers (client-observed: node counters can be wiped by the rolling
    restart, which is exactly why the sim keeps its own ledger)."""
    record, _ = sim_run
    assert record["degraded_answers"] >= 1
    assert record["asks"] > 10


def test_continuous_slo_engine_evaluated_and_alerted(sim_run):
    """PR-11 acceptance: the SLOs are evaluated in burn-rate windows
    DURING the run — >= 1 window evaluated per SLO, zero false alarms on
    the healthy baseline, and the injected tutoring blackout raises
    (then clears) at least one fast-window alert, recorded as timeline
    events and classified against the fault schedule."""
    record, _ = sim_run
    cont = record["slos"]["continuous"]
    assert cont is not None and cont["enabled"]
    for slo in ("answer_p95", "degraded_rate", "tick_stalls"):
        assert cont["windows_evaluated"].get(slo, 0) >= 1, slo
    checks = record["slos"]["checks"]
    assert checks["burn_windows_evaluated"]["ok"]
    assert checks["no_false_alarms"]["ok"], checks["no_false_alarms"]
    fast = [a for a in cont["alerts"]
            if a["window"] == "fast" and a["during_fault"]]
    assert fast, f"blackout raised no fast-window alert: {cont['alerts']}"
    assert any(a["cleared_at_s"] is not None for a in fast), (
        "the fast alert must clear once the fault passes"
    )
    # Alerts double as timeline events in the exported cluster timeline.
    kinds = [e["kind"] for e in record["timeline"]["cluster"]["events"]]
    assert "slo_alert_raised" in kinds and "slo_alert_cleared" in kinds


def test_timeline_export_feeds_capacity_model(sim_run):
    """PR-11 acceptance: the run's exported timeline + stage p95s feed
    `scripts/telemetry.py --capacity`, which emits the capacity-model
    JSON (req/s-per-node-at-SLO) the router and autoscaler consume."""
    import importlib.util
    import json

    record, _ = sim_run
    timeline = record["timeline"]
    assert timeline and len(timeline["cluster"]["points"]) >= 10
    assert "tutoring" in timeline["nodes"]
    spec = importlib.util.spec_from_file_location(
        "telemetry", str(Path(__file__).resolve().parent.parent
                         / "scripts" / "telemetry.py")
    )
    telemetry = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(telemetry)
    model = telemetry.fit_capacity(
        json.loads(json.dumps(record)),  # as the CLI would read it
        slo_p95_s=TIER1_CFG.slo_answer_p95_s,
        ceiling_tokens_per_s=61500.0,
    )
    assert model["metric"] == "capacity_req_s_per_node_at_slo"
    assert model["unit"] == "req/s/node"
    assert model["value"] > 0, "the sim served real load at SLO"
    assert model["samples"] >= 5
    # The echo engine never saturates the SLO in 16 s: the fit must say
    # so (lower bound), not fabricate a knee.
    assert model["slo_saturated"] is False
    assert model["service_time_p95_s"] is not None, (
        "flight-recorder stage p95s fold into the model"
    )


def test_sim_exercised_relevance_gate(sim_run):
    """The off-topic asks really hit the gate (KeywordGate in the sim
    cluster): both counters moved on the nodes' /metrics. Sums survive
    the rolling restart only on never-restarted nodes, so >= 1, not an
    exact count."""
    record, _ = sim_run
    assert record["gate_pass"] >= 1
    assert record["gate_reject"] >= 1


def test_keyword_gate_splits_workload_queries():
    """Every on-topic query passes against the assignment text and every
    off-topic one is rejected — with margin, so the threshold is not
    sitting on a knife edge."""
    from distributed_lms_raft_llm_tpu.sim.cluster import KeywordGate

    import distributed_lms_raft_llm_tpu.sim.workload as wl

    g = KeywordGate()
    for q in wl.ON_TOPIC_QUERIES:
        passed, sim = g.check(q, wl.ASSIGNMENT_TEXT)
        assert passed and sim >= 2 * g.threshold, (q, sim)
    for q in wl.OFF_TOPIC_QUERIES:
        passed, sim = g.check(q, wl.ASSIGNMENT_TEXT)
        assert not passed and sim == 0.0, (q, sim)
    # The ops bot's probes must pass against ITS assignment text (a
    # gated-out settle probe could never re-close a breaker).
    for probe in ("ops bot probe: what is Raft?", "ops bot settle probe?"):
        assert g.check(probe, "ops bot assignment")[0], probe


def test_sim_record_is_bench_schema(sim_run):
    """One JSON record, BENCH shape: headline metric + replay anchors."""
    record, _ = sim_run
    assert record["metric"] == "semester_sim_ask_p95_s"
    assert isinstance(record["value"], float)
    assert record["unit"] == "s"
    assert record["seed"] == TIER1_CFG.seed
    # Replayability: digests of the decision-level inputs.
    gen = WorkloadGenerator(TIER1_CFG)
    assert record["trace_digest"] == trace_digest(gen.ops())


def test_tier1_sim_wall_budget(sim_run):
    """CI guard: the tier-1 sim must stay inside its time box."""
    _, wall = sim_run
    assert wall < TIER1_WALL_BUDGET_S, (
        f"tier-1 semester sim took {wall:.1f}s (budget "
        f"{TIER1_WALL_BUDGET_S}s) — trim the config or demote it to slow"
    )


# ------------------------------------------------------ seeded determinism


def test_same_seed_same_trace_and_schedule():
    """Replayability contract: the op trace and the event schedule are
    pure functions of the config (seed included)."""
    a = WorkloadGenerator(TIER1_CFG).ops()
    b = WorkloadGenerator(TIER1_CFG).ops()
    assert [o.key() for o in a] == [o.key() for o in b]
    assert trace_digest(a) == trace_digest(b)
    assert [e.key() for e in plan_events(TIER1_CFG)] == [
        e.key() for e in plan_events(TIER1_CFG)
    ]


def test_different_seed_different_trace():
    other = dataclasses.replace(TIER1_CFG, seed=TIER1_CFG.seed + 1)
    assert trace_digest(WorkloadGenerator(TIER1_CFG).ops()) != trace_digest(
        WorkloadGenerator(other).ops()
    )
    assert [e.key() for e in plan_events(TIER1_CFG)] != [
        e.key() for e in plan_events(other)
    ]


def test_sim_config_rejects_degenerate_shapes():
    """Bad [sim] values fail at load like every other section — not as
    ZeroDivisionError/IndexError minutes into a run."""
    for bad in ({"courses": 0}, {"instructors": 0}, {"base_rate": 0.0},
                {"students": 0}, {"workers": 0}, {"duration_s": 0.0}):
        with pytest.raises(ValueError):
            dataclasses.replace(TIER1_CFG, **bad)


def test_diurnal_curve_shapes_the_trace():
    """The load really follows the day: the midday half of the run must
    carry more ops than the edges (amplitude 0 flattens it)."""
    cfg = dataclasses.replace(TIER1_CFG, duration_s=60.0, base_rate=12.0,
                              diurnal_amplitude=0.9)
    ops = WorkloadGenerator(cfg).ops()
    mid = sum(1 for o in ops if 15.0 <= o.at_s < 45.0)
    edges = len(ops) - mid
    assert mid > 1.3 * edges, (mid, edges)


# ------------------------------------------- fault/campaign introspection


def test_admin_faults_get_reports_campaigns(tmp_path):
    """Satellite: GET /admin/faults (the plane was write-only) returns
    the live fault + campaign configuration; campaigns install, report,
    and clear their specs."""
    cfg = dataclasses.replace(TIER1_CFG, events=False)
    cluster = SimCluster(str(tmp_path), cfg, nodes=1)
    cluster.start()
    try:
        nid = cluster.node_ids()[0]
        state = cluster.admin_get(nid, "/admin/faults")
        assert state["ok"] and state["faults"]["targets"] == {}
        assert state["campaign"]["active"] is False

        # One-shot spec shows up in the GET.
        cluster.admin_post(nid, "/admin/faults",
                           {"target": "tutoring", "drop": 0.5})
        state = cluster.admin_get(nid, "/admin/faults")
        assert state["faults"]["targets"]["tutoring"]["drop"] == 0.5

        # A campaign: phase visible while live, spec installed, and both
        # gone once cancelled.
        cluster.admin_post(nid, "/admin/faults", {"campaign": {
            "name": "introspection",
            "phases": [{"target": "*", "duration_s": 30.0, "drop": 0.25}],
        }})
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            state = cluster.admin_get(nid, "/admin/faults")
            if "*" in state["faults"]["targets"]:
                break
            time.sleep(0.05)
        assert state["campaign"]["active"] is True
        assert state["campaign"]["name"] == "introspection"
        assert state["campaign"]["phase"]["drop"] == 0.25
        assert state["faults"]["targets"]["*"]["drop"] == 0.25

        # The cancel POST's own response is authoritative: the teardown
        # has landed by the time it returns (CampaignRunner.stop), so no
        # polling — a stranded spec here is a regression.
        state = cluster.admin_post(nid, "/admin/faults",
                                   {"campaign_cancel": True})
        assert state["campaign"]["active"] is False
        assert "*" not in state["faults"]["targets"], (
            "cancelled campaign stranded its spec"
        )

        # Unknown spec fields in a campaign fail the POST up front.
        with pytest.raises(RuntimeError, match="unknown fault field"):
            cluster.admin_post(nid, "/admin/faults", {"campaign": {
                "name": "typo",
                "phases": [{"target": "*", "duration_s": 1.0, "dorp": 1.0}],
            }})
        # GET of an unknown admin path is a 404, not a crash.
        import urllib.error

        with pytest.raises(urllib.error.HTTPError):
            cluster.admin_get(nid, "/admin/nope")
    finally:
        cluster.stop()


# ------------------------------------------- sharded control plane (PR 16)

# A second, smaller seeded run with TWO Raft groups: the full fault
# schedule plus the group drills — a group-1 leader loss and a live
# course split (group 0 → group 1) under a chaos overlay at the diurnal
# peak. Every assertion below reads this one run.
GROUPS_CFG = SimConfig(
    seed=23, students=8, instructors=2, courses=2,
    duration_s=12.0, base_rate=6.0, workers=6, llm_budget_s=10.0,
    tutoring_nodes=1, bulk_scoring=False, lms_groups=2,
    slo_answer_p95_s=10.0, slo_degraded_rate_max=0.6,
    slo_tick_stalls_max=50,
)

GROUPS_WALL_BUDGET_S = 90.0


@pytest.fixture(scope="module")
def groups_run(tmp_path_factory):
    t0 = time.monotonic()
    record = SemesterSim(
        GROUPS_CFG, str(tmp_path_factory.mktemp("sharded"))
    ).run()
    return record, time.monotonic() - t0


def test_sharded_sim_slos_hold_with_zero_acked_loss(groups_run):
    """The PR-16 acceptance scenario: a live group split under load
    (chaos campaign active, diurnal peak) completes with every SLO —
    including zero acked-write loss — still green."""
    record, _ = groups_run
    slos = record["slos"]
    assert slos["ok"], "SLO failures: " + str({
        k: v for k, v in slos["checks"].items() if not v["ok"]
    })
    assert slos["checks"]["zero_acked_write_loss"]["ok"]
    assert slos["checks"]["groups_routable"]["ok"]
    assert slos["checks"]["reshard_completed"]["ok"], (
        slos["checks"]["reshard_completed"]
    )
    assert record["acked_writes"] > 20, "the run must really write"


def test_sharded_sim_ran_group_drills(groups_run):
    """Both group drills executed through the real admin plane: the
    targeted `raft:<gid>` leader loss recovered by re-election, and the
    mid-peak split flipped the routing map on every node."""
    record, _ = groups_run
    failed = [e for e in record["events"] if not e["ok"]]
    assert not failed, f"events failed: {failed}"
    executed = record["events_executed"]
    assert executed.get("group_leader_loss", 0) >= 1
    assert executed.get("group_split", 0) >= 1
    # The classic drills still run alongside the group ones.
    for kind in ("rolling_restart", "chaos_campaign", "membership_add",
                 "membership_remove"):
        assert executed.get(kind, 0) >= 1, f"missing event kind {kind}"


def test_sharded_sim_reshard_evidence_in_ledger(groups_run):
    """The ledger is group-aware: acked writes carry their owning group,
    the split left a reshard mark, and the end-of-run audit re-read
    every pre-split write through the POST-flip map (that is what
    `acked_across_reshard` counts)."""
    record, _ = groups_run
    groups = record["groups"]
    assert groups is not None and groups["n_groups"] == 2
    assert len(groups["reshards"]) >= 1
    move = groups["reshards"][0]
    assert move["src"] != move["dst"]
    assert set(groups["acked_by_group"]) == {"group0", "group1"}
    assert groups["acked_across_reshard"] >= 1, (
        "no acked write predated the split — the drill must run "
        "mid-workload, not after it"
    )
    # The flip bumped the replicated map exactly as many times as there
    # were completed handoffs.
    assert groups["routing_map"]["version"] == 1 + len(groups["reshards"])


def test_sharded_sim_topology_endpoint_shape(groups_run):
    """GET /admin/raft (satellite 3): the routing map plus one row per
    group with members/leader/term/applied/commit — what
    scripts/telemetry.py renders as per-group dashboard rows."""
    record, _ = groups_run
    groups = record["groups"]
    topo = groups["topology"]
    assert set(topo) == {"0", "1"}
    for gid, row in topo.items():
        assert row["leader"] is not None, f"group {gid} leaderless"
        assert row["term"] >= 1
        assert row["commit"] >= row["applied"] >= 0
        assert len(row["members"]) >= 3
    assert all(nid is not None for nid in groups["leaders"].values())


def test_sharded_sim_bench_record_fields(groups_run):
    """The BENCH record carries the sharding verdict inputs for replay:
    group count and the groups block itself."""
    record, _ = groups_run
    assert record["lms_groups"] == 2
    assert record["metric"] == "semester_sim_ask_p95_s"
    assert record["groups"]["expected_reshard"] is True


def test_sharded_sim_replicas_converged(groups_run):
    """PR 18 acceptance: at settle — AFTER the mid-peak group_split
    drill — every group's surviving replicas sit at one applied index
    with one state digest (the raft_state_digest chain), and the SLO
    layer turns that evidence into a `replicas_converged` verdict."""
    record, _ = groups_run
    check = record["slos"]["checks"]["replicas_converged"]
    assert check["ok"], check
    digests = record["groups"]["replica_digests"]
    assert digests["converged"] is True
    assert set(digests["groups"]) == {"0", "1"}
    for gid, rows in digests["groups"].items():
        assert len(rows) >= 2, f"group {gid} audited <2 replicas: {rows}"
        assert len({r["digest"] for r in rows.values()}) == 1, rows
        assert len({r["applied"] for r in rows.values()}) == 1, rows
        for r in rows.values():
            assert isinstance(r["digest"], str) and len(r["digest"]) == 16


def test_sharded_sim_wall_budget(groups_run):
    """CI guard: the sharded tier-1 sim must stay inside its time box."""
    _, wall = groups_run
    assert wall < GROUPS_WALL_BUDGET_S, (
        f"sharded semester sim took {wall:.1f}s (budget "
        f"{GROUPS_WALL_BUDGET_S}s) — trim the config or demote it to slow"
    )


def test_sim_lock_acquisition_graph_acyclic_and_consistent(sim_run):
    """The runtime half of the `lock-order` rule: across the whole sim
    (in-process cluster, every OrderedLock in every node), the recorded
    live acquisition graph has no violations (no re-entry on a
    non-reentrant lock, no cycle-closing edge), and composing it with
    the statically computed acquisition-order graph stays acyclic — the
    order the process actually walked never contradicts the order the
    lint rule proved from source."""
    from distributed_lms_raft_llm_tpu.analysis.concurrency import (
        ConcurrencyEngine,
    )
    from distributed_lms_raft_llm_tpu.analysis.core import (
        iter_sources,
        repo_root,
    )
    from distributed_lms_raft_llm_tpu.analysis.project import Project
    from distributed_lms_raft_llm_tpu.utils import locks

    _ = sim_run  # ordering only: the recorded graph is the run's output
    assert locks.violations() == [], locks.violations()
    runtime = locks.acquisition_edges()
    # The sim exercises breakers and metrics enough that at least one
    # nested acquisition must have been recorded; an empty graph means
    # the recording hook silently broke.
    assert runtime, "sim recorded no lock acquisition edges"
    locks.assert_acyclic()

    root = repo_root()
    engine = ConcurrencyEngine(Project(iter_sources(None, root=root),
                                       root=root))
    merged: dict = {}
    for src, dst in set(engine.static_order_shorts()) | runtime:
        merged.setdefault(src, set()).add(dst)
    # DFS cycle check over the merged graph.
    WHITE, GRAY, BLACK = 0, 1, 2
    color: dict = {}

    def visit(node: str, trail: tuple) -> None:
        color[node] = GRAY
        for nxt in sorted(merged.get(node, ())):
            c = color.get(nxt, WHITE)
            assert c != GRAY, (
                f"runtime acquisition order contradicts the static "
                f"order: cycle through {trail + (node, nxt)}"
            )
            if c == WHITE:
                visit(nxt, trail + (node,))
        color[node] = BLACK

    for start in sorted(merged):
        if color.get(start, WHITE) == WHITE:
            visit(start, ())


# ------------------------------------------------------------ tier-2 soak


@pytest.mark.slow
def test_semester_sim_soak_scaled(tmp_path):
    """The same harness at scale: more students, longer semester, the
    REAL paged JAX engine (shared-prefix cache on) behind tutoring, a
    concentrated same-course workload, and tighter stall bounds."""
    cfg = SimConfig(
        seed=11, students=48, instructors=4, courses=4,
        duration_s=90.0, base_rate=10.0, workers=12, llm_budget_s=15.0,
        tutoring_engine="tiny-paged", course_concentration=0.6,
        slo_answer_p95_s=15.0, slo_degraded_rate_max=0.5,
        slo_tick_stalls_max=200,
    )
    record = SemesterSim(cfg, str(tmp_path)).run()
    assert record["slos"]["ok"], record["slos"]
    assert not [e for e in record["events"] if not e["ok"]]
    for kind in ("rolling_restart", "quarantine", "membership_add",
                 "membership_remove", "chaos_campaign"):
        assert record["events_executed"].get(kind, 0) >= 1
    assert record["acked_writes"] > 150
    # Concentrated same-course traffic repeats the same course
    # questions, so the radix cache serves a real measured hit rate in
    # the verdict (at tiny scale the engine's 32-token window truncates
    # the shared context, so these are verbatim-repeat hits — the
    # lookup/splice/partial-prefill path, not cross-question context
    # sharing, which bench.py's shared-prefix scenario pins instead).
    assert record["prefix_cache_hit_rate"] is not None
    assert record["prefix_cache_hit_rate"] > 0.2
