"""Client leader-hint cache under membership churn (satellite of the
semester-sim PR).

The failure this pins: the client's cached leader hint points at a node
that a membership change removed (and that then went away). Before the
fix, (a) the hint was never evicted, (b) discovery re-trusted the first
stale report naming the dead address, and (c) `RaftServicer.GetLeader`
answered from a boot-time COPY of the address map, so a
membership-ADDED leader's address was unreportable and the client could
never follow the cluster off its boot list. Now: the failed address is
evicted and probed last, stale first-round reports naming it are
skipped, and the servicer shares the LMSNode's live map.
"""

import asyncio
import threading

import grpc

from distributed_lms_raft_llm_tpu.client import LMSClient
from distributed_lms_raft_llm_tpu.lms.node import LMSNode
from distributed_lms_raft_llm_tpu.lms.service import (
    FileTransferServicer,
    LMSServicer,
)
from distributed_lms_raft_llm_tpu.proto import rpc
from distributed_lms_raft_llm_tpu.raft import RaftConfig
from distributed_lms_raft_llm_tpu.raft.grpc_transport import RaftServicer

FAST = RaftConfig(
    election_timeout_min=0.11, election_timeout_max=0.22,
    heartbeat_interval=0.05,
)


def _boot_node(loop, tmp_path, nid, addresses):
    """One LMS node + gRPC server on `loop`; returns its record."""

    async def boot():
        server = grpc.aio.server()
        port = int(addresses[nid].rsplit(":", 1)[1])
        bound = server.add_insecure_port(f"127.0.0.1:{port}")
        assert bound == port
        node = LMSNode(nid, addresses, str(tmp_path / f"node{nid}"),
                       raft_config=FAST)
        rpc.add_LMSServicer_to_server(
            LMSServicer(node.node, node.state, node.blobs,
                        peer_addresses=node.addresses, self_id=nid),
            server,
        )
        rpc.add_RaftServiceServicer_to_server(
            # LIVE map (the fix under test): GetLeader must be able to
            # name a membership-added node.
            RaftServicer(node.node, node.addresses,
                         kv=node.state.data["kv"]),
            server,
        )
        rpc.add_FileTransferServiceServicer_to_server(
            FileTransferServicer(node.blobs), server
        )
        await server.start()
        await node.start()
        return {"node": node, "server": server}

    return asyncio.run_coroutine_threadsafe(boot(), loop).result(30)


def _free_port():
    import socket

    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_hint_evicted_and_added_leader_discovered(tmp_path):
    """Rolling decommission: leadership moves to a membership-ADDED node,
    the old (hinted) leader is removed and stopped — the client must
    evict the dead hint, learn the new leader's off-boot-list address
    from any live peer, and finish its op inside its budget."""
    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()
    nodes = {}
    client = None
    try:
        addresses = {i: f"127.0.0.1:{_free_port()}" for i in (1, 2, 3)}
        for i in (1, 2, 3):
            nodes[i] = _boot_node(loop, tmp_path, i, dict(addresses))

        client = LMSClient(
            [addresses[i] for i in (1, 2, 3)],
            discovery_rounds=8, discovery_backoff_s=0.1,
            rpc_retries=8, request_timeout_s=20.0,
            backoff_base_s=0.02, backoff_max_s=0.2, seed=3,
        )
        # A retried-but-committed Register reports "exists" (the frozen
        # proto carries no request id); login success proves the account
        # committed either way.
        client.register("ana", "pw", "student")
        assert client.login("ana", "pw")
        hinted = client._leader_addr
        assert hinted in addresses.values()
        leader_id = next(i for i, a in addresses.items() if a == hinted)

        async def admin():
            leader = nodes[leader_id]["node"]
            # Add node 4 (booted first, operator-style), hand leadership
            # to it, then remove + stop the old leader.
            members = {**{i: addresses[i] for i in (1, 2, 3)},
                       4: addresses[4]}
            await leader.node.propose_config(members)
            await leader.node.transfer_leadership(4)

        addresses[4] = f"127.0.0.1:{_free_port()}"
        nodes[4] = _boot_node(loop, tmp_path, 4, dict(addresses))
        asyncio.run_coroutine_threadsafe(admin(), loop).result(30)

        async def decommission():
            new_leader = nodes[4]["node"]
            deadline = asyncio.get_running_loop().time() + 10
            while not new_leader.node.is_leader:
                if asyncio.get_running_loop().time() > deadline:
                    raise AssertionError("transfer never settled on 4")
                await asyncio.sleep(0.05)
            members = {i: addresses[i] for i in (2, 3, 4)}
            # A freshly-transferred leader reports the prior config
            # change in flight until it commits in its own term; and
            # under full-suite CPU load a tick stall can bounce node 4
            # through a momentary step-down-and-re-elect, surfacing a
            # transient NotLeader (the test_crashpoints de-flake class)
            # — retry both until the remove commits.
            from distributed_lms_raft_llm_tpu.raft.core import (
                ConfigChangeInFlight,
                NotLeader,
            )

            for _ in range(50):
                try:
                    await new_leader.node.propose_config(members)
                    break
                except (ConfigChangeInFlight, NotLeader):
                    await asyncio.sleep(0.1)
            else:
                raise AssertionError("remove config never accepted")
            old = nodes.pop(leader_id)
            await old["node"].stop()
            await old["server"].stop(None)

        asyncio.run_coroutine_threadsafe(decommission(), loop).result(30)

        # The client still hints at the dead, removed ex-leader.
        assert client._leader_addr == hinted
        assert client.login("ana", "pw"), (
            "op must succeed after the hinted node was removed"
        )
        assert client._leader_addr != hinted, "dead hint must be evicted"
        assert client._leader_addr == addresses[4], (
            f"client should have learned the added leader "
            f"{addresses[4]}, hints {client._leader_addr}"
        )
        # The learned address becomes a discovery peer of its own.
        assert addresses[4] in client._extra_servers
    finally:
        if client is not None:
            client.close()

        async def teardown():
            for rec in nodes.values():
                await rec["node"].stop()
                await rec["server"].stop(None)

        asyncio.run_coroutine_threadsafe(teardown(), loop).result(30)
        loop.call_soon_threadsafe(loop.stop)
        t.join(timeout=10)


def test_unavailable_hint_falls_back_to_live_peer(tmp_path):
    """Mid-churn UNAVAILABLE: the hinted leader stops; the client must
    evict the hint and recover via the remaining quorum."""
    loop = asyncio.new_event_loop()
    t = threading.Thread(target=loop.run_forever, daemon=True)
    t.start()
    nodes = {}
    client = None
    try:
        addresses = {i: f"127.0.0.1:{_free_port()}" for i in (1, 2, 3)}
        for i in (1, 2, 3):
            nodes[i] = _boot_node(loop, tmp_path, i, dict(addresses))
        client = LMSClient(
            [addresses[i] for i in (1, 2, 3)],
            discovery_rounds=8, discovery_backoff_s=0.1,
            rpc_retries=8, request_timeout_s=20.0,
            backoff_base_s=0.02, backoff_max_s=0.2, seed=5,
        )
        client.register("bo", "pw", "student")
        assert client.login("bo", "pw")  # proves the register committed
        hinted = client._leader_addr
        leader_id = next(i for i, a in addresses.items() if a == hinted)

        async def kill_leader():
            rec = nodes.pop(leader_id)
            await rec["node"].stop()
            await rec["server"].stop(None)

        asyncio.run_coroutine_threadsafe(kill_leader(), loop).result(30)
        client.register("cy", "pw", "student")
        assert client.login("cy", "pw")  # proves the register committed
        assert client._leader_addr != hinted
    finally:
        if client is not None:
            client.close()

        async def teardown():
            for rec in nodes.values():
                await rec["node"].stop()
                await rec["server"].stop(None)

        asyncio.run_coroutine_threadsafe(teardown(), loop).result(30)
        loop.call_soon_threadsafe(loop.stop)
        t.join(timeout=10)
