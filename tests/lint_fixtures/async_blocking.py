"""no-blocking-in-async fixtures."""

import asyncio
import subprocess
import time

import jax
import numpy as np


async def bad_handler(request, fut, arr):
    time.sleep(0.5)  # EXPECT: no-blocking-in-async
    fh = open("state.json")  # EXPECT: no-blocking-in-async
    data = fut.result()  # EXPECT: no-blocking-in-async
    out = subprocess.run(["ls"])  # EXPECT: no-blocking-in-async
    host = jax.device_get(arr)  # EXPECT: no-blocking-in-async
    buf = np.asarray(arr)  # EXPECT: no-blocking-in-async
    n = arr.item()  # EXPECT: no-blocking-in-async
    return fh, data, out, host, buf, n


async def good_handler(request, arr, engine, loop):
    await asyncio.sleep(0.5)
    content = await loop.run_in_executor(None, engine.answer_batch, ["q"])

    def read_blob():  # sync helper destined for the executor: exempt
        with open("blob.bin", "rb") as fh:
            return fh.read()

    blob = await loop.run_in_executor(None, read_blob)
    return content, blob


def sync_code_is_out_of_scope(path):
    time.sleep(0.1)          # blocking is fine off the event loop
    with open(path) as fh:
        return fh.read()


async def suppressed_handler(path):
    # Startup-only read on an otherwise idle loop.
    with open(path) as fh:  # lint: disable=no-blocking-in-async
        return fh.read()
