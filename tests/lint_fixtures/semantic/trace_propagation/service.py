"""trace-propagation fixtures: handlers, helpers, callbacks, egress."""

import asyncio

from tracing import trace_metadata  # noqa: F401 - fixture-local stand-in


class FooServicer(rpc.FooServicer):  # noqa: F821 - fixture, never imported
    async def NoMetadata(self, request, context):
        stub = self._stub()
        return await stub.FetchThing(request, timeout=t)  # EXPECT: trace-propagation

    async def BareMetadata(self, request, context):
        # Metadata built without the wrapper: the x-trace-context chain
        # breaks even though SOME metadata flows.
        return await self.stub.SendThing(  # EXPECT: trace-propagation
            request, metadata=deadline.to_metadata()  # noqa: F821
        )

    async def HelperPath(self, request, context):
        return await self._forward(request)

    async def _forward(self, request):
        # Reachable through the handler's call, one hop deep.
        return await self.stub.SendThing(request)  # EXPECT: trace-propagation

    async def GoodWrapped(self, request, context):
        # The fix shape: existing metadata wrapped, never flagged.
        return await self.stub.FetchThing(
            request, metadata=trace_metadata(deadline.to_metadata())  # noqa: F821
        )

    async def GoodWrappedNone(self, request, context):
        return await self.stub.FetchThing(request,
                                          metadata=trace_metadata())

    async def GoodModuleQualified(self, request, context):
        return await self.stub.FetchThing(
            request, metadata=tracing.trace_metadata()  # noqa: F821
        )

    async def ConstructorsAreNotEgress(self, request, context):
        # CamelCase but never awaited: protobuf request constructors.
        req = pb2.FetchThingRequest(path="x")  # noqa: F821
        return await self.stub.FetchThing(req, metadata=trace_metadata())

    async def SnakeCaseHelpersAreNotEgress(self, request, context):
        # asyncio.wait_for is not a gRPC stub call (snake_case).
        return await asyncio.wait_for(self.queue.get(), timeout=5)

    async def StreamNoMetadata(self, request, context):
        # Server-streaming egress as an async-for iterable: even without
        # a timeout= keyword (which the awaited-later shape relies on),
        # the iteration context marks this as a wire RPC.
        async for chunk in self.stub.StreamThing(request):  # EXPECT: trace-propagation
            yield chunk

    async def StreamBareMetadata(self, request, context):
        async for chunk in self.stub.StreamThing(  # EXPECT: trace-propagation
            request, metadata=deadline.to_metadata()  # noqa: F821
        ):
            yield chunk

    async def GoodStreamWrapped(self, request, context):
        # The streaming fix shape: wrapped metadata, never flagged.
        async for chunk in self.stub.StreamThing(
            request, metadata=trace_metadata()
        ):
            yield chunk

    async def AsyncForHelpersAreNotEgress(self, request, context):
        # snake_case async iterables (the engine queue) are not wire RPCs.
        async for delta in self.queue.submit_stream(request):
            yield delta

    async def Sanctioned(self, request, context):
        # A deliberately untraced probe, visibly suppressed.
        return await self.stub.Probe(request)  # lint: disable=trace-propagation


class Node:
    def __init__(self, raft):
        # Address-taken: the callback runs on the serving loop in response
        # to committed RPCs, so everything it calls is handler-reachable.
        raft.apply_cb = self._apply

    def _apply(self, index, entry):
        asyncio.ensure_future(replicate_to_peers(self.addresses, entry))


async def replicate_to_peers(addresses, entry):
    for addr in addresses:
        async with channel(addr) as ch:  # noqa: F821
            stub = make_stub(ch)  # noqa: F821
            await stub.SendFile(entry, timeout=t)  # EXPECT: trace-propagation


async def unreferenced_helper(stub, request):
    # Dead code: no handler reaches it, no reference escapes — this
    # rule's reachability requirement keeps it out of scope.
    return await stub.SendAll(request)
