"""guarded-by-flow fixtures: loop-confined state reached from executors."""

import threading


class Pipeline:
    def __init__(self, loop):
        self._loop = loop
        self._futures = {}  # guarded-by: event-loop
        self._done = []     # guarded-by: event-loop

    def _reap(self):
        # Mutation looks loop-confined, but run() hands this method's
        # REFERENCE to an executor — the lexical rule cannot see that.
        self._futures.clear()  # EXPECT: guarded-by-flow

    def _outer(self):
        self._reap_helper()

    def _reap_helper(self):
        # Two hops from the executor: seeded via _outer, closed over the
        # call graph.
        self._done.append(1)  # EXPECT: guarded-by-flow

    def on_loop(self, rid, fut):
        # Only ever called from coroutines on the loop: never flagged.
        self._futures[rid] = fut

    async def run(self):
        await self._loop.run_in_executor(None, self._reap)
        await self._loop.run_in_executor(None, self._outer)

    def _sanctioned(self):
        # Deliberate (e.g. a shutdown path with the loop stopped),
        # visibly suppressed.
        self._futures.clear()  # lint: disable=guarded-by-flow

    async def drain_on_shutdown(self):
        await self._loop.run_in_executor(None, self._sanctioned)


def _background_sync():
    return 42  # touches no guarded state: seeded, but nothing to flag


def spawn_thread():
    # Thread(target=...) keyword references seed thread context too.
    t = threading.Thread(target=_background_sync)
    t.start()
    return t
