"""Fixture mini-project for the state-machine-determinism rule.

TPs: clock/RNG/env/process-identity reads and unordered set iteration
reachable from appliers (directly, transitively, and via apply_cb=/
install_cb= wiring), plus awaited RPC egress on the apply path.
TNs: pure appliers, sorted() iteration, spawned (ensure_future) work,
effects in functions no applier reaches, and a sanctioned suppression.
"""

import asyncio
import os
import random
import time
import uuid


def load_config(path):
    with open(path) as f:  # TN: not reachable from any applier root
        return f.read()


class State:
    def __init__(self):
        self.data = {}
        self.stub = None

    def apply(self, op, args):
        handler = getattr(self, f"_apply_{op.lower()}", None)
        if handler is None:
            raise ValueError(op)
        handler(args)

    def _apply_set(self, a):
        self.data[a["key"]] = a["value"]  # TN: pure

    def _apply_stamp(self, a):
        self.data["at"] = time.time()  # EXPECT: state-machine-determinism

    def _apply_mint(self, a):
        self.data["rid"] = uuid.uuid4().hex  # EXPECT: state-machine-determinism

    def _apply_env(self, a):
        self.data["home"] = os.environ["HOME"]  # EXPECT: state-machine-determinism

    def _apply_indirect(self, a):
        self._stash_pid(a)

    def _stash_pid(self, a):
        self.data["pid"] = os.getpid()  # EXPECT: state-machine-determinism

    def _apply_unordered(self, a):
        moved = {}
        for user in set(a["users"]):  # EXPECT: state-machine-determinism
            moved[user] = True
        self.data["moved"] = moved

    def _apply_sorted(self, a):
        moved = {}
        for user in sorted(set(a["users"])):  # TN: sorted() imposes order
            moved[user] = True
        self.data["moved"] = moved

    def _apply_spawned(self, a):
        # TN: replication is SPAWNED off the apply path, never awaited on
        # the tick loop — the exact idiom LMSNode._apply uses.
        asyncio.ensure_future(self._push(a))

    async def _push(self, a):
        await self.stub.Replicate(a, timeout=1.0)

    async def _apply_egress(self, a):
        await self.stub.Replicate(a)  # EXPECT: state-machine-determinism

    def _apply_sanctioned(self, a):
        self.data["seed"] = time.time()  # lint: disable=state-machine-determinism (sanctioned: fixture)


class Runner:
    """apply_cb=/install_cb= wiring makes the callbacks rule roots."""

    def __init__(self, raft):
        self.committed = []
        raft.configure(apply_cb=self._on_apply, install_cb=self._on_install)

    def _on_apply(self, index, entry):
        self.committed.append((index, random.random()))  # EXPECT: state-machine-determinism

    def _on_install(self, index, data):
        self.committed.append((index, time.monotonic()))  # EXPECT: state-machine-determinism
