"""Fixture mini-project for the wire-taint rule.

TPs: x-lms trust metadata read from raw invocation_metadata (via a dict,
a generic raw-reader helper, a for-scan, and one forwarding hop), a
secret compared with ==, and a request field reaching a path sink.
TNs: reads through the sanctioned verifier, the exempt unsigned hint,
hmac.compare_digest, a sanitized path hop, and a suppressed probe.
"""

import hmac
import os

GROUP_KEY = "x-lms-group"
USER_KEY = "x-lms-user"


def hash_password(password):
    return "hash:" + password


def _signed_md(context):
    # Sanctioned verifier: raw metadata reads INSIDE it are the point.
    return dict(context.invocation_metadata() or ())


def _metadata_get(context, key):
    for k, v in context.invocation_metadata() or ():
        if k == key:
            return v
    return None


def sanitize_filename(name):
    return os.path.basename(name)


class Router:
    def good_target(self, context):
        return _signed_md(context).get(GROUP_KEY)  # TN: via the verifier

    def bad_target(self, context):
        md = dict(context.invocation_metadata() or ())
        return md.get(GROUP_KEY)  # EXPECT: wire-taint

    def laundered_target(self, context):
        return _metadata_get(context, GROUP_KEY)  # EXPECT: wire-taint

    def hint_target(self, context):
        return _metadata_get(context, USER_KEY)  # TN: unsigned routing hint

    def scanned_target(self, context):
        for k, v in context.invocation_metadata() or ():
            if k == GROUP_KEY:  # EXPECT: wire-taint
                return v
        return None

    def forwarded_target(self, context):
        md = dict(context.invocation_metadata() or ())
        return self._pick(md)

    def _pick(self, md):
        return md.get(GROUP_KEY)  # EXPECT: wire-taint

    def suppressed_target(self, context):
        md = dict(context.invocation_metadata() or ())
        return md.get(GROUP_KEY)  # lint: disable=wire-taint (sanctioned: fixture probe)

    def check_secret(self, stored, presented):
        return stored == hash_password(presented)  # EXPECT: wire-taint

    def check_secret_safe(self, stored, presented):
        return hmac.compare_digest(stored, hash_password(presented))  # TN


class FileServicer:
    async def Fetch(self, request, context):
        return os.path.join("/srv", request.filename)  # EXPECT: wire-taint

    async def FetchSafe(self, request, context):
        rel = sanitize_filename(request.filename)
        return os.path.join("/srv", rel)  # TN: sanitized hop
