"""metrics-registry fixtures: emission sites, good and bad."""

from . import metrics_registry


class Server:
    def __init__(self, metrics):
        self.metrics = metrics

    def declared_literal(self):
        self.metrics.inc("good_series")  # fine: declared

    def declared_gauge(self):
        self.metrics.set_gauge("state_series", 1.0)  # fine: declared

    def typo(self):
        self.metrics.inc("goood_series")  # EXPECT: metrics-registry

    def undeclared_hist(self):
        with self.metrics.time("mystery_latency"):  # EXPECT: metrics-registry
            pass

    def branch_literals(self, ok):
        # IfExp of literals: both branches are checked individually.
        self.metrics.inc("good_series" if ok else "state_series")  # fine

    def dynamic(self, which):
        self.metrics.inc("prefix_" + which)  # EXPECT: metrics-registry

    def registry_rooted(self, state):
        # Rooted at the registry module: declared by construction.
        self.metrics.inc(metrics_registry.FAMILY[state])

    def registry_constant(self):
        self.metrics.inc(metrics_registry.GOOD)

    def sanctioned_dynamic(self, name):
        self.metrics.inc("scratch_" + name)  # lint: disable=metrics-registry

    def _inc(self, name):
        # Forwarding seam: the parameter flows straight into the
        # primitive, so CALL SITES are checked, not this line.
        if self.metrics is not None:
            self.metrics.inc(name)

    def via_wrapper_ok(self):
        self._inc("good_series")  # fine: declared

    def via_wrapper_typo(self):
        self._inc("bad_series")  # EXPECT: metrics-registry
