"""metrics-registry fixtures: emission sites, good and bad."""

from . import metrics_registry


class Server:
    def __init__(self, metrics):
        self.metrics = metrics

    def declared_literal(self):
        self.metrics.inc("good_series")  # fine: declared

    def declared_gauge(self):
        self.metrics.set_gauge("state_series", 1.0)  # fine: declared

    def typo(self):
        self.metrics.inc("goood_series")  # EXPECT: metrics-registry

    def undeclared_hist(self):
        with self.metrics.time("mystery_latency"):  # EXPECT: metrics-registry
            pass

    def branch_literals(self, ok):
        # IfExp of literals: both branches are checked individually.
        self.metrics.inc("good_series" if ok else "state_series")  # fine

    def dynamic(self, which):
        self.metrics.inc("prefix_" + which)  # EXPECT: metrics-registry

    def registry_rooted(self, state):
        # Rooted at the registry module: declared by construction.
        self.metrics.inc(metrics_registry.FAMILY[state])

    def registry_constant(self):
        self.metrics.inc(metrics_registry.GOOD)

    def sanctioned_dynamic(self, name):
        self.metrics.inc("scratch_" + name)  # lint: disable=metrics-registry

    def _inc(self, name):
        # Forwarding seam: the parameter flows straight into the
        # primitive, so CALL SITES are checked, not this line.
        if self.metrics is not None:
            self.metrics.inc(name)

    def via_wrapper_ok(self):
        self._inc("good_series")  # fine: declared

    def via_wrapper_typo(self):
        self._inc("bad_series")  # EXPECT: metrics-registry

    # Snapshot/timeline READ sites are checked like emissions (an SLO
    # bound on a never-declared series would read 0 forever) but never
    # count as emissions themselves.

    def read_declared(self, snap):
        return snap_counter(snap, "good_series")  # fine: declared read

    def read_typo(self, snap):
        return snap_gauge(snap, "state_seeries")  # EXPECT: metrics-registry

    def read_window_typo(self, timeline):
        return timeline.hist_p95("mystery_latency", 30.0)  # EXPECT: metrics-registry

    def read_dynamic(self, snap, which):
        return snap_counter(snap, "prefix_" + which)  # EXPECT: metrics-registry

    def read_registry_rooted(self, timeline):
        return timeline.counter_rate(metrics_registry.GOOD, 60.0)  # fine

    def _node_sum(self, name, snaps):
        # Read-forwarding seam (first non-self parameter flows into a
        # reader's name slot): call sites are checked, not this line,
        # and the forwarded names never count as emitted.
        return sum(snap_counter(s, name) for s in snaps)

    def via_read_wrapper_ok(self, snaps):
        return self._node_sum("good_series", snaps)  # fine: declared

    def via_read_wrapper_typo(self, snaps):
        return self._node_sum("goood_series", snaps)  # EXPECT: metrics-registry


def snap_counter(snap, name):
    # Stand-in for utils/timeline.snap_counter: the rule matches readers
    # by NAME, so the helper living here keeps the mini-project
    # self-contained. The dict access below is not a reader call, so
    # nothing in this body is checked.
    return snap.get("counters", {}).get(name, 0)


def snap_gauge(snap, name):
    return snap.get("gauges", {}).get(name, 0.0)
