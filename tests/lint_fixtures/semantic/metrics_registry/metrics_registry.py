"""metrics-registry fixtures: the declaration point (well- and ill-formed)."""


def counter(name, help):
    return name


def gauge(name, help):
    return name


GOOD = counter("good_series", "a documented counter")
STATE = gauge("state_series", "a documented gauge")
UNUSED = counter("unused_series", "declared but nothing emits it")  # EXPECT: metrics-registry
DUPLICATE = counter("good_series", "second declaration of the same name")  # EXPECT: metrics-registry
NON_LITERAL = counter(SOME_VAR, "name the linter cannot read")  # noqa: F821  # EXPECT: metrics-registry
NO_HELP = counter("undocumented_series", "")  # EXPECT: metrics-registry

# Grouped names stay declared-by-construction when emitted through the
# mapping (see emitter.registry_rooted).
FAMILY = {
    "ok": GOOD,
    "literal": "state_series",
}
