"""deadline-flow fixtures: handlers, helpers, callbacks, stub egress."""

import asyncio


class FooServicer(rpc.FooServicer):  # noqa: F821 - fixture, never imported
    async def GetThing(self, request, context):
        stub = self._stub()
        return await stub.FetchThing(request, timeout=5)  # EXPECT: deadline-flow

    async def HelperPath(self, request, context):
        return await self._forward(request)

    async def _forward(self, request):
        # Reachable through the handler's call, one hop deep.
        return await self.stub.SendThing(request, timeout=30)  # EXPECT: deadline-flow

    async def GoodDerived(self, request, context):
        deadline = Deadline.from_grpc_context(context)  # noqa: F821
        # Derived from the propagated budget: the fix shape, never flagged.
        return await self.stub.FetchThing(
            request, timeout=deadline.timeout(cap=5.0)
        )

    async def GoodCapped(self, request, context):
        budget = context.time_remaining()
        return await self.stub.FetchThing(
            request, timeout=max(0.001, budget - 0.25)
        )

    async def SnakeCaseHelpersAreNotEgress(self, request, context):
        # asyncio.wait_for is not a gRPC stub call (snake_case).
        return await asyncio.wait_for(self.queue.get(), timeout=5)

    async def StreamLiteralTimeout(self, request, context):
        # Server-streaming egress as an async-for iterable: a literal
        # timeout drops the budget exactly like the unary shape.
        async for chunk in self.stub.StreamThing(request, timeout=5):  # EXPECT: deadline-flow
            yield chunk

    async def StreamNoTimeout(self, request, context):
        # No timeout at all: the open stream outlives any client budget.
        async for chunk in self.stub.StreamThing(request):  # EXPECT: deadline-flow
            yield chunk

    async def GoodStreamDerived(self, request, context):
        deadline = Deadline.from_grpc_context(context)  # noqa: F821
        # Budget-derived stream timeout: the fix shape, never flagged.
        async for chunk in self.stub.StreamThing(
            request, timeout=deadline.timeout(cap=5.0)
        ):
            yield chunk

    async def AsyncForHelpersAreNotEgress(self, request, context):
        # snake_case async iterables (the engine queue) are not wire RPCs.
        async for delta in self.queue.submit_stream(request):
            yield delta

    async def Sanctioned(self, request, context):
        # A deliberate fixed-latency probe, visibly suppressed.
        return await self.stub.Probe(request, timeout=1)  # lint: disable=deadline-flow


class Node:
    def __init__(self, raft):
        # Address-taken: the callback runs on the serving loop in response
        # to committed RPCs, so everything it calls is handler-reachable.
        raft.apply_cb = self._apply

    def _apply(self, index, entry):
        asyncio.ensure_future(replicate_to_peers(self.addresses, entry))


async def replicate_to_peers(addresses, entry):
    for addr in addresses:
        async with channel(addr) as ch:  # noqa: F821
            stub = make_stub(ch)  # noqa: F821
            await stub.SendFile(entry, timeout=30)  # EXPECT: deadline-flow


async def unreferenced_helper(stub, request):
    # Dead code: no handler reaches it, no reference escapes — a literal
    # timeout here is someone else's problem, not this rule's.
    return await stub.SendAll(request, timeout=30)
