"""config-consistency fixtures: the declarative config module."""

import dataclasses


@dataclasses.dataclass
class ServerConfig:
    host: str = "127.0.0.1"
    port: int = 50051
    dead_knob: float = 1.0  # EXPECT: config-consistency
    sanctioned_future_knob: int = 0  # lint: disable=config-consistency
    nodes: dict = dataclasses.field(default_factory=dict)


@dataclasses.dataclass
class LimitsConfig:
    max_queue: int = 64


@dataclasses.dataclass
class AppConfig:
    server: ServerConfig = dataclasses.field(default_factory=ServerConfig)
    limits: LimitsConfig = dataclasses.field(default_factory=LimitsConfig)
