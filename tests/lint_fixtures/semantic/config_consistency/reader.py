"""config-consistency fixtures: the code that consumes the knobs."""


def boot(cfg):
    bind = f"{cfg.server.host}:{cfg.server.port}"
    peers = dict(cfg.server.nodes)
    return bind, peers, cfg.limits.max_queue
