"""guarded-by fixtures."""

import threading


class GoodCounter:
    def __init__(self):
        self._counts = {}   # guarded-by: _lock
        self._total = 0     # guarded-by: _lock
        self._lock = threading.Lock()

    def inc(self, name):
        with self._lock:
            self._counts[name] = self._counts.get(name, 0) + 1
            self._total += 1

    def _drain(self):  # guarded-by: _lock
        self._counts.clear()
        self._total = 0

    def reset(self):
        with self._lock:
            self._drain()

    def read(self, name):
        with self._lock:
            return self._counts.get(name, 0)  # reads aren't checked


class BadCounter:
    def __init__(self):
        self._counts = {}   # guarded-by: _lock
        self._lock = threading.Lock()

    def inc_unlocked(self, name):
        self._counts[name] = 1  # EXPECT: guarded-by

    def clear_unlocked(self):
        self._counts.clear()  # EXPECT: guarded-by

    def del_unlocked(self, name):
        del self._counts[name]  # EXPECT: guarded-by

    def _locked_helper(self):  # guarded-by: _lock
        self._counts.clear()

    def calls_locked_helper_without_lock(self):
        self._locked_helper()  # EXPECT: guarded-by


class LoopConfined:
    def __init__(self, loop):
        self._loop = loop
        self._futures = {}  # guarded-by: event-loop

    def on_loop(self, rid, fut):
        self._futures[rid] = fut         # fine: loop context

    def escapes(self, executor):
        def mutate():
            self._futures.clear()  # EXPECT: guarded-by

        executor.submit(mutate)

    def escapes_via_run_in_executor(self):
        self._loop.run_in_executor(
            None, lambda: self._futures.pop(1)  # EXPECT: guarded-by
        )


class Suppressed:
    def __init__(self):
        self._state = 0  # guarded-by: _lock
        self._lock = threading.Lock()

    def single_writer_path(self):
        self._state = 1  # lint: disable=guarded-by
