"""Fixture corpus for the durable-rename rule (tests/test_lint_rules.py).

EXPECT markers name the lines the rule must flag; everything else must
stay silent. A rename missing BOTH the preceding fsync and the
following dir fsync earns two findings on the same line (one expected-
line entry covers both — the harness compares line sets).
"""

import os


class GoodStore:
    def __init__(self, fs):
        self.fs = fs

    def save_atomic(self, tmp, final, f):
        self.fs.write(f, b"payload")
        self.fs.fsync(f)                    # data durable before the swap
        self.fs.replace(tmp, final)
        self.fs.fsync_dir(os.path.dirname(final))  # swap durable

    def module_os_variant(self, tmp, final, f):
        f.flush()
        os.fsync(f.fileno())
        os.replace(tmp, final)
        self.fs.fsync_dir(os.path.dirname(final))


class BadStore:
    def __init__(self, fs):
        self.fs = fs

    def rename_without_fsync(self, tmp, final):
        # The PR-5 blob bug: temp contents never synced, rename survives.
        self.fs.replace(tmp, final)  # EXPECT: durable-rename
        self.fs.fsync_dir(os.path.dirname(final))

    def rename_without_dir_fsync(self, tmp, final, f):
        self.fs.fsync(f)
        os.rename(tmp, final)  # EXPECT: durable-rename

    def rename_bare(self, tmp, final):
        os.replace(tmp, final)  # EXPECT: durable-rename


def not_a_rename(name: str) -> str:
    # String .replace must not count as a filesystem rename.
    return name.replace(".tmp", ".json")


def sanctioned_quarantine(fs, path):
    # Renaming an already-closed, already-durable file: no open handle to
    # fsync.  # lint: disable-next=durable-rename
    fs.replace(path, path + ".corrupt")
    fs.fsync_dir(os.path.dirname(path))
