"""canonical-pspec fixtures: trailing literal Nones are flagged, canonical
spellings and computed specs are not."""

import jax.sharding
from jax.sharding import PartitionSpec as P

# ------------------------------------------------------------------ bad

BAD_REPLICATED = P(None, None)  # EXPECT: canonical-pspec
BAD_TRAILING = P("tp", None)  # EXPECT: canonical-pspec
BAD_ROW_PARALLEL = P(None, "tp", None)  # EXPECT: canonical-pspec
BAD_LONG_FORM = jax.sharding.PartitionSpec(None)  # EXPECT: canonical-pspec
BAD_TRIPLE = P(None, None, None)  # EXPECT: canonical-pspec

# ----------------------------------------------------------------- good

GOOD_EMPTY = P()
GOOD_LEADING_NONE = P(None, "tp")       # leading None is meaningful
GOOD_INTERIOR_NONE = P(None, None, "tp")
GOOD_AXIS_ONLY = P("tp")
GOOD_COMPUTED = P(*([None] * 3))        # canonicalizers build these
GOOD_VARIABLE_TAIL = P("dp", some_axis_name)

# ------------------------------------------------------------ suppressed

SHARD_MAP_SPEC = P("dp", "tp", "sp", None)  # lint: disable=canonical-pspec
