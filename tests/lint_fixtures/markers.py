"""slow-marker fixtures (filename intentionally not test_-prefixed so
pytest never collects these; the rule is exercised directly on the Source).
"""

import pytest


def test_soak_unmarked():  # EXPECT: slow-marker
    pass


def test_sustained_load_unmarked():  # EXPECT: slow-marker
    pass


def test_fast_unit():
    pass


@pytest.mark.slow
def test_soak_marked():
    pass


@pytest.mark.slow
class TestSlowGroup:
    def test_stress_many_in_marked_class(self):
        pass


def test_soak_suppressed():  # lint: disable=slow-marker
    pass


# ---- SimConfig duration coverage (semester sim) ----

class SimConfig:  # stand-in so the fixture needs no imports
    def __init__(self, **kw):
        pass


TIER1 = SimConfig(duration_s=16.0)  # short: fine at module scope


def test_long_sim_unmarked():
    cfg = SimConfig(seed=1, duration_s=90.0)  # EXPECT: slow-marker
    return cfg


def test_short_sim_unmarked_ok():
    return SimConfig(duration_s=30.0)


@pytest.mark.slow
def test_long_sim_marked_ok():
    return SimConfig(duration_s=900.0)


def _fixture_helper_long():
    # Helpers count: tier-1 pays the wall clock wherever it is built.
    return SimConfig(duration_s=120.0)  # EXPECT: slow-marker


LONG_MODULE_CFG = SimConfig(duration_s=600.0)  # EXPECT: slow-marker


def test_long_sim_suppressed():
    return SimConfig(duration_s=120.0)  # lint: disable=slow-marker


# ---- guard-nested tests (an `if HAVE_X:` / try-import shim) ----

HAVE_GUARD = True

if HAVE_GUARD:
    @pytest.mark.slow
    def test_soak_marked_in_guard():  # its own decorator must be read
        return SimConfig(duration_s=300.0)

    def test_soak_unmarked_in_guard():  # EXPECT: slow-marker
        pass

    GUARDED_LONG_CFG = SimConfig(duration_s=600.0)  # EXPECT: slow-marker

try:
    @pytest.mark.slow
    def test_stress_many_marked_in_try():
        return SimConfig(duration_s=120.0)
except Exception:
    pass
