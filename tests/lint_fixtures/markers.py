"""slow-marker fixtures (filename intentionally not test_-prefixed so
pytest never collects these; the rule is exercised directly on the Source).
"""

import pytest


def test_soak_unmarked():  # EXPECT: slow-marker
    pass


def test_sustained_load_unmarked():  # EXPECT: slow-marker
    pass


def test_fast_unit():
    pass


@pytest.mark.slow
def test_soak_marked():
    pass


@pytest.mark.slow
class TestSlowGroup:
    def test_stress_many_in_marked_class(self):
        pass


def test_soak_suppressed():  # lint: disable=slow-marker
    pass
