"""no-orphan-task fixtures."""

import asyncio


async def worker():
    await asyncio.sleep(0)


async def deliver(peer, message):
    await asyncio.sleep(0)


class Runner:
    async def _run(self):
        await asyncio.sleep(0)

    def bad_spawns(self, loop, old_channel):
        asyncio.ensure_future(old_channel.close())  # EXPECT: no-orphan-task
        asyncio.create_task(worker())  # EXPECT: no-orphan-task
        loop.create_task(worker())  # EXPECT: no-orphan-task

    def bad_unawaited(self):
        worker()  # EXPECT: no-orphan-task
        self._run()  # EXPECT: no-orphan-task

    def good_spawns(self, loop):
        task = asyncio.ensure_future(worker())
        self._tasks = [task]
        task.add_done_callback(self._tasks.remove)
        kept = loop.create_task(worker())
        return kept

    async def good_awaits(self):
        await worker()
        await self._run()
        result = worker()          # handle kept: caller's responsibility
        return await result

    def good_out_of_scope(self):
        # Receiver types are unknown to a lexical pass: not flagged.
        asyncio.run(worker())
        self.queue.close()

    def suppressed(self):
        asyncio.ensure_future(worker())  # lint: disable=no-orphan-task
