"""donation-safety fixture: donated buffers die at dispatch.

True positives: a read after the donating call, a read through an alias
taken before it (even when the call rebinds the donated name), a donating
call in a loop that never rebinds, and a donated `self.attr` the
statement doesn't rebind. True negatives: the rebind-in-one-statement
idiom, sibling branches (no order between them), a compile-only throwaway
donation, and a suppressed sanctioned case.
"""

from functools import partial

import jax


def _step_program(params, state, rng):
    return state


def _grow_program(state, n):
    return state


def make_state():
    return None


class Engine:
    def __init__(self):
        self._step = jax.jit(partial(_step_program), donate_argnums=(1,))
        self._grow = jax.jit(_grow_program, donate_argnums=(0,))
        self.state = make_state()

    def good_step(self, params, state, rng):
        state = self._step(params, state, rng)
        return state

    def read_after_donate(self, params, state, rng):
        out = self._step(params, state, rng)
        return out, state.tok  # EXPECT: donation-safety

    def alias_read(self, params, state, rng):
        snap = state
        state = self._step(params, state, rng)
        return state, snap.tok  # EXPECT: donation-safety

    def branches_are_unordered(self, params, state, rng, flag):
        if flag:
            out = self._step(params, state, rng)
        else:
            out = self._step(params, state, rng)
        return out

    def loop_rebind_ok(self, params, state, rng):
        for _ in range(3):
            state = self._step(params, state, rng)
        return state

    def loop_never_rebinds(self, params, state, rng):
        for _ in range(3):
            self._step(params, state, rng)  # EXPECT: donation-safety

    def attr_rebound_ok(self, params, rng):
        self.state = self._step(params, self.state, rng)

    def attr_not_rebound(self, params, rng):
        out = self._step(params, self.state, rng)  # EXPECT: donation-safety
        return out

    def throwaway_warmup(self, params, rng):
        # Compile-only dispatch of a fresh local: nothing reads it later.
        state = make_state()
        self._grow(state, 8)

    def sanctioned(self, params, state, rng):
        # A backend quirk needs the pre-donation handle for its shape only.
        out = self._step(params, state, rng)
        return out, state.shape  # lint: disable=donation-safety
