"""Plane-table fixture: the module-level literal spec table IS the
policy. Keys are plane names, values literal P(...) calls — the shape
`absint.collect_plane_tables` recognizes (mirrors
parallel/partition.PAGED_PLANE_SPECS)."""

from jax.sharding import PartitionSpec as P

PLANE_SPECS = {
    "cache.k": P(None, None, "tp"),
    "cache.length": P(),
    "tok": P(),
}

# Not a spec table (values are not P-calls): must be skipped whole, never
# treated as policy.
CLASSIFICATION = {
    "cache.k": "kv",
    "tok": "host",
}
