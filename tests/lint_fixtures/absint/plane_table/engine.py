"""Plane-table fixture producers: every device_put of a DECLARED plane
must land it under exactly the table's spec.

Cases: a name-keyed producer resolving through the table subscript
(silent — the real `_init_state`/`_canon_state` shape), a producer
disagreeing with the table (the reversion pin: re-introducing a
replicated put of a tp-sharded KV plane must fail lint), and a
suppressed disagreement (sanctioned one-off gather)."""

import jax
from jax.sharding import NamedSharding, PartitionSpec as P

from . import partition


def _plane_spec(name):
    return partition.PLANE_SPECS[name]


def init_state(mesh, state):
    def put(x, name):
        return jax.device_put(
            x, NamedSharding(mesh, _plane_spec(name))
        )

    k = put(state.cache.k, "cache.k")
    length = put(state.cache.length, "cache.length")
    tok = put(state.tok, "tok")
    return k, length, tok


def bad_canon(mesh, state):
    # Replicating the tp-sharded KV plane: disagrees with the table.
    k = jax.device_put(state.cache.k, NamedSharding(mesh, P()))  # EXPECT: pspec-flow
    # Agreeing literal spelling is fine (same canonical meaning).
    tok = jax.device_put(state.tok, NamedSharding(mesh, P()))
    return k, tok


def debug_gather(mesh, state):
    # Cold-path full gather for a debug dump; deliberate.
    return jax.device_put(state.cache.k, NamedSharding(mesh, P()))  # lint: disable=pspec-flow
