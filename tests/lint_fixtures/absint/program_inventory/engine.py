"""program-inventory fixture engine: jit sites vs the checked-in manifest.

`_step` is inventoried and warmup-covered through a helper (call-graph
coverage counts). `_prefill` is inventoried as warmup-covered but warmup
never reaches it — flagged on the warmup def. `_rogue` is uninventoried;
`_tmp` is uninventoried but suppressed with a reason. `_drifted` exists
in both but the donation contracts disagree.
"""

from functools import partial

import jax


def _step_program(params, state, rng):
    return state


def _prefill_program(params, ids):
    return ids


def _drift_program(state):
    return state


def _rogue_program(x):
    return x


class MiniEngine:
    def __init__(self):
        self._step = jax.jit(partial(_step_program), donate_argnums=(1,))
        self._prefill = jax.jit(_prefill_program)
        self._drifted = jax.jit(_drift_program, donate_argnums=(0,))  # EXPECT: program-inventory
        self._rogue = jax.jit(_rogue_program)  # EXPECT: program-inventory
        # Experimental program, deliberately unclassified while it bakes.
        self._tmp = jax.jit(_rogue_program)  # lint: disable=program-inventory

    def warmup(self):  # EXPECT: program-inventory
        self._run_once()

    def _run_once(self):
        self._step(None, None, None)
