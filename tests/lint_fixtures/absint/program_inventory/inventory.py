"""Fixture manifest (parsed by the rule, never imported).

`_step`/`_prefill` match live sites; `_drifted`'s donation contract
disagrees with its site (reported there); `_gone` matches nothing —
stale, reported here.
"""

INVENTORY = (
    ProgramEntry(  # noqa: F821 - parse-only fixture
        engine="MiniEngine", attr="_step", target="_step_program",
        donate_argnums=(1,), static_argnums=(),
        domain="widths", coverage="warmup",
    ),
    ProgramEntry(  # noqa: F821 - parse-only fixture
        engine="MiniEngine", attr="_prefill", target="_prefill_program",
        donate_argnums=(), static_argnums=(),
        domain="buckets", coverage="warmup",
    ),
    ProgramEntry(  # noqa: F821 - parse-only fixture
        engine="MiniEngine", attr="_drifted", target="_drift_program",
        donate_argnums=(), static_argnums=(),
        domain="shapes", coverage="on-demand",
    ),
    ProgramEntry(  # EXPECT: program-inventory
        engine="MiniEngine", attr="_gone", target="_gone_program",
        donate_argnums=(), static_argnums=(),
        domain="shapes", coverage="on-demand",
    ),
)
