"""dtype-flow fixture: hot-path arrays keep their dtype.

True positives: `.astype(float)` on a value the walker knows is int8
(directly, through an assignment, and through a project-local helper's
return), weak-type promotion (known-int array times a bare float
literal), and a cast of a KV cache plane. True negatives: casts between
int dtypes, float work on values of unknown dtype, dequant-named
functions (their job), and a suppressed sanctioned case.
"""

import jax.numpy as jnp


def quantize(x):
    return x.astype(jnp.int8)


def dequantize_plane(q, scale):
    # Dequantization is the sanctioned int8 -> float conversion point.
    return q.astype(jnp.float32) * scale


def upcast_direct():
    q = jnp.zeros((4, 4), jnp.int8)
    return q.astype(jnp.float32)  # EXPECT: dtype-flow


def upcast_through_assignment(x):
    q = x.astype(jnp.int8)
    wide = q.astype(jnp.bfloat16)  # EXPECT: dtype-flow
    return wide


def upcast_through_helper(x):
    q = quantize(x)
    return q.astype(jnp.float32)  # EXPECT: dtype-flow


def weak_promotion():
    counts = jnp.zeros((8,), jnp.int32)
    return counts * 0.5  # EXPECT: dtype-flow


def weak_promotion_int8(x):
    q = x.astype(jnp.int8)
    return 0.125 * q  # EXPECT: dtype-flow


def kv_plane_cast(state):
    return state.cache.k.astype(jnp.float32)  # EXPECT: dtype-flow


def int_to_int_is_fine():
    q = jnp.zeros((4,), jnp.int8)
    return q.astype(jnp.int32)


def unknown_dtype_is_silent(x):
    # x's dtype is unknown: no fact, no finding (unsound-by-design).
    return x.astype(jnp.float32) * 0.5


def int_times_int_literal_is_fine():
    counts = jnp.zeros((8,), jnp.int32)
    return counts * 2


def sanctioned(x):
    q = x.astype(jnp.int8)
    # One-off float view for a debug histogram; documented.
    return q.astype(jnp.float32)  # lint: disable=dtype-flow
