"""pspec-flow fixture: per-plane sharding MEANING must be consistent.

Planes here: 'tok' (consistent through a helper), 'lengths' (two
semantically different specs — both spellings canonical, so only
pspec-flow sees it), 'seen' (spelling-different but meaning-identical —
must stay silent), 'extra' (one producer suppressed with a reason — the
sanctioned reshard neither reports nor creates a conflict).
"""

import jax
from jax.sharding import NamedSharding, PartitionSpec as P


def _state_spec(x):
    del x
    return P()


def init_state(mesh, state):
    tok = jax.device_put(
        state.tok, NamedSharding(mesh, _state_spec(state.tok))
    )
    lengths = jax.device_put(state.lengths, NamedSharding(mesh, P()))  # EXPECT: pspec-flow
    # P(None) means the same layout as P(): semantic normalization keeps
    # this silent under pspec-flow (the spelling is canonical-pspec's job).
    seen = jax.device_put(
        state.seen,
        NamedSharding(mesh, P(None)),  # lint: disable=canonical-pspec
    )
    return tok, lengths, seen


def canon_state(mesh, state):
    def put(x, spec=None):
        sh = NamedSharding(mesh, spec if spec is not None else _state_spec(x))
        return jax.device_put(x, sh)

    tok = put(state.tok)
    lengths = put(state.lengths, P("dp"))  # EXPECT: pspec-flow
    seen = put(state.seen)
    return tok, lengths, seen


def sanctioned_reshard(mesh, state):
    # Cold-path gather onto dp for a one-off debug dump; deliberate.
    return jax.device_put(state.extra, NamedSharding(mesh, P("dp")))  # lint: disable=pspec-flow


def steady_producer(mesh, state):
    return jax.device_put(state.extra, NamedSharding(mesh, P()))
