# Known-good and known-bad snippets for tests/test_lint_rules.py.
#
# These files are PARSED by the lint framework, never imported — undefined
# names are fine. Lines expected to be flagged carry an `# EXPECT: <rule>`
# marker; everything else must stay clean. The directory is excluded from
# full lint runs (analysis.core.EXCLUDE_PARTS) and from pytest collection
# (no test_ prefix on the snippet files).
