"""tracer-hygiene fixtures."""

from functools import partial

import jax
import jax.numpy as jnp


def bad_program(params, x, flags):
    total = jnp.sum(x)
    if total > 0:  # EXPECT: tracer-hygiene
        x = x + 1
    while jnp.any(x > 0):  # EXPECT: tracer-hygiene
        x = x - 1
    mask = jax.lax.select(x > 0, x, -x)
    y = mask * 2
    assert x.shape  # param shapes are static: no traced name in the test
    probe = bool(jnp.all(y > 0))  # EXPECT: tracer-hygiene
    sign = 1 if jnp.sum(y) > 0 else -1  # EXPECT: tracer-hygiene
    return y, probe, sign


def good_program(params, x, cfg, cache):
    if cfg.quantized:          # python config attribute: static under trace
        x = x * cfg.scale
    if cache is None:          # None-checks of params are static
        cache = jnp.zeros_like(x)
    n = x.shape[0]
    if n > 4:                  # shapes are python ints
        x = x[:4]
    y = jnp.where(x > 0, x, 0)  # the traced-friendly spelling
    return jax.lax.cond(True, lambda v: v, lambda v: -v, y)


def not_jitted(x):
    # Plain host code: control flow on jnp results is legal (eager).
    if jnp.sum(x) > 0:
        return x
    return -x


def suppressed_program(params, x):
    s = jnp.sum(x)
    if s > 0:  # lint: disable=tracer-hygiene
        return x
    return -x


_bad = jax.jit(partial(bad_program, flags=()))
_good = jax.jit(good_program)
_suppressed = jax.jit(suppressed_program)

_grow = jax.jit(grow_program, static_argnums=(1,))


def dispatches(state):
    _grow(state, (4, 8))                       # tuple: hashable, fine
    _grow(state, [4, 8])  # EXPECT: tracer-hygiene
