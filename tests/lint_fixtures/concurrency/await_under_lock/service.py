"""await-under-lock fixtures: suspension points and blocking calls
reached while a threading lock is held in async code; asyncio.Lock is
exempt by design.
"""

import asyncio
import threading
import time


class Service:
    def __init__(self):
        self._lock = threading.Lock()
        self._alock = asyncio.Lock()
        self._data = {}

    async def bad_await(self):
        with self._lock:
            await asyncio.sleep(0.01)  # EXPECT: await-under-lock

    async def bad_blocking(self):
        with self._lock:
            time.sleep(0.01)  # EXPECT: await-under-lock

    def _load(self):
        time.sleep(0.05)
        return dict(self._data)

    async def bad_call_into_blocking(self):
        with self._lock:
            return self._load()  # EXPECT: await-under-lock

    async def ok_asyncio_lock(self):
        # Suspending under an asyncio.Lock is its design: waiters queue,
        # the loop keeps running.
        async with self._alock:
            await asyncio.sleep(0.01)

    async def ok_snapshot_then_await(self):
        with self._lock:
            snapshot = dict(self._data)
        await asyncio.sleep(0.01)
        return snapshot

    async def ok_sync_critical_section(self):
        with self._lock:
            self._data["k"] = 1
        return True

    async def sanctioned(self):
        with self._lock:
            await asyncio.sleep(0)  # lint: disable=await-under-lock
