"""atomicity-across-await fixtures: the event-loop TOCTOU in miniature.

Annotated (`# guarded-by: event-loop`) and inferred shared attributes,
true-suspension modelling (awaiting a coroutine that never suspends is
not a window), re-validation, blind stores, and a sanctioned last-wins
write.
"""

import asyncio


class Cache:
    def __init__(self):
        self._inflight = {}  # guarded-by: event-loop
        self._hits = 0       # guarded-by: event-loop

    async def _fetch(self, rid):
        await asyncio.sleep(0.001)
        return rid

    async def _count(self):
        await asyncio.sleep(0.001)
        return 1

    async def _tally(self):
        # Async but never suspends: awaiting it is NOT a window.
        return len(self._inflight)

    async def bad_admit(self, rid):
        # Decide on a pre-await read, write the stale decision after.
        if rid not in self._inflight:
            data = await self._fetch(rid)
            self._inflight[rid] = data  # EXPECT: atomicity-across-await
        return self._inflight[rid]

    async def bad_lost_update(self):
        self._hits += await self._count()  # EXPECT: atomicity-across-await

    async def ok_recheck(self, rid):
        # The fix shape: re-validate after the await.
        if rid not in self._inflight:
            data = await self._fetch(rid)
            if rid not in self._inflight:
                self._inflight[rid] = data
        return self._inflight[rid]

    async def ok_blind_store(self, rid):
        # No pre-await decision: a blind store is last-wins by intent.
        data = await self._fetch(rid)
        self._inflight[rid] = data

    async def ok_await_never_suspends(self, rid):
        # _tally has no suspension point, so no other task can run
        # between the read and the write.
        if rid in self._inflight:
            n = await self._tally()
            self._inflight[rid] = n


class Tally:
    """Unannotated state: the conservative inference fallback."""

    def __init__(self):
        self._counts = {}
        self._last_flush = 0.0

    def bump(self, key):
        self._counts[key] = self._counts.get(key, 0) + 1

    async def flush(self, sink):
        snapshot = dict(self._counts)
        await sink.send(snapshot)
        # _counts is inferred shared (mutated from bump AND flush,
        # flush is async): clearing on the pre-await snapshot drops
        # bumps that landed during the send.
        self._counts.clear()  # EXPECT: atomicity-across-await
        # _last_flush has a single writer outside __init__: not shared.
        self._last_flush = 1.0

    async def sanctioned(self, sink):
        stamp = len(self._counts)
        await sink.send(stamp)
        # Deliberate last-wins, visibly suppressed.
        # lint: disable-next=atomicity-across-await
        self._counts["stamp"] = stamp
