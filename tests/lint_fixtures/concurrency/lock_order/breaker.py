"""lock-order fixtures: re-entrance (direct, via callee, via callback)
and acquisition-order cycles. The Breaker/Pool pair below is the PR-13
single-thread self-deadlock in miniature: a callback fired under a
non-reentrant lock whose body re-enters the same lock class through a
property on a *sibling* instance.
"""

import threading


class Breaker:
    def __init__(self):
        self._lock = threading.Lock()
        self._cb = None
        self._state = "closed"

    def set_state_callback(self, cb):
        self._cb = cb

    @property
    def state(self):
        with self._lock:
            return self._state

    def _transition(self, new):
        with self._lock:
            self._state = new
            cb = self._cb
            if cb is not None:
                cb("closed", new)  # EXPECT: lock-order


class Pool:
    def __init__(self, a: "Breaker", b: "Breaker"):
        self.a = a
        self.b = b
        a.set_state_callback(self._on_change)

    def _on_change(self, old, new):
        # Reads the sibling breaker's live locked state: lock identity
        # is per declaration site, so this re-enters Breaker._lock.
        return self.b.state


class Recount:
    def __init__(self):
        self._lock = threading.Lock()
        self._rlock = threading.RLock()

    def bad_direct(self):
        with self._lock:
            with self._lock:  # EXPECT: lock-order
                pass

    def bad_via_callee(self):
        with self._lock:
            self._helper()  # EXPECT: lock-order

    def _helper(self):
        with self._lock:
            pass

    def ok_rlock(self):
        with self._rlock:
            with self._rlock:  # reentrant by design
                pass

    def ok_disjoint(self):
        with self._lock:
            pass
        with self._rlock:
            pass

    def sanctioned(self):
        with self._lock:
            self._helper()  # lint: disable=lock-order


class Pair:
    def __init__(self):
        self._a = threading.Lock()
        self._b = threading.Lock()

    def forward(self):
        with self._a:
            with self._b:  # EXPECT: lock-order
                pass

    def reverse(self):
        with self._b:
            with self._a:  # EXPECT: lock-order
                pass
