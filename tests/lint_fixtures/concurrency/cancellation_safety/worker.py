"""cancellation-safety fixtures: awaits in finally, swallowed
CancelledError, unawaited cancels — plus every allowed idiom (shield,
wait_for, cancel-then-reap, canceller-absorb, unknown-receiver cancel).
"""

import asyncio


async def bad_finally(coro):
    try:
        return await coro
    finally:
        await asyncio.sleep(0.1)  # EXPECT: cancellation-safety


async def ok_shielded(coro, cleanup):
    try:
        return await coro
    finally:
        await asyncio.shield(cleanup())


async def ok_bounded(coro, cleanup):
    try:
        return await coro
    finally:
        await asyncio.wait_for(cleanup(), 1.0)


async def ok_cancel_then_reap(tasks):
    try:
        await asyncio.sleep(1.0)
    finally:
        for t in tasks:
            t.cancel()
        await asyncio.gather(*tasks, return_exceptions=True)


async def bad_swallow(fut):
    try:
        return await fut
    except asyncio.CancelledError:  # EXPECT: cancellation-safety
        return None


async def bad_bare_except(fut):
    try:
        return await fut
    except:  # noqa: E722  # EXPECT: cancellation-safety
        return None


async def ok_exception_only(fut):
    # CancelledError derives from BaseException: Exception is safe.
    try:
        return await fut
    except Exception:
        return None


async def ok_reraise(fut):
    try:
        return await fut
    except asyncio.CancelledError:
        raise


async def ok_canceller_absorb(task):
    # Absorbing the CancelledError you injected yourself is the reap.
    task.cancel()
    try:
        await task
    except asyncio.CancelledError:
        pass


async def bad_unawaited_cancel():
    task = asyncio.create_task(asyncio.sleep(5))
    task.cancel()  # EXPECT: cancellation-safety
    return True


async def ok_cancel_then_await():
    task = asyncio.create_task(asyncio.sleep(5))
    task.cancel()
    try:
        await task
    except asyncio.CancelledError:
        pass


async def ok_cancel_unknown_receiver(runner):
    # `runner` may be a non-task with a synchronous cancel(): receivers
    # of unknown type are skipped rather than guessed at.
    runner.cancel()
    await asyncio.sleep(0)


async def sanctioned(fut):
    try:
        return await fut
    except BaseException:  # lint: disable=cancellation-safety
        return None
