"""The tests/-scoping case: a test coroutine run by asyncio.run has no
canceller, so its `finally` never races a pending CancelledError and
the await-in-finally check skips files under tests/. The same file
rooted at the fixture directory (rel without the tests/ prefix) IS
flagged — the marker below is asserted under that root only.
"""


async def teardown(server):
    try:
        await server.serve()
    finally:
        await server.stop()  # EXPECT: cancellation-safety
