"""no-host-sync-in-dispatch fixtures: unmarked readbacks are flagged,
syncs inside `with intended_transfer():` are not."""

import jax
import jax.numpy as jnp
import numpy as np

from distributed_lms_raft_llm_tpu.utils.guards import intended_transfer


def hot_loop(state, toks_dev):
    # ------------------------------------------------------------- bad
    toks = np.asarray(toks_dev)  # EXPECT: no-host-sync-in-dispatch
    first = jax.device_get(state.tok)  # EXPECT: no-host-sync-in-dispatch
    n = state.length.item()  # EXPECT: no-host-sync-in-dispatch
    xs = state.tok.tolist()  # EXPECT: no-host-sync-in-dispatch
    total = float(jnp.sum(state.seen))  # EXPECT: no-host-sync-in-dispatch
    state.tok.block_until_ready()  # EXPECT: no-host-sync-in-dispatch
    return toks, first, n, xs, total


def sanctioned(state, toks_dev):
    # ------------------------------------------------------------ good
    with intended_transfer():
        toks = np.asarray(toks_dev)
        first = jax.device_get(state.tok)
    host_batch = np.zeros((4, 4))
    host_list = host_batch.shape[0]          # host-side numpy is fine
    ids = jnp.asarray(host_batch)            # h2d staging is not a sync
    try:
        toks_dev.copy_to_host_async()        # async copy: not a sync point
    except AttributeError:
        pass
    x = float(host_list)                     # cast of a host value
    return toks, first, ids, x


def suppressed(val_dev):
    return val_dev.item()  # lint: disable=no-host-sync-in-dispatch
