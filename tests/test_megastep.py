"""Device-resident megastep decode: K chunks per host dispatch.

The megastep changes WHEN the host talks to the device, never WHAT the
device computes: greedy streams through megasteps (any K) must be
bit-identical to the chunk-loop paged engine AND the bucketed engine, in
plain, spec, kv-quant, slot-churn, and mid-megastep-admission scenarios.
On top of exactness: the TTFT-aware K controller shrinks whenever work
waits for a slot (the p90-TTFT guard), step-program host dispatches per
emitted token drop by exactly K at steady state, the on-device dead-lane
account matches a first-principles derivation, the whole megastep domain
is warmup-covered (`expected_from_inventory` equality), and the serving
queue surfaces the new efficiency gauges.
"""

import asyncio

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from distributed_lms_raft_llm_tpu.engine import (
    EngineConfig,
    PagedEngine,
    PagedQueue,
    SamplingParams,
    TutoringEngine,
)
from distributed_lms_raft_llm_tpu.engine.paged import (
    SlotState,
    _megastep_program,
    _step_program,
    next_megastep_k,
)
from distributed_lms_raft_llm_tpu.engine.program_inventory import (
    effective_megastep_max,
    megastep_ladder,
)
from distributed_lms_raft_llm_tpu.models import registry
from distributed_lms_raft_llm_tpu.utils.guards import (
    compile_count_guard,
    expected_from_inventory,
)
from distributed_lms_raft_llm_tpu.utils.metrics import Metrics

MAX_NEW = 8

PROMPTS = ["what is raft?", "hello world", "explain paging", "k"]


def make_config(**kw):
    kw.setdefault("sampling", SamplingParams.greedy(max_new_tokens=MAX_NEW))
    kw.setdefault("length_buckets", (16,))
    return EngineConfig(
        model="tiny",
        batch_buckets=(1, 2, 4),
        dtype=jnp.float32,
        **kw,
    )


# ------------------------------------------------------- controller + ladder


def test_megastep_ladder_shapes():
    assert megastep_ladder(1) == [1]
    assert megastep_ladder(0) == [1]
    assert megastep_ladder(2) == [1, 2]
    assert megastep_ladder(8) == [1, 2, 4, 8]
    assert megastep_ladder(6) == [1, 2, 4, 6]  # ceiling always a rung


def test_effective_megastep_max_explicit_ceiling_wins():
    """An explicitly configured ceiling caps the starting rung (the
    worst-case admission wait the operator bounded must hold); 0 means
    follow `megastep`."""
    assert effective_megastep_max(8, 4) == 4   # ceiling clamps the start
    assert effective_megastep_max(2, 8) == 8
    assert effective_megastep_max(4, 0) == 4   # 0 = follow megastep
    assert effective_megastep_max(0, 0) == 1
    eng = PagedEngine(make_config(), slots=2, chunk=2,
                      megastep=8, megastep_max=4)
    assert eng.megastep_ks == [1, 2, 4]
    assert eng.megastep_k == 4


def test_controller_shrinks_when_pending_queue_nonempty():
    """The TTFT guard: backlogged work caps K at the guaranteed
    admission horizon. At the horizon (a slot frees within one chunk) or
    with no horizon at all, the engine IS the chunk loop — a waiting
    request is never delayed past the boundary a chunk loop would have
    admitted it at."""
    ladder = [1, 2, 4, 8]
    assert next_megastep_k(8, ladder, pending=1, slack_chunks=1) == 1
    assert next_megastep_k(8, ladder, pending=3, slack_chunks=0) == 1
    assert next_megastep_k(8, ladder, pending=1, slack_chunks=None) == 1
    assert next_megastep_k(4, ladder, pending=1, slack_chunks=3) == 2
    assert next_megastep_k(8, ladder, pending=1, slack_chunks=5) == 4
    assert next_megastep_k(1, [1], pending=5, slack_chunks=9) == 1


def test_controller_holds_amortization_under_saturation():
    """A sustained backlog with the next guaranteed slot-free far away
    must NOT pin K at the floor: boundaries before the horizon admit
    nobody and only forfeit amortization — this is the saturation regime
    the megastep exists for, and an unconditional shrink-on-pending
    would disable it exactly there."""
    ladder = [1, 2, 4, 8]
    assert next_megastep_k(1, ladder, pending=16, slack_chunks=64) == 8
    assert next_megastep_k(8, ladder, pending=16, slack_chunks=8) == 8
    assert next_megastep_k(2, ladder, pending=1, slack_chunks=4) == 4


def test_controller_fused_floor_is_second_rung():
    """Satellite pin (staged chunked admission): with fusion on, a
    boundary's only admission value is handing a freed slot to the
    stager — the prefill itself drains through scan iterations — so the
    pending-queue shrink must NOT reach the K=1 chunk loop. K stays >= 2
    under a non-empty pending queue at any slack, while the slack cap
    still applies above the floor."""
    ladder = [1, 2, 4, 8]
    # The sequential path drops to 1 at these points; fused holds 2.
    assert next_megastep_k(8, ladder, pending=1, slack_chunks=1,
                           fused=True) == 2
    assert next_megastep_k(8, ladder, pending=3, slack_chunks=0,
                           fused=True) == 2
    assert next_megastep_k(8, ladder, pending=1, slack_chunks=None,
                           fused=True) == 2
    # Above the floor the slack/horizon math is unchanged.
    assert next_megastep_k(8, ladder, pending=1, slack_chunks=5,
                           fused=True) == 4
    assert next_megastep_k(1, ladder, pending=16, slack_chunks=64,
                           fused=True) == 8
    # Idle growth identical; a [1] ladder (megastep disabled) still
    # returns its only rung.
    assert next_megastep_k(1, ladder, pending=0, fused=True) == 2
    assert next_megastep_k(1, [1], pending=5, slack_chunks=0,
                           fused=True) == 1


def test_controller_grows_toward_max_when_idle():
    ladder = [1, 2, 4, 8]
    assert next_megastep_k(1, ladder, pending=0) == 2
    assert next_megastep_k(4, ladder, pending=0) == 8
    assert next_megastep_k(8, ladder, pending=0) == 8  # ceiling
    assert next_megastep_k(1, [1], pending=0) == 1     # disabled


def test_engine_controller_tracks_admission_horizon():
    """Through the real engine: a backlog keeps K wide while no slot can
    free (slack = remaining budget), steps K down to the floor once the
    dispatched debt covers the guaranteed finish, and pops back up the
    moment the freed lanes refill — amortization under saturation,
    chunk-loop admission timing at the boundary."""
    eng = PagedEngine(make_config(), slots=2, chunk=2,
                      megastep=4, megastep_max=4)
    for i in range(6):
        eng.submit(f"question number {i}")
    eng.step()  # 2 admitted (7 budget tokens left -> 4-chunk horizon)
    assert eng.megastep_k == 4
    eng.step()  # in-flight megastep covers the horizon -> boundary K
    assert eng.megastep_k == 1
    eng.step()  # wave reaped, lanes refilled from the backlog -> wide
    assert eng.megastep_k == 4
    eng.drain()


# ------------------------------------------------------- greedy bit-equality


class TestGreedyBitEquality:
    @pytest.mark.parametrize("megastep", [1, 4])
    def test_matches_chunk_loop_and_bucketed(self, megastep):
        """Acceptance pin: megastep K in {1, 4} emits exactly what the
        chunk-loop paged engine and the bucketed engine emit."""
        cfg = make_config()
        expected = TutoringEngine(cfg).answer_batch(list(PROMPTS))
        plain = PagedEngine(cfg, slots=4, chunk=2)
        pr = [plain.submit(p) for p in PROMPTS]
        out_plain = plain.drain()
        assert [out_plain[r] for r in pr] == expected

        mega = PagedEngine(cfg, slots=4, chunk=2,
                           megastep=megastep, megastep_max=megastep)
        mr = [mega.submit(p) for p in PROMPTS]
        out_mega = mega.drain()
        assert [out_mega[r] for r in mr] == expected

    @pytest.mark.parametrize("spec_tokens", [1, 3])
    def test_spec_mode(self, spec_tokens):
        """Megastep x speculation: K fused chunks of [S, k+1] verify
        windows must still match the non-spec engines bit for bit."""
        cfg = make_config(spec_tokens=0)
        expected = TutoringEngine(cfg).answer_batch(list(PROMPTS))
        mega = PagedEngine(
            make_config(spec_tokens=spec_tokens), slots=4, chunk=2,
            megastep=4, megastep_max=4,
        )
        mr = [mega.submit(p) for p in PROMPTS]
        out = mega.drain()
        assert [out[r] for r in mr] == expected
        windows, emitted = mega.pop_spec_stats()
        assert windows > 0
        assert windows <= emitted <= windows * (spec_tokens + 1)

    def test_kv_quant(self):
        cfg = make_config(kv_quant=True)
        expected = TutoringEngine(cfg).answer_batch(list(PROMPTS[:2]))
        mega = PagedEngine(cfg, slots=2, chunk=2,
                           megastep=4, megastep_max=4)
        mr = [mega.submit(p) for p in PROMPTS[:2]]
        out = mega.drain()
        assert [out[r] for r in mr] == expected

    def test_slot_churn_and_prompt_buckets(self):
        """5 requests over 2 slots with mixed prompt buckets: admissions
        land at megastep boundaries, the controller moves along the
        ladder as the backlog drains, and every stream still matches the
        bucketed engine."""
        cfg = make_config(length_buckets=(4, 8, 16))
        prompts = list(PROMPTS) + ["k v"]
        expected = TutoringEngine(cfg).answer_batch(prompts)
        mega = PagedEngine(cfg, slots=2, chunk=2,
                           megastep=2, megastep_max=4)
        rids = [mega.submit(p) for p in prompts]
        out = mega.drain()
        assert [out[r] for r in rids] == expected

    def test_pipelined_megasteps_match_serialized(self):
        """inflight=2 (dispatch megastep N+1 before reading N) with the
        stacked [K, chunk, S] reap must produce byte-identical answers."""
        cfg = make_config()
        ser = PagedEngine(cfg, slots=2, chunk=2, inflight=1,
                          megastep=4, megastep_max=4)
        rs = [ser.submit(p) for p in PROMPTS]
        out_ser = ser.drain()
        pipe = PagedEngine(cfg, slots=2, chunk=2, inflight=2,
                           megastep=4, megastep_max=4)
        rp = [pipe.submit(p) for p in PROMPTS]
        out_pipe = pipe.drain()
        assert [out_pipe[r] for r in rp] == [out_ser[r] for r in rs]


def test_mid_megastep_admission_joins_at_next_boundary():
    """A request submitted while megasteps are in flight is admitted at
    the next dispatch boundary, and the controller's shrink keeps its
    wait bounded — it finishes within its own budget, not after A's."""
    eng = PagedEngine(make_config(), slots=2, chunk=2,
                      megastep=4, megastep_max=4)
    eng.submit("a long question about distributed consensus and logs")
    for _ in range(2):
        eng.step()  # A mid-decode; megasteps pipelined in flight
    b = eng.submit("b")
    finished = {}
    steps_after_b = 0
    while eng.has_work and steps_after_b < 3 * MAX_NEW:
        steps_after_b += 1
        for rid, _ in eng.step():
            finished.setdefault(rid, steps_after_b)
        if steps_after_b == 1:
            in_slots = {r.rid for r in eng._slot_req if r is not None}
            assert b in in_slots or b in finished
    assert b in finished
    # Each dispatch advances >= chunk tokens for B once admitted; with the
    # admission + pipelined-reap slack, B cannot have waited for A's
    # remaining decode.
    assert finished[b] <= MAX_NEW // 2 + 3


# -------------------------------------------------- dispatch amortization


def test_step_dispatches_per_token_reduced_4x_at_k4():
    """The megastep's target number: at K=4 the host pays 4x fewer
    decode-step dispatches per emitted token than the chunk loop (the
    per-request prefill+install dispatches are admission constants that
    megastep does not touch; the chunk loop proper is what it removes).
    inflight=1 keeps the dispatch count exact (no pipelined overhang)."""
    max_new = 17  # 1 admission token + 16 decode steps at chunk=1
    cfg = make_config(
        sampling=SamplingParams.greedy(max_new_tokens=max_new),
        length_buckets=(8,),
    )
    prompt = "a question about raft elections and paging"

    def run(megastep):
        eng = PagedEngine(cfg, slots=1, chunk=1, inflight=1,
                          megastep=megastep, megastep_max=megastep)
        eng.submit(prompt)
        eng.drain()
        dispatches, tokens, _dead, _stall, _stalled = \
            eng.pop_dispatch_stats()
        steps = sum(
            1 for name, _, _ in eng.pop_program_times()
            if name in ("step", "megastep")
        )
        return dispatches, tokens, steps

    d1, t1, steps1 = run(1)
    d4, t4, steps4 = run(4)
    assert t1 == t4 == max_new, "prompt must use its full budget (no eos)"
    assert steps1 / steps4 >= 4.0
    # Total host dispatches per token (admissions included) shrink too.
    assert d4 / t4 < d1 / t1


# ------------------------------------------------------ dead-lane account


def test_dead_lane_account_matches_first_principles():
    """A slot that dies (eos) inside a megastep burns one pad lane per
    remaining scan iteration; the device-side account must equal
    chunk * (chunks remaining after the one it died in), derived
    independently from a chunk-loop discovery run."""
    family, cfg = registry.resolve("tiny", jnp.float32)
    params = family.init_params(jax.random.key(0), cfg)
    sampling = SamplingParams.greedy(max_new_tokens=32)
    s_slots, t0, width, chunk, k_chunks = 2, 4, 40, 2, 3
    rng = np.random.default_rng(0)
    ids = jnp.asarray(
        rng.integers(1, cfg.vocab_size, (s_slots, t0)), jnp.int32
    )
    cache = family.init_cache(cfg, s_slots, width, dtype=cfg.dtype)
    _, cache = family.forward(params, cfg, ids, cache=cache)
    cache = cache._replace(length=jnp.full((s_slots,), t0, jnp.int32))
    transcript = jnp.zeros((s_slots, width), jnp.int32)
    transcript = transcript.at[:, :t0].set(ids)
    key_shape = jax.random.key_data(jax.random.key(0)).shape
    state = SlotState(
        cache=cache,
        tok=ids[:, -1],
        active=jnp.ones((s_slots,), bool),
        seen=jnp.zeros((s_slots, cfg.vocab_size), bool),
        transcript=transcript,
        staged=jnp.zeros((s_slots,), bool),
        stage_cursor=jnp.zeros((s_slots,), jnp.int32),
        stage_len=jnp.ones((s_slots,), jnp.int32),
        stage_seq=jnp.zeros((s_slots,), jnp.int32),
        stage_rng=jnp.zeros((s_slots,) + key_shape, jnp.uint32),
    )
    statics = dict(cfg=cfg, sampling=sampling, pad_id=0, model=family,
                   chunk=chunk)
    # Discovery: with an unreachable eos, greedy decode runs the full
    # k_chunks * chunk iterations; pick slot 0's token at iteration 1
    # (mid-chunk-0) as the eos for the measured run.
    _, toks, _ = _step_program(
        params, state, jax.random.key(1), eos_id=-1,
        **dict(statics, chunk=chunk * k_chunks),
    )
    toks = np.asarray(toks)  # [chunk*K, S]
    eos = int(toks[1, 0])
    # Slot 0 must die in chunk 0 and slot 1 must survive the whole
    # megastep for the expected count below to be exact.
    die_iter = int(np.argmax(toks[:, 0] == eos))
    assert die_iter < chunk
    assert eos not in toks[:, 1]
    rngs = jnp.stack([jax.random.key(1)] + [
        jax.random.key(100 + i) for i in range(k_chunks - 1)
    ])
    _, _, active, dead = _megastep_program(
        params, state, rngs, eos_id=eos, spec_tokens=0, **statics
    )
    active = np.asarray(active)
    assert active[0, 0] == 0 and all(active[:, 1] == 1)
    # Slot 0 is dead after chunk 0 -> burns chunk lanes in each of the
    # K-1 remaining chunks; slot 1 never dies -> contributes nothing.
    assert int(np.asarray(dead)) == chunk * (k_chunks - 1)


def test_k1_dispatches_account_no_dead_lanes():
    """Chunk-loop mode reaps every chunk, so the dead-lane account stays
    zero by construction."""
    eng = PagedEngine(make_config(), slots=2, chunk=2)
    for p in PROMPTS[:2]:
        eng.submit(p)
    eng.drain()
    _, _, dead, _, _ = eng.pop_dispatch_stats()
    assert dead == 0


# --------------------------------------------- warmup / inventory coverage


def test_warmed_megastep_session_passes_inventory_guard():
    """compile_count_guard(expected_from_inventory(...)): warmup compiles
    the full megastep domain (widths x ladder rungs >= 2) and a live
    session that walks the controller across rungs, churns slots, and
    grows the cache adds ZERO programs."""
    eng = PagedEngine(
        make_config(length_buckets=(4, 16)), slots=2, chunk=2,
        megastep=2, megastep_max=4,
    )
    assert eng.megastep_ks == [1, 2, 4]
    eng.warmup()
    expectation = expected_from_inventory(eng)
    assert expectation.expected["_megastep"] == len(eng.widths) * 2
    assert expectation.mismatches() == {}
    with compile_count_guard(expectation) as guard:
        eng.submit("k v")
        eng.step()
        eng.submit("a longer question about raft elections and logs")
        eng.drain()
        for prompt in ("k v", "a longer question about raft", "k v"):
            eng.submit(prompt)
        eng.drain()
    assert guard.new_compiles() == 0


def test_unwarmed_megastep_engine_fails_inventory_guard():
    from distributed_lms_raft_llm_tpu.utils.guards import RecompileError

    eng = PagedEngine(
        make_config(length_buckets=(4, 16)), slots=2, chunk=2,
        megastep=4, megastep_max=4,
    )
    with pytest.raises(RecompileError):
        with compile_count_guard(expected_from_inventory(eng)):
            eng.submit("hello")
            eng.drain()


# ------------------------------------------------------- serving queue


def test_paged_queue_reports_megastep_metrics():
    """The serving path surfaces megastep efficiency: the live K gauge,
    the host-dispatches-per-token ratio, and (when megasteps strand
    finished slots) the dead-lane counter."""
    metrics = Metrics()
    engine = PagedEngine(make_config(), slots=2, chunk=2,
                         megastep=2, megastep_max=4)

    async def run():
        q = PagedQueue(engine, metrics=metrics)
        await q.start()
        answers = await asyncio.gather(
            *[q.submit(f"query number {i}") for i in range(4)]
        )
        await q.close()
        return answers

    answers = asyncio.run(run())
    assert len(answers) == 4
    snap = metrics.snapshot()
    assert snap["gauges"]["megastep_k"] in {
        float(k) for k in engine.megastep_ks
    }
    dpt = snap["gauges"]["host_dispatches_per_token"]
    assert 0.0 < dpt < 2.0
    assert metrics.hist("ttft").snapshot()["count"] == 4
