"""Simulated Raft clusters on MemTransport: elections, failover, partitions,
durability across restart — the deterministic-simulation harness replacing
the reference's run-five-terminals-and-watch validation (SURVEY.md §4)."""

import asyncio

import pytest

from distributed_lms_raft_llm_tpu.raft import (
    MemNetwork,
    MemoryStorage,
    NotLeader,
    RaftConfig,
    RaftNode,
    encode_command,
)

FAST = RaftConfig(
    election_timeout_min=0.11, election_timeout_max=0.22, heartbeat_interval=0.05
)


def build_cluster(network, n=3, applied=None, storages=None):
    ids = list(range(1, n + 1))
    storages = storages or {i: MemoryStorage() for i in ids}
    nodes = {}
    for i in ids:
        def make_cb(i=i):
            def cb(index, entry):
                if applied is not None:
                    applied.setdefault(i, []).append((index, entry.command))
            return cb

        node = RaftNode(
            i, ids, storages[i], network.transport_for(i),
            apply_cb=make_cb(), config=FAST, tick_interval=0.01, seed=100 + i,
        )
        network.register(node)
        nodes[i] = node
    return nodes, storages


async def wait_for_leader(nodes, timeout=5.0):
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        leaders = [n for n in nodes.values() if n.is_leader and not n._stopped]
        if leaders:
            return leaders[0]
        await asyncio.sleep(0.02)
    raise AssertionError("no leader elected")


def test_elects_single_leader():
    async def run():
        net = MemNetwork()
        nodes, _ = build_cluster(net, 3)
        for n in nodes.values():
            await n.start()
        leader = await wait_for_leader(nodes)
        await asyncio.sleep(0.3)
        leaders = [n.node_id for n in nodes.values() if n.is_leader]
        assert leaders == [leader.node_id]
        # Followers learn the leader id (WhoIsLeader capability).
        assert all(n.leader_id == leader.node_id for n in nodes.values())
        for n in nodes.values():
            await n.stop()

    asyncio.run(run())


def test_replication_commits_and_applies_everywhere():
    async def run():
        net = MemNetwork()
        applied = {}
        nodes, _ = build_cluster(net, 3, applied=applied)
        for n in nodes.values():
            await n.start()
        leader = await wait_for_leader(nodes)
        for k in range(5):
            await leader.propose(encode_command("Register", {"u": f"user{k}"}))
        await asyncio.sleep(0.3)  # let commit index propagate via heartbeat
        for n in nodes.values():
            await n.stop()
        # All three nodes applied the same 5 commands in the same order.
        assert set(applied) == {1, 2, 3}
        seqs = {i: [c for _, c in applied[i]] for i in applied}
        assert seqs[1] == seqs[2] == seqs[3]
        assert len(seqs[1]) == 5

    asyncio.run(run())


def test_leader_failover_and_log_continuity():
    async def run():
        net = MemNetwork()
        applied = {}
        nodes, _ = build_cluster(net, 3, applied=applied)
        for n in nodes.values():
            await n.start()
        leader = await wait_for_leader(nodes)
        await leader.propose(encode_command("SetVal", {"key": "a", "value": "1"}))
        # Kill the leader.
        await leader.stop()
        survivors = {i: n for i, n in nodes.items() if i != leader.node_id}
        new_leader = await wait_for_leader(survivors)
        assert new_leader.node_id != leader.node_id
        await new_leader.propose(encode_command("SetVal", {"key": "b", "value": "2"}))
        await asyncio.sleep(0.3)
        for n in survivors.values():
            await n.stop()
        # Survivors applied both commands in order.
        for i in survivors:
            cmds = [c for _, c in applied[i]]
            assert len(cmds) == 2

    asyncio.run(run())


def test_minority_partition_cannot_commit():
    async def run():
        net = MemNetwork()
        nodes, _ = build_cluster(net, 5)
        for n in nodes.values():
            await n.start()
        leader = await wait_for_leader(nodes)
        others = [i for i in nodes if i != leader.node_id]
        # Isolate the leader with one follower (minority side).
        minority = {leader.node_id, others[0]}
        majority = set(others[1:])
        net.partition(minority, majority)
        with pytest.raises((NotLeader, TimeoutError)):
            await leader.propose(encode_command("X", {}), timeout=1.0)
        # Majority side elects a fresh leader and can commit.
        maj_nodes = {i: nodes[i] for i in majority}
        new_leader = await wait_for_leader(maj_nodes)
        idx = await new_leader.propose(encode_command("Y", {}))
        assert idx > 0
        # Heal: old leader steps down and converges.
        net.heal()
        await asyncio.sleep(0.6)
        assert not nodes[leader.node_id].is_leader
        assert nodes[leader.node_id].core.current_term >= new_leader.core.current_term
        for n in nodes.values():
            await n.stop()

    asyncio.run(run())


def test_restart_from_storage_preserves_log():
    async def run():
        net = MemNetwork()
        applied = {}
        nodes, storages = build_cluster(net, 3, applied=applied)
        for n in nodes.values():
            await n.start()
        leader = await wait_for_leader(nodes)
        await leader.propose(encode_command("SetVal", {"key": "k", "value": "v"}))
        await asyncio.sleep(0.2)
        # Stop a follower, then "restart" it with the same storage.
        follower_id = next(i for i in nodes if i != leader.node_id)
        await nodes[follower_id].stop()
        await asyncio.sleep(0.1)
        net2_node = RaftNode(
            follower_id, list(nodes), storages[follower_id],
            net.transport_for(follower_id), config=FAST, tick_interval=0.01,
        )
        net.register(net2_node)  # replaces the stopped incarnation
        await net2_node.start()
        assert net2_node.core.last_log_index >= 1  # log survived the restart
        assert net2_node.core.current_term >= 1
        await asyncio.sleep(0.3)
        for n in [*nodes.values(), net2_node]:
            if not n._stopped:
                await n.stop()

    asyncio.run(run())


def test_waiter_not_resolved_by_other_leaders_entry():
    """A commit waiter must fail, not resolve, when a different term's entry
    lands at its index (lost-leadership overwrite)."""

    async def run():
        from distributed_lms_raft_llm_tpu.raft import MemoryStorage
        from distributed_lms_raft_llm_tpu.raft.node import RaftNode, Transport

        class NullTransport(Transport):
            async def send(self, peer, message):
                raise ConnectionError("isolated")

        node = RaftNode(1, [1, 2, 3], MemoryStorage(), NullTransport(), config=FAST)
        # Manually become leader without quorum contact (simulated).
        node.core.start_election(0.0)
        node.core.votes = {1, 2}
        node.core._maybe_win(0.0)
        assert node.is_leader
        term1 = node.core.current_term
        fut_task = asyncio.ensure_future(
            node.propose(encode_command("A", {}), timeout=2.0)
        )
        await asyncio.sleep(0.01)
        # New leader (higher term) overwrites our slot and commits past it.
        from distributed_lms_raft_llm_tpu.raft import AppendRequest, Entry
        from distributed_lms_raft_llm_tpu.raft.messages import NOOP

        req = AppendRequest(
            term=term1 + 1, leader_id=2, prev_log_index=0, prev_log_term=0,
            entries=(Entry(term1 + 1, NOOP), Entry(term1 + 1, encode_command("B", {}))),
            leader_commit=2,
        )
        node.handle_append_request(req)
        with pytest.raises(Exception) as e:
            await fut_task
        assert "leader" in str(e.value).lower() or "not" in str(e.value).lower()
        await node.stop()

    asyncio.run(run())


def test_fast_catchup_streams_beyond_one_batch():
    """A far-behind follower catches up without waiting a heartbeat per batch."""

    async def run():
        net = MemNetwork()
        nodes, storages = build_cluster(net, 3)
        # Only start two nodes; propose many entries.
        await nodes[1].start()
        await nodes[2].start()
        leader = await wait_for_leader({1: nodes[1], 2: nodes[2]})
        small_batch = leader.core.config.max_entries_per_append
        n_entries = small_batch * 4
        for k in range(n_entries):
            await leader.propose(encode_command("E", {"k": k}))
        # Now start the lagging third node and time its catch-up.
        await nodes[3].start()
        loop = asyncio.get_running_loop()
        t0 = loop.time()
        while nodes[3].core.last_log_index < leader.core.last_log_index:
            if loop.time() - t0 > 3.0:
                raise AssertionError("catch-up too slow")
            await asyncio.sleep(0.02)
        elapsed = loop.time() - t0
        # 4+ batches in far less than 4 heartbeat intervals => streaming works.
        assert elapsed < 1.0
        for n in nodes.values():
            await n.stop()

    asyncio.run(run())
