"""The request-path file RPCs spend deadline budget instead of wall-clock
constants (PR-4 sweep for the deadline-flow rule's two real findings).

Before: blob fetch-on-miss dialed every peer with `timeout=5` and the
upload replication sweep gave each peer `timeout=30` — a client whose
budget had already expired could still pin the node for (peers × cap)
seconds. Now both derive per-attempt timeouts from the live budget with
`[resilience]` caps, and an expired budget fails fast (counted, not
dialed). These tests pin the fail-fast property with wall-clock bounds
far below the old fixed timeouts.
"""

import asyncio
import time
import types

import pytest

from distributed_lms_raft_llm_tpu.lms import service as service_mod
from distributed_lms_raft_llm_tpu.lms.persistence import BlobStore
from distributed_lms_raft_llm_tpu.lms.service import (
    LMSServicer,
    replicate_file_to_peers,
)
from distributed_lms_raft_llm_tpu.lms.state import LMSState
from distributed_lms_raft_llm_tpu.utils.metrics import Metrics
from distributed_lms_raft_llm_tpu.utils.resilience import Deadline


def _servicer(tmp_path, metrics, blob_fetch_timeout_s=5.0):
    return LMSServicer(
        types.SimpleNamespace(leader_id=1),
        LMSState(),
        BlobStore(str(tmp_path / "blobs")),
        metrics=metrics,
        peer_addresses={1: "127.0.0.1:1", 2: "127.0.0.1:2"},
        self_id=3,
        blob_fetch_timeout_s=blob_fetch_timeout_s,
    )


def test_blob_fetch_expired_budget_fails_fast(tmp_path, monkeypatch):
    """An expired client budget returns metadata-only WITHOUT dialing any
    peer (the old code spent up to 5 s per peer on a dead request)."""
    metrics = Metrics()
    servicer = _servicer(tmp_path, metrics)

    def no_dial(*a, **k):  # the whole point: the sweep never starts
        raise AssertionError("dialed a peer with an expired budget")

    monkeypatch.setattr(service_mod.grpc.aio, "insecure_channel", no_dial)
    t0 = time.monotonic()
    content = asyncio.run(
        servicer._blob("materials/x.pdf", deadline=Deadline.after(0.0))
    )
    assert content == b""
    assert time.monotonic() - t0 < 1.0
    assert metrics.snapshot()["counters"]["blob_fetch_budget_exhausted"] == 1


def test_blob_fetch_timeout_derived_from_live_budget(tmp_path, monkeypatch):
    """With budget below the cap, each per-peer FetchFile timeout is the
    remaining budget, not the 5 s cap."""
    metrics = Metrics()
    servicer = _servicer(tmp_path, metrics)
    captured = []

    class FakeChannel:
        async def __aenter__(self):
            return self

        async def __aexit__(self, *exc):
            return False

    class FakeStub:
        def __init__(self, channel):
            pass

        async def FetchFile(self, request, timeout=None, metadata=None):
            captured.append(timeout)
            return types.SimpleNamespace(found=False, content=b"")

    monkeypatch.setattr(
        service_mod.grpc.aio, "insecure_channel",
        lambda *a, **k: FakeChannel(),
    )
    monkeypatch.setattr(
        service_mod.rpc, "FileTransferServiceStub", FakeStub
    )
    content = asyncio.run(
        servicer._blob("materials/x.pdf", deadline=Deadline.after(0.8))
    )
    assert content == b""
    assert captured, "with budget in hand the sweep should try peers"
    assert all(0.0 < t <= 0.8 for t in captured), captured
    # Unlimited-budget callers still get the configured cap.
    captured.clear()
    servicer._blob_missing.clear()
    asyncio.run(servicer._blob("materials/x.pdf", deadline=None))
    assert captured and all(t == 5.0 for t in captured), captured


def test_replicate_expired_budget_fails_fast(tmp_path):
    """An exhausted replication budget skips every remaining peer
    immediately instead of spending timeout=30 each (the :741 finding)."""
    blobs = BlobStore(str(tmp_path / "blobs"))
    blobs.put("materials/a.pdf", b"x" * 1024)
    metrics = Metrics()
    t0 = time.monotonic()
    results = asyncio.run(replicate_file_to_peers(
        {1: "127.0.0.1:1", 2: "127.0.0.1:2"}, 0, blobs, "materials/a.pdf",
        per_peer_timeout_s=30.0,
        deadline=Deadline.after(0.0),
        metrics=metrics,
    ))
    assert time.monotonic() - t0 < 1.0, "must not wait out per-peer caps"
    assert results == {
        1: "skipped: replication budget exhausted",
        2: "skipped: replication budget exhausted",
    }
    assert metrics.snapshot()["counters"]["replicate_budget_exhausted"] == 2


def test_replicate_live_budget_caps_per_peer_timeout(tmp_path):
    """Alive-but-small budget: attempts happen, each capped by the
    remaining budget (unroutable peers fail fast with UNAVAILABLE)."""
    blobs = BlobStore(str(tmp_path / "blobs"))
    blobs.put("materials/a.pdf", b"y")
    t0 = time.monotonic()
    results = asyncio.run(replicate_file_to_peers(
        {1: "127.0.0.1:1"}, 0, blobs, "materials/a.pdf",
        per_peer_timeout_s=30.0,
        deadline=Deadline.after(1.5),
    ))
    # Whatever the failure mode (refused fast or deadline), the sweep is
    # bounded by the budget, not the 30 s cap.
    assert time.monotonic() - t0 < 10.0
    assert list(results) == [1]
    assert results[1].startswith("error:") or "skipped" in results[1]


def test_missing_blob_returns_empty_without_deadline(tmp_path):
    """Source-missing blobs short-circuit before any peer logic."""
    blobs = BlobStore(str(tmp_path / "blobs"))
    results = asyncio.run(replicate_file_to_peers(
        {1: "127.0.0.1:1"}, 0, blobs, "materials/none.pdf",
        deadline=Deadline.after(0.0),
    ))
    assert results == {}


@pytest.mark.parametrize("budget_s,cap,expect_floor", [
    (0.1, 5.0, True),    # under the 0.25 floor: degrade, don't dial
    (3.0, 5.0, False),   # healthy: dial with ~3 s
    # A cap tighter than the floor shortens attempts but must NOT
    # disable the sweep while real budget remains (the floor compares
    # against the remaining budget, not the cap-limited timeout).
    (100.0, 0.2, False),
])
def test_blob_fetch_floor_behavior(tmp_path, monkeypatch, budget_s, cap,
                                   expect_floor):
    metrics = Metrics()
    servicer = _servicer(tmp_path, metrics, blob_fetch_timeout_s=cap)
    dialed = []

    class FakeChannel:
        async def __aenter__(self):
            return self

        async def __aexit__(self, *exc):
            return False

    class FakeStub:
        def __init__(self, channel):
            pass

        async def FetchFile(self, request, timeout=None, metadata=None):
            dialed.append(timeout)
            return types.SimpleNamespace(found=False, content=b"")

    monkeypatch.setattr(
        service_mod.grpc.aio, "insecure_channel",
        lambda *a, **k: FakeChannel(),
    )
    monkeypatch.setattr(service_mod.rpc, "FileTransferServiceStub", FakeStub)
    asyncio.run(
        servicer._blob("materials/x.pdf", deadline=Deadline.after(budget_s))
    )
    counters = metrics.snapshot()["counters"]
    if expect_floor:
        assert not dialed
        assert counters.get("blob_fetch_budget_exhausted") == 1
    else:
        assert dialed
        assert all(t <= cap for t in dialed), dialed
        assert "blob_fetch_budget_exhausted" not in counters
