"""Sharded training step on the 8-device mesh + graft entry points."""

import numpy as np

import jax
import jax.numpy as jnp

from distributed_lms_raft_llm_tpu.models import gpt2
from distributed_lms_raft_llm_tpu.parallel import make_mesh
from distributed_lms_raft_llm_tpu.train import (
    TrainConfig,
    make_sharded_train_step,
)

TINY = gpt2.GPT2Config(
    vocab_size=256,
    max_position_embeddings=32,
    hidden_size=64,
    num_layers=2,
    num_heads=4,
    dtype=jnp.float32,
)


def test_sharded_train_step_loss_decreases():
    mesh = make_mesh({"tp": 2, "dp": -1})
    assert mesh.shape["dp"] == 4 and mesh.shape["tp"] == 2
    step, state, batch_shardings = make_sharded_train_step(
        mesh, TINY, TrainConfig(learning_rate=1e-2, warmup_steps=1, remat=True),
        jax.random.key(0),
    )
    rng = np.random.default_rng(0)
    # A tiny repetitive corpus the model can memorize in a few steps.
    seq = np.tile(np.arange(16, dtype=np.int32), (8, 2))
    batch = {
        "input_ids": jax.device_put(seq, batch_shardings["input_ids"]),
        "loss_mask": jax.device_put(
            np.ones_like(seq, np.float32), batch_shardings["loss_mask"]
        ),
    }
    losses = []
    with mesh:
        for _ in range(8):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.8, losses
    assert float(metrics["grad_norm"]) > 0


def test_remat_matches_no_remat():
    mesh = make_mesh({"tp": 1, "dp": -1})
    rng = jax.random.key(1)
    results = []
    for remat in (False, True):
        step, state, shardings = make_sharded_train_step(
            mesh, TINY, TrainConfig(warmup_steps=1, remat=remat), rng
        )
        seq = np.tile(np.arange(8, dtype=np.int32), (8, 1))
        batch = {
            "input_ids": jax.device_put(seq, shardings["input_ids"]),
            "loss_mask": jax.device_put(
                np.ones_like(seq, np.float32), shardings["loss_mask"]
            ),
        }
        with mesh:
            _, metrics = step(state, batch)
        results.append(float(metrics["loss"]))
    assert abs(results[0] - results[1]) < 1e-5


def test_graft_entry_dryrun():
    import __graft_entry__ as graft

    graft.dryrun_multichip(8)


def test_graft_entry_forward():
    import __graft_entry__ as graft

    fn, (params, ids) = graft.entry()
    logits = jax.jit(fn)(params, ids)
    assert logits.shape == (1, 32, 50257)
