"""Sharded training step on the 8-device mesh + graft entry points."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_lms_raft_llm_tpu.models import gpt2
from distributed_lms_raft_llm_tpu.parallel import make_mesh
from distributed_lms_raft_llm_tpu.train import (
    TrainConfig,
    make_sharded_train_step,
)

TINY = gpt2.GPT2Config(
    vocab_size=256,
    max_position_embeddings=32,
    hidden_size=64,
    num_layers=2,
    num_heads=4,
    dtype=jnp.float32,
)


def test_sharded_train_step_loss_decreases():
    mesh = make_mesh({"tp": 2, "dp": -1})
    assert mesh.shape["dp"] == 4 and mesh.shape["tp"] == 2
    step, state, batch_shardings = make_sharded_train_step(
        mesh, TINY, TrainConfig(learning_rate=1e-2, warmup_steps=1, remat=True),
        jax.random.key(0),
    )
    rng = np.random.default_rng(0)
    # A tiny repetitive corpus the model can memorize in a few steps.
    seq = np.tile(np.arange(16, dtype=np.int32), (8, 2))
    batch = {
        "input_ids": jax.device_put(seq, batch_shardings["input_ids"]),
        "loss_mask": jax.device_put(
            np.ones_like(seq, np.float32), batch_shardings["loss_mask"]
        ),
    }
    losses = []
    with mesh:
        for _ in range(8):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0] * 0.8, losses
    assert float(metrics["grad_norm"]) > 0


def test_remat_matches_no_remat():
    mesh = make_mesh({"tp": 1, "dp": -1})
    rng = jax.random.key(1)
    results = []
    for remat in (False, True):
        step, state, shardings = make_sharded_train_step(
            mesh, TINY, TrainConfig(warmup_steps=1, remat=remat), rng
        )
        seq = np.tile(np.arange(8, dtype=np.int32), (8, 1))
        batch = {
            "input_ids": jax.device_put(seq, shardings["input_ids"]),
            "loss_mask": jax.device_put(
                np.ones_like(seq, np.float32), shardings["loss_mask"]
            ),
        }
        with mesh:
            _, metrics = step(state, batch)
        results.append(float(metrics["loss"]))
    assert abs(results[0] - results[1]) < 1e-5


def test_graft_entry_dryrun():
    import __graft_entry__ as graft

    graft.dryrun_multichip(8)


def test_graft_entry_forward():
    import __graft_entry__ as graft

    fn, (params, ids) = graft.entry()
    logits = jax.jit(fn)(params, ids)
    assert logits.shape == (1, 32, 50257)


# ------------------------------------------------- data pipeline + resume


def test_pack_and_batches_deterministic(tmp_path):
    from distributed_lms_raft_llm_tpu.train.data import (
        DataConfig, PackedDataset, load_corpus_texts, pack_tokens,
    )
    from distributed_lms_raft_llm_tpu.utils import pdf as pdf_lib

    (tmp_path / "notes.txt").write_text("raft elects a leader by majority " * 40)
    (tmp_path / "slides.pdf").write_bytes(
        pdf_lib.make_pdf("consensus requires a quorum of acceptors")
    )
    (tmp_path / "ignore.bin").write_bytes(b"\x00\x01")
    texts = load_corpus_texts([str(tmp_path)])
    assert len(texts) == 2
    assert any("quorum" in t for t in texts)

    class ByteTok:
        eos_id = 0

        def encode(self, text):
            return [b % 251 + 1 for b in text.encode()]

    blocks = pack_tokens(texts, ByteTok(), seq_len=32)
    assert blocks.shape[1] == 32 and blocks.dtype == np.int32

    ds = PackedDataset(blocks, DataConfig(batch_size=2, seq_len=32, seed=3))
    a = [b["input_ids"].copy() for b in ds.batches(epoch=0)]
    b = [b["input_ids"].copy() for b in ds.batches(epoch=0)]
    c = [b["input_ids"].copy() for b in ds.batches(epoch=1)]
    assert all(np.array_equal(x, y) for x, y in zip(a, b))  # same epoch = same
    assert any(not np.array_equal(x, y) for x, y in zip(a, c))  # epochs differ


def _tiny_dataset():
    from distributed_lms_raft_llm_tpu.train.data import DataConfig, PackedDataset

    rng = np.random.default_rng(0)
    blocks = rng.integers(1, 250, (16, 16)).astype(np.int32)
    return PackedDataset(blocks, DataConfig(batch_size=8, seq_len=16, seed=1))


@pytest.mark.slow
def test_checkpoint_roundtrip_and_resume_bitexact(tmp_path):
    """Interrupted-and-resumed training walks the same step sequence as an
    uninterrupted run: final params match bit-for-bit.

    Marked slow: the three back-to-back fit() compilations are the
    heaviest single test in the suite, and in this image's jax build the
    test aborts the interpreter (SIGABRT inside XLA, non-deterministic
    crash point) when it runs at the tail of the full in-process tier-1
    session — while passing standalone and in the slow lane every time.
    """
    from distributed_lms_raft_llm_tpu.train import train as train_lib

    mesh = make_mesh({"tp": 1, "dp": -1})
    ds = _tiny_dataset()
    cfg = train_lib.TrainConfig(learning_rate=1e-3, warmup_steps=1,
                                decay_steps=8, remat=False)

    # Run A: 2 epochs straight through.
    a = train_lib.fit(mesh, TINY, cfg, ds, epochs=2, seed=5,
                      checkpoint_path=None)
    assert a["step"] == 2 * ds.steps_per_epoch()

    # Run B: 1 epoch, checkpoint, then a FRESH fit resumes to 2 epochs.
    ck = str(tmp_path / "state.safetensors")
    b1 = train_lib.fit(mesh, TINY, cfg, ds, epochs=1, seed=5,
                       checkpoint_path=ck)
    assert b1["step"] == ds.steps_per_epoch()
    from distributed_lms_raft_llm_tpu.train import checkpoint as ck_lib

    assert ck_lib.latest_step(ck) == ds.steps_per_epoch()
    b2 = train_lib.fit(mesh, TINY, cfg, ds, epochs=2, seed=5,
                       checkpoint_path=ck)
    assert b2["step"] == a["step"]

    pa = jax.device_get(a["state"]["params"])
    pb = jax.device_get(b2["state"]["params"])
    flat_a = jax.tree_util.tree_leaves(pa)
    flat_b = jax.tree_util.tree_leaves(pb)
    assert all(np.array_equal(x, y) for x, y in zip(flat_a, flat_b))


def test_export_model_serves_through_standard_checkpoint_path(tmp_path):
    """export_model writes HF layout; the conversion round-trips exactly."""
    from distributed_lms_raft_llm_tpu.models import convert
    from distributed_lms_raft_llm_tpu.train import checkpoint as ck_lib
    from distributed_lms_raft_llm_tpu.train import train as train_lib

    mesh = make_mesh({"tp": 1, "dp": -1})
    ds = _tiny_dataset()
    cfg = train_lib.TrainConfig(warmup_steps=1, decay_steps=4, remat=False)
    result = train_lib.fit(mesh, TINY, cfg, ds, epochs=1)

    path = str(tmp_path / "model.safetensors")
    ck_lib.export_model(path, result["state"])
    sd = convert.load_safetensors(path)
    cfg32 = gpt2.GPT2Config(
        vocab_size=256, max_position_embeddings=32, hidden_size=64,
        num_layers=2, num_heads=4, dtype=jnp.float32, param_dtype=jnp.float32,
    )
    reloaded = convert.gpt2_params_from_hf(sd, cfg32)
    orig = jax.device_get(result["state"]["params"])
    ids = np.arange(12, dtype=np.int32)[None, :]
    ref_logits, _ = gpt2.forward(orig, cfg32, jnp.asarray(ids))
    new_logits, _ = gpt2.forward(reloaded, cfg32, jnp.asarray(ids))
    np.testing.assert_allclose(
        np.asarray(ref_logits), np.asarray(new_logits), rtol=1e-5, atol=1e-5
    )
