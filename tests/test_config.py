"""Declarative deployment config (config.py + configs/cluster.toml).

SURVEY §5: the reference configures by editing source (hardcoded address
maps, sampling constants, gate threshold). One TOML must drive every
entrypoint; these tests parse the shipped example, check strictness, check
both servers' CLI config phases, and boot a real single-node cluster +
tutoring node from one generated file.
"""

import argparse
import asyncio
import os
import socket
import textwrap
from unittest import mock

import pytest

from distributed_lms_raft_llm_tpu import config as cfg_lib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLE = os.path.join(REPO, "configs", "cluster.toml")


class _Stop(Exception):
    pass


def _capture_args(module, argv):
    """Run a server module's main() through its argparse+config phase only,
    returning the fully-resolved namespace (engine/event-loop construction
    is cut off)."""
    captured = {}
    real_parse = argparse.ArgumentParser.parse_args

    def capture(self, argv_=None):
        ns = real_parse(self, argv_)
        captured["ns"] = ns
        return ns

    def stop(*a, **kw):
        raise _Stop

    patches = [mock.patch.object(argparse.ArgumentParser, "parse_args",
                                 capture)]
    for name in ("TutoringEngine", "PagedEngine"):
        if hasattr(module, name):
            patches.append(mock.patch.object(module, name, side_effect=stop))

    def fake_run(coro):
        coro.close()
        raise _Stop

    patches.append(mock.patch.object(module.asyncio, "run", fake_run))
    for p in patches:
        p.start()
    try:
        module.main(argv)
    except _Stop:
        pass
    finally:
        for p in patches:
            p.stop()
    return captured["ns"]


def test_example_config_parses_to_reference_topology():
    cfg = cfg_lib.load_config(EXAMPLE)
    assert len(cfg.cluster.nodes) == 5                    # 5 LMS servers
    assert cfg.client_servers[0] == "127.0.0.1:50051"
    assert cfg.tutoring.port == 50054                     # reference port
    assert cfg.sampling.temperature == 0.7                # reference sampling
    assert cfg.sampling.top_k == 50
    assert cfg.sampling.repetition_penalty == 1.2
    assert cfg.gate.threshold == 0.6                      # reference gate
    assert cfg.cluster.linearizable_reads is True
    assert cfg.resilience.queue_depth == 64               # bounded admission
    assert cfg.resilience.breaker_failure_threshold == 5


def test_resilience_section_and_client_kwargs(tmp_path):
    f = tmp_path / "r.toml"
    f.write_text(
        "[resilience]\n"
        "llm_timeout_s = 15.0\n"
        "queue_depth = 4\n"
        "breaker_recovery_s = 1.5\n"
        "backoff_max_s = 0.5\n"
    )
    cfg = cfg_lib.load_config(str(f))
    assert cfg.resilience.llm_timeout_s == 15.0
    assert cfg.resilience.queue_depth == 4
    assert cfg.resilience.breaker_recovery_s == 1.5
    kw = cfg_lib.client_kwargs(cfg)
    assert kw["llm_timeout_s"] == 15.0 and kw["backoff_max_s"] == 0.5
    # Unset knobs keep their defaults.
    assert cfg.resilience.deadline_floor_s == 0.25


def test_unknown_keys_rejected(tmp_path):
    bad = tmp_path / "bad.toml"
    bad.write_text("[tutoring]\nmodle = 'gpt2'\n")
    with pytest.raises(ValueError, match="modle"):
        cfg_lib.load_config(str(bad))
    bad.write_text("[tutorng]\nmodel = 'gpt2'\n")
    with pytest.raises(ValueError, match="tutorng"):
        cfg_lib.load_config(str(bad))
    bad.write_text("[resilience]\nqueue_dpeth = 4\n")
    with pytest.raises(ValueError, match="queue_dpeth"):
        cfg_lib.load_config(str(bad))


def test_engine_and_raft_adapters(tmp_path):
    f = tmp_path / "c.toml"
    f.write_text(textwrap.dedent("""
        [cluster]
        election_timeout = 0.3
        heartbeat_interval = 0.05
        [cluster.nodes]
        1 = "127.0.0.1:7001"
        [tutoring]
        model = "tiny"
        quant = "int8"
        kv_quant = true
        [sampling]
        max_new_tokens = 16
        temperature = 0.9
    """))
    cfg = cfg_lib.load_config(str(f))
    ec = cfg_lib.engine_config(cfg)
    assert ec.model == "tiny" and ec.quant == "int8" and ec.kv_quant
    assert ec.sampling.max_new_tokens == 16
    assert ec.sampling.temperature == 0.9
    rc = cfg_lib.raft_config(cfg)
    assert rc.election_timeout_max == 0.3
    assert rc.election_timeout_min == 0.15


def _free_port():
    with socket.socket() as s:
        s.bind(("127.0.0.1", 0))
        return s.getsockname()[1]


def _write_deploy_toml(tmp_path, lms_port, tut_port):
    f = tmp_path / "deploy.toml"
    f.write_text(textwrap.dedent(f"""
        [cluster]
        data_dir = "{tmp_path}/lms"
        election_timeout = 0.3
        heartbeat_interval = 0.05
        [cluster.nodes]
        1 = "127.0.0.1:{lms_port}"
        [tutoring]
        address = "127.0.0.1:{tut_port}"
        model = "tiny"
        kv_quant = true
        paged = true
        [sampling]
        max_new_tokens = 8
    """))
    return f


def test_server_cli_config_phases(tmp_path):
    """Both servers resolve their settings from the file; explicit flags win."""
    from distributed_lms_raft_llm_tpu.serving import lms_server, tutoring_server

    lms_port, tut_port = _free_port(), _free_port()
    f = _write_deploy_toml(tmp_path, lms_port, tut_port)

    targs = _capture_args(tutoring_server, ["--config", str(f)])
    assert targs.port == tut_port
    assert targs.model == "tiny"
    assert targs.kv_quant and targs.paged
    assert targs.max_new_tokens == 8

    # Explicit flag beats the file.
    targs2 = _capture_args(
        tutoring_server, ["--config", str(f), "--max-new-tokens", "4"]
    )
    assert targs2.max_new_tokens == 4

    largs = _capture_args(lms_server, ["--config", str(f), "--id", "1"])
    assert largs.id == 1
    assert largs.port == lms_port
    assert largs.peers == [f"127.0.0.1:{lms_port}"]
    assert largs.tutoring == f"127.0.0.1:{tut_port}"
    assert largs.data_dir == f"{tmp_path}/lms/node1"
    assert largs.election_timeout == 0.3
    assert largs.linearizable_reads is True


def test_cluster_and_tutoring_boot_from_one_file(tmp_path):
    """The done-criterion: LMS node + tutoring node + client all launch from
    one TOML and serve a real register/login."""
    from distributed_lms_raft_llm_tpu.client.client import LMSClient
    from distributed_lms_raft_llm_tpu.engine import PagedEngine
    from distributed_lms_raft_llm_tpu.serving import lms_server, tutoring_server

    lms_port, tut_port = _free_port(), _free_port()
    f = _write_deploy_toml(tmp_path, lms_port, tut_port)
    largs = _capture_args(lms_server, ["--config", str(f), "--id", "1"])

    async def boot():
        cfg = cfg_lib.load_config(str(f))
        engine = PagedEngine(cfg_lib.engine_config(cfg),
                             slots=cfg.tutoring.max_batch)
        tut = await tutoring_server.serve_async(cfg.tutoring.port, engine)
        lms_task = asyncio.get_running_loop().create_task(
            lms_server.serve_async(largs)
        )
        try:
            client = LMSClient(cfg.client_servers, discovery_rounds=30,
                               discovery_backoff_s=0.2)
            loop = asyncio.get_running_loop()
            leader = await loop.run_in_executor(None, client.discover_leader)
            assert leader == f"127.0.0.1:{lms_port}"
            resp = await loop.run_in_executor(
                None, lambda: client.register("cfguser", "pw", "student")
            )
            assert resp.success
            ok = await loop.run_in_executor(
                None, lambda: client.login("cfguser", "pw")
            )
            assert ok
            client.close()
        finally:
            lms_task.cancel()
            try:
                await lms_task
            except (asyncio.CancelledError, Exception):
                pass
            await tut.stop(None)

    asyncio.run(boot())
