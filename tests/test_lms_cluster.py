"""Full-system integration: 3-node LMS cluster + TPU tutoring node + gate,
driven through the sync client library over real gRPC — the end-to-end
journey the reference validated manually (SURVEY.md §4)."""

import asyncio
import threading

import pytest

import jax

from distributed_lms_raft_llm_tpu.client import LMSClient
from distributed_lms_raft_llm_tpu.engine import (
    BatchingQueue,
    EngineConfig,
    GateConfig,
    RelevanceGate,
    SamplingParams,
    TutoringEngine,
)
from distributed_lms_raft_llm_tpu.lms.node import LMSNode
from distributed_lms_raft_llm_tpu.lms.service import (
    FileTransferServicer,
    LMSServicer,
)
from distributed_lms_raft_llm_tpu.proto import rpc
from distributed_lms_raft_llm_tpu.raft import RaftConfig
from distributed_lms_raft_llm_tpu.raft.grpc_transport import RaftServicer
from distributed_lms_raft_llm_tpu.serving import tutoring_server as ts
from distributed_lms_raft_llm_tpu.utils import pdf
from distributed_lms_raft_llm_tpu.utils.metrics import Metrics

import grpc

FAST = RaftConfig(
    election_timeout_min=0.11, election_timeout_max=0.22, heartbeat_interval=0.05
)


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    """3 LMS nodes + tutoring server on a private event-loop thread."""
    tmp = tmp_path_factory.mktemp("cluster")
    loop = asyncio.new_event_loop()
    started = threading.Event()
    state = {}

    def run():
        asyncio.set_event_loop(loop)

        async def boot():
            # Tutoring node (tiny model).
            engine = TutoringEngine(
                EngineConfig(
                    model="tiny",
                    sampling=SamplingParams(max_new_tokens=6),
                    length_buckets=(32,),
                    batch_buckets=(1, 2, 4),
                    dtype=jax.numpy.float32,
                )
            )
            queue = BatchingQueue(engine, max_batch=4, max_wait_ms=10)
            await queue.start()
            tut_server = grpc.aio.server()
            rpc.add_TutoringServicer_to_server(
                ts.TutoringService(queue, Metrics()), tut_server
            )
            tut_port = tut_server.add_insecure_port("127.0.0.1:0")
            await tut_server.start()

            gate = RelevanceGate(
                GateConfig(model="tiny", dtype=jax.numpy.float32, threshold=0.0)
            )

            ids = [1, 2, 3]
            servers, addresses = {}, {}
            for i in ids:
                servers[i] = grpc.aio.server(
                    options=[("grpc.max_receive_message_length", 50 * 1024 * 1024)]
                )
                port = servers[i].add_insecure_port("127.0.0.1:0")
                addresses[i] = f"127.0.0.1:{port}"
            lms_nodes = {}
            for i in ids:
                node = LMSNode(
                    i, addresses, str(tmp / f"node{i}"), raft_config=FAST
                )
                servicer = LMSServicer(
                    node.node, node.state, node.blobs, gate=gate,
                    tutoring_address=f"127.0.0.1:{tut_port}",
                )
                rpc.add_LMSServicer_to_server(servicer, servers[i])
                rpc.add_RaftServiceServicer_to_server(
                    RaftServicer(node.node, addresses,
                                 kv=node.state.data["kv"]),
                    servers[i],
                )
                rpc.add_FileTransferServiceServicer_to_server(
                    FileTransferServicer(node.blobs), servers[i]
                )
                await servers[i].start()
                await node.start()
                lms_nodes[i] = node
            state.update(
                servers=servers, nodes=lms_nodes, addresses=addresses,
                tut_server=tut_server, queue=queue, tmp=tmp, loop=loop,
            )
            started.set()

        loop.run_until_complete(boot())
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert started.wait(60)
    yield state

    async def teardown():
        for node in state["nodes"].values():
            if not node.node._stopped:
                await node.stop()
        for s in state["servers"].values():
            await s.stop(None)
        await state["queue"].close()
        await state["tut_server"].stop(None)

    asyncio.run_coroutine_threadsafe(teardown(), loop).result(30)
    loop.call_soon_threadsafe(loop.stop)


@pytest.fixture(scope="module")
def client(cluster):
    c = LMSClient(list(cluster["addresses"].values()),
                  discovery_backoff_s=0.2)
    yield c
    c.close()


def test_full_student_instructor_journey(client):
    # -- registration / login ------------------------------------------------
    assert client.register("ana", "pw1", "student").success
    assert client.register("prof", "pw2", "instructor").success
    assert not client.register("ana", "zzz", "student").success  # duplicate
    assert client.login("prof", "pw2") and client.role == "instructor"

    # -- instructor posts course material ------------------------------------
    material = pdf.make_pdf("Lecture 4: B-trees, LSM trees, and storage engines")
    assert client.upload_course_material("lecture4.pdf", material)
    client.logout()

    # -- student journey -----------------------------------------------------
    assert client.login("ana", "pw1") and client.role == "student"
    mats = client.course_materials()
    assert [m.filename for m in mats] == ["lecture4.pdf"]
    assert mats[0].file == material  # bytes round-trip through the blob store

    hw = pdf.make_pdf("Homework: implement a B-tree with insert and split")
    assert client.upload_assignment("hw1.pdf", hw)
    assert "No grade" in client.my_grade()

    # LLM path: gate (threshold 0 in fixture) + tutoring node
    resp = client.ask_llm("How does a B-tree split work?")
    assert resp.success

    assert client.ask_instructor("When is hw1 due?")
    client.logout()

    # -- instructor grades + responds ----------------------------------------
    assert client.login("prof", "pw2")
    subs = client.student_assignments()
    assert [(e.id, e.filename) for e in subs] == [("ana", "hw1.pdf")]
    assert subs[0].file == hw
    assert client.grade("ana", "A").success
    queries = client.unanswered_queries()
    assert [(q.id, q.data) for q in queries] == [("ana", "When is hw1 due?")]
    assert client.respond_to_query("ana", "Friday midnight.")
    client.logout()

    # -- student sees results ------------------------------------------------
    assert client.login("ana", "pw1")
    assert client.my_grade() == "Your grade: A"
    responses = client.instructor_responses()
    assert len(responses) == 1
    assert "Friday midnight." in responses[0].data
    client.logout()


def test_unauthorized_paths(client):
    assert client.login("ana", "pw1")
    # Student cannot grade or list assignments.
    assert not client.grade("ana", "F").success
    assert client.student_assignments() == []
    client.logout()
    # Bogus token fails cleanly.
    client.token = "forged-token"
    assert client.my_grade() in ("Invalid session",)
    client.token = None


def test_state_replicated_to_all_nodes(cluster, client):
    """After the journey, every node's state machine has converged."""
    import time

    time.sleep(0.5)  # let followers apply the tail
    datas = [n.state.data for n in cluster["nodes"].values()]
    for d in datas:
        assert set(d["users"]) == {"ana", "prof"}
        assert [a["grade"] for a in d["assignments"]["ana"]] == ["A"]
        assert d["queries"]["ana"][0]["answered"]


def test_uploaded_files_replicated_to_followers(cluster, client):
    import time

    time.sleep(0.5)
    present = [
        n.blobs.exists("materials/lecture4.pdf")
        and n.blobs.exists("assignments/ana/hw1.pdf")
        for n in cluster["nodes"].values()
    ]
    assert all(present), present


def test_replica_state_digests_converge(cluster, client):
    """PR 18: every replica folds LMSState.digest() into a per-applied-
    index digest chain; at quiescence all three replicas of the group
    must sit at the same applied index with the SAME digest — the
    runtime half of the state-machine-determinism rule.

    (Runs before the failover test below, which stops the leader.)"""
    import time

    deadline = time.monotonic() + 10.0
    nodes = list(cluster["nodes"].values())
    while time.monotonic() < deadline:
        applied = {n._last_applied_index for n in nodes}
        digests = {n.state_digest for n in nodes}
        if len(applied) == 1 and len(digests) == 1:
            break
        time.sleep(0.1)
    assert len(applied) == 1, f"applied indexes diverged: {applied}"
    assert len(digests) == 1, (
        "replicas diverged at the same applied index — "
        f"nondeterministic apply: {digests}"
    )
    (digest,) = digests
    assert len(digest) == 16 and int(digest, 16) >= 0
    # The chain is a pure fold of (index, state): recomputing on each
    # node reproduces the live value, and raw state digests agree too.
    for n in nodes:
        assert n._fold_digest(n._last_applied_index) == digest
    assert len({n.state.digest() for n in nodes}) == 1


def test_digest_chain_survives_restart_and_snapshot_install(tmp_path):
    """PR 18: the digest is a pure function of (applied index, state) —
    NOT an in-memory running hash — so a node restarted from its own
    WAL+snapshot, and a wiped node rejoining via InstallSnapshot, both
    land back on the exact chain value their peers report."""
    from distributed_lms_raft_llm_tpu.lms.node import LMSNode as _LMSNode
    from distributed_lms_raft_llm_tpu.raft.messages import encode_command

    async def run():
        ids = [1, 2, 3]
        servers, addresses, ports = {}, {}, {}
        for i in ids:
            servers[i] = grpc.aio.server()
            ports[i] = servers[i].add_insecure_port("127.0.0.1:0")
            addresses[i] = f"127.0.0.1:{ports[i]}"
        nodes = {}

        async def boot(i, dirname):
            node = _LMSNode(i, addresses, str(tmp_path / dirname),
                            raft_config=FAST, snapshot_every=5)
            rpc.add_RaftServiceServicer_to_server(
                RaftServicer(node.node, addresses), servers[i]
            )
            await servers[i].start()
            await node.start()
            nodes[i] = node

        async def reboot_server(i):
            servers[i] = grpc.aio.server()
            bound = servers[i].add_insecure_port(f"127.0.0.1:{ports[i]}")
            assert bound == ports[i], "could not rebind node port"

        async def converged_digest(expect_members=3):
            """Wait for one (applied, digest) across all live nodes."""
            for _ in range(500):
                live = list(nodes.values())
                applied = {n._last_applied_index for n in live}
                digests = {n.state_digest for n in live}
                if (len(live) == expect_members and len(applied) == 1
                        and len(digests) == 1):
                    return applied.pop(), digests.pop()
                await asyncio.sleep(0.02)
            raise AssertionError(
                f"no digest convergence: applied={applied} digests={digests}"
            )

        for i in ids:
            await boot(i, f"node{i}")
        try:
            leader = None
            for _ in range(300):
                leaders = [n for n in nodes.values() if n.node.is_leader]
                if leaders:
                    leader = leaders[0]
                    break
                await asyncio.sleep(0.02)
            assert leader is not None

            async def register(k):
                await leader.node.propose(encode_command(
                    "Register",
                    {"username": f"user{k}", "password_hash": "h",
                     "salt": "", "role": "student"},
                ))

            # Past the snapshot cadence (5) so restarts replay from a
            # snapshot + WAL suffix, not a fresh log.
            for k in range(12):
                await register(k)
            applied0, digest0 = await converged_digest()

            # -- restart a follower from its own data dir ------------------
            victim = next(i for i in ids if not nodes[i].node.is_leader)
            await nodes[victim].stop()
            await servers[victim].stop(None)
            del nodes[victim]
            await reboot_server(victim)
            await boot(victim, f"node{victim}")  # SAME dir: snapshot+WAL
            applied1, digest1 = await converged_digest()
            assert applied1 == applied0 and digest1 == digest0, (
                "restart-from-snapshot left the digest chain"
            )

            # -- wipe a follower; rejoin via InstallSnapshot ---------------
            victim2 = next(
                i for i in ids
                if i != victim and not nodes[i].node.is_leader
            )
            await nodes[victim2].stop()
            await servers[victim2].stop(None)
            del nodes[victim2]
            for k in range(12, 15):  # commits while it is down
                await register(k)
            await reboot_server(victim2)
            await boot(victim2, f"node{victim2}-wiped")  # EMPTY dir
            applied2, digest2 = await converged_digest()
            assert applied2 > applied0
            assert digest2 != digest0  # state moved on; chain did too
            # The rejoiner really came through snapshot install.
            assert nodes[victim2].node.core.snapshot_index >= 5
            assert len(nodes[victim2].state.data["users"]) == 15
        finally:
            for n in nodes.values():
                await n.stop()
            for s in servers.values():
                await s.stop(None)

    asyncio.run(run())


def test_sessions_survive_failover(cluster, client):
    """The D7 fix: a login taken before leader failure works after it."""

    async def stop_leader():
        for node in cluster["nodes"].values():
            if node.node.is_leader:
                await node.stop()
                return node.node_id
        return None

    assert client.login("ana", "pw1")
    token_before = client.token
    # Stop the current leader from the cluster's own loop.
    fut = asyncio.run_coroutine_threadsafe(stop_leader(), cluster["loop"])
    stopped = fut.result(10)
    assert stopped is not None
    client.discover_leader(force=True)
    # Old token still valid on the new leader (sessions are replicated).
    assert client.my_grade() == "Your grade: A"
    assert client.token == token_before
