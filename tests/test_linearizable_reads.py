"""Linearizable reads: the read barrier refuses stale-leader reads.

The reference serves every read from whichever node the client reached
(reference: GUI_RAFT_LLM_SourceCode/lms_server.py:1063-1133) — after a
partition, a deposed leader happily answers from stale state. Here every
read RPC passes `RaftNode.read_barrier()` (a no-op commit fence) first:
the deposed leader cannot commit in its term, so the read fails over
instead of lying.
"""

import asyncio

import pytest

from distributed_lms_raft_llm_tpu.raft import (
    MemNetwork,
    MemoryStorage,
    NotLeader,
    RaftConfig,
    RaftNode,
    encode_command,
)

from test_raft_cluster import FAST, build_cluster, wait_for_leader


def test_read_barrier_resolves_on_healthy_leader():
    async def run():
        net = MemNetwork()
        applied = {}
        nodes, _ = build_cluster(net, 3, applied=applied)
        for n in nodes.values():
            await n.start()
        leader = await wait_for_leader(nodes)
        await leader.propose(encode_command("set", {"k": 1}))
        index = await asyncio.wait_for(leader.read_barrier(), 3.0)
        # The barrier point covers the write: the entry is applied locally
        # by the time the fence resolves.
        assert any(i <= index for i, _ in applied[leader.node_id])
        for n in nodes.values():
            await n.stop()

    asyncio.run(run())


def test_read_barrier_coalesces_concurrent_readers():
    async def run():
        net = MemNetwork()
        nodes, _ = build_cluster(net, 3)
        for n in nodes.values():
            await n.start()
        leader = await wait_for_leader(nodes)
        base = leader.core.last_log_index
        results = await asyncio.gather(
            *[leader.read_barrier() for _ in range(8)]
        )
        # One barrier no-op served the whole burst (one log entry, maybe
        # two if a tick raced in — never eight).
        assert leader.core.last_log_index - base <= 2
        assert all(r >= base for r in results)
        for n in nodes.values():
            await n.stop()

    asyncio.run(run())


def test_deposed_leader_refuses_reads_new_leader_serves():
    """The VERDICT done-criterion: partition the leader away, let the
    majority elect a successor and commit new writes; the old leader's
    read barrier must fail (no quorum / stepped down) while the new
    leader's resolves and covers the new writes."""

    async def run():
        net = MemNetwork()
        applied = {}
        nodes, _ = build_cluster(net, 3, applied=applied)
        for n in nodes.values():
            await n.start()
        old = await wait_for_leader(nodes)
        await old.propose(encode_command("set", {"k": "old"}))

        # Cut the leader off from the majority.
        minority = {old.node_id}
        majority = set(nodes) - minority
        net.partition(minority, majority)

        # Majority elects a successor and commits a write the old leader
        # never sees.
        new = await wait_for_leader(
            {i: nodes[i] for i in majority}, timeout=5.0
        )
        await new.propose(encode_command("set", {"k": "new"}))

        # Old leader: barrier cannot commit. Depending on timing it either
        # still thinks it leads (timeout: no quorum) or has stepped down
        # after its election timeout (NotLeader) — both REFUSE the read.
        with pytest.raises((NotLeader, TimeoutError)):
            await old.read_barrier(timeout=0.8)

        # New leader: barrier resolves, and its barrier point covers the
        # post-partition write (applied before the fence resolved).
        index = await asyncio.wait_for(new.read_barrier(), 3.0)
        cmds = [c for _, c in applied[new.node_id]]
        assert encode_command("set", {"k": "new"}) in cmds
        assert index >= max(i for i, _ in applied[new.node_id])

        # Heal: the old leader rejoins, steps down, and can serve again
        # through the new leader's replication.
        net.heal()
        await asyncio.sleep(0.6)
        assert not old.is_leader
        for n in nodes.values():
            await n.stop()

    asyncio.run(run())


def test_service_read_fence_refuses_on_follower():
    """Service-level: a GetGrade against a node whose barrier fails aborts
    with UNAVAILABLE (the client's retry path re-resolves the leader)."""
    import grpc

    from distributed_lms_raft_llm_tpu.lms.persistence import BlobStore
    from distributed_lms_raft_llm_tpu.lms.service import LMSServicer
    from distributed_lms_raft_llm_tpu.lms.state import LMSState

    class AbortCalled(Exception):
        pass

    class FakeContext:
        def __init__(self):
            self.code = None

        async def abort(self, code, details):
            self.code = code
            raise AbortCalled(details)

    async def run(tmp):
        net = MemNetwork()
        nodes, _ = build_cluster(net, 3)
        for n in nodes.values():
            await n.start()
        leader = await wait_for_leader(nodes)
        follower = next(
            n for n in nodes.values() if n.node_id != leader.node_id
        )
        svc = LMSServicer(
            follower, LMSState(), BlobStore(str(tmp / "blobs"))
        )

        class Req:
            token = "whatever"

        ctx = FakeContext()
        with pytest.raises(AbortCalled):
            await svc.GetGrade(Req(), ctx)
        assert ctx.code == grpc.StatusCode.UNAVAILABLE
        for n in nodes.values():
            await n.stop()

    import tempfile, pathlib
    with tempfile.TemporaryDirectory() as d:
        asyncio.run(run(pathlib.Path(d)))


def test_mutation_auth_miss_rechecked_behind_fence():
    """A committed-but-unapplied Login (the window right after a
    leadership transfer: the new leader serves before its own-term no-op
    commits) must not be answered success=False/'invalid session' — the
    auth miss is re-checked behind the read fence, which resolves only
    once prior committed entries have applied."""
    from distributed_lms_raft_llm_tpu.lms.persistence import BlobStore
    from distributed_lms_raft_llm_tpu.lms.service import LMSServicer
    from distributed_lms_raft_llm_tpu.lms.state import LMSState
    from distributed_lms_raft_llm_tpu.proto import lms_pb2

    state = LMSState()
    state.apply("Register", {"username": "ana", "password_hash": "x",
                             "role": "student"})

    class LaggedLeader:
        """The Login entry is in the (committed) log but applies only
        when the barrier resolves — exactly a fresh leader's state."""

        def __init__(self):
            self.barriers = 0
            self.proposed = []

        async def read_barrier(self, timeout: float = 10.0) -> int:
            self.barriers += 1
            state.apply("Login", {"username": "ana", "token": "tok"})
            return 1

        async def propose(self, command, timeout: float = 10.0) -> int:
            self.proposed.append(command)
            return 2

    class Ctx:
        async def abort(self, code, details):  # pragma: no cover - unused
            raise AssertionError(f"abort({code}): {details}")

    async def run(tmp):
        node = LaggedLeader()
        svc = LMSServicer(node, state, BlobStore(str(tmp / "blobs")))
        req = lms_pb2.PostRequest(token="tok", type="query", data="q?",
                                  request_id="r1")
        resp = await svc.Post(req, Ctx())
        assert resp.success, "apply-lagged session treated as invalid"
        assert node.barriers == 1, "auth miss must fence exactly once"
        assert node.proposed, "the query must still commit"
        # Fast path: a now-visible session pays no extra barrier.
        resp = await svc.Post(req, Ctx())
        assert resp.success and node.barriers == 1

    import pathlib
    import tempfile

    with tempfile.TemporaryDirectory() as d:
        asyncio.run(run(pathlib.Path(d)))
