"""Flight-recorder tracing through the REAL 3-node sim cluster.

The PR's acceptance scenario: one GetLLMAnswer's span tree — fetched over
the live admin plane, not from internal handles — must show the whole
journey (client ask, LMS handler, Raft commit, relevance gate, tutoring
forward, batcher queue wait, engine program) with durations that nest
inside the measured end-to-end latency; the degraded path must keep
trace continuity down to the instructor-queue write under one request
id; and `scripts/trace_report.py` must render both from `/admin/trace`.
"""

import sys
import time
from pathlib import Path

import pytest

from distributed_lms_raft_llm_tpu.client import LMSClient
from distributed_lms_raft_llm_tpu.config import SimConfig
from distributed_lms_raft_llm_tpu.sim.cluster import SimCluster
from distributed_lms_raft_llm_tpu.sim.workload import ASSIGNMENT_TEXT
from distributed_lms_raft_llm_tpu.utils import pdf
from distributed_lms_raft_llm_tpu.utils.tracing import get_tracer

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "scripts"))

import trace_report  # noqa: E402  (scripts/ CLI under test)


@pytest.fixture(scope="module")
def cluster(tmp_path_factory):
    get_tracer().reset()
    c = SimCluster(str(tmp_path_factory.mktemp("trace-e2e")), SimConfig())
    c.start()
    try:
        assert c.wait_leader(timeout=20.0) is not None
        yield c
    finally:
        c.stop()


@pytest.fixture(scope="module")
def student(cluster):
    client = LMSClient(
        cluster.client_servers(),
        discovery_rounds=8, discovery_backoff_s=0.2,
        rpc_retries=6, rpc_timeout=5.0,
        request_timeout_s=20.0, llm_timeout_s=15.0,
        backoff_base_s=0.02, backoff_max_s=0.3, seed=11,
    )
    try:
        assert client.register("tracee", "pw", "student") is not None
        assert client.login("tracee", "pw")
        # ask_llm needs a submitted assignment (the gate scores the query
        # against its text).
        assert client.upload_assignment(
            "tracee_hw.pdf", pdf.make_pdf(ASSIGNMENT_TEXT)
        )
        yield client
    finally:
        client.close()


def _flatten(span, depth=0, out=None):
    out = out if out is not None else []
    out.append((depth, span))
    for child in span.get("children", ()):
        _flatten(child, depth + 1, out)
    return out


def _spans_by_name(tree):
    rows = []
    for root in tree["spans"]:
        rows.extend(_flatten(root))
    by_name = {}
    for _, span in rows:
        by_name.setdefault(span["name"], []).append(span)
    return by_name


def _assert_nesting(span, skew_s=0.05):
    """Every child's interval sits inside its parent's (small skew
    allowance: remote fragments align by wall clock, and the engine's
    timed children are measured on another thread)."""
    t0, d = span["start_s"], span["duration_s"]
    for child in span.get("children", ()):
        assert child["start_s"] >= t0 - skew_s, (span["name"],
                                                 child["name"])
        assert (child["start_s"] + child["duration_s"]
                <= t0 + d + skew_s), (span["name"], child["name"])
        _assert_nesting(child, skew_s)


@pytest.fixture(scope="module")
def traced_ask(cluster, student):
    """One successful on-topic ask under a known request id, plus its
    measured end-to-end latency and its span tree fetched over HTTP."""
    rid = "trace-e2e-ask-1"
    t0 = time.monotonic()
    resp = student.ask_llm(
        "Explain Raft leader election and log replication.",
        budget_s=15.0, request_id=rid,
    )
    wall_s = time.monotonic() - t0
    assert resp.success and "Echo tutor" in resp.response
    doc = cluster.admin_get(cluster.node_ids()[0], f"/admin/trace/{rid}")
    assert doc["ok"]
    return rid, doc["trace"], wall_s


def test_ask_span_tree_covers_the_full_path(traced_ask):
    """THE acceptance criterion: client -> handler -> raft commit ->
    gate -> tutoring forward -> queue wait -> engine program, one tree,
    one request id."""
    rid, tree, _ = traced_ask
    assert tree["trace_id"] == rid
    by_name = _spans_by_name(tree)
    for required in (
        "client.ask_llm",          # the client's whole logical op
        "lms.GetLLMAnswer",        # LMS servicer handler fragment
        "raft.commit",             # the read fence's no-op barrier commit
        "gate.check",              # relevance gate (KeywordGate in sim)
        "tutoring.forward",        # the HMAC'd LMS -> tutoring hop
        "tutoring.GetLLMAnswer",   # tutoring servicer handler fragment
        "queue.wait",              # batcher admission -> dispatch
        "engine.batch",            # the request's device batch
        "engine.generate",         # the engine program (EchoEngine keeps
                                   # the real pop_program_times contract)
    ):
        assert required in by_name, (
            f"span {required!r} missing; tree has {sorted(by_name)}"
        )
    # One tree, not orphan fragments: the client span is the single root
    # and every other span hangs beneath it.
    assert len(tree["spans"]) == 1
    assert tree["spans"][0]["name"] == "client.ask_llm"
    # The gate verdict rides the span.
    assert by_name["gate.check"][0]["attrs"]["passed"] is True


def test_ask_span_durations_nest_within_e2e_latency(traced_ask):
    _, tree, wall_s = traced_ask
    (root,) = tree["spans"]
    assert root["duration_s"] <= wall_s + 0.05, (
        "client span must not exceed the latency the caller measured"
    )
    _assert_nesting(root)
    # The stages the waterfall attributes must be real time, not zeros.
    by_name = _spans_by_name(tree)
    assert by_name["engine.generate"][0]["duration_s"] > 0
    assert by_name["tutoring.forward"][0]["duration_s"] > 0


def test_trace_listing_pins_the_ask_exemplar(cluster, traced_ask):
    rid, _, _ = traced_ask
    listing = cluster.admin_get(cluster.node_ids()[0], "/admin/trace")
    assert listing["ok"]
    everything = listing["exemplars"] + listing["recent"]
    assert any(s["trace_id"] == rid for s in everything)
    # The first ask is by definition among the slowest-N for its route.
    assert any(s["trace_id"] == rid and "slowest" in s["pinned"]
               for s in listing["exemplars"])


def test_degraded_ask_keeps_trace_continuity(cluster, student):
    """Satellite: a breaker-open/blackout ask still reaches the
    instructor-queue write under ONE request id — the flight recorder
    pins it, and the tree shows handler -> degraded.queue ->
    raft.commit."""
    rid = "trace-e2e-degraded-1"
    for nid in cluster.node_ids():
        cluster.admin_post(nid, "/admin/faults",
                           {"target": "tutoring", "drop": 1.0})
    try:
        resp = student.ask_llm(
            "Explain Raft commitment and safety under partitions.",
            budget_s=15.0, request_id=rid,
        )
        assert resp.success and "forwarded to an instructor" in resp.response
    finally:
        for nid in cluster.node_ids():
            cluster.admin_post(nid, "/admin/faults", {"reset": True})
    doc = cluster.admin_get(cluster.node_ids()[0], f"/admin/trace/{rid}")
    tree = doc["trace"]
    assert tree["trace_id"] == rid
    assert "degraded" in tree["flags"]
    by_name = _spans_by_name(tree)
    assert "lms.GetLLMAnswer" in by_name
    assert "degraded.queue" in by_name
    # The instructor-queue write is a replicated command: its raft.commit
    # span must sit UNDER the degraded.queue span of this same trace.
    queue_span = by_name["degraded.queue"][0]
    assert any(c["name"] == "raft.commit"
               for c in queue_span.get("children", ())), (
        "the degraded path's instructor-queue write lost its raft.commit"
    )
    # Anomalies are never sampled away: the trace is pinned.
    listing = cluster.admin_get(cluster.node_ids()[0], "/admin/trace")
    assert any(s["trace_id"] == rid and "flagged" in s["pinned"]
               for s in listing["exemplars"])


# ------------------------------------------------- trace_report.py smoke


def test_trace_report_listing_smoke(cluster, traced_ask, capsys):
    """Satellite: the waterfall CLI reads /admin/trace from a live
    cluster."""
    url = f"http://127.0.0.1:{cluster.health_port(cluster.node_ids()[0])}"
    assert trace_report.main(["--endpoint", url]) == 0
    out = capsys.readouterr().out
    assert "exemplars" in out and "client.ask_llm" in out


def test_trace_report_waterfall_smoke(cluster, traced_ask, capsys):
    rid, _, _ = traced_ask
    urls = []
    for nid in cluster.node_ids():
        urls += ["--endpoint",
                 f"http://127.0.0.1:{cluster.health_port(nid)}"]
    assert trace_report.main(urls + [rid]) == 0
    out = capsys.readouterr().out
    assert f"trace {rid}" in out
    for stage in ("client.ask_llm", "lms.GetLLMAnswer", "raft.commit",
                  "gate.check", "tutoring.forward", "queue.wait",
                  "engine.generate"):
        assert stage in out, f"waterfall lost stage {stage}"


def test_trace_report_unknown_trace_fails(cluster, capsys):
    url = f"http://127.0.0.1:{cluster.health_port(cluster.node_ids()[0])}"
    assert trace_report.main(["--endpoint", url, "never-existed"]) == 2
