"""Single-server cluster membership changes (Raft §4, one at a time).

The reference freezes its topology in source (5 servers hardcoded —
reference: GUI_RAFT_LLM_SourceCode/lms_server.py:1608-1612, 1454-1460);
growing or shrinking the cluster means editing code on every machine.
Here membership is a replicated log entry carrying the full id -> address
map: it takes effect on append, one server may change per committed entry
(consecutive configs share a quorum — no joint consensus needed), a
truncated uncommitted change rolls back, and the base membership persists
through WAL compaction. The round-4 verdict's done-criterion — a wiped
extra node joins a RUNNING cluster over real gRPC and serves — is the
final test.
"""

import asyncio

import pytest

from distributed_lms_raft_llm_tpu.raft import (
    MemNetwork,
    MemoryStorage,
    RaftConfig,
    RaftNode,
    encode_command,
)
from distributed_lms_raft_llm_tpu.raft.core import ConfigChangeInFlight

from test_raft_cluster import FAST, build_cluster, wait_for_leader


def addr(i: int) -> str:
    return f"127.0.0.1:{9000 + i}"


async def wait_until(cond, timeout=5.0, what="condition"):
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        if cond():
            return
        await asyncio.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


def test_add_server_catches_up_and_counts_toward_quorum():
    async def run():
        net = MemNetwork()
        applied = {}
        nodes, _ = build_cluster(net, 3, applied=applied)
        for n in nodes.values():
            await n.start()
        leader = await wait_for_leader(nodes)
        for k in range(4):
            await leader.propose(encode_command("set", {"k": str(k)}))

        # Wiped 4th node: its own boot config already lists the 4-node
        # topology (the operator knows the target); the RUNNING cluster
        # learns about it only through the membership entry.
        s4 = MemoryStorage()
        n4 = RaftNode(
            4, {i: addr(i) for i in (1, 2, 3, 4)}, s4,
            net.transport_for(4),
            apply_cb=lambda i, e: applied.setdefault(4, []).append(
                (i, e.command)
            ),
            config=FAST, tick_interval=0.01, seed=104,
        )
        net.register(n4)
        await n4.start()

        members = {i: addr(i) for i in (1, 2, 3, 4)}
        await leader.propose_config(members)
        assert set(leader.core.members) == {1, 2, 3, 4}
        assert leader.core.quorum() == 3

        # The new node catches up (historical entries replicated to it).
        await wait_until(
            lambda: len(applied.get(4, [])) >= 4, what="node 4 catch-up"
        )
        # And participates: a post-change command applies everywhere.
        await leader.propose(encode_command("set", {"k": "after"}))
        await wait_until(
            lambda: all(
                any("after" in cmd for _, cmd in applied.get(i, []))
                for i in (1, 2, 3, 4)
            ),
            what="post-change replication to all 4",
        )
        # New quorum is real: stop one OLD node; 3 of 4 still commit.
        await nodes[3].stop()
        leader = await wait_for_leader({**nodes, 4: n4})
        await leader.propose(encode_command("set", {"k": "quorum3of4"}))
        for n in (*nodes.values(), n4):
            await n.stop()

    asyncio.run(run())


def test_remove_server_shrinks_quorum_and_stops_heartbeats():
    async def run():
        net = MemNetwork()
        nodes, _ = build_cluster(net, 4)
        for n in nodes.values():
            await n.start()
        leader = await wait_for_leader(nodes)
        victim = next(i for i in nodes if i != leader.node_id)
        members = {
            i: addr(i) for i in nodes if i != victim
        }
        await leader.propose_config(members)
        assert victim not in leader.core.members
        assert leader.core.quorum() == 2  # 3-node cluster now

        # The removed server never LEARNS of its removal (the leader stops
        # replicating to it — Raft §4.2's acknowledged gap); it times out
        # and campaigns, but the §4.2.3 vote guard makes the members
        # disregard it AND pre-vote semantics keep its own term from
        # inflating — the live leader's term holds, and the victim stays
        # harmless even if later re-added.
        term_before = leader.core.current_term
        await asyncio.sleep(0.8)  # > 3 election timeouts of campaigning
        assert leader.is_leader and leader.core.current_term == term_before
        assert nodes[victim].core.role.value == "candidate"  # it IS trying
        assert nodes[victim].core.current_term <= term_before  # ...harmlessly

        # Cluster still commits with the shrunken quorum.
        await leader.propose(encode_command("set", {"k": "postremove"}))
        for n in nodes.values():
            await n.stop()

    asyncio.run(run())


def test_one_change_at_a_time_and_leader_self_removal_rejected():
    async def run():
        net = MemNetwork(delay=0.05)  # slow network: change stays in flight
        nodes, _ = build_cluster(net, 3)
        for n in nodes.values():
            await n.start()
        leader = await wait_for_leader(nodes)
        # The barrier precondition: config changes are rejected until the
        # leader has committed an entry of its own term (its no-op).
        with pytest.raises(ConfigChangeInFlight, match="barrier"):
            leader.core.propose_config(
                {i: addr(i) for i in (1, 2, 3, 4)}, 0.0
            )
        await wait_until(
            lambda: leader.core.entry_term(leader.core.commit_index)
            == leader.core.current_term,
            what="leader's no-op barrier commit",
        )
        members4 = {i: addr(i) for i in (1, 2, 3, 4)}
        # Not awaited: the entry is appended but not yet committed.
        task = asyncio.ensure_future(leader.propose_config(members4))
        await asyncio.sleep(0)
        with pytest.raises(ConfigChangeInFlight):
            leader.core.propose_config(
                {i: addr(i) for i in (1, 2, 3, 4, 5)}, 0.0
            )
        await task  # first change commits fine
        with pytest.raises(ValueError, match="exactly one"):
            leader.core.propose_config(
                {i: addr(i) for i in (1, 2, 3, 4, 5, 6)}, 0.0
            )
        with pytest.raises(ValueError, match="cannot remove itself"):
            members = dict(leader.core.members)
            members.pop(leader.node_id)
            leader.core.propose_config(members, 0.0)
        for n in nodes.values():
            await n.stop()

    asyncio.run(run())


def test_membership_survives_compaction_and_restart():
    """After the change entry compacts out of the WAL, a node restarted
    with the OLD boot topology must still know the 4-node membership
    (durable base via storage.save_members)."""

    async def run():
        net = MemNetwork()
        nodes, storages = build_cluster(net, 3)
        for n in nodes.values():
            await n.start()
        leader = await wait_for_leader(nodes)
        s4 = MemoryStorage()
        n4 = RaftNode(
            4, {i: addr(i) for i in (1, 2, 3, 4)}, s4,
            net.transport_for(4), config=FAST, tick_interval=0.01, seed=104,
        )
        net.register(n4)
        await n4.start()
        await leader.propose_config({i: addr(i) for i in (1, 2, 3, 4)})
        for k in range(3):
            await leader.propose(encode_command("set", {"k": str(k)}))
        # Compact past the membership entry on the leader.
        leader.core.compact(leader.core.last_applied, b"snap")
        assert leader.core.snapshot_index >= 2
        lid = leader.node_id
        stored = storages[lid].members
        assert stored is not None and set(stored) == {1, 2, 3, 4}

        # Restart the leader node from its storage with the ORIGINAL
        # 3-node boot list: durable membership wins. (last_applied mirrors
        # the app snapshot that drove the compaction, per the boot
        # invariant.)
        applied_at = leader.core.last_applied
        await leader.stop()
        reborn = RaftNode(
            lid, [1, 2, 3], storages[lid], net.transport_for(lid),
            config=FAST, tick_interval=0.01, seed=200 + lid,
            last_applied=applied_at,
        )
        assert set(reborn.core.members) == {1, 2, 3, 4}
        assert reborn.core.members[4] == addr(4)
        for n in nodes.values():
            if n.node_id != lid:
                await n.stop()
        await n4.stop()

    asyncio.run(run())


def test_wiped_sixth_node_joins_running_five_node_grpc_cluster():
    """The verdict's done-criterion, over the real wire: a 5-node cluster
    (reference topology) runs over gRPC; a wiped 6th node boots; one
    admin membership change later it has replicated the full history and
    serves as a member."""
    import grpc

    from distributed_lms_raft_llm_tpu.proto import rpc
    from distributed_lms_raft_llm_tpu.raft.grpc_transport import (
        GrpcTransport, RaftServicer,
    )

    async def serve_raft(node, address):
        server = grpc.aio.server()
        rpc.add_RaftServiceServicer_to_server(
            RaftServicer(node, {}, kv={}), server
        )
        server.add_insecure_port(address)
        await server.start()
        return server

    async def run():
        import socket

        def free_port():
            with socket.socket() as s:
                s.bind(("127.0.0.1", 0))
                return s.getsockname()[1]

        addrs = {i: f"127.0.0.1:{free_port()}" for i in range(1, 7)}
        applied = {}
        nodes, servers = {}, {}
        for i in range(1, 6):
            def make_cb(i=i):
                return lambda idx, e: applied.setdefault(i, []).append(
                    (idx, e.command)
                )

            node = RaftNode(
                i, {j: addrs[j] for j in range(1, 6)}, MemoryStorage(),
                GrpcTransport({j: addrs[j] for j in range(1, 6)}),
                apply_cb=make_cb(), config=FAST, tick_interval=0.01,
                seed=300 + i,
            )
            servers[i] = await serve_raft(node, addrs[i])
            nodes[i] = node
            await node.start()
        leader = await wait_for_leader(nodes, timeout=10.0)
        for k in range(5):
            await leader.propose(encode_command("set", {"k": str(k)}))

        # Wiped 6th node: fresh storage, boots knowing the 6-node map.
        node6 = RaftNode(
            6, {j: addrs[j] for j in range(1, 7)}, MemoryStorage(),
            GrpcTransport({j: addrs[j] for j in range(1, 7)}),
            apply_cb=lambda idx, e: applied.setdefault(6, []).append(
                (idx, e.command)
            ),
            config=FAST, tick_interval=0.01, seed=306,
        )
        servers[6] = await serve_raft(node6, addrs[6])
        nodes[6] = node6
        await node6.start()

        await leader.propose_config({j: addrs[j] for j in range(1, 7)})
        assert leader.core.quorum() == 4  # 6-node cluster

        await wait_until(
            lambda: len(applied.get(6, [])) >= 5, timeout=10.0,
            what="node 6 catch-up over gRPC",
        )
        await leader.propose(encode_command("set", {"k": "joined"}))
        await wait_until(
            lambda: any("joined" in cmd for _, cmd in applied.get(6, [])),
            timeout=10.0, what="node 6 applies post-join entry",
        )
        for n in nodes.values():
            await n.stop()
        for s in servers.values():
            await s.stop(None)

    asyncio.run(run())


def test_snapshot_envelope_delivers_membership_to_lagging_follower():
    """A follower that was DOWN while a membership change committed and
    compacted into the snapshot must learn the new config from the
    InstallSnapshot envelope (the frozen wire message has no config field;
    raft/messages.wrap_snapshot carries it inside `data`) — otherwise its
    quorum view diverges from the cluster's."""

    async def run():
        net = MemNetwork()
        nodes, storages = build_cluster(net, 3)
        for n in nodes.values():
            await n.start()
        leader = await wait_for_leader(nodes)
        await leader.propose(encode_command("set", {"k": "pre"}))

        # Follower F goes down before the membership change.
        fid = next(i for i in nodes if i != leader.node_id)
        await nodes[fid].stop()

        s4 = MemoryStorage()
        n4 = RaftNode(
            4, {i: addr(i) for i in (1, 2, 3, 4)}, s4,
            net.transport_for(4), config=FAST, tick_interval=0.01, seed=104,
        )
        net.register(n4)
        await n4.start()
        await leader.propose_config({i: addr(i) for i in (1, 2, 3, 4)})
        for k in range(6):
            await leader.propose(encode_command("set", {"k": str(k)}))
        # Compact PAST the membership entry: it now lives only inside the
        # snapshot envelope.
        leader.core.compact(leader.core.last_applied, b"appstate")
        assert leader.core.snapshot_index > 0

        # F restarts with its OLD storage (pre-change log) and OLD 3-node
        # boot view; the leader must bring it up via InstallSnapshot.
        reborn = RaftNode(
            fid, [1, 2, 3], storages[fid], net.transport_for(fid),
            config=FAST, tick_interval=0.01, seed=400 + fid,
        )
        assert set(reborn.core.members) == {1, 2, 3}  # stale view at boot
        net.register(reborn)
        await reborn.start()
        await wait_until(
            lambda: set(reborn.core.members) == {1, 2, 3, 4},
            what="lagging follower learns membership from the snapshot",
        )
        assert reborn.core.members[4] == addr(4)
        assert reborn.core.snapshot_data == b"appstate"  # app bytes unwrapped
        assert storages[fid].members is not None
        for n in (*(n for n in nodes.values() if n.node_id != fid),
                  n4, reborn):
            await n.stop()

    asyncio.run(run())
