"""End-to-end resilience over real gRPC: deadline propagation
client → LMS → tutoring → batcher, circuit-broken degraded answers
(instructor queue), and a seeded chaos soak with `FaultInjector` on the
live Raft transport — the acceptance scenarios of the resilience layer.
"""

import asyncio
import threading
import time

import grpc
import pytest

import jax

from distributed_lms_raft_llm_tpu.client import LMSClient
from distributed_lms_raft_llm_tpu.engine import (
    BatchingQueue,
    EngineConfig,
    SamplingParams,
    TutoringEngine,
)
from distributed_lms_raft_llm_tpu.lms.node import LMSNode
from distributed_lms_raft_llm_tpu.lms.service import (
    FileTransferServicer,
    LMSServicer,
)
from distributed_lms_raft_llm_tpu.proto import lms_pb2, rpc
from distributed_lms_raft_llm_tpu.raft import RaftConfig
from distributed_lms_raft_llm_tpu.raft.grpc_transport import RaftServicer
from distributed_lms_raft_llm_tpu.serving import tutoring_server as ts
from distributed_lms_raft_llm_tpu.utils import pdf
from distributed_lms_raft_llm_tpu.utils.faults import FaultInjector
from distributed_lms_raft_llm_tpu.utils.metrics import Metrics
from distributed_lms_raft_llm_tpu.utils.resilience import (
    DEADLINE_METADATA_KEY,
    REQUEST_ID_METADATA_KEY,
    CircuitBreaker,
)

FAST = RaftConfig(
    election_timeout_min=0.11, election_timeout_max=0.22,
    heartbeat_interval=0.05,
)


@pytest.fixture(scope="module")
def stack(tmp_path_factory):
    """1-node LMS + tiny tutoring node, breaker + injector installed."""
    tmp = tmp_path_factory.mktemp("resilience")
    loop = asyncio.new_event_loop()
    started = threading.Event()
    state = {}

    def run():
        asyncio.set_event_loop(loop)

        async def boot():
            engine = TutoringEngine(
                EngineConfig(
                    model="tiny",
                    sampling=SamplingParams(max_new_tokens=6),
                    length_buckets=(32,),
                    batch_buckets=(1, 2, 4),
                    dtype=jax.numpy.float32,
                )
            )
            tut_metrics = Metrics()
            queue = BatchingQueue(engine, max_batch=4, max_wait_ms=10,
                                  metrics=tut_metrics, max_queue=8)
            await queue.start()
            tut_server = grpc.aio.server()
            rpc.add_TutoringServicer_to_server(
                ts.TutoringService(queue, tut_metrics), tut_server
            )
            tut_port = tut_server.add_insecure_port("127.0.0.1:0")
            await tut_server.start()

            injector = FaultInjector(seed=1234)
            metrics = Metrics()
            breaker = CircuitBreaker(failure_threshold=2, recovery_s=0.5)

            server = grpc.aio.server(
                options=[("grpc.max_receive_message_length", 50 * 1024 * 1024)]
            )
            port = server.add_insecure_port("127.0.0.1:0")
            addresses = {1: f"127.0.0.1:{port}"}
            node = LMSNode(1, addresses, str(tmp / "node1"), raft_config=FAST,
                           fault_injector=injector)
            servicer = LMSServicer(
                node.node, node.state, node.blobs,
                tutoring_address=f"127.0.0.1:{tut_port}",
                metrics=metrics,
                tutoring_breaker=breaker,
                fault_injector=injector,
                tutoring_timeout_s=30.0,
                deadline_floor_s=0.25,
            )
            rpc.add_LMSServicer_to_server(servicer, server)
            rpc.add_RaftServiceServicer_to_server(
                RaftServicer(node.node, addresses, kv=node.state.data["kv"]),
                server,
            )
            rpc.add_FileTransferServiceServicer_to_server(
                FileTransferServicer(node.blobs), server
            )
            await server.start()
            await node.start()
            state.update(
                node=node, server=server, queue=queue, servicer=servicer,
                tut_server=tut_server, tut_metrics=tut_metrics,
                metrics=metrics, breaker=breaker, injector=injector,
                address=addresses[1], tut_address=f"127.0.0.1:{tut_port}",
                loop=loop,
            )
            started.set()

        loop.run_until_complete(boot())
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert started.wait(60)
    yield state

    async def teardown():
        await state["node"].stop()
        await state["server"].stop(None)
        await state["queue"].close()
        await state["tut_server"].stop(None)

    asyncio.run_coroutine_threadsafe(teardown(), loop).result(30)
    loop.call_soon_threadsafe(loop.stop)


@pytest.fixture(scope="module")
def student(stack):
    c = LMSClient([stack["address"]], discovery_backoff_s=0.2,
                  backoff_base_s=0.02, backoff_max_s=0.2, seed=5)
    assert c.register("ana", "pw", "student").success
    assert c.login("ana", "pw")
    assert c.upload_assignment("hw.pdf", pdf.make_pdf("B-tree homework"))
    yield c
    c.close()


def test_ask_llm_works_with_no_faults(stack, student):
    resp = student.ask_llm("How does a B-tree split?")
    assert resp.success
    assert "instructor" not in resp.response.lower()


def test_ask_llm_degrades_within_deadline_when_tutoring_faulted(stack, student):
    """The acceptance scenario: tutoring at 100% injected failure — ask_llm
    returns a degraded instructor-queue answer within the client budget
    instead of hanging or erroring."""
    stack["injector"].configure("tutoring", drop=1.0)
    try:
        t0 = time.monotonic()
        resp = student.ask_llm("What is an LSM tree?", budget_s=10.0)
        elapsed = time.monotonic() - t0
    finally:
        stack["injector"].clear("tutoring")
    assert elapsed < 10.0, "must answer within the client deadline"
    assert resp.success
    assert "instructor" in resp.response.lower()
    # The query really landed on the replicated instructor queue.
    queries = [q["query"] for q in stack["node"].state.unanswered_queries()]
    assert "What is an LSM tree?" in queries
    # One failure (threshold 2): breaker still closed, service recovers.
    resp = student.ask_llm("What is an LSM tree, again?")
    assert resp.success and "instructor" not in resp.response.lower()


def test_breaker_opens_after_consecutive_failures_then_recovers(stack, student):
    breaker = stack["breaker"]
    stack["injector"].configure("tutoring", drop=1.0)
    try:
        for _ in range(2):  # threshold=2 consecutive failures
            assert student.ask_llm("q?").success
        assert breaker.state == CircuitBreaker.OPEN
        rejections_before = (
            stack["metrics"].snapshot()["counters"]
            .get("tutoring_breaker_rejections", 0)
        )
        # Open circuit: degraded in O(1), no dial, no timeout stacking.
        t0 = time.monotonic()
        resp = student.ask_llm("q while open?")
        assert time.monotonic() - t0 < 2.0
        assert resp.success and "instructor" in resp.response.lower()
        counters = stack["metrics"].snapshot()["counters"]
        assert counters["tutoring_breaker_rejections"] == rejections_before + 1
    finally:
        stack["injector"].clear("tutoring")
    time.sleep(0.6)  # recovery_s=0.5: open -> half-open
    resp = student.ask_llm("probe?")  # half-open probe succeeds, closes
    assert resp.success and "instructor" not in resp.response.lower()
    assert breaker.state == CircuitBreaker.CLOSED


def test_budget_below_floor_degrades_without_forwarding(stack, student):
    """Deadline propagation client → LMS: a budget under the floor makes
    the LMS degrade immediately rather than start a forward it cannot
    finish in time. The floor is temporarily raised to 2 s so the check
    (budget 1.5 < floor 2) is deterministic while the wall-clock margin
    for the degrade round trip stays generous on slow CI."""
    servicer = stack["servicer"]
    before = stack["tut_metrics"].snapshot()["counters"]["llm_requests"]
    old_floor = servicer._deadline_floor_s
    servicer._deadline_floor_s = 2.0
    try:
        resp = student.ask_llm("tiny budget?", budget_s=1.5)
    finally:
        servicer._deadline_floor_s = old_floor
    assert resp.success and "instructor" in resp.response.lower()
    counters = stack["metrics"].snapshot()["counters"]
    assert counters.get("tutoring_budget_exhausted", 0) >= 1
    after = stack["tut_metrics"].snapshot()["counters"]["llm_requests"]
    assert after == before  # never dialed tutoring


def test_tutoring_honors_deadline_metadata_over_wire(stack):
    """Deadline propagation LMS → tutoring: an already-expired budget
    header aborts with DEADLINE_EXCEEDED before any generation."""
    with grpc.insecure_channel(stack["tut_address"]) as channel:
        stub = rpc.TutoringStub(channel)
        with pytest.raises(grpc.RpcError) as err:
            stub.GetLLMAnswer(
                lms_pb2.QueryRequest(token="t", query="late question"),
                timeout=5,
                metadata=[(DEADLINE_METADATA_KEY, "0")],
            )
    assert err.value.code() == grpc.StatusCode.DEADLINE_EXCEEDED
    assert stack["tut_metrics"].snapshot()["counters"]["shed_expired"] >= 1


def test_tutoring_overload_returns_resource_exhausted(stack):
    """Bounded admission over the wire: saturate the queue bound and the
    surplus RPC is refused with RESOURCE_EXHAUSTED (not queued forever)."""
    queue = stack["queue"]
    loop = stack["loop"]

    # Block the engine worker with a synthetic slow batch, then fill the
    # bounded queue from the cluster loop so qsize really accumulates.
    real_engine = queue.engine

    class Plug:
        def answer_batch(self, prompts):
            time.sleep(2.0)
            return ["plugged"] * len(prompts)

    async def saturate():
        queue.engine = Plug()
        # Stage 1: one request the runner takes alone into the (plugged)
        # engine; stage 2: exactly max_queue more fill the bound while the
        # engine is busy.
        futs = [asyncio.ensure_future(queue.submit("fill first"))]
        await asyncio.sleep(0.1)
        futs += [asyncio.ensure_future(queue.submit(f"fill {i}"))
                 for i in range(queue.max_queue)]
        await asyncio.sleep(0.05)
        assert queue._queue.qsize() >= queue.max_queue
        return futs

    futs = asyncio.run_coroutine_threadsafe(saturate(), loop).result(10)
    try:
        with grpc.insecure_channel(stack["tut_address"]) as channel:
            stub = rpc.TutoringStub(channel)
            with pytest.raises(grpc.RpcError) as err:
                stub.GetLLMAnswer(
                    lms_pb2.QueryRequest(token="t", query="one too many"),
                    timeout=5,
                )
        assert err.value.code() == grpc.StatusCode.RESOURCE_EXHAUSTED
        assert (stack["tut_metrics"].snapshot()["counters"]
                .get("shed_overload", 0) >= 1)
    finally:
        async def drain():
            queue.engine = real_engine
            await asyncio.gather(*futs, return_exceptions=True)

        asyncio.run_coroutine_threadsafe(drain(), loop).result(30)


def test_degraded_fallback_dedupes_client_retries(stack, student):
    """ROADMAP item (a): ONE logical ask_llm, retried, queues ONE
    instructor entry. The client threads a single x-request-id across its
    retries; the degraded fallback keys the replicated AskQuery on it, so
    the applier's idempotency ledger drops the retry's duplicate."""
    stack["injector"].configure("tutoring", drop=1.0)
    query = "idempotent degraded question (one entry expected)"
    try:
        with grpc.insecure_channel(stack["address"]) as channel:
            stub = rpc.LMSStub(channel)
            # Two wire attempts of the SAME logical request (what the
            # client's retry loop sends after a lost response).
            for _ in range(2):
                resp = stub.GetLLMAnswer(
                    lms_pb2.QueryRequest(token=student.token, query=query),
                    timeout=10,
                    metadata=[(REQUEST_ID_METADATA_KEY, "logical-req-1")],
                )
                assert resp.success
                assert "instructor" in resp.response.lower()
    finally:
        stack["injector"].clear("tutoring")
        # The induced failures may have opened the breaker; close it so
        # later tests start from the healthy state.
        stack["breaker"].record_success()
    queued = [q for q in stack["node"].state.unanswered_queries()
              if q["query"] == query]
    assert len(queued) == 1, (
        f"expected one instructor entry for one logical request, got "
        f"{len(queued)}"
    )


def test_degraded_fallback_without_request_id_still_queues(stack, student):
    """Clients that send no x-request-id keep the old per-attempt ids (no
    dedupe, but never dropped either) — pins the fallback's default."""
    stack["injector"].configure("tutoring", drop=1.0)
    query = "degraded question without idempotency key"
    try:
        with grpc.insecure_channel(stack["address"]) as channel:
            stub = rpc.LMSStub(channel)
            resp = stub.GetLLMAnswer(
                lms_pb2.QueryRequest(token=student.token, query=query),
                timeout=10,
            )
            assert resp.success and "instructor" in resp.response.lower()
    finally:
        stack["injector"].clear("tutoring")
        stack["breaker"].record_success()  # close again for later tests
    queued = [q for q in stack["node"].state.unanswered_queries()
              if q["query"] == query]
    assert len(queued) == 1


def test_duplicate_fault_delivers_tutoring_query_twice(stack, student):
    """ROADMAP item (b): the "duplicate" fault is now real on the tutoring
    hop — the forward is delivered twice (idempotent: same success, extra
    compute only), it counts as injected, and the tutoring node really
    sees both deliveries."""
    before = (stack["tut_metrics"].snapshot()["counters"]
              .get("llm_requests", 0))
    injected_before = stack["injector"].snapshot()["injected_total"]
    stack["injector"].configure("tutoring", duplicate=1.0)
    try:
        resp = student.ask_llm("duplicated question?")
    finally:
        stack["injector"].clear("tutoring")
    assert resp.success
    assert "instructor" not in resp.response.lower()  # not degraded
    after = stack["tut_metrics"].snapshot()["counters"]["llm_requests"]
    assert after == before + 2, "tutoring must see both deliveries"
    assert stack["injector"].snapshot()["injected_total"] > injected_before
    assert (stack["metrics"].snapshot()["counters"]
            .get("tutoring_duplicates", 0) >= 1)


# ----------------------------------------------------------- chaos over gRPC


@pytest.mark.slow
def test_chaos_soak_over_real_grpc(tmp_path):
    """Seeded chaos on the LIVE Raft gRPC transport: drops, delays, and
    duplicates on every node's egress while clients keep mutating. After
    healing, all replicas converge — the MemNetwork chaos guarantees,
    now over real sockets."""
    loop = asyncio.new_event_loop()
    started = threading.Event()
    state = {}

    def run():
        asyncio.set_event_loop(loop)

        async def boot():
            ids = [1, 2, 3]
            injectors = {i: FaultInjector(seed=100 + i) for i in ids}
            servers, addresses = {}, {}
            for i in ids:
                servers[i] = grpc.aio.server()
                port = servers[i].add_insecure_port("127.0.0.1:0")
                addresses[i] = f"127.0.0.1:{port}"
            nodes = {}
            for i in ids:
                node = LMSNode(i, addresses, str(tmp_path / f"node{i}"),
                               raft_config=FAST,
                               fault_injector=injectors[i])
                servicer = LMSServicer(node.node, node.state, node.blobs)
                rpc.add_LMSServicer_to_server(servicer, servers[i])
                rpc.add_RaftServiceServicer_to_server(
                    RaftServicer(node.node, addresses,
                                 kv=node.state.data["kv"]),
                    servers[i],
                )
                rpc.add_FileTransferServiceServicer_to_server(
                    FileTransferServicer(node.blobs), servers[i]
                )
                await servers[i].start()
                await node.start()
                nodes[i] = node
            state.update(nodes=nodes, servers=servers, addresses=addresses,
                         injectors=injectors)
            started.set()

        loop.run_until_complete(boot())
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert started.wait(60)
    try:
        client = LMSClient(list(state["addresses"].values()),
                           discovery_backoff_s=0.2, backoff_base_s=0.05,
                           backoff_max_s=0.5, rpc_retries=8,
                           request_timeout_s=30.0, seed=9)
        # Let a leader emerge cleanly, then unleash the chaos.
        client.discover_leader()
        for inj in state["injectors"].values():
            inj.configure("*", drop=0.15, delay_s=0.002,
                          delay_jitter_s=0.01, duplicate=0.1)
        users = [f"user{i}" for i in range(4)]
        for u in users:
            assert client.register(u, "pw", "student").success
        assert client.login(users[0], "pw")
        assert client.ask_instructor("chaos question?")
        client.logout()
        # Heal and verify convergence across all replicas.
        for inj in state["injectors"].values():
            inj.clear()
        faulted = sum(
            inj.snapshot()["injected_total"]
            for inj in state["injectors"].values()
        )
        assert faulted > 0, "the soak must actually have injected faults"
        deadline = time.monotonic() + 15.0
        while time.monotonic() < deadline:
            datas = [n.state.data for n in state["nodes"].values()]
            if all(set(d["users"]) == set(users) for d in datas) and all(
                d["queries"].get(users[0]) for d in datas
            ):
                break
            time.sleep(0.25)
        for n in state["nodes"].values():
            assert set(n.state.data["users"]) == set(users)
            assert n.state.data["queries"][users[0]][0]["query"] == (
                "chaos question?"
            )
        client.close()
    finally:
        async def teardown():
            for n in state["nodes"].values():
                await n.stop()
            for s in state["servers"].values():
                await s.stop(None)

        asyncio.run_coroutine_threadsafe(teardown(), loop).result(30)
        loop.call_soon_threadsafe(loop.stop)
        t.join(timeout=10)
