"""Storage integrity: checksummed WAL + snapshot headers, fault handling.

The disk-surface counterpart of tests/test_resilience.py: every detection
and repair path the crash-consistent storage layer added —

- WAL v2 framing round-trips; a torn tail truncates and replay continues
  (crash semantics), while MID-FILE corruption (bit rot) raises
  `WALCorruption` instead of silently dropping the committed suffix;
- the LMS snapshot integrity header rejects corrupt files with
  `SnapshotCorruption` instead of loading an empty state at index 0;
- legacy (pre-checksum) WALs and snapshots written by the v1 code load
  cleanly once and upgrade in place on the next compaction/save;
- ENOSPC mid-append rolls the file back to the last good record so the
  NEXT append cannot merge into a partial line;
- stale temp files leak-swept at boot, counted in
  `stale_tmp_files_removed`;
- LMSNode recovery policy: 'fail' refuses to start on corrupt state,
  'rejoin' quarantines it and boots in recovering mode.
"""

import json
import os

import pytest

from distributed_lms_raft_llm_tpu.lms.node import LMSNode
from distributed_lms_raft_llm_tpu.lms.persistence import (
    BlobStore,
    SnapshotCorruption,
    SnapshotStore,
)
from distributed_lms_raft_llm_tpu.lms.state import LMSState
from distributed_lms_raft_llm_tpu.raft import Entry, FileStorage
from distributed_lms_raft_llm_tpu.raft.node import MemNetwork
from distributed_lms_raft_llm_tpu.raft.storage import (
    WALCorruption,
    _parse_line,
    frame_record,
)
from distributed_lms_raft_llm_tpu.utils.diskfaults import (
    REAL_FS,
    DiskFault,
    DiskFaultInjector,
    FaultyFS,
)
from distributed_lms_raft_llm_tpu.utils.metrics import Metrics


def write_entries(storage, first, n, term=1):
    for i in range(first, first + n):
        storage.append_entries(i, [Entry(term, f"cmd-{i}")])


# ----------------------------------------------------------- WAL framing


def test_v2_frame_round_trips():
    rec = {"t": "entry", "i": 3, "term": 2, "cmd": "x"}
    line = frame_record(rec)
    parsed, legacy = _parse_line(line.strip().encode())
    assert parsed == rec and not legacy


def test_torn_tail_truncated_and_replay_continues(tmp_path):
    path = str(tmp_path / "wal.jsonl")
    s = FileStorage(path, fsync=False)
    write_entries(s, 1, 3)
    s.close()
    # Crash mid-append: a partial, unterminated final record.
    with open(path, "ab") as fh:
        fh.write(frame_record({"t": "entry", "i": 4, "term": 1,
                               "cmd": "torn"}).encode()[:20])
    m = Metrics()
    s2 = FileStorage(path, fsync=False, metrics=m)
    _, _, entries, _, _ = s2.load()
    assert [e.command for e in entries] == ["cmd-1", "cmd-2", "cmd-3"]
    assert m.snapshot()["counters"]["wal_torn_tail_truncations"] == 1
    # The torn bytes are physically gone: the next append lands clean.
    write_entries(s2, 4, 1)
    s2.close()
    s3 = FileStorage(path, fsync=False)
    assert [e.command for e in s3.load()[2]] == [
        "cmd-1", "cmd-2", "cmd-3", "cmd-4"]
    s3.close()


def test_midfile_corruption_refuses_to_load(tmp_path):
    path = str(tmp_path / "wal.jsonl")
    s = FileStorage(path, fsync=False)
    write_entries(s, 1, 5)
    s.close()
    raw = open(path, "rb").read()
    # Flip one payload bit in the SECOND record (mid-file, not the tail).
    lines = raw.splitlines(keepends=True)
    target = lines[1]
    pos = len(target) // 2
    lines[1] = target[:pos] + bytes([target[pos] ^ 0x01]) + target[pos + 1:]
    open(path, "wb").write(b"".join(lines))
    m = Metrics()
    with pytest.raises(WALCorruption):
        FileStorage(path, fsync=False, metrics=m)
    assert m.snapshot()["counters"]["wal_corrupt_records"] == 1


def test_corrupt_final_terminated_record_is_corruption_not_torn(tmp_path):
    """A COMPLETE (newline-terminated) final record with a bad CRC is bit
    rot, not a torn tail: a crash truncates, it does not rewrite bytes."""
    path = str(tmp_path / "wal.jsonl")
    s = FileStorage(path, fsync=False)
    write_entries(s, 1, 2)
    s.close()
    raw = open(path, "rb").read()
    lines = raw.splitlines(keepends=True)
    last = lines[-1]
    pos = len(last) // 2
    lines[-1] = last[:pos] + bytes([last[pos] ^ 0x01]) + last[pos + 1:]
    open(path, "wb").write(b"".join(lines))
    with pytest.raises(WALCorruption):
        FileStorage(path, fsync=False)


# ------------------------------------------------------ legacy migration


def test_legacy_wal_loads_once_and_upgrades_on_compaction(tmp_path):
    """A WAL written by the pre-checksum code (bare JSON lines) must boot
    cleanly; the next compaction rewrites every record framed."""
    path = str(tmp_path / "wal.jsonl")
    with open(path, "w") as fh:  # exactly the v1 writer's format
        fh.write(json.dumps({"t": "meta", "term": 4, "voted_for": 2}) + "\n")
        for i in range(1, 6):
            fh.write(json.dumps(
                {"t": "entry", "i": i, "term": 4, "cmd": f"legacy-{i}"}
            ) + "\n")
    s = FileStorage(path, fsync=False)
    term, voted, entries, snap_idx, _ = s.load()
    assert (term, voted, snap_idx) == (4, 2, 0)
    assert [e.command for e in entries] == [f"legacy-{i}" for i in range(1, 6)]
    assert s.legacy_records == 6
    s.compact_to(2, 4)
    s.close()
    # Post-compaction the file is pure v2: every line carries a CRC frame.
    with open(path, "rb") as fh:
        for line in fh:
            assert not line.startswith(b"{"), "legacy line survived upgrade"
            rec, legacy = _parse_line(line.strip())
            assert not legacy
    s2 = FileStorage(path, fsync=False)
    assert [e.command for e in s2.load()[2]] == [
        "legacy-3", "legacy-4", "legacy-5"]
    assert s2.legacy_records == 0
    s2.close()


def test_legacy_snapshot_loads_once_and_upgrades_on_save(tmp_path):
    path = str(tmp_path / "lms_data.json")
    with open(path, "w") as fh:  # exactly the v1 writer's format
        json.dump({"applied_index": 9,
                   "data": {"kv": {"k": "v"}}}, fh)
    store = SnapshotStore(path)
    state, applied = store.load()
    assert applied == 9 and state.data["kv"] == {"k": "v"}
    assert store.legacy_loaded
    store.save(state, 9)
    raw = open(path, "rb").read()
    assert raw.startswith(b'{"t": "lmssnap"')  # upgraded in place
    fresh = SnapshotStore(path)
    state2, applied2 = fresh.load()
    assert applied2 == 9 and state2.data["kv"] == {"k": "v"}
    assert not fresh.legacy_loaded


def test_mixed_legacy_and_v2_wal_replays(tmp_path):
    """The first post-upgrade boot appends v2 frames AFTER v1 lines; both
    must replay in order until compaction homogenizes the file."""
    path = str(tmp_path / "wal.jsonl")
    with open(path, "w") as fh:
        fh.write(json.dumps(
            {"t": "entry", "i": 1, "term": 1, "cmd": "old"}) + "\n")
    s = FileStorage(path, fsync=False)
    write_entries(s, 2, 1)
    s.close()
    s2 = FileStorage(path, fsync=False)
    assert [e.command for e in s2.load()[2]] == ["old", "cmd-2"]
    assert s2.legacy_records == 1
    s2.close()


# ----------------------------------------------------- snapshot integrity


def test_snapshot_corruption_raises_everywhere(tmp_path):
    path = str(tmp_path / "lms_data.json")
    store = SnapshotStore(path)
    state = LMSState()
    state.data["kv"]["a"] = "1"
    store.save(state, 17)
    golden = open(path, "rb").read()
    # Any single flipped byte — header or payload — must be detected.
    for pos in range(0, len(golden), max(1, len(golden) // 23)):
        open(path, "wb").write(
            golden[:pos] + bytes([golden[pos] ^ 0x01]) + golden[pos + 1:]
        )
        with pytest.raises(SnapshotCorruption):
            SnapshotStore(path).load()
    # Truncation (torn write that somehow got renamed) is detected too.
    open(path, "wb").write(golden[: len(golden) - 7])
    with pytest.raises(SnapshotCorruption):
        SnapshotStore(path).load()
    open(path, "wb").write(golden)
    st, idx = SnapshotStore(path).load()
    assert idx == 17 and st.data["kv"] == {"a": "1"}


def test_missing_snapshot_is_still_empty_not_error(tmp_path):
    st, idx = SnapshotStore(str(tmp_path / "absent.json")).load()
    assert idx == 0 and st.data["kv"] == {}


# ------------------------------------------------------- ENOSPC handling


def test_enospc_mid_append_rolls_back_to_last_good_record(tmp_path):
    """A short write (ENOSPC) leaves a partial record; without the
    truncate-back, the next in-process append merges into it and the
    following replay refuses the merged garbage as corruption."""
    path = str(tmp_path / "wal.jsonl")
    inj = DiskFaultInjector(seed=7)
    s = FileStorage(path, fsync=False, fs=FaultyFS(REAL_FS, inj))
    write_entries(s, 1, 3)
    inj.configure(write_error=1.0)
    with pytest.raises(DiskFault):
        s.append_entries(4, [Entry(1, "doomed")])
    # In-memory state matches disk: the failed entry is NOT in the log.
    assert [e.command for e in s.load()[2]] == ["cmd-1", "cmd-2", "cmd-3"]
    inj.clear()
    # The next append lands on a clean boundary and replays fine.
    write_entries(s, 4, 1)
    s.close()
    s2 = FileStorage(path, fsync=False)
    assert [e.command for e in s2.load()[2]] == [
        "cmd-1", "cmd-2", "cmd-3", "cmd-4"]
    s2.close()


def test_fsync_failure_rolls_back_and_surfaces(tmp_path):
    path = str(tmp_path / "wal.jsonl")
    inj = DiskFaultInjector(seed=7)
    s = FileStorage(path, fs=FaultyFS(REAL_FS, inj))  # fsync=True
    write_entries(s, 1, 2)
    inj.configure(fsync_error=1.0)
    with pytest.raises(DiskFault):
        s.save_meta(5, 1)
    assert s.load()[0] == 0  # meta unchanged: disk-first, memory-second
    inj.clear()
    s.save_meta(5, 1)
    s.close()
    assert FileStorage(path).load()[0] == 5


def test_bit_flip_injection_is_caught_by_replay(tmp_path):
    """End-to-end: a flipped bit on the write path (FaultyFS) produces a
    record whose CRC fails — mid-file it refuses, at the tail it is NOT
    torn (terminated line) so it also refuses."""
    path = str(tmp_path / "wal.jsonl")
    inj = DiskFaultInjector(seed=3)
    s = FileStorage(path, fsync=False, fs=FaultyFS(REAL_FS, inj))
    write_entries(s, 1, 2)
    inj.configure(bit_flip=1.0)
    write_entries(s, 3, 1)  # written corrupted, in-memory believes it
    inj.clear()
    write_entries(s, 4, 1)
    s.close()
    with pytest.raises(WALCorruption):
        FileStorage(path, fsync=False)


# ----------------------------------------------------- stale temp sweeps


def test_boot_sweeps_stale_tmp_files(tmp_path):
    wal = str(tmp_path / "wal.jsonl")
    FileStorage(wal, fsync=False).close()
    (tmp_path / ".raftwal.stale1").write_bytes(b"x")
    (tmp_path / ".raftwal.stale2").write_bytes(b"x")
    (tmp_path / ".lmssnap.stale").write_bytes(b"x")
    blobs = tmp_path / "uploads" / "materials"
    blobs.mkdir(parents=True)
    (blobs / ".blob.stale").write_bytes(b"x")
    (blobs / ".blobstream.stale").write_bytes(b"x")
    (blobs / "real.pdf").write_bytes(b"keep me")
    m = Metrics()
    FileStorage(wal, fsync=False, metrics=m).close()
    SnapshotStore(str(tmp_path / "lms_data.json"), metrics=m)
    BlobStore(str(tmp_path / "uploads"), metrics=m)
    assert m.snapshot()["counters"]["stale_tmp_files_removed"] == 5
    assert not (tmp_path / ".raftwal.stale1").exists()
    assert not (blobs / ".blob.stale").exists()
    assert (blobs / "real.pdf").read_bytes() == b"keep me"


# --------------------------------------------------- LMSNode recovery path


def _corrupt_midfile(path):
    raw = open(path, "rb").read()
    lines = raw.splitlines(keepends=True)
    assert len(lines) >= 2, "need a mid-file record to corrupt"
    t = lines[1]
    lines[1] = t[: len(t) // 2] + bytes([t[len(t) // 2] ^ 1]) \
        + t[len(t) // 2 + 1:]
    open(path, "wb").write(b"".join(lines))


def _seed_node_state(tmp_path, node_id=1):
    """Build a single-node LMSNode, apply a few commands via direct WAL
    writes, and return its data_dir (node never started: no event loop)."""
    data_dir = str(tmp_path / f"node{node_id}")
    net = MemNetwork()
    node = LMSNode(node_id, {node_id: ""}, data_dir,
                   transport=net.transport_for(node_id))
    storage = node.node.core.storage
    storage.save_meta(3, None)
    for i in range(1, 5):
        storage.append_entries(i, [Entry(3, f"cmd-{i}")])
    storage.close()
    return data_dir


def test_recovery_fail_refuses_to_start_on_corrupt_wal(tmp_path):
    data_dir = _seed_node_state(tmp_path)
    _corrupt_midfile(os.path.join(data_dir, "raft_wal.jsonl"))
    net = MemNetwork()
    with pytest.raises(WALCorruption):
        LMSNode(1, {1: ""}, data_dir, transport=net.transport_for(1),
                storage_recovery="fail")


def test_recovery_rejoin_quarantines_and_boots_recovering(tmp_path):
    data_dir = _seed_node_state(tmp_path)
    wal = os.path.join(data_dir, "raft_wal.jsonl")
    _corrupt_midfile(wal)
    net = MemNetwork()
    m = Metrics()
    node = LMSNode(1, {1: ""}, data_dir, transport=net.transport_for(1),
                   metrics=m)  # default recovery="rejoin"
    assert node.recovering
    assert node.node.core.recovering
    assert m.snapshot()["gauges"]["storage_recovering"] == 1
    assert m.snapshot()["counters"]["wal_corrupt_records"] == 1
    # The damaged file is quarantined for forensics, not destroyed.
    assert os.path.exists(wal + ".corrupt")
    # Fresh, empty durable state: the node will re-sync from the leader.
    assert node.node.core.last_log_index == 0
    assert node.node.core.current_term == 0


def test_recovery_rejoin_on_corrupt_snapshot(tmp_path):
    data_dir = _seed_node_state(tmp_path)
    snap = os.path.join(data_dir, "lms_data.json")
    # Write a valid-looking but damaged v2 snapshot.
    SnapshotStore(snap).save(LMSState(), 0)
    raw = open(snap, "rb").read()
    open(snap, "wb").write(raw[:30] + bytes([raw[30] ^ 1]) + raw[31:])
    net = MemNetwork()
    m = Metrics()
    node = LMSNode(1, {1: ""}, data_dir, transport=net.transport_for(1),
                   metrics=m)
    assert node.recovering
    assert m.snapshot()["counters"]["snapshot_integrity_failures"] == 1
    assert os.path.exists(snap + ".corrupt")


def test_recovery_mode_survives_restart_via_marker(tmp_path):
    """A crash MID-recovery leaves clean (empty) stores behind; without a
    durable marker the next boot would resume normal voting before the
    re-sync finished."""
    data_dir = _seed_node_state(tmp_path)
    _corrupt_midfile(os.path.join(data_dir, "raft_wal.jsonl"))
    net = MemNetwork()
    node = LMSNode(1, {1: "", 2: "", 3: ""}, data_dir,
                   transport=net.transport_for(1))
    assert node.recovering
    marker = os.path.join(data_dir, "storage_recovering")
    assert os.path.exists(marker)
    # Simulated crash mid-recovery: a fresh boot on the SAME dir (whose
    # stores are now clean and empty) must still come up recovering.
    node2 = LMSNode(1, {1: "", 2: "", 3: ""}, data_dir,
                    transport=MemNetwork().transport_for(1))
    assert node2.recovering
    # Heal removes the marker; the boot after that is normal.
    node2._on_recovered()
    assert not os.path.exists(marker)
    node3 = LMSNode(1, {1: "", 2: "", 3: ""}, data_dir,
                    transport=MemNetwork().transport_for(1))
    assert not node3.recovering


def test_marker_resume_quarantines_blob_tree(tmp_path):
    """A crash between the WAL/snapshot renames and the uploads rename
    leaves the (possibly bit-flipped) blob tree live while the log loads
    clean — the corruption handler never runs on the next boot, so the
    marker-resume path must quarantine the blobs itself or the healed
    node serves corrupt bytes (blobs carry no checksums)."""
    data_dir = _seed_node_state(tmp_path)
    _corrupt_midfile(os.path.join(data_dir, "raft_wal.jsonl"))
    node = LMSNode(1, {1: "", 2: "", 3: ""}, data_dir,
                   transport=MemNetwork().transport_for(1))
    assert node.recovering
    # Recreate the crash window: a stale blob sits under the LIVE uploads
    # path while marker + clean stores say "resume recovery".
    blob = os.path.join(data_dir, "uploads", "materials", "week1.pdf")
    os.makedirs(os.path.dirname(blob), exist_ok=True)
    with open(blob, "wb") as fh:
        fh.write(b"possibly bit-flipped bytes")
    node2 = LMSNode(1, {1: "", 2: "", 3: ""}, data_dir,
                    transport=MemNetwork().transport_for(1))
    assert node2.recovering
    assert not os.path.exists(blob), (
        "marker-resume boot left the stale blob tree live"
    )
    quarantined = [
        d for d in os.listdir(data_dir)
        if d.startswith("uploads.corrupt")
        and os.path.exists(os.path.join(data_dir, d, "materials",
                                        "week1.pdf"))
    ]
    assert quarantined, "stale blob tree was deleted, not quarantined"


def test_storage_config_rejects_typod_policies(tmp_path):
    """`fsync = "on"` must fail at load, not silently disable fsync."""
    from distributed_lms_raft_llm_tpu.config import load_config

    cfg = tmp_path / "c.toml"
    cfg.write_text("[storage]\nfsync = \"on\"\n")
    with pytest.raises(ValueError, match="fsync"):
        load_config(str(cfg))
    cfg.write_text("[storage]\nrecovery = \"rejion\"\n")
    with pytest.raises(ValueError, match="recovery"):
        load_config(str(cfg))


def test_blob_sweep_spares_wire_named_dotblob_files(tmp_path):
    """Blob names come over the wire: the sweep matches only the exact
    temp prefixes, and those prefixes are reserved at the API."""
    root = str(tmp_path / "uploads")
    b = BlobStore(root)
    b.put("materials/.blobs-week3.pdf", b"acked upload")
    with pytest.raises(ValueError):
        b.put("materials/.blob.sneaky", b"x")
    with pytest.raises(ValueError):
        b.put("materials/.blobstream.sneaky", b"x")
    b2 = BlobStore(root)  # restart: sweep runs
    assert b2.get("materials/.blobs-week3.pdf") == b"acked upload"


def test_transient_snapshot_read_error_is_not_corruption(tmp_path):
    """EIO at load must propagate as OSError (fail the boot loudly), not
    masquerade as corruption and trigger rejoin-mode quarantine."""
    from distributed_lms_raft_llm_tpu.utils.diskfaults import FileSystem

    path = str(tmp_path / "lms_data.json")
    SnapshotStore(path).save(LMSState(), 3)

    class EIOFS(FileSystem):
        def read_bytes(self, p):
            raise OSError(5, "Input/output error")

    with pytest.raises(OSError) as exc:
        SnapshotStore(path, fs=EIOFS()).load()
    assert not isinstance(exc.value, SnapshotCorruption)


def test_recovering_node_does_not_campaign_or_vote(tmp_path):
    from distributed_lms_raft_llm_tpu.raft.messages import VoteRequest

    data_dir = _seed_node_state(tmp_path)
    _corrupt_midfile(os.path.join(data_dir, "raft_wal.jsonl"))
    net = MemNetwork()
    node = LMSNode(1, {1: "", 2: "", 3: ""}, data_dir,
                   transport=net.transport_for(1))
    import time

    core = node.node.core
    # Ticking far past every election deadline never starts a campaign.
    base = time.monotonic()
    for t in range(1, 50):
        core.tick(base + float(t))
    assert core.role.value == "follower" and core.outbox == []
    # And a live candidate gets no vote from discarded state.
    resp = core.on_vote_request(
        VoteRequest(term=9, candidate_id=2, last_log_index=9,
                    last_log_term=9), now=base + 100.0,
    )
    assert not resp.granted
