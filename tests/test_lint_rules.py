"""Per-rule lint tests over the fixture corpus.

Each fixture file under `tests/lint_fixtures/` carries `# EXPECT: <rule>`
markers on exactly the lines a rule must flag; everything else (the
known-good and the `# lint: disable=` suppressed examples) must stay
silent. The harness compares flagged-line sets to expected-line sets, so
each rule's hits, misses, AND suppression handling are pinned in one
assertion per fixture.

Rules are exercised via `rule.check(Source)` directly — path scoping
(`applies_to`) is tested separately, so the host-sync and slow-marker
fixtures don't need to masquerade as engine files or collectible tests.
"""

import re
import subprocess
import sys
from pathlib import Path

from distributed_lms_raft_llm_tpu.analysis import all_rules
from distributed_lms_raft_llm_tpu.analysis.core import Source
from distributed_lms_raft_llm_tpu.analysis.rules.async_blocking import (
    BlockingInAsyncRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.canonical_pspec import (
    CanonicalPSpecRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.guarded_by import (
    GuardedByRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.host_sync import (
    HostSyncInDispatchRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.orphan_task import (
    OrphanTaskRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.slow_marker import (
    SlowMarkerRule,
    audit,
)
from distributed_lms_raft_llm_tpu.analysis.rules.tracer_hygiene import (
    TracerHygieneRule,
)

REPO = Path(__file__).resolve().parent.parent
FIXTURES = REPO / "tests" / "lint_fixtures"

_EXPECT_RE = re.compile(r"#\s*EXPECT:\s*([A-Za-z0-9_,\- ]+)")


def expected_lines(src: Source, rule_name: str):
    out = set()
    for lineno, line in enumerate(src.lines, start=1):
        m = _EXPECT_RE.search(line)
        if m and rule_name in {n.strip() for n in m.group(1).split(",")}:
            out.add(lineno)
    return out


def run_rule(rule, fixture: str):
    src = Source(FIXTURES / fixture, root=REPO)
    flagged = {
        f.line for f in rule.check(src) if not src.suppressed(f.rule, f.line)
    }
    expected = expected_lines(src, rule.name)
    assert flagged == expected, (
        f"{rule.name} on {fixture}: flagged {sorted(flagged)} but expected "
        f"{sorted(expected)} (false positives: "
        f"{sorted(flagged - expected)}, misses: {sorted(expected - flagged)})"
    )
    return src


def test_canonical_pspec_fixture():
    run_rule(CanonicalPSpecRule(), "pspec.py")


def test_host_sync_fixture():
    run_rule(HostSyncInDispatchRule(), "host_sync.py")


def test_async_blocking_fixture():
    run_rule(BlockingInAsyncRule(), "async_blocking.py")


def test_orphan_task_fixture():
    run_rule(OrphanTaskRule(), "orphan_task.py")


def test_guarded_by_fixture():
    run_rule(GuardedByRule(), "guarded_by.py")


def test_tracer_hygiene_fixture():
    run_rule(TracerHygieneRule(), "tracer_hygiene.py")


def test_slow_marker_fixture():
    run_rule(SlowMarkerRule(), "markers.py")


# ------------------------------------------------------------- framework


def test_rule_registry_has_the_catalog():
    names = {r.name for r in all_rules()}
    assert {
        "canonical-pspec",
        "no-host-sync-in-dispatch",
        "no-blocking-in-async",
        "no-orphan-task",
        "guarded-by",
        "tracer-hygiene",
        "slow-marker",
    } <= names
    assert len(names) >= 6
    for rule in all_rules():
        assert rule.description, f"{rule.name} needs a description"


def test_suppression_forms(tmp_path):
    """Same-line, next-line, and file-level suppressions all work, and an
    unrelated rule name does not suppress."""
    code = (
        "from jax.sharding import PartitionSpec as P\n"
        "A = P(None, None)  # lint: disable=canonical-pspec\n"
        "# lint: disable-next=canonical-pspec\n"
        "B = P(None, None)\n"
        "C = P(None, None)  # lint: disable=some-other-rule\n"
        "D = P(None, None)\n"
    )
    path = tmp_path / "snippet.py"
    path.write_text(code)
    src = Source(path, root=tmp_path)
    rule = CanonicalPSpecRule()
    live = {
        f.line for f in rule.check(src) if not src.suppressed(f.rule, f.line)
    }
    assert live == {5, 6}

    path.write_text("# lint: disable-file=canonical-pspec\n" + code)
    src = Source(path, root=tmp_path)
    live = {
        f.line for f in rule.check(src) if not src.suppressed(f.rule, f.line)
    }
    assert live == set()


def test_path_scoping():
    """applies_to: host-sync only watches the engine dispatch modules;
    slow-marker only watches test files."""
    host = HostSyncInDispatchRule()
    assert host.applies_to("distributed_lms_raft_llm_tpu/engine/paged.py")
    assert host.applies_to("distributed_lms_raft_llm_tpu/engine/engine.py")
    assert not host.applies_to("distributed_lms_raft_llm_tpu/lms/service.py")
    marker = SlowMarkerRule()
    assert marker.applies_to("tests/test_engine.py")
    assert not marker.applies_to("tests/conftest.py")
    assert not marker.applies_to("distributed_lms_raft_llm_tpu/config.py")


def test_audit_markers_shim_still_works():
    """The folded-in rule keeps the audit() API the old script exposed;
    the real tests tree must be clean through it."""
    assert audit(REPO / "tests") == []


def test_cli_json_and_exit_codes(tmp_path):
    """`scripts/lint.py` is the same runner: clean tree -> exit 0 and
    clean JSON; a bad file -> exit 1 with the finding listed."""
    out = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "lint.py"), "--json"],
        capture_output=True, text=True, cwd=str(REPO), timeout=120,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert '"clean": true' in out.stdout

    listing = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "lint.py"), "--list-rules"],
        capture_output=True, text=True, cwd=str(REPO), timeout=120,
    )
    assert listing.returncode == 0
    assert "canonical-pspec" in listing.stdout

    bad = tmp_path / "bad.py"
    bad.write_text(
        "from jax.sharding import PartitionSpec as P\nA = P(None, None)\n"
    )
    failing = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "lint.py"), str(bad)],
        capture_output=True, text=True, cwd=str(REPO), timeout=120,
    )
    assert failing.returncode == 1
    assert "canonical-pspec" in failing.stderr
