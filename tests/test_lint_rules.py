"""Per-rule lint tests over the fixture corpus.

Each fixture file under `tests/lint_fixtures/` carries `# EXPECT: <rule>`
markers on exactly the lines a rule must flag; everything else (the
known-good and the `# lint: disable=` suppressed examples) must stay
silent. The harness compares flagged-line sets to expected-line sets, so
each rule's hits, misses, AND suppression handling are pinned in one
assertion per fixture.

Rules are exercised via `rule.check(Source)` directly — path scoping
(`applies_to`) is tested separately, so the host-sync and slow-marker
fixtures don't need to masquerade as engine files or collectible tests.
"""

import json
import re
import subprocess
import sys
from pathlib import Path

from distributed_lms_raft_llm_tpu.analysis import all_rules
from distributed_lms_raft_llm_tpu.analysis.core import Source
from distributed_lms_raft_llm_tpu.analysis.project import Project
from distributed_lms_raft_llm_tpu.analysis.rules.async_blocking import (
    BlockingInAsyncRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.atomicity_across_await import (
    AtomicityAcrossAwaitRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.await_under_lock import (
    AwaitUnderLockRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.cancellation_safety import (
    CancellationSafetyRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.lock_order import (
    LockOrderRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.config_consistency import (
    ConfigConsistencyRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.deadline_flow import (
    DeadlineFlowRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.guarded_by_flow import (
    GuardedByFlowRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.metrics_registry import (
    MetricsRegistryRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.canonical_pspec import (
    CanonicalPSpecRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.donation_safety import (
    DonationSafetyRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.dtype_flow import (
    DtypeFlowRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.program_inventory import (
    ProgramInventoryRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.pspec_flow import (
    PSpecFlowRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.durable_rename import (
    DurableRenameRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.guarded_by import (
    GuardedByRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.host_sync import (
    HostSyncInDispatchRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.orphan_task import (
    OrphanTaskRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.slow_marker import (
    SlowMarkerRule,
    audit,
)
from distributed_lms_raft_llm_tpu.analysis.rules.state_machine_determinism import (
    StateMachineDeterminismRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.trace_propagation import (
    TracePropagationRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.tracer_hygiene import (
    TracerHygieneRule,
)
from distributed_lms_raft_llm_tpu.analysis.rules.wire_taint import (
    WireTaintRule,
)

REPO = Path(__file__).resolve().parent.parent
FIXTURES = REPO / "tests" / "lint_fixtures"

_EXPECT_RE = re.compile(r"#\s*EXPECT:\s*([A-Za-z0-9_,\- ]+)")


def expected_lines(src: Source, rule_name: str):
    out = set()
    for lineno, line in enumerate(src.lines, start=1):
        m = _EXPECT_RE.search(line)
        if m and rule_name in {n.strip() for n in m.group(1).split(",")}:
            out.add(lineno)
    return out


def run_rule(rule, fixture: str):
    src = Source(FIXTURES / fixture, root=REPO)
    flagged = {
        f.line for f in rule.check(src) if not src.suppressed(f.rule, f.line)
    }
    expected = expected_lines(src, rule.name)
    assert flagged == expected, (
        f"{rule.name} on {fixture}: flagged {sorted(flagged)} but expected "
        f"{sorted(expected)} (false positives: "
        f"{sorted(flagged - expected)}, misses: {sorted(expected - flagged)})"
    )
    return src


def test_canonical_pspec_fixture():
    run_rule(CanonicalPSpecRule(), "pspec.py")


def test_host_sync_fixture():
    run_rule(HostSyncInDispatchRule(), "host_sync.py")


def test_async_blocking_fixture():
    run_rule(BlockingInAsyncRule(), "async_blocking.py")


def test_orphan_task_fixture():
    run_rule(OrphanTaskRule(), "orphan_task.py")


def test_guarded_by_fixture():
    run_rule(GuardedByRule(), "guarded_by.py")


def test_durable_rename_fixture():
    run_rule(DurableRenameRule(), "durable_rename.py")


def test_durable_rename_scopes_to_storage_modules():
    rule = DurableRenameRule()
    assert rule.applies_to("distributed_lms_raft_llm_tpu/raft/storage.py")
    assert rule.applies_to("distributed_lms_raft_llm_tpu/lms/persistence.py")
    # The seam itself and non-storage writers stay out of scope.
    assert not rule.applies_to(
        "distributed_lms_raft_llm_tpu/utils/diskfaults.py"
    )
    assert not rule.applies_to(
        "distributed_lms_raft_llm_tpu/models/convert.py"
    )


def test_tracer_hygiene_fixture():
    run_rule(TracerHygieneRule(), "tracer_hygiene.py")


def test_slow_marker_fixture():
    run_rule(SlowMarkerRule(), "markers.py")


# ---------------------------------------------------- semantic (project)


SEMANTIC = FIXTURES / "semantic"
ABSINT = FIXTURES / "absint"


def run_project_rule(rule, case: str, base: Path = SEMANTIC):
    """Run a ProjectRule over the mini-project at <base>/<case>/ and
    compare flagged lines per file to `# EXPECT: <rule>` markers in every
    .py AND .toml file of the case (suppressions applied, as run_lint
    does)."""
    case_dir = base / case
    sources = [
        Source(path, root=case_dir)
        for path in sorted(case_dir.rglob("*.py"))
    ]
    project = Project(sources, root=case_dir)
    by_rel = {src.rel: src for src in sources}
    flagged = {}
    for f in rule.check_project(project):
        src = by_rel.get(f.path)
        if src is not None and src.suppressed(f.rule, f.line):
            continue
        flagged.setdefault(f.path, set()).add(f.line)
    expected = {}
    for path in sorted(case_dir.rglob("*")):
        if path.suffix not in (".py", ".toml"):
            continue
        rel = path.relative_to(case_dir).as_posix()
        for lineno, line in enumerate(path.read_text().splitlines(), 1):
            m = _EXPECT_RE.search(line)
            if m and rule.name in {n.strip() for n in m.group(1).split(",")}:
                expected.setdefault(rel, set()).add(lineno)
    assert flagged == expected, (
        f"{rule.name} on semantic/{case}: flagged "
        f"{ {k: sorted(v) for k, v in flagged.items()} } but expected "
        f"{ {k: sorted(v) for k, v in expected.items()} }"
    )
    return project


def test_deadline_flow_fixture():
    # watch everything in the mini-project (the real default scopes to
    # the lms/ + serving/ request-path modules).
    run_project_rule(DeadlineFlowRule(watch_prefixes=("",)), "deadline_flow")


def test_metrics_registry_fixture():
    run_project_rule(
        MetricsRegistryRule(watch_prefixes=("",), exclude_rels=()),
        "metrics_registry",
    )


def test_config_consistency_fixture():
    run_project_rule(ConfigConsistencyRule(), "config_consistency")


def test_guarded_by_flow_fixture():
    run_project_rule(GuardedByFlowRule(), "guarded_by_flow")


def test_trace_propagation_fixture():
    # Same widening as deadline-flow: the real default scopes to the
    # lms/ + serving/ request-path modules.
    run_project_rule(
        TracePropagationRule(watch_prefixes=("",)), "trace_propagation"
    )


def test_state_machine_determinism_fixture():
    # Widened to the whole mini-project (the real default scopes to the
    # package). Pins: direct/transitive/callback-wired roots, the
    # unordered set-for, awaited egress, and the spawned-work +
    # sorted() + unreachable-function true negatives.
    run_project_rule(
        StateMachineDeterminismRule(watch_prefixes=("",)),
        "state_machine_determinism",
    )


def test_state_machine_determinism_witness_chain():
    """Findings carry the root-to-leaf call chain so a transitive leak
    (applier -> helper -> os.getpid) is actionable at the leaf."""
    case_dir = SEMANTIC / "state_machine_determinism"
    sources = [Source(p, root=case_dir)
               for p in sorted(case_dir.rglob("*.py"))]
    project = Project(sources, root=case_dir)
    rule = StateMachineDeterminismRule(watch_prefixes=("",))
    chained = [f for f in rule.check_project(project)
               if "_apply_indirect" in f.message]
    assert chained, "the transitive pid leak must be reported"
    assert any("_stash_pid" in f.message for f in chained), (
        "the witness chain must name the helper the effect lives in"
    )


def test_wire_taint_fixture():
    # Widened to the whole mini-project (the real default scopes to
    # lms/). Pins: raw-dict read, raw-reader laundering, for-scan, the
    # one-hop forward, the == secret compare and the request-derived
    # path sink — plus the verifier/exempt-hint/compare_digest/
    # sanitizer true negatives.
    run_project_rule(WireTaintRule(watch_prefixes=("",)), "wire_taint")


# ------------------------------------------- abstract interpretation


def test_pspec_flow_fixture():
    run_project_rule(
        PSpecFlowRule(watch_prefixes=("",)), "pspec_flow", base=ABSINT
    )


def test_plane_table_fixture():
    """The table-declared half of pspec-flow: a producer that disagrees
    with the module-level plane table fails lint (the reversion pin for
    re-replicating a tp-sharded KV plane); name-keyed producers resolving
    through the table subscript stay silent."""
    project = run_project_rule(
        PSpecFlowRule(watch_prefixes=("",)), "plane_table", base=ABSINT
    )
    from distributed_lms_raft_llm_tpu.analysis import absint as ai
    tables = ai.plane_tables(project)
    assert tables["PLANE_SPECS"]["cache.k"] == "P(None, None, 'tp')"
    # The non-spec dict (string values) must not masquerade as policy.
    assert "CLASSIFICATION" not in tables


def test_donation_safety_fixture():
    run_project_rule(
        DonationSafetyRule(watch_prefixes=("",)), "donation_safety",
        base=ABSINT,
    )


def test_dtype_flow_fixture():
    run_project_rule(
        DtypeFlowRule(watch_prefixes=("",)), "dtype_flow", base=ABSINT
    )


def test_program_inventory_fixture():
    run_project_rule(
        ProgramInventoryRule(scan_prefixes=("",), manifest_rel="inventory.py"),
        "program_inventory", base=ABSINT,
    )


# --------------------------------------------------------- concurrency

CONC = FIXTURES / "concurrency"


def test_atomicity_across_await_fixture():
    # Annotated + inferred shared attrs, the true-suspension model
    # (awaiting a never-suspending coroutine is not a window), the
    # re-read/blind-store true negatives, and a sanctioned last-wins.
    run_project_rule(
        AtomicityAcrossAwaitRule(), "atomicity_across_await", base=CONC
    )


def test_lock_order_fixture():
    # Direct re-entrance, re-entrance through a callee's lockset, the
    # PR-13 callback shape (dynamic call under a lock + registered
    # callback whose lockset re-enters it through a sibling instance's
    # property), and an A->B / B->A acquisition-order cycle; RLock
    # re-entry and the suppressed case stay silent.
    run_project_rule(LockOrderRule(), "lock_order", base=CONC)


def test_await_under_lock_fixture():
    # Suspension, blocking intrinsic, and a call into a BLOCKING-effect
    # path, each under a threading lock; asyncio.Lock and the
    # snapshot-then-await shape stay silent.
    run_project_rule(AwaitUnderLockRule(), "await_under_lock", base=CONC)


def test_cancellation_safety_fixture():
    # Per-file rule, but rooted at the case dir so the rel does not
    # carry the tests/ prefix (which scopes out the finally check).
    case = CONC / "cancellation_safety"
    src = Source(case / "worker.py", root=case)
    rule = CancellationSafetyRule()
    flagged = {
        f.line for f in rule.check(src) if not src.suppressed(f.rule, f.line)
    }
    expected = expected_lines(src, rule.name)
    assert flagged == expected, (
        f"cancellation-safety: flagged {sorted(flagged)} but expected "
        f"{sorted(expected)} (false positives: {sorted(flagged - expected)}, "
        f"misses: {sorted(expected - flagged)})"
    )


def test_cancellation_safety_finally_check_scopes_out_tests():
    """The same file flips between flagged and silent purely on whether
    its rel sits under tests/ — test teardown coroutines run under
    asyncio.run with no canceller, so their finally blocks never race a
    pending CancelledError."""
    case = CONC / "cancellation_safety"
    path = case / "teardown_in_tests.py"
    rule = CancellationSafetyRule()

    as_project_file = Source(path, root=case)
    assert {f.line for f in rule.check(as_project_file)} == expected_lines(
        as_project_file, rule.name
    ), "rooted outside tests/, the finally await must be flagged"

    as_test_file = Source(path, root=REPO)
    assert as_test_file.rel.startswith("tests/")
    assert rule.check(as_test_file) == [], (
        "rooted under tests/, the finally check must scope out"
    )


def test_subset_runs_scope_concurrency_reports_not_analysis(tmp_path):
    """The --changed contract for the concurrency rules: a subset run
    still analyzes the FULL tree (locksets over half a repo prove
    nothing) but reports only into the requested files."""
    from distributed_lms_raft_llm_tpu.analysis import run_lint

    pkg = tmp_path / "distributed_lms_raft_llm_tpu"
    pkg.mkdir()
    (tmp_path / "scripts").mkdir()
    (tmp_path / "tests").mkdir()
    reenter = (
        "import threading\n\n\n"
        "class C:\n"
        "    def __init__(self):\n"
        "        self._lock = threading.Lock()\n\n"
        "    def reenter(self):\n"
        "        with self._lock:\n"
        "            with self._lock:\n"
        "                pass\n"
    )
    (pkg / "a.py").write_text(reenter)
    (pkg / "b.py").write_text(reenter.replace("class C", "class D"))

    full = run_lint(rules=[LockOrderRule()], root=tmp_path)
    assert {f.path for f in full} == {
        "distributed_lms_raft_llm_tpu/a.py",
        "distributed_lms_raft_llm_tpu/b.py",
    }, "full runs must report the re-entrance in both files"

    scoped = run_lint(
        paths=[pkg / "b.py"], rules=[LockOrderRule()], root=tmp_path
    )
    assert {f.path for f in scoped} == {"distributed_lms_raft_llm_tpu/b.py"}, (
        "a subset run must report only into the requested files"
    )


def test_same_line_emissions_are_all_checked(tmp_path):
    """Two metric emissions sharing one source line must BOTH be checked
    (the nested-def dedup collapses on (line, col), never on line alone)."""
    (tmp_path / "metrics_registry.py").write_text(
        "def counter(name, help):\n    return name\n"
        'GOOD = counter("good_series", "doc")\n'
    )
    (tmp_path / "emit.py").write_text(
        "class S:\n"
        "    def go(self, metrics):\n"
        '        metrics.inc("good_series"); metrics.inc("bogus_series")\n'
    )
    sources = [Source(p, root=tmp_path)
               for p in sorted(tmp_path.glob("*.py"))]
    project = Project(sources, root=tmp_path)
    rule = MetricsRegistryRule(watch_prefixes=("",), exclude_rels=())
    findings = [f for f in rule.check_project(project)
                if "bogus_series" in f.message]
    assert len(findings) == 1, [f.format() for f in
                                rule.check_project(project)]


def test_deadline_flow_default_scope_is_request_path():
    """The registered instance watches lms/ + serving/, not raft/ (whose
    protocol timeouts are consensus-liveness knobs, not client budgets)."""
    rule = next(r for r in all_rules() if r.name == "deadline-flow")
    assert any(p.endswith("/lms/") for p in rule.watch_prefixes)
    assert any(p.endswith("/serving/") for p in rule.watch_prefixes)
    assert not any(p.endswith("/raft/") for p in rule.watch_prefixes)


# ------------------------------------------------------------- framework


def test_rule_registry_has_the_catalog():
    names = {r.name for r in all_rules()}
    assert {
        "canonical-pspec",
        "no-host-sync-in-dispatch",
        "no-blocking-in-async",
        "no-orphan-task",
        "guarded-by",
        "tracer-hygiene",
        "slow-marker",
    } <= names
    assert len(names) >= 6
    for rule in all_rules():
        assert rule.description, f"{rule.name} needs a description"


def test_suppression_forms(tmp_path):
    """Same-line, next-line, and file-level suppressions all work, and an
    unrelated rule name does not suppress."""
    code = (
        "from jax.sharding import PartitionSpec as P\n"
        "A = P(None, None)  # lint: disable=canonical-pspec\n"
        "# lint: disable-next=canonical-pspec\n"
        "B = P(None, None)\n"
        "C = P(None, None)  # lint: disable=some-other-rule\n"
        "D = P(None, None)\n"
    )
    path = tmp_path / "snippet.py"
    path.write_text(code)
    src = Source(path, root=tmp_path)
    rule = CanonicalPSpecRule()
    live = {
        f.line for f in rule.check(src) if not src.suppressed(f.rule, f.line)
    }
    assert live == {5, 6}

    path.write_text("# lint: disable-file=canonical-pspec\n" + code)
    src = Source(path, root=tmp_path)
    live = {
        f.line for f in rule.check(src) if not src.suppressed(f.rule, f.line)
    }
    assert live == set()


def test_path_scoping():
    """applies_to: host-sync only watches the engine dispatch modules;
    slow-marker only watches test files."""
    host = HostSyncInDispatchRule()
    assert host.applies_to("distributed_lms_raft_llm_tpu/engine/paged.py")
    assert host.applies_to("distributed_lms_raft_llm_tpu/engine/engine.py")
    assert not host.applies_to("distributed_lms_raft_llm_tpu/lms/service.py")
    marker = SlowMarkerRule()
    assert marker.applies_to("tests/test_engine.py")
    assert not marker.applies_to("tests/conftest.py")
    assert not marker.applies_to("distributed_lms_raft_llm_tpu/config.py")


def test_audit_markers_shim_still_works():
    """The folded-in rule keeps the audit() API the old script exposed;
    the real tests tree must be clean through it."""
    assert audit(REPO / "tests") == []


def test_cli_json_and_exit_codes(tmp_path):
    """`scripts/lint.py` is the same runner: clean tree -> exit 0 and
    clean JSON; a bad file -> exit 1 with the finding listed."""
    out = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "lint.py"), "--json"],
        capture_output=True, text=True, cwd=str(REPO), timeout=120,
    )
    assert out.returncode == 0, out.stdout + out.stderr
    assert '"clean": true' in out.stdout

    listing = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "lint.py"), "--list-rules"],
        capture_output=True, text=True, cwd=str(REPO), timeout=120,
    )
    assert listing.returncode == 0
    assert "canonical-pspec" in listing.stdout

    bad = tmp_path / "bad.py"
    bad.write_text(
        "from jax.sharding import PartitionSpec as P\nA = P(None, None)\n"
    )
    failing = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "lint.py"), str(bad)],
        capture_output=True, text=True, cwd=str(REPO), timeout=120,
    )
    assert failing.returncode == 1
    assert "canonical-pspec" in failing.stderr


def test_cli_rules_selection_and_baseline(tmp_path):
    """--rules takes comma lists; --baseline suppresses recorded findings
    and fails only on NEW ones (the incremental-adoption workflow)."""
    lint = str(REPO / "scripts" / "lint.py")
    bad = tmp_path / "bad.py"
    bad.write_text(
        "from jax.sharding import PartitionSpec as P\n"
        "A = P(None, None)\n"
    )

    unknown = subprocess.run(
        [sys.executable, lint, "--rules", "canonical-pspec,nope"],
        capture_output=True, text=True, cwd=str(REPO), timeout=120,
    )
    assert unknown.returncode == 2
    assert "unknown rule" in unknown.stderr

    baseline = tmp_path / "baseline.json"
    wrote = subprocess.run(
        [sys.executable, lint, "--rules", "canonical-pspec",
         "--write-baseline", str(baseline), str(bad)],
        capture_output=True, text=True, cwd=str(REPO), timeout=120,
    )
    assert wrote.returncode == 0, wrote.stdout + wrote.stderr
    doc = json.loads(baseline.read_text())
    assert doc["schema"] == "dlrl-lint/1"
    assert len(doc["findings"]) == 1

    # Same tree + baseline: clean.
    clean = subprocess.run(
        [sys.executable, lint, "--rules", "canonical-pspec",
         "--baseline", str(baseline), "--json", str(bad)],
        capture_output=True, text=True, cwd=str(REPO), timeout=120,
    )
    assert clean.returncode == 0, clean.stdout + clean.stderr
    out = json.loads(clean.stdout)
    assert out["clean"] and out["baselined"] == 1

    # A NEW finding still fails; fixing the old one reports it stale.
    bad.write_text(
        "from jax.sharding import PartitionSpec as P\n"
        "B = P('x', None)\n"
    )
    fresh = subprocess.run(
        [sys.executable, lint, "--rules", "canonical-pspec",
         "--baseline", str(baseline), "--json", str(bad)],
        capture_output=True, text=True, cwd=str(REPO), timeout=120,
    )
    assert fresh.returncode == 1
    out = json.loads(fresh.stdout)
    assert not out["clean"] and out["baselined"] == 0
    assert len(out["stale_baseline"]) == 1


def test_cli_sarif_round_trips_the_json_findings(tmp_path):
    """--sarif is the same finding set as --json rendered as SARIF 2.1.0:
    every (rule, path, line, message) survives the mapping, exit codes
    still reflect findings, and the two flags are mutually exclusive."""
    lint = str(REPO / "scripts" / "lint.py")
    bad = tmp_path / "bad.py"
    bad.write_text(
        "from jax.sharding import PartitionSpec as P\n"
        "A = P(None, None)\n"
        "B = P('x', None)\n"
    )
    args = [sys.executable, lint, "--rules", "canonical-pspec", str(bad)]
    as_json = subprocess.run(
        args[:2] + ["--json"] + args[2:],
        capture_output=True, text=True, cwd=str(REPO), timeout=120,
    )
    as_sarif = subprocess.run(
        args[:2] + ["--sarif"] + args[2:],
        capture_output=True, text=True, cwd=str(REPO), timeout=120,
    )
    assert as_json.returncode == 1 and as_sarif.returncode == 1

    doc = json.loads(as_sarif.stdout)
    assert doc["version"] == "2.1.0"
    assert "sarif-schema-2.1.0" in doc["$schema"]
    (run,) = doc["runs"]
    driver = run["tool"]["driver"]
    assert driver["name"] == "dlrl-lint"
    assert {r["id"] for r in driver["rules"]} == {"canonical-pspec"}
    assert all(r["shortDescription"]["text"] for r in driver["rules"])

    def key(result):
        (loc,) = result["locations"]
        phys = loc["physicalLocation"]
        assert phys["artifactLocation"]["uriBaseId"] == "%SRCROOT%"
        return (
            result["ruleId"],
            phys["artifactLocation"]["uri"],
            phys["region"]["startLine"],
            result["message"]["text"],
        )

    sarif_keys = sorted(key(r) for r in run["results"])
    assert all(r["level"] == "error" for r in run["results"])
    json_keys = sorted(
        (f["rule"], f["path"], f["line"], f["message"])
        for f in json.loads(as_json.stdout)["findings"]
    )
    assert sarif_keys == json_keys and len(sarif_keys) == 2

    # A clean scope emits a valid empty run and exits 0.
    clean = subprocess.run(
        [sys.executable, lint, "--rules", "canonical-pspec", "--sarif",
         str(REPO / "scripts")],
        capture_output=True, text=True, cwd=str(REPO), timeout=120,
    )
    assert clean.returncode == 0, clean.stdout + clean.stderr
    assert json.loads(clean.stdout)["runs"][0]["results"] == []

    both = subprocess.run(
        args[:2] + ["--json", "--sarif"] + args[2:],
        capture_output=True, text=True, cwd=str(REPO), timeout=120,
    )
    assert both.returncode == 2
    assert "mutually exclusive" in both.stderr
