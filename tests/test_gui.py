"""Headless GUI tests: drive every LMSApp screen through fake Tk widgets.

There is no display in CI, so `client.gui` is written to touch the toolkit
only via its module attributes (`gui.tk`, `gui.messagebox`,
`gui.filedialog`); these tests substitute a minimal widget fake that
records the tree, lets tests click buttons / fill entries / select list
rows, and asserts the RPCs the screens issue against a scripted client.

Covers the reference screen inventory (SURVEY.md C11) including the D8
regression: downloading saves the *selected* entry, not entries[0].
"""

from __future__ import annotations

import types

import pytest

from distributed_lms_raft_llm_tpu.client import gui
from distributed_lms_raft_llm_tpu.proto import lms_pb2


# --------------------------------------------------------------- fake toolkit


class FakeWidget:
    def __init__(self, master=None, **kw):
        self.master = master
        self.kw = kw
        self.children = []
        self.destroyed = False
        if master is not None:
            master.children.append(self)

    def pack(self, **kw):
        return self

    def winfo_children(self):
        return list(self.children)

    def destroy(self):
        self.destroyed = True
        if self.master is not None and self in self.master.children:
            self.master.children.remove(self)
        for child in list(self.children):
            child.destroy()


class FakeTk(FakeWidget):
    def __init__(self):
        super().__init__(None)

    def title(self, *_):
        pass

    def geometry(self, *_):
        pass

    def after(self, _ms, fn):
        fn()

    def mainloop(self):
        pass


class FakeFrame(FakeWidget):
    pass


class FakeLabel(FakeWidget):
    pass


class FakeButton(FakeWidget):
    def invoke(self):
        self.kw["command"]()


class FakeEntry(FakeWidget):
    def __init__(self, master=None, **kw):
        super().__init__(master, **kw)
        self.value = ""

    def get(self):
        return self.value

    def insert(self, _index, text):
        self.value += text

    def delete(self, *_):
        self.value = ""


class FakeText(FakeWidget):
    def __init__(self, master=None, **kw):
        super().__init__(master, **kw)
        self.value = ""

    def get(self, *_):
        return self.value

    def insert(self, _index, text):
        self.value += text


class FakeListbox(FakeWidget):
    def __init__(self, master=None, **kw):
        super().__init__(master, **kw)
        self.items = []
        self._selection = ()

    def insert(self, _index, item):
        self.items.append(item)

    def curselection(self):
        return self._selection

    def selection_set(self, index):
        self._selection = (index,)


class FakeVar:
    def __init__(self, master=None, value=""):
        self.value = value

    def get(self):
        return self.value

    def set(self, value):
        self.value = value


class FakeRadiobutton(FakeWidget):
    def invoke(self):
        self.kw["variable"].set(self.kw["value"])


def make_fake_tk():
    ns = types.SimpleNamespace(
        Tk=FakeTk,
        Frame=FakeFrame,
        Label=FakeLabel,
        Button=FakeButton,
        Entry=FakeEntry,
        Text=FakeText,
        Listbox=FakeListbox,
        Radiobutton=FakeRadiobutton,
        StringVar=FakeVar,
        BOTH="both",
        X="x",
        END="end",
        LEFT="left",
        RIGHT="right",
        BOTTOM="bottom",
    )
    return ns


class Recorder:
    def __init__(self):
        self.calls = []
        self.preset = {}

    def __getattr__(self, name):
        def record(*args, **kw):
            self.calls.append((name, args))
            return self.preset.get(name)

        return record


# ------------------------------------------------------------- widget helpers


def widgets(root, cls):
    out = []
    queue = [root]
    while queue:
        w = queue.pop(0)
        if isinstance(w, cls):
            out.append(w)
        queue.extend(w.children)
    return out


def button(app, text):
    for b in widgets(app.body, FakeButton):
        if b.kw.get("text") == text:
            return b
    raise AssertionError(
        f"no button {text!r}; have "
        f"{[b.kw.get('text') for b in widgets(app.body, FakeButton)]}"
    )


def entries(app):
    return widgets(app.body, FakeEntry)


# ---------------------------------------------------------------- fake client


def entry(**kw):
    return lms_pb2.DataEntry(**kw)


class ScriptedClient:
    """LMSClient stand-in: records mutations, serves canned reads."""

    def __init__(self, role="student"):
        self.role_after_login = role
        self.role = None
        self.token = None
        self.calls = []
        self.materials = [
            entry(filename="week1.pdf", instructor="prof", file=b"AAA"),
            entry(filename="week2.pdf", instructor="prof", file=b"BBB"),
        ]
        self.assignments = [
            entry(id="alice", filename="hw.pdf", file=b"HW"),
            entry(id="bob", filename="hw2.pdf", file=b"HW2"),
        ]
        self.queries = [entry(id="alice", data="what is Raft?")]

    def register(self, username, password, role):
        self.calls.append(("register", username, role))
        return lms_pb2.RegisterResponse(success=True, message="registered")

    def login(self, username, password):
        self.calls.append(("login", username))
        self.role = self.role_after_login
        self.token = "tok"
        return True

    def logout(self):
        self.calls.append(("logout",))
        self.role = self.token = None
        return True

    def course_materials(self):
        return self.materials

    def student_assignments(self):
        return self.assignments

    def unanswered_queries(self):
        return self.queries

    def instructor_responses(self):
        return [entry(data="read chapter 3")]

    def my_grade(self):
        return "A"

    def grade(self, student, grade):
        self.calls.append(("grade", student, grade))
        return lms_pb2.GradeResponse(success=True, message=f"graded {student}")

    def respond_to_query(self, student, response):
        self.calls.append(("respond", student, response))
        return True

    def ask_llm(self, query):
        self.calls.append(("ask_llm", query))
        return lms_pb2.QueryResponse(success=True, response="42")

    def ask_instructor(self, query):
        self.calls.append(("ask_instructor", query))
        return True

    def upload_assignment(self, name, content):
        self.calls.append(("upload_assignment", name, content))
        return True

    def upload_course_material(self, name, content):
        self.calls.append(("upload_material", name, content))
        return True


# -------------------------------------------------------------------- fixture


@pytest.fixture()
def app(monkeypatch):
    fake_tk = make_fake_tk()
    msg = Recorder()
    dlg = Recorder()
    dlg.preset = {}
    monkeypatch.setattr(gui, "tk", fake_tk)
    monkeypatch.setattr(gui, "messagebox", msg)
    monkeypatch.setattr(gui, "filedialog", dlg)
    client = ScriptedClient()
    application = gui.LMSApp(client, root=FakeTk(), background=False)
    application.msg = msg
    application.dlg = dlg
    yield application


def login_as(app, role):
    app.client.role_after_login = role
    button(app, "Login").invoke()
    user, pw = entries(app)[:2]
    user.insert(0, "u")
    pw.insert(0, "p")
    button(app, "Login").invoke()


# ----------------------------------------------------------------------- tests


def test_welcome_screen_has_entry_points(app):
    for label in ("Login", "Register", "Quit"):
        button(app, label)


def test_register_flow(app):
    button(app, "Register").invoke()
    user, pw = entries(app)[:2]
    user.insert(0, "newbie")
    pw.insert(0, "secret")
    # pick the instructor radio
    for rb in widgets(app.body, FakeRadiobutton):
        if rb.kw.get("value") == "instructor":
            rb.invoke()
    button(app, "Register").invoke()
    assert ("register", "newbie", "instructor") in app.client.calls
    assert any(c[0] == "showinfo" for c in app.msg.calls)
    # success returns to the welcome screen
    button(app, "Login")


def test_register_requires_fields(app):
    button(app, "Register").invoke()
    button(app, "Register").invoke()  # empty submit
    assert any(c[0] == "showwarning" for c in app.msg.calls)
    assert not app.client.calls


def test_student_journey(app, tmp_path):
    login_as(app, "student")
    button(app, "View course materials")  # student menu rendered

    # materials list shows both files
    button(app, "View course materials").invoke()
    box = widgets(app.body, FakeListbox)[0]
    assert len(box.items) == 2 and "week1.pdf" in box.items[0]
    button(app, "Back").invoke()

    # D8 regression: download saves the SELECTED entry (index 1)
    button(app, "Download course material").invoke()
    box = widgets(app.body, FakeListbox)[0]
    box.selection_set(1)
    target = tmp_path / "week2.pdf"
    app.dlg.preset["asksaveasfilename"] = str(target)
    button(app, "Save selected").invoke()
    assert target.read_bytes() == b"BBB"

    button(app, "Back").invoke()
    button(app, "View my grade").invoke()
    labels = [w.kw.get("text") for w in widgets(app.body, FakeLabel)]
    assert "A" in labels
    button(app, "Back").invoke()

    # ask the LLM
    button(app, "Ask a query").invoke()
    widgets(app.body, FakeText)[0].insert(0, "what is a mesh?")
    button(app, "Submit").invoke()
    assert ("ask_llm", "what is a mesh?") in app.client.calls
    assert any(c == ("showinfo", (gui.TITLE, "42")) for c in app.msg.calls)

    # ask the instructor instead
    for rb in widgets(app.body, FakeRadiobutton):
        if rb.kw.get("value") == "instructor":
            rb.invoke()
    button(app, "Submit").invoke()
    assert ("ask_instructor", "what is a mesh?") in app.client.calls
    button(app, "Back").invoke()

    # typed-text assignment upload goes through the PDF synthesizer
    button(app, "Upload assignment").invoke()
    widgets(app.body, FakeText)[0].insert(0, "my essay")
    button(app, "Upload typed text as PDF").invoke()
    upload = next(c for c in app.client.calls if c[0] == "upload_assignment")
    assert upload[1] == "typed.pdf" and upload[2].startswith(b"%PDF")
    button(app, "Back").invoke()

    button(app, "View instructor responses").invoke()
    box = widgets(app.body, FakeListbox)[0]
    assert box.items == ["read chapter 3"]
    button(app, "Back").invoke()

    button(app, "Logout").invoke()
    assert ("logout",) in app.client.calls
    button(app, "Register")  # back on welcome


def test_instructor_grading_and_responses(app):
    login_as(app, "instructor")

    button(app, "View & grade assignments").invoke()
    box = widgets(app.body, FakeListbox)[0]
    assert len(box.items) == 2
    box.selection_set(1)  # bob
    entries(app)[-1].insert(0, "B+")
    button(app, "Submit grade").invoke()
    assert ("grade", "bob", "B+") in app.client.calls
    button(app, "Back").invoke()

    button(app, "View unanswered queries").invoke()
    box = widgets(app.body, FakeListbox)[0]
    assert "what is Raft?" in box.items[0]
    button(app, "Back").invoke()

    button(app, "Respond to a query").invoke()
    widgets(app.body, FakeListbox)[0].selection_set(0)
    widgets(app.body, FakeText)[0].insert(0, "log replication")
    button(app, "Send response").invoke()
    assert ("respond", "alice", "log replication") in app.client.calls


def test_grade_requires_selection(app):
    login_as(app, "instructor")
    button(app, "View & grade assignments").invoke()
    button(app, "Submit grade").invoke()  # nothing selected
    assert any(c[0] == "showwarning" for c in app.msg.calls)
    assert not any(c[0] == "grade" for c in app.client.calls)


def test_rpc_failure_surfaces_as_error_dialog(app):
    def boom():
        raise RuntimeError("leader lost")

    app.client.my_grade = boom
    login_as(app, "student")
    button(app, "View my grade").invoke()
    assert any(c[0] == "showerror" for c in app.msg.calls)
