"""The mypy strict-subset gate wired into scripts/lint.py --types.

The runtime container intentionally ships without mypy (the serving stack
does not need it), so the gate must degrade to an explicit skip there —
and actually enforce when mypy is present (CI images / dev machines).
"""

import importlib.util
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent


def _lint_module():
    spec = importlib.util.spec_from_file_location(
        "dlrl_lint_cli", REPO / "scripts" / "lint.py"
    )
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_typed_subset_targets_exist():
    lint = _lint_module()
    for target in lint.TYPED_SUBSET:
        assert (REPO / target).exists(), target
    # The ISSUE's contract: these four surfaces are type-gated.
    joined = " ".join(lint.TYPED_SUBSET)
    for needle in ("raft/core.py", "utils/resilience.py",
                   "utils/guards.py", "analysis"):
        assert needle in joined, needle


def test_type_gate_skips_cleanly_without_mypy(capsys):
    lint = _lint_module()
    have_mypy = importlib.util.find_spec("mypy") is not None
    rc = lint.run_type_gate()
    captured = capsys.readouterr()
    if have_mypy:
        # With mypy installed the gate must actually pass on the
        # annotated subset (this is the enforcing path on CI images).
        assert rc == 0, captured.out + captured.err
        assert "types ok" in captured.out
    else:
        assert rc == 0
        assert "skipping the type gate" in captured.err


def test_mypy_config_present():
    text = (REPO / "pyproject.toml").read_text()
    assert "[tool.mypy]" in text
    assert "disallow_untyped_defs" in text


@pytest.mark.skipif(importlib.util.find_spec("mypy") is None,
                    reason="mypy not installed in this image")
def test_type_gate_enforces_with_mypy():
    import subprocess

    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "lint.py"), "--types",
         str(REPO / "scripts" / "lint.py")],
        capture_output=True, text=True, cwd=str(REPO), timeout=300,
    )
    assert "types" in proc.stdout + proc.stderr
