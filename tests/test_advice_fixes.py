"""Tests for the advisor-flagged correctness/security fixes:

1. tutoring port rejects unsigned queries when an auth key is configured;
2. a node serving committed metadata fetches a missing blob from a peer
   instead of returning empty bytes;
3. a retried mutation carrying the same request_id applies exactly once;
4. a Raft node whose snapshot is ahead of its WAL fails fast instead of
   silently re-applying committed entries onto snapshot state;
5. passwords are salted-KDF hashed, salt carried in the replicated command.
"""

import asyncio

import grpc
import pytest

from distributed_lms_raft_llm_tpu.lms.persistence import BlobStore
from distributed_lms_raft_llm_tpu.lms.service import (
    FileTransferServicer,
    LMSServicer,
)
from distributed_lms_raft_llm_tpu.lms.state import LMSState, hash_password
from distributed_lms_raft_llm_tpu.proto import lms_pb2, rpc
from distributed_lms_raft_llm_tpu.raft.core import RaftCore
from distributed_lms_raft_llm_tpu.serving.tutoring_server import TutoringService
from distributed_lms_raft_llm_tpu.utils import auth
from distributed_lms_raft_llm_tpu.utils.metrics import Metrics


# ------------------------------------------------------- 1. tutoring auth


class _EchoQueue:
    async def submit(self, prompt: str, deadline=None, span=None) -> str:
        return "the tutor's answer"


def test_tutoring_rejects_unsigned_queries():
    svc = TutoringService(_EchoQueue(), Metrics(), auth_key="secret-key")

    async def run():
        bogus = await svc.GetLLMAnswer(
            lms_pb2.QueryRequest(token="some-session-token", query="q"), None
        )
        assert not bogus.success
        assert "Unauthorized" in bogus.response

        signed = await svc.GetLLMAnswer(
            lms_pb2.QueryRequest(
                token=auth.sign_query("secret-key", "q"), query="q"
            ),
            None,
        )
        assert signed.success
        assert signed.response == "the tutor's answer"

        # Ticket is bound to the query text: replaying it for another
        # query fails.
        replay = await svc.GetLLMAnswer(
            lms_pb2.QueryRequest(
                token=auth.sign_query("secret-key", "q"), query="other"
            ),
            None,
        )
        assert not replay.success

        # Tickets expire: an observed one can't be replayed forever.
        stale = auth.sign_query(
            "secret-key", "q", now=1000.0 - auth.TICKET_TTL_S - 1
        )
        old = await svc.GetLLMAnswer(
            lms_pb2.QueryRequest(token=stale, query="q"), None
        )
        assert not old.success

    asyncio.run(run())


def test_ticket_expiry_is_authenticated():
    good = auth.sign_query("k", "q", now=1000.0)
    assert auth.verify_query("k", "q", good, now=1000.0)
    assert not auth.verify_query("k", "q", good, now=1000.0 + auth.TICKET_TTL_S)
    # Bearer can't extend the expiry: it is inside the MAC.
    expiry, _, mac = good.partition(":")
    forged = f"{int(expiry) + 9999}:{mac}"
    assert not auth.verify_query("k", "q", forged, now=1000.0)
    assert not auth.verify_query("k", "q", "garbage", now=1000.0)
    assert not auth.verify_query("k", "q", "", now=1000.0)


def test_tutoring_without_key_keeps_reference_behavior():
    svc = TutoringService(_EchoQueue(), Metrics(), auth_key=None)

    async def run():
        resp = await svc.GetLLMAnswer(
            lms_pb2.QueryRequest(token="anything", query="q"), None
        )
        assert resp.success

    asyncio.run(run())


# ------------------------------------------------- 2. blob fetch-on-miss


class _FakeNode:
    leader_id = 1
    is_leader = False


def test_blob_fetch_on_miss_heals_from_peer(tmp_path):
    src = BlobStore(str(tmp_path / "peer"))
    src.put("materials/notes.pdf", b"%PDF real content")

    async def run():
        server = grpc.aio.server()
        rpc.add_FileTransferServiceServicer_to_server(
            FileTransferServicer(src), server
        )
        port = server.add_insecure_port("127.0.0.1:0")
        await server.start()
        try:
            local = BlobStore(str(tmp_path / "local"))
            svc = LMSServicer(
                _FakeNode(),
                LMSState(),
                local,
                peer_addresses={1: f"127.0.0.1:{port}"},
                self_id=2,
            )
            content = await svc._blob("materials/notes.pdf")
            assert content == b"%PDF real content"
            # The miss healed permanently: the blob is now local.
            assert local.get("materials/notes.pdf") == b"%PDF real content"
            # A blob nobody has comes back empty (logged, not fatal) and is
            # negative-cached so the next read skips the peer sweep.
            assert await svc._blob("materials/ghost.pdf") == b""
            assert svc._blob_missing.get("materials/ghost.pdf", 0) > 0
            assert await svc._blob("materials/ghost.pdf") == b""
            # A traversal path from a hostile peer is found=False, not an
            # unhandled server error.
            ch = grpc.aio.insecure_channel(f"127.0.0.1:{port}")
            stub = rpc.FileTransferServiceStub(ch)
            resp = await stub.FetchFile(
                lms_pb2.FetchFileRequest(path="../../etc/passwd"), timeout=5
            )
            assert not resp.found
            await ch.close()
        finally:
            await server.stop(None)

    asyncio.run(run())


# ------------------------------------------------- 3. request-id dedup


def test_duplicate_request_id_applies_once():
    state = LMSState()
    args = {"username": "amy", "query": "what is raft?", "request_id": "r1"}
    state.apply("AskQuery", dict(args))
    state.apply("AskQuery", dict(args))  # client retry, same id
    assert len(state.data["queries"]["amy"]) == 1
    # A different id is a genuinely new mutation.
    state.apply(
        "AskQuery",
        {"username": "amy", "query": "what is raft?", "request_id": "r2"},
    )
    assert len(state.data["queries"]["amy"]) == 2
    # Commands without an id (old clients) are never deduplicated.
    state.apply("AskQuery", {"username": "amy", "query": "q"})
    state.apply("AskQuery", {"username": "amy", "query": "q"})
    assert len(state.data["queries"]["amy"]) == 4


def test_request_ledger_survives_snapshot_roundtrip():
    import json

    state = LMSState()
    state.apply("AskQuery", {"username": "a", "query": "q", "request_id": "x"})
    restored = LMSState(json.loads(json.dumps(state.data)))
    restored.apply("AskQuery", {"username": "a", "query": "q", "request_id": "x"})
    assert len(restored.data["queries"]["a"]) == 1


# --------------------------------------------- 4. snapshot-ahead-of-WAL


class _EmptyStorage:
    def load(self):
        # term 3, no vote, EMPTY log, no compaction (lost/truncated WAL)
        return 3, None, [], 0, 0


def test_snapshot_ahead_of_wal_fails_fast():
    with pytest.raises(RuntimeError, match="ahead of the WAL"):
        RaftCore(1, [1, 2, 3], _EmptyStorage(), last_applied=5)


# ----------------------------------------------------- 5. salted KDF


def test_passwords_salted_and_replicated_deterministically():
    state = LMSState()
    state.apply(
        "Register",
        {
            "username": "amy",
            "password_hash": hash_password("pw", "ab" * 16),
            "salt": "ab" * 16,
            "role": "student",
        },
    )
    state.apply(
        "Register",
        {
            "username": "bob",
            "password_hash": hash_password("pw", "cd" * 16),
            "salt": "cd" * 16,
            "role": "student",
        },
    )
    # Same password, different salts -> different stored hashes.
    assert (
        state.data["users"]["amy"]["password"]
        != state.data["users"]["bob"]["password"]
    )
    assert state.check_password("amy", "pw")
    assert not state.check_password("amy", "wrong")

    # Legacy states (pre-salt) still authenticate.
    legacy = LMSState()
    legacy.data["users"]["old"] = {
        "password": hash_password("pw"),
        "role": "student",
    }
    assert legacy.check_password("old", "pw")


def test_failed_handler_does_not_poison_request_ledger():
    """A handler exception must leave the request_id unrecorded so a client
    retry is re-attempted, not silently dropped (ADVICE r3 #4)."""
    state = LMSState()
    args = {"username": "amy", "query": "q", "request_id": "boom"}
    with pytest.raises(ValueError):
        state.apply("NoSuchCommand", dict(args))
    assert "boom" not in state.data.get("applied_requests", {})
    # The retry with the same id goes through once the command is valid.
    state.apply("AskQuery", dict(args))
    assert len(state.data["queries"]["amy"]) == 1
    assert "boom" in state.data["applied_requests"]
