"""CPU smoke of the BENCH record paths (BENCH_NOTES' still-unmeasured
`--paged --spec-tokens` configurations).

The real-chip numbers land in BENCH_NOTES when a TPU is attached; these
seeded tiny-model runs pin the RECORD path meanwhile — both harnesses
must keep emitting BENCH-schema dicts that carry the paged+spec fields
AND the new megastep knobs (megastep/megastep_max/chunk/inflight plus the
measured host-dispatches-per-token ratio), so the recording command
cannot rot between measurement rounds.
"""

import argparse
import asyncio
import sys
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))
sys.path.insert(0, str(REPO / "scripts"))


def test_bench_paged_spec_record_smoke():
    """bench.py's engine-direct paged+spec measurement: one seeded tiny
    run, record carries throughput + acceptance + megastep knobs."""
    from bench import bench_paged

    out = bench_paged(
        model="tiny", batch=2, spec_tokens=2, greedy=True, chunk=2,
        megastep=2, megastep_max=4, max_new=8, rounds=1, prompt_len=8,
        length_buckets=(8, 16),
    )
    assert out["tokens_per_sec_per_chip"] > 0
    assert out["requests_per_s"] > 0
    assert out["ttft_p50_ms"] > 0
    assert out["chunk"] == 2
    assert out["megastep"] == 2
    assert out["megastep_max"] == 4
    assert out["inflight"] == 2
    assert 0.0 < out["host_dispatches_per_token"] < 2.0
    assert out["megastep_dead_lane_tokens"] >= 0
    # Spec acceptance rides along: mean emitted tokens per verify window
    # is in [1, k+1] whenever any window ran.
    assert out["spec_tokens_per_window"] is None or (
        1.0 <= out["spec_tokens_per_window"] <= 3.0
    )


def test_bench_shared_prefix_record_smoke():
    """bench.py's shared-prefix scenario: N requests against one common
    course context; the record must carry prefill ms and tokens/s cold
    vs warm plus the measured hit rate (>= 50% shared-prefix tokens at
    steady state — the ISSUE acceptance workload)."""
    from bench import bench_shared_prefix

    out = bench_shared_prefix(
        model="tiny", n_requests=6, prefix_len=24, suffix_len=8,
        max_new=8, chunk=2, slots=2, prefix_cache_blocks=64,
        prefix_block_tokens=4, length_buckets=(16, 32, 64),
    )
    assert out["metric"] == "paged_shared_prefix_prefill_speedup"
    assert out["prefill_ms_cold"] > 0
    assert out["prefill_ms_warm"] > 0
    # The headline value is the cold/warm ratio (both fields are rounded
    # independently, so compare with tolerance, not equality).
    assert out["value"] == pytest.approx(
        out["prefill_ms_cold"] / out["prefill_ms_warm"], abs=0.02
    )
    assert out["tokens_per_sec_per_chip_cold"] > 0
    assert out["tokens_per_sec_per_chip_warm"] > 0
    # The warm phase really shares >= 50% of its prompt tokens; the cold
    # phase (distinct contexts) must not.
    assert out["prefix_cache_hit_rate"] >= 0.5
    assert out["cold_hit_rate"] < 0.1


def test_bench_paged_fused_admission_record_smoke():
    """bench.py --prefill-chunk-tokens: the record carries the fused
    knob and the stall-free before/after fields (zero by construction
    with fusion on)."""
    from bench import bench_paged

    out = bench_paged(
        model="tiny", batch=2, greedy=True, chunk=2, megastep=2,
        megastep_max=2, max_new=8, rounds=1, prompt_len=8,
        length_buckets=(8, 16), prefill_chunk_tokens=4,
    )
    assert out["tokens_per_sec_per_chip"] > 0
    assert out["prefill_chunk_tokens"] == 4
    assert out["prefill_stall_ms"] == 0
    assert out["decode_stalled_tokens"] == 0


def test_bench_sweep_grid_smoke():
    """bench.py --sweep: one BENCH-schema JSON record per
    (slots, inflight, megastep) grid point, each carrying the megastep
    knobs and the admission-stall fields — the round-6 grid runner the
    next chip-attached session executes verbatim (BENCH_NOTES round 6)."""
    from bench import bench_sweep

    grid = bench_sweep(
        model="tiny", slots_grid=(2,), inflight_grid=(1, 2),
        megastep_grid=(2,), greedy=True, chunk=2, max_new=8,
        rounds=1, prompt_len=8, length_buckets=(8, 16),
        prefill_chunk_tokens=4,
    )
    assert len(grid) == 2
    metrics = {r["metric"] for r in grid}
    assert "paged_sweep_slots2_inflight1_mega2" in metrics
    assert "paged_sweep_slots2_inflight2_mega2" in metrics
    for r in grid:
        assert r["unit"] == "tokens/sec/chip"
        assert r["value"] > 0
        assert r["slots"] == 2
        assert r["inflight"] in (1, 2)
        assert r["megastep"] == 2
        assert r["prefill_chunk_tokens"] == 4
        assert r["decode_stalled_tokens"] == 0
        assert r["host_dispatches_per_token"] > 0


def test_bench_score_scenario_record_smoke():
    """bench.py --score-scenario: the two-tenant record (interactive load
    with the background scoring tenant off/on) must witness the
    acceptance claims — quanta executed ONLY while the interactive
    pending queue was empty (quanta_with_pending == 0), the bulk job
    completed in the idle lanes, every preemption wait stayed under one
    quantum, and the interactive p90 TTFT delta is bounded."""
    from bench import bench_score_scenario

    out = bench_score_scenario(
        model="tiny", slots=2, chunk=2, interactive=6, arrival_s=0.02,
        score_texts_n=10, score_text_tokens=12, max_new=8, prompt_len=8,
        length_buckets=(8, 16), greedy=True,
    )
    assert out["metric"] == "paged_score_tenant_total_tokens_per_sec_per_chip"
    assert out["unit"] == "tokens/sec/chip"
    assert out["total_tokens_per_sec_per_chip_off"] > 0
    assert out["total_tokens_per_sec_per_chip_on"] > 0
    # The harvest: the ON phase really scored the bulk corpus...
    assert out["scored_tokens"] > 0
    assert out["scoring_jobs_completed"] == 1
    assert out["scoring_quanta"] >= 2  # ceil(10 texts / batch cap 8)
    # ...and ONLY in idle lanes: zero quanta admitted while interactive
    # work waited, and any arrival that landed mid-quantum waited at
    # most one quantum for its dispatch.
    assert out["quanta_with_pending"] == 0
    assert out["max_preempt_wait_ms"] <= out["max_quantum_wall_ms"] + 50
    # Interactive p90 TTFT holds (pinned loosely for CPU CI noise: the
    # real bound is the chip record's; a co-scheduler that blocked
    # interactive work behind the whole job would blow far past this).
    assert out["ttft_p90_ms_on"] <= out["ttft_p90_ms_off"] + 2000.0


def test_bench_paged_carries_prefix_knob_and_hit_rate():
    from bench import bench_paged

    out = bench_paged(
        model="tiny", batch=2, greedy=True, chunk=2, max_new=8,
        rounds=1, prompt_len=8, length_buckets=(8, 16),
        prefix_cache_blocks=16,
    )
    assert out["prefix_cache_blocks"] == 16
    assert out["prefix_cache_hit_rate"] is not None


def test_bench_server_paged_spec_record_smoke():
    """bench_server.py through the real gRPC stack: the one-line record
    must carry the paged+spec configuration, the megastep knobs, and the
    queue-maintained host-dispatches-per-token gauge."""
    import bench_server

    args = argparse.Namespace(
        model="tiny", clients=2, queries=1, max_new_tokens=8,
        paged=True, slots=2, chunk=2, megastep=2, megastep_max=2,
        inflight=2, quant=None, kv_quant=False, greedy=True,
        spec_tokens=2,
    )
    out = asyncio.run(bench_server.run(args))
    assert out["engine"] == "paged"
    assert out["spec_tokens"] == 2
    assert out["megastep"] == 2
    assert out["megastep_max"] == 2
    assert out["chunk"] == 2
    assert out["tokens_per_sec_per_chip"] > 0
    assert out["ttft_count"] == 2
    dpt = out["host_dispatches_per_token"]
    assert dpt is not None and 0.0 < dpt < 3.0
    # Prefix-cache fields ride along (disabled here: knob recorded False,
    # gauge absent => None, never fabricated).
    assert out["prefix_cache"] is False
    assert out["prefix_cache_hit_rate"] is None
