"""Exhaustive crash-point recovery checking for the storage layer.

The disk-surface analogue of tests/test_raft_modelcheck.py: instead of
enumerating message schedules, enumerate CRASH POINTS. The storage
workload (WAL appends -> fsync -> snapshot save -> compaction's
tmp-write -> fsync -> rename -> dir fsync) runs against
`utils.diskfaults.MemCrashFS`, which crashes at op N and then
materializes every adversarial post-crash view the POSIX contract
allows:

    "none"      nothing un-fsynced survived
    "all"       everything issued survived
    "meta"      namespace ops (renames/creates) survived, un-fsynced
                data did not — the reordering that used to turn an
                uploaded PDF into a durable empty file
    ("tail", n) the final un-fsynced write kept only its first n bytes

For EVERY (crash point x view), a restart must recover a
prefix-consistent state containing every entry acked durable before the
crash, invent and reorder nothing, and never mistake pure crash damage
for corruption (WALCorruption/SnapshotCorruption are for bit rot, not
for torn tails).

Plus the cluster-level acceptance paths: a node with mid-file WAL
corruption refuses to campaign, rejoins via the leader's
InstallSnapshot, and converges; and a slow-marked soak composes disk
faults with network partitions over a 5-node cluster and checks zero
acked-write loss after heal.
"""

import asyncio
import os
import random

import pytest

from distributed_lms_raft_llm_tpu.lms.node import LMSNode
from distributed_lms_raft_llm_tpu.lms.persistence import (
    BlobStore,
    SnapshotStore,
)
from distributed_lms_raft_llm_tpu.lms.state import LMSState
from distributed_lms_raft_llm_tpu.raft import Entry, FileStorage, RaftConfig
from distributed_lms_raft_llm_tpu.raft.core import NotLeader
from distributed_lms_raft_llm_tpu.raft.messages import encode_command
from distributed_lms_raft_llm_tpu.raft.node import MemNetwork
from distributed_lms_raft_llm_tpu.utils.diskfaults import (
    DiskFaultInjector,
    MemCrashFS,
    SimulatedCrash,
)
from distributed_lms_raft_llm_tpu.utils.metrics import Metrics

FAST = RaftConfig(
    election_timeout_min=0.11, election_timeout_max=0.22,
    heartbeat_interval=0.05,
)

WAL = "/data/raft_wal.jsonl"
SNAP = "/data/lms_data.json"
BLOBS = "/data/uploads"

# ("tail", -1): the final write persisted every byte but its last — for
# a WAL append, a complete record missing only its newline, which replay
# must treat as torn (drop), never apply-then-truncate.
CRASH_VIEWS = ("none", "all", "meta",
               ("tail", 0), ("tail", 1), ("tail", 7), ("tail", -1))


# ------------------------------------------------------------- workloads


def wal_snapshot_workload(fs, acked):
    """The LMSNode persistence flow in miniature: append entries, apply
    them to a kv state, snapshot every 4 applies, compact the WAL to the
    snapshot. `acked` collects facts the moment they are durably acked —
    exactly what recovery must preserve."""
    snaps = SnapshotStore(SNAP, fs=fs)
    storage = FileStorage(WAL, fsync=True, fs=fs)
    storage.save_meta(1, None)
    acked.append(("meta", 1))
    kv = {}
    for i in range(1, 11):
        storage.append_entries(i, [Entry(1, f"cmd-{i}")])
        acked.append(("entry", i))
        kv[str(i)] = i
        if i % 4 == 0:
            state = LMSState()
            state.data["kv"] = dict(kv)
            snaps.save(state, i)
            acked.append(("snapshot", i))
            storage.compact_to(i, 1)
    storage.close()


def blob_workload(fs, acked):
    blobs = BlobStore(BLOBS, fs=fs)
    blobs.put("materials/a.pdf", b"A" * 100)
    acked.append(("a.pdf", b"A" * 100))
    w = blobs.open_writer("materials/b.pdf")
    w.write(b"B" * 50)
    w.write(b"b" * 50)
    w.commit()
    acked.append(("b.pdf", b"B" * 50 + b"b" * 50))
    # Overwrite: post-crash content must be old-or-new, never partial.
    blobs.put("materials/a.pdf", b"Z" * 160)
    acked.append(("a.pdf", b"Z" * 160))


def count_ops(workload):
    fs = MemCrashFS()  # crash_at_op=0: never crashes
    acked = []
    workload(fs, acked)
    return fs.ops, acked


# ----------------------------------------------- WAL + snapshot recovery


def recover_wal_snapshot(post):
    """Boot the stores over a post-crash view. Must never raise: a pure
    crash (no bit flips) produces torn tails at worst, and those truncate
    cleanly."""
    snaps = SnapshotStore(SNAP, fs=post)
    state, applied = snaps.load()
    storage = FileStorage(WAL, fsync=True, fs=post)
    term, voted, entries, snap_idx, snap_term = storage.load()
    storage.close()
    return state, applied, term, entries, snap_idx


def check_wal_snapshot_recovery(crash_op, view, post, acked):
    ctx = f"crash@{crash_op} view={view}"
    state, applied, term, entries, snap_idx = recover_wal_snapshot(post)
    # Boot invariants RaftCore enforces (a violation there bricks the
    # node): the app snapshot sits between the WAL's compaction point and
    # its head — crash ordering must never break this.
    last_index = snap_idx + len(entries)
    assert snap_idx <= applied <= last_index, (
        f"{ctx}: snapshot applied_index={applied} outside WAL coverage "
        f"[{snap_idx}, {last_index}]"
    )
    # Prefix consistency: recovered entries are exactly the golden
    # commands at contiguous absolute indices — nothing invented or
    # reordered.
    for off, e in enumerate(entries):
        idx = snap_idx + 1 + off
        assert e.command == f"cmd-{idx}", (
            f"{ctx}: index {idx} recovered {e.command!r}"
        )
    assert last_index <= 10, f"{ctx}: invented entries past the workload"
    # Acked coverage: every durably-acked fact survived.
    for kind, val in acked:
        if kind == "meta":
            assert term >= val, f"{ctx}: acked meta term {val} lost"
        elif kind == "entry":
            assert val <= last_index, f"{ctx}: acked entry {val} lost"
            if val > applied:
                # Not in the snapshot: must be replayable from the WAL.
                assert val > snap_idx, (
                    f"{ctx}: entry {val} compacted away but not applied"
                )
        elif kind == "snapshot":
            assert applied >= val, f"{ctx}: acked snapshot {val} lost"
    # The snapshot's own integrity: state matches its applied_index.
    for j in range(1, applied + 1):
        assert state.data["kv"].get(str(j)) == j, (
            f"{ctx}: snapshot at {applied} is missing apply {j}"
        )


def test_exhaustive_crash_points_wal_and_snapshot():
    total_ops, golden_acked = count_ops(wal_snapshot_workload)
    assert total_ops > 30, "workload too small to mean anything"
    assert ("entry", 10) in golden_acked and ("snapshot", 8) in golden_acked
    checked = 0
    for crash_op in range(1, total_ops + 1):
        fs = MemCrashFS(crash_at_op=crash_op)
        acked = []
        with pytest.raises(SimulatedCrash):
            wal_snapshot_workload(fs, acked)
        for view in CRASH_VIEWS:
            check_wal_snapshot_recovery(
                crash_op, view, fs.crashed_view(view), acked
            )
            checked += 1
    assert checked == total_ops * len(CRASH_VIEWS)


def test_exhaustive_crash_points_then_continue_and_recrash():
    """Second-order: recover from a crash view, append MORE entries, and
    verify the continuation replays — the repaired tail must be a clean
    append point, not a lurking merge."""
    total_ops, _ = count_ops(wal_snapshot_workload)
    for crash_op in range(1, total_ops + 1, 3):
        fs = MemCrashFS(crash_at_op=crash_op)
        with pytest.raises(SimulatedCrash):
            wal_snapshot_workload(fs, [])
        post = fs.crashed_view(("tail", 1))
        storage = FileStorage(WAL, fsync=True, fs=post)
        _, _, entries, snap_idx, _ = storage.load()
        nxt = snap_idx + len(entries) + 1
        storage.append_entries(nxt, [Entry(2, f"cmd-{nxt}")])
        storage.close()
        again = FileStorage(WAL, fsync=True, fs=post)
        _, _, entries2, snap2, _ = again.load()
        assert snap2 + len(entries2) == nxt
        assert entries2[-1].command == f"cmd-{nxt}"
        again.close()


def test_exhaustive_crash_points_blob_store():
    """Acked blobs survive EVERY crash view byte-for-byte — including
    'meta' (rename persisted, data writes not), the exact reordering that
    produced durable empty PDFs before the fsync-before-rename fix."""
    total_ops, golden_acked = count_ops(blob_workload)
    assert len(golden_acked) == 3
    for crash_op in range(1, total_ops + 1):
        fs = MemCrashFS(crash_at_op=crash_op)
        acked = []
        with pytest.raises(SimulatedCrash):
            blob_workload(fs, acked)
        expected = {}
        for name, content in acked:
            expected[name] = content
        overwrite_acked = acked.count(("a.pdf", b"Z" * 160)) > 0
        for view in CRASH_VIEWS:
            post = fs.crashed_view(view)
            blobs = BlobStore(BLOBS, fs=post)
            for name, content in expected.items():
                got = blobs.get(f"materials/{name}")
                ctx = (f"crash@{crash_op} view={view}: {name} = "
                       f"{len(got) if got is not None else None} bytes")
                if name == "a.pdf" and not overwrite_acked:
                    # The overwrite was in flight: old-or-new is legal,
                    # partial/empty/missing never is.
                    assert got in (b"A" * 100, b"Z" * 160), ctx
                else:
                    assert got == content, f"{ctx}: acked blob lost/mangled"


# ----------------------------------------- corrupt node rejoins the cluster


def _corrupt_midfile(path):
    raw = open(path, "rb").read()
    lines = raw.splitlines(keepends=True)
    assert len(lines) >= 2
    t = lines[len(lines) // 2]
    pos = len(t) // 2
    lines[len(lines) // 2] = t[:pos] + bytes([t[pos] ^ 1]) + t[pos + 1:]
    open(path, "wb").write(b"".join(lines))


async def _wait(predicate, timeout=10.0, interval=0.02):
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        if predicate():
            return True
        await asyncio.sleep(interval)
    return predicate()


async def _wait_leader(nodes):
    leader = None

    def found():
        nonlocal leader
        live = [n for n in nodes.values() if not n.node._stopped]
        leaders = [n for n in live if n.node.is_leader]
        leader = leaders[0] if leaders else None
        return leader is not None

    assert await _wait(found), "no leader elected"
    return leader


def test_corrupt_wal_node_rejoins_via_install_snapshot(tmp_path):
    """Acceptance: mid-file WAL corruption -> the node refuses its local
    log, boots recovering (no campaigning, no votes), receives the
    leader's InstallSnapshot + suffix, converges, and drops the
    storage_recovering gauge."""

    async def run():
        ids = [1, 2, 3]
        addresses = {i: "" for i in ids}
        net = MemNetwork()
        nodes, metrics = {}, {}

        def boot(i, **kw):
            metrics.setdefault(i, Metrics())
            node = LMSNode(
                i, addresses, str(tmp_path / f"node{i}"),
                raft_config=FAST, transport=net.transport_for(i),
                snapshot_every=4, metrics=metrics[i], **kw,
            )
            net.register(node.node)
            nodes[i] = node
            return node

        for i in ids:
            boot(i)
        for i in ids:
            await nodes[i].start()
        try:
            leader = await _wait_leader(nodes)
            for k in range(10):
                # Re-resolve on NotLeader: a tick stall under suite load
                # can re-elect between _wait_leader and the propose.
                for _ in range(20):
                    try:
                        await leader.node.propose(encode_command(
                            "SetVal", {"key": f"k{k}", "value": str(k)}
                        ))
                        break
                    except NotLeader:
                        leader = await _wait_leader(nodes)
                else:
                    raise AssertionError("leadership never settled")
            # Snapshots every 4 applies: the leader compacted, so a
            # log-less rejoiner can only converge via InstallSnapshot.
            assert await _wait(
                lambda: leader.node.core.snapshot_index >= 4
            )
            victim = next(i for i in ids if not nodes[i].node.is_leader)
            await nodes[victim].stop()
            _corrupt_midfile(
                str(tmp_path / f"node{victim}" / "raft_wal.jsonl")
            )

            fresh = boot(victim)
            assert fresh.recovering, "corrupt WAL must boot in recovery"
            g = metrics[victim].snapshot()["gauges"]
            assert g["storage_recovering"] == 1
            assert os.path.exists(
                str(tmp_path / f"node{victim}" / "raft_wal.jsonl.corrupt")
            )
            # Blobs share the quarantine (no integrity headers: whatever
            # corrupted the log may have flipped blob bytes too); a fresh
            # empty tree replaces them and fetch-on-miss heals reads.
            assert os.path.exists(
                str(tmp_path / f"node{victim}" / "uploads.corrupt")
            )
            assert os.path.isdir(
                str(tmp_path / f"node{victim}" / "uploads")
            )
            await fresh.start()

            # More traffic while it heals.
            leader = await _wait_leader(nodes)
            for k in range(10, 14):
                await leader.node.propose(encode_command(
                    "SetVal", {"key": f"k{k}", "value": str(k)}
                ))
            assert await _wait(lambda: not fresh.recovering, timeout=15), \
                "recovery never completed"
            assert await _wait(
                lambda: len(fresh.state.data["kv"]) == 14, timeout=15
            ), f"converged to {len(fresh.state.data['kv'])}/14 keys"
            for k in range(14):
                assert fresh.state.data["kv"][f"k{k}"] == str(k)
            # It re-synced via snapshot install, not full replay (the
            # leader compacted the prefix away).
            assert fresh.node.core.snapshot_index >= 4
            g = metrics[victim].snapshot()["gauges"]
            assert g["storage_recovering"] == 0
        finally:
            for n in nodes.values():
                if not n.node._stopped:
                    await n.stop()

    asyncio.run(run())


# ------------------------------------------------- disk + network chaos


@pytest.mark.slow
def test_disk_and_network_chaos_soak_zero_acked_loss(tmp_path):
    """Compose the two fault planes over a 5-node cluster: network
    partitions + crash-restarts + mid-file corruption of follower WALs +
    probabilistic disk faults (ENOSPC short writes, fsync failures) on
    followers — after heal, every acked (quorum-committed) write is on
    every node. Seeded: a failure replays."""

    async def run():
        rng = random.Random(1234)
        ids = [1, 2, 3, 4, 5]
        addresses = {i: "" for i in ids}
        net = MemNetwork()
        nodes, metrics, disk = {}, {}, {}

        def boot(i):
            metrics.setdefault(i, Metrics())
            disk[i] = DiskFaultInjector(seed=i)
            node = LMSNode(
                i, addresses, str(tmp_path / f"node{i}"),
                raft_config=FAST, transport=net.transport_for(i),
                snapshot_every=8, metrics=metrics[i],
                disk_fault_injector=disk[i],
            )
            net.register(node.node)
            nodes[i] = node
            return node

        for i in ids:
            boot(i)
        for i in ids:
            await nodes[i].start()
        acked = {}
        seq = 0
        try:
            for round_no in range(5):
                leader = await _wait_leader(nodes)
                follower_ids = [
                    i for i in ids if nodes[i] is not leader
                    and not nodes[i].node._stopped
                ]
                # Disk chaos on one follower: rare short writes + fsync
                # failures on the live append path.
                chaotic = rng.choice(follower_ids)
                disk[chaotic].configure(write_error=0.05, fsync_error=0.05)
                # Network chaos: partition one OTHER follower away.
                cut = rng.choice([i for i in follower_ids if i != chaotic])
                net.partition([i for i in ids if i != cut], [cut])
                for _ in range(8):
                    seq += 1
                    key, val = f"key{seq}", f"val{seq}"
                    try:
                        await nodes[leader.node_id].node.propose(
                            encode_command(
                                "SetVal", {"key": key, "value": val}
                            ),
                            timeout=3.0,
                        )
                        acked[key] = val  # quorum-committed: must survive
                    except Exception:
                        pass  # un-acked; the checker ignores it
                disk[chaotic].clear()
                net.heal()
                # Crash-restart a follower; half the time, corrupt its
                # WAL mid-file so it must take the recovery path.
                leader = await _wait_leader(nodes)
                victim = rng.choice([
                    i for i in ids if nodes[i] is not leader
                    and not nodes[i].node._stopped
                ])
                await nodes[victim].stop()
                wal = str(tmp_path / f"node{victim}" / "raft_wal.jsonl")
                # The victim is stopped and the cluster idles between
                # rounds; tiny test file.
                # lint: disable-next=no-blocking-in-async
                if rng.random() < 0.5 and os.path.getsize(wal) > 0:
                    with open(wal, "rb") as fh:  # lint: disable=no-blocking-in-async
                        if len(fh.read().splitlines()) >= 2:
                            _corrupt_midfile(wal)
                fresh = boot(victim)
                await fresh.start()
                await asyncio.sleep(0.3)

            # Heal everything and wait for full convergence.
            net.heal()
            for inj in disk.values():
                inj.clear()
            leader = await _wait_leader(nodes)

            def converged():
                return all(
                    not n.recovering
                    and all(
                        n.state.data["kv"].get(k) == v
                        for k, v in acked.items()
                    )
                    for n in nodes.values()
                )

            assert await _wait(converged, timeout=30), (
                f"acked-write loss after heal: "
                + str({
                    i: [k for k, v in acked.items()
                        if nodes[i].state.data['kv'].get(k) != v][:5]
                    for i in ids
                })
            )
            assert len(acked) >= 20, "soak acked too few writes to be real"
        finally:
            for n in nodes.values():
                if not n.node._stopped:
                    await n.stop()

    asyncio.run(run())
