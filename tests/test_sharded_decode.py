"""BASELINE configs 3-4 on the virtual mesh: larger GPT-2s under real tp.

Config 4 is GPT-2-large tp=8 sharded decode (BASELINE.json). Running the
true 774M model on the CPU test mesh is minutes of compile, so the test
shards the REAL topology (36 layers / 20 heads / tp=8 — note 20 % 8 != 0,
exercising GSPMD's uneven-shard padding) at reduced width, then a smoke at
true depth. What's validated is the sharding program: prefill + while_loop
decode + sampling compile and execute with tp=8 NamedShardings.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_lms_raft_llm_tpu.engine import generate as gen_lib
from distributed_lms_raft_llm_tpu.engine.sampling import SamplingParams
from distributed_lms_raft_llm_tpu.models import gpt2
from distributed_lms_raft_llm_tpu.parallel import mesh as mesh_lib
from distributed_lms_raft_llm_tpu.parallel import partition


def _sharded_generate(cfg, tp, batch, bucket, max_new):
    mesh = mesh_lib.make_mesh({"tp": tp, "dp": -1})
    params = gpt2.init_params(jax.random.key(0), cfg)
    params = partition.shard_tree(params, mesh, partition.GPT2_RULES)
    ids = np.ones((batch, bucket), np.int32)
    mask = np.ones((batch, bucket), bool)
    with mesh:
        result = jax.jit(
            lambda p, i, m, r: gen_lib.generate(
                p, cfg, i, m, r,
                sampling=SamplingParams.reference_defaults(max_new_tokens=max_new),
                eos_id=0, pad_id=0,
            )
        )(params, jnp.asarray(ids), jnp.asarray(mask), jax.random.key(1))
    return jax.device_get(result)


def test_gpt2_large_topology_tp8_decode():
    """GPT-2-large's head/layer topology (narrowed) under tp=8."""
    cfg = dataclasses.replace(
        gpt2.GPT2Config.large(dtype=jnp.float32, param_dtype=jnp.float32),
        hidden_size=80,   # 20 heads x 4 head_dim (true: 20 x 64)
        num_layers=6,     # scan depth is compile-O(1); 6 keeps runtime sane
        vocab_size=512,
        max_position_embeddings=64,
    )
    result = _sharded_generate(cfg, tp=8, batch=2, bucket=16, max_new=4)
    assert result.tokens.shape == (2, 4)
    assert np.isfinite(result.lengths).all()
    assert (result.tokens < cfg.vocab_size).all()


def test_gpt2_medium_topology_tp4_dp2_decode():
    """Config 3 analogue: gpt2-medium topology (16 heads) on tp=4 x dp=2."""
    cfg = dataclasses.replace(
        gpt2.GPT2Config.medium(dtype=jnp.float32, param_dtype=jnp.float32),
        hidden_size=64,   # 16 heads x 4
        num_layers=4,
        vocab_size=512,
        max_position_embeddings=64,
    )
    result = _sharded_generate(cfg, tp=4, batch=2, bucket=16, max_new=4)
    assert result.tokens.shape == (2, 4)
    assert (result.tokens < cfg.vocab_size).all()


def test_tp_sharded_decode_with_int8_kv_cache():
    """kv_quant under tensor parallelism: the int8 cache planes and their
    [L, B, H, S] scale planes must ride jit's sharding propagation next to
    the tp-sharded head axis without repartition errors."""
    cfg = dataclasses.replace(
        gpt2.GPT2Config(dtype=jnp.float32, param_dtype=jnp.float32),
        hidden_size=64, num_layers=4, num_heads=8,
        vocab_size=512, max_position_embeddings=64,
        quant_kv=True,
    )
    result = _sharded_generate(cfg, tp=4, batch=2, bucket=16, max_new=4)
    assert result.tokens.shape == (2, 4)
    assert (result.tokens < cfg.vocab_size).all()
    assert np.isfinite(result.lengths).all()


def test_llama3_topology_tp8_gqa_decode():
    """BASELINE config 5's sharding surface: Llama-3-8B's real head
    topology (32 q heads over 8 kv heads -> exactly 1 kv head per device
    at tp=8) at reduced width, through prefill + while_loop decode +
    sampling with int8 KV, under tp=8 NamedShardings."""
    from distributed_lms_raft_llm_tpu.models import llama, registry

    cfg = dataclasses.replace(
        llama.LlamaConfig.llama3_8b(dtype=jnp.float32,
                                    param_dtype=jnp.float32),
        hidden_size=128,        # 32 heads x 4 head_dim (true: 32 x 128)
        num_layers=4,
        intermediate_size=256,
        vocab_size=512,
        max_position_embeddings=64,
        quant_kv=True,
    )
    mesh = mesh_lib.make_mesh({"tp": 8, "dp": -1})
    params = llama.init_params(jax.random.key(9), cfg)
    params = partition.shard_tree(params, mesh, partition.LLAMA_RULES)
    ids = np.ones((2, 16), np.int32)
    mask = np.ones((2, 16), bool)
    with mesh:
        result = jax.jit(
            lambda p, i, m, r: gen_lib.generate(
                p, cfg, i, m, r,
                sampling=SamplingParams.reference_defaults(max_new_tokens=4),
                eos_id=0, pad_id=0, model=registry.LLAMA_FAMILY,
            )
        )(params, jnp.asarray(ids), jnp.asarray(mask), jax.random.key(2))
    result = jax.device_get(result)
    assert result.tokens.shape == (2, 4)
    assert (result.tokens < cfg.vocab_size).all()
    assert np.isfinite(result.lengths).all()


def test_llama_int8_weights_tp4_decode():
    """Llama int8 weight-only quant under tp=4 (the {q, s} LLAMA_RULES):
    sharded generate runs and emits valid tokens."""
    from distributed_lms_raft_llm_tpu.models import llama, quant, registry

    cfg = dataclasses.replace(
        llama.LlamaConfig.tiny(dtype=jnp.float32, param_dtype=jnp.float32),
        hidden_size=64, num_layers=3, num_heads=8, num_kv_heads=4,
        intermediate_size=128,
    )
    qparams = quant.quantize_params(
        llama.init_params(jax.random.key(10), cfg), "llama"
    )
    mesh = mesh_lib.make_mesh({"tp": 4, "dp": -1})
    sharded = partition.shard_tree(qparams, mesh, partition.LLAMA_RULES)
    ids = np.ones((2, 12), np.int32)
    mask = np.ones((2, 12), bool)
    with mesh:
        result = jax.jit(
            lambda p, i, m, r: gen_lib.generate(
                p, cfg, i, m, r,
                sampling=SamplingParams.reference_defaults(max_new_tokens=4),
                eos_id=0, pad_id=0, model=registry.LLAMA_FAMILY,
            )
        )(sharded, jnp.asarray(ids), jnp.asarray(mask), jax.random.key(3))
    result = jax.device_get(result)
    assert result.tokens.shape == (2, 4)
    assert (result.tokens < cfg.vocab_size).all()
