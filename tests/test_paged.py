"""PagedEngine continuous batching: parity, mid-decode admission, slot reuse.

The round-1 done-criterion for continuous batching: a request submitted
mid-decode completes without waiting for the running group (the reference
serves strictly one request at a time — reference:
GUI_RAFT_LLM_SourceCode/tutoring_server.py:21-29).
"""

import asyncio

import jax
import pytest

from distributed_lms_raft_llm_tpu.engine import (
    EngineConfig,
    PagedEngine,
    PagedQueue,
    SamplingParams,
    TutoringEngine,
)
from distributed_lms_raft_llm_tpu.utils.metrics import Metrics

MAX_NEW = 8

PROMPTS = ["what is raft?", "hello world", "explain paging", "k"]


def make_config(**kw):
    kw.setdefault("sampling", SamplingParams.greedy(max_new_tokens=MAX_NEW))
    kw.setdefault("length_buckets", (16,))
    return EngineConfig(
        model="tiny",
        batch_buckets=(1, 2, 4),
        dtype=jax.numpy.float32,
        **kw,
    )


def test_greedy_parity_with_bucketed_engine():
    """Same params (same seed), greedy sampling: the paged engine must emit
    exactly what the bucketed engine emits, despite its different padding
    (right vs left) and per-slot ragged cache layout."""
    cfg = make_config()
    expected = TutoringEngine(cfg).answer_batch(list(PROMPTS))
    paged = PagedEngine(cfg, slots=4)
    rids = [paged.submit(p) for p in PROMPTS]
    out = paged.drain()
    assert [out[rid] for rid in rids] == expected


def test_mid_decode_admission_completes_without_waiting():
    paged = PagedEngine(make_config(), slots=2)
    paged.submit("a long question about distributed consensus and logs")
    for _ in range(3):
        paged.step()  # A is now mid-decode
    b = paged.submit("b")
    finished = {}
    steps_after_b = 0
    while paged.has_work and steps_after_b < 3 * MAX_NEW:
        steps_after_b += 1
        for rid, text in paged.step():
            finished.setdefault(rid, steps_after_b)
        if steps_after_b == 1:
            # B was admitted into a free slot immediately, joining the
            # running batch rather than queueing behind it.
            in_slots = {r.rid for r in paged._slot_req if r is not None}
            assert b in in_slots or b in finished
    assert b in finished
    # B finished within its own generation budget (+1 for the admission
    # step) — it did not wait for A's remaining decode.
    assert finished[b] <= MAX_NEW + 1


def test_pipelined_outputs_match_serialized():
    """inflight=2 (dispatch N+1 before reading N — the throughput mode)
    must produce byte-identical answers to the serialized inflight=1 loop,
    including through slot churn (4 requests over 2 slots)."""
    cfg = make_config()
    ser = PagedEngine(cfg, slots=2, inflight=1)
    rs = [ser.submit(p) for p in PROMPTS]
    out_ser = ser.drain()
    pipe = PagedEngine(cfg, slots=2, inflight=2)
    rp = [pipe.submit(p) for p in PROMPTS]
    out_pipe = pipe.drain()
    assert [out_pipe[r] for r in rp] == [out_ser[r] for r in rs]


def test_greedy_parity_with_prompt_buckets_and_churn():
    """Per-prompt prefill buckets (short prompt -> narrow prefill program)
    plus slot reuse: answers still match the bucketed engine exactly."""
    cfg = make_config(length_buckets=(4, 8, 16))
    prompts = list(PROMPTS) + ["k v"]
    expected = TutoringEngine(cfg).answer_batch(prompts)
    paged = PagedEngine(cfg, slots=2)  # 5 requests churn through 2 slots
    widths = set()
    real_prefill = paged._prefill
    paged._prefill = lambda params, ids, *a, **kw: (
        widths.add(ids.shape[1]) or real_prefill(params, ids, *a, **kw)
    )
    rids = [paged.submit(p) for p in prompts]
    out = paged.drain()
    assert [out[r] for r in rids] == expected
    # Short prompts really took narrower prefill programs.
    assert len(widths) >= 2 and min(widths) < 16, widths


def test_cache_width_grows_and_shrinks_with_prompt_mix():
    """Width-bucketed slot cache: short prompts run at a narrow width, a
    long prompt grows the live cache mid-batch, and an idle engine shrinks
    back — all with exact greedy parity against the bucketed engine."""
    cfg = make_config(length_buckets=(4, 16))
    long_prompt = "a long question about raft elections and replicated logs"
    prompts = ["k v", long_prompt, "hi"]
    expected = TutoringEngine(cfg).answer_batch(prompts)

    paged = PagedEngine(cfg, slots=2)
    assert len(paged.widths) == 2  # (4 + 8, 16 + 8) admissible widths
    narrow, wide = paged.widths
    # Short prompt first: engine rebuilds/stays at the narrow width.
    r0 = paged.submit(prompts[0])
    paged.step()
    assert paged.state.cache.k.shape[3] == narrow
    # Long prompt arrives mid-decode: the live cache pads up.
    r1 = paged.submit(prompts[1])
    out = {}
    while paged.has_work and len(out) < 2:
        out.update(paged.step())
    assert paged.state.cache.k.shape[3] == wide
    # Idle, then a short prompt: rebuild shrinks back to narrow.
    r2 = paged.submit(prompts[2])
    while paged.has_work:
        out.update(paged.step())
    assert paged.state.cache.k.shape[3] == narrow
    assert [out[r] for r in (r0, r1, r2)] == expected


def test_slot_reuse_evict_then_readmit():
    """slots=1 forces the second request through an evict→re-admit cycle in
    the same slot; outputs must match sequential fresh-drain runs."""
    cfg = make_config()
    sequential = PagedEngine(cfg, slots=1)
    r1 = sequential.submit(PROMPTS[0])
    out1 = sequential.drain()
    r2 = sequential.submit(PROMPTS[1])
    out2 = sequential.drain()

    fresh = PagedEngine(cfg, slots=1)
    f1 = fresh.submit(PROMPTS[0])
    f2 = fresh.submit(PROMPTS[1])
    both = fresh.drain()
    assert both[f1] == out1[r1]
    assert both[f2] == out2[r2]


def test_overflow_budget_clamped_or_rejected():
    # tiny's position table is 64. A budget of 50 clamps the prompt bucket
    # to 14 so bucket + max_new always fits (no silent KV corruption at
    # tmax); a budget leaving no prompt room at all is rejected.
    eng = PagedEngine(
        make_config(sampling=SamplingParams.greedy(max_new_tokens=50)), slots=2
    )
    assert eng.bucket == 14
    assert eng.bucket + 50 <= 64
    rid = eng.submit("a prompt much longer than fourteen byte-tokens")
    assert isinstance(eng.drain()[rid], str)
    with pytest.raises(ValueError, match="no room"):
        PagedEngine(
            make_config(sampling=SamplingParams.greedy(max_new_tokens=64)),
            slots=2,
        )


def test_paged_queue_serves_concurrent_requests():
    metrics = Metrics()
    engine = PagedEngine(make_config(), slots=2)

    async def run():
        q = PagedQueue(engine, metrics=metrics)
        await q.start()
        answers = await asyncio.gather(
            *[q.submit(f"query number {i}") for i in range(5)]
        )
        await q.close()
        return answers

    answers = asyncio.run(run())
    assert len(answers) == 5
    assert all(isinstance(a, str) for a in answers)
    # Per-request TTFT landed in the serving histogram.
    assert metrics.hist("ttft").snapshot()["count"] == 5


def test_paged_queue_recovers_after_step_failure():
    """A failed step fails its in-flight requests but must not poison the
    engine (step donates the live state) — later requests still serve."""
    engine = PagedEngine(make_config(), slots=2)
    orig_step = engine.step
    armed = {"on": True}

    def flaky_step():
        if armed["on"]:
            armed["on"] = False
            raise RuntimeError("injected device failure")
        return orig_step()

    engine.step = flaky_step

    async def run():
        q = PagedQueue(engine)
        await q.start()
        with pytest.raises(RuntimeError, match="injected"):
            await q.submit("first")
        answer = await q.submit("second")
        await q.close()
        return answer

    assert isinstance(asyncio.run(run()), str)


def test_dead_slot_pad_filler_not_appended_when_pad_differs_from_eos():
    """Regression (review): with a tokenizer where pad != eos, a slot that
    is inactive from admission (first sampled token is eos) must return an
    empty answer — chunk pad filler is not content."""
    import numpy as np

    from distributed_lms_raft_llm_tpu.engine.paged import PagedEngine

    paged = PagedEngine(make_config(), slots=2)
    # Force pad != eos and make admission sample eos immediately by
    # stubbing the prefill program's sampled first token.
    paged.tokenizer.pad_id = 0
    assert paged.tokenizer.eos_id != 0
    real_prefill = paged._prefill

    def eos_first(params, ids, true_len, rng):
        cache, _first, seen = real_prefill(params, ids, true_len, rng)
        import jax.numpy as jnp

        return cache, jnp.asarray(paged.tokenizer.eos_id, jnp.int32), seen

    paged._prefill = eos_first
    rid = paged.submit("anything at all")
    out = paged.drain()
    # The request finished with no pad-filler tokens decoded as content.
    assert out[rid] == paged.tokenizer.decode([])
