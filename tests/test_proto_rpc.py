"""Wire-contract smoke tests: message round-trips and a live gRPC exchange."""

import concurrent.futures

import grpc
import pytest

from distributed_lms_raft_llm_tpu.proto import lms_pb2, rpc


def test_message_roundtrip():
    req = lms_pb2.AppendEntriesRequest(
        leader=lms_pb2.TermLeaderIDPair(leaderID=2, term=5),
        prevLogIndex=3,
        prevLogTerm=4,
        entries=[lms_pb2.LogEntry(term=5, command='{"operation":"Register"}')],
        leaderCommit=3,
    )
    out = lms_pb2.AppendEntriesRequest.FromString(req.SerializeToString())
    assert out.leader.leaderID == 2 and out.leader.term == 5
    assert out.entries[0].command == '{"operation":"Register"}'


def test_frozen_contract_method_names():
    # The frozen contract (reference GUI_RAFT_LLM_SourceCode/lms.proto:106-142):
    # exact service and method names — a rename breaks every existing client.
    services = lms_pb2.DESCRIPTOR.services_by_name
    assert sorted(services) == [
        "FileTransferService",
        "LMS",
        "RaftService",
        "Tutoring",
    ]
    lms_methods = {m.name for m in services["LMS"].methods}
    lms_frozen = {
        "Register",
        "Login",
        "Logout",
        "Post",
        "Get",
        "GradeAssignment",
        "GetGrade",
        "GetLLMAnswer",
        "GetUnansweredQueries",
        "RespondToQuery",
        "GetInstructorResponse",
        "WhoIsLeader",
    }
    assert lms_methods >= lms_frozen
    assert lms_methods - lms_frozen == {"StreamLLMAnswer"}
    tutoring_methods = {m.name for m in services["Tutoring"].methods}
    assert tutoring_methods >= {"GetLLMAnswer"}
    assert tutoring_methods - {"GetLLMAnswer"} == {"StreamLLMAnswer"}
    # The streaming additions are server-streaming (unary-stream) on both
    # services, with identical request/response shapes.
    for svc in ("LMS", "Tutoring"):
        method = services[svc].methods_by_name["StreamLLMAnswer"]
        assert method.server_streaming and not method.client_streaming
        assert method.input_type.name == "StreamRequest"
        assert method.output_type.name == "StreamChunk"
        assert rpc._SERVICES[svc]["StreamLLMAnswer"][2] == "us"
    # Frozen = the reference surface never shrinks or renames; additive
    # methods (which old peers simply never call) are the sanctioned
    # extension mechanism. Assert superset + name the additions exactly, so
    # an accidental addition still fails here.
    raft_methods = {m.name for m in services["RaftService"].methods}
    assert raft_methods >= {
        "RequestVote", "AppendEntries", "SetVal", "GetVal", "GetLeader",
        "WhoIsLeader",
    }
    assert raft_methods - {
        "RequestVote", "AppendEntries", "SetVal", "GetVal", "GetLeader",
        "WhoIsLeader",
    } == {"InstallSnapshot", "TimeoutNow"}
    ft_methods = {m.name for m in services["FileTransferService"].methods}
    assert ft_methods >= {"SendFile", "ReplicateData"}
    assert ft_methods - {"SendFile", "ReplicateData"} == {"FetchFile"}
    # Stream-unary only for SendFile.
    assert services["FileTransferService"].methods_by_name["SendFile"].client_streaming
    assert rpc._SERVICES["FileTransferService"]["SendFile"][2] == "su"


class _Raft(rpc.RaftServiceServicer):
    def WhoIsLeader(self, request, context):
        return lms_pb2.LeaderResponse(leader_id=3)


class _StreamTutor(rpc.TutoringServicer):
    def StreamLLMAnswer(self, request, context):
        for i in range(request.resume_offset, 3):
            yield lms_pb2.StreamChunk(
                success=True,
                text=f"tok{i} ",
                offset=i,
                count=1,
                final=(i == 2),
                digest="d" if i == 2 else "",
            )


class _Files(rpc.FileTransferServiceServicer):
    def SendFile(self, request_iterator, context):
        total = sum(len(chunk.content) for chunk in request_iterator)
        return lms_pb2.FileTransferResponse(status=f"success:{total}")


@pytest.fixture()
def live_server():
    server = grpc.server(concurrent.futures.ThreadPoolExecutor(max_workers=4))
    rpc.add_RaftServiceServicer_to_server(_Raft(), server)
    rpc.add_FileTransferServiceServicer_to_server(_Files(), server)
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    yield f"127.0.0.1:{port}"
    server.stop(grace=None)


def test_unary_rpc_over_wire(live_server):
    with grpc.insecure_channel(live_server) as channel:
        stub = rpc.RaftServiceStub(channel)
        resp = stub.WhoIsLeader(lms_pb2.Empty(), timeout=5)
        assert resp.leader_id == 3


def test_stream_unary_rpc_over_wire(live_server):
    with grpc.insecure_channel(live_server) as channel:
        stub = rpc.FileTransferServiceStub(channel)
        chunks = (
            lms_pb2.FileChunk(content=b"x" * 10, destination_path="uploads/a.pdf")
            for _ in range(3)
        )
        resp = stub.SendFile(chunks, timeout=5)
        assert resp.status == "success:30"


def test_unary_stream_rpc_over_wire():
    server = grpc.server(concurrent.futures.ThreadPoolExecutor(max_workers=1))
    rpc.add_TutoringServicer_to_server(_StreamTutor(), server)
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    try:
        with grpc.insecure_channel(f"127.0.0.1:{port}") as channel:
            stub = rpc.TutoringStub(channel)
            chunks = list(
                stub.StreamLLMAnswer(
                    lms_pb2.StreamRequest(query="q", resume_offset=1), timeout=5
                )
            )
            assert [c.offset for c in chunks] == [1, 2]
            assert chunks[-1].final and chunks[-1].digest == "d"
    finally:
        server.stop(grace=None)


def test_unimplemented_method_raises():
    server = grpc.server(concurrent.futures.ThreadPoolExecutor(max_workers=1))
    rpc.add_TutoringServicer_to_server(rpc.TutoringServicer(), server)
    port = server.add_insecure_port("127.0.0.1:0")
    server.start()
    try:
        with grpc.insecure_channel(f"127.0.0.1:{port}") as channel:
            stub = rpc.TutoringStub(channel)
            with pytest.raises(grpc.RpcError) as e:
                stub.GetLLMAnswer(lms_pb2.QueryRequest(query="q"), timeout=5)
            assert e.value.code() in (
                grpc.StatusCode.UNIMPLEMENTED,
                grpc.StatusCode.UNKNOWN,
            )
    finally:
        server.stop(grace=None)
