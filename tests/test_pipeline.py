"""Pipeline parallelism (parallel/pipeline.py): stage-sharded trunk parity."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_lms_raft_llm_tpu.parallel import make_mesh
from distributed_lms_raft_llm_tpu.parallel.pipeline import pipeline_trunk


def _block(lp, h):
    """A representative transformer-ish layer: norm + dense + gelu + residual."""
    hn = h * jax.lax.rsqrt(jnp.mean(h * h, axis=-1, keepdims=True) + 1e-6)
    return h + jax.nn.gelu(hn @ lp["w"]) @ lp["w2"]


def _stacked_params(layers, d, rng):
    return {
        "w": jnp.asarray(rng.normal(size=(layers, d, 2 * d)) * 0.1,
                         jnp.float32),
        "w2": jnp.asarray(rng.normal(size=(layers, 2 * d, d)) * 0.1,
                          jnp.float32),
    }


def _sequential(params, x):
    def body(h, lp):
        return _block(lp, h), None

    out, _ = jax.lax.scan(body, x, params)
    return out


@pytest.mark.parametrize("pp,n_micro", [(2, 2), (4, 4), (8, 2), (2, 8)])
def test_pipeline_matches_sequential_scan(pp, n_micro):
    mesh = make_mesh({"pp": pp, "dp": -1})
    rng = np.random.default_rng(0)
    layers, b, t, d = 8, 8, 4, 16
    params = _stacked_params(layers, d, rng)
    x = jnp.asarray(rng.normal(size=(b, t, d)), jnp.float32)
    expected = _sequential(params, x)
    with mesh:
        got = pipeline_trunk(_block, params, x, mesh, n_micro=n_micro)
    np.testing.assert_allclose(
        np.asarray(expected), np.asarray(got), rtol=2e-5, atol=2e-5
    )


def test_pipeline_under_jit_with_gpt2_block():
    """The real GPT-2 block math through the pipeline, jitted."""
    from distributed_lms_raft_llm_tpu.models import gpt2
    from distributed_lms_raft_llm_tpu.models.common import (
        attend, causal_window_mask, dense, layer_norm, merge_heads,
        split_heads,
    )

    cfg = gpt2.GPT2Config(
        vocab_size=384, max_position_embeddings=64, hidden_size=32,
        num_layers=4, num_heads=4,
    )
    params = gpt2.init_params(jax.random.key(0), cfg)
    b, t = 4, 8
    # Batch-dim 1: the same mask must broadcast over full batch (sequential
    # reference) and per-stage microbatches (pipeline).
    mask = causal_window_mask(jnp.arange(t)[None, :], t)

    def block(lp, x):
        h = layer_norm(x, lp["ln1"]["scale"], lp["ln1"]["bias"],
                       cfg.layer_norm_eps)
        qkv = dense(h, lp["attn"]["wqkv"], lp["attn"]["bqkv"])
        q, k, v = jnp.split(qkv, 3, axis=-1)
        a = attend(split_heads(q, cfg.num_heads),
                   split_heads(k, cfg.num_heads),
                   split_heads(v, cfg.num_heads), mask)
        x = x + dense(merge_heads(a), lp["attn"]["wo"], lp["attn"]["bo"])
        h2 = layer_norm(x, lp["ln2"]["scale"], lp["ln2"]["bias"],
                        cfg.layer_norm_eps)
        m = dense(h2, lp["mlp"]["wi"], lp["mlp"]["bi"])
        return x + dense(jax.nn.gelu(m, approximate=True),
                         lp["mlp"]["wo"], lp["mlp"]["bo"])

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.normal(size=(b, t, cfg.hidden_size)), jnp.float32)
    blocks = params["blocks"]

    def seq(blocks, x):
        out, _ = jax.lax.scan(lambda h, lp: (block(lp, h), None), x, blocks)
        return out

    expected = seq(blocks, x)
    mesh = make_mesh({"pp": 2, "dp": -1})
    with mesh:
        got = jax.jit(
            lambda p, x: pipeline_trunk(block, p, x, mesh, n_micro=2)
        )(blocks, x)
    np.testing.assert_allclose(
        np.asarray(expected), np.asarray(got), rtol=2e-5, atol=2e-5
    )


def test_pipeline_rejects_bad_microbatching():
    mesh = make_mesh({"pp": 2, "dp": -1})
    params = _stacked_params(4, 8, np.random.default_rng(2))
    x = jnp.zeros((6, 2, 8), jnp.float32)
    with pytest.raises(ValueError, match="not divisible"):
        pipeline_trunk(_block, params, x, mesh, n_micro=4)
