"""The compiled-program inventory: static manifest <-> runtime caches.

Three claims, each pinned:

- the checked-in manifest and README table match what the generator
  derives from the tree (drift fails tier-1, same scheme as the metrics
  table);
- after `warmup()`, a live paged session under
  `compile_count_guard(expected_from_inventory(eng))` compiles nothing
  and every inventoried program's cache size EQUALS the manifest's
  expectation — the acceptance path;
- both drift directions raise: skipping warmup (uncovered programs
  compile live) and a stale expectation (manifest counts the engine
  doesn't have).
"""

import subprocess
import sys
from pathlib import Path

import jax.numpy as jnp
import pytest

from distributed_lms_raft_llm_tpu.engine import (
    EngineConfig,
    PagedEngine,
    SamplingParams,
    TutoringEngine,
)
from distributed_lms_raft_llm_tpu.engine import program_inventory as inv
from distributed_lms_raft_llm_tpu.utils.guards import (
    InventoryMismatchError,
    RecompileError,
    compile_count_guard,
    expected_from_inventory,
)

REPO = Path(__file__).resolve().parent.parent


def make_engine(**kw):
    kw.setdefault("length_buckets", (4, 16))
    return PagedEngine(
        EngineConfig(
            model="tiny",
            sampling=SamplingParams.greedy(max_new_tokens=8),
            batch_buckets=(1, 2),
            dtype=jnp.float32,
            **kw,
        ),
        slots=2, chunk=2,
    )


# ----------------------------------------------------- generated artifacts


def test_manifest_and_readme_match_static_scan():
    """scripts/gen_program_inventory.py --check: the INVENTORY block and
    the README program-inventory table are regenerated and compared."""
    out = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "gen_program_inventory.py"),
         "--check"],
        capture_output=True, text=True, cwd=str(REPO), timeout=300,
    )
    assert out.returncode == 0, out.stdout + out.stderr


def test_manifest_covers_the_paged_program_set():
    attrs = {e.attr for e in inv.entries_for("PagedEngine")}
    assert attrs == {"_prefill", "_install", "_step", "_megastep", "_grow",
                     "_partial_prefill", "_load_block", "_export_block",
                     "_stage", "_stage_block", "_score"}
    assert all(
        e.coverage == "warmup" for e in inv.entries_for("PagedEngine")
    ), "the paged engine's whole program set is a warmup promise"
    # The bulk-scoring program is a warmup promise on BOTH engines
    # (domain empty when EngineConfig.scoring is off).
    score = [e for e in inv.entries_for("TutoringEngine")
             if e.attr == "_score"]
    assert score and score[0].coverage == "warmup"
    assert score[0].domain == "score-pairs"


def test_static_domain_math_is_engine_math():
    """static_paged_domain mirrors PagedEngine.__init__'s derivation for
    representative configs (incl. spec-mode overhang and bucket capping)."""
    for spec_tokens, buckets in ((0, (4, 16)), (3, (4, 8, 16)), (2, (16,))):
        eng = make_engine(length_buckets=buckets, spec_tokens=spec_tokens)
        dom = inv.static_paged_domain(
            eng.cfg.max_position_embeddings,
            eng.config.sampling.max_new_tokens,
            buckets, spec_tokens,
        )
        assert dom["widths"] == list(eng.widths)
        assert max(dom["buckets"]) <= eng.bucket
    # The shared-prefix domain: zero with the cache off, the admissible
    # (bucket, suffix-bucket) pairs (one whole block of prefix must fit
    # the window) with it on.
    off = inv.static_paged_domain(64, 8, (8, 16), 0)
    assert off["partial_pairs"] == off["export_buckets"] == 0
    on = inv.static_paged_domain(64, 8, (8, 16), 0, prefix_cache=True,
                                 prefix_block_tokens=4)
    assert on["partial_pairs"] == 1   # only (t=16, s=8) admits a block
    assert on["export_buckets"] == 2  # both buckets can publish
    assert on["load_buckets"] == 1    # only t=16 can splice


# ------------------------------------------------- runtime cross-validation


def test_warmed_paged_session_passes_inventory_guard():
    """The acceptance path: warmup compiles exactly the inventoried
    domain, then a live session (two widths, slot churn) adds nothing."""
    eng = make_engine()
    eng.warmup()
    expectation = expected_from_inventory(eng)
    # The static counts ARE the live caches post-warmup...
    assert expectation.mismatches() == {}
    # ...and stay so through a live session.
    with compile_count_guard(expectation) as guard:
        eng.submit("k v")
        eng.step()
        eng.submit("a longer question about raft elections and logs")
        eng.drain()
    assert guard.new_compiles() == 0


def test_missing_warmup_fails_the_inventory_guard():
    """Removing warmup coverage the static rule can't see (warmup still
    REACHES every program, it just compiles fewer shapes) is the runtime
    guard's half: an unwarmed engine compiles live and the guard raises."""
    eng = make_engine()  # no warmup()
    with pytest.raises(RecompileError):
        with compile_count_guard(expected_from_inventory(eng)):
            eng.submit("hello")
            eng.drain()


def test_stale_inventory_expectation_fails_the_guard():
    """The other drift direction: the manifest expecting MORE programs
    than the engine compiles (a stale entry/domain) fails at guard exit."""
    eng = make_engine()
    eng.warmup()
    expectation = expected_from_inventory(eng)
    expectation.expected["_step"] += 1  # simulate a stale manifest claim
    with pytest.raises(InventoryMismatchError, match="stale"):
        with compile_count_guard(expectation):
            pass


def test_inventory_guard_rejects_unlisted_engines():
    """expected_from_inventory only makes sense for engines whose warmup
    promises full coverage; the bucketed engine compiles per live shape
    by design and must be rejected loudly, not guarded wrongly."""
    eng = TutoringEngine(EngineConfig(
        model="tiny", sampling=SamplingParams.greedy(max_new_tokens=4),
        length_buckets=(8,), batch_buckets=(1,), dtype=jnp.float32,
    ))
    with pytest.raises(InventoryMismatchError, match="warmup-covered"):
        expected_from_inventory(eng)
