"""Tier-1 guard: soak-shaped tests must be marked `slow`.

Runs the same audit as `python scripts/audit_markers.py` (tier-1 executes
with `-m 'not slow'` under a hard timeout, so one unmarked soak blows the
whole budget — this makes the convention self-enforcing).
"""

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO / "scripts"))

from audit_markers import audit  # noqa: E402


def test_slow_marker_convention_enforced():
    violations = audit(REPO / "tests")
    assert not violations, "\n".join(violations)
