"""Engine end-to-end on the 8-device CPU mesh: tiny model, real pipeline."""

import asyncio

import numpy as np
import pytest

import jax

from distributed_lms_raft_llm_tpu.engine import (
    BatchingQueue,
    EngineConfig,
    GateConfig,
    RelevanceGate,
    SamplingParams,
    TutoringEngine,
)


@pytest.fixture(scope="module")
def engine():
    cfg = EngineConfig(
        model="tiny",
        sampling=SamplingParams(max_new_tokens=8),
        length_buckets=(16, 32),
        batch_buckets=(1, 2, 4),
        tp=2,  # exercise tensor parallelism on the virtual mesh
        dtype=jax.numpy.float32,
    )
    return TutoringEngine(cfg)


def test_engine_mesh_uses_all_devices(engine):
    assert engine.mesh.devices.size == 8  # 2-way tp × 4-way dp


def test_answer_batch_shapes_and_determinism(engine, strict_dispatch_guard):
    # Runs under dispatch-hygiene assertion mode (conftest fixture): every
    # host sync on the serving path must be a marked intended_transfer().
    answers = engine.answer_batch(["hello world", "what is raft?"])
    assert len(answers) == 2
    assert all(isinstance(a, str) for a in answers)


def test_prompt_longer_than_bucket_keeps_tail(engine):
    long_prompt = "x" * 500  # 500 byte-tokens > largest bucket (32)
    ids, mask, bucket = engine.encode_prompts([long_prompt])
    assert bucket <= 32 - 0  # bucketed
    assert ids.shape[1] <= 32
    assert mask[0].all()  # fully real after truncation to the tail


def test_empty_prompt_is_well_formed(engine):
    answers = engine.answer_batch([""])
    assert len(answers) == 1


def test_batch_bucketing_pads_filler_rows(engine):
    ids, mask, _ = engine.encode_prompts(["a", "b", "c"])
    assert ids.shape[0] == 4  # bucketed to 4
    assert mask[3].sum() == 1  # filler row has exactly one valid slot


def test_generation_respects_max_new_tokens(engine):
    ids, mask, _ = engine.encode_prompts(["hello"])
    result = engine.generate_ids(ids, mask)
    assert result.tokens.shape[1] == 8
    assert (result.lengths <= 8).all()


def test_batching_queue_coalesces():
    cfg = EngineConfig(
        model="tiny",
        sampling=SamplingParams(max_new_tokens=4),
        length_buckets=(16,),
        batch_buckets=(1, 2, 4),
        dtype=jax.numpy.float32,
    )
    eng = TutoringEngine(cfg)
    calls = []
    orig = eng.answer_batch

    def spy(prompts):
        calls.append(len(prompts))
        return orig(prompts)

    eng.answer_batch = spy

    async def run():
        q = BatchingQueue(eng, max_batch=4, max_wait_ms=200)
        await q.start()
        answers = await asyncio.gather(*[q.submit(f"q{i}") for i in range(4)])
        await q.close()
        return answers

    answers = asyncio.run(run())
    assert len(answers) == 4
    assert max(calls) >= 2  # at least some coalescing happened


def test_relevance_gate_threshold():
    gate = RelevanceGate(GateConfig(model="tiny", dtype=jax.numpy.float32))
    ok, sim = gate.check("what is a binary tree", "binary trees and traversals")
    assert -1.0 <= sim <= 1.0
    self_ok, self_sim = gate.check("same text", "same text")
    assert self_ok and self_sim == pytest.approx(1.0, abs=1e-4)


def test_answer_batch_chunks_oversized_groups():
    cfg = EngineConfig(
        model="tiny",
        sampling=SamplingParams(max_new_tokens=4),
        length_buckets=(16,),
        batch_buckets=(1, 2, 4),
        dtype=jax.numpy.float32,
    )
    eng = TutoringEngine(cfg)
    answers = eng.answer_batch([f"q{i}" for i in range(9)])  # > max bucket 4
    assert len(answers) == 9


def test_max_new_tokens_validated_against_position_table():
    with pytest.raises(ValueError, match="max_new_tokens"):
        TutoringEngine(
            EngineConfig(model="tiny", sampling=SamplingParams(max_new_tokens=128))
        )


def test_queue_close_fails_pending_submits():
    cfg = EngineConfig(
        model="tiny",
        sampling=SamplingParams(max_new_tokens=4),
        length_buckets=(16,),
        batch_buckets=(1,),
        dtype=jax.numpy.float32,
    )
    eng = TutoringEngine(cfg)

    async def run():
        q = BatchingQueue(eng, max_batch=1, max_wait_ms=1)
        await q.start()
        tasks = [asyncio.create_task(q.submit(f"q{i}")) for i in range(3)]
        await asyncio.sleep(0.05)  # let some enter flight
        await q.close()
        results = await asyncio.gather(*tasks, return_exceptions=True)
        return results

    results = asyncio.run(run())
    # Every pending submit resolved (answer or RuntimeError) — none hang.
    assert all(isinstance(r, (str, RuntimeError)) for r in results)


class TestScore:
    """engine.score: log-likelihood scoring (the long-context surface)."""

    def _engine(self, **kw):
        kw.setdefault("model", "tiny")
        kw.setdefault("sampling", SamplingParams(max_new_tokens=4))
        kw.setdefault("length_buckets", (16, 32))
        kw.setdefault("batch_buckets", (1, 2))
        kw.setdefault("dtype", jax.numpy.float32)
        kw.setdefault("param_dtype", jax.numpy.float32)
        return TutoringEngine(EngineConfig(**kw))

    def test_matches_manual_log_softmax(self):
        import jax.numpy as jnp

        eng = self._engine()
        text = "raft elects a leader"  # fits the 32-token bucket
        [res] = eng.score([text])
        toks = eng.tokenizer.encode(text)
        logits, _ = eng.family.forward(
            eng.params, eng.cfg, jnp.asarray([toks], jnp.int32)
        )
        logp = jax.nn.log_softmax(
            jnp.asarray(logits[0], jnp.float32), axis=-1
        )
        want = float(sum(
            logp[i, toks[i + 1]] for i in range(len(toks) - 1)
        ))
        assert res["tokens"] == len(toks) - 1
        np.testing.assert_allclose(res["logprob"], want, rtol=1e-4,
                                   atol=1e-4)
        np.testing.assert_allclose(
            res["ppl"], float(np.exp(-want / (len(toks) - 1))), rtol=1e-4
        )

    def test_ring_sharded_score_matches_single_device(self):
        dense = self._engine()
        ring = self._engine(sp=2)
        assert ring.mesh.shape["sp"] == 2
        texts = ["the leader replicates logs",
                 "a quorum is a majority"]
        a = dense.score(texts)
        b = ring.score(texts)
        for ra, rb in zip(a, b):
            assert ra["tokens"] == rb["tokens"]
            np.testing.assert_allclose(ra["logprob"], rb["logprob"],
                                       rtol=1e-4, atol=1e-4)

    def test_moe_scores(self):
        eng = self._engine(model="moe-tiny")
        [res] = eng.score(["hello experts"])
        assert res["tokens"] >= 1 and np.isfinite(res["ppl"])

    def test_oversized_group_chunks(self):
        # More texts than the largest batch bucket run as several device
        # batches (mirrors answer_batch), order preserved.
        eng = self._engine()
        texts = [f"text number {i}" for i in range(5)]  # cap is 2
        res = eng.score(texts)
        assert len(res) == 5
        # Chunking must not change any individual score.
        [alone] = eng.score([texts[3]])
        np.testing.assert_allclose(res[3]["logprob"], alone["logprob"],
                                   rtol=1e-4, atol=1e-4)
