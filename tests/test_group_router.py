"""Unit tests for the sharded control plane (PR 16).

Covers the routing map, per-group leader-hint lanes, per-group fault
targeting, config validation, and — the acceptance bar — the
crash-point checker: the reshard handoff is crashed after EVERY
journaled step and rolled forward by `recover()`, asserting the moved
slice lands exactly once, the map flips exactly once, and the source is
left with tombstones instead of frozen markers. The live-cluster side
(split under chaos at diurnal peak) is exercised in test_semester_sim.
"""

import asyncio
from types import SimpleNamespace
from typing import Any, Dict, List, Optional

import pytest

from distributed_lms_raft_llm_tpu.client.client import LMSClient
from distributed_lms_raft_llm_tpu.config import GroupsConfig, SimConfig
from distributed_lms_raft_llm_tpu.lms.group_router import (
    RESHARD_JOURNAL_KEY,
    ROUTING_MAP_KEY,
    GroupLeaderHints,
    GroupsAdmin,
    ReshardCoordinator,
    RoutingMap,
    stable_hash,
)
from distributed_lms_raft_llm_tpu.lms.state import LMSState
from distributed_lms_raft_llm_tpu.utils.faults import FaultInjector


# --------------------------------------------------------------- RoutingMap


def test_routing_map_initial_assigns_courses_round_robin():
    m = RoutingMap.initial(2, ["course1", "course0", "course2"])
    # Sorted course order, then round-robin over the groups.
    assert m.courses == {"course0": 0, "course1": 1, "course2": 0}
    assert m.version == 1
    assert m.n_groups == 2


def test_routing_map_resolution_order():
    m = RoutingMap(
        version=3,
        n_groups=3,
        courses={"course0": 1},
        overrides={"special": 2},
    )
    course_of = lambda u: "course0" if u.startswith("stu") else None
    # Override beats everything.
    assert m.group_for("special", course_of) == 2
    # Course table next.
    assert m.group_for("stu7", course_of) == 1
    # Hash fallback when the course is unknown.
    assert m.group_for("nobody", course_of) == stable_hash("nobody") % 3
    # Hash fallback also without a course function at all.
    assert m.group_for("stu7") == stable_hash("stu7") % 3


def test_routing_map_ignores_out_of_range_entries():
    m = RoutingMap(n_groups=2, courses={"course0": 9}, overrides={"a": -1})
    assert m.group_for("a", lambda u: "course0") == stable_hash("a") % 2


def test_routing_map_json_round_trip_and_defaults():
    m = RoutingMap(version=5, n_groups=4, courses={"c": 3}, overrides={"u": 1})
    again = RoutingMap.from_json(m.to_json())
    assert again == m
    # Old/foreign documents with missing fields get sane defaults.
    bare = RoutingMap.from_json("{}")
    assert (bare.version, bare.n_groups, bare.courses, bare.overrides) == (
        1, 1, {}, {},
    )


def test_stable_hash_is_process_independent():
    # sha1-derived, unlike builtin hash(): pin a literal so a future
    # "optimization" to hash() fails loudly.
    assert stable_hash("alice") == int(
        __import__("hashlib").sha1(b"alice").hexdigest()[:12], 16
    )


# --------------------------------------------------- leader hints, per lane


def test_group_leader_hints_evict_is_per_lane():
    hints = GroupLeaderHints()
    hints.update(0, 1)
    hints.update(2, 3)
    hints.evict(2)
    assert hints.get(0) == 1
    assert hints.get(2) is None
    assert hints.snapshot() == {0: 1}


def test_client_hint_lanes_are_independent():
    client = LMSClient(["127.0.0.1:1", "127.0.0.1:2"])
    client._set_leader("127.0.0.1:1", group=0)
    client._set_leader("127.0.0.1:2", group=1)
    # Losing group 1's leader must not blow away group 0's hint.
    client.evict_leader_hint(group=1)
    assert client._leader_hints == {0: "127.0.0.1:1"}
    # Address-scoped evict drops every lane pointing at that address.
    client._set_leader("127.0.0.1:1", group=1)
    client.evict_leader_hint("127.0.0.1:1")
    assert client._leader_hints == {}


def test_client_leader_addr_property_is_lane_zero():
    client = LMSClient(["127.0.0.1:1"])
    client._leader_addr = "127.0.0.1:9"
    assert client._leader_hints == {0: "127.0.0.1:9"}
    assert client._leader_addr == "127.0.0.1:9"
    client._leader_addr = None
    assert client._leader_addr is None


def test_client_home_group_uses_group_of():
    client = LMSClient(["127.0.0.1:1"], group_of=lambda u: 2)
    assert client._home_group() == 0  # not logged in yet
    client._username = "alice"
    assert client._home_group() == 2


# ----------------------------------------------------- per-group fault tier


def test_fault_spec_for_walks_group_hierarchy():
    inj = FaultInjector(seed=0)
    inj.configure("raft", drop=0.1)
    inj.configure("raft:1", drop=0.2)
    inj.configure("raft:1:3", drop=0.3)
    # Most specific wins; missing levels fall back one segment at a time.
    assert inj.spec_for("raft:1:3").drop == 0.3
    assert inj.spec_for("raft:1:9").drop == 0.2
    assert inj.spec_for("raft:2:9").drop == 0.1
    assert inj.spec_for("raft:2").drop == 0.1
    inj.configure("*", drop=0.9)
    assert inj.spec_for("tutoring:5").drop == 0.9


# ------------------------------------------------------------------- config


def test_groups_config_validates():
    assert GroupsConfig().count == 1
    with pytest.raises(ValueError):
        GroupsConfig(count=0)
    with pytest.raises(ValueError):
        GroupsConfig(port_stride=0)
    with pytest.raises(ValueError):
        SimConfig(lms_groups=0)


# ------------------------------------------------- state-machine idempotence


def test_register_applier_is_idempotent():
    state = LMSState()
    args = {
        "username": "alice",
        "password_hash": "h1",
        "role": "student",
        "request_id": "r1",
    }
    state.apply("Register", args)
    # Retry with the same request id: dropped by the ledger.
    state.apply("Register", args)
    # A different rid but same username: applier keeps the first record.
    state.apply(
        "Register",
        {**args, "password_hash": "h2", "request_id": "r2"},
    )
    assert state.data["users"]["alice"]["password"] == "h1"


def test_frozen_guard_blocks_source_writes():
    state = LMSState()
    state.apply("FreezeKeys", {"users": ["alice"], "reshard_id": "rs1"})
    state.apply(
        "PostAssignment",
        {"student": "alice", "filename": "a", "filepath": "p", "text": "t"},
    )
    assert "alice" not in state.data["assignments"]
    assert state.frozen_for("alice") == "rs1"


# ----------------------------------------------------- crash-point checker


class FakeAccess:
    """GroupAccess over in-memory LMSStates: proposals apply directly,
    the meta kv is group 0's kv — exactly the meta-group layout the live
    cluster replicates, minus the Raft hop. State survives coordinator
    "crashes" the way Raft-committed state survives process crashes."""

    def __init__(self, n_groups: int, courses: List[str], users: Dict[str, str]):
        self._n = n_groups
        self._users = users  # username -> course
        self._states = {gid: LMSState() for gid in range(n_groups)}
        self._initial = RoutingMap.initial(n_groups, courses)
        self.fences: List[int] = []

    def course_of(self, username: str) -> Optional[str]:
        return self._users.get(username)

    def n_groups(self) -> int:
        return self._n

    def users(self) -> List[str]:
        return sorted(self._users)

    def state(self, gid: int) -> LMSState:
        return self._states[gid]

    def current_map(self) -> RoutingMap:
        raw = self._states[0].data["kv"].get(ROUTING_MAP_KEY)
        return RoutingMap.from_json(raw) if raw else self._initial

    async def read_fence(self, gid: int) -> None:
        self.fences.append(gid)

    async def propose(self, gid: int, op: str, args: Dict[str, Any]) -> None:
        self._states[gid].apply(op, args)

    async def meta_get(self, key: str) -> Optional[str]:
        return self._states[0].data["kv"].get(key)

    async def meta_set(self, key: str, value: str) -> None:
        self._states[0].apply("SetVal", {"key": key, "value": value})


class _Crash(Exception):
    pass


def _seeded_access() -> FakeAccess:
    """Two groups; course0 lives on group 0 with two users who have
    acked writes. The handoff under test moves course0 to group 1."""
    access = FakeAccess(
        2,
        ["course0", "course1"],
        {"alice": "course0", "bob": "course0", "carol": "course1"},
    )
    src = access.state(0)
    src.apply(
        "PostAssignment",
        {"student": "alice", "filename": "hw1", "filepath": "p1",
         "text": "t1", "request_id": "w1"},
    )
    src.apply(
        "AskQuery",
        {"username": "bob", "query": "why?", "request_id": "w2"},
    )
    src.apply(
        "PostCourseMaterial",
        {"instructor": "alice", "filename": "notes", "filepath": "p2",
         "request_id": "w3"},
    )
    return access


def _assert_handoff_consistent(access: FakeAccess) -> None:
    """The acceptance invariants, checked after recovery from ANY crash
    point: map flipped exactly once, slice present exactly once on the
    target, source left with tombstones (not frozen markers), and no
    acked write lost."""
    m = access.current_map()
    assert m.courses["course0"] == 1
    assert m.version == 2  # exactly one bump, no matter how many replays
    dst = access.state(1).data
    assert len(dst["assignments"]["alice"]) == 1
    assert dst["assignments"]["alice"][0]["filename"] == "hw1"
    assert len(dst["queries"]["bob"]) == 1
    assert [mat["filepath"] for mat in dst["course_materials"]] == ["p2"]
    # The source's idempotency ledger rode along: late client retries of
    # pre-freeze writes dedup on the target instead of applying twice.
    for rid in ("w1", "w2", "w3"):
        assert rid in dst["applied_requests"]
    src = access.state(0).data
    assert "alice" not in src["assignments"]
    assert "bob" not in src["queries"]
    assert src["course_materials"] == []
    assert not src.get("frozen")
    assert set(src["moved"]) == {"alice", "bob"}
    # carol (course1) was never part of the handoff.
    assert "carol" not in src["moved"]


def test_reshard_completes_without_crash():
    async def run():
        access = _seeded_access()
        steps: List[str] = []
        coord = ReshardCoordinator(
            access, course_of=access.course_of, on_step=steps.append
        )
        result = await coord.reshard("course0", 1)
        assert result["ok"] and result["step"] == "done"
        assert result["moved_users"] == 2
        assert result["version"] == 2
        assert steps == ["begin", "frozen", "installed", "committed", "done"]
        # The slice was read behind a fence on the source.
        assert access.fences == [0]
        _assert_handoff_consistent(access)
        # Re-running recover() afterwards is a no-op.
        again = await ReshardCoordinator(
            access, course_of=access.course_of
        ).recover()
        assert again["noop"]

    asyncio.run(run())


@pytest.mark.parametrize(
    "crash_at", ["begin", "frozen", "installed", "committed"]
)
def test_reshard_crash_point_checker(crash_at):
    """Crash the coordinator immediately after EVERY journaled step in
    turn, then roll forward with a fresh coordinator (a restarted node),
    asserting the same final invariants every time — this is the
    acceptance criterion's handoff-journal checker."""

    async def run():
        access = _seeded_access()

        def crash(step: str) -> None:
            if step == crash_at:
                raise _Crash(step)

        coord = ReshardCoordinator(
            access, course_of=access.course_of, on_step=crash
        )
        with pytest.raises(_Crash):
            await coord.reshard("course0", 1)
        # The journal names the furthest persisted step.
        raw = await access.meta_get(RESHARD_JOURNAL_KEY)
        assert raw is not None
        # A fresh coordinator (no crash hook) rolls forward to done.
        result = await ReshardCoordinator(
            access, course_of=access.course_of
        ).recover()
        assert result["ok"] and result["step"] == "done"
        _assert_handoff_consistent(access)

    asyncio.run(run())


def test_reshard_recover_replays_committed_substep():
    """The nastiest crash window: a state-machine command committed but
    the journal step after it did NOT persist. Recovery blindly
    re-proposes the command; the deterministic request_id makes the
    replay a ledger no-op instead of a double-apply."""

    async def run():
        access = _seeded_access()
        rid = "reshard-course0-0-1-v1"
        # FreezeKeys committed on the source...
        await access.propose(
            0,
            "FreezeKeys",
            {"users": ["alice", "bob"], "reshard_id": rid,
             "request_id": rid + ":freeze"},
        )
        # ...but the journal still says "begin" (crash before _journal).
        import json

        await access.meta_set(
            RESHARD_JOURNAL_KEY,
            json.dumps({
                "id": rid, "step": "begin", "course": "course0",
                "src": 0, "dst": 1, "users": ["alice", "bob"],
            }),
        )
        result = await ReshardCoordinator(
            access, course_of=access.course_of
        ).recover()
        assert result["step"] == "done"
        _assert_handoff_consistent(access)

    asyncio.run(run())


def test_reshard_noop_and_validation():
    async def run():
        access = _seeded_access()
        coord = ReshardCoordinator(access, course_of=access.course_of)
        # Already home: structured no-op, no journal written.
        result = await coord.reshard("course0", 0)
        assert result["noop"]
        assert await access.meta_get(RESHARD_JOURNAL_KEY) is None
        with pytest.raises(ValueError):
            await coord.reshard("courseX", 1)
        with pytest.raises(ValueError):
            await coord.reshard("course0", 7)
        # Nothing in flight: recover is a clean no-op too.
        assert (await coord.recover())["noop"]

    asyncio.run(run())


# -------------------------------------------------------------- admin plane


def _fake_lms_node(leader_id, is_leader, term, applied, commit):
    core = SimpleNamespace(
        current_term=term, last_applied=applied, commit_index=commit
    )
    node = SimpleNamespace(leader_id=leader_id, is_leader=is_leader, core=core)
    return SimpleNamespace(node=node, addresses={1: "127.0.0.1:7001"})


def test_groups_admin_topology_shape():
    admin = GroupsAdmin({
        0: _fake_lms_node(1, True, 3, 10, 10),
        1: _fake_lms_node(2, False, 2, 5, 6),
    })
    topo = admin.topology()
    assert set(topo) == {"routing_map", "groups"}
    assert topo["routing_map"]["n_groups"] == 2
    row = topo["groups"]["1"]
    assert row["leader"] == 2
    assert row["is_leader"] is False
    assert (row["term"], row["applied"], row["commit"]) == (2, 5, 6)
    assert row["members"] == {"1": "127.0.0.1:7001"}


def test_groups_admin_reshard_refused_without_coordinator():
    admin = GroupsAdmin({0: _fake_lms_node(1, True, 1, 0, 0)})

    async def run():
        with pytest.raises(ValueError):
            await admin.reshard({"course": "course0", "to_group": 1})

    asyncio.run(run())


def test_groups_admin_reshard_validates_body():
    access = _seeded_access()
    coord = ReshardCoordinator(access, course_of=access.course_of)
    admin = GroupsAdmin(
        {0: _fake_lms_node(1, True, 1, 0, 0)}, coordinator=coord
    )

    async def run():
        with pytest.raises(ValueError):
            await admin.reshard({"to_group": 1})
        with pytest.raises(ValueError):
            await admin.reshard({"course": "course0", "to_group": "1"})
        result = await admin.reshard({"course": "course0", "to_group": 1})
        assert result["step"] == "done"

    asyncio.run(run())
