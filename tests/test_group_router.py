"""Unit tests for the sharded control plane (PR 16).

Covers the routing map, per-group leader-hint lanes, per-group fault
targeting, config validation, and — the acceptance bar — the
crash-point checker: the reshard handoff is crashed after EVERY
journaled step and rolled forward by `recover()`, asserting the moved
slice lands exactly once, the map flips exactly once, and the source is
left with tombstones instead of frozen markers. The live-cluster side
(split under chaos at diurnal peak) is exercised in test_semester_sim.
"""

import asyncio
from types import SimpleNamespace
from typing import Any, Dict, List, Optional

import pytest

import grpc

from distributed_lms_raft_llm_tpu.client.client import LMSClient
from distributed_lms_raft_llm_tpu.config import GroupsConfig, SimConfig
from distributed_lms_raft_llm_tpu.lms.group_router import (
    AUTH_SALT_METADATA_KEY,
    GROUP_METADATA_KEY,
    RESHARD_JOURNAL_KEY,
    ROUTER_SIG_METADATA_KEY,
    ROUTING_MAP_KEY,
    GroupLeaderHints,
    GroupsAdmin,
    ReshardCoordinator,
    RoutedLMSServicer,
    RoutingMap,
    _InnerContext,
    sign_router_metadata,
    stable_hash,
)
from distributed_lms_raft_llm_tpu.lms.service import _forced_auth
from distributed_lms_raft_llm_tpu.lms.state import LMSState
from distributed_lms_raft_llm_tpu.utils.faults import FaultInjector


# --------------------------------------------------------------- RoutingMap


def test_routing_map_initial_assigns_courses_round_robin():
    m = RoutingMap.initial(2, ["course1", "course0", "course2"])
    # Sorted course order, then round-robin over the groups.
    assert m.courses == {"course0": 0, "course1": 1, "course2": 0}
    assert m.version == 1
    assert m.n_groups == 2


def test_routing_map_resolution_order():
    m = RoutingMap(
        version=3,
        n_groups=3,
        courses={"course0": 1},
        overrides={"special": 2},
    )
    course_of = lambda u: "course0" if u.startswith("stu") else None
    # Override beats everything.
    assert m.group_for("special", course_of) == 2
    # Course table next.
    assert m.group_for("stu7", course_of) == 1
    # Hash fallback when the course is unknown.
    assert m.group_for("nobody", course_of) == stable_hash("nobody") % 3
    # Hash fallback also without a course function at all.
    assert m.group_for("stu7") == stable_hash("stu7") % 3


def test_routing_map_ignores_out_of_range_entries():
    m = RoutingMap(n_groups=2, courses={"course0": 9}, overrides={"a": -1})
    assert m.group_for("a", lambda u: "course0") == stable_hash("a") % 2


def test_routing_map_json_round_trip_and_defaults():
    m = RoutingMap(version=5, n_groups=4, courses={"c": 3}, overrides={"u": 1})
    again = RoutingMap.from_json(m.to_json())
    assert again == m
    # Old/foreign documents with missing fields get sane defaults.
    bare = RoutingMap.from_json("{}")
    assert (bare.version, bare.n_groups, bare.courses, bare.overrides) == (
        1, 1, {}, {},
    )


def test_stable_hash_is_process_independent():
    # sha1-derived, unlike builtin hash(): pin a literal so a future
    # "optimization" to hash() fails loudly.
    assert stable_hash("alice") == int(
        __import__("hashlib").sha1(b"alice").hexdigest()[:12], 16
    )


# --------------------------------------------------- leader hints, per lane


def test_group_leader_hints_evict_is_per_lane():
    hints = GroupLeaderHints()
    hints.update(0, 1)
    hints.update(2, 3)
    hints.evict(2)
    assert hints.get(0) == 1
    assert hints.get(2) is None
    assert hints.snapshot() == {0: 1}


def test_client_hint_lanes_are_independent():
    client = LMSClient(["127.0.0.1:1", "127.0.0.1:2"])
    client._set_leader("127.0.0.1:1", group=0)
    client._set_leader("127.0.0.1:2", group=1)
    # Losing group 1's leader must not blow away group 0's hint.
    client.evict_leader_hint(group=1)
    assert client._leader_hints == {0: "127.0.0.1:1"}
    # Address-scoped evict drops every lane pointing at that address.
    client._set_leader("127.0.0.1:1", group=1)
    client.evict_leader_hint("127.0.0.1:1")
    assert client._leader_hints == {}


def test_client_leader_addr_property_is_lane_zero():
    client = LMSClient(["127.0.0.1:1"])
    client._leader_addr = "127.0.0.1:9"
    assert client._leader_hints == {0: "127.0.0.1:9"}
    assert client._leader_addr == "127.0.0.1:9"
    client._leader_addr = None
    assert client._leader_addr is None


def test_client_home_group_uses_group_of():
    client = LMSClient(["127.0.0.1:1"], group_of=lambda u: 2)
    assert client._home_group() == 0  # not logged in yet
    client._username = "alice"
    assert client._home_group() == 2


# ----------------------------------------------------- per-group fault tier


def test_fault_spec_for_walks_group_hierarchy():
    inj = FaultInjector(seed=0)
    inj.configure("raft", drop=0.1)
    inj.configure("raft:1", drop=0.2)
    inj.configure("raft:1:3", drop=0.3)
    # Most specific wins; missing levels fall back one segment at a time.
    assert inj.spec_for("raft:1:3").drop == 0.3
    assert inj.spec_for("raft:1:9").drop == 0.2
    assert inj.spec_for("raft:2:9").drop == 0.1
    assert inj.spec_for("raft:2").drop == 0.1
    inj.configure("*", drop=0.9)
    assert inj.spec_for("tutoring:5").drop == 0.9


# ------------------------------------------------------------------- config


def test_groups_config_validates():
    assert GroupsConfig().count == 1
    with pytest.raises(ValueError):
        GroupsConfig(count=0)
    with pytest.raises(ValueError):
        GroupsConfig(port_stride=0)
    with pytest.raises(ValueError):
        SimConfig(lms_groups=0)


# ------------------------------------------------- state-machine idempotence


def test_register_applier_is_idempotent():
    state = LMSState()
    args = {
        "username": "alice",
        "password_hash": "h1",
        "role": "student",
        "request_id": "r1",
    }
    state.apply("Register", args)
    # Retry with the same request id: dropped by the ledger.
    state.apply("Register", args)
    # A different rid but same username: applier keeps the first record.
    state.apply(
        "Register",
        {**args, "password_hash": "h2", "request_id": "r2"},
    )
    assert state.data["users"]["alice"]["password"] == "h1"


def test_frozen_guard_blocks_source_writes():
    state = LMSState()
    state.apply("FreezeKeys", {"users": ["alice"], "reshard_id": "rs1"})
    state.apply(
        "PostAssignment",
        {"student": "alice", "filename": "a", "filepath": "p", "text": "t"},
    )
    assert "alice" not in state.data["assignments"]
    assert state.frozen_for("alice") == "rs1"


def test_installkeys_lifts_moved_tombstones():
    """A course can reshard BACK to a group it previously left: the
    install must clear that group's 'moved' tombstones, or the router
    would reject the returning users' writes forever."""
    state = LMSState()
    state.apply("DropKeys", {"users": ["alice", "bob"], "reshard_id": "rs1"})
    assert set(state.data["moved"]) == {"alice", "bob"}
    state.apply(
        "InstallKeys",
        {
            "payload": {
                "users": ["alice"],
                "assignments": {"alice": [{"filename": "hw", "filepath": "p",
                                           "grade": None, "text": "t"}]},
            },
            "reshard_id": "rs2",
        },
    )
    assert "alice" not in state.data["moved"]
    # bob did not ride this install; his tombstone stays.
    assert "bob" in state.data["moved"]
    assert len(state.data["assignments"]["alice"]) == 1


# ----------------------------------------------------- crash-point checker


class FakeAccess:
    """GroupAccess over in-memory LMSStates: proposals apply directly,
    the meta kv is group 0's kv — exactly the meta-group layout the live
    cluster replicates, minus the Raft hop. State survives coordinator
    "crashes" the way Raft-committed state survives process crashes."""

    def __init__(self, n_groups: int, courses: List[str], users: Dict[str, str]):
        self._n = n_groups
        self._users = users  # username -> course
        self._states = {gid: LMSState() for gid in range(n_groups)}
        self._initial = RoutingMap.initial(n_groups, courses)
        self.fences: List[int] = []

    def course_of(self, username: str) -> Optional[str]:
        return self._users.get(username)

    def n_groups(self) -> int:
        return self._n

    def users(self) -> List[str]:
        return sorted(self._users)

    def state(self, gid: int) -> LMSState:
        return self._states[gid]

    def current_map(self) -> RoutingMap:
        raw = self._states[0].data["kv"].get(ROUTING_MAP_KEY)
        return RoutingMap.from_json(raw) if raw else self._initial

    async def read_fence(self, gid: int) -> None:
        self.fences.append(gid)

    async def propose(self, gid: int, op: str, args: Dict[str, Any]) -> None:
        self._states[gid].apply(op, args)

    async def meta_get(self, key: str) -> Optional[str]:
        return self._states[0].data["kv"].get(key)

    async def meta_set(self, key: str, value: str) -> None:
        self._states[0].apply("SetVal", {"key": key, "value": value})


class _Crash(Exception):
    pass


def _seeded_access() -> FakeAccess:
    """Two groups; course0 lives on group 0 with two users who have
    acked writes. The handoff under test moves course0 to group 1."""
    access = FakeAccess(
        2,
        ["course0", "course1"],
        {"alice": "course0", "bob": "course0", "carol": "course1"},
    )
    src = access.state(0)
    src.apply(
        "PostAssignment",
        {"student": "alice", "filename": "hw1", "filepath": "p1",
         "text": "t1", "request_id": "w1"},
    )
    src.apply(
        "AskQuery",
        {"username": "bob", "query": "why?", "request_id": "w2"},
    )
    src.apply(
        "PostCourseMaterial",
        {"instructor": "alice", "filename": "notes", "filepath": "p2",
         "request_id": "w3"},
    )
    return access


def _assert_handoff_consistent(access: FakeAccess) -> None:
    """The acceptance invariants, checked after recovery from ANY crash
    point: map flipped exactly once, slice present exactly once on the
    target, source left with tombstones (not frozen markers), and no
    acked write lost."""
    m = access.current_map()
    assert m.courses["course0"] == 1
    assert m.version == 2  # exactly one bump, no matter how many replays
    dst = access.state(1).data
    assert len(dst["assignments"]["alice"]) == 1
    assert dst["assignments"]["alice"][0]["filename"] == "hw1"
    assert len(dst["queries"]["bob"]) == 1
    assert [mat["filepath"] for mat in dst["course_materials"]] == ["p2"]
    # The source's idempotency ledger rode along: late client retries of
    # pre-freeze writes dedup on the target instead of applying twice.
    for rid in ("w1", "w2", "w3"):
        assert rid in dst["applied_requests"]
    src = access.state(0).data
    assert "alice" not in src["assignments"]
    assert "bob" not in src["queries"]
    assert src["course_materials"] == []
    assert not src.get("frozen")
    assert set(src["moved"]) == {"alice", "bob"}
    # carol (course1) was never part of the handoff.
    assert "carol" not in src["moved"]


def test_reshard_completes_without_crash():
    async def run():
        access = _seeded_access()
        steps: List[str] = []
        coord = ReshardCoordinator(
            access, course_of=access.course_of, on_step=steps.append
        )
        result = await coord.reshard("course0", 1)
        assert result["ok"] and result["step"] == "done"
        assert result["moved_users"] == 2
        assert result["version"] == 2
        assert steps == ["begin", "frozen", "installed", "committed", "done"]
        # The slice was read behind a fence on the source.
        assert access.fences == [0]
        _assert_handoff_consistent(access)
        # Re-running recover() afterwards is a no-op.
        again = await ReshardCoordinator(
            access, course_of=access.course_of
        ).recover()
        assert again["noop"]

    asyncio.run(run())


@pytest.mark.parametrize(
    "crash_at", ["begin", "frozen", "installed", "committed"]
)
def test_reshard_crash_point_checker(crash_at):
    """Crash the coordinator immediately after EVERY journaled step in
    turn, then roll forward with a fresh coordinator (a restarted node),
    asserting the same final invariants every time — this is the
    acceptance criterion's handoff-journal checker."""

    async def run():
        access = _seeded_access()

        def crash(step: str) -> None:
            if step == crash_at:
                raise _Crash(step)

        coord = ReshardCoordinator(
            access, course_of=access.course_of, on_step=crash
        )
        with pytest.raises(_Crash):
            await coord.reshard("course0", 1)
        # The journal names the furthest persisted step.
        raw = await access.meta_get(RESHARD_JOURNAL_KEY)
        assert raw is not None
        # A fresh coordinator (no crash hook) rolls forward to done.
        result = await ReshardCoordinator(
            access, course_of=access.course_of
        ).recover()
        assert result["ok"] and result["step"] == "done"
        _assert_handoff_consistent(access)

    asyncio.run(run())


def test_reshard_recover_replays_committed_substep():
    """The nastiest crash window: a state-machine command committed but
    the journal step after it did NOT persist. Recovery blindly
    re-proposes the command; the deterministic request_id makes the
    replay a ledger no-op instead of a double-apply."""

    async def run():
        access = _seeded_access()
        rid = "reshard-course0-0-1-v1"
        # FreezeKeys committed on the source...
        await access.propose(
            0,
            "FreezeKeys",
            {"users": ["alice", "bob"], "reshard_id": rid,
             "request_id": rid + ":freeze"},
        )
        # ...but the journal still says "begin" (crash before _journal).
        import json

        await access.meta_set(
            RESHARD_JOURNAL_KEY,
            json.dumps({
                "id": rid, "step": "begin", "course": "course0",
                "src": 0, "dst": 1, "users": ["alice", "bob"],
            }),
        )
        result = await ReshardCoordinator(
            access, course_of=access.course_of
        ).recover()
        assert result["step"] == "done"
        _assert_handoff_consistent(access)

    asyncio.run(run())


def test_reshard_noop_and_validation():
    async def run():
        access = _seeded_access()
        coord = ReshardCoordinator(access, course_of=access.course_of)
        # Already home: structured no-op, no journal written.
        result = await coord.reshard("course0", 0)
        assert result["noop"]
        assert await access.meta_get(RESHARD_JOURNAL_KEY) is None
        with pytest.raises(ValueError):
            await coord.reshard("courseX", 1)
        with pytest.raises(ValueError):
            await coord.reshard("course0", 7)
        # Nothing in flight: recover is a clean no-op too.
        assert (await coord.recover())["noop"]

    asyncio.run(run())


def test_reshard_round_trip_back_to_origin():
    """Moving a course away and then back again must leave its users
    fully writable on the original group: the return leg's InstallKeys
    lifts the 'moved' tombstones the first leg's DropKeys left behind."""

    async def run():
        access = _seeded_access()
        coord = ReshardCoordinator(access, course_of=access.course_of)
        await coord.reshard("course0", 1)
        assert set(access.state(0).data["moved"]) == {"alice", "bob"}
        result = await coord.reshard("course0", 0)
        assert result["ok"] and result["step"] == "done"
        # Home again: no tombstones on group 0, slice restored there...
        src = access.state(0).data
        assert "alice" not in src.get("moved", {})
        assert "bob" not in src.get("moved", {})
        assert len(src["assignments"]["alice"]) == 1
        assert len(src["queries"]["bob"]) == 1
        # ...and the return leg tombstoned group 1 instead.
        assert set(access.state(1).data["moved"]) == {"alice", "bob"}
        m = access.current_map()
        assert m.courses["course0"] == 0
        assert m.version == 3  # two flips

    asyncio.run(run())


def test_reshard_rolls_forward_inflight_journal_instead_of_clobbering():
    """Starting a NEW reshard while a crashed handoff is mid-flight must
    not overwrite its journal (that would orphan its FreezeKeys and
    strand the frozen users as UNAVAILABLE forever): the in-flight
    handoff is rolled forward to 'done' first, then the new one runs."""

    async def run():
        access = _seeded_access()

        def crash(step: str) -> None:
            if step == "frozen":
                raise _Crash(step)

        with pytest.raises(_Crash):
            await ReshardCoordinator(
                access, course_of=access.course_of, on_step=crash
            ).reshard("course0", 1)
        # course0's users sit frozen on group 0, journal step 'frozen'.
        assert access.state(0).frozen_for("alice")
        coord = ReshardCoordinator(access, course_of=access.course_of)
        result = await coord.reshard("course1", 0)
        assert result["ok"] and result["step"] == "done"
        # The crashed handoff completed rather than being clobbered:
        src = access.state(0).data
        assert not src.get("frozen")
        assert set(src["moved"]) >= {"alice", "bob"}
        assert len(access.state(1).data["assignments"]["alice"]) == 1
        m = access.current_map()
        assert m.courses["course0"] == 1
        # ...and the new handoff landed too, with its own version bump.
        assert m.courses["course1"] == 0
        assert m.version == 3

    asyncio.run(run())


# -------------------------------------------------------------- admin plane


def _fake_lms_node(leader_id, is_leader, term, applied, commit):
    core = SimpleNamespace(
        current_term=term, last_applied=applied, commit_index=commit
    )
    node = SimpleNamespace(leader_id=leader_id, is_leader=is_leader, core=core)
    return SimpleNamespace(
        node=node, addresses={1: "127.0.0.1:7001"},
        # The PR-18 digest-chain fields LMSNode maintains per apply.
        state_digest="00" * 8, _last_applied_index=applied,
    )


def test_groups_admin_topology_shape():
    admin = GroupsAdmin({
        0: _fake_lms_node(1, True, 3, 10, 10),
        1: _fake_lms_node(2, False, 2, 5, 6),
    })
    topo = admin.topology()
    assert set(topo) == {"routing_map", "groups"}
    assert topo["routing_map"]["n_groups"] == 2
    row = topo["groups"]["1"]
    assert row["leader"] == 2
    assert row["is_leader"] is False
    assert (row["term"], row["applied"], row["commit"]) == (2, 5, 6)
    assert row["members"] == {"1": "127.0.0.1:7001"}
    # PR 18: replica digest chain rides the per-group rows.
    assert (row["digest"], row["digest_applied"]) == ("00" * 8, 5)


def test_groups_admin_reshard_refused_without_coordinator():
    admin = GroupsAdmin({0: _fake_lms_node(1, True, 1, 0, 0)})

    async def run():
        with pytest.raises(ValueError):
            await admin.reshard({"course": "course0", "to_group": 1})

    asyncio.run(run())


def test_groups_admin_reshard_validates_body():
    access = _seeded_access()
    coord = ReshardCoordinator(access, course_of=access.course_of)
    admin = GroupsAdmin(
        {0: _fake_lms_node(1, True, 1, 0, 0)}, coordinator=coord
    )

    async def run():
        with pytest.raises(ValueError):
            await admin.reshard({"to_group": 1})
        with pytest.raises(ValueError):
            await admin.reshard({"course": "course0", "to_group": "1"})
        result = await admin.reshard({"course": "course0", "to_group": 1})
        assert result["step"] == "done"

    asyncio.run(run())


# ------------------------------------------------- router metadata trust


class _Aborted(Exception):
    pass


class _FakeContext:
    """Stands in for a grpc.aio context: carries metadata, raises on
    abort like the real thing."""

    def __init__(self, md: Optional[List] = None) -> None:
        self._md = list(md or [])
        self.aborted: Optional[tuple] = None

    def invocation_metadata(self):
        return list(self._md)

    async def abort(self, code, details=""):
        self.aborted = (code, details)
        raise _Aborted(details)


class _FakeInner:
    """Inner per-group servicer double: records (gid, rpc) dispatches
    and answers success=True unless told otherwise."""

    def __init__(self, gid: int, record: List, responses: Optional[Dict] = None):
        self._gid = gid
        self._record = record
        self._responses = responses or {}

    def __getattr__(self, name: str):
        async def handler(request, context):
            self._record.append((self._gid, name))
            return self._responses.get(name, SimpleNamespace(success=True))

        return handler


def _make_router(record: List, responses_by_gid: Optional[Dict] = None,
                 secret: str = "sekrit"):
    """Two groups, both locally led, alice's session known on group 0
    and her course (course0) homed there."""
    nodes = {
        0: SimpleNamespace(node=SimpleNamespace(is_leader=True, leader_id=1),
                           state=LMSState()),
        1: SimpleNamespace(node=SimpleNamespace(is_leader=True, leader_id=1),
                           state=LMSState()),
    }
    nodes[0].state.data["sessions"]["tok"] = "alice"
    inner = {
        gid: _FakeInner(gid, record, (responses_by_gid or {}).get(gid))
        for gid in nodes
    }
    router = RoutedLMSServicer(
        nodes, inner, {1: "127.0.0.1:1"}, 1,
        course_of=lambda u: "course0" if u == "alice" else None,
        initial_map=RoutingMap.initial(2, ["course0", "course1"]),
        router_secret=secret,
    )
    return router, nodes


def test_sign_router_metadata_is_order_independent():
    pairs = [("x-lms-group", "1"), ("x-lms-hops", "1")]
    assert sign_router_metadata("k", pairs) == sign_router_metadata(
        "k", list(reversed(pairs))
    )
    assert sign_router_metadata("k", pairs) != sign_router_metadata("k2", pairs)


def test_router_ignores_forged_group_targeting():
    """A client-sent x-lms-group with no router signature must not let
    it target writes at a non-home group (where they would be invisible
    to home-group reads and reshard slices)."""
    record: List = []
    router, _ = _make_router(record)
    ctx = _FakeContext([(GROUP_METADATA_KEY, "1")])  # forged: unsigned
    resp = asyncio.run(router.Post(SimpleNamespace(token="tok"), ctx))
    assert resp.success
    assert record == [(0, "Post")]  # routed home, not to the forged group


def test_router_honors_signed_group_targeting():
    record: List = []
    router, _ = _make_router(record)
    pairs = [(GROUP_METADATA_KEY, "1")]
    ctx = _FakeContext(
        pairs + [(ROUTER_SIG_METADATA_KEY,
                  sign_router_metadata("sekrit", pairs))]
    )
    asyncio.run(router.Post(SimpleNamespace(token="tok"), ctx))
    assert record == [(1, "Post")]
    # A signature minted under the wrong secret is a forgery again.
    record2: List = []
    router2, _ = _make_router(record2)
    ctx2 = _FakeContext(
        pairs + [(ROUTER_SIG_METADATA_KEY,
                  sign_router_metadata("wrong", pairs))]
    )
    asyncio.run(router2.Post(SimpleNamespace(token="tok"), ctx2))
    assert record2 == [(0, "Post")]


def test_forced_auth_requires_router_vouched_leg():
    """A client dialing a servicer directly cannot pin its own KDF salt
    or session token: x-lms-auth-* is only honored behind the router's
    _InnerContext mark, which also strips raw wire x-lms-* pairs."""
    raw = _FakeContext([(AUTH_SALT_METADATA_KEY, "attacker-salt")])
    assert _forced_auth(raw, AUTH_SALT_METADATA_KEY) is None
    # Wrapped with no router-vouched extra: the raw pair is stripped.
    assert _forced_auth(_InnerContext(raw), AUTH_SALT_METADATA_KEY) is None
    # Router-minted material on the leg IS honored.
    vouched = _InnerContext(raw, [(AUTH_SALT_METADATA_KEY, "router-salt")])
    assert _forced_auth(vouched, AUTH_SALT_METADATA_KEY) == "router-salt"


def test_router_treats_llm_ask_as_write_for_freeze_guards():
    """GetLLMAnswer's degraded fallback proposes an AskQuery; for a
    frozen user that proposal would be silently no-opped while the
    handler acks 'forwarded to an instructor'. The router must turn the
    mid-reshard case into an UNAVAILABLE retry instead."""
    record: List = []
    router, nodes = _make_router(record)
    nodes[0].state.apply("FreezeKeys", {"users": ["alice"],
                                        "reshard_id": "rs"})
    ctx = _FakeContext()
    with pytest.raises(_Aborted):
        asyncio.run(router.GetLLMAnswer(SimpleNamespace(token="tok"), ctx))
    assert ctx.aborted is not None
    assert ctx.aborted[0] == grpc.StatusCode.UNAVAILABLE
    assert record == []  # the handler (and its fallback) never ran


def test_auth_fanout_register_leg_failure_is_not_silent():
    """A secondary Register leg answering success=False means that
    group holds a conflicting record; acking the primary anyway would
    let credentials diverge across groups."""
    record: List = []
    router, _ = _make_router(
        record,
        responses_by_gid={1: {"Register": SimpleNamespace(success=False)}},
    )
    req = SimpleNamespace(username="alice", password="pw", role="student")
    ctx = _FakeContext()
    with pytest.raises(_Aborted):
        asyncio.run(router.Register(req, ctx))
    assert ctx.aborted is not None
    assert ctx.aborted[0] == grpc.StatusCode.UNAVAILABLE


def test_auth_fanout_logout_leg_failure_aborts_only_on_divergence():
    # success=False with the token unknown on that group: the session
    # is already absent there — the desired end state — so the op acks.
    record: List = []
    router, _ = _make_router(
        record,
        responses_by_gid={1: {"Logout": SimpleNamespace(success=False)}},
    )
    resp = asyncio.run(router.Logout(SimpleNamespace(token="tok"),
                                     _FakeContext()))
    assert resp.success
    # Same failure while the group still shows the session: diverged.
    record2: List = []
    router2, nodes2 = _make_router(
        record2,
        responses_by_gid={1: {"Logout": SimpleNamespace(success=False)}},
    )
    nodes2[1].state.data["sessions"]["tok"] = "alice"
    ctx = _FakeContext()
    with pytest.raises(_Aborted):
        asyncio.run(router2.Logout(SimpleNamespace(token="tok"), ctx))
    assert ctx.aborted is not None
    assert ctx.aborted[0] == grpc.StatusCode.UNAVAILABLE
