"""Leadership transfer (Raft thesis §3.10).

The reference can only change leaders by crashing one and waiting out a
randomized election timeout (reference: GUI_RAFT_LLM_SourceCode/
lms_server.py:1539-1547 — 10-30 s of unavailability). Here the leader
hands off deliberately: it picks the most caught-up member, refuses new
proposals while the target catches the log head, sends TimeoutNow, and
the target campaigns immediately — its vote requests carry the additive
`transfer` flag that bypasses voters' leader-lease guard, so the handoff
completes in one round trip instead of an election timeout. Planned
maintenance (drain-then-restart) becomes a sub-second blip.
"""

import asyncio

import pytest

from distributed_lms_raft_llm_tpu.raft import (
    MemNetwork,
    MemoryStorage,
    RaftConfig,
    RaftNode,
    TransferInFlight,
    encode_command,
)
from distributed_lms_raft_llm_tpu.raft.core import NotLeader, RaftCore, Role
from distributed_lms_raft_llm_tpu.raft.messages import (
    Entry,
    TimeoutNowRequest,
    VoteRequest,
)

from test_raft_cluster import FAST, build_cluster, wait_for_leader


async def wait_until(cond, timeout=5.0, what="condition"):
    loop = asyncio.get_running_loop()
    deadline = loop.time() + timeout
    while loop.time() < deadline:
        if cond():
            return
        await asyncio.sleep(0.02)
    raise AssertionError(f"timed out waiting for {what}")


# ------------------------------------------------------------ core semantics


def _leader_core(n_peers=2) -> RaftCore:
    core = RaftCore(1, list(range(1, n_peers + 2)), MemoryStorage(),
                    RaftConfig(), now=0.0, seed=7)
    core.current_term = 2
    core.role = Role.LEADER
    core.leader_id = 1
    core.next_index = {p: 1 for p in core.peer_ids}
    core.match_index = {p: 0 for p in core.peer_ids}
    # An entry of the current term, fully replicated (through storage so
    # the WAL mirror stays consistent with core.log).
    core.log.append(Entry(term=2, command="x"))
    core.storage.append_entries(1, core.log[-1:])
    for p in core.peer_ids:
        core.match_index[p] = core.last_log_index
        core.next_index[p] = core.last_log_index + 1
    core.commit_index = core.last_log_index
    core.drain_outbox()
    return core


class TestCore:
    def test_fires_timeout_now_when_target_caught_up(self):
        core = _leader_core()
        target = core.transfer_leadership(1.0, target=2)
        assert target == 2
        sent = [(p, m) for p, m in core.drain_outbox()
                if isinstance(m, TimeoutNowRequest)]
        assert sent == [(2, TimeoutNowRequest(term=2, leader_id=1))]

    def test_auto_target_is_most_caught_up(self):
        core = _leader_core()
        core.match_index[3] = core.last_log_index
        core.match_index[2] = 0  # lagging
        assert core.transfer_leadership(1.0) == 3

    def test_waits_for_lagging_target_then_fires(self):
        core = _leader_core()
        core.match_index[2] = 0  # target behind
        core.transfer_leadership(1.0, target=2)
        assert not any(isinstance(m, TimeoutNowRequest)
                       for _, m in core.drain_outbox())
        # Catch-up ack arrives -> TimeoutNow fires exactly once.
        from distributed_lms_raft_llm_tpu.raft.messages import AppendResponse

        core.on_append_response(2, AppendResponse(
            term=2, success=True, match_index=core.last_log_index), 1.1)
        fired = [m for _, m in core.drain_outbox()
                 if isinstance(m, TimeoutNowRequest)]
        assert len(fired) == 1
        core.on_append_response(2, AppendResponse(
            term=2, success=True, match_index=core.last_log_index), 1.2)
        assert not any(isinstance(m, TimeoutNowRequest)
                       for _, m in core.drain_outbox())

    def test_proposals_refused_during_transfer(self):
        core = _leader_core()
        core.transfer_leadership(1.0, target=2)
        with pytest.raises(TransferInFlight):
            core.propose("nope", 1.1)
        with pytest.raises(TransferInFlight):
            core.propose_config({1: "", 2: ""}, 1.1)

    def test_transfer_aborts_at_deadline(self):
        core = _leader_core()
        core.transfer_leadership(1.0, target=2)
        core.tick(1.0 + core.config.election_timeout_max + 0.01)
        assert core.transfer_target is None
        core.propose("resumed", 2.0)  # accepted again

    def test_transfer_requires_leadership_and_valid_target(self):
        core = _leader_core()
        with pytest.raises(ValueError):
            core.transfer_leadership(1.0, target=1)  # self
        with pytest.raises(ValueError):
            core.transfer_leadership(1.0, target=99)  # not a member
        core.role = Role.FOLLOWER
        with pytest.raises(NotLeader):
            core.transfer_leadership(1.0)

    def test_transfer_vote_bypasses_leader_lease(self):
        # A follower freshly contacted by its leader disregards normal
        # vote requests (§4.2.3) but must process a transfer election.
        core = RaftCore(2, [1, 2, 3], MemoryStorage(), RaftConfig(),
                        now=0.0, seed=8)
        core.current_term = 2
        core._leader_contact = 10.0  # just heard from leader 1
        plain = VoteRequest(term=3, candidate_id=3, last_log_index=0,
                            last_log_term=0)
        assert not core.on_vote_request(plain, 10.01).granted
        xfer = VoteRequest(term=3, candidate_id=3, last_log_index=0,
                           last_log_term=0, transfer=True)
        assert core.on_vote_request(xfer, 10.02).granted

    def test_timeout_now_starts_immediate_campaign(self):
        core = RaftCore(2, [1, 2, 3], MemoryStorage(), RaftConfig(),
                        now=0.0, seed=9)
        core.current_term = 2
        core._leader_contact = 10.0
        core.on_timeout_now(TimeoutNowRequest(term=2, leader_id=1), 10.01)
        assert core.role is Role.CANDIDATE
        votes = [m for _, m in core.drain_outbox()
                 if isinstance(m, VoteRequest)]
        assert votes and all(v.transfer and v.term == 3 for v in votes)

    def test_second_transfer_refused_while_in_flight(self):
        core = _leader_core()
        core.transfer_leadership(1.0, target=2)
        with pytest.raises(TransferInFlight):
            core.transfer_leadership(1.1, target=3)

    def test_equal_term_heartbeat_does_not_cancel_campaign(self):
        # The abdicating leader's in-flight appends arrive at the target's
        # still-equal term mid-campaign; they must not demote it.
        from distributed_lms_raft_llm_tpu.raft.messages import (
            AppendRequest,
            VoteResponse,
        )

        core = RaftCore(2, [1, 2, 3], MemoryStorage(), RaftConfig(),
                        now=0.0, seed=11)
        core.current_term = 2
        core.on_timeout_now(TimeoutNowRequest(term=2, leader_id=1), 10.0)
        assert core.role is Role.CANDIDATE
        hb = AppendRequest(term=2, leader_id=1, prev_log_index=0,
                           prev_log_term=0, entries=(), leader_commit=0)
        resp = core.on_append_request(hb, 10.01)
        assert not resp.success
        assert core.role is Role.CANDIDATE  # campaign survives
        core.drain_outbox()
        core.on_vote_response(3, VoteResponse(term=3, granted=True), 10.02)
        assert core.role is Role.LEADER

    def test_equal_term_append_from_other_leader_demotes_campaign(self):
        # Mid-campaign, an equal-term append from a leader OTHER than the
        # abdicating one means that term is already won elsewhere: step
        # down and accept immediately instead of stalling convergence by
        # up to an election timeout (ADVICE round 5).
        from distributed_lms_raft_llm_tpu.raft.messages import AppendRequest

        core = RaftCore(2, [1, 2, 3], MemoryStorage(), RaftConfig(),
                        now=0.0, seed=12)
        core.current_term = 2
        core.on_timeout_now(TimeoutNowRequest(term=2, leader_id=1), 10.0)
        assert core.role is Role.CANDIDATE
        hb = AppendRequest(term=2, leader_id=3, prev_log_index=0,
                           prev_log_term=0, entries=(), leader_commit=0)
        resp = core.on_append_request(hb, 10.01)
        assert resp.success
        assert core.role is Role.FOLLOWER
        assert core.leader_id == 3

    def test_leader_goes_quiet_to_target_after_timeout_now(self):
        core = _leader_core()
        core.transfer_leadership(1.0, target=2)
        core.drain_outbox()
        core.tick(1.0 + core.config.heartbeat_interval + 0.001)
        dests = {p for p, _ in core.drain_outbox()}
        assert 2 not in dests and 3 in dests

    def test_stale_timeout_now_ignored(self):
        core = RaftCore(2, [1, 2, 3], MemoryStorage(), RaftConfig(),
                        now=0.0, seed=10)
        core.current_term = 5
        core.on_timeout_now(TimeoutNowRequest(term=2, leader_id=1), 1.0)
        assert core.role is Role.FOLLOWER


# --------------------------------------------------------- cluster behavior


def test_mem_cluster_graceful_handoff():
    """Full handoff on a 3-node cluster: sub-election-timeout, no lost
    committed writes, old leader steps down, new leader serves."""

    async def run():
        net = MemNetwork()
        applied = {}
        nodes, _ = build_cluster(net, 3, applied=applied)
        for n in nodes.values():
            await n.start()
        leader = await wait_for_leader(nodes)
        for k in range(5):
            await leader.propose(encode_command("set", {"k": str(k)}))

        loop = asyncio.get_running_loop()
        t0 = loop.time()
        target = await leader.transfer_leadership()
        took = loop.time() - t0
        assert not leader.is_leader
        await wait_until(lambda: nodes[target].is_leader, what="target leads")
        # Well under the minimum election timeout: the whole point.
        assert took < FAST.election_timeout_min, took

        # The new leader serves writes; nothing committed was lost.
        await nodes[target].propose(encode_command("set", {"k": "after"}))
        await wait_until(
            lambda: all(
                any('"after"' in cmd for _, cmd in applied.get(i, []))
                for i in nodes
            ),
            what="post-transfer write applied everywhere",
        )
        seen = [cmd for _, cmd in applied[target]]
        assert len([c for c in seen if '"k"' in c]) == 6

        for n in nodes.values():
            await n.stop()

    asyncio.run(run())


def test_mem_cluster_transfer_to_explicit_lagging_target():
    """A lagging explicit target is streamed up to date first, then takes
    over — the §3.10 prior-catch-up step."""

    async def run():
        net = MemNetwork()
        nodes, _ = build_cluster(net, 3, applied={})
        for n in nodes.values():
            await n.start()
        leader = await wait_for_leader(nodes)
        others = [i for i in nodes if i != leader.node_id]
        lag = others[0]
        # Cut the target off, commit writes through the remaining quorum.
        net.drop_pairs = {(leader.node_id, lag), (lag, leader.node_id)}
        for k in range(4):
            await leader.propose(encode_command("set", {"k": str(k)}))
        assert nodes[lag].core.last_log_index < leader.core.last_log_index
        net.heal()

        target = await leader.transfer_leadership(lag)
        assert target == lag
        await wait_until(lambda: nodes[lag].is_leader, what="laggard leads")
        # Leader completeness: it caught up before campaigning.
        assert nodes[lag].core.last_log_index >= 5

        for n in nodes.values():
            await n.stop()

    asyncio.run(run())


def test_grpc_cluster_graceful_handoff(tmp_path):
    """The whole path over real gRPC: TimeoutNow RPC, transfer-flagged
    RequestVote, step-down, new leader serving SetVal."""
    import grpc as grpc_mod

    from distributed_lms_raft_llm_tpu.proto import lms_pb2, rpc
    from distributed_lms_raft_llm_tpu.raft.grpc_transport import (
        GrpcTransport,
        RaftServicer,
    )
    from distributed_lms_raft_llm_tpu.raft.storage import FileStorage

    async def run():
        ids = [1, 2, 3]
        servers, nodes, addresses = {}, {}, {}
        for i in ids:
            servers[i] = grpc_mod.aio.server()
            port = servers[i].add_insecure_port("127.0.0.1:0")
            addresses[i] = f"127.0.0.1:{port}"
        for i in ids:
            storage = FileStorage(str(tmp_path / f"wal{i}.jsonl"),
                                  fsync=False)
            node = RaftNode(i, ids, storage, GrpcTransport(addresses),
                            config=FAST, tick_interval=0.01, seed=i)
            rpc.add_RaftServiceServicer_to_server(
                RaftServicer(node, addresses), servers[i]
            )
            nodes[i] = node
            await servers[i].start()
            await node.start()
        try:
            leader = await wait_for_leader(nodes)
            target = await leader.transfer_leadership()
            assert not leader.is_leader
            await wait_until(lambda: nodes[target].is_leader,
                             what="target leads over gRPC")
            async with grpc_mod.aio.insecure_channel(
                addresses[target]
            ) as ch:
                stub = rpc.RaftServiceStub(ch)
                setr = await stub.SetVal(
                    lms_pb2.SetValRequest(key="k", value="v"), timeout=10
                )
                assert setr.verdict
        finally:
            for n in nodes.values():
                await n.stop()
            for s in servers.values():
                await s.stop(None)

    asyncio.run(run())


def test_mem_cluster_transfer_aborts_when_target_down():
    async def run():
        net = MemNetwork()
        nodes, _ = build_cluster(net, 3, applied={})
        for n in nodes.values():
            await n.start()
        leader = await wait_for_leader(nodes)
        others = [i for i in nodes if i != leader.node_id]
        dead = others[0]
        await nodes[dead].stop()
        with pytest.raises(TimeoutError):
            await leader.transfer_leadership(dead, timeout=2.0)
        # Aborted: still (or again) able to serve.
        await wait_until(lambda: leader.core.transfer_target is None,
                         what="transfer aborted")
        await leader.propose(encode_command("set", {"k": "alive"}))

        for n in nodes.values():
            if n is not nodes[dead]:
                await n.stop()

    asyncio.run(run())
