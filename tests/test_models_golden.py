"""Golden-output tests: JAX models vs HF transformers (torch CPU) logits.

Strategy (SURVEY.md §4d): instantiate tiny *random-init* HF models, convert
their state_dicts through `models.convert`, and demand near-exact agreement.
This checks every weight mapping and every architectural detail (pre/post-LN,
gelu variant, fused QKV ordering, tied unembedding) without network access.

HF comparisons run both sides in float64 (`jax.enable_x64`):
in float32 the two frameworks differ by ~1e-3 purely from matmul
accumulation order (oneDNN), which would mask real architecture bugs behind
a loose tolerance. Internal consistency tests (KV cache vs full forward)
stay in float32, where identical op graphs agree tightly.
"""

import dataclasses

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from distributed_lms_raft_llm_tpu.models import bert, convert, gpt2

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

try:
    enable_x64 = jax.enable_x64  # jax >= 0.5
except AttributeError:  # pragma: no cover - version-dependent
    from jax.experimental import enable_x64


@pytest.fixture(scope="module")
def hf_gpt2():
    cfg = transformers.GPT2Config(
        vocab_size=128,
        n_positions=64,
        n_embd=32,
        n_layer=2,
        n_head=4,
    )
    torch.manual_seed(0)
    model = transformers.GPT2LMHeadModel(cfg)
    model.eval()
    return cfg, model


@pytest.fixture(scope="module")
def hf_bert():
    cfg = transformers.BertConfig(
        vocab_size=128,
        max_position_embeddings=64,
        hidden_size=32,
        num_hidden_layers=2,
        num_attention_heads=4,
        intermediate_size=128,
    )
    torch.manual_seed(1)
    model = transformers.BertModel(cfg)
    model.eval()
    return cfg, model


def test_gpt2_logits_match_hf(hf_gpt2):
    hf_cfg, hf_model = hf_gpt2
    hf_model = hf_model.double()
    with enable_x64(True):
        cfg = dataclasses.replace(
            convert.gpt2_config_from_hf(hf_cfg.to_dict()),
            dtype=jnp.float64,
            param_dtype=jnp.float64,
        )
        params = convert.gpt2_params_from_hf(hf_model.state_dict(), cfg)

        rng = np.random.default_rng(0)
        ids = rng.integers(0, cfg.vocab_size, size=(2, 17))
        with torch.no_grad():
            ref = hf_model(torch.tensor(ids)).logits.numpy()
        ours, cache = gpt2.forward(params, cfg, jnp.asarray(ids))
        assert cache is None
        np.testing.assert_allclose(np.asarray(ours, np.float64), ref, atol=1e-6)


def test_gpt2_kv_cache_decode_matches_full_forward(hf_gpt2):
    """Prefill+decode through the cache must equal the uncached forward.

    Runs in float64 where the agreement is exact (~1e-8); in float32 the two
    graph shapes differ by accumulation order alone (~1e-3 worst case).
    """
    hf_cfg, hf_model = hf_gpt2
    with enable_x64(True):
        cfg = dataclasses.replace(
            convert.gpt2_config_from_hf(hf_cfg.to_dict()),
            dtype=jnp.float64,
            param_dtype=jnp.float64,
        )
        params = convert.gpt2_params_from_hf(hf_model.state_dict(), cfg)

        rng = np.random.default_rng(1)
        ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(2, 12)))

        full_logits, _ = gpt2.forward(params, cfg, ids)

        cache = gpt2.init_cache(cfg, batch=2, max_len=32, dtype=jnp.float64)
        prefill_logits, cache = gpt2.forward(params, cfg, ids[:, :7], cache=cache)
        np.testing.assert_allclose(
            np.asarray(prefill_logits), np.asarray(full_logits[:, :7]), atol=1e-6
        )
        # Decode the rest one token at a time.
        for t in range(7, 12):
            step_logits, cache = gpt2.forward(params, cfg, ids[:, t : t + 1], cache=cache)
            np.testing.assert_allclose(
                np.asarray(step_logits[:, 0]), np.asarray(full_logits[:, t]), atol=1e-6
            )
        assert int(cache.length) == 12


def test_gpt2_left_padded_prefill(hf_gpt2):
    """Left-padded rows with explicit positions/kv_mask match unpadded rows."""
    hf_cfg, hf_model = hf_gpt2
    cfg = convert.gpt2_config_from_hf(hf_cfg.to_dict())

    rng = np.random.default_rng(2)
    with enable_x64(True):
        cfg = dataclasses.replace(cfg, dtype=jnp.float64, param_dtype=jnp.float64)
        params = convert.gpt2_params_from_hf(hf_model.state_dict(), cfg)
        ids = jnp.asarray(rng.integers(1, cfg.vocab_size, size=(1, 6)))
        clean_logits, _ = gpt2.forward(params, cfg, ids)

        pad = 3
        padded = jnp.concatenate([jnp.zeros((1, pad), ids.dtype), ids], axis=1)
        positions = jnp.concatenate(
            [jnp.zeros((1, pad), jnp.int32), jnp.arange(6, dtype=jnp.int32)[None]],
            axis=1,
        )
        cache = gpt2.init_cache(cfg, batch=1, max_len=16, dtype=jnp.float64)
        kv_mask = (jnp.arange(16) >= pad)[None, :]
        logits, cache = gpt2.forward(
            params, cfg, padded, cache=cache, positions=positions, kv_mask=kv_mask
        )
        np.testing.assert_allclose(
            np.asarray(logits[:, pad:]), np.asarray(clean_logits), atol=1e-6
        )


def test_bert_hidden_states_match_hf(hf_bert):
    hf_cfg, hf_model = hf_bert
    hf_model = hf_model.double()
    with enable_x64(True):
        cfg = dataclasses.replace(
            convert.bert_config_from_hf(hf_cfg.to_dict()),
            dtype=jnp.float64,
            param_dtype=jnp.float64,
        )
        params = convert.bert_params_from_hf(hf_model.state_dict(), cfg)

        rng = np.random.default_rng(3)
        ids = rng.integers(0, cfg.vocab_size, size=(2, 20))
        attn = np.ones((2, 20), np.int64)
        attn[1, 13:] = 0  # second row padded
        with torch.no_grad():
            ref = hf_model(
                torch.tensor(ids), attention_mask=torch.tensor(attn)
            ).last_hidden_state.numpy()
        ours = bert.forward(
            params, cfg, jnp.asarray(ids), attention_mask=jnp.asarray(attn)
        )
        ours = np.asarray(ours, np.float64)
        # Padded positions are undefined; compare valid region only.
        np.testing.assert_allclose(ours[0], ref[0], atol=1e-5)
        np.testing.assert_allclose(ours[1, :13], ref[1, :13], atol=1e-5)


def test_bert_embed_and_cosine_gate(hf_bert):
    hf_cfg, hf_model = hf_bert
    cfg = convert.bert_config_from_hf(hf_cfg.to_dict())
    params = convert.bert_params_from_hf(hf_model.state_dict(), cfg)

    rng = np.random.default_rng(4)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, size=(2, 10)))
    e = bert.embed(params, cfg, ids)
    assert e.shape == (2, cfg.hidden_size)
    sim_self = bert.cosine_similarity(e[0], e[0])
    sim_cross = bert.cosine_similarity(e[0], e[1])
    assert float(sim_self) == pytest.approx(1.0, abs=1e-5)
    assert -1.0 <= float(sim_cross) <= 1.0
