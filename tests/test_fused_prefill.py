"""Stall-free admission: chunked prefill fused into the megastep scan.

The fusion changes WHERE prefill compute runs (inside the decode scan,
one bounded chunk per iteration) and WHEN a slot joins the train (at a
scan-iteration flip instead of a dispatch-boundary install) — never WHAT
the device computes. Greedy outputs through fused staged admission must
be bit-identical to the sequential prefill-then-decode engine at every
ladder rung and any chunk budget, across plain/spec/kv-quant/
prefix-cache-hit/slot-churn configs. On top of exactness: warmup covers
the fused program domain with exact inventory equality (a live session
walking admissions mid-megastep adds zero programs), the decode train
records ZERO stalled tokens under fused admission while the sequential
path records them (the PR's before/after number), and the K controller
holds K >= 2 under a non-empty pending queue. The per-slot n-gram-table
drafter (`draft_source = "ngram"`) rides along: acceptance pinned above
prompt-lookup's on a temperature-0.8 workload.
"""

import asyncio

import jax.numpy as jnp
import pytest

from distributed_lms_raft_llm_tpu.engine import (
    EngineConfig,
    PagedEngine,
    PagedQueue,
    SamplingParams,
    TutoringEngine,
)
from distributed_lms_raft_llm_tpu.engine.prefix_cache import plan_staged
from distributed_lms_raft_llm_tpu.utils.guards import (
    compile_count_guard,
    expected_from_inventory,
)
from distributed_lms_raft_llm_tpu.utils.metrics import Metrics

MAX_NEW = 8

PROMPTS = ["what is raft?", "hello world", "explain paging", "k"]

SHARED = "the raft consensus algorithm elects a leader and replicates a log"


def make_config(**kw):
    kw.setdefault("sampling", SamplingParams.greedy(max_new_tokens=MAX_NEW))
    kw.setdefault("length_buckets", (16,))
    return EngineConfig(
        model="tiny",
        batch_buckets=(1, 2, 4),
        dtype=jnp.float32,
        **kw,
    )


_EXPECTED_CACHE = {}


def expected_answers(cfg, prompts):
    """Bucketed-engine reference stream, memoized per (config, prompts):
    several tests pin against the same reference, and a TutoringEngine
    build is the expensive part of each."""
    key = (repr(cfg), tuple(prompts))
    if key not in _EXPECTED_CACHE:
        _EXPECTED_CACHE[key] = TutoringEngine(cfg).answer_batch(
            list(prompts)
        )
    return _EXPECTED_CACHE[key]


# ------------------------------------------------------- greedy bit-equality


class TestGreedyBitEquality:
    @pytest.mark.parametrize("megastep", [1, 4])
    def test_matches_sequential_at_every_rung(self, megastep):
        """Acceptance pin: fused admission at the ladder floor AND a
        wide rung — rung 1 included, where the fused engine still
        dispatches through the megastep program — emits exactly what the
        sequential prefill-then-decode paged engine and the bucketed
        engine emit (rung 2 rides in the churn/prefix tests below)."""
        cfg = make_config()
        expected = expected_answers(cfg, PROMPTS)
        # (sequential-paged == bucketed at these rungs is test_megastep's
        # pin; here the fused engine closes the triangle.)
        fused = PagedEngine(cfg, slots=4, chunk=2, megastep=megastep,
                            megastep_max=megastep, prefill_chunk_tokens=4)
        fr = [fused.submit(p) for p in PROMPTS]
        out_fused = fused.drain()
        assert [out_fused[r] for r in fr] == expected

    @pytest.mark.parametrize("prefill_chunk", [1, 3])
    def test_any_chunk_budget(self, prefill_chunk):
        """The chunk budget moves how many scan iterations a prompt's
        prefill spans (one position at a time at 1; multi-chunk with a
        final-chunk pad overshoot at 3) — never the emitted stream.
        (The whole-prompt-in-one-chunk shape is the rung tests' budget
        of 4 over shorter prompts.)"""
        cfg = make_config()
        expected = expected_answers(cfg, PROMPTS)
        eng = PagedEngine(cfg, slots=4, chunk=2, megastep=2,
                          megastep_max=4,
                          prefill_chunk_tokens=prefill_chunk)
        rs = [eng.submit(p) for p in PROMPTS]
        out = eng.drain()
        assert [out[r] for r in rs] == expected

    @pytest.mark.parametrize("spec_tokens", [1, 3])
    def test_spec_mode(self, spec_tokens):
        """Fused admission x speculation: staged slots flip into verify
        windows (drafts from the transcript the stage seeded) and still
        match the non-spec engines bit for bit."""
        expected = expected_answers(make_config(), PROMPTS)
        eng = PagedEngine(
            make_config(spec_tokens=spec_tokens), slots=4, chunk=2,
            megastep=4, megastep_max=4, prefill_chunk_tokens=3,
        )
        rs = [eng.submit(p) for p in PROMPTS]
        out = eng.drain()
        assert [out[r] for r in rs] == expected
        windows, emitted = eng.pop_spec_stats()
        assert windows > 0
        assert windows <= emitted <= windows * (spec_tokens + 1)

    def test_kv_quant(self):
        cfg = make_config(kv_quant=True)
        expected = TutoringEngine(cfg).answer_batch(list(PROMPTS[:2]))
        eng = PagedEngine(cfg, slots=2, chunk=2, megastep=4,
                          megastep_max=4, prefill_chunk_tokens=4)
        rs = [eng.submit(p) for p in PROMPTS[:2]]
        out = eng.drain()
        assert [out[r] for r in rs] == expected

    def test_slot_churn_and_prompt_buckets(self):
        """5 requests over 2 slots with mixed prompt buckets: stagings
        land as slots free, prefills of different lengths interleave
        with live decode inside the same megasteps, and every stream
        still matches the bucketed engine."""
        cfg = make_config(length_buckets=(4, 8, 16))
        prompts = list(PROMPTS) + ["k v"]
        expected = TutoringEngine(cfg).answer_batch(prompts)
        eng = PagedEngine(cfg, slots=2, chunk=2, megastep=2,
                          megastep_max=4, prefill_chunk_tokens=3)
        rs = [eng.submit(p) for p in prompts]
        out = eng.drain()
        assert [out[r] for r in rs] == expected

    def test_prefix_cache_hit(self):
        """Fused staged admission composes with the radix cache: a hit
        splices blocks straight into the slot's pages (`_stage_block`)
        and only the uncached suffix is chunked — warm output
        bit-identical to cold, both bit-identical to the bucketed
        engine."""
        cfg = make_config(length_buckets=(8, 16, 32))
        q1, q2 = SHARED + " why?", SHARED + " how?"
        expected = TutoringEngine(cfg).answer_batch([q1, q2])
        eng = PagedEngine(cfg, slots=2, chunk=2, megastep=2,
                          megastep_max=4, prefill_chunk_tokens=4,
                          prefix_cache=True, prefix_cache_blocks=64,
                          prefix_block_tokens=4)
        r1 = eng.submit(q1)
        o1 = eng.drain()
        r2 = eng.submit(q2)
        o2 = eng.drain()
        assert [o1[r1], o2[r2]] == expected
        hit, _total, _ev, _blocks = eng.pop_prefix_stats()
        assert hit > 0, "the second request must splice cached blocks"
        # The staged planner keeps hits block-aligned (no suffix-bucket
        # fitting to give blocks back).
        assert hit % 4 == 0

    def test_pipelined_matches_serialized(self):
        """inflight=2 with staged admission: flips are learned one reap
        late, snapshots carry staged requests across dispatches, and the
        answers stay byte-identical to the serialized engine."""
        cfg = make_config()
        ser = PagedEngine(cfg, slots=2, chunk=2, inflight=1, megastep=4,
                          megastep_max=4, prefill_chunk_tokens=4)
        rs = [ser.submit(p) for p in PROMPTS]
        out_ser = ser.drain()
        pipe = PagedEngine(cfg, slots=2, chunk=2, inflight=2, megastep=4,
                           megastep_max=4, prefill_chunk_tokens=4)
        rp = [pipe.submit(p) for p in PROMPTS]
        out_pipe = pipe.drain()
        assert [out_pipe[r] for r in rp] == [out_ser[r] for r in rs]


# ------------------------------------------------- stall-free acceptance


def _churn(engine):
    """A mid-decode arrival: A is admitted and decoding when B and C
    arrive, so their admissions happen under a LIVE train — the exact
    scenario sequential admission pays a full prefill stall for and
    staged admission absorbs into the scan."""
    engine.submit("a long question about distributed consensus and logs")
    for _ in range(2):
        engine.step()  # A live, mid-decode
    engine.submit("b second question")
    engine.submit("c third question")
    engine.drain()
    return engine.pop_dispatch_stats()


def test_sequential_admission_stalls_fused_does_not():
    """THE before/after number: a request arriving mid-decode pauses the
    sequential engine's live decode train for its prefill (stalled
    tokens + stall wall accrue); the fused engine records ZERO decode
    stall for the identical workload, and its K controller never drops
    to the chunk loop while requests wait."""
    cfg = make_config()
    _, _, _, stall_ms, stalled = _churn(
        PagedEngine(cfg, slots=2, chunk=2, megastep=2, megastep_max=2)
    )
    assert stalled > 0, "sequential admission under churn must stall decode"
    assert stall_ms > 0

    _, _, _, stall_ms, stalled = _churn(
        PagedEngine(cfg, slots=2, chunk=2, megastep=2, megastep_max=2,
                    prefill_chunk_tokens=4)
    )
    assert stalled == 0, "fused staged admission must never pause decode"
    assert stall_ms == 0

    # Saturation: K stays wide (>= 2) the whole time a backlog waits.
    fused = PagedEngine(cfg, slots=2, chunk=2, megastep=4,
                        megastep_max=4, prefill_chunk_tokens=4)
    ks = []
    for i in range(8):
        fused.submit(f"question number {i}")
    while fused.has_work:
        fused.step()
        if fused._pending:
            ks.append(fused.megastep_k)
    _, _, _, stall_ms, stalled = fused.pop_dispatch_stats()
    assert stalled == 0 and stall_ms == 0
    assert ks and min(ks) >= 2, "K must stay wide while admissions drain"


# --------------------------------------------- warmup / inventory coverage


def test_warmed_fused_session_passes_inventory_guard():
    """compile_count_guard(expected_from_inventory(...)): warmup compiles
    the fused domain — stage pairs, megasteps at EVERY rung including 1,
    zero sequential admission programs — and a live session walking
    admissions mid-megastep, churning slots, and growing the cache adds
    ZERO programs."""
    eng = PagedEngine(
        make_config(length_buckets=(4, 16)), slots=2, chunk=2,
        megastep=2, megastep_max=4, prefill_chunk_tokens=3,
    )
    eng.warmup()
    expectation = expected_from_inventory(eng)
    dom_widths = len(eng.widths)
    assert expectation.expected["_megastep"] == dom_widths * 3  # rungs 1,2,4
    assert expectation.expected["_step"] == 0
    assert expectation.expected["_prefill"] == 0
    assert expectation.expected["_install"] == 0
    assert expectation.expected["_stage"] > 0
    assert expectation.mismatches() == {}
    with compile_count_guard(expectation) as guard:
        eng.submit("k v")
        eng.step()
        eng.submit("a longer question about raft elections and logs")
        eng.drain()
        for prompt in ("k v", "a longer question about raft", "k v"):
            eng.submit(prompt)
        eng.drain()
    assert guard.new_compiles() == 0


def test_warmed_fused_prefix_session_passes_inventory_guard():
    """Fused + shared-prefix: block export moves to the live cache and
    `_stage_block` splices per width; hits, misses, publishes, and
    evictions mid-session add zero programs."""
    eng = PagedEngine(
        make_config(length_buckets=(8, 16, 32)), slots=2, chunk=2,
        megastep=2, megastep_max=4, prefill_chunk_tokens=4,
        prefix_cache=True, prefix_cache_blocks=64, prefix_block_tokens=4,
    )
    eng.warmup()
    expectation = expected_from_inventory(eng)
    assert expectation.expected["_stage_block"] == len(eng.widths)
    assert expectation.expected["_export_block"] == len(eng.widths)
    assert expectation.expected["_load_block"] == 0
    assert expectation.expected["_partial_prefill"] == 0
    assert expectation.mismatches() == {}
    with compile_count_guard(expectation) as guard:
        eng.submit(SHARED + " why?")
        eng.drain()
        for q in (SHARED + " how?", "short q", SHARED + " when?"):
            eng.submit(q)
        eng.drain()
    assert guard.new_compiles() == 0
    hit, total, _ev, _blocks = eng.pop_prefix_stats()
    assert hit > 0


def test_unwarmed_fused_engine_fails_inventory_guard():
    from distributed_lms_raft_llm_tpu.utils.guards import RecompileError

    eng = PagedEngine(make_config(), slots=2, chunk=2,
                      prefill_chunk_tokens=4)
    with pytest.raises(RecompileError):
        with compile_count_guard(expected_from_inventory(eng)):
            eng.submit("hello")
            eng.drain()


# ------------------------------------------------------- serving queue


class _StallingStubEngine:
    """Paged-protocol stub whose dispatch stats report a known admission
    stall: pins the PagedQueue emission path deterministically (driving
    a real engine into a mid-decode arrival from the queue is a timing
    race on CPU)."""

    def __init__(self):
        self._work = []
        self._rid = 0

    def submit(self, prompt):
        self._rid += 1
        self._work.append((self._rid, prompt))
        return self._rid

    @property
    def has_work(self):
        return bool(self._work)

    backlog = 0

    def step(self):
        done, self._work = self._work[:1], self._work[1:]
        return [(rid, f"answer to {p}") for rid, p in done]

    def pop_ttfts(self):
        return {}

    def pop_dispatch_stats(self):
        return (3, 10, 0, 12.5, 4)


def test_paged_queue_reports_stall_metrics():
    """The serving path surfaces the admission-stall series from
    `pop_dispatch_stats()`: prefill_stall_ms and decode_stalled_tokens
    counters when the engine reports a blocking admission, and neither
    (zero) from a fused engine's real run."""

    async def run(q, n):
        await q.start()
        answers = await asyncio.gather(
            *[q.submit(f"query number {i}") for i in range(n)]
        )
        await q.close()
        return answers

    metrics = Metrics()
    answers = asyncio.run(run(PagedQueue(_StallingStubEngine(),
                                         metrics=metrics), 2))
    assert len(answers) == 2
    snap = metrics.snapshot()
    assert snap["counters"].get("decode_stalled_tokens", 0) > 0
    assert snap["counters"].get("prefill_stall_ms", 0) > 0

    fused_metrics = Metrics()
    fused = PagedEngine(make_config(), slots=2, chunk=2,
                        prefill_chunk_tokens=4)
    answers = asyncio.run(run(PagedQueue(fused, metrics=fused_metrics), 6))
    assert len(answers) == 6
    snap = fused_metrics.snapshot()
    assert snap["counters"].get("decode_stalled_tokens", 0) == 0
    assert snap["counters"].get("prefill_stall_ms", 0) == 0
    assert fused_metrics.hist("ttft").snapshot()["count"] == 6


# ------------------------------------------------- staged planning + knobs


def test_plan_staged_block_alignment():
    assert plan_staged(16, 20, 4) == 16
    assert plan_staged(16, 16, 4) == 12   # >= 1 recomputed token
    assert plan_staged(15, 20, 4) == 12   # block-aligned down
    assert plan_staged(3, 20, 4) == 0     # under one block: cold
    assert plan_staged(0, 20, 4) == 0


def test_draft_source_validation():
    with pytest.raises(ValueError, match="draft_source"):
        PagedEngine(make_config(draft_source="nope"), slots=2)
    with pytest.raises(ValueError, match="paged-engine"):
        TutoringEngine(make_config(spec_tokens=2, draft_source="ngram"))


def test_fused_spec_requires_decode_headroom():
    with pytest.raises(ValueError, match="max_new_tokens >= 2"):
        PagedEngine(
            make_config(
                spec_tokens=2,
                sampling=SamplingParams.greedy(max_new_tokens=1),
            ),
            slots=2, prefill_chunk_tokens=4,
        )


# ------------------------------------------------- n-gram table drafter


def test_ngram_drafter_beats_prompt_lookup_at_temperature():
    """Satellite pin: at temperature 0.8, the per-slot n-gram TABLE
    drafter (modal continuation of the current context) accepts more
    tokens per verify window than prompt-lookup (most recent
    continuation) on a repetitive tutoring-style workload — the regime
    prompt-lookup was built for greedy streams and loses at temp>0."""
    # Workload shape matters: the separation lives in MODEL-SAMPLED
    # history (where the most recent continuation is a random draw but
    # the modal one tracks the distribution), so short prompts + long
    # generations; top_k=2 keeps the random-weight tiny model's
    # processed support peaked enough that drafts CAN be accepted (the
    # full 50k-vocab distribution of an untrained model is near-uniform
    # — acceptance ~0 for every drafter, no signal). Everything is
    # seeded: same submission order, same rng split sequence per
    # drafter, deterministic on CPU.
    sampling = SamplingParams(temperature=0.8, top_k=2, top_p=1.0,
                              repetition_penalty=1.0, max_new_tokens=56)
    base = dict(
        sampling=sampling, length_buckets=(64,), spec_tokens=3,
        batch_buckets=(1, 2, 4, 8), model="tiny", dtype=jnp.float32,
    )
    prompts = [f"q{i} the cat" for i in range(8)]

    def acceptance(source):
        eng = PagedEngine(
            EngineConfig(draft_source=source, **base),
            slots=4, chunk=2, prefill_chunk_tokens=8,
        )
        for p in prompts:
            eng.submit(p)
        eng.drain()
        windows, emitted = eng.pop_spec_stats()
        assert windows > 100, "need a real window population"
        return emitted / windows

    lookup = acceptance("prompt_lookup")
    ngram = acceptance("ngram")
    assert ngram > lookup, (
        f"ngram acceptance {ngram:.3f} must beat prompt_lookup "
        f"{lookup:.3f} at temperature 0.8"
    )
