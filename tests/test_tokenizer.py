"""Tokenizer parity vs the HF `tokenizers` library (offline, real algorithms).

The image has no pretrained vocab files and no network, so parity is proven
the strong way: train a REAL byte-level BPE (and build a real WordPiece
vocab) with HuggingFace `tokenizers`, then assert our pure-Python
implementations produce identical ids/round-trips on adversarial strings.
This is the same algorithm pair the reference relies on through
`GPT2Tokenizer` / `BertTokenizer` (reference: GUI_RAFT_LLM_SourceCode/
tutoring_server.py:10, lms_server.py:11).
"""

import json

import pytest

from distributed_lms_raft_llm_tpu.utils.tokenizer import (
    BPETokenizer,
    ByteTokenizer,
    WordPieceTokenizer,
)

TRICKY = [
    "Hello, world!",
    "The instructor's reply: don't  panic — it's FINE.",
    "  leading and trailing   whitespace  ",
    "newlines\nand\ttabs\r\nmixed",
    "numbers 123 456.789 and mixed a1b2c3",
    "unicode: café naïve résumé Ångström",
    "emoji 🙂 and CJK 你好世界 mixed in",
    "contractions: I'll you're we've they'd it's can't",
    "symbols @#$%^&*() [brackets] {braces} <angles>",
    "",
    "a",
    "don't",
    "ALLCAPS and CamelCase and snake_case",
    "price: $19.99, 50% off!!",
    "quoted \"strings\" and 'single' ones",
]

CORPUS = [
    "The quick brown fox jumps over the lazy dog. " * 3,
    "Students ask questions about assignments and instructors grade them.",
    "Distributed systems replicate state machines for fault tolerance.",
    "don't can't won't it's we're they've I'll you'd",
    "café naïve résumé — unicode text with punctuation!",
    "Numbers: 0 1 2 3 42 123 456 789 1000 19.99 50%",
    "def tokenize(text): return [t for t in pattern.findall(text)]",
    "grading rubric: correctness 50%, style 25%, tests 25%",
] * 50


@pytest.fixture(scope="module")
def trained_bpe(tmp_path_factory):
    """Train a real byte-level BPE with HF `tokenizers`, dump vocab files."""
    tokenizers = pytest.importorskip("tokenizers")
    d = tmp_path_factory.mktemp("bpe")
    corpus = d / "corpus.txt"
    corpus.write_text("\n".join(CORPUS), encoding="utf-8")
    hf = tokenizers.ByteLevelBPETokenizer()
    hf.train(
        [str(corpus)], vocab_size=800, min_frequency=1,
        special_tokens=["<|endoftext|>"],
    )
    hf.save_model(str(d))
    return hf, str(d / "vocab.json"), str(d / "merges.txt")


def test_bpe_matches_hf_on_tricky_strings(trained_bpe):
    hf, vocab_path, merges_path = trained_bpe
    ours = BPETokenizer.from_files(vocab_path, merges_path)
    assert ours.vocab_size == hf.get_vocab_size()
    for text in TRICKY:
        expected = hf.encode(text).ids
        got = ours.encode(text)
        assert got == expected, f"BPE mismatch on {text!r}: {got} != {expected}"


def test_bpe_roundtrip(trained_bpe):
    _, vocab_path, merges_path = trained_bpe
    ours = BPETokenizer.from_files(vocab_path, merges_path)
    for text in TRICKY:
        assert ours.decode(ours.encode(text)) == text


def test_bpe_eos_id_from_vocab(trained_bpe):
    _, vocab_path, merges_path = trained_bpe
    ours = BPETokenizer.from_files(vocab_path, merges_path)
    with open(vocab_path, encoding="utf-8") as f:
        vocab = json.load(f)
    assert ours.eos_id == vocab["<|endoftext|>"]


@pytest.fixture(scope="module")
def wordpiece_vocab(tmp_path_factory):
    """A realistic WordPiece vocab: specials, whole words, ## continuations."""
    words = [
        "[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]",
        "the", "quick", "brown", "fox", "jump", "##s", "##ed", "##ing",
        "over", "lazy", "dog", "student", "##,", "instructor", "grade",
        "assign", "##ment", "question", "answer", "don", "'", "t", "can",
        "won", "it", "s", "ll", "re", "ve", "d", "m", "cafe", "naive",
        "resume", "a", "b", "c", "1", "2", "3", "##1", "##2", "##3",
        ",", ".", "!", "?", "$", "%", "(", ")", '"', "-", "angstrom",
        "all", "##cap", "##case", "camel", "snake", "_", "price", "19",
        "##9", "99", "50", "off", "hello", "world", "你", "好",
    ]
    d = tmp_path_factory.mktemp("wp")
    vocab_file = d / "vocab.txt"
    vocab_file.write_text("\n".join(words), encoding="utf-8")
    return str(vocab_file)


def test_wordpiece_matches_hf(wordpiece_vocab):
    tokenizers = pytest.importorskip("tokenizers")
    hf = tokenizers.BertWordPieceTokenizer(wordpiece_vocab, lowercase=True)
    ours = WordPieceTokenizer.from_file(wordpiece_vocab)
    for text in TRICKY:
        expected = hf.encode(text).ids
        got = ours.encode(text)
        assert got == expected, (
            f"WordPiece mismatch on {text!r}: {got} != {expected}"
        )


def test_wordpiece_accent_stripping(wordpiece_vocab):
    ours = WordPieceTokenizer.from_file(wordpiece_vocab)
    # lowercase mode strips accents: café -> cafe, Ångström -> angstrom
    cafe = ours.encode("café", add_special_tokens=False)
    assert cafe == [ours.vocab["cafe"]]
    ang = ours.encode("Ångström", add_special_tokens=False)
    assert ang == [ours.vocab["angstrom"]]


def test_wordpiece_unk_and_subwords(wordpiece_vocab):
    ours = WordPieceTokenizer.from_file(wordpiece_vocab)
    ids = ours.encode("jumps", add_special_tokens=False)
    assert ids == [ours.vocab["jump"], ours.vocab["##s"]]
    assert ours.encode("zzzzqqq", add_special_tokens=False) == [ours.unk_id]


def test_byte_tokenizer_roundtrip():
    t = ByteTokenizer()
    for text in TRICKY:
        assert t.decode(t.encode(text)) == text
