"""End-to-end tutoring server test: tiny engine behind real gRPC."""

import asyncio
import threading

import grpc
import pytest

import jax

from distributed_lms_raft_llm_tpu.engine import (
    EngineConfig,
    SamplingParams,
    TutoringEngine,
)
from distributed_lms_raft_llm_tpu.proto import lms_pb2, rpc
from distributed_lms_raft_llm_tpu.serving import tutoring_server


@pytest.fixture(scope="module")
def server_addr():
    """Run the aio server on a private event loop thread."""
    engine = TutoringEngine(
        EngineConfig(
            model="tiny",
            sampling=SamplingParams(max_new_tokens=6),
            length_buckets=(32,),
            batch_buckets=(1, 2, 4),
            dtype=jax.numpy.float32,
        )
    )
    loop = asyncio.new_event_loop()
    started = threading.Event()
    state = {}

    def run():
        asyncio.set_event_loop(loop)

        async def boot():
            server = grpc.aio.server()
            from distributed_lms_raft_llm_tpu.engine import BatchingQueue
            from distributed_lms_raft_llm_tpu.utils.metrics import Metrics

            metrics = Metrics()
            queue = BatchingQueue(engine, max_batch=4, max_wait_ms=20,
                                  metrics=metrics)
            await queue.start()
            rpc.add_TutoringServicer_to_server(
                tutoring_server.TutoringService(queue, metrics), server
            )
            port = server.add_insecure_port("127.0.0.1:0")
            await server.start()
            state["port"] = port
            state["server"] = server
            state["metrics"] = metrics
            state["queue"] = queue
            started.set()

        loop.run_until_complete(boot())
        loop.run_forever()

    t = threading.Thread(target=run, daemon=True)
    t.start()
    assert started.wait(timeout=60)
    yield f"127.0.0.1:{state['port']}", state

    async def teardown():
        await state["server"].stop(None)
        await state["queue"].close()

    asyncio.run_coroutine_threadsafe(teardown(), loop).result(30)
    loop.call_soon_threadsafe(loop.stop)
    t.join(timeout=10)


def test_get_llm_answer_over_wire(server_addr):
    addr, state = server_addr
    with grpc.insecure_channel(addr) as channel:
        stub = rpc.TutoringStub(channel)
        resp = stub.GetLLMAnswer(
            lms_pb2.QueryRequest(token="t", query="What is a mutex?"), timeout=120
        )
    assert resp.success
    assert isinstance(resp.response, str)
    snap = state["metrics"].snapshot()
    assert snap["counters"]["llm_requests"] == 1
    assert snap["latency"]["ttft"]["count"] == 1


def test_concurrent_queries_batched(server_addr):
    addr, state = server_addr
    with grpc.insecure_channel(addr) as channel:
        stub = rpc.TutoringStub(channel)
        futures = [
            stub.GetLLMAnswer.future(
                lms_pb2.QueryRequest(token="t", query=f"question {i}"), timeout=120
            )
            for i in range(4)
        ]
        responses = [f.result() for f in futures]
    assert all(r.success for r in responses)


def test_empty_query_rejected(server_addr):
    addr, _ = server_addr
    with grpc.insecure_channel(addr) as channel:
        stub = rpc.TutoringStub(channel)
        resp = stub.GetLLMAnswer(
            lms_pb2.QueryRequest(token="t", query="   "), timeout=30
        )
    assert not resp.success
