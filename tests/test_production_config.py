"""The shipped deployment is real end-to-end: configs/cluster.toml points
at checked-in artifacts and every model boots from them — ZERO random-init
warnings.

The reference always serves pretrained weights (reference:
GUI_RAFT_LLM_SourceCode/tutoring_server.py:10-12 `from_pretrained("gpt2")`,
lms_server.py:1258-1260 `bert-base-uncased`); a default config that boots
random-init would pass or reject gate queries arbitrarily and answer
babble. These tests pin the round-4 verdict's Missing #1/#2: the TOML the
README quick start uses must load `data/gpt2-local` and `data/bert-local`
through the identical HF-layout paths hub-downloaded weights use.

`data/` is deliberately untracked (a ~1 GB of seeded-deterministic
artifacts); on a fresh clone the fixture below builds them once via
`scripts/make_local_checkpoint.py` — the same step the README quick start
runs — so the suite is self-contained.
"""

import logging
import os
import sys

import pytest

from distributed_lms_raft_llm_tpu import config as config_lib

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CLUSTER_TOML = os.path.join(REPO, "configs", "cluster.toml")


@pytest.fixture(scope="module")
def cfg():
    cfg = config_lib.load_config(CLUSTER_TOML)
    t, g = cfg.tutoring, cfg.gate
    for path in (t.checkpoint, t.vocab, t.merges, g.checkpoint, g.vocab):
        assert path, "production config must name every artifact"
    if not all(
        os.path.exists(os.path.join(REPO, p))
        for p in (t.checkpoint, t.vocab, t.merges, g.checkpoint, g.vocab)
    ):
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        from make_local_checkpoint import build_bert_local, build_gpt2_local

        build_bert_local(os.path.join(REPO, "data", "bert-local"))
        build_gpt2_local(os.path.join(REPO, "data", "gpt2-local"))
    return cfg


def test_production_config_artifacts_exist(cfg):
    t, g = cfg.tutoring, cfg.gate
    for path in (t.checkpoint, t.vocab, t.merges, g.checkpoint, g.vocab):
        assert os.path.exists(os.path.join(REPO, path)), path


def test_tutoring_engine_boots_from_shipped_checkpoint(cfg, caplog):
    from distributed_lms_raft_llm_tpu.engine import TutoringEngine

    econf = config_lib.engine_config(cfg)
    # Resolve relative to the repo root the TOML ships with.
    econf.checkpoint = os.path.join(REPO, econf.checkpoint)
    econf.vocab_path = os.path.join(REPO, econf.vocab_path)
    econf.merges_path = os.path.join(REPO, econf.merges_path)
    with caplog.at_level(logging.WARNING):
        eng = TutoringEngine(econf)
    assert not [r for r in caplog.records if "random" in r.message.lower()], (
        "production config must not boot random-init weights"
    )
    # The trained BPE vocab really drives tokenization (not the byte
    # fallback): a common word round-trips through merges.
    toks = eng.tokenizer.encode("what is the raft consensus algorithm?")
    assert 0 < len(toks) < 15
    # Production quant config survived the TOML round trip.
    assert econf.quant == "int8" and econf.kv_quant


def test_gate_boots_from_shipped_checkpoint(cfg, caplog):
    from distributed_lms_raft_llm_tpu.engine import GateConfig, RelevanceGate

    g = cfg.gate
    with caplog.at_level(logging.WARNING):
        gate = RelevanceGate(
            GateConfig(
                model=g.model,
                checkpoint=os.path.join(REPO, g.checkpoint),
                vocab_path=os.path.join(REPO, g.vocab),
                threshold=g.threshold,
                quant=g.quant,
            )
        )
    assert not [r for r in caplog.records if "random" in r.message.lower()], (
        "production gate must not boot random-init BERT"
    )
    # Real WordPiece vocab loaded (not the byte fallback).
    assert gate.tokenizer.vocab_size > 5000
    ok, sim = gate.check("what is raft?", "distributed consensus homework")
    assert -1.0 <= sim <= 1.0
