"""Raft log compaction + snapshot install (Raft §7).

The WAL prefix below the LMS state snapshot's applied_index is truncated,
and a follower whose next entry precedes the compaction point receives the
state snapshot over the wire (`RaftService.InstallSnapshot`, additive RPC)
and converges from snapshot + suffix. The reference persisted no Raft state
at all (reference: GUI_RAFT_LLM_SourceCode/lms_server.py keeps log/term in
memory), so its analogue grew without bound and a wiped node could never
catch up correctly.
"""

import asyncio
import json
import os

import grpc

from distributed_lms_raft_llm_tpu.lms.node import LMSNode
from distributed_lms_raft_llm_tpu.proto import rpc
from distributed_lms_raft_llm_tpu.raft import Entry, FileStorage, RaftConfig
from distributed_lms_raft_llm_tpu.raft.grpc_transport import RaftServicer
from distributed_lms_raft_llm_tpu.raft.messages import encode_command
from distributed_lms_raft_llm_tpu.raft.storage import _parse_line

FAST = RaftConfig(
    election_timeout_min=0.11, election_timeout_max=0.22,
    heartbeat_interval=0.05,
)


def test_file_storage_compact_to_drops_prefix(tmp_path):
    path = str(tmp_path / "wal.jsonl")
    s = FileStorage(path, fsync=False)
    for i in range(1, 11):
        s.append_entries(i, [Entry(1, f"cmd-{i}")])
    s.compact_to(6, 1)
    # Suffix keeps absolute indexing.
    s.append_entries(11, [Entry(2, "cmd-11")])
    s.close()

    s2 = FileStorage(path, fsync=False)
    term, voted, entries, snap_idx, snap_term = s2.load()
    assert (snap_idx, snap_term) == (6, 1)
    assert [e.command for e in entries] == ["cmd-7", "cmd-8", "cmd-9",
                                           "cmd-10", "cmd-11"]
    # The dropped prefix is physically gone from the file.
    with open(path) as fh:
        content = fh.read()
    assert "cmd-3" not in content
    s2.close()


def test_wiped_follower_converges_via_install_snapshot(tmp_path):
    """Done-criterion: commit past the snapshot cadence so the leader
    compacts, wipe a follower, and watch it converge from the leader's
    snapshot + log suffix over real gRPC — with the WAL bounded."""

    async def run():
        ids = [1, 2, 3]
        servers, addresses, ports = {}, {}, {}
        for i in ids:
            servers[i] = grpc.aio.server()
            ports[i] = servers[i].add_insecure_port("127.0.0.1:0")
            addresses[i] = f"127.0.0.1:{ports[i]}"

        nodes = {}

        async def boot(i, dirname):
            node = LMSNode(i, addresses, str(tmp_path / dirname),
                           raft_config=FAST, snapshot_every=5)
            rpc.add_RaftServiceServicer_to_server(
                RaftServicer(node.node, addresses), servers[i]
            )
            await servers[i].start()
            await node.start()
            nodes[i] = node

        for i in ids:
            await boot(i, f"node{i}")

        try:
            leader = None
            for _ in range(300):
                leaders = [n for n in nodes.values() if n.node.is_leader]
                if leaders:
                    leader = leaders[0]
                    break
                await asyncio.sleep(0.02)
            assert leader is not None

            async def register(k):
                await leader.node.propose(encode_command(
                    "Register",
                    {"username": f"user{k}", "password_hash": "h",
                     "salt": "", "role": "student"},
                ))

            # Enough commits to trigger snapshot+compaction (cadence 5).
            for k in range(12):
                await register(k)
            await asyncio.sleep(0.3)
            assert leader.node.core.snapshot_index >= 5  # WAL compacted
            assert len(leader.node.core.log) < 12        # ...and bounded

            # Wipe a follower: kill its server, restart with an EMPTY dir.
            victim = next(i for i in ids if not nodes[i].node.is_leader)
            await nodes[victim].stop()
            await servers[victim].stop(None)
            del nodes[victim]

            # More commits while the victim is down.
            for k in range(12, 15):
                await register(k)

            servers[victim] = grpc.aio.server()
            bound = servers[victim].add_insecure_port(
                f"127.0.0.1:{ports[victim]}"
            )
            assert bound == ports[victim], "could not rebind follower port"
            await boot(victim, f"node{victim}-wiped")

            # The wiped follower converges: all 15 users present.
            fresh = nodes[victim]
            for _ in range(400):
                if len(fresh.state.data["users"]) == 15:
                    break
                await asyncio.sleep(0.02)
            assert len(fresh.state.data["users"]) == 15
            # It got there via snapshot install (full replay is impossible:
            # the leader compacted the prefix away), plus the live suffix.
            assert fresh.node.core.snapshot_index >= 5
            assert fresh.state.data["users"]["user0"]["role"] == "student"

            # And its own WAL was persisted in compacted form: restartable.
            wal = str(tmp_path / f"node{victim}-wiped" / "raft_wal.jsonl")
            assert os.path.getsize(wal) > 0
            # Post-assertion WAL inspection in a test whose loop has nothing
            # else to run.  # lint: disable-next=no-blocking-in-async
            with open(wal, "rb") as fh:
                kinds = [
                    _parse_line(line.strip())[0]["t"]
                    for line in fh if line.strip()
                ]
            assert "snap" in kinds
        finally:
            for n in nodes.values():
                await n.stop()
            for s in servers.values():
                await s.stop(None)

    asyncio.run(run())


def test_install_callback_failure_rejects_and_retry_converges():
    """If the app cannot persist an installed snapshot, raft state must not
    advance past it (ADVICE r3 #2): the response is success=False (so the
    leader re-sends instead of streaming entries past a hole), last_applied
    and the WAL base stay put, and a later retry — app recovered — installs
    cleanly. The earlier fail-fast-by-raising design didn't actually stop
    anything: neither transport turns the exception into a crash, and the
    leader's retry was absorbed by the last_applied early-return."""
    from distributed_lms_raft_llm_tpu.raft.messages import (
        InstallSnapshotRequest,
    )
    from distributed_lms_raft_llm_tpu.raft.node import RaftNode, Transport
    from distributed_lms_raft_llm_tpu.raft.storage import MemoryStorage

    installed = []

    def flaky_install(index, data):
        if not installed:
            installed.append("failed")
            raise IOError("disk full")
        installed.append((index, data))

    storage = MemoryStorage()
    node = RaftNode(2, [1, 2, 3], storage, Transport(),
                    config=FAST, install_cb=flaky_install)
    req = InstallSnapshotRequest(
        term=1, leader_id=1, last_included_index=5, last_included_term=1,
        data=b"{}",
    )
    resp = node.handle_install_snapshot(req)
    assert resp.success is False
    # Nothing moved: raft state pre-install, WAL base untouched.
    assert node.core.last_applied == 0
    assert node.core.snapshot_index == 0
    _, _, _, snap_idx, _ = storage.load()
    assert snap_idx == 0

    # Leader retries (same request); the app has recovered.
    resp2 = node.handle_install_snapshot(req)
    assert resp2.success is True
    assert installed[-1] == (5, b"{}")
    assert node.core.last_applied == 5
    assert node.core.snapshot_index == 5
    _, _, _, snap_idx2, snap_term2 = storage.load()
    assert (snap_idx2, snap_term2) == (5, 1)
