"""Flight-recorder tracer (utils/tracing.py): span trees, the bounded
ring with anomaly/slowest pinning, cross-process propagation, and the
overhead budget the PR's acceptance criteria put on it.

These are the unit tests; tests/test_trace_e2e.py drives the same tracer
through the real 3-node sim cluster.
"""

import asyncio
import threading
import time

import pytest

from distributed_lms_raft_llm_tpu.utils.tracing import (
    FLAG_DEADLINE,
    FLAG_DEGRADED,
    NULL_SPAN,
    TRACE_METADATA_KEY,
    Tracer,
    assemble_forest,
    get_tracer,
    parse_trace_context,
    set_tracer,
    trace_admin_get,
    trace_metadata,
)


@pytest.fixture()
def tracer():
    """A private tracer installed as the process global (so the module
    adapters — trace_metadata, trace_admin_get — see it), restored after
    the test."""
    prev = get_tracer()
    t = set_tracer(Tracer(ring_size=8, exemplars_per_route=2,
                          flagged_max=4))
    yield t
    set_tracer(prev)


class FakeContext:
    """gRPC server context stand-in: just invocation_metadata()."""

    def __init__(self, md):
        self._md = md

    def invocation_metadata(self):
        return self._md


# ------------------------------------------------------------- span trees


def test_span_tree_nesting_and_durations(tracer):
    with tracer.trace("client.op", trace_id="rid-1") as root:
        with tracer.span("stage.a") as a:
            time.sleep(0.01)
            with tracer.span("stage.a.inner"):
                pass
        with tracer.span("stage.b", key="v"):
            pass
    tree = tracer.tree("rid-1")
    assert tree is not None and tree["route"] == "client.op"
    (r,) = tree["spans"]
    assert r["name"] == "client.op"
    assert [c["name"] for c in r["children"]] == ["stage.a", "stage.b"]
    assert r["children"][0]["children"][0]["name"] == "stage.a.inner"
    assert r["children"][1]["attrs"] == {"key": "v"}
    # Durations nest: every child fits inside its parent.
    assert r["duration_s"] >= r["children"][0]["duration_s"] >= 0.01
    assert r["children"][0]["duration_s"] >= (
        r["children"][0]["children"][0]["duration_s"]
    )


def test_span_outside_trace_is_noop(tracer):
    with tracer.span("orphan") as sp:
        assert sp is NULL_SPAN
    assert tracer.records() == []


def test_disabled_tracer_records_nothing():
    prev = get_tracer()
    t = set_tracer(Tracer(enabled=False))
    try:
        with t.trace("client.op", trace_id="x") as sp:
            assert sp is NULL_SPAN
            assert trace_metadata() is None
        assert t.tree("x") is None
    finally:
        set_tracer(prev)


def test_exception_flags_and_errors_span(tracer):
    with pytest.raises(ValueError):
        with tracer.trace("client.op", trace_id="boom"):
            with tracer.span("stage"):
                raise ValueError("x")
    tree = tracer.tree("boom")
    assert "error" in tree["flags"]
    assert tree["spans"][0]["children"][0]["status"] == "error"
    # Anomalous -> pinned past eviction.
    for i in range(64):
        with tracer.trace("client.op", trace_id=f"filler-{i}"):
            pass
    assert tracer.tree("boom") is not None


def test_manual_child_and_timed_child(tracer):
    with tracer.trace("route", trace_id="t") as root:
        q = root.child("queue.wait")
        q.end(duration_s=1.25)
        q.end(duration_s=99.0)  # idempotent: first measurement wins
        root.child_timed("engine.prefill", start_unix=123.0,
                         duration_s=0.5, shared=True)
    (r,) = tracer.tree("t")["spans"]
    by_name = {c["name"]: c for c in r["children"]}
    assert by_name["queue.wait"]["duration_s"] == 1.25
    assert by_name["engine.prefill"]["start_s"] == 123.0
    assert by_name["engine.prefill"]["attrs"]["shared"] is True


def test_contextvar_isolation_across_tasks(tracer):
    """Two concurrent asyncio tasks each see their own current span."""

    async def one(i):
        with tracer.trace(f"route", trace_id=f"task-{i}"):
            with tracer.span(f"inner-{i}"):
                await asyncio.sleep(0.01)

    async def main():
        await asyncio.gather(one(0), one(1))

    asyncio.run(main())
    for i in range(2):
        (r,) = tracer.tree(f"task-{i}")["spans"]
        assert [c["name"] for c in r["children"]] == [f"inner-{i}"]


# ------------------------------------------------------- flight recorder


def test_ring_evicts_oldest_unpinned(tracer):
    for i in range(20):
        with tracer.trace("bulk", trace_id=f"r-{i}"):
            pass
    # ring_size=8 plus at most 2 slowest-per-route exemplar pins: the
    # oldest unpinned traces are gone, the newest survive.
    pinned = {s["trace_id"] for s in tracer.summaries()["exemplars"]}
    assert tracer.tree("r-19") is not None
    retained = {f"r-{i}" for i in range(20)
                if tracer.tree(f"r-{i}") is not None}
    assert len(retained) <= 8 + 2
    assert all(tid in pinned for tid in retained - {
        f"r-{i}" for i in range(20 - 8, 20)
    }), "anything retained beyond the newest ring entries must be pinned"


def test_slowest_per_route_pinned_past_eviction(tracer):
    clock = [0.0]
    t = Tracer(ring_size=4, exemplars_per_route=1, flagged_max=4,
               clock=lambda: clock[0], wall=time.time)
    with t.trace("ask", trace_id="slowpoke"):
        clock[0] += 10.0
    for i in range(50):
        with t.trace("ask", trace_id=f"fast-{i}"):
            clock[0] += 0.001
    tree = t.tree("slowpoke")
    assert tree is not None, "slowest exemplar must never be evicted"
    summary = t.summaries()
    assert any(s["trace_id"] == "slowpoke" and "slowest" in s["pinned"]
               for s in summary["exemplars"])


def test_flagged_pins_bounded_fifo(tracer):
    for i in range(10):
        with tracer.trace("ask", trace_id=f"bad-{i}") as sp:
            sp.flag(FLAG_DEGRADED)
    pinned = [s["trace_id"] for s in tracer.summaries()["exemplars"]
              if "flagged" in s["pinned"]]
    # flagged_max=4: only the newest 4 stay pinned.
    assert len(pinned) == 4
    assert set(pinned) == {f"bad-{i}" for i in range(6, 10)}


def test_span_cap_truncates_not_grows(tracer):
    t = Tracer(ring_size=4, max_spans_per_trace=10)
    with t.trace("big", trace_id="big"):
        pass
    for _ in range(30):
        with t.continue_trace("frag", "big", None):
            pass
    tree = t.tree("big")
    assert "truncated" in tree["flags"]
    total = len(tree["spans"])
    assert total <= 10


def test_span_cap_keeps_first_n_of_oversized_fragment():
    """A single fragment larger than the whole budget is trimmed
    (keep-first-N), not dropped: the runaway request is exactly the trace
    the flight recorder exists to keep."""
    t = Tracer(ring_size=4, max_spans_per_trace=5)
    with t.trace("big", trace_id="big"):
        for _ in range(20):
            with t.span("child"):
                pass
    tree = t.tree("big")
    assert "truncated" in tree["flags"]

    def count(spans):
        return sum(1 + count(s.get("children", [])) for s in spans)

    n = count(tree["spans"])
    assert 1 <= n <= 5, f"expected a trimmed non-empty tree, got {n} spans"


def test_route_rename_leaves_one_exemplar_heap():
    """When the outermost client fragment lands after a handler fragment
    and renames the record's route, the old route's exemplar heap must
    drop its entry: a stale entry would block that route's future
    exemplars forever and let displacement there strip the pin the new
    route still relies on."""
    clock, wall = [0.0], [100.0]
    t = Tracer(ring_size=4, exemplars_per_route=1, flagged_max=4,
               clock=lambda: clock[0], wall=lambda: wall[0])
    # Handler fragment records first (route lms.GetLLMAnswer, 10 s) ...
    with t.continue_trace("lms.GetLLMAnswer", "t1", None):
        clock[0] += 10.0
    # ... then the outer client fragment (earlier wall start) renames it.
    wall[0] = 90.0
    with t.trace("client.ask_llm", trace_id="t1"):
        clock[0] += 0.1
    # A fresh, much faster handler-routed trace must still become the
    # lms.GetLLMAnswer exemplar (a stale 10 s heap entry would block it).
    wall[0] = 200.0
    with t.continue_trace("lms.GetLLMAnswer", "t2", None):
        clock[0] += 1.0
    pins = {s["trace_id"]: s["pinned"]
            for s in t.summaries()["exemplars"]}
    assert "slowest" in pins.get("t2", []), (
        "stale heap entry for the renamed trace blocked the new exemplar"
    )
    assert "slowest" in pins.get("t1", []), (
        "renamed trace must stay pinned under its new route"
    )


def test_pins_do_not_starve_the_ring():
    """`ring_size` bounds the unpinned ring only: a burst of flagged
    anomalies must not evict every subsequent normal trace."""
    t = Tracer(ring_size=2, exemplars_per_route=0, flagged_max=8)
    for i in range(8):
        with t.trace("ask", trace_id=f"bad-{i}") as sp:
            sp.flag(FLAG_DEGRADED)
    for i in range(2):
        with t.trace("quiet-route", trace_id=f"ok-{i}"):
            pass
    for i in range(2):
        assert t.tree(f"ok-{i}") is not None, (
            "normal traces evicted by pinned anomalies"
        )


# ----------------------------------------------------------- propagation


def test_parse_trace_context_malformed():
    assert parse_trace_context(None) is None
    assert parse_trace_context("") is None
    assert parse_trace_context("no-slash") is None
    assert parse_trace_context("/x") is None
    assert parse_trace_context("x/") is None
    assert parse_trace_context("tid/sid") == ("tid", "sid")


def test_trace_metadata_appends_header(tracer):
    assert trace_metadata() is None
    assert trace_metadata([("x-base", "1")]) == [("x-base", "1")]
    with tracer.trace("op", trace_id="tid-1") as sp:
        md = trace_metadata([("x-base", "1")])
        assert md[0] == ("x-base", "1")
        key, value = md[1]
        assert key == TRACE_METADATA_KEY
        assert value == f"tid-1/{sp.span_id}"


def test_continue_from_grpc_context_variants(tracer):
    # 1. Full trace context: remote-parented fragment of the same trace.
    with tracer.continue_from_grpc_context(
        FakeContext([(TRACE_METADATA_KEY, "tid-x/span-y")]), "server.h"
    ):
        pass
    (frag,) = tracer.tree("tid-x")["spans"]
    assert frag["parent_id"] == "span-y"
    # 2. Request id only: fresh trace under the client's logged id.
    with tracer.continue_from_grpc_context(
        FakeContext([("x-request-id", "rid-z")]), "server.h"
    ):
        pass
    assert tracer.tree("rid-z") is not None
    # 3. Nothing: fresh random trace, never an error.
    with tracer.continue_from_grpc_context(FakeContext([]), "server.h"):
        pass
    # 4. A context whose metadata call explodes degrades the same way.
    class Broken:
        def invocation_metadata(self):
            raise RuntimeError("no metadata")
    with tracer.continue_from_grpc_context(Broken(), "server.h"):
        pass


def test_assemble_forest_grafts_remote_fragments():
    client = {"name": "client.ask", "span_id": "c1", "start_s": 1.0,
              "duration_s": 2.0,
              "children": [{"name": "attempt", "span_id": "c2",
                            "start_s": 1.1, "duration_s": 1.8}]}
    server = {"name": "lms.handler", "span_id": "s1", "parent_id": "c2",
              "start_s": 1.2, "duration_s": 1.5}
    orphan = {"name": "other.handler", "span_id": "o1",
              "parent_id": "nowhere", "start_s": 0.5, "duration_s": 0.1}
    forest = assemble_forest([server, client, orphan])
    assert [f["name"] for f in forest] == ["other.handler", "client.ask"]
    grafted = forest[1]["children"][0]["children"]
    assert grafted[0]["name"] == "lms.handler"


# ---------------------------------------------------------- admin plane


def test_trace_admin_get_endpoints(tracer):
    with tracer.trace("op", trace_id="seen") as sp:
        sp.flag(FLAG_DEADLINE)
    listing = trace_admin_get("/admin/trace")
    assert listing["ok"] and any(
        s["trace_id"] == "seen" for s in listing["exemplars"]
    )
    tree = trace_admin_get("/admin/trace/seen")
    assert tree["trace"]["spans"][0]["name"] == "op"
    with pytest.raises(KeyError):
        trace_admin_get("/admin/trace/never-seen")
    with pytest.raises(KeyError):
        trace_admin_get("/admin/nope")


def test_thread_safety_under_concurrent_recording(tracer):
    """Fragments recorded from many threads into one trace id must not
    corrupt the store (the sim's client threads + server loop do this)."""
    errs = []

    def worker(i):
        try:
            for j in range(50):
                with tracer.continue_trace("frag", f"shared-{j % 4}",
                                           None):
                    pass
        except Exception as e:  # pragma: no cover - the assertion
            errs.append(e)

    threads = [threading.Thread(target=worker, args=(i,))
               for i in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert not errs
    assert tracer.summaries() is not None


# -------------------------------------------------------------- overhead


def test_tracing_overhead_budget():
    """Acceptance bound: tracing must stay within 5% of the seeded sim's
    ask p95 at the default ring size. A traced ask creates ~15 spans and
    the sim's p95 bound is seconds-scale, so the budget per span is
    generous (5% of even a 100 ms ask across 15 spans is >300 us each);
    this pins the per-span cost two orders of magnitude under that, on
    the DEFAULT ring configuration, including ring-eviction churn."""
    t = Tracer()  # default knobs — the configuration the bound is about
    n = 2000
    t0 = time.perf_counter()
    for i in range(n):
        with t.trace("bench.route", trace_id=f"b-{i}"):
            with t.span("stage.a"):
                pass
            with t.span("stage.b"):
                pass
    per_span_s = (time.perf_counter() - t0) / (n * 3)
    assert per_span_s < 200e-6, (
        f"span overhead {per_span_s * 1e6:.1f} us; at ~15 spans per ask "
        "this would threaten the 5% ask-p95 budget"
    )
