"""Per-op device-time breakdown of one int8 decode call, fusion-correlated.

Round-4's profile (profiles/decode_int8_r4.json) named the costly fusions
but not what is INSIDE them, so the ~3x headroom between batch-32 effective
parameter streaming (~92 GB/s) and the measured ~275 GB/s ceiling stayed
unexplained. This script closes that gap:

1. runs one `generate_ids` (prefill + 128-step while_loop decode) under
   `jax.profiler.trace` and aggregates the device lane per op;
2. lowers/compiles the same decode program and extracts each hot fusion's
   fused-computation body from the optimized HLO, so every `fusion.N` line
   in the output carries the opcodes (and the largest tensor shapes) it
   executes;
3. writes profiles/decode_int8_r5_batch<B>.json.

Usage: python scripts/profile_decode.py [--batch 8] [--bf16]
           [--greedy] [--spec-tokens 8] [--out ...]
(--spec-tokens profiles the speculative verify-window loop of
engine/spec.py instead of the plain 128-step while_loop decode.)

Dispatch-gap mode: `--megastep K` profiles the PAGED engine's host loop
instead of the device ops — it runs the same workload through the chunk
loop (K=1) and through K-chunk megasteps, and reports host round trips
per emitted token plus per-program dispatch wall times before/after, so
the dispatch-gap share of decode latency is visible without a device
trace. (Chunk-loop dispatch gaps are what megasteps exist to remove.)

(Methodology per BENCH_NOTES.md: `block_until_ready` does not sync on the
axon backend — every timed region ends in a host readback.)
"""

from __future__ import annotations

import argparse
import collections
import glob
import gzip
import json
import os
import re
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def build_engine(batch: int, quant: bool, spec_tokens: int = 0,
                 greedy: bool = False, tp: int = 1, ep: int = 1):
    from distributed_lms_raft_llm_tpu.engine import (
        EngineConfig, SamplingParams, TutoringEngine,
    )

    ckpt_dir = os.path.join(REPO, "data", "gpt2-local")
    sampling = (SamplingParams.greedy(max_new_tokens=128) if greedy
                else SamplingParams.reference_defaults(max_new_tokens=128))
    cfg = EngineConfig(
        model="gpt2",
        checkpoint=os.path.join(ckpt_dir, "model.safetensors"),
        vocab_path=os.path.join(ckpt_dir, "vocab.json"),
        merges_path=os.path.join(ckpt_dir, "merges.txt"),
        sampling=sampling,
        quant="int8" if quant else None,
        kv_quant=quant,
        spec_tokens=spec_tokens,
        batch_buckets=(batch,),
        length_buckets=(64,),
        tp=tp,
        ep=ep,
    )
    return TutoringEngine(cfg)


def trace_events(trace_dir: str):
    """Load every *.trace.json.gz under trace_dir; yield complete events."""
    for path in glob.glob(
        os.path.join(trace_dir, "**", "*.trace.json.gz"), recursive=True
    ):
        with gzip.open(path, "rt") as fh:
            data = json.load(fh)
        names = {}  # (pid, tid) -> lane name from metadata events
        pids = {}
        for ev in data.get("traceEvents", []):
            if ev.get("ph") == "M" and ev.get("name") == "thread_name":
                names[(ev.get("pid"), ev.get("tid"))] = ev["args"]["name"]
            elif ev.get("ph") == "M" and ev.get("name") == "process_name":
                pids[ev.get("pid")] = ev["args"]["name"]
        for ev in data.get("traceEvents", []):
            if ev.get("ph") == "X":
                lane = names.get((ev.get("pid"), ev.get("tid")), "")
                proc = pids.get(ev.get("pid"), "")
                yield proc, lane, ev


def aggregate_device_ops(trace_dir: str):
    """Sum device-lane op durations by name; return (total_ms, [op rows])."""
    per_op = collections.Counter()
    per_op_count = collections.Counter()
    for proc, lane, ev in trace_events(trace_dir):
        # Device lanes are under the TPU/device process, XLA Ops threads.
        text = f"{proc}/{lane}".lower()
        if "xla op" not in text and "tensorflow op" not in text:
            continue
        name = ev.get("name", "?")
        per_op[name] += ev.get("dur", 0) / 1000.0  # us -> ms
        per_op_count[name] += 1
    rows = [
        {"op": op, "ms": round(ms, 3), "count": per_op_count[op]}
        for op, ms in per_op.most_common()
    ]
    return round(sum(per_op.values()), 2), rows


def fusion_bodies(hlo_text: str):
    """Map fusion instruction name -> opcode summary of its computation.

    Optimized HLO prints `%name = ... fusion(...), kind=..., calls=%comp`;
    each `%comp` is a computation block whose instruction opcodes tell us
    what the fusion actually does (scatter, iota-compare, reduce, dot...).
    """
    # computation name -> list of "opcode shape" strings
    comps: dict[str, list[str]] = {}
    current = None
    for line in hlo_text.splitlines():
        m = re.match(r"\s*%?([\w\.\-]+)\s*\([^)]*\)\s*->\s*.*{\s*$", line)
        if m:
            current = m.group(1)
            comps[current] = []
            continue
        if current is not None:
            if line.strip() == "}":
                current = None
                continue
            im = re.match(
                r"\s*(?:ROOT\s+)?%?[\w\.\-]+\s*=\s*(\(.*?\)|\S+)\s+([\w\-]+)",
                line,
            )
            if im:
                comps[current].append(f"{im.group(2)} {im.group(1)}")
    # fusion instr -> calls= (line-based: shapes nest parens/braces — e.g.
    # tuple outputs with T(8,128) tilings — so a single regex over the whole
    # instruction is fragile)
    fus = {}
    for line in hlo_text.splitlines():
        if " fusion(" not in line or "calls=" not in line:
            continue
        nm = re.match(r"\s*(?:ROOT\s+)?%?([\w\.\-]+)\s*=", line)
        cm = re.search(r"calls=%?([\w\.\-]+)", line)
        if nm and cm:
            fus[nm.group(1)] = cm.group(1)
    out = {}
    for name, comp in fus.items():
        ops = comps.get(comp, [])
        # Opcode histogram + the biggest shapes, compact.
        hist = collections.Counter(o.split()[0] for o in ops)
        big = sorted(
            (o for o in ops if "[" in o),
            key=lambda o: -eval_size(o.split()[1]),
        )[:4]
        out[name] = {
            "opcodes": dict(hist.most_common()),
            "largest": big,
        }
    return out


def eval_size(shape: str) -> int:
    m = re.search(r"\[([\d,]*)\]", shape)
    if not m or not m.group(1):
        return 0
    n = 1
    for d in m.group(1).split(","):
        n *= int(d)
    return n


def profile_megastep(args) -> None:
    """Host-dispatch-gap profile of the paged engine: the same request
    mix through the chunk loop and through --megastep K, with host round
    trips per token and per-program dispatch walls side by side."""
    import time

    import numpy as np

    from distributed_lms_raft_llm_tpu.engine import (
        EngineConfig, PagedEngine, SamplingParams,
    )

    # --model tiny runs the random-init test model so the dispatch-gap
    # profile works off-chip (CPU-speed smoke of the tooling itself;
    # dispatch COUNTS are model-independent, only the walls change).
    tiny = args.model == "tiny"
    max_new = 16 if tiny else 128
    paths = {}
    if not tiny:
        ckpt_dir = os.path.join(REPO, "data", "gpt2-local")
        paths = dict(
            checkpoint=os.path.join(ckpt_dir, "model.safetensors"),
            vocab_path=os.path.join(ckpt_dir, "vocab.json"),
            merges_path=os.path.join(ckpt_dir, "merges.txt"),
        )
    sampling = (
        SamplingParams.greedy(max_new_tokens=max_new) if args.greedy
        else SamplingParams.reference_defaults(max_new_tokens=max_new)
    )
    cfg = EngineConfig(
        model=args.model,
        sampling=sampling,
        quant=None if args.bf16 or tiny else "int8",
        kv_quant=not (args.bf16 or tiny),
        spec_tokens=args.spec_tokens,
        length_buckets=(16,) if tiny else (64,),
        batch_buckets=(args.batch,),
        tp=args.tp,
        ep=args.ep,
        **paths,
    )
    def run(megastep: int) -> dict:
        # Re-seeded per run: both the K=1 and K=args.megastep passes must
        # measure the IDENTICAL workload, or the before/after ratio
        # compares two different prompt sets.
        rng = np.random.default_rng(0)
        eng = PagedEngine(cfg, slots=args.batch, chunk=args.chunk,
                          megastep=megastep, megastep_max=megastep)
        plen = 8 if tiny else 48
        prompts = [
            eng.tokenizer.decode(
                rng.integers(0, eng.tokenizer.vocab_size, plen).tolist()
            )
            for _ in range(2 * args.batch)
        ]
        eng.warmup()
        eng.pop_dispatch_stats()
        eng.pop_program_times()
        t0 = time.monotonic()
        for p in prompts:
            eng.submit(p)
        eng.drain()
        wall = time.monotonic() - t0
        dispatches, tokens, dead, _stall_ms, _stalled = \
            eng.pop_dispatch_stats()
        per_prog: dict = {}
        for pname, _start, wall_s in eng.pop_program_times():
            n, tot = per_prog.get(pname, (0, 0.0))
            per_prog[pname] = (n + 1, tot + wall_s)
        return {
            "megastep": megastep,
            "host_dispatches": dispatches,
            "emitted_tokens": tokens,
            "host_dispatches_per_token": (
                round(dispatches / tokens, 4) if tokens else None
            ),
            "megastep_dead_lane_tokens": dead,
            "tokens_per_sec": round(tokens / wall, 1),
            "dispatch_wall_ms": {
                name: {"count": n, "mean_ms": round(tot / n * 1000, 2)}
                for name, (n, tot) in sorted(per_prog.items())
            },
        }

    before = run(1)
    after = run(args.megastep)
    out_path = args.out or os.path.join(
        REPO, "profiles",
        f"megastep_dispatch_gap_k{args.megastep}_chunk{args.chunk}"
        f"_batch{args.batch}.json",
    )
    payload = {
        "description": (
            "Host dispatch-gap profile of the paged engine: identical "
            f"workload (2x{args.batch} requests, {max_new} new tokens) "
            "through "
            f"the chunk loop (megastep=1) and through {args.megastep}-"
            "chunk device-resident megasteps; host round trips per "
            "emitted token is the ratio the megastep attacks"
        ),
        "chunk": args.chunk,
        "before": before,
        "after": after,
    }
    with open(out_path, "w") as fh:
        json.dump(payload, fh, indent=1)
    print(f"wrote {out_path}")
    for row in (before, after):
        print(
            f"  megastep={row['megastep']:<3} dispatches/token="
            f"{row['host_dispatches_per_token']} "
            f"tok/s={row['tokens_per_sec']} "
            f"dead_lanes={row['megastep_dead_lane_tokens']}"
        )


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--bf16", action="store_true",
                    help="profile the bf16 config instead of int8+int8kv")
    ap.add_argument("--out", default=None)
    ap.add_argument("--trace-dir", default="/tmp/decode_trace")
    ap.add_argument("--spec-tokens", type=int, default=0,
                    help="profile the speculative decode path (pair with "
                         "--greedy; engine/spec.py verify windows)")
    ap.add_argument("--greedy", action="store_true")
    ap.add_argument("--megastep", type=int, default=0,
                    help="dispatch-gap mode: profile the PAGED engine's "
                         "host loop at K-chunk megasteps vs the chunk "
                         "loop (host round trips per token before/after)")
    ap.add_argument("--model", default="gpt2", choices=["gpt2", "tiny"],
                    help="dispatch-gap mode: tiny = random-init test "
                         "model (CPU-speed smoke of the profile tooling; "
                         "dispatch counts are model-independent)")
    ap.add_argument("--chunk", type=int, default=16,
                    help="paged device chunk size (dispatch-gap mode)")
    ap.add_argument("--tp", type=int, default=1,
                    help="tensor-parallel ways; the paged engine shards "
                         "its slot KV cache heads axis over tp too, so a "
                         "tp>1 dispatch-gap profile measures the sharded "
                         "step programs")
    ap.add_argument("--ep", type=int, default=1,
                    help="expert-parallel ways (MoE models only)")
    args = ap.parse_args()

    if args.megastep:
        profile_megastep(args)
        return

    import jax
    import numpy as np

    eng = build_engine(args.batch, quant=not args.bf16,
                       spec_tokens=args.spec_tokens,
                       greedy=args.greedy, tp=args.tp, ep=args.ep)
    if args.spec_tokens:
        # A REAL prompt: an all-zeros one is 64 repeated tokens, which
        # prompt-lookup drafting predicts near-perfectly — the profile
        # would show best-case window counts, not representative ones.
        prompt = (
            "You are an intelligent assistant. Answer the following "
            "question clearly and concisely.\nQuestion: Explain how "
            "leader election works in the Raft consensus algorithm and "
            "why a quorum is needed.\nAnswer:"
        )
        ids, mask, _ = eng.encode_prompts([prompt] * args.batch)
    else:
        ids = np.zeros((args.batch, 64), np.int32)
        mask = np.ones((args.batch, 64), bool)
    eng.generate_ids(ids, mask)  # compile + warm
    import shutil

    shutil.rmtree(args.trace_dir, ignore_errors=True)
    with jax.profiler.trace(args.trace_dir):
        result = eng.generate_ids(ids, mask)  # device_get inside = sync
    del result

    total_ms, rows = aggregate_device_ops(args.trace_dir)

    # HLO bodies for the decode program (the dominant while_loop lives
    # there); prefill adds its own fusions — correlate against both.
    import jax.numpy as jnp

    with eng.mesh:
        state = eng._prefill(
            eng.params, input_ids=jnp.asarray(ids),
            prompt_mask=jnp.asarray(mask), rng=jax.random.key(0),
        )
        if args.spec_tokens:
            lowered = eng._decode.lower(eng.params, state, jnp.asarray(ids))
        else:
            lowered = eng._decode.lower(eng.params, state)
        hlo = lowered.compile().as_text()
    bodies = fusion_bodies(hlo)

    for row in rows[:60]:
        base = row["op"].split("(")[0]
        if base in bodies:
            row["hlo"] = bodies[base]

    label = "bf16" if args.bf16 else "int8w_int8kv"
    if args.greedy:
        label += "_greedy"
    if args.spec_tokens:
        label += f"_spec{args.spec_tokens}"
    out_path = args.out or os.path.join(
        REPO, "profiles", f"decode_{label}_r5_batch{args.batch}.json"
    )
    payload = {
        "description": (
            f"Device-time breakdown of ONE generate_ids call (64-token "
            f"prompt prefill + decode to 128 tokens), GPT-2-small batch "
            f"{args.batch}, {label}; fusions annotated with their "
            f"fused-computation opcode histograms from the optimized HLO "
            f"of the decode program"
        ),
        "total_device_ms": total_ms,
        "ops_ms": rows[:80],
    }
    with open(out_path, "w") as fh:
        json.dump(payload, fh, indent=1)
    print(f"wrote {out_path}  total_device_ms={total_ms}")
    for row in rows[:12]:
        extra = ""
        if "hlo" in row:
            extra = " " + ",".join(
                f"{k}x{v}" for k, v in row["hlo"]["opcodes"].items()
            )
        print(f"  {row['ms']:9.2f} ms x{row['count']:<5} {row['op'][:60]}{extra[:90]}")


if __name__ == "__main__":
    main()
