"""Server-level bench: concurrent gRPC clients against the tutoring server.

BASELINE's TTFT metric is per student query UNDER CONCURRENCY, through the
real serving stack (gRPC -> queue -> engine), not an idle-engine
measurement. This boots the tutoring server in-process (same serve_async
the CLI uses), fires N concurrent clients x M queries each over real gRPC,
and reports the p50/p95 TTFT from the server's own histogram plus
end-to-end answer latency and aggregate throughput.

    python scripts/bench_server.py [--clients 8] [--queries 4] [--paged]
                                   [--quant int8] [--kv-quant]
                                   [--greedy] [--spec-tokens 8]

Prints ONE JSON line (same contract as bench.py).
"""

from __future__ import annotations

import argparse
import asyncio
import json
import os
import sys
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

QUESTIONS = [
    "How does Raft consensus elect a leader after a network partition?",
    "Explain the difference between eventual and linearizable consistency.",
    "Why does two-phase commit block when the coordinator fails?",
    "What does the MXU on a TPU actually multiply?",
    "How does a KV cache speed up autoregressive decoding?",
    "When should a distributed system prefer leases over locks?",
    "What is the purpose of a write-ahead log in a database?",
    "How does gRPC multiplex requests over one HTTP/2 connection?",
]


async def run(args) -> dict:
    import grpc

    from bench import ensure_local_artifacts
    from distributed_lms_raft_llm_tpu.engine import (
        EngineConfig, PagedEngine, SamplingParams, TutoringEngine,
    )
    from distributed_lms_raft_llm_tpu.engine.program_inventory import (
        effective_megastep_max,
    )
    from distributed_lms_raft_llm_tpu.proto import lms_pb2, rpc
    from distributed_lms_raft_llm_tpu.serving import tutoring_server
    from distributed_lms_raft_llm_tpu.utils.metrics import Metrics

    # The local trained checkpoint is gpt2-small; larger gpt2-* models
    # bench random-init at full size (decode cost is weight-value-
    # independent — same caveat as bench.py / BASELINE config 3) but KEEP
    # the BPE vocab/merges: tokenization is model-size-independent, and
    # the byte fallback would tokenize ~4x longer prompts, skewing
    # cross-size TTFT comparisons.
    artifacts = {}
    if args.model.startswith("gpt2"):
        art = ensure_local_artifacts()
        artifacts = {"vocab_path": art["vocab_path"],
                     "merges_path": art["merges_path"]}
        if args.model == "gpt2":
            artifacts["checkpoint"] = art["checkpoint"]
    sampling = (
        SamplingParams.greedy(max_new_tokens=args.max_new_tokens)
        if args.greedy
        else SamplingParams.reference_defaults(
            max_new_tokens=args.max_new_tokens
        )
    )
    config = EngineConfig(
        model=args.model,
        sampling=sampling,
        quant=args.quant,
        kv_quant=args.kv_quant,
        spec_tokens=args.spec_tokens,
        **artifacts,
    )
    if args.paged:
        engine = PagedEngine(
            config, slots=args.slots, chunk=args.chunk,
            inflight=args.inflight, megastep=args.megastep,
            megastep_max=args.megastep_max,
            prefix_cache=getattr(args, "prefix_cache", False),
            prefix_cache_blocks=getattr(args, "prefix_cache_blocks", 512),
        )
    else:
        engine = TutoringEngine(config)
    engine.warmup()
    engine.total_generated_tokens = 0  # count only benched traffic

    # Same queue + servicer stack serve_async wires, but bound to an
    # ephemeral port the test can read back.
    metrics = Metrics()
    if args.paged:
        from distributed_lms_raft_llm_tpu.engine import PagedQueue

        queue = PagedQueue(engine, metrics=metrics)
    else:
        from distributed_lms_raft_llm_tpu.engine import BatchingQueue

        queue = BatchingQueue(engine, max_batch=8, metrics=metrics)
    await queue.start()
    server = grpc.aio.server()
    rpc.add_TutoringServicer_to_server(
        tutoring_server.TutoringService(queue, metrics), server
    )
    port = server.add_insecure_port("127.0.0.1:0")
    await server.start()

    async def client(cid: int) -> list:
        lat = []
        async with grpc.aio.insecure_channel(f"127.0.0.1:{port}") as channel:
            stub = rpc.TutoringStub(channel)
            for q in range(args.queries):
                question = QUESTIONS[(cid + q) % len(QUESTIONS)]
                t0 = time.monotonic()
                resp = await stub.GetLLMAnswer(
                    lms_pb2.QueryRequest(token="t", query=question),
                    timeout=120,
                )
                lat.append(time.monotonic() - t0)
                assert resp.success, resp.response
        return lat

    t0 = time.monotonic()
    per_client = await asyncio.gather(
        *[client(i) for i in range(args.clients)]
    )
    wall = time.monotonic() - t0
    await server.stop(None)
    await queue.close()

    snap = metrics.snapshot()
    answer_lat = sorted(x for lats in per_client for x in lats)
    n = len(answer_lat)
    ttft = snap["latency"].get("ttft", {})
    import jax

    n_chips = max(1, len(jax.devices()))
    return {
        "metric": "tutoring_server_ttft_p50_ms_under_concurrency",
        "value": round(ttft.get("p50_s", 0.0) * 1000, 2),
        "unit": "ms",
        "model": args.model,
        "clients": args.clients,
        "queries_per_client": args.queries,
        "engine": "paged" if args.paged else "batched",
        "quant": args.quant or "bf16",
        "kv_quant": args.kv_quant,
        "greedy": args.greedy,
        "spec_tokens": args.spec_tokens,
        # Megastep configuration + the measured host-round-trips ratio
        # (PagedQueue keeps the gauge current from the engine's drained
        # dispatch stats; None on the batched engine).
        "chunk": args.chunk,
        "megastep": args.megastep,
        "megastep_max": effective_megastep_max(args.megastep,
                                               args.megastep_max),
        "inflight": args.inflight,
        "host_dispatches_per_token": snap.get("gauges", {}).get(
            "host_dispatches_per_token"
        ),
        "megastep_dead_lane_tokens": snap.get("counters", {}).get(
            "megastep_dead_lane_tokens"
        ),
        # Last completed batch's mean (the gauge is last-value); batch
        # counts here are small enough that it is representative, but it
        # is a sample, not a run aggregate. The counter IS an aggregate:
        # tokens speculation produced beyond the guaranteed one/window.
        "spec_tokens_per_window": snap.get("gauges", {}).get(
            "spec_tokens_per_window"
        ),
        "spec_accepted_tokens": snap.get("counters", {}).get(
            "spec_accepted_tokens"
        ),
        # Shared-prefix cache effectiveness (None when disabled or on
        # the batched engine): run-cumulative hit rate plus the raw
        # hit-token and eviction counters the queue maintains.
        "prefix_cache": getattr(args, "prefix_cache", False),
        "prefix_cache_hit_rate": snap.get("gauges", {}).get(
            "prefix_cache_hit_rate"
        ),
        "prefix_cache_hit_tokens": snap.get("counters", {}).get(
            "prefix_cache_hit_tokens"
        ),
        "prefix_cache_evictions": snap.get("counters", {}).get(
            "prefix_cache_evictions"
        ),
        "ttft_p90_ms": round(ttft.get("p90_s", 0.0) * 1000, 2),
        "ttft_count": ttft.get("count", 0),
        "answer_p50_s": round(answer_lat[n // 2], 3),
        "answer_p95_s": round(answer_lat[min(int(n * 0.95), n - 1)], 3),
        "requests_per_s": round(n / wall, 2),
        "tokens_per_sec_per_chip": round(
            getattr(engine, "total_generated_tokens", 0) / wall / n_chips, 2
        ),
        "wall_s": round(wall, 1),
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--model", default="gpt2",
                    help="any models/registry preset (BASELINE config 3 = "
                         "gpt2-medium)")
    ap.add_argument("--clients", type=int, default=8)
    ap.add_argument("--queries", type=int, default=4)
    ap.add_argument("--max-new-tokens", type=int, default=128)
    ap.add_argument("--paged", action="store_true")
    ap.add_argument("--slots", type=int, default=8)
    ap.add_argument("--chunk", type=int, default=16)
    ap.add_argument("--megastep", type=int, default=1,
                    help="paged megastep: starting K of the controller — "
                         "device chunks fused per host dispatch")
    ap.add_argument("--megastep-max", type=int, default=0,
                    help="megastep controller ceiling (0 = follow "
                         "--megastep)")
    ap.add_argument("--inflight", type=int, default=2,
                    help="paged dispatch pipelining depth")
    ap.add_argument("--prefix-cache", action="store_true",
                    help="paged radix shared-prefix KV cache (hit rate "
                         "lands in the record)")
    ap.add_argument("--prefix-cache-blocks", type=int, default=512,
                    help="shared-prefix cache block budget")
    ap.add_argument("--quant", default=None, choices=["int8"])
    ap.add_argument("--kv-quant", action="store_true")
    ap.add_argument("--greedy", action="store_true",
                    help="temperature-0 sampling (the speculative serving "
                         "configuration)")
    ap.add_argument("--spec-tokens", type=int, default=0,
                    help="speculative decoding draft window (exact; both "
                         "engines — with --paged the step verifies per-slot "
                         "draft windows)")
    args = ap.parse_args()
    print(json.dumps(asyncio.run(run(args))))


if __name__ == "__main__":
    main()
