"""North-star end-to-end bench: `LMS.GetLLMAnswer` through the FULL stack.

BASELINE's student-visible latency is defined at the LMS `GetLLMAnswer`
entry point — linearizable read fence, session check, BERT relevance gate,
HMAC'd fan-out to the TPU tutoring node, generation, and the answer back
through the leader (reference path: GUI_RAFT_LLM_SourceCode/
lms_gui_final.py:900-929 -> lms_server.py:1237-1274). bench_server.py
measures the tutoring node alone; this script boots the real deployment —
3 Raft LMS nodes (quorum of the reference's 5-node topology) + the gate +
the tutoring server, all from configs/cluster.toml artifacts — registers N
student accounts over real gRPC, uploads an assignment each, and fires
N x M concurrent `ask_llm` queries.

Prints ONE JSON line: answer-latency p50/p90/p95 (for a unary RPC the
student-visible TTFT IS the answer latency), throughput, and the gate
pass/reject split.

    python scripts/bench_cluster.py [--students 8] [--queries 4]
"""

from __future__ import annotations

import argparse
import concurrent.futures
import json
import os
import shutil
import subprocess
import sys
import tempfile
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

CONFIG = os.path.join(REPO, "configs", "cluster.toml")

QUESTIONS = [
    "How does Raft consensus elect a leader after a network partition?",
    "Explain the difference between eventual and linearizable consistency.",
    "Why does two-phase commit block when the coordinator fails?",
    "How does a KV cache speed up autoregressive decoding?",
]

ASSIGNMENT = (
    b"Homework: explain the Raft consensus algorithm - leader election, "
    b"log replication, commitment, and safety under network partitions; "
    b"compare with two-phase commit and discuss consistency models."
)


def boot(args) -> list:
    """Start 3 LMS nodes + the tutoring node as subprocesses; return them."""
    procs = []
    env = dict(os.environ)
    tmp = args.workdir

    def spawn(cmd, log_name):
        log = open(os.path.join(tmp, log_name), "w")
        p = subprocess.Popen(
            cmd, cwd=REPO, env=env, stdout=log, stderr=subprocess.STDOUT
        )
        p._log_path = log.name
        procs.append(p)
        return p

    spawn(
        [sys.executable, "-m",
         "distributed_lms_raft_llm_tpu.serving.tutoring_server",
         "--config", CONFIG],
        "tutoring.log",
    )
    for i in (1, 2, 3):
        spawn(
            [sys.executable, "-m",
             "distributed_lms_raft_llm_tpu.serving.lms_server",
             "--config", CONFIG, "--id", str(i),
             "--data-dir", os.path.join(tmp, f"node{i}")],
            f"lms{i}.log",
        )
    return procs


def run_bench(args) -> dict:
    from distributed_lms_raft_llm_tpu import config as config_lib
    from distributed_lms_raft_llm_tpu.client.client import LMSClient

    cfg = config_lib.load_config(CONFIG)
    servers = [cfg.cluster.nodes[k] for k in sorted(cfg.cluster.nodes)][:3]

    def setup(sid: int):
        c = LMSClient(servers, discovery_rounds=30, discovery_backoff_s=3.0)
        user = f"bench_student_{os.getpid()}_{sid}"
        c.register(user, "pw12345", "student")
        assert c.login(user, "pw12345"), f"login failed for {user}"
        assert c.upload_assignment("hw1.txt", ASSIGNMENT)
        # One untimed warm query so per-bucket first-compile (if any) and
        # channel setup don't land in the measured window.
        c.ask_llm(QUESTIONS[sid % len(QUESTIONS)])
        return c

    def timed_queries(arg) -> list:
        sid, c = arg
        lat = []
        for q in range(args.queries):
            t0 = time.monotonic()
            resp = c.ask_llm(QUESTIONS[(sid + q) % len(QUESTIONS)])
            dt = time.monotonic() - t0
            assert resp.response, "empty GetLLMAnswer response"
            gated = "does not appear related" in resp.response
            lat.append((dt, bool(resp.success), gated))
        return lat

    with concurrent.futures.ThreadPoolExecutor(args.students) as pool:
        clients = list(pool.map(setup, range(args.students)))
        # Only the steady-state query phase is timed: registration, login,
        # upload, and the warm queries all happened above.
        t0 = time.monotonic()
        per_student = list(pool.map(timed_queries, enumerate(clients)))
        wall = time.monotonic() - t0
    for c in clients:
        c.close()

    flat = [x for lats in per_student for x in lats]
    # Gate rejections short-circuit before the tutoring fan-out (success
    # with an advisory message) — a different, much cheaper code path, so
    # they are counted but kept OUT of the answer-latency percentiles.
    ok = sorted(dt for dt, success, gated in flat if success and not gated)
    gated = sum(1 for _, _, g in flat if g)
    n = len(ok)
    assert n >= 0.8 * len(flat), (
        f"only {n}/{len(flat)} queries reached the tutoring node "
        f"({gated} gate-rejected)"
    )
    pct = lambda p: round(ok[min(int(n * p), n - 1)], 3)  # noqa: E731
    return {
        "metric": "lms_get_llm_answer_e2e_p50_s",
        "value": pct(0.50),
        "unit": "s",
        "students": args.students,
        "queries_per_student": args.queries,
        "p90_s": pct(0.90),
        "p95_s": pct(0.95),
        "count": n,
        "gate_rejected": gated,
        "requests_per_s": round(n / wall, 2),
        "wall_s": round(wall, 1),
        "stack": "gui-client-lib -> LMS leader (read fence + session + "
                 "BERT gate) -> HMAC fan-out -> TPU tutoring (paged int8)",
    }


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--students", type=int, default=8)
    ap.add_argument("--queries", type=int, default=4)
    ap.add_argument("--startup-wait", type=float, default=150.0,
                    help="max seconds to wait for cluster + engine warmup")
    ap.add_argument("--keep-workdir", action="store_true")
    args = ap.parse_args()
    args.workdir = tempfile.mkdtemp(prefix="bench_cluster_")

    procs = boot(args)
    try:
        # Wait for the tutoring server's warmup (it logs "listening").
        deadline = time.monotonic() + args.startup_wait
        tut_log = os.path.join(args.workdir, "tutoring.log")
        while time.monotonic() < deadline:
            if os.path.exists(tut_log) and "listening" in open(tut_log).read():
                break
            if any(p.poll() is not None for p in procs):
                for p in procs:
                    if p.poll() is not None:
                        sys.stderr.write(open(p._log_path).read()[-2000:])
                raise SystemExit("a server process died during startup")
            time.sleep(2)
        else:
            raise SystemExit("tutoring server did not come up in time")
        print(json.dumps(run_bench(args)))
    finally:
        for p in procs:
            p.terminate()
        for p in procs:
            try:
                p.wait(timeout=10)
            except subprocess.TimeoutExpired:
                p.kill()
        if not args.keep_workdir:
            shutil.rmtree(args.workdir, ignore_errors=True)


if __name__ == "__main__":
    main()
