"""Build local HF-format serving artifacts: checkpoint + real BPE vocab.

This image has zero network egress and no HF cache, so pretrained GPT-2
weights are unobtainable. What CAN be real offline:

- the checkpoint FORMAT and loading path: a full-size HF `GPT2LMHeadModel`
  state_dict (seeded random weights) written to `.safetensors`, exactly the
  artifact `models.convert.load_safetensors` + `gpt2_params_from_hf`
  consume in production;
- the tokenizer: a REAL byte-level BPE trained with the HF `tokenizers`
  trainer on local text, emitting the standard `vocab.json`/`merges.txt`
  our `BPETokenizer` loads.

The bench and servers then run the identical code path a user with hub
access runs — point `--checkpoint/--vocab/--merges` at downloaded files and
nothing else changes. Reference analogue: GUI_RAFT_LLM_SourceCode/
tutoring_server.py:10-12 (`GPT2LMHeadModel.from_pretrained("gpt2")`).

Usage: python scripts/make_local_checkpoint.py [--out data/gpt2-local]
"""

from __future__ import annotations

import argparse
import glob
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def build_corpus(out_path: str, max_files: int = 400) -> str:
    """Concatenate local prose/code into a BPE training corpus."""
    sources: list[str] = []
    for pattern in (
        "/root/repo/*.md",
        "/root/repo/distributed_lms_raft_llm_tpu/**/*.py",
        "/root/repo/tests/*.py",
        "/usr/lib/python3*/[a-z]*.py",
        "/usr/share/doc/**/*.txt",
    ):
        sources.extend(sorted(glob.glob(pattern, recursive=True))[:max_files])
    with open(out_path, "w", encoding="utf-8") as out:
        for src in sources:
            try:
                with open(src, encoding="utf-8", errors="ignore") as f:
                    out.write(f.read())
                    out.write("\n")
            except OSError:
                continue
    return out_path


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="data/gpt2-local")
    ap.add_argument("--model", default="gpt2",
                    choices=["gpt2", "gpt2-medium", "gpt2-large"])
    ap.add_argument("--vocab-size", type=int, default=50257)
    ap.add_argument("--seed", type=int, default=0)
    args = ap.parse_args()

    os.makedirs(args.out, exist_ok=True)
    ckpt = os.path.join(args.out, "model.safetensors")
    vocab = os.path.join(args.out, "vocab.json")
    merges = os.path.join(args.out, "merges.txt")

    if not (os.path.exists(vocab) and os.path.exists(merges)):
        import tokenizers

        corpus = build_corpus(os.path.join(args.out, "corpus.txt"))
        bpe = tokenizers.ByteLevelBPETokenizer()
        bpe.train([corpus], vocab_size=args.vocab_size, min_frequency=2,
                  special_tokens=["<|endoftext|>"])
        bpe.save_model(args.out)
        os.remove(corpus)
        print(f"trained BPE vocab: {bpe.get_vocab_size()} tokens -> {vocab}")

    if not os.path.exists(ckpt):
        import torch
        import transformers

        from distributed_lms_raft_llm_tpu.models import convert

        arch = {
            "gpt2": dict(),
            "gpt2-medium": dict(n_embd=1024, n_layer=24, n_head=16),
            "gpt2-large": dict(n_embd=1280, n_layer=36, n_head=20),
        }[args.model]
        torch.manual_seed(args.seed)
        model = transformers.GPT2LMHeadModel(transformers.GPT2Config(**arch))
        sd = {
            k: v.detach().cpu().numpy()
            for k, v in model.state_dict().items()
            if k != "lm_head.weight"  # tied to wte
        }
        convert.save_safetensors(ckpt, sd)
        n = sum(v.size for v in sd.values())
        print(f"wrote {args.model} checkpoint: {n/1e6:.0f}M params -> {ckpt}")


if __name__ == "__main__":
    main()
