"""Build local HF-format serving artifacts: checkpoint + real BPE vocab.

This image has zero network egress and no HF cache, so pretrained GPT-2
weights are unobtainable. What CAN be real offline:

- the checkpoint FORMAT and loading path: a full-size HF `GPT2LMHeadModel`
  state_dict (seeded random weights) written to `.safetensors`, exactly the
  artifact `models.convert.load_safetensors` + `gpt2_params_from_hf`
  consume in production;
- the tokenizer: a REAL byte-level BPE trained with the HF `tokenizers`
  trainer on local text, emitting the standard `vocab.json`/`merges.txt`
  our `BPETokenizer` loads.

The bench and servers then run the identical code path a user with hub
access runs — point `--checkpoint/--vocab/--merges` at downloaded files and
nothing else changes. Reference analogue: GUI_RAFT_LLM_SourceCode/
tutoring_server.py:10-12 (`GPT2LMHeadModel.from_pretrained("gpt2")`).

Usage: python scripts/make_local_checkpoint.py [--out data/gpt2-local]
"""

from __future__ import annotations

import argparse
import glob
import os
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def build_corpus(out_path: str, max_files: int = 400) -> str:
    """Concatenate local prose/code into a BPE training corpus."""
    sources: list[str] = []
    for pattern in (
        f"{REPO}/*.md",
        f"{REPO}/distributed_lms_raft_llm_tpu/**/*.py",
        f"{REPO}/tests/*.py",
        "/usr/lib/python3*/[a-z]*.py",
        "/usr/share/doc/**/*.txt",
    ):
        sources.extend(sorted(glob.glob(pattern, recursive=True))[:max_files])
    with open(out_path, "w", encoding="utf-8") as out:
        for src in sources:
            try:
                with open(src, encoding="utf-8", errors="ignore") as f:
                    out.write(f.read())
                    out.write("\n")
            except OSError:
                continue
    return out_path


def build_bert_local(out_dir: str, seed: int = 0,
                     vocab_size: int = 30522) -> None:
    """data/bert-local: WordPiece vocab.txt trained on local text + a
    full-size HF-layout BertModel `.safetensors` (seeded random weights)
    consumed through the identical `convert.bert_params_from_hf` path the
    gate uses for real pretrained weights. Reference analogue:
    GUI_RAFT_LLM_SourceCode/lms_server.py:1258-1260 (`bert-base-uncased`
    loaded for the relevance gate)."""
    os.makedirs(out_dir, exist_ok=True)
    ckpt = os.path.join(out_dir, "model.safetensors")
    vocab = os.path.join(out_dir, "vocab.txt")

    if not os.path.exists(vocab):
        import tokenizers

        corpus = build_corpus(os.path.join(out_dir, "corpus.txt"))
        wp = tokenizers.BertWordPieceTokenizer(lowercase=True)
        wp.train([corpus], vocab_size=vocab_size, min_frequency=2,
                 special_tokens=["[PAD]", "[UNK]", "[CLS]", "[SEP]", "[MASK]"])
        wp.save_model(out_dir)
        os.remove(corpus)
        print(f"trained WordPiece vocab: {wp.get_vocab_size()} tokens -> {vocab}")

    if not os.path.exists(ckpt):
        import torch
        import transformers

        from distributed_lms_raft_llm_tpu.models import convert

        torch.manual_seed(seed)
        model = transformers.BertModel(
            transformers.BertConfig()  # bert-base-uncased architecture
        )
        sd = {
            k: v.detach().cpu().numpy()
            for k, v in model.state_dict().items()
            if not k.startswith("pooler.")  # mean-pooled gate: pooler unused
        }
        convert.save_safetensors(ckpt, sd)
        n = sum(v.size for v in sd.values())
        print(f"wrote bert-base checkpoint: {n/1e6:.0f}M params -> {ckpt}")


def build_gpt2_local(out_dir: str, model: str = "gpt2", seed: int = 0,
                     vocab_size: int = 50257) -> None:
    """data/gpt2-local: byte-level BPE vocab/merges trained on local text +
    a full-size HF-layout GPT2LMHeadModel `.safetensors` (seeded random
    weights) consumed through the identical `convert.gpt2_params_from_hf`
    path pretrained weights use."""
    os.makedirs(out_dir, exist_ok=True)
    ckpt = os.path.join(out_dir, "model.safetensors")
    vocab = os.path.join(out_dir, "vocab.json")
    merges = os.path.join(out_dir, "merges.txt")

    if not (os.path.exists(vocab) and os.path.exists(merges)):
        import tokenizers

        corpus = build_corpus(os.path.join(out_dir, "corpus.txt"))
        bpe = tokenizers.ByteLevelBPETokenizer()
        bpe.train([corpus], vocab_size=vocab_size, min_frequency=2,
                  special_tokens=["<|endoftext|>"])
        bpe.save_model(out_dir)
        os.remove(corpus)
        print(f"trained BPE vocab: {bpe.get_vocab_size()} tokens -> {vocab}")

    if not os.path.exists(ckpt):
        import torch
        import transformers

        from distributed_lms_raft_llm_tpu.models import convert

        arch = {
            "gpt2": dict(),
            "gpt2-medium": dict(n_embd=1024, n_layer=24, n_head=16),
            "gpt2-large": dict(n_embd=1280, n_layer=36, n_head=20),
        }[model]
        torch.manual_seed(seed)
        hf = transformers.GPT2LMHeadModel(transformers.GPT2Config(**arch))
        sd = {
            k: v.detach().cpu().numpy()
            for k, v in hf.state_dict().items()
            if k != "lm_head.weight"  # tied to wte
        }
        convert.save_safetensors(ckpt, sd)
        n = sum(v.size for v in sd.values())
        print(f"wrote {model} checkpoint: {n/1e6:.0f}M params -> {ckpt}")


def main() -> None:
    ap = argparse.ArgumentParser()
    ap.add_argument("--out", default="data/gpt2-local")
    ap.add_argument("--model", default="gpt2",
                    choices=["gpt2", "gpt2-medium", "gpt2-large"])
    ap.add_argument("--vocab-size", type=int, default=50257)
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--bert-out", default="data/bert-local",
                    help="BERT gate artifact directory ('' skips)")
    args = ap.parse_args()

    if args.bert_out:
        build_bert_local(args.bert_out, seed=args.seed)
    build_gpt2_local(args.out, model=args.model, seed=args.seed,
                     vocab_size=args.vocab_size)


if __name__ == "__main__":
    main()
