#!/usr/bin/env python
"""Cluster telemetry: live dashboard, timeline export, capacity model.

Polls every node's `/metrics` (utils/scrape.py) into one merged cluster
timeline and renders a terminal dashboard of rates, gauges, and latency
percentiles — the time dimension `/metrics` snapshots alone can't show:

    # Live dashboard over a running cluster (Ctrl-C to stop):
    python scripts/telemetry.py \
        --endpoint http://127.0.0.1:9100 --endpoint http://127.0.0.1:9101

    # Bounded run + JSON export of the full scraped timeline:
    python scripts/telemetry.py --endpoint ... --duration 60 \
        --json run_timeline.json

    # Fit the capacity model over an exported timeline (or a semester-sim
    # BENCH record, which embeds one under "timeline"):
    python scripts/telemetry.py --capacity run_timeline.json \
        --slo-p95 6.0 --ceiling 61500

`--capacity` emits ONE JSON line: req/s per node at the SLO — the
demonstrated load under which the p95 bound still held, plus the
utilization extrapolation (serving tok/s against the chip's measured
saturation ceiling, BENCH_NOTES round 5) and the flight-recorder stage
p95s when available. This artifact is what the ROADMAP's router and
autoscaler consume: "how many req/s can one node take before the SLO
goes" as a measured number instead of a guess.

With `--config`, `[telemetry]` supplies the poll interval, burn-rate
windows/thresholds (the dashboard shows live fast/slow-window burn for
the degraded-rate SLO), and the chip ceiling; `[sim]` supplies the SLO
bounds. Flags override the file.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time
from typing import Any, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from distributed_lms_raft_llm_tpu.utils.metrics import (  # noqa: E402
    percentile_of_sorted,
)
from distributed_lms_raft_llm_tpu.utils.scrape import (  # noqa: E402
    ClusterScraper,
    endpoints_sources,
)
from distributed_lms_raft_llm_tpu.utils.timeline import (  # noqa: E402
    degraded_rate_burn,
)

# Dashboard rows: (label, kind, series). Kinds: rate (counter /s over the
# window), gauge (last value), p95 (histogram p95_s).
_DASH_ROWS: Tuple[Tuple[str, str, str], ...] = (
    ("requests/s", "rate", "llm_requests"),
    ("degraded/s", "rate", "tutoring_degraded"),
    ("shed overload/s", "rate", "shed_overload"),
    ("shed expired/s", "rate", "shed_expired"),
    ("tick stalls/s", "rate", "raft_tick_stalls"),
    ("serving tok/s", "gauge", "serving_tokens_per_s"),
    # The tenant split: background bulk scoring's share of the chip next
    # to interactive serving (utilization is vs the 61.5k ceiling).
    ("scoring tok/s", "gauge", "scoring_tokens_per_s"),
    ("scoring util", "gauge", "scoring_utilization"),
    ("score quanta/s", "rate", "scoring_quanta"),
    ("queue depth", "gauge", "serving_queue_depth"),
    ("prefix hit rate", "gauge", "prefix_cache_hit_rate"),
    ("megastep K", "gauge", "megastep_k"),
    ("router spills/s", "rate", "tutoring_spills"),
    ("hedge wins/s", "rate", "tutoring_hedge_wins"),
    ("fleet size", "gauge", "tutoring_fleet_size"),
    # Streaming/session plane: chunk throughput, resume-at-offset
    # failovers and stall trips (both should be ~0 outside faults), and
    # the live conversational state pinned on the fleet.
    ("stream chunks/s", "rate", "stream_chunks"),
    ("stream resumes/s", "rate", "stream_resumes"),
    ("stream stalls/s", "rate", "stream_stalls"),
    ("sessions live", "gauge", "session_active"),
    ("session pins", "gauge", "session_pinned_blocks"),
    ("answer p95 (s)", "p95", "answer_latency"),
    ("llm_ttft p95 (s)", "p95", "llm_ttft"),
    ("ttft p95 (s)", "p95", "ttft"),
)


def _fmt(value: Optional[float]) -> str:
    if value is None:
        return "      -"
    if abs(value) >= 1000:
        return f"{value:7.0f}"
    return f"{value:7.2f}"


def fetch_groups(endpoints: List[str],
                 timeout_s: float = 2.0) -> Optional[Dict[str, Any]]:
    """GET /admin/raft from the first endpoint that answers: the sharded
    control plane's routing-map version and per-group rows. None when no
    node serves the endpoint (pre-shard deployments keep the old frame)."""
    import urllib.error
    import urllib.request

    for base in endpoints:
        try:
            req = urllib.request.Request(f"{base}/admin/raft",
                                         method="GET")
            with urllib.request.urlopen(req, timeout=timeout_s) as resp:
                return json.loads(resp.read().decode())
        except (urllib.error.URLError, OSError, ValueError):
            continue
    return None


def render_groups(groups: Dict[str, Any], out: Any) -> None:
    """Per-Raft-group dashboard rows from one node's GET /admin/raft."""
    rmap = groups.get("routing_map", {})
    out.write(
        f"  routing map v{rmap.get('version', '?')}  "
        f"groups={rmap.get('n_groups', '?')}  "
        f"courses={len(rmap.get('courses', {}))}\n"
    )
    rows = groups.get("groups", {})
    if not rows:
        return
    out.write(f"  {'group':<7} {'leader':>7} {'term':>7} "
              f"{'applied':>8} {'commit':>8} {'members':>8}\n")
    for gid in sorted(rows, key=lambda g: int(g)):
        row = rows[gid]
        leader = row.get("leader")
        out.write(
            f"  {gid:<7} {('-' if leader is None else leader):>7} "
            f"{row.get('term', 0):>7} {row.get('applied', 0):>8} "
            f"{row.get('commit', 0):>8} {len(row.get('members', {})):>8}\n"
        )


def render_dashboard(scraper: ClusterScraper, window_s: float,
                     burn: Optional[Dict[str, float]] = None,
                     out: Any = None) -> None:
    """One dashboard frame from the scraper's merged cluster timeline."""
    out = out if out is not None else sys.stdout
    tl = scraper.cluster
    out.write(
        f"== cluster telemetry  nodes={scraper.node_count}  "
        f"window={window_s:.0f}s  "
        f"unreachable={sum(scraper.unreachable.values())}\n"
    )
    for label, kind, series in _DASH_ROWS:
        if kind == "rate":
            value = tl.counter_rate(series, window_s)
        elif kind == "gauge":
            value = tl.gauge_last(series)
        else:
            value = tl.hist_p95(series, window_s)
        out.write(f"  {label:<18} {_fmt(value)}\n")
    if burn:
        pairs = "  ".join(f"{k}={v:.2f}" for k, v in sorted(burn.items()))
        out.write(f"  degraded-rate burn: {pairs}\n")
    # Per-node rows: the scraper already keeps one timeline per source —
    # with a tutoring fleet behind the router, per-member req/s, queue
    # depth, and prefix hit rate are what drain/warm-up decisions (and
    # post-mortems of a drill) read; the merged row above can't show a
    # cold rejoined node refilling its cache.
    if len(scraper.nodes) > 1:
        out.write(f"  {'node':<14} {'req/s':>7} {'queue':>7} "
                  f"{'tok/s':>7} {'hit':>7} {'strm/s':>7} "
                  f"{'sess':>7} {'pins':>7} {'p95 s':>7}\n")
        for name in sorted(scraper.nodes):
            ntl = scraper.nodes[name]
            out.write(
                f"  {name:<14}"
                f" {_fmt(ntl.counter_rate('llm_requests', window_s))}"
                f" {_fmt(ntl.gauge_last('serving_queue_depth'))}"
                f" {_fmt(ntl.gauge_last('serving_tokens_per_s'))}"
                f" {_fmt(ntl.gauge_last('prefix_cache_hit_rate'))}"
                f" {_fmt(ntl.counter_rate('stream_chunks', window_s))}"
                f" {_fmt(ntl.gauge_last('session_active'))}"
                f" {_fmt(ntl.gauge_last('session_pinned_blocks'))}"
                f" {_fmt(ntl.hist_p95('answer_latency', window_s))}\n"
            )
    events = tl.events()
    for event in events[-3:]:
        out.write(f"  event: {event.get('kind')}: {event.get('detail')}\n")


def _degraded_burn(scraper: ClusterScraper, windows: Dict[str, float],
                   bound: float) -> Dict[str, float]:
    # THE alerting formula (utils/timeline.degraded_rate_burn, also what
    # the sim's ContinuousSloEngine pages on), not a local variant: the
    # dashboard's burn figure must match what pages.
    out: Dict[str, float] = {}
    for name, window_s in windows.items():
        burn = degraded_rate_burn(scraper.cluster, window_s, bound)
        if burn is not None:
            out[name] = burn
    return out


# ------------------------------------------------------- capacity model


def _point_sample(point: Dict[str, Any]) -> Optional[Dict[str, float]]:
    rates = point.get("rates", {})
    hists = point.get("hists", {})
    gauges = point.get("gauges", {})
    req_s = rates.get("llm_requests")
    p95 = None
    for series in ("answer_latency", "llm_ttft", "sim_ask_latency"):
        block = hists.get(series)
        if block and "p95_s" in block:
            p95 = float(block["p95_s"])
            break
    if not req_s or req_s <= 0 or p95 is None:
        return None
    return {
        "req_s": float(req_s),
        "p95_s": p95,
        "tokens_s": float(gauges.get("serving_tokens_per_s", 0.0)),
        "queue_depth": float(gauges.get("serving_queue_depth", 0.0)),
    }


def fit_capacity(
    doc: Dict[str, Any],
    *,
    slo_p95_s: float,
    ceiling_tokens_per_s: float,
    node: Optional[str] = None,
    stage_p95s: Optional[Dict[str, Dict[str, float]]] = None,
    bins: int = 8,
) -> Dict[str, Any]:
    """Fit req/s-per-node-at-SLO from an exported timeline.

    `doc` is a scraper export ({"cluster": ..., "nodes": {...}}), a bare
    timeline ({"points": ...}), or a semester-sim BENCH record (its
    "timeline"/"slos" fields are used). The model is deliberately
    empirical — Borg/Autopilot-style utilization accounting, not
    queueing theory: bin the run's samples by offered load, find the
    highest load bin whose p95 held the SLO. When the run never pushed
    past the SLO the result is a demonstrated LOWER bound
    (`slo_saturated: false`) and the utilization extrapolation (tokens/s
    against the chip ceiling) says how much headroom the fit left."""
    if "timeline" in doc and isinstance(doc["timeline"], dict):
        if stage_p95s is None:
            stage_p95s = (doc.get("slos") or {}).get("stage_p95s")
        doc = doc["timeline"]
    nodes = doc.get("nodes", {})
    source = "cluster"
    node_count = max(1, int(doc.get("node_count", 1) or 1))
    per_node_scale = 1.0
    if node is not None and node in nodes:
        timeline, source = nodes[node], node
    elif node is not None:
        raise SystemExit(f"node {node!r} not in export "
                         f"(have: {sorted(nodes)})")
    elif "tutoring" in nodes:
        # The serving node IS the capacity question; prefer it when the
        # export names one.
        timeline, source = nodes["tutoring"], "tutoring"
    elif "cluster" in doc:
        timeline = doc["cluster"]
        per_node_scale = 1.0 / node_count
    else:
        timeline = doc  # bare {"points": [...]}
    samples = [s for s in (_point_sample(p)
                           for p in timeline.get("points", []))
               if s is not None]
    if not samples:
        raise SystemExit(
            "no usable samples (need points with llm_requests rate and a "
            "latency p95) — was the timeline exported from a loaded run?"
        )
    for s in samples:
        s["req_s"] *= per_node_scale
    max_req = max(s["req_s"] for s in samples)
    width = max_req / bins if max_req > 0 else 1.0
    bin_rows: List[Dict[str, Any]] = []
    demonstrated = 0.0
    p95_at_demonstrated = 0.0
    saturated = False
    for i in range(bins):
        lo, hi = i * width, (i + 1) * width
        members = [s for s in samples
                   if lo < s["req_s"] <= hi or (i == 0 and s["req_s"] == 0)]
        if not members:
            continue
        p95s = sorted(m["p95_s"] for m in members)
        bin_p95 = percentile_of_sorted(p95s, 95)
        ok = bin_p95 <= slo_p95_s
        bin_rows.append({
            "req_s_lo": round(lo, 3), "req_s_hi": round(hi, 3),
            "n": len(members), "p95_s": round(bin_p95, 4),
            "slo_ok": ok,
        })
        if ok:
            best = max(m["req_s"] for m in members)
            if best > demonstrated:
                demonstrated, p95_at_demonstrated = best, bin_p95
        else:
            saturated = True
    utilization: Optional[Dict[str, float]] = None
    tokens = sorted(s["tokens_s"] for s in samples if s["tokens_s"] > 0)
    if source == "cluster":
        # Cluster gauges are worst-of merges (one node's tokens/s) while
        # the req/s above was divided across node_count — a tokens/req
        # ratio from the two would be off by the fleet size. Utilization
        # extrapolation needs a per-node fit (--node, or an export whose
        # serving node is named).
        tokens = []
    if tokens:
        peak_tokens = tokens[-1]
        loaded = [s for s in samples if s["tokens_s"] > 0]
        tokens_per_req = percentile_of_sorted(
            sorted(s["tokens_s"] / s["req_s"] for s in loaded), 50
        )
        utilization = {
            "peak_tokens_per_s": round(peak_tokens, 1),
            "chip_ceiling_tokens_per_s": ceiling_tokens_per_s,
            "peak_fraction": round(peak_tokens / ceiling_tokens_per_s, 4),
            "tokens_per_req": round(tokens_per_req, 1),
            # Where the chip itself would cap req/s if the SLO never
            # binds first — the extrapolated ceiling, NOT a demonstrated
            # number.
            "token_limited_req_s": round(
                ceiling_tokens_per_s / tokens_per_req, 2
            ) if tokens_per_req > 0 else None,
        }
    qdepths = sorted(s["queue_depth"] for s in samples)
    service_p95 = None
    if stage_p95s:
        for span in ("engine.decode", "engine.batch", "engine.generate"):
            if span in stage_p95s and "p95_s" in stage_p95s[span]:
                service_p95 = stage_p95s[span]["p95_s"]
                break
    return {
        "metric": "capacity_req_s_per_node_at_slo",
        "value": round(demonstrated, 3),
        "unit": "req/s/node",
        "slo_p95_s": slo_p95_s,
        "source": source,
        "node_count": node_count,
        "samples": len(samples),
        "p95_at_capacity_s": round(p95_at_demonstrated, 4),
        # False = the run never drove p95 past the SLO, so `value` is a
        # demonstrated lower bound, not the knee of the curve.
        "slo_saturated": saturated,
        "bins": bin_rows,
        "utilization": utilization,
        "queue_depth_p95": round(percentile_of_sorted(qdepths, 95), 2)
        if qdepths else 0.0,
        # Where the latency budget goes at this load (flight-recorder
        # per-stage p95s), so a capacity number arrives self-explaining.
        "service_time_p95_s": service_p95,
        "stage_p95s": stage_p95s,
    }


# ---------------------------------------------------------------- main


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("--endpoint", action="append", default=[],
                    help="node admin-plane base URL (http://host:port); "
                         "repeatable")
    ap.add_argument("--interval", type=float, default=None,
                    help="poll interval seconds (default: [telemetry] "
                         "sample_interval_s, else 1.0)")
    ap.add_argument("--duration", type=float, default=0.0,
                    help="stop after this many seconds (0 = until Ctrl-C)")
    ap.add_argument("--once", action="store_true",
                    help="one poll + one frame, then exit")
    ap.add_argument("--no-clear", action="store_true",
                    help="append frames instead of clearing the screen")
    ap.add_argument("--json", dest="json_out", default=None,
                    help="write the full scraped timeline export here on "
                         "exit")
    ap.add_argument("--config", default=None,
                    help="TOML deployment file; [telemetry] fills "
                         "interval/windows/ceiling, [sim] the SLO bounds")
    ap.add_argument("--capacity", default=None, metavar="TIMELINE.json",
                    help="fit the capacity model over an exported "
                         "timeline (or a semester-sim BENCH record) "
                         "instead of polling")
    ap.add_argument("--node", default=None,
                    help="capacity: fit over this exported node timeline "
                         "(default: 'tutoring' when present, else the "
                         "merged cluster divided by node count)")
    ap.add_argument("--slo-p95", type=float, default=None,
                    help="answer p95 bound (default: [sim] "
                         "slo_answer_p95_s, else 6.0)")
    ap.add_argument("--ceiling", type=float, default=None,
                    help="chip saturation tok/s (default: [telemetry] "
                         "chip_ceiling_tokens_per_s, else 61500)")
    ap.add_argument("--stage-p95s", default=None,
                    help="capacity: JSON file of flight-recorder stage "
                         "p95s to fold into the model")
    args = ap.parse_args(argv)

    interval = 1.0
    slo_p95 = 6.0
    ceiling = 61500.0
    degraded_bound = 0.5
    windows = {"fast": 60.0, "slow": 600.0}
    if args.config:
        from distributed_lms_raft_llm_tpu.config import load_config

        cfg = load_config(args.config)
        interval = cfg.telemetry.sample_interval_s
        ceiling = cfg.telemetry.chip_ceiling_tokens_per_s
        windows = {"fast": cfg.telemetry.fast_window_s,
                   "slow": cfg.telemetry.slow_window_s}
        # The thresholds contextualize the dashboard's burn figures.
        windows_note = (f"burn thresholds fast={cfg.telemetry.fast_burn} "
                        f"slow={cfg.telemetry.slow_burn}")
        slo_p95 = cfg.sim.slo_answer_p95_s
        degraded_bound = cfg.sim.slo_degraded_rate_max
    else:
        windows_note = ""
    if args.interval is not None:
        interval = args.interval
    if args.slo_p95 is not None:
        slo_p95 = args.slo_p95
    if args.ceiling is not None:
        ceiling = args.ceiling

    if args.capacity:
        with open(args.capacity, encoding="utf-8") as fh:
            doc = json.load(fh)
        stage = None
        if args.stage_p95s:
            with open(args.stage_p95s, encoding="utf-8") as fh:
                stage = json.load(fh)
        model = fit_capacity(doc, slo_p95_s=slo_p95,
                             ceiling_tokens_per_s=ceiling,
                             node=args.node, stage_p95s=stage)
        print(json.dumps(model))
        return 0

    if not args.endpoint:
        ap.error("need --endpoint (live mode) or --capacity (offline fit)")
    scraper = ClusterScraper(
        sources=endpoints_sources(args.endpoint)
    )
    t_end = time.monotonic() + args.duration if args.duration else None
    try:
        while True:
            scraper.poll()
            if not args.no_clear and not args.once:
                sys.stdout.write("\x1b[2J\x1b[H")
            render_dashboard(
                scraper, window_s=max(10.0, 2 * interval),
                burn=_degraded_burn(scraper, windows, degraded_bound),
            )
            groups = fetch_groups(args.endpoint)
            if groups is not None:
                render_groups(groups, sys.stdout)
            if windows_note:
                sys.stdout.write(f"  {windows_note}\n")
            sys.stdout.flush()
            if args.once or (t_end and time.monotonic() >= t_end):
                break
            time.sleep(interval)
    except KeyboardInterrupt:
        pass
    finally:
        if args.json_out:
            with open(args.json_out, "w", encoding="utf-8") as fh:
                json.dump(scraper.export(), fh)
            sys.stderr.write(f"timeline export written to "
                             f"{args.json_out}\n")
    return 0


if __name__ == "__main__":
    sys.exit(main())
