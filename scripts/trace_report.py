#!/usr/bin/env python
"""Flight-recorder waterfall: render one request's span tree as text.

Every serving process retains its own trace fragments in a bounded
flight-recorder ring (utils/tracing.py) and exposes them read-only on its
admin plane. This CLI fetches those fragments over plain HTTP and renders
them:

    # What's retained (pinned exemplars + recent traces) on one node:
    python scripts/trace_report.py --endpoint http://127.0.0.1:9100

    # One request's waterfall, fragments merged across processes (the
    # LMS leader holds client/handler/raft spans; the tutoring node
    # holds queue/engine spans — list every endpoint that saw it):
    python scripts/trace_report.py \
        --endpoint http://127.0.0.1:9100 \
        --endpoint http://127.0.0.1:9101  <request-id>

    # Offline: --json a saved `GET /admin/trace/<id>` response (or a
    # BENCH record's embedded `slowest_trace`) instead of an endpoint.
    python scripts/trace_report.py --json trace.json <request-id>

    # Regression triage: side-by-side per-stage p95 diff of two runs.
    # Each file is a stage_p95s export — a semester-sim BENCH record
    # (slos.stage_p95s), an SLO verdict, a saved trace (the breakdown is
    # computed from its spans), or a bare {stage: {p95_s, ...}} mapping:
    python scripts/trace_report.py --diff before.json after.json

The waterfall is wall-clock aligned: fragments recorded by different
processes line up by their absolute start times, so cross-process clock
skew shows up as (small) overlap rather than being hidden.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import urllib.error
import urllib.request
from typing import Any, Dict, List, Optional, Tuple

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

from distributed_lms_raft_llm_tpu.utils.tracing import (  # noqa: E402
    assemble_forest,
)

BAR_WIDTH = 32


def _fetch(url: str, timeout: float) -> Optional[Dict]:
    try:
        with urllib.request.urlopen(url, timeout=timeout) as resp:
            return json.loads(resp.read().decode())
    except (urllib.error.URLError, OSError, ValueError) as e:
        sys.stderr.write(f"warning: {url}: {e}\n")
        return None


def _flatten(span: Dict[str, Any], depth: int,
             out: List[Tuple[int, Dict[str, Any]]]) -> None:
    out.append((depth, span))
    for child in span.get("children", ()):
        _flatten(child, depth + 1, out)


def render_waterfall(trace: Dict[str, Any], out=None) -> None:
    """Text waterfall for one assembled trace dict (`trace_id`, `route`,
    `flags`, `spans`: forest of span dicts)."""
    out = out if out is not None else sys.stdout
    rows: List[Tuple[int, Dict[str, Any]]] = []
    for root in trace.get("spans", []):
        _flatten(root, 0, rows)
    if not rows:
        out.write("(no spans retained for this trace)\n")
        return
    t0 = min(s.get("start_s", 0.0) for _, s in rows)
    t1 = max(s.get("start_s", 0.0) + s.get("duration_s", 0.0)
             for _, s in rows)
    total = max(t1 - t0, 1e-9)
    flags = ",".join(trace.get("flags", [])) or "-"
    out.write(
        f"trace {trace.get('trace_id', '?')}  route={trace.get('route', '?')}"
        f"  total={total * 1e3:.1f} ms  flags={flags}\n"
    )
    name_w = max(2 + 2 * d + len(s["name"]) for d, s in rows)
    for depth, span in rows:
        start = span.get("start_s", 0.0) - t0
        dur = span.get("duration_s", 0.0)
        lo = int(start / total * BAR_WIDTH)
        hi = max(lo + 1, int((start + dur) / total * BAR_WIDTH))
        bar = " " * lo + "#" * (hi - lo) + " " * (BAR_WIDTH - hi)
        name = "  " * depth + span["name"]
        status = "" if span.get("status", "ok") == "ok" else " !ERROR"
        attrs = span.get("attrs", {})
        extra = ""
        if attrs:
            extra = "  " + ",".join(f"{k}={v}" for k, v in
                                    sorted(attrs.items()))
        out.write(
            f"  {name:<{name_w}} |{bar}| {start * 1e3:8.1f} ms "
            f"+{dur * 1e3:8.1f} ms{status}{extra}\n"
        )


def load_stage_p95s(path: str) -> Dict[str, Dict[str, float]]:
    """Per-stage stats from any artifact this repo emits: a BENCH record
    (slos.stage_p95s), an SLO verdict (stage_p95s), a saved trace doc
    (breakdown computed from its spans), or the bare mapping itself."""
    from distributed_lms_raft_llm_tpu.sim.slo import stage_breakdown

    with open(path, encoding="utf-8") as fh:
        doc = json.load(fh)
    if not isinstance(doc, dict):
        raise SystemExit(f"{path}: expected a JSON object")
    slos = doc.get("slos")
    if isinstance(slos, dict) and isinstance(slos.get("stage_p95s"), dict):
        return slos["stage_p95s"]
    if isinstance(doc.get("stage_p95s"), dict):
        return doc["stage_p95s"]
    tree = doc.get("trace", doc)
    if isinstance(tree, dict) and isinstance(tree.get("spans"), list):
        return stage_breakdown([tree])
    # A bare mapping: every value must look like a stats block.
    if doc and all(isinstance(v, dict) for v in doc.values()):
        return {k: {kk: float(vv) for kk, vv in v.items()}
                for k, v in doc.items()}
    raise SystemExit(f"{path}: no stage_p95s / spans found")


def render_stage_diff(a: Dict[str, Dict[str, float]],
                      b: Dict[str, Dict[str, float]],
                      label_a: str, label_b: str, out=None) -> None:
    """Side-by-side per-stage waterfall diff: where run B's latency
    budget moved relative to run A, worst p95 regression first — the
    round-6 measurement campaign's triage view."""
    out = out if out is not None else sys.stdout
    stages = sorted(
        set(a) | set(b),
        key=lambda s: -abs(b.get(s, {}).get("p95_s", 0.0)
                           - a.get(s, {}).get("p95_s", 0.0)),
    )
    name_w = max([len(s) for s in stages] + [5])
    out.write(
        f"  {'stage':<{name_w}} {'A p95':>10} {'B p95':>10} "
        f"{'delta':>10} {'pct':>8}   A={label_a}  B={label_b}\n"
    )
    for stage in stages:
        pa = a.get(stage, {}).get("p95_s")
        pb = b.get(stage, {}).get("p95_s")
        cell_a = f"{pa * 1e3:8.1f}ms" if pa is not None else "       -"
        cell_b = f"{pb * 1e3:8.1f}ms" if pb is not None else "       -"
        if pa is not None and pb is not None:
            delta = pb - pa
            pct = (f"{delta / pa * 100:+7.1f}%" if pa > 0 else "      -")
            cell_d = f"{delta * 1e3:+8.1f}ms"
        else:
            # A stage only one run has IS the finding (a new stage
            # appeared, or one vanished) — keep it visible, not dropped.
            cell_d, pct = "     new" if pa is None else "    gone", "      -"
        out.write(
            f"  {stage:<{name_w}} {cell_a:>10} {cell_b:>10} "
            f"{cell_d:>10} {pct:>8}\n"
        )


def render_summaries(listing: Dict[str, Any], source: str,
                     out=None) -> None:
    out = out if out is not None else sys.stdout
    out.write(f"== {source}\n")
    for section in ("exemplars", "recent"):
        entries = listing.get(section, [])
        out.write(f"  {section} ({len(entries)}):\n")
        for s in entries:
            flags = ",".join(s.get("flags", [])) or "-"
            pins = ",".join(s.get("pinned", [])) or "-"
            out.write(
                f"    {s.get('trace_id', '?'):<20} "
                f"{s.get('route', '?'):<28} "
                f"{s.get('duration_s', 0.0) * 1e3:9.1f} ms  "
                f"spans={s.get('spans', 0):<4} flags={flags} pins={pins}\n"
            )


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    ap.add_argument("trace_id", nargs="?", default=None,
                    help="request id / trace id to render; omit to list "
                         "what each endpoint retains")
    ap.add_argument("--endpoint", action="append", default=[],
                    help="admin-plane base URL (http://host:port); "
                         "repeatable — fragments merge across endpoints")
    ap.add_argument("--json", action="append", default=[], dest="json_files",
                    help="saved /admin/trace/<id> response (or embedded "
                         "slowest_trace) to merge; repeatable")
    ap.add_argument("--diff", nargs=2, metavar=("A", "B"), default=None,
                    help="side-by-side per-stage p95 diff of two "
                         "stage_p95s exports (BENCH records, SLO "
                         "verdicts, saved traces, or bare mappings)")
    ap.add_argument("--timeout", type=float, default=5.0)
    args = ap.parse_args(argv)
    if args.diff:
        a, b = args.diff
        render_stage_diff(load_stage_p95s(a), load_stage_p95s(b),
                          os.path.basename(a), os.path.basename(b))
        return 0
    if not args.endpoint and not args.json_files:
        ap.error("need at least one --endpoint or --json")

    if args.trace_id is None:
        if args.json_files:
            ap.error("--json holds one trace; pass its trace id to render")
        ok = False
        for ep in args.endpoint:
            listing = _fetch(f"{ep.rstrip('/')}/admin/trace", args.timeout)
            if listing is not None:
                render_summaries(listing, ep)
                ok = True
        return 0 if ok else 2

    # Collect this trace's fragments from every source and re-assemble:
    # a fragment whose remote parent lives in another process's fragment
    # grafts under it (assemble_forest is pure-dict, same machinery the
    # in-process store uses).
    fragments: List[Dict[str, Any]] = []
    route, flags = "", set()
    for ep in args.endpoint:
        doc = _fetch(
            f"{ep.rstrip('/')}/admin/trace/{args.trace_id}", args.timeout
        )
        tree = (doc or {}).get("trace")
        if tree:
            fragments.extend(tree.get("spans", []))
            route = route or tree.get("route", "")
            flags |= set(tree.get("flags", []))
    for path in args.json_files:
        with open(path, encoding="utf-8") as fh:
            doc = json.load(fh)
        tree = doc.get("trace", doc)
        fragments.extend(tree.get("spans", []))
        route = route or tree.get("route", "")
        flags |= set(tree.get("flags", []))
    if not fragments:
        sys.stderr.write(f"trace {args.trace_id} not found anywhere\n")
        return 2
    # Endpoints that share a store (in-process test clusters, a node
    # asked twice) return the same fragments; a span's id is unique, so
    # a repeated root is the same fragment — keep the first copy.
    seen: set = set()
    unique: List[Dict[str, Any]] = []
    for frag in fragments:
        sid = frag.get("span_id")
        if sid in seen:
            continue
        seen.add(sid)
        unique.append(frag)
    fragments = unique
    render_waterfall({
        "trace_id": args.trace_id,
        "route": route,
        "flags": sorted(flags),
        "spans": assemble_forest(fragments),
    })
    return 0


if __name__ == "__main__":
    sys.exit(main())
