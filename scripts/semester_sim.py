#!/usr/bin/env python
"""Semester simulator CLI: one production scenario, one JSON verdict.

Boots an in-process LMS cluster (3 Raft nodes + tutoring), drives a
seeded semester of student traffic along a diurnal curve while the
operations schedule injects a chaos campaign, a TimeoutNow rolling
restart, a disk-fault storage-recovery quarantine, and a membership
add/remove — then audits the acked-write ledger and asserts the SLOs
from every node's /metrics and /healthz.

Prints ONE BENCH-schema JSON line (metric: semester_sim_ask_p95_s) with
the full story: per-event outcomes, SLO verdicts, ledger counts, and the
trace/event digests that make a failed seed replayable:

    python scripts/semester_sim.py                      # [sim] defaults
    python scripts/semester_sim.py --seed 7 --duration 60 --students 48
    python scripts/semester_sim.py --config configs/cluster.toml

Exit status 0 only if every event executed and every SLO held.
"""

from __future__ import annotations

import argparse
import json
import os
import shutil
import sys
import tempfile

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

os.environ.setdefault("JAX_PLATFORMS", "cpu")


def main() -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--config", default=None,
                    help="TOML deployment file; its [sim] section seeds "
                         "the defaults, flags below override")
    ap.add_argument("--seed", type=int, default=None)
    ap.add_argument("--students", type=int, default=None)
    ap.add_argument("--duration", type=float, default=None,
                    help="workload wall-clock seconds")
    ap.add_argument("--base-rate", type=float, default=None,
                    help="mean op arrival rate (ops/s)")
    ap.add_argument("--engine", choices=["echo", "tiny"], default=None,
                    help="tutoring engine: wire-complete echo stand-in "
                         "or the real tiny JAX engine")
    ap.add_argument("--tutoring-nodes", type=int, default=None,
                    help="tutoring fleet size behind the routing tier "
                         "(> 1 adds the fleet drills: kill-one-of-N "
                         "blackout, drain-and-rejoin, autoscale)")
    ap.add_argument("--no-events", action="store_true",
                    help="pure-workload run (no operations schedule)")
    ap.add_argument("--keep-workdir", action="store_true")
    args = ap.parse_args()

    import dataclasses

    from distributed_lms_raft_llm_tpu.config import SimConfig, load_config
    from distributed_lms_raft_llm_tpu.sim import SemesterSim

    cfg = (load_config(args.config).sim if args.config else SimConfig())
    overrides = {}
    if args.seed is not None:
        overrides["seed"] = args.seed
    if args.students is not None:
        overrides["students"] = args.students
    if args.duration is not None:
        overrides["duration_s"] = args.duration
    if args.base_rate is not None:
        overrides["base_rate"] = args.base_rate
    if args.engine is not None:
        overrides["tutoring_engine"] = args.engine
    if args.tutoring_nodes is not None:
        overrides["tutoring_nodes"] = args.tutoring_nodes
    if args.no_events:
        overrides["events"] = False
    if overrides:
        cfg = dataclasses.replace(cfg, **overrides)

    workdir = tempfile.mkdtemp(prefix="semester_sim_")
    try:
        record = SemesterSim(cfg, workdir).run()
        print(json.dumps(record))
        ok = record["slos"]["ok"] and not [
            e for e in record["events"] if not e["ok"]
        ]
        return 0 if ok else 1
    finally:
        if args.keep_workdir:
            sys.stderr.write(f"workdir kept at {workdir}\n")
        else:
            shutil.rmtree(workdir, ignore_errors=True)


if __name__ == "__main__":
    sys.exit(main())
