#!/usr/bin/env python
"""Render the README metrics table from utils/metrics_registry.py.

The registry is the single declaration point for every metric series the
servers emit (enforced by the `metrics-registry` lint rule); this script
keeps the README's human-facing catalog generated from it, so the docs
cannot drift from what /metrics actually exports:

    python scripts/gen_metrics_table.py            # print the table
    python scripts/gen_metrics_table.py --check    # exit 1 if README drifted
    python scripts/gen_metrics_table.py --write    # rewrite the README block

The table lives between the `<!-- metrics-table:begin -->` /
`<!-- metrics-table:end -->` markers in README.md;
tests/test_lint_clean.py runs the --check logic in tier-1.
"""

from __future__ import annotations

import argparse
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from distributed_lms_raft_llm_tpu.utils import metrics_registry  # noqa: E402

BEGIN = "<!-- metrics-table:begin -->"
END = "<!-- metrics-table:end -->"
README = REPO / "README.md"


def rendered_block() -> str:
    return f"{BEGIN}\n{metrics_registry.render_markdown_table()}\n{END}"


def current_block(text: str) -> str | None:
    start = text.find(BEGIN)
    end = text.find(END)
    if start == -1 or end == -1 or end < start:
        return None
    return text[start : end + len(END)]


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    mode = parser.add_mutually_exclusive_group()
    mode.add_argument("--check", action="store_true",
                      help="exit 1 when README's table differs from the "
                           "registry")
    mode.add_argument("--write", action="store_true",
                      help="rewrite README's table block in place")
    args = parser.parse_args(argv)

    block = rendered_block()
    if not (args.check or args.write):
        print(block)
        return 0

    text = README.read_text()
    existing = current_block(text)
    if existing is None:
        print(f"README.md has no {BEGIN} / {END} markers", file=sys.stderr)
        return 1
    if args.check:
        if existing != block:
            print("README metrics table is stale; run "
                  "`python scripts/gen_metrics_table.py --write`",
                  file=sys.stderr)
            return 1
        print("metrics table up to date "
              f"({len(metrics_registry.all_metrics())} series)")
        return 0
    if existing != block:
        README.write_text(text.replace(existing, block))
        print("README metrics table rewritten")
    else:
        print("README metrics table already up to date")
    return 0


if __name__ == "__main__":
    sys.exit(main())
