#!/usr/bin/env python
"""dlrl-lint CLI: run the repo-native static-analysis suite.

    python scripts/lint.py                 # whole tree (package+scripts+tests)
    python scripts/lint.py --json          # machine-readable findings
    python scripts/lint.py --sarif         # SARIF 2.1.0 (CI/editor annotations)
    python scripts/lint.py --rules guarded-by,deadline-flow engine/
    python scripts/lint.py --rules lock-order,atomicity-across-await
    python scripts/lint.py --changed       # only git-changed files (pre-commit)
    python scripts/lint.py --baseline lint-baseline.json   # fail on NEW only
    python scripts/lint.py --types         # + the mypy strict-subset gate
    python scripts/lint.py --list-rules    # the catalog

Exit status: 0 when clean, 1 when any (non-baselined) finding remains or
the type gate fails, 2 on usage errors. `tests/test_lint_clean.py` runs
the same `run_lint()` entry point in tier-1, so CI and this CLI can never
disagree about "clean".

## JSON schema (stable; additive changes only)

`--json` emits one document:

    {
      "schema": "dlrl-lint/1",
      "clean": bool,                  // no live findings (after baseline)
      "rules": [str, ...],            // rule names that ran
      "findings": [                   // live findings, sorted
        {"rule": str, "path": str, "line": int, "message": str}, ...
      ],
      "baselined": int,               // findings suppressed by --baseline
      "stale_baseline": [             // baseline entries nothing matched
        {"rule": str, "path": str, "message": str}, ...
      ]
    }

## Baselines (incremental adoption)

`--write-baseline f.json` records today's findings; `--baseline f.json`
then suppresses exactly those (matched on rule+path+message — line
numbers drift with unrelated edits) so a tree that predates a rule can
gate on NEW findings immediately and burn the baseline down over time.
Stale entries are reported so a shrinking baseline stays honest.
"""

from __future__ import annotations

import argparse
import json
import subprocess
import sys
from pathlib import Path
from typing import Dict, List, Tuple

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from distributed_lms_raft_llm_tpu.analysis import (  # noqa: E402
    all_rules,
    default_paths,
    run_lint,
)

# The mypy strict-subset gate (--types): these modules carry full
# annotations; pyproject.toml holds the per-module strictness flags.
TYPED_SUBSET = [
    "distributed_lms_raft_llm_tpu/raft/core.py",
    "distributed_lms_raft_llm_tpu/lms/state.py",
    "distributed_lms_raft_llm_tpu/utils/resilience.py",
    "distributed_lms_raft_llm_tpu/utils/guards.py",
    "distributed_lms_raft_llm_tpu/utils/metrics_registry.py",
    "distributed_lms_raft_llm_tpu/utils/locks.py",
    "distributed_lms_raft_llm_tpu/analysis",
]

_BaselineKey = Tuple[str, str, str]


def changed_paths() -> List[Path]:
    """Lintable files the checkout touched: `git status --porcelain`
    covers staged, unstaged, AND untracked in one listing (renames report
    the new name). Deleted files and non-Python artifacts are dropped."""
    proc = subprocess.run(
        # -uall: report untracked files individually — the default
        # collapses a new directory to one "dir/" entry and every .py
        # under it would silently skip the run.
        ["git", "status", "--porcelain", "--no-renames", "-uall"],
        cwd=str(REPO), capture_output=True, text=True, check=True,
    )
    out: List[Path] = []
    for line in proc.stdout.splitlines():
        status, rel = line[:2], line[3:]
        if status == "!!" or status.strip() == "D":
            continue
        if rel.startswith('"') and rel.endswith('"'):
            # git C-quotes names with spaces/non-ASCII (octal escapes);
            # undo it or the file silently drops out of the run.
            rel = (
                rel[1:-1].encode("ascii", "backslashreplace")
                .decode("unicode_escape").encode("latin-1").decode("utf-8")
            )
        path = REPO / rel
        if path.suffix != ".py" or not path.is_file():
            continue
        # Only files the full gate covers: a repo-root stray (bench.py)
        # would otherwise make --changed and the tier-1 clean run disagree
        # about what "clean" means.
        if any(path.resolve().is_relative_to(base.resolve())
               for base in default_paths(REPO)):
            out.append(path)
    return sorted(out)


def _baseline_key(f: Dict[str, object]) -> _BaselineKey:
    return (str(f["rule"]), str(f["path"]), str(f["message"]))


def _load_baseline(path: Path) -> List[_BaselineKey]:
    """Accepts a --write-baseline file or any --json output document."""
    doc = json.loads(path.read_text())
    entries = doc["findings"] if isinstance(doc, dict) else doc
    return [_baseline_key(e) for e in entries]


def to_sarif(findings, rules) -> Dict[str, object]:
    """Render the stable dlrl-lint/1 finding set as SARIF 2.1.0 — the
    interchange shape GitHub code scanning and editors consume, so lint
    findings surface as PR annotations instead of a CI log to scroll.
    Mapping: rule -> reportingDescriptor, finding -> result (level
    "error"; this linter has no warning tier), path/line ->
    physicalLocation with a repo-relative artifact URI."""
    by_name = {r.name: r for r in rules}
    return {
        "version": "2.1.0",
        "$schema": ("https://raw.githubusercontent.com/oasis-tcs/"
                    "sarif-spec/master/Schemata/sarif-schema-2.1.0.json"),
        "runs": [{
            "tool": {"driver": {
                "name": "dlrl-lint",
                "rules": [
                    {
                        "id": name,
                        "shortDescription": {
                            "text": by_name[name].description or name
                        },
                    }
                    for name in sorted(by_name)
                ],
            }},
            "results": [
                {
                    "ruleId": f.rule,
                    "level": "error",
                    "message": {"text": f.message},
                    "locations": [{
                        "physicalLocation": {
                            "artifactLocation": {
                                "uri": f.path,
                                "uriBaseId": "%SRCROOT%",
                            },
                            "region": {"startLine": f.line},
                        }
                    }],
                }
                for f in findings
            ],
        }],
    }


def run_type_gate() -> int:
    """The mypy strict-on-subset gate; returns an exit code.

    The container may not ship mypy (the runtime stack doesn't need it);
    in that case the gate reports itself skipped and passes — the lint
    rules still run everywhere, and CI images with mypy enforce types.
    """
    try:
        import mypy  # noqa: F401
    except ImportError:
        print("types: mypy not installed; skipping the type gate "
              "(pip install mypy to enable)", file=sys.stderr)
        return 0
    proc = subprocess.run(
        [sys.executable, "-m", "mypy", "--config-file", "pyproject.toml",
         *TYPED_SUBSET],
        cwd=str(REPO), capture_output=True, text=True,
    )
    out = (proc.stdout or "") + (proc.stderr or "")
    if proc.returncode != 0:
        sys.stderr.write(out)
        print("types: FAILED", file=sys.stderr)
        return 1
    print(f"types ok ({len(TYPED_SUBSET)} targets)")
    return 0


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(
        description=__doc__,
        formatter_class=argparse.RawDescriptionHelpFormatter,
    )
    parser.add_argument("paths", nargs="*",
                        help="files/directories to lint (default: the "
                             "package, scripts/ and tests/)")
    parser.add_argument("--changed", action="store_true",
                        help="lint only files git reports as changed "
                             "(staged, unstaged, or untracked) — the "
                             "pre-commit loop; project rules still analyze "
                             "the full tree but report only into changed "
                             "paths")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit the dlrl-lint/1 JSON document")
    parser.add_argument("--sarif", action="store_true", dest="as_sarif",
                        help="emit SARIF 2.1.0 (for CI upload / editor "
                             "annotations); exit status still reflects "
                             "findings")
    parser.add_argument("--rule", "--rules", action="append", default=None,
                        dest="rules", metavar="RULES",
                        help="run only these rules (comma-separated; "
                             "repeatable)")
    parser.add_argument("--baseline", type=Path, default=None,
                        help="JSON baseline of known findings to suppress; "
                             "only NEW findings fail the run")
    parser.add_argument("--write-baseline", type=Path, default=None,
                        metavar="PATH",
                        help="write the current findings as a baseline "
                             "file and exit 0")
    parser.add_argument("--types", action="store_true",
                        help="also run the mypy strict-subset gate "
                             "(skipped with a note when mypy is not "
                             "installed)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    args = parser.parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        for rule in sorted(rules, key=lambda r: r.name):
            print(f"{rule.name}: {rule.description}")
        return 0
    if args.rules:
        wanted = {
            name.strip()
            for chunk in args.rules
            for name in chunk.split(",")
            if name.strip()
        }
        known = {r.name for r in rules}
        unknown = wanted - known
        if unknown:
            print(f"unknown rule(s): {sorted(unknown)} "
                  f"(known: {sorted(known)})", file=sys.stderr)
            return 2
        rules = [r for r in rules if r.name in wanted]

    if args.as_json and args.as_sarif:
        print("--json and --sarif are mutually exclusive", file=sys.stderr)
        return 2
    paths = [Path(p) for p in args.paths] or None
    nothing_changed = False
    if args.changed:
        if paths is not None:
            print("--changed and explicit paths are mutually exclusive",
                  file=sys.stderr)
            return 2
        try:
            paths = changed_paths()
        except (OSError, subprocess.CalledProcessError) as e:
            print(f"--changed needs a git checkout: {e}", file=sys.stderr)
            return 2
        # An empty changed set is trivially clean — but fall through to
        # the normal output stage so --json still emits the dlrl-lint/1
        # document and --write-baseline still writes a (empty) baseline.
        nothing_changed = not paths
    findings = [] if nothing_changed else run_lint(
        paths=paths, rules=rules, root=REPO
    )

    if args.write_baseline is not None:
        args.write_baseline.write_text(json.dumps({
            "schema": "dlrl-lint/1",
            "findings": [f.to_json() for f in findings],
        }, indent=2) + "\n")
        print(f"wrote {len(findings)} finding(s) to {args.write_baseline}")
        return 0

    baselined = 0
    stale: List[_BaselineKey] = []
    if args.baseline is not None:
        try:
            known_keys = set(_load_baseline(args.baseline))
        except (OSError, ValueError, KeyError) as e:
            print(f"cannot read baseline {args.baseline}: {e}",
                  file=sys.stderr)
            return 2
        live = []
        matched = set()
        for f in findings:
            key = _baseline_key(f.to_json())
            if key in known_keys:
                baselined += 1
                matched.add(key)
            else:
                live.append(f)
        stale = sorted(known_keys - matched)
        findings = live

    if args.as_sarif:
        print(json.dumps(to_sarif(findings, rules), indent=2))
    elif args.as_json:
        print(json.dumps({
            "schema": "dlrl-lint/1",
            "clean": not findings,
            "rules": sorted(r.name for r in rules),
            "findings": [f.to_json() for f in findings],
            "baselined": baselined,
            "stale_baseline": [
                {"rule": r, "path": p, "message": m} for r, p, m in stale
            ],
        }, indent=2))
    else:
        for f in findings:
            print(f.format(), file=sys.stderr)
        if findings:
            print(f"\n{len(findings)} finding(s) across "
                  f"{len({f.path for f in findings})} file(s); suppress "
                  "intentional cases with `# lint: disable=<rule>` "
                  "(see README)", file=sys.stderr)
        else:
            note = f" ({baselined} baselined)" if baselined else ""
            print(f"lint ok ({len(rules)} rules){note}")
        if stale:
            print(f"note: {len(stale)} stale baseline entr"
                  f"{'y' if len(stale) == 1 else 'ies'} (fixed findings) — "
                  "regenerate with --write-baseline", file=sys.stderr)

    rc = 1 if findings else 0
    if args.types and run_type_gate() != 0:
        rc = 1
    return rc


if __name__ == "__main__":
    sys.exit(main())
