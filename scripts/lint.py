#!/usr/bin/env python
"""dlrl-lint CLI: run the repo-native static-analysis suite.

    python scripts/lint.py                 # whole tree (package+scripts+tests)
    python scripts/lint.py --json          # machine-readable findings
    python scripts/lint.py --rule guarded-by engine/  # one rule, one subtree
    python scripts/lint.py --list-rules    # the catalog

Exit status: 0 when clean, 1 when any unsuppressed finding remains, 2 on
usage errors. `tests/test_lint_clean.py` runs the same `run_lint()` entry
point in tier-1, so CI and this CLI can never disagree about "clean".
"""

from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from distributed_lms_raft_llm_tpu.analysis import (  # noqa: E402
    all_rules,
    run_lint,
)


def main(argv=None) -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("paths", nargs="*",
                        help="files/directories to lint (default: the "
                             "package, scripts/ and tests/)")
    parser.add_argument("--json", action="store_true", dest="as_json",
                        help="emit findings as a JSON document")
    parser.add_argument("--rule", action="append", default=None,
                        help="run only this rule (repeatable)")
    parser.add_argument("--list-rules", action="store_true",
                        help="print the rule catalog and exit")
    args = parser.parse_args(argv)

    rules = all_rules()
    if args.list_rules:
        for rule in sorted(rules, key=lambda r: r.name):
            print(f"{rule.name}: {rule.description}")
        return 0
    if args.rule:
        known = {r.name for r in rules}
        unknown = set(args.rule) - known
        if unknown:
            print(f"unknown rule(s): {sorted(unknown)} "
                  f"(known: {sorted(known)})", file=sys.stderr)
            return 2
        rules = [r for r in rules if r.name in set(args.rule)]

    paths = [Path(p) for p in args.paths] or None
    findings = run_lint(paths=paths, rules=rules, root=REPO)

    if args.as_json:
        print(json.dumps({
            "clean": not findings,
            "rules": sorted(r.name for r in rules),
            "findings": [f.to_json() for f in findings],
        }, indent=2))
    else:
        for f in findings:
            print(f.format(), file=sys.stderr)
        if findings:
            print(f"\n{len(findings)} finding(s) across "
                  f"{len({f.path for f in findings})} file(s); suppress "
                  "intentional cases with `# lint: disable=<rule>` "
                  "(see README)", file=sys.stderr)
        else:
            print(f"lint ok ({len(rules)} rules)")
    return 1 if findings else 0


if __name__ == "__main__":
    sys.exit(main())
