#!/usr/bin/env python
"""Tier-1 marker audit: long-running tests must be marked `slow`.

Tier-1 runs `pytest -m 'not slow'` under a hard timeout; one unmarked soak
blows the whole budget. This audit makes the convention mechanical instead
of tribal: any test function whose name advertises a long-running shape
(`soak`, `sustained`, `stress_many`) must carry `@pytest.mark.slow` —
either directly, on its class, or via a module-level `pytestmark`.

Run standalone (`python scripts/audit_markers.py`) for CI, or through
`tests/test_marker_audit.py` so the audit itself rides tier-1.
"""

from __future__ import annotations

import ast
import sys
from pathlib import Path
from typing import List

# Name fragments that mean "this test is a soak, not a unit test".
SLOW_NAME_HINTS = ("soak", "sustained", "stress_many")


def _is_slow_mark(node: ast.expr) -> bool:
    """True for `pytest.mark.slow` / `mark.slow` (bare or called)."""
    if isinstance(node, ast.Call):
        node = node.func
    return isinstance(node, ast.Attribute) and node.attr == "slow"


def _module_marked_slow(tree: ast.Module) -> bool:
    for stmt in tree.body:
        if isinstance(stmt, ast.Assign):
            targets = [t.id for t in stmt.targets if isinstance(t, ast.Name)]
            if "pytestmark" in targets:
                values = (
                    stmt.value.elts
                    if isinstance(stmt.value, (ast.List, ast.Tuple))
                    else [stmt.value]
                )
                if any(_is_slow_mark(v) for v in values):
                    return True
    return False


def audit(tests_dir: Path) -> List[str]:
    """Paths of soak-shaped tests missing the slow marker."""
    violations: List[str] = []
    for path in sorted(tests_dir.glob("test_*.py")):
        tree = ast.parse(path.read_text(), filename=str(path))
        module_slow = _module_marked_slow(tree)

        def visit(body, class_slow: bool) -> None:
            for node in body:
                if isinstance(node, ast.ClassDef):
                    cls_slow = class_slow or any(
                        _is_slow_mark(d) for d in node.decorator_list
                    )
                    visit(node.body, cls_slow)
                elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                    if not node.name.startswith("test_"):
                        continue
                    if not any(h in node.name for h in SLOW_NAME_HINTS):
                        continue
                    fn_slow = any(
                        _is_slow_mark(d) for d in node.decorator_list
                    )
                    if not (fn_slow or class_slow or module_slow):
                        violations.append(
                            f"{path.name}::{node.name} looks like a soak "
                            "(name hints: "
                            f"{[h for h in SLOW_NAME_HINTS if h in node.name]}) "
                            "but lacks @pytest.mark.slow"
                        )

        visit(tree.body, class_slow=False)
    return violations


def main() -> int:
    tests_dir = Path(__file__).resolve().parent.parent / "tests"
    violations = audit(tests_dir)
    for v in violations:
        print(f"MARKER AUDIT: {v}", file=sys.stderr)
    if violations:
        print(
            f"{len(violations)} unmarked soak test(s); add "
            "@pytest.mark.slow so tier-1 stays within its timeout",
            file=sys.stderr,
        )
        return 1
    print("marker audit ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
