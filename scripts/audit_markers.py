#!/usr/bin/env python
"""Tier-1 marker audit: long-running tests must be marked `slow`.

Thin shim kept so existing invocations (`python scripts/audit_markers.py`,
`tests/test_marker_audit.py`) keep working — the check itself now lives in
the lint framework as the `slow-marker` rule
(`distributed_lms_raft_llm_tpu/analysis/rules/slow_marker.py`) and also
runs as part of `python scripts/lint.py`.
"""

from __future__ import annotations

import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))

from distributed_lms_raft_llm_tpu.analysis.rules.slow_marker import (  # noqa: E402,F401
    SLOW_NAME_HINTS,
    audit,
)


def main() -> int:
    violations = audit(REPO / "tests")
    for v in violations:
        print(f"MARKER AUDIT: {v}", file=sys.stderr)
    if violations:
        print(
            f"{len(violations)} unmarked soak test(s); add "
            "@pytest.mark.slow so tier-1 stays within its timeout",
            file=sys.stderr,
        )
        return 1
    print("marker audit ok")
    return 0


if __name__ == "__main__":
    sys.exit(main())
