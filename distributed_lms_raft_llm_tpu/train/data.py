"""Course-text data pipeline for fine-tuning the tutoring model.

The training story the LMS implies (SURVEY.md §2.2: no training in the
reference, models frozen from the hub): fine-tune GPT-2 on the course's own
materials so the tutor answers in-domain. Sources are plain-text or PDF
files — the same PDFs instructors upload through `LMS.Post`
(utils/pdf.py extracts their text, the identical path the BERT gate uses,
reference analogue lms_server.py:918).

Pipeline shape (TPU-first): tokenize once, concatenate with EOS joints,
and PACK into fixed [B, T] blocks — static shapes, no padding waste, every
token supervised (loss_mask all-ones except the leading position of each
block which has no preceding context beyond the pack boundary; packing
keeps it simple and dense, the standard LM recipe). Shuffling is
deterministic per epoch via a seeded permutation of block starts.
"""

from __future__ import annotations

import dataclasses
import os
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from ..utils import pdf as pdf_lib


@dataclasses.dataclass
class DataConfig:
    batch_size: int = 8
    seq_len: int = 128
    seed: int = 0


def load_corpus_texts(paths: Sequence[str]) -> List[str]:
    """Read .txt/.md as UTF-8 and .pdf via the stdlib extractor; directories
    are walked recursively in sorted order (deterministic)."""
    texts: List[str] = []
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            for root, _dirs, names in os.walk(p):
                files.extend(os.path.join(root, n) for n in sorted(names))
        else:
            files.append(p)
    for f in sorted(files):
        ext = os.path.splitext(f)[1].lower()
        if ext == ".pdf":
            with open(f, "rb") as fh:
                text = pdf_lib.extract_text(fh.read())
        elif ext in (".txt", ".md", ""):
            with open(f, "r", encoding="utf-8", errors="replace") as fh:
                text = fh.read()
        else:
            continue
        if text.strip():
            texts.append(text)
    return texts


def pack_tokens(
    texts: Sequence[str], tokenizer, seq_len: int
) -> np.ndarray:
    """Tokenize + concatenate (EOS between documents) + reshape into
    [num_blocks, seq_len]; the ragged tail is dropped (static shapes)."""
    stream: List[int] = []
    for text in texts:
        stream.extend(tokenizer.encode(text))
        stream.append(tokenizer.eos_id)
    n_blocks = len(stream) // seq_len
    if n_blocks == 0:
        raise ValueError(
            f"corpus too small: {len(stream)} tokens < seq_len {seq_len}"
        )
    return np.asarray(
        stream[: n_blocks * seq_len], np.int32
    ).reshape(n_blocks, seq_len)


class PackedDataset:
    """Deterministically shuffled epochs of packed [B, T] batches."""

    def __init__(self, blocks: np.ndarray, cfg: DataConfig):
        if len(blocks) < cfg.batch_size:
            raise ValueError(
                f"{len(blocks)} blocks < batch_size {cfg.batch_size}; "
                f"lower batch_size/seq_len or add course material"
            )
        self.blocks = blocks
        self.cfg = cfg

    @classmethod
    def from_paths(
        cls, paths: Sequence[str], tokenizer, cfg: DataConfig
    ) -> "PackedDataset":
        texts = load_corpus_texts(paths)
        if not texts:
            raise ValueError(f"no usable .txt/.md/.pdf files under {paths}")
        return cls(pack_tokens(texts, tokenizer, cfg.seq_len), cfg)

    def batches(self, epoch: int) -> Iterator[Dict[str, np.ndarray]]:
        """One epoch of {input_ids, loss_mask} batches, seeded by epoch."""
        order = np.random.default_rng(
            self.cfg.seed + epoch
        ).permutation(len(self.blocks))
        b = self.cfg.batch_size
        for start in range(0, len(order) - b + 1, b):
            ids = self.blocks[order[start : start + b]]
            yield {
                "input_ids": ids,
                "loss_mask": np.ones_like(ids, bool),
            }

    def steps_per_epoch(self) -> int:
        return len(self.blocks) // self.cfg.batch_size
