"""Training path: LM loss, sharded optimizer step, train state."""

from .train import (  # noqa: F401
    TrainConfig,
    init_train_state,
    lm_loss,
    make_optimizer,
    make_sharded_train_step,
    make_train_step,
    train_state_shardings,
)
