"""Sharded training step: LM loss + optax optimizer under pjit.

The reference has no training at all (SURVEY.md §2.2) — models come frozen
from the HF hub. A TPU-native framework needs the training path anyway
(fine-tuning the tutoring model on course data is the obvious extension),
and the multi-chip dry-run validates it: parameters/optimizer state shard
per `parallel.partition` rules (tp), the batch shards over dp, gradients
reduce across dp implicitly via jit's sharding propagation, and activations
can be rematerialized (`jax.checkpoint`) to trade FLOPs for HBM.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import gpt2, moe
from ..parallel import partition


@dataclasses.dataclass
class TrainConfig:
    learning_rate: float = 3e-4
    weight_decay: float = 0.01
    warmup_steps: int = 100
    decay_steps: int = 10_000  # cosine horizon; set to the planned run length
    max_grad_norm: float = 1.0
    remat: bool = True  # rematerialize block activations (HBM for FLOPs)
    # GPipe microbatches per step when the mesh has a pp axis > 1 (the
    # stacked trunk pipelines via parallel.pipeline.pipeline_trunk; bubble
    # fraction (pp-1)/(pp_micro+pp-1)).
    pp_micro: int = 2
    # MoE: weight of the Switch load-balance aux loss (models/moe.py,
    # applies only to GPT2MoEConfig models — keeps the router from
    # collapsing onto a few experts).
    moe_aux_weight: float = 0.01


def _is_moe(model_cfg) -> bool:
    return isinstance(model_cfg, moe.GPT2MoEConfig)


def _init_params_for(model_cfg):
    return moe.init_params if _is_moe(model_cfg) else gpt2.init_params


def make_optimizer(cfg: TrainConfig) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=cfg.learning_rate,
        warmup_steps=cfg.warmup_steps,
        decay_steps=cfg.decay_steps,
    )
    return optax.chain(
        optax.clip_by_global_norm(cfg.max_grad_norm),
        optax.adamw(schedule, weight_decay=cfg.weight_decay),
    )


def lm_loss(
    logits: jax.Array, targets: jax.Array, mask: jax.Array
) -> jax.Array:
    """Token-mean cross entropy; logits [B,T,V] f32, targets/mask [B,T]."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    picked = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    mask = mask.astype(jnp.float32)
    return -jnp.sum(picked * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def init_train_state(
    rng: jax.Array, model_cfg: gpt2.GPT2Config, optimizer
) -> Dict[str, Any]:
    params = _init_params_for(model_cfg)(rng, model_cfg)
    return {
        "params": params,
        "opt_state": optimizer.init(params),
        "step": jnp.zeros((), jnp.int32),
    }


def train_state_shardings(state, mesh: Mesh):
    """NamedShardings for the whole train state: params + optimizer moments
    follow the model partition rules (adam mu/nu mirror param shapes);
    scalars replicate. A pp axis > 1 additionally shards every stacked
    block leaf's leading layer axis over pp — each pipeline stage stores
    only its own L/pp layers (and their optimizer moments). MoE states are
    recognized by their param structure and use the gpt2_moe rules
    (experts over ep)."""

    is_moe_state = "moe" in state["params"].get("blocks", {})
    param_specs = partition.match_partition_rules(
        partition.RULES_FOR["gpt2_moe"] if is_moe_state
        else partition.GPT2_RULES,
        state["params"],
    )
    if mesh.shape.get("pp", 1) > 1:
        param_specs["blocks"] = jax.tree.map(
            lambda s: P("pp", *tuple(s)[1:]),
            param_specs["blocks"],
            is_leaf=lambda s: isinstance(s, P),
        )

    # Optimizer leaves that mirror a parameter (same shape) reuse its spec;
    # everything else (counts, scalars) replicates.
    flat_params, _ = jax.tree_util.tree_flatten(state["params"])
    flat_specs, _ = jax.tree_util.tree_flatten(
        param_specs, is_leaf=lambda x: isinstance(x, P)
    )
    shape_to_spec = {}
    for leaf, spec in zip(flat_params, flat_specs):
        shape_to_spec.setdefault(leaf.shape, spec)

    def leaf_spec(leaf):
        if getattr(leaf, "ndim", 0) == 0:
            return P()
        return shape_to_spec.get(leaf.shape, P())

    specs = {
        "params": param_specs,
        "opt_state": jax.tree.map(leaf_spec, state["opt_state"]),
        "step": P(),
    }
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def make_train_step(
    model_cfg: gpt2.GPT2Config,
    optimizer,
    remat: bool = True,
    mesh: Optional[Mesh] = None,
    pp_micro: int = 2,
    moe_aux_weight: float = 0.01,
) -> Callable:
    """Returns train_step(state, batch) -> (state, metrics); jit it with the
    shardings from `train_state_shardings` + batch over dp.

    Parallel axes beyond dp/tp activate from the mesh shape:
    - sp > 1: the model's full-sequence attention runs as ring attention
      (gpt2.GPT2Config.ring_mesh), the batch's sequence dim sharded over sp;
    - pp > 1: the stacked trunk runs as a GPipe pipeline
      (gpt2.forward_pipelined) with `pp_micro` microbatches, layer weights
      stage-sharded per `train_state_shardings`.
    """
    is_moe = _is_moe(model_cfg)
    if mesh is not None and mesh.shape.get("sp", 1) > 1:
        # Composes with MoE too: forward_with_aux IS gpt2.forward, whose
        # ring path carries the aux channel (parity-tested in test_moe).
        model_cfg = dataclasses.replace(model_cfg, ring_mesh=mesh)
    pipelined = mesh is not None and mesh.shape.get("pp", 1) > 1
    if pipelined and is_moe:
        raise ValueError(
            "pp and MoE cannot combine yet: the pipeline stage body has "
            "no aux-loss channel; use ep x tp x dp"
        )

    if pipelined:
        # Combinations the pipeline schedule does not implement yet — fail
        # loudly rather than silently degrade:
        # - sp: trunk_layer uses dense full-sequence attention, so ring
        #   attention (the whole point of --sp) would be dropped;
        # - tp: the shard_map stage body has no tp collectives, so sharded
        #   weight in_specs would compute wrong partials (and replicated
        #   ones would all-gather tp-sharded weights every step).
        if mesh.shape.get("sp", 1) > 1:
            raise ValueError(
                "pp and sp cannot combine: the pipeline stage body uses "
                "dense attention (ring attention unreachable under pp)"
            )
        if mesh.shape.get("tp", 1) > 1:
            raise ValueError(
                "pp and tp cannot combine: the pipeline stage body has no "
                "tensor-parallel collectives; use pp x dp"
            )

        def forward(params, _cfg, input_ids):
            logits = gpt2.forward_pipelined(
                params, model_cfg, input_ids, mesh, n_micro=pp_micro,
                batch_spec=P(None, "dp"), remat=remat,
            )
            return logits, None
    else:
        forward = moe.forward_with_aux if is_moe else gpt2.forward
        if remat:
            forward = jax.checkpoint(partial(forward), static_argnums=(1,))

    def loss_fn(params, input_ids, loss_mask):
        if is_moe:
            logits, aux = forward(params, model_cfg, input_ids)
        else:
            logits, _ = forward(params, model_cfg, input_ids)
            aux = 0.0
        # next-token prediction: shift by one
        loss = lm_loss(logits[:, :-1], input_ids[:, 1:], loss_mask[:, 1:])
        return loss + moe_aux_weight * aux, aux

    def train_step(state, batch):
        (loss, aux), grads = jax.value_and_grad(loss_fn, has_aux=True)(
            state["params"], batch["input_ids"], batch["loss_mask"]
        )
        updates, opt_state = optimizer.update(
            grads, state["opt_state"], state["params"]
        )
        params = optax.apply_updates(state["params"], updates)
        new_state = {
            "params": params,
            "opt_state": opt_state,
            "step": state["step"] + 1,
        }
        gnorm = optax.global_norm(grads)
        metrics = {"loss": loss, "grad_norm": gnorm}
        if is_moe:
            metrics["moe_balance"] = aux
        return new_state, metrics

    return train_step


def make_sharded_train_step(
    mesh: Mesh, model_cfg: gpt2.GPT2Config, train_cfg: TrainConfig, rng
):
    """Everything wired: returns (jitted_step, sharded_state, batch_sharding).

    The batch shards over dp; XLA derives the gradient all-reduce over dp
    and the tensor-parallel collectives over tp from the argument shardings
    alone — no hand-written collectives (SURVEY.md §2.2 TPU-native plan).
    """
    optimizer = make_optimizer(train_cfg)
    with jax.default_device(jax.devices()[0]):
        state = init_train_state(rng, model_cfg, optimizer)
    state_shardings = train_state_shardings(state, mesh)
    state = jax.tree.map(
        lambda x, s: jax.device_put(x, s), state, state_shardings
    )
    # sp > 1: the sequence dim shards too (ring attention consumes it).
    seq_axis = "sp" if mesh.shape.get("sp", 1) > 1 else None
    batch_sharding = {
        "input_ids": NamedSharding(mesh, P("dp", seq_axis)),
        "loss_mask": NamedSharding(mesh, P("dp", seq_axis)),
    }
    step = jax.jit(
        make_train_step(model_cfg, optimizer, remat=train_cfg.remat,
                        mesh=mesh, pp_micro=train_cfg.pp_micro,
                        moe_aux_weight=train_cfg.moe_aux_weight),
        in_shardings=(state_shardings, batch_sharding),
        out_shardings=(state_shardings, None),
        donate_argnums=(0,),
    )
    return step, state, batch_sharding


# ------------------------------------------------------------------ driver


def fit(
    mesh: Mesh,
    model_cfg: gpt2.GPT2Config,
    train_cfg: TrainConfig,
    dataset,                      # train.data.PackedDataset
    *,
    epochs: int = 1,
    checkpoint_path: Optional[str] = None,
    checkpoint_every: int = 50,
    seed: int = 0,
    log_every: int = 10,
) -> Dict[str, Any]:
    """Fine-tune on course data with periodic checkpointing and resume.

    If `checkpoint_path` exists, training RESUMES from it: the full state
    (params, optimizer moments, step) restores through the run's shardings
    and the data order continues from the recorded step, so an interrupted
    run and an uninterrupted one walk the same step sequence.
    Returns the final (host-fetched) metrics + state handle.
    """
    import logging

    from . import checkpoint as ckpt_lib

    log = logging.getLogger("train")
    step_fn, state, batch_sharding = make_sharded_train_step(
        mesh, model_cfg, train_cfg, jax.random.key(seed)
    )
    if checkpoint_path and ckpt_lib.latest_step(checkpoint_path) is not None:
        template = jax.tree.map(np.asarray, jax.device_get(state))
        state = ckpt_lib.restore_train_state(
            checkpoint_path, template,
            shardings=train_state_shardings(template, mesh),
        )
        log.info("resumed from %s at step %d", checkpoint_path,
                 int(jax.device_get(state["step"])))

    start_step = int(jax.device_get(state["step"]))
    steps_per_epoch = dataset.steps_per_epoch()
    metrics_host: Dict[str, float] = {}
    step_no = start_step
    for epoch in range(epochs):
        for i, batch in enumerate(dataset.batches(epoch)):
            # Resume: skip batches the restored run already consumed.
            if epoch * steps_per_epoch + i < start_step:
                continue
            batch = {
                k: jax.device_put(v, batch_sharding[k])
                for k, v in batch.items()
            }
            state, metrics = step_fn(state, batch)
            step_no += 1
            if step_no % log_every == 0 or step_no == start_step + 1:
                metrics_host = {
                    k: float(jax.device_get(v)) for k, v in metrics.items()
                }
                log.info("step %d loss %.4f gnorm %.3f", step_no,
                         metrics_host["loss"], metrics_host["grad_norm"])
            if checkpoint_path and step_no % checkpoint_every == 0:
                ckpt_lib.save_train_state(checkpoint_path, state)
    if checkpoint_path:
        ckpt_lib.save_train_state(checkpoint_path, state)
    if not metrics_host:
        metrics_host = {"loss": float("nan"), "grad_norm": float("nan")}
    return {"state": state, "metrics": metrics_host, "step": step_no}


def main(argv=None) -> None:
    """CLI: fine-tune the tutoring model on course materials.

    python -m distributed_lms_raft_llm_tpu.train.train \
        --data lms_data/node1/uploads --vocab data/gpt2-local/vocab.json \
        --merges data/gpt2-local/merges.txt --model tiny \
        --checkpoint ckpt/train_state.safetensors --epochs 2
    """
    import argparse
    import logging

    from ..models import registry
    from ..parallel import mesh as mesh_lib
    from ..utils import tokenizer as tok_lib
    from . import checkpoint as ckpt_lib
    from .data import DataConfig, PackedDataset

    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--data", nargs="+", required=True,
                        help="course-text files/dirs (.txt/.md/.pdf)")
    parser.add_argument("--model", default="gpt2")
    parser.add_argument("--vocab", default=None)
    parser.add_argument("--merges", default=None)
    parser.add_argument("--checkpoint", default=None,
                        help="train-state .safetensors (resume if present)")
    parser.add_argument("--export", default=None,
                        help="write fine-tuned params here when done")
    parser.add_argument("--epochs", type=int, default=1)
    parser.add_argument("--batch-size", type=int, default=8)
    parser.add_argument("--seq-len", type=int, default=128)
    parser.add_argument("--lr", type=float, default=3e-4)
    parser.add_argument("--tp", type=int, default=1)
    parser.add_argument("--sp", type=int, default=1,
                        help="sequence-parallel ways: full-sequence "
                        "attention runs as ring attention over sp shards "
                        "(long-context training)")
    parser.add_argument("--pp", type=int, default=1,
                        help="pipeline stages: the stacked trunk shards "
                        "L/pp layers per device (GPipe microbatching)")
    parser.add_argument("--pp-micro", type=int, default=2,
                        help="microbatches per step when --pp > 1")
    parser.add_argument("--ep", type=int, default=1,
                        help="expert-parallel ways (MoE presets: expert "
                        "stacks shard over ep; aux load-balance loss is "
                        "applied automatically)")
    parser.add_argument("--checkpoint-every", type=int, default=50)
    args = parser.parse_args(argv)
    logging.basicConfig(level=logging.INFO)

    _, model_cfg = registry.resolve(args.model, jnp.bfloat16, jnp.float32)
    if args.ep > 1 and not _is_moe(model_cfg):
        # Before the (potentially minutes-long) corpus tokenization.
        parser.error(
            f"--ep {args.ep} requires an MoE model preset; {args.model!r} "
            f"has no expert axis — the ep chips would silently replicate"
        )
    tokenizer = tok_lib.load_gpt2_tokenizer(args.vocab, args.merges, None)
    dataset = PackedDataset.from_paths(
        args.data, tokenizer,
        DataConfig(batch_size=args.batch_size, seq_len=args.seq_len),
    )
    mesh = mesh_lib.make_mesh(
        {"pp": args.pp, "ep": args.ep, "sp": args.sp, "tp": args.tp,
         "dp": -1}
    )
    steps = args.epochs * dataset.steps_per_epoch()
    train_cfg = TrainConfig(
        learning_rate=args.lr,
        warmup_steps=max(1, steps // 20),
        decay_steps=max(2, steps),
        pp_micro=args.pp_micro,
    )
    result = fit(
        mesh, model_cfg, train_cfg, dataset, epochs=args.epochs,
        checkpoint_path=args.checkpoint,
        checkpoint_every=args.checkpoint_every,
    )
    if args.export:
        ckpt_lib.export_model(args.export, result["state"])
    print(f"trained to step {result['step']}: {result['metrics']}")


if __name__ == "__main__":
    main()
