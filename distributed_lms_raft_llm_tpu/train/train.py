"""Sharded training step: LM loss + optax optimizer under pjit.

The reference has no training at all (SURVEY.md §2.2) — models come frozen
from the HF hub. A TPU-native framework needs the training path anyway
(fine-tuning the tutoring model on course data is the obvious extension),
and the multi-chip dry-run validates it: parameters/optimizer state shard
per `parallel.partition` rules (tp), the batch shards over dp, gradients
reduce across dp implicitly via jit's sharding propagation, and activations
can be rematerialized (`jax.checkpoint`) to trade FLOPs for HBM.
"""

from __future__ import annotations

import dataclasses
from functools import partial
from typing import Any, Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from ..models import gpt2
from ..parallel import partition


@dataclasses.dataclass
class TrainConfig:
    learning_rate: float = 3e-4
    weight_decay: float = 0.01
    warmup_steps: int = 100
    max_grad_norm: float = 1.0
    remat: bool = True  # rematerialize block activations (HBM for FLOPs)


def make_optimizer(cfg: TrainConfig) -> optax.GradientTransformation:
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=cfg.learning_rate,
        warmup_steps=cfg.warmup_steps,
        decay_steps=10_000,
    )
    return optax.chain(
        optax.clip_by_global_norm(cfg.max_grad_norm),
        optax.adamw(schedule, weight_decay=cfg.weight_decay),
    )


def lm_loss(
    logits: jax.Array, targets: jax.Array, mask: jax.Array
) -> jax.Array:
    """Token-mean cross entropy; logits [B,T,V] f32, targets/mask [B,T]."""
    logp = jax.nn.log_softmax(logits, axis=-1)
    picked = jnp.take_along_axis(logp, targets[..., None], axis=-1)[..., 0]
    mask = mask.astype(jnp.float32)
    return -jnp.sum(picked * mask) / jnp.maximum(jnp.sum(mask), 1.0)


def init_train_state(
    rng: jax.Array, model_cfg: gpt2.GPT2Config, optimizer
) -> Dict[str, Any]:
    params = gpt2.init_params(rng, model_cfg)
    return {
        "params": params,
        "opt_state": optimizer.init(params),
        "step": jnp.zeros((), jnp.int32),
    }


def train_state_shardings(state, mesh: Mesh):
    """NamedShardings for the whole train state: params + optimizer moments
    follow the model partition rules (adam mu/nu mirror param shapes);
    scalars replicate."""

    param_specs = partition.match_partition_rules(
        partition.GPT2_RULES, state["params"]
    )

    # Optimizer leaves that mirror a parameter (same shape) reuse its spec;
    # everything else (counts, scalars) replicates.
    flat_params, _ = jax.tree_util.tree_flatten(state["params"])
    flat_specs, _ = jax.tree_util.tree_flatten(
        param_specs, is_leaf=lambda x: isinstance(x, P)
    )
    shape_to_spec = {}
    for leaf, spec in zip(flat_params, flat_specs):
        shape_to_spec.setdefault(leaf.shape, spec)

    def leaf_spec(leaf):
        if getattr(leaf, "ndim", 0) == 0:
            return P()
        return shape_to_spec.get(leaf.shape, P())

    specs = {
        "params": param_specs,
        "opt_state": jax.tree.map(leaf_spec, state["opt_state"]),
        "step": P(),
    }
    return jax.tree.map(
        lambda s: NamedSharding(mesh, s),
        specs,
        is_leaf=lambda x: isinstance(x, P),
    )


def make_train_step(
    model_cfg: gpt2.GPT2Config,
    optimizer,
    remat: bool = True,
) -> Callable:
    """Returns train_step(state, batch) -> (state, metrics); jit it with the
    shardings from `train_state_shardings` + batch over dp."""

    forward = gpt2.forward
    if remat:
        forward = jax.checkpoint(
            partial(gpt2.forward), static_argnums=(1,)
        )

    def loss_fn(params, input_ids, loss_mask):
        logits, _ = forward(params, model_cfg, input_ids)
        # next-token prediction: shift by one
        loss = lm_loss(logits[:, :-1], input_ids[:, 1:], loss_mask[:, 1:])
        return loss

    def train_step(state, batch):
        loss, grads = jax.value_and_grad(loss_fn)(
            state["params"], batch["input_ids"], batch["loss_mask"]
        )
        updates, opt_state = optimizer.update(
            grads, state["opt_state"], state["params"]
        )
        params = optax.apply_updates(state["params"], updates)
        new_state = {
            "params": params,
            "opt_state": opt_state,
            "step": state["step"] + 1,
        }
        gnorm = optax.global_norm(grads)
        return new_state, {"loss": loss, "grad_norm": gnorm}

    return train_step


def make_sharded_train_step(
    mesh: Mesh, model_cfg: gpt2.GPT2Config, train_cfg: TrainConfig, rng
):
    """Everything wired: returns (jitted_step, sharded_state, batch_sharding).

    The batch shards over dp; XLA derives the gradient all-reduce over dp
    and the tensor-parallel collectives over tp from the argument shardings
    alone — no hand-written collectives (SURVEY.md §2.2 TPU-native plan).
    """
    optimizer = make_optimizer(train_cfg)
    with jax.default_device(jax.devices()[0]):
        state = init_train_state(rng, model_cfg, optimizer)
    state_shardings = train_state_shardings(state, mesh)
    state = jax.tree.map(
        lambda x, s: jax.device_put(x, s), state, state_shardings
    )
    batch_sharding = {
        "input_ids": NamedSharding(mesh, P("dp", None)),
        "loss_mask": NamedSharding(mesh, P("dp", None)),
    }
    step = jax.jit(
        make_train_step(model_cfg, optimizer, remat=train_cfg.remat),
        in_shardings=(state_shardings, batch_sharding),
        out_shardings=(state_shardings, None),
        donate_argnums=(0,),
    )
    return step, state, batch_sharding
