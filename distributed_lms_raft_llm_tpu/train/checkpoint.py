"""Training checkpoint save/restore (safetensors + sidecar metadata).

Round-trips the FULL train state — params, optimizer moments, step — via
the same safetensors writer the serving path uses (models/convert.py), so
a fine-tuned model is immediately servable: `export_model()` writes the
params alone in HF layout for `TutoringEngine(checkpoint=...)`.

Layout: one `.safetensors` holding every state leaf under its tree path
(`params/blocks/attn/wqkv`, `opt_state/1/0/mu/...`), plus `<path>.json`
with the step and leaf manifest. Restore maps leaves back into a freshly
built state template (shapes validated), then device_puts through the
caller's shardings — works for both single-chip and pjit-sharded resumes.
"""

from __future__ import annotations

import json
import os
from typing import Any, Dict, Optional

import jax
import numpy as np

from ..models import convert


def _flatten(state: Any) -> Dict[str, np.ndarray]:
    flat, _ = jax.tree_util.tree_flatten_with_path(state)
    out = {}
    for keypath, leaf in flat:
        key = "/".join(_key_str(k) for k in keypath)
        out[key] = np.asarray(jax.device_get(leaf))
    return out


def _key_str(k) -> str:
    if hasattr(k, "key"):
        return str(k.key)
    if hasattr(k, "idx"):
        return str(k.idx)
    if hasattr(k, "name"):
        return str(k.name)
    return str(k)


def save_train_state(path: str, state: Any) -> None:
    """Write the whole train state to `path` (.safetensors) + `path`.json."""
    flat = _flatten(state)
    convert.save_safetensors(path, flat)
    meta = {
        "step": int(np.asarray(jax.device_get(state["step"]))),
        "leaves": sorted(flat),
    }
    tmp = path + ".json.tmp"
    with open(tmp, "w") as fh:
        json.dump(meta, fh)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path + ".json")


def restore_train_state(
    path: str, template: Any, shardings: Optional[Any] = None
) -> Any:
    """Load a checkpoint back into `template`'s structure.

    `template` is a freshly-built train state (init_train_state) providing
    the pytree structure and expected shapes; `shardings` (optional, same
    structure) device_puts each restored leaf — pass the pjit shardings to
    resume a sharded run.
    """
    tensors = convert.load_safetensors(path)
    flat, treedef = jax.tree_util.tree_flatten_with_path(template)
    leaves = []
    for keypath, leaf in flat:
        key = "/".join(_key_str(k) for k in keypath)
        if key not in tensors:
            raise ValueError(f"checkpoint {path} missing leaf {key!r}")
        value = tensors[key]
        if tuple(value.shape) != tuple(np.shape(leaf)):
            raise ValueError(
                f"checkpoint leaf {key!r} has shape {value.shape}, "
                f"expected {np.shape(leaf)}"
            )
        leaves.append(value.astype(np.asarray(leaf).dtype))
    state = jax.tree_util.tree_unflatten(treedef, leaves)
    if shardings is not None:
        state = jax.tree.map(
            lambda x, s: jax.device_put(x, s), state, shardings
        )
    return state


def export_model(path: str, state: Any) -> None:
    """Write just the fine-tuned parameters in HF GPT-2 layout (the inverse
    of the import mapping), so `TutoringEngine(checkpoint=path)` serves the
    fine-tuned model through the standard checkpoint path. MoE params have
    no HF counterpart layout; they export in the native tree layout
    (slash-joined paths), which `models.moe.params_from_hf` reads back."""
    params = jax.device_get(state["params"])
    if "moe" in params.get("blocks", {}):
        convert.save_safetensors(path, _flatten(params))
        return
    convert.save_safetensors(path, convert.gpt2_params_to_hf(params))


def latest_step(path: str) -> Optional[int]:
    """Step recorded in `path`'s sidecar, or None if no checkpoint."""
    if not os.path.exists(path + ".json"):
        return None
    with open(path + ".json") as fh:
        return int(json.load(fh)["step"])
