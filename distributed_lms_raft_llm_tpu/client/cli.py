"""Interactive LMS terminal client.

Covers every screen of the reference Tkinter GUI (reference:
GUI_RAFT_LLM_SourceCode/lms_gui_final.py — register/login, student menu:
view/download materials, upload assignment, view grades, ask query [llm |
instructor], view instructor responses; instructor menu: post material,
view & grade assignments, respond to queries) as a REPL suited to headless
deployments; `client.gui` offers the Tkinter face where displays exist.

Run: python -m distributed_lms_raft_llm_tpu.client.cli \
        --servers 127.0.0.1:50051,127.0.0.1:50052,...
"""

from __future__ import annotations

import argparse
import getpass
import os
import sys

from ..utils import pdf
from .client import LMSClient, NoLeader


def _print_menu(role: str) -> None:
    if role == "student":
        print(
            "\n[student] 1) view course materials  2) download material\n"
            "          3) upload assignment       4) view my grade\n"
            "          5) ask LLM tutor           6) ask instructor\n"
            "          7) view instructor responses  q) logout"
        )
    else:
        print(
            "\n[instructor] 1) post course material  2) view student assignments\n"
            "             3) grade a student        4) view unanswered queries\n"
            "             5) respond to a query     q) logout"
        )


def _read_file(prompt: str) -> tuple:
    path = input(prompt).strip()
    if path and os.path.exists(path):
        with open(path, "rb") as f:
            return os.path.basename(path), f.read()
    # No file? Offer to synthesize a PDF from typed text (demo-friendly).
    text = input("File not found. Enter text to wrap as a PDF instead: ")
    name = input("Filename to upload as [notes.pdf]: ").strip() or "notes.pdf"
    return name, pdf.make_pdf(text)


def student_loop(client: LMSClient) -> None:
    while True:
        _print_menu("student")
        choice = input("> ").strip().lower()
        if choice == "1":
            for e in client.course_materials():
                print(f"  {e.filename} (by {e.instructor}, {len(e.file)} bytes)")
        elif choice == "2":
            entries = client.course_materials()
            for i, e in enumerate(entries):
                print(f"  [{i}] {e.filename}")
            idx = input("which #? ").strip()
            if idx.isdigit() and int(idx) < len(entries):
                e = entries[int(idx)]  # the picked one, not entries[0] (D8)
                # basename: never let a server-supplied name escape the cwd
                name = os.path.basename(e.filename) or "material.pdf"
                with open(name, "wb") as f:
                    f.write(e.file)
                print(f"saved ./{name}")
        elif choice == "3":
            name, content = _read_file("path to assignment PDF: ")
            print("uploaded" if client.upload_assignment(name, content) else "failed")
        elif choice == "4":
            print(" ", client.my_grade())
        elif choice == "5":
            resp = client.ask_llm(input("your question: "))
            print(f"  [{'ok' if resp.success else 'error'}] {resp.response}")
        elif choice == "6":
            print("sent" if client.ask_instructor(input("your question: "))
                  else "failed")
        elif choice == "7":
            for e in client.instructor_responses():
                print(" ", e.data.replace("\n", "\n  "))
        elif choice == "q":
            client.logout()
            return


def instructor_loop(client: LMSClient) -> None:
    while True:
        _print_menu("instructor")
        choice = input("> ").strip().lower()
        if choice == "1":
            name, content = _read_file("path to material PDF: ")
            print("posted" if client.upload_course_material(name, content)
                  else "failed")
        elif choice == "2":
            for e in client.student_assignments():
                print(f"  {e.id}: {e.filename} ({len(e.file)} bytes)")
        elif choice == "3":
            resp = client.grade(input("student: ").strip(),
                                input("grade: ").strip())
            print(f"  [{'ok' if resp.success else 'error'}] {resp.message}")
        elif choice == "4":
            for e in client.unanswered_queries():
                print(f"  {e.id}: {e.data}")
        elif choice == "5":
            ok = client.respond_to_query(
                input("student: ").strip(), input("response: ")
            )
            print("responded" if ok else "failed")
        elif choice == "q":
            client.logout()
            return


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--servers",
        default="127.0.0.1:50051,127.0.0.1:50052,127.0.0.1:50053,"
                "127.0.0.1:50055,127.0.0.1:50056",
        help="comma-separated LMS server addresses",
    )
    parser.add_argument("--config", default=None,
                        help="TOML deployment file; [cluster.nodes] supplies "
                             "the server list")
    args = parser.parse_args(argv)
    servers = args.servers.split(",")
    client_opts = {}
    # Explicit --servers beats the file (same precedence as the servers).
    if args.config:
        from ..config import client_kwargs, load_config

        cfg = load_config(args.config)
        if args.servers == parser.get_default("servers"):
            servers = cfg.client_servers
        client_opts = client_kwargs(cfg)
    client = LMSClient(servers, **client_opts)

    try:
        leader = client.discover_leader()
        print(f"connected; current leader: {leader}")
    except NoLeader as e:
        print(f"error: {e}")
        sys.exit(1)

    while True:
        action = input("\n1) register  2) login  q) quit\n> ").strip().lower()
        if action == "1":
            user = input("username: ").strip()
            pw = getpass.getpass("password: ")
            role = input("role (student/instructor): ").strip()
            resp = client.register(user, pw, role)
            print(resp.message)
        elif action == "2":
            user = input("username: ").strip()
            pw = getpass.getpass("password: ")
            if client.login(user, pw):
                print(f"logged in as {user} ({client.role})")
                if client.role == "student":
                    student_loop(client)
                else:
                    instructor_loop(client)
            else:
                print("login failed")
        elif action == "q":
            client.close()
            return


if __name__ == "__main__":
    main()
