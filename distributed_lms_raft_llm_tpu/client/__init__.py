"""LMS clients: leader-discovering library + CLI."""

from .client import LMSClient, NoLeader  # noqa: F401
