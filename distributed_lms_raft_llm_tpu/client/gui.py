"""Tkinter desktop client: the reference GUI, rebuilt over `LMSClient`.

Capability parity: every screen of the reference Tkinter app (reference:
GUI_RAFT_LLM_SourceCode/lms_gui_final.py — register/login :305-368, student
menu :377-426, view/download course material :474-593, upload assignment
:597-670, view grades :730-838, ask query [instructor | llm] :844-940, view
instructor responses :946-1013; instructor menu :429-468, post course
material :1034-1109, view & grade assignments :1112-1248, respond to query
:1255-1361, logout :1369-1404) over this package's leader-discovering
client library instead of per-call channel dialing.

Deliberate differences from the reference:

- Downloads save the *selected* list entry, not `entries[0]`
  (reference defect D8, lms_gui_final.py:588, 1207).
- RPCs run on one worker thread and marshal results back through
  `Tk.after`, so the UI never blocks on the network and widget access
  stays on the main thread (the reference mutated Tk state from pool
  threads, lms_gui_final.py:112-155).
- Leader discovery/retry/failover live in `LMSClient` (same behavior:
  re-resolve + retry on transient codes).

Headless testing: the module touches the toolkit only through the module
attributes `tk`, `messagebox`, and `filedialog`, so tests substitute fake
widget classes and drive every screen without a display
(tests/test_gui.py); run interactively with
    python -m distributed_lms_raft_llm_tpu.client.gui --servers host:port,...
"""

from __future__ import annotations

import argparse
import concurrent.futures
import os
import sys
import traceback
from typing import Callable, List, Optional

import tkinter as tk
from tkinter import filedialog, messagebox

from ..utils import pdf as pdf_lib
from .client import LMSClient, NoLeader

TITLE = "Distributed LMS"


class LMSApp:
    """The application: one window, one active screen at a time.

    Every `show_*` method clears the body frame and rebuilds it; every
    network call goes through `_async` (worker thread + `after` marshal)
    unless the app was built with `background=False` (tests).
    """

    def __init__(self, client: LMSClient, root=None, background: bool = True):
        self.client = client
        self.root = root if root is not None else tk.Tk()
        self.background = background
        self._pool = (
            concurrent.futures.ThreadPoolExecutor(max_workers=2)
            if background
            else None
        )
        self.root.title(TITLE)
        try:
            self.root.geometry("640x480")
        except Exception:
            pass
        self.status = tk.StringVar(master=self.root)
        self.body = tk.Frame(self.root)
        self.body.pack(fill=tk.BOTH, expand=True, padx=12, pady=12)
        self.statusbar = tk.Label(self.root, textvariable=self.status, anchor="w")
        self.statusbar.pack(fill=tk.X, side=tk.BOTTOM)
        self.show_welcome()

    # ------------------------------------------------------------ plumbing

    def run(self) -> None:
        self.root.mainloop()

    def destroy(self) -> None:
        if self._pool:
            self._pool.shutdown(wait=False)
        self.root.destroy()

    def _clear(self) -> None:
        for child in self.body.winfo_children():
            child.destroy()

    def _async(self, fn: Callable, on_done: Callable, what: str = "") -> None:
        """Run `fn()` off the UI thread; call `on_done(result)` back on it.

        Errors surface as a messagebox (leader loss, RPC failure) instead of
        a dead button. In synchronous mode (tests) everything runs inline.
        """
        self.status.set(what or "working…")

        def finish(result, error):
            self.status.set("")
            if error is not None:
                messagebox.showerror(TITLE, f"{what or 'operation'} failed: {error}")
            else:
                on_done(result)

        if not self.background:
            try:
                result, error = fn(), None
            except (NoLeader, Exception) as e:  # noqa: BLE001 — surfaced to user
                result, error = None, e
            finish(result, error)
            return

        def work():
            try:
                result, error = fn(), None
            except Exception as e:  # noqa: BLE001 — surfaced to user
                traceback.print_exc()
                result, error = None, e
            self.root.after(0, lambda: finish(result, error))

        self._pool.submit(work)

    def _header(self, text: str, back: Optional[Callable] = None) -> None:
        row = tk.Frame(self.body)
        row.pack(fill=tk.X)
        tk.Label(row, text=text, font=("TkDefaultFont", 14, "bold")).pack(
            side=tk.LEFT
        )
        if back is not None:
            tk.Button(row, text="Back", command=back).pack(side=tk.RIGHT)

    @staticmethod
    def _entry_row(parent, label: str, show: str = "") -> "tk.Entry":
        row = tk.Frame(parent)
        row.pack(fill=tk.X, pady=4)
        tk.Label(row, text=label, width=14, anchor="w").pack(side=tk.LEFT)
        entry = tk.Entry(row, show=show)
        entry.pack(side=tk.LEFT, fill=tk.X, expand=True)
        return entry

    def _listbox(self, items: List[str]) -> "tk.Listbox":
        box = tk.Listbox(self.body)
        for item in items:
            box.insert(tk.END, item)
        box.pack(fill=tk.BOTH, expand=True, pady=6)
        return box

    @staticmethod
    def _selected(box: "tk.Listbox") -> Optional[int]:
        sel = box.curselection()
        return int(sel[0]) if sel else None

    # ------------------------------------------------------------- screens

    def show_welcome(self) -> None:
        self._clear()
        self._header("Welcome to the LMS")
        tk.Button(self.body, text="Login", command=self.show_login).pack(
            fill=tk.X, pady=4
        )
        tk.Button(self.body, text="Register", command=self.show_register).pack(
            fill=tk.X, pady=4
        )
        tk.Button(self.body, text="Quit", command=self.destroy).pack(
            fill=tk.X, pady=4
        )

    def show_register(self) -> None:
        self._clear()
        self._header("Register", back=self.show_welcome)
        user = self._entry_row(self.body, "Username")
        pw = self._entry_row(self.body, "Password", show="*")
        role = tk.StringVar(master=self.root, value="student")
        row = tk.Frame(self.body)
        row.pack(fill=tk.X, pady=4)
        tk.Radiobutton(row, text="student", variable=role, value="student").pack(
            side=tk.LEFT
        )
        tk.Radiobutton(
            row, text="instructor", variable=role, value="instructor"
        ).pack(side=tk.LEFT)

        def submit():
            username, password = user.get().strip(), pw.get()
            if not username or not password:
                messagebox.showwarning(TITLE, "username and password required")
                return
            self._async(
                lambda: self.client.register(username, password, role.get()),
                lambda resp: (
                    messagebox.showinfo(TITLE, resp.message),
                    self.show_welcome() if resp.success else None,
                ),
                what="registering",
            )

        tk.Button(self.body, text="Register", command=submit).pack(pady=8)

    def show_login(self) -> None:
        self._clear()
        self._header("Login", back=self.show_welcome)
        user = self._entry_row(self.body, "Username")
        pw = self._entry_row(self.body, "Password", show="*")

        def submit():
            username, password = user.get().strip(), pw.get()

            def done(ok: bool):
                if not ok:
                    messagebox.showerror(TITLE, "login failed")
                elif self.client.role == "student":
                    self.show_student_menu()
                else:
                    self.show_instructor_menu()

            self._async(
                lambda: self.client.login(username, password), done, what="logging in"
            )

        tk.Button(self.body, text="Login", command=submit).pack(pady=8)

    def _logout(self) -> None:
        self._async(
            lambda: self.client.logout(),
            lambda _ok: self.show_welcome(),
            what="logging out",
        )

    # ------------------------------------------------------ student screens

    def show_student_menu(self) -> None:
        self._clear()
        self._header("Student menu")
        for text, cmd in [
            ("View course materials", self.show_materials),
            ("Download course material", self.show_download_material),
            ("Upload assignment", self.show_upload_assignment),
            ("View my grade", self.show_grades),
            ("Ask a query", self.show_ask_query),
            ("View instructor responses", self.show_responses),
            ("Logout", self._logout),
        ]:
            tk.Button(self.body, text=text, command=cmd).pack(fill=tk.X, pady=3)

    def show_materials(self) -> None:
        def done(entries):
            self._clear()
            self._header("Course materials", back=self.show_student_menu)
            self._listbox(
                [
                    f"{e.filename}  (by {e.instructor}, {len(e.file)} bytes)"
                    for e in entries
                ]
                or ["(no course materials posted)"]
            )

        self._async(self.client.course_materials, done, what="fetching materials")

    def show_download_material(self) -> None:
        def done(entries):
            self._clear()
            self._header("Download material", back=self.show_student_menu)
            box = self._listbox([e.filename for e in entries])

            def save():
                idx = self._selected(box)
                if idx is None or idx >= len(entries):
                    messagebox.showwarning(TITLE, "select a file first")
                    return
                # The SELECTED entry — the reference saved entries[0] no
                # matter the selection (D8, lms_gui_final.py:588).
                entry = entries[idx]
                default = os.path.basename(entry.filename) or "material.pdf"
                path = filedialog.asksaveasfilename(initialfile=default)
                if not path:
                    return
                with open(path, "wb") as f:
                    f.write(entry.file)
                messagebox.showinfo(TITLE, f"saved {path}")

            tk.Button(self.body, text="Save selected", command=save).pack(pady=6)

        self._async(self.client.course_materials, done, what="fetching materials")

    def show_upload_assignment(self) -> None:
        self._clear()
        self._header("Upload assignment", back=self.show_student_menu)

        def pick_and_upload():
            path = filedialog.askopenfilename(
                filetypes=[("PDF files", "*.pdf"), ("All files", "*")]
            )
            if not path:
                return
            with open(path, "rb") as f:
                content = f.read()
            name = os.path.basename(path)
            self._async(
                lambda: self.client.upload_assignment(name, content),
                lambda ok: messagebox.showinfo(
                    TITLE, "uploaded" if ok else "upload failed"
                ),
                what="uploading",
            )

        tk.Button(self.body, text="Choose PDF…", command=pick_and_upload).pack(pady=6)

        text = tk.Text(self.body, height=8)
        text.pack(fill=tk.BOTH, expand=True, pady=6)

        def upload_typed():
            content = text.get("1.0", tk.END).strip()
            if not content:
                messagebox.showwarning(TITLE, "type some text first")
                return
            blob = pdf_lib.make_pdf(content)
            self._async(
                lambda: self.client.upload_assignment("typed.pdf", blob),
                lambda ok: messagebox.showinfo(
                    TITLE, "uploaded" if ok else "upload failed"
                ),
                what="uploading",
            )

        tk.Button(
            self.body, text="Upload typed text as PDF", command=upload_typed
        ).pack(pady=2)

    def show_grades(self) -> None:
        def done(grade: str):
            self._clear()
            self._header("My grade", back=self.show_student_menu)
            tk.Label(self.body, text=grade or "(not graded yet)").pack(pady=12)

        self._async(self.client.my_grade, done, what="fetching grade")

    def show_ask_query(self) -> None:
        self._clear()
        self._header("Ask a query", back=self.show_student_menu)
        text = tk.Text(self.body, height=6)
        text.pack(fill=tk.BOTH, expand=True, pady=6)
        target = tk.StringVar(master=self.root, value="llm")
        row = tk.Frame(self.body)
        row.pack(fill=tk.X)
        tk.Radiobutton(row, text="LLM tutor", variable=target, value="llm").pack(
            side=tk.LEFT
        )
        tk.Radiobutton(
            row, text="Instructor", variable=target, value="instructor"
        ).pack(side=tk.LEFT)

        def submit():
            query = text.get("1.0", tk.END).strip()
            if not query:
                messagebox.showwarning(TITLE, "type a question first")
                return
            if target.get() == "llm":
                self._async(
                    lambda: self.client.ask_llm(query),
                    lambda resp: messagebox.showinfo(
                        TITLE, resp.response if resp.success else f"rejected: {resp.response}"
                    ),
                    what="asking the LLM tutor",
                )
            else:
                self._async(
                    lambda: self.client.ask_instructor(query),
                    lambda ok: messagebox.showinfo(
                        TITLE, "sent to instructor" if ok else "failed"
                    ),
                    what="sending query",
                )

        tk.Button(self.body, text="Submit", command=submit).pack(pady=6)

    def show_responses(self) -> None:
        def done(entries):
            self._clear()
            self._header("Instructor responses", back=self.show_student_menu)
            self._listbox(
                [e.data.replace("\n", " | ") for e in entries]
                or ["(no responses yet)"]
            )

        self._async(
            self.client.instructor_responses, done, what="fetching responses"
        )

    # --------------------------------------------------- instructor screens

    def show_instructor_menu(self) -> None:
        self._clear()
        self._header("Instructor menu")
        for text, cmd in [
            ("Post course material", self.show_post_material),
            ("View & grade assignments", self.show_grade_assignments),
            ("View unanswered queries", self.show_queries),
            ("Respond to a query", self.show_respond_query),
            ("Logout", self._logout),
        ]:
            tk.Button(self.body, text=text, command=cmd).pack(fill=tk.X, pady=3)

    def show_post_material(self) -> None:
        self._clear()
        self._header("Post course material", back=self.show_instructor_menu)

        def pick_and_post():
            path = filedialog.askopenfilename(
                filetypes=[("PDF files", "*.pdf"), ("All files", "*")]
            )
            if not path:
                return
            with open(path, "rb") as f:
                content = f.read()
            name = os.path.basename(path)
            self._async(
                lambda: self.client.upload_course_material(name, content),
                lambda ok: messagebox.showinfo(
                    TITLE, "posted" if ok else "post failed"
                ),
                what="posting material",
            )

        tk.Button(self.body, text="Choose PDF…", command=pick_and_post).pack(pady=6)

    def show_grade_assignments(self) -> None:
        def done(entries):
            self._clear()
            self._header("Grade assignments", back=self.show_instructor_menu)
            box = self._listbox(
                [f"{e.id}: {e.filename} ({len(e.file)} bytes)" for e in entries]
            )
            grade_entry = self._entry_row(self.body, "Grade")

            def submit():
                idx = self._selected(box)
                grade = grade_entry.get().strip()
                if idx is None or idx >= len(entries):
                    messagebox.showwarning(TITLE, "select a student first")
                    return
                if not grade:
                    messagebox.showwarning(TITLE, "enter a grade")
                    return
                student = entries[idx].id
                self._async(
                    lambda: self.client.grade(student, grade),
                    lambda resp: messagebox.showinfo(TITLE, resp.message),
                    what="grading",
                )

            tk.Button(self.body, text="Submit grade", command=submit).pack(pady=6)

        self._async(self.client.student_assignments, done, what="fetching assignments")

    def show_queries(self) -> None:
        def done(entries):
            self._clear()
            self._header("Unanswered queries", back=self.show_instructor_menu)
            self._listbox(
                [f"{e.id}: {e.data}" for e in entries] or ["(no open queries)"]
            )

        self._async(self.client.unanswered_queries, done, what="fetching queries")

    def show_respond_query(self) -> None:
        def done(entries):
            self._clear()
            self._header("Respond to query", back=self.show_instructor_menu)
            box = self._listbox([f"{e.id}: {e.data}" for e in entries])
            text = tk.Text(self.body, height=5)
            text.pack(fill=tk.BOTH, expand=True, pady=6)

            def submit():
                idx = self._selected(box)
                response = text.get("1.0", tk.END).strip()
                if idx is None or idx >= len(entries):
                    messagebox.showwarning(TITLE, "select a query first")
                    return
                if not response:
                    messagebox.showwarning(TITLE, "type a response")
                    return
                student = entries[idx].id
                self._async(
                    lambda: self.client.respond_to_query(student, response),
                    lambda ok: messagebox.showinfo(
                        TITLE, "responded" if ok else "failed"
                    ),
                    what="responding",
                )

            tk.Button(self.body, text="Send response", command=submit).pack(pady=6)

        self._async(self.client.unanswered_queries, done, what="fetching queries")


def main(argv=None) -> None:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument(
        "--servers",
        default="127.0.0.1:50051,127.0.0.1:50052,127.0.0.1:50053,"
                "127.0.0.1:50055,127.0.0.1:50056",
        help="comma-separated LMS server addresses",
    )
    parser.add_argument("--config", default=None,
                        help="TOML deployment file; [cluster.nodes] supplies "
                             "the server list")
    args = parser.parse_args(argv)
    servers = args.servers.split(",")
    client_opts = {}
    # Explicit --servers beats the file (same precedence as the servers).
    if args.config:
        from ..config import client_kwargs, load_config

        cfg = load_config(args.config)
        if args.servers == parser.get_default("servers"):
            servers = cfg.client_servers
        client_opts = client_kwargs(cfg)
    client = LMSClient(servers, **client_opts)
    try:
        client.discover_leader()
    except NoLeader as e:
        print(f"error: {e}", file=sys.stderr)
        sys.exit(1)
    LMSApp(client).run()


if __name__ == "__main__":
    main()
