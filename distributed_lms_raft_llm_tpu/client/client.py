"""Leader-discovering LMS client library.

Reference behavior (GUI_RAFT_LLM_SourceCode/lms_gui_final.py:64-155): poll
`RaftService.WhoIsLeader` across all servers (≤5 rounds, 3 s backoff),
follow redirects to the named leader, and on transient RPC failures
re-resolve the leader and retry (≤3). Reimplemented as a clean synchronous
library the CLI/GUI layers (and tests) share, with channel reuse instead of
per-call dialing.

Retry semantics (utils/resilience.py): every logical operation runs under
ONE overall `Deadline` — created here, propagated to the server as the gRPC
timeout plus an explicit budget header, decremented across redirects and
retries. Transient failures back off with full jitter instead of the
reference's immediate-retry hammering (a synchronized retry herd is what
turns a leader blip into an outage), and the loop stops the moment the
budget is gone — the caller gets its answer or its error within the
deadline, never a hang.
"""

from __future__ import annotations

import dataclasses
import hashlib
import logging
import random
import time
import uuid
from typing import Callable, Dict, List, Optional, Sequence, TypeVar

import grpc

from ..lms.group_router import USER_METADATA_KEY
from ..proto import lms_pb2, rpc
from ..utils.resilience import (
    REQUEST_ID_METADATA_KEY,
    Deadline,
    DeadlineExpired,
    jittered_backoff,
)
from ..utils.tracing import FLAG_DEADLINE, FLAG_ERROR, get_tracer, \
    trace_metadata

log = logging.getLogger(__name__)

T = TypeVar("T")

RETRYABLE = {
    grpc.StatusCode.UNAVAILABLE,
    grpc.StatusCode.UNKNOWN,
    grpc.StatusCode.DEADLINE_EXCEEDED,
    grpc.StatusCode.CANCELLED,
    grpc.StatusCode.RESOURCE_EXHAUSTED,
}


class NoLeader(Exception):
    pass


@dataclasses.dataclass
class StreamAnswer:
    """One streamed ask_llm's outcome, shaped for unary parity.

    `success`/`response` match `ask_llm`'s QueryResponse contract
    (`response` is the stripped full answer), so call sites can treat
    the two paths interchangeably. The streaming-only evidence rides
    along: chunk/resume counts, time-to-first-token, and the digest
    verdict (`digest_ok` is None when the stream ended on a failure or
    degraded chunk that carries no digest)."""

    success: bool
    response: str
    chunks: int = 0
    resumes: int = 0
    ttft_s: Optional[float] = None
    digest: str = ""
    digest_ok: Optional[bool] = None


class LMSClient:
    def __init__(
        self,
        servers: Sequence[str],
        *,
        discovery_rounds: int = 5,
        discovery_backoff_s: float = 1.0,
        rpc_retries: int = 3,
        rpc_timeout: float = 30.0,
        request_timeout_s: float = 60.0,
        llm_timeout_s: float = 120.0,
        backoff_base_s: float = 0.05,
        backoff_max_s: float = 2.0,
        seed: Optional[int] = None,
        group_of: Optional[Callable[[str], int]] = None,
    ):
        self.servers = list(servers)
        self.discovery_rounds = discovery_rounds
        self.discovery_backoff_s = discovery_backoff_s
        self.rpc_retries = rpc_retries
        self.rpc_timeout = rpc_timeout
        # Overall budgets: one Deadline bounds discovery + all retries of a
        # logical op. ask_llm gets its own (generation is the slow path).
        self.request_timeout_s = request_timeout_s
        self.llm_timeout_s = llm_timeout_s
        self.backoff_base_s = backoff_base_s
        self.backoff_max_s = backoff_max_s
        self._rng = random.Random(seed)
        self.token: Optional[str] = None
        self.role: Optional[str] = None
        self._channels: Dict[str, grpc.Channel] = {}
        # Leader hints keyed by Raft GROUP (sharded control plane, PR 16).
        # Lane 0 is the meta group — the only lane a single-group cluster
        # ever uses, so this stays behavior-identical there. Against a
        # sharded cluster, `group_of` (username → home group) picks the
        # lane per logical op, and a failed RPC distrusts ONLY that lane:
        # losing group 2's leader must not blow away good hints for 0/1.
        self._group_of = group_of
        self._username: Optional[str] = None
        self._leader_hints: Dict[int, str] = {}
        # Leader addresses learned over the wire (GetLeader) that the boot
        # list doesn't contain — a server added by a runtime membership
        # change. Probed during discovery so the client can follow the
        # cluster as it grows; `self.servers` stays the user's boot list
        # (WhoIsLeader's positional id->address mapping depends on it).
        self._extra_servers: List[str] = []

    # ------------------------------------------------------------ plumbing

    def _channel(self, addr: str) -> grpc.Channel:
        if addr not in self._channels:
            self._channels[addr] = grpc.insecure_channel(
                addr,
                options=[
                    ("grpc.max_send_message_length", 50 * 1024 * 1024),
                    ("grpc.max_receive_message_length", 50 * 1024 * 1024),
                ],
            )
        return self._channels[addr]

    def close(self) -> None:
        for ch in self._channels.values():
            ch.close()
        self._channels.clear()

    @property
    def _leader_addr(self) -> Optional[str]:
        """Back-compat view of the meta-group (lane 0) hint."""
        return self._leader_hints.get(0)

    @_leader_addr.setter
    def _leader_addr(self, addr: Optional[str]) -> None:
        if addr is None:
            self._leader_hints.pop(0, None)
        else:
            self._leader_hints[0] = addr

    def _home_group(self) -> int:
        """The logged-in user's home Raft group (lane 0 when unknown)."""
        if self._group_of is not None and self._username:
            try:
                return max(0, int(self._group_of(self._username)))
            except (TypeError, ValueError):
                return 0
        return 0

    def _set_leader(self, addr: str, group: int = 0) -> str:
        self._leader_hints[group] = addr
        if addr not in self.servers and addr not in self._extra_servers:
            # A leader the boot list doesn't know (membership-added node):
            # remember it as a discovery peer of its own, so the client
            # still finds the cluster if the boot-list nodes go away.
            self._extra_servers.append(addr)
        return addr

    def evict_leader_hint(self, addr: Optional[str] = None,
                          group: Optional[int] = None) -> None:
        """Drop cached leader hints. Called when a hinted node fails an
        RPC — it may have been removed by a membership change, restarted,
        or deposed — so the next op re-discovers from any live peer
        instead of re-dialing a corpse.

        Distrust is scoped: with `group` given, only that group's lane is
        dropped; with only `addr`, every lane currently pointing at that
        address is dropped (but other groups' healthy hints survive);
        with neither, everything goes.

        A wire-learned (off-boot-list) address is also dropped from the
        discovery peers: without this the list grows without bound under
        membership churn and every sweep keeps probing removed nodes. If
        the node is alive and leads again, the next GetLeader re-learns
        it."""
        if group is not None:
            hinted = self._leader_hints.get(group)
            if addr is None or hinted == addr:
                self._leader_hints.pop(group, None)
        elif addr is None:
            self._leader_hints.clear()
        else:
            for lane in [g for g, a in self._leader_hints.items() if a == addr]:
                self._leader_hints.pop(lane, None)
        if addr is not None and addr in self._extra_servers:
            self._extra_servers.remove(addr)

    def discover_leader(
        self, force: bool = False, deadline: Optional[Deadline] = None,
        avoid: Optional[str] = None, group: Optional[int] = None,
    ) -> str:
        """Address of the current leader (cached until an RPC fails).

        Bounded by `deadline` when given: discovery gives up the moment the
        caller's budget is gone instead of finishing its sweep schedule.

        `avoid` is an address that just failed an RPC (the evicted hint):
        it is probed last, and during the first sweep a peer's report
        naming it is treated as stale churn — other peers get the chance
        to name the REAL leader first. If a full sweep produces nothing
        else, the avoided address is accepted after all (the failure may
        have been transient), so discovery degrades gracefully instead of
        blacklisting a healthy node.

        `group` selects the hint lane (default: the logged-in user's home
        group). Discovery itself names the meta-group leader — ANY router
        node accepts and forwards every RPC — so against a sharded
        cluster each lane converges on the entry point that served it
        last, and eviction on failure is per group.
        """
        lane = self._home_group() if group is None else group
        hinted = self._leader_hints.get(lane)
        if hinted and not force:
            return hinted
        for attempt in range(self.discovery_rounds):
            # Probe healthy candidates first; the just-failed node last.
            order = [a for a in (*self.servers, *self._extra_servers)
                     if a != avoid]
            if avoid is not None:
                order.append(avoid)
            fallback: Optional[str] = None
            for addr in order:
                if deadline is not None and deadline.expired:
                    raise NoLeader(
                        f"no leader found among {self.servers} within budget"
                    )
                try:
                    probe_timeout = 2.0
                    if deadline is not None:
                        probe_timeout = max(0.1, deadline.timeout(cap=2.0))
                    stub = rpc.RaftServiceStub(self._channel(addr))
                    resp = stub.GetLeader(
                        lms_pb2.GetLeaderRequest(), timeout=probe_timeout
                    )
                    if resp.nodeId > 0 and resp.nodeAddress:
                        if resp.nodeAddress == avoid and attempt == 0:
                            fallback = resp.nodeAddress
                            continue
                        return self._set_leader(resp.nodeAddress, lane)
                    who = stub.WhoIsLeader(lms_pb2.Empty(), timeout=probe_timeout)
                    if 0 < who.leader_id <= len(self.servers):
                        cand = self.servers[who.leader_id - 1]
                        if cand == avoid and attempt == 0:
                            fallback = cand
                            continue
                        return self._set_leader(cand, lane)
                except grpc.RpcError:
                    continue
            if fallback is not None:
                # Every live peer still names the avoided address and a
                # full sweep found no alternative: trust it after all.
                return self._set_leader(fallback, lane)
            sleep_s = jittered_backoff(
                attempt, base_s=self.discovery_backoff_s,
                cap_s=self.discovery_backoff_s * 4, rng=self._rng,
            )
            if deadline is not None:
                if deadline.expired:
                    break
                sleep_s = min(sleep_s, deadline.remaining())
            time.sleep(sleep_s)
        raise NoLeader(f"no leader found among {self.servers}")

    def _call(
        self,
        fn: Callable[[rpc.LMSStub, float, Optional[Deadline]], T],
        *,
        budget_s: Optional[float] = None,
        attempt_cap_s: Optional[float] = -1.0,
        route: str = "call",
        trace_id: Optional[str] = None,
    ) -> T:
        """Run an op against the leader under one overall deadline.

        `fn(stub, timeout, deadline)` performs the RPC with the given
        per-attempt timeout (the remaining budget capped at rpc_timeout).
        Transient failures re-resolve the leader and retry with jittered
        exponential backoff until the retry count or the budget runs out.

        Mutating callers bake a `request_id` into the request (see
        `_request_id`): the SAME id is re-sent on every retry, so if the
        original proposal actually committed (e.g. the client timed out
        waiting for the quorum ACK), the replicated applier drops the
        duplicate instead of double-applying a non-idempotent command.
        """
        deadline = Deadline.after(budget_s or self.request_timeout_s)
        # -1 sentinel: default to the per-attempt rpc_timeout cap; None
        # means "let one attempt use the whole remaining budget" (ask_llm,
        # where generation legitimately outlasts control-plane RPCs).
        cap = self.rpc_timeout if attempt_cap_s == -1.0 else attempt_cap_s
        # ONE client span covers the whole logical op — discovery, every
        # retry, the backoffs between them. Server-side fragments graft
        # under it via the x-trace-context each attempt carries (_md), and
        # mutating ops reuse their idempotency id as the trace id, so
        # `/admin/trace/<request-id>` answers for the id already in logs.
        with get_tracer().trace(f"client.{route}",
                                trace_id=trace_id) as root:
            return self._attempts(fn, deadline, cap, budget_s, root)

    def _attempts(
        self,
        fn: Callable[[rpc.LMSStub, float, Optional[Deadline]], T],
        deadline: Deadline,
        cap: Optional[float],
        budget_s: Optional[float],
        root,
    ) -> T:
        last_error: Optional[Exception] = None
        avoid: Optional[str] = None
        lane = self._home_group()
        for attempt in range(self.rpc_retries + 1):
            if deadline.expired:
                break
            addr = None
            try:
                addr = self.discover_leader(force=attempt > 0,
                                            deadline=deadline, avoid=avoid,
                                            group=lane)
                stub = rpc.LMSStub(self._channel(addr))
                timeout = max(0.001, deadline.timeout(cap=cap))
                return fn(stub, timeout, deadline)
            except grpc.RpcError as e:
                last_error = e
                if e.code() not in RETRYABLE:
                    raise
                if addr is not None:
                    # Evict the hint and steer the next discovery sweep
                    # away from the failed node: mid-churn (a membership
                    # remove, a rolling restart) stale peers may keep
                    # naming it, and re-trusting them first would pin every
                    # retry on the same dead address. Distrust is scoped to
                    # this op's group lane — other groups keep their hints.
                    self.evict_leader_hint(addr, group=lane)
                    avoid = addr
                log.info("rpc failed (%s); re-resolving leader", e.code())
                if attempt >= self.rpc_retries:
                    break  # out of attempts: fail now, don't sleep first
                sleep_s = min(
                    jittered_backoff(
                        attempt, base_s=self.backoff_base_s,
                        cap_s=self.backoff_max_s, rng=self._rng,
                    ),
                    deadline.remaining(),
                )
                if sleep_s > 0:
                    time.sleep(sleep_s)
        if last_error is not None:
            root.flag(FLAG_ERROR)
            raise last_error
        root.flag(FLAG_DEADLINE)
        raise DeadlineExpired(
            f"request budget ({budget_s or self.request_timeout_s:.1f}s) "
            "exhausted before the first attempt"
        )

    @staticmethod
    def _request_id() -> str:
        """Idempotency key for one logical mutation (stable across retries)."""
        return uuid.uuid4().hex

    def _md(self, deadline: Optional[Deadline],
            request_id: Optional[str] = None):
        """Per-attempt metadata: the live deadline budget, plus (when given)
        the logical request id — the SAME id on every retry, so server-side
        mutations made on this request's behalf (the degraded instructor
        fallback) dedupe in the replicated applier."""
        md = deadline.to_metadata() if deadline is not None else []
        if request_id:
            md = md + [(REQUEST_ID_METADATA_KEY, request_id)]
        if self.token and self._username:
            # Routing HINT for the sharded control plane: lets a router
            # whose local session replicas lag still home-route the op.
            # Auth stays with the token — a wrong hint only mis-routes to
            # a group that rejects it.
            md = md + [(USER_METADATA_KEY, self._username)]
        # The trace context rides the same metadata: each attempt carries
        # the client span's position so server fragments graft under it.
        return trace_metadata(md)

    # ----------------------------------------------------------------- api

    def register(self, username: str, password: str, role: str):
        return self._call(
            lambda s, t, d: s.Register(
                lms_pb2.RegisterRequest(
                    username=username, password=password, role=role
                ),
                timeout=t, metadata=self._md(d),
            ),
            route="register",
        )

    def login(self, username: str, password: str) -> bool:
        resp = self._call(
            lambda s, t, d: s.Login(
                lms_pb2.LoginRequest(username=username, password=password),
                timeout=t, metadata=self._md(d),
            ),
            route="login",
        )
        if resp.success:
            self.token = resp.token
            self.role = resp.role
            self._username = username
        return resp.success

    def logout(self) -> bool:
        if not self.token:
            return False
        resp = self._call(
            lambda s, t, d: s.Logout(
                lms_pb2.LogoutRequest(token=self.token), timeout=t,
                metadata=self._md(d),
            ),
            route="logout",
        )
        if resp.success:
            self.token = None
            self.role = None
        return resp.success

    def upload_assignment(self, filename: str, content: bytes) -> bool:
        rid = self._request_id()
        return self._call(
            lambda s, t, d: s.Post(
                lms_pb2.PostRequest(
                    token=self.token or "", type="assignment",
                    file=content, filename=filename, request_id=rid,
                ),
                timeout=t, metadata=self._md(d),
            ),
            route="upload_assignment", trace_id=rid,
        ).success

    def upload_course_material(self, filename: str, content: bytes) -> bool:
        rid = self._request_id()
        return self._call(
            lambda s, t, d: s.Post(
                lms_pb2.PostRequest(
                    token=self.token or "", type="course_material",
                    file=content, filename=filename, request_id=rid,
                ),
                timeout=t, metadata=self._md(d),
            ),
            route="upload_course_material", trace_id=rid,
        ).success

    def ask_instructor(self, query: str) -> bool:
        rid = self._request_id()
        return self._call(
            lambda s, t, d: s.Post(
                lms_pb2.PostRequest(
                    token=self.token or "", type="query", data=query,
                    request_id=rid,
                ),
                timeout=t, metadata=self._md(d),
            ),
            route="ask_instructor", trace_id=rid,
        ).success

    def course_materials(self) -> List[lms_pb2.DataEntry]:
        resp = self._call(
            lambda s, t, d: s.Get(
                lms_pb2.GetRequest(token=self.token or "", type="course_material"),
                timeout=t, metadata=self._md(d),
            ),
            route="course_materials",
        )
        return list(resp.entries)

    def student_assignments(self) -> List[lms_pb2.DataEntry]:
        resp = self._call(
            lambda s, t, d: s.Get(
                lms_pb2.GetRequest(token=self.token or "", type="student_list"),
                timeout=t, metadata=self._md(d),
            ),
            route="student_assignments",
        )
        return list(resp.entries)

    def grade(self, student: str, grade: str):
        rid = self._request_id()
        return self._call(
            lambda s, t, d: s.GradeAssignment(
                lms_pb2.GradeRequest(
                    token=self.token or "", studentId=student, grade=grade,
                    request_id=rid,
                ),
                timeout=t, metadata=self._md(d),
            ),
            route="grade", trace_id=rid,
        )

    def my_grade(self) -> str:
        resp = self._call(
            lambda s, t, d: s.GetGrade(
                lms_pb2.GetGradeRequest(token=self.token or ""),
                timeout=t, metadata=self._md(d),
            ),
            route="my_grade",
        )
        return resp.grade

    def unanswered_queries(self) -> List[lms_pb2.DataEntry]:
        resp = self._call(
            lambda s, t, d: s.GetUnansweredQueries(
                lms_pb2.GetRequest(token=self.token or ""),
                timeout=t, metadata=self._md(d),
            ),
            route="unanswered_queries",
        )
        return list(resp.entries)

    def respond_to_query(self, student: str, response: str) -> bool:
        rid = self._request_id()
        return self._call(
            lambda s, t, d: s.RespondToQuery(
                lms_pb2.PostRequest(
                    token=self.token or "", studentId=student, data=response,
                    request_id=rid,
                ),
                timeout=t, metadata=self._md(d),
            ),
            route="respond_to_query", trace_id=rid,
        ).success

    def instructor_responses(self) -> List[lms_pb2.DataEntry]:
        resp = self._call(
            lambda s, t, d: s.GetInstructorResponse(
                lms_pb2.GetRequest(token=self.token or ""),
                timeout=t, metadata=self._md(d),
            ),
            route="instructor_responses",
        )
        return list(resp.entries)

    def ask_llm(
        self, query: str, *, budget_s: Optional[float] = None,
        request_id: Optional[str] = None,
    ) -> lms_pb2.QueryResponse:
        """One student query under one overall budget (default
        `llm_timeout_s`). The LMS forwards the remaining budget to the
        tutoring node; if tutoring is down or too slow the LMS answers
        degraded (query queued for an instructor) within the budget.

        One `request_id` spans ALL retries of this logical call: a retry
        whose earlier attempt already queued the degraded instructor entry
        must not queue a second one (ROADMAP item a). It doubles as the
        TRACE id — `GET /admin/trace/<request_id>` returns this call's
        span tree — and callers may supply their own (pre-logged) id."""
        rid = request_id or self._request_id()
        return self._call(
            lambda s, t, d: s.GetLLMAnswer(
                lms_pb2.QueryRequest(token=self.token or "", query=query),
                timeout=t, metadata=self._md(d, request_id=rid),
            ),
            budget_s=budget_s or self.llm_timeout_s,
            attempt_cap_s=None,
            route="ask_llm", trace_id=rid,
        )

    def ask_llm_stream(
        self, query: str, *, session_id: str = "",
        budget_s: Optional[float] = None,
        request_id: Optional[str] = None,
    ) -> StreamAnswer:
        """Streamed ask_llm under the resumable-stream contract.

        The client tracks the last delivered token offset; any mid-stream
        failure (leader loss, a serving-node kill behind the LMS, a
        breaker opening) re-discovers the leader and RESUMES at that
        offset via `resume_offset` — tokens already delivered are never
        re-requested, and a resumed stream splices gap-free because the
        server regenerates deterministically and skips the delivered
        prefix. Chunks are validated client-side: pure duplicates are
        dropped, an offset gap fails the attempt (retryable — the resend
        starts at our offset), so the delivered text is monotone,
        gap-free, and duplicate-free by construction.

        `session_id` threads conversational turns: the server keys
        tutoring-node affinity on it and splices turn N's transcript as
        a shared KV prefix for turn N+1.

        The final chunk's digest is checked against sha256 of the
        stripped full answer (exactly what unary `ask_llm` returns), so
        `digest_ok=True` proves the streamed answer is bit-identical to
        the unary one end to end — across resumes included."""
        rid = request_id or self._request_id()
        deadline = Deadline.after(budget_s or self.llm_timeout_s)
        delivered = 0
        parts: List[str] = []
        resumes = 0
        chunks = 0
        ttft_s: Optional[float] = None
        with get_tracer().trace("client.ask_llm_stream",
                                trace_id=rid) as root:
            t_start = time.monotonic()
            last_error: Optional[Exception] = None
            avoid: Optional[str] = None
            lane = self._home_group()
            for attempt in range(self.rpc_retries + 1):
                if deadline.expired:
                    break
                addr = None
                if delivered > 0 and attempt > 0:
                    resumes += 1
                try:
                    addr = self.discover_leader(
                        force=attempt > 0, deadline=deadline,
                        avoid=avoid, group=lane,
                    )
                    stub = rpc.LMSStub(self._channel(addr))
                    timeout = max(0.001, deadline.timeout(cap=None))
                    final = None
                    call = stub.StreamLLMAnswer(
                        lms_pb2.StreamRequest(
                            token=self.token or "", query=query,
                            session_id=session_id,
                            resume_offset=delivered,
                        ),
                        timeout=timeout,
                        metadata=self._md(deadline, request_id=rid),
                    )
                    for chunk in call:
                        chunks += 1
                        if chunk.count > 0 and chunk.success:
                            end = chunk.offset + chunk.count
                            if end <= delivered:
                                continue  # pure duplicate: drop
                            if chunk.offset != delivered:
                                # A gap (or mid-chunk overlap) breaks
                                # the monotone contract: fail the
                                # attempt; the resume re-requests from
                                # OUR offset, never trusts the gap.
                                raise grpc.RpcError()
                            if ttft_s is None:
                                ttft_s = time.monotonic() - t_start
                            parts.append(chunk.text)
                            delivered = end
                        if chunk.final:
                            final = chunk
                            break
                    if final is None:
                        # Stream ended cleanly but without a final chunk
                        # (server died between chunks): resume.
                        raise grpc.RpcError()
                    full = "".join(parts)
                    digest_ok: Optional[bool] = None
                    if final.digest:
                        digest_ok = (
                            hashlib.sha256(full.strip().encode())
                            .hexdigest() == final.digest
                        )
                    text = (full.strip() if delivered > 0
                            else final.text)
                    return StreamAnswer(
                        success=final.success, response=text,
                        chunks=chunks, resumes=resumes,
                        ttft_s=ttft_s, digest=final.digest,
                        digest_ok=digest_ok,
                    )
                except grpc.RpcError as e:
                    last_error = e
                    code = e.code() if hasattr(e, "code") else None
                    if code is not None and code not in RETRYABLE:
                        raise
                    if addr is not None:
                        self.evict_leader_hint(addr, group=lane)
                        avoid = addr
                    log.info("stream attempt failed (%s) at offset %d; "
                             "re-resolving leader", code, delivered)
                    if attempt >= self.rpc_retries:
                        break
                    sleep_s = min(
                        jittered_backoff(
                            attempt, base_s=self.backoff_base_s,
                            cap_s=self.backoff_max_s, rng=self._rng,
                        ),
                        deadline.remaining(),
                    )
                    if sleep_s > 0:
                        time.sleep(sleep_s)
            if last_error is not None:
                root.flag(FLAG_ERROR)
                raise last_error
            root.flag(FLAG_DEADLINE)
            raise DeadlineExpired(
                f"stream budget ({budget_s or self.llm_timeout_s:.1f}s) "
                f"exhausted at offset {delivered}"
            )
