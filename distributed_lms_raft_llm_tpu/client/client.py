"""Leader-discovering LMS client library.

Reference behavior (GUI_RAFT_LLM_SourceCode/lms_gui_final.py:64-155): poll
`RaftService.WhoIsLeader` across all servers (≤5 rounds, 3 s backoff),
follow redirects to the named leader, and on transient RPC failures
re-resolve the leader and retry (≤3). Reimplemented as a clean synchronous
library the CLI/GUI layers (and tests) share, with channel reuse instead of
per-call dialing.
"""

from __future__ import annotations

import logging
import time
import uuid
from typing import Callable, Dict, List, Optional, Sequence, TypeVar

import grpc

from ..proto import lms_pb2, rpc

log = logging.getLogger(__name__)

T = TypeVar("T")

RETRYABLE = {
    grpc.StatusCode.UNAVAILABLE,
    grpc.StatusCode.UNKNOWN,
    grpc.StatusCode.DEADLINE_EXCEEDED,
    grpc.StatusCode.CANCELLED,
    grpc.StatusCode.RESOURCE_EXHAUSTED,
}


class NoLeader(Exception):
    pass


class LMSClient:
    def __init__(
        self,
        servers: Sequence[str],
        *,
        discovery_rounds: int = 5,
        discovery_backoff_s: float = 1.0,
        rpc_retries: int = 3,
        rpc_timeout: float = 30.0,
    ):
        self.servers = list(servers)
        self.discovery_rounds = discovery_rounds
        self.discovery_backoff_s = discovery_backoff_s
        self.rpc_retries = rpc_retries
        self.rpc_timeout = rpc_timeout
        self.token: Optional[str] = None
        self.role: Optional[str] = None
        self._channels: Dict[str, grpc.Channel] = {}
        self._leader_addr: Optional[str] = None

    # ------------------------------------------------------------ plumbing

    def _channel(self, addr: str) -> grpc.Channel:
        if addr not in self._channels:
            self._channels[addr] = grpc.insecure_channel(
                addr,
                options=[
                    ("grpc.max_send_message_length", 50 * 1024 * 1024),
                    ("grpc.max_receive_message_length", 50 * 1024 * 1024),
                ],
            )
        return self._channels[addr]

    def close(self) -> None:
        for ch in self._channels.values():
            ch.close()
        self._channels.clear()

    def discover_leader(self, force: bool = False) -> str:
        """Address of the current leader (cached until an RPC fails)."""
        if self._leader_addr and not force:
            return self._leader_addr
        for attempt in range(self.discovery_rounds):
            for addr in self.servers:
                try:
                    stub = rpc.RaftServiceStub(self._channel(addr))
                    resp = stub.GetLeader(lms_pb2.GetLeaderRequest(), timeout=2)
                    if resp.nodeId > 0 and resp.nodeAddress:
                        self._leader_addr = resp.nodeAddress
                        return self._leader_addr
                    who = stub.WhoIsLeader(lms_pb2.Empty(), timeout=2)
                    if 0 < who.leader_id <= len(self.servers):
                        self._leader_addr = self.servers[who.leader_id - 1]
                        return self._leader_addr
                except grpc.RpcError:
                    continue
            time.sleep(self.discovery_backoff_s)
        raise NoLeader(f"no leader found among {self.servers}")

    def _call(self, fn: Callable[[rpc.LMSStub], T]) -> T:
        """Run an op against the leader; re-resolve + retry on transients.

        Mutating callers bake a `request_id` into the request (see
        `_request_id`): the SAME id is re-sent on every retry, so if the
        original proposal actually committed (e.g. the client timed out
        waiting for the quorum ACK), the replicated applier drops the
        duplicate instead of double-applying a non-idempotent command.
        """
        last_error: Optional[Exception] = None
        for attempt in range(self.rpc_retries + 1):
            try:
                addr = self.discover_leader(force=attempt > 0)
                stub = rpc.LMSStub(self._channel(addr))
                return fn(stub)
            except grpc.RpcError as e:
                last_error = e
                if e.code() not in RETRYABLE:
                    raise
                log.info("rpc failed (%s); re-resolving leader", e.code())
        raise last_error  # type: ignore[misc]

    @staticmethod
    def _request_id() -> str:
        """Idempotency key for one logical mutation (stable across retries)."""
        return uuid.uuid4().hex

    # ----------------------------------------------------------------- api

    def register(self, username: str, password: str, role: str):
        return self._call(
            lambda s: s.Register(
                lms_pb2.RegisterRequest(
                    username=username, password=password, role=role
                ),
                timeout=self.rpc_timeout,
            )
        )

    def login(self, username: str, password: str) -> bool:
        resp = self._call(
            lambda s: s.Login(
                lms_pb2.LoginRequest(username=username, password=password),
                timeout=self.rpc_timeout,
            )
        )
        if resp.success:
            self.token = resp.token
            self.role = resp.role
        return resp.success

    def logout(self) -> bool:
        if not self.token:
            return False
        resp = self._call(
            lambda s: s.Logout(
                lms_pb2.LogoutRequest(token=self.token), timeout=self.rpc_timeout
            )
        )
        if resp.success:
            self.token = None
            self.role = None
        return resp.success

    def upload_assignment(self, filename: str, content: bytes) -> bool:
        rid = self._request_id()
        return self._call(
            lambda s: s.Post(
                lms_pb2.PostRequest(
                    token=self.token or "", type="assignment",
                    file=content, filename=filename, request_id=rid,
                ),
                timeout=self.rpc_timeout,
            )
        ).success

    def upload_course_material(self, filename: str, content: bytes) -> bool:
        rid = self._request_id()
        return self._call(
            lambda s: s.Post(
                lms_pb2.PostRequest(
                    token=self.token or "", type="course_material",
                    file=content, filename=filename, request_id=rid,
                ),
                timeout=self.rpc_timeout,
            )
        ).success

    def ask_instructor(self, query: str) -> bool:
        rid = self._request_id()
        return self._call(
            lambda s: s.Post(
                lms_pb2.PostRequest(
                    token=self.token or "", type="query", data=query,
                    request_id=rid,
                ),
                timeout=self.rpc_timeout,
            )
        ).success

    def course_materials(self) -> List[lms_pb2.DataEntry]:
        resp = self._call(
            lambda s: s.Get(
                lms_pb2.GetRequest(token=self.token or "", type="course_material"),
                timeout=self.rpc_timeout,
            )
        )
        return list(resp.entries)

    def student_assignments(self) -> List[lms_pb2.DataEntry]:
        resp = self._call(
            lambda s: s.Get(
                lms_pb2.GetRequest(token=self.token or "", type="student_list"),
                timeout=self.rpc_timeout,
            )
        )
        return list(resp.entries)

    def grade(self, student: str, grade: str):
        rid = self._request_id()
        return self._call(
            lambda s: s.GradeAssignment(
                lms_pb2.GradeRequest(
                    token=self.token or "", studentId=student, grade=grade,
                    request_id=rid,
                ),
                timeout=self.rpc_timeout,
            )
        )

    def my_grade(self) -> str:
        resp = self._call(
            lambda s: s.GetGrade(
                lms_pb2.GetGradeRequest(token=self.token or ""),
                timeout=self.rpc_timeout,
            )
        )
        return resp.grade

    def unanswered_queries(self) -> List[lms_pb2.DataEntry]:
        resp = self._call(
            lambda s: s.GetUnansweredQueries(
                lms_pb2.GetRequest(token=self.token or ""),
                timeout=self.rpc_timeout,
            )
        )
        return list(resp.entries)

    def respond_to_query(self, student: str, response: str) -> bool:
        rid = self._request_id()
        return self._call(
            lambda s: s.RespondToQuery(
                lms_pb2.PostRequest(
                    token=self.token or "", studentId=student, data=response,
                    request_id=rid,
                ),
                timeout=self.rpc_timeout,
            )
        ).success

    def instructor_responses(self) -> List[lms_pb2.DataEntry]:
        resp = self._call(
            lambda s: s.GetInstructorResponse(
                lms_pb2.GetRequest(token=self.token or ""),
                timeout=self.rpc_timeout,
            )
        )
        return list(resp.entries)

    def ask_llm(self, query: str) -> lms_pb2.QueryResponse:
        return self._call(
            lambda s: s.GetLLMAnswer(
                lms_pb2.QueryRequest(token=self.token or "", query=query),
                timeout=max(self.rpc_timeout, 120.0),
            )
        )
