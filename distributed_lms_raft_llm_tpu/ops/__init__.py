"""Custom TPU (Pallas) kernels for the hot serving ops.

XLA's automatic fusion covers almost everything in this framework; kernels
live here only where a hand schedule measurably beats it. Current contents:

- `attention.decode_attention` — fused single-token attention for the
  autoregressive decode loop (q·K^T → masked softmax → ·V in one VMEM
  pass per layer).
"""

from .attention import decode_attention  # noqa: F401
