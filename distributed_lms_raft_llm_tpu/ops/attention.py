"""Pallas TPU kernel: fused single-token (decode) attention.

The autoregressive decode step is HBM-bandwidth-bound: every step streams
the whole KV cache once per layer. XLA compiles `attend`'s einsum chain
(models/common.py:attend) into separate score and weighted-sum fusions
with a f32 [B, H, S] intermediate between them; this kernel computes
q·K^T → masked softmax → ·V in one pass per batch row, so K and V each
cross HBM exactly once per layer and nothing round-trips in between.

The kernel reads the layer's K/V directly out of the STACKED cache
([L, B, Hkv, S, Dh], the scan carry) via a scalar-prefetched layer index —
slicing the layer out first (`dynamic_index_in_dim`) and handing pallas
the slice costs a 2×[B,Hkv,S,Dh] HBM copy per layer, which measured
SLOWER than the XLA einsum path it was meant to beat.

Scope: decode only (one query token per row). Prefill and training keep
the XLA einsum path — there the query dimension is large, the MXU is busy,
and XLA's tiling is already the right schedule. Grouped-query models pass
kv_heads < num_heads; the kernel indexes the shared KV head directly, so
the repeat_kv materialization is skipped too. Capability parity note: the
reference has no analogue (HF torch `model.generate` on CPU, reference:
GUI_RAFT_LLM_SourceCode/tutoring_server.py:21-29); this file exists purely
to buy TPU headroom.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

NEG_INF = -1e30


def _decode_attn_kernel(l_ref, q_ref, k_ref, v_ref, bias_ref, o_ref, *,
                        num_heads: int, kv_heads: int, scale: float):
    """One batch row: [H, Dh] query against the layer's [Hkv, S, Dh] K/V.

    Heads run as a static loop of 2-D dots — Mosaic does not lower batched
    dot_general, and per-head [1, Dh] x [Dh, S] products keep everything in
    VMEM anyway. Scores and softmax accumulate in f32; the weighted sum
    returns to the cache dtype only at the end.
    """
    del l_ref  # consumed by the BlockSpec index maps
    group = num_heads // kv_heads
    bias = bias_ref[0]  # [1, S] additive mask: 0 or NEG_INF
    for h in range(num_heads):
        qh = q_ref[0, h][None, :]  # [1, Dh]
        sc = jax.lax.dot_general(
            qh, k_ref[0, 0, h // group], (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [1, S]
        sc = sc * scale + bias
        m = jnp.max(sc, axis=-1, keepdims=True)
        p = jnp.exp(sc - m)
        denom = jnp.sum(p, axis=-1, keepdims=True)
        oh = jax.lax.dot_general(
            p.astype(k_ref.dtype), v_ref[0, 0, h // group],
            (((1,), (0,)), ((), ())),
            preferred_element_type=jnp.float32,
        )  # [1, Dh]
        o_ref[0, h] = ((oh / denom)[0]).astype(o_ref.dtype)


def decode_attention(q: jax.Array, k_cache: jax.Array, v_cache: jax.Array,
                     layer: jax.Array, bias: jax.Array) -> jax.Array:
    """Fused decode attention against one layer of the stacked KV cache.

    q        [B, H, 1, Dh] — the decode step's queries
    k_cache  [L, B, Hkv, S, Dh] — the scan-carried stacked cache
    v_cache  [L, B, Hkv, S, Dh]
    layer    [] int32 — which layer's K/V to attend against
    bias     [B, 1, S] f32 — additive mask (0 = attend, NEG_INF = not)
    returns  [B, H, 1, Dh] in q's dtype.

    Gating lives in the engine (`EngineConfig.fused_attention` sets the
    model config's `fused_decode_attention`, unsharded-mesh only); this
    function assumes a TPU backend.
    """
    b, h, t, dh = q.shape
    _, _, hkv, s, _ = k_cache.shape
    assert t == 1, "decode_attention handles one query token per row"
    scale = 1.0 / (dh ** 0.5)

    out = pl.pallas_call(
        functools.partial(
            _decode_attn_kernel, num_heads=h, kv_heads=hkv, scale=scale
        ),
        grid_spec=pltpu.PrefetchScalarGridSpec(
            num_scalar_prefetch=1,
            grid=(b,),
            in_specs=[
                pl.BlockSpec((1, h, dh), lambda i, l: (i, 0, 0)),
                pl.BlockSpec(
                    (1, 1, hkv, s, dh), lambda i, l: (l[0], i, 0, 0, 0)
                ),
                pl.BlockSpec(
                    (1, 1, hkv, s, dh), lambda i, l: (l[0], i, 0, 0, 0)
                ),
                pl.BlockSpec((1, 1, s), lambda i, l: (i, 0, 0)),
            ],
            out_specs=pl.BlockSpec((1, h, dh), lambda i, l: (i, 0, 0)),
        ),
        out_shape=jax.ShapeDtypeStruct((b, h, dh), q.dtype),
    )(layer[None].astype(jnp.int32), q[:, :, 0, :], k_cache, v_cache, bias)
    return out[:, :, None, :]


def mask_to_bias(mask: jax.Array) -> jax.Array:
    """[B, 1, T, S] boolean attend-mask -> [B, 1, S] additive f32 bias
    (layer-invariant: compute once per decode step, outside the layer scan)."""
    return jnp.where(mask[:, 0, 0, :], 0.0, NEG_INF).astype(jnp.float32)[
        :, None, :
    ]
