"""Partition rules: tree-path regex -> PartitionSpec.

Parameters are plain nested dicts, so sharding assignment is a pure function
of the flattened key path — the idiomatic JAX pattern for 1D/2D weight
sharding (cf. public fmengine/EasyLM-style `match_partition_rules`; pattern
reimplemented here for our stacked-layer layout).

Weight layout reminders (models/gpt2.py, models/bert.py, models/llama.py):
per-layer tensors carry a leading layer axis L, linears are [in, out].
Megatron-style TP: column-parallel QKV/FFN-in (shard the out dim),
row-parallel attn-out/FFN-out (shard the in dim) — one psum per block pair,
inserted automatically by XLA from these specs.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

P = PartitionSpec

Rules = Sequence[Tuple[str, PartitionSpec]]

# GPT-2 family (stacked blocks; layer axis first, replicated).
#
# Weight-only int8 (models/quant.py) replaces a dense leaf `X` with the pair
# `X/q` (int8, same shape) and `X/s` (f32 scales): `q` shards exactly like
# the dense leaf; `s` is the leaf's shape minus the contracted `in` axis
# (per-out-channel scales) — so column-parallel leaves shard their scales
# over tp and row-parallel leaves replicate them (the scale applies after
# the tp psum). Embedding tables scale per ROW (quantize_embedding), so
# their `s` is [V], vocab-sharded like `q`'s leading axis.
#
# Spelling: trailing Nones are dropped everywhere (P() not P(None, None),
# P(None, "tp") not P(None, "tp", None)) — PartitionSpec pads with None,
# and one canonical spelling per layout keeps spelling-keyed jit caches
# from silently recompiling (the canonical-pspec lint rule enforces this;
# see engine/paged._plane_spec for the incident).
GPT2_RULES: List[Tuple[str, PartitionSpec]] = [
    (r"wte(/q)?$", P("tp")),       # vocab-sharded embedding
    (r"wte/s$", P("tp")),
    (r"wpe$", P()),
    (r"blocks/attn/wqkv(/q)?$", P(None, None, "tp")),   # column parallel
    (r"blocks/attn/wqkv/s$", P(None, "tp")),
    (r"blocks/attn/bqkv$", P(None, "tp")),
    (r"blocks/attn/wo(/q)?$", P(None, "tp")),     # row parallel
    (r"blocks/attn/wo/s$", P()),
    (r"blocks/attn/bo$", P()),
    (r"blocks/mlp/wi(/q)?$", P(None, None, "tp")),
    (r"blocks/mlp/wi/s$", P(None, "tp")),
    (r"blocks/mlp/bi$", P(None, "tp")),
    (r"blocks/mlp/wo(/q)?$", P(None, "tp")),
    (r"blocks/mlp/wo/s$", P()),
    (r"blocks/mlp/bo$", P()),
    (r"ln|lnf", P()),                    # norms replicated
    (r".*", P()),
]

# Llama family: Megatron TP like GPT-2; q/k/v/gate/up column-parallel,
# o/down row-parallel; untied vocab-sharded embed + lm_head.
LLAMA_RULES: List[Tuple[str, PartitionSpec]] = [
    (r"embed(/q)?$", P("tp")),
    (r"embed/s$", P("tp")),
    (r"lm_head(/q)?$", P("tp")),
    (r"lm_head/s$", P("tp")),
    (r"blocks/attn/w[qkv](/q)?$", P(None, None, "tp")),
    (r"blocks/attn/w[qkv]/s$", P(None, "tp")),
    (r"blocks/attn/wo(/q)?$", P(None, "tp")),
    (r"blocks/attn/wo/s$", P()),
    (r"blocks/mlp/w[gu](/q)?$", P(None, None, "tp")),
    (r"blocks/mlp/w[gu]/s$", P(None, "tp")),
    (r"blocks/mlp/wd(/q)?$", P(None, "tp")),
    (r"blocks/mlp/wd/s$", P()),
    (r"ln|lnf", P()),
    (r".*", P()),
]

BERT_RULES: List[Tuple[str, PartitionSpec]] = [
    (r"embeddings/word(/q)?$", P("tp")),
    (r"embeddings/word/s$", P("tp")),
    (r"embeddings/(position|token_type)$", P()),
    (r"blocks/attn/wqkv(/q)?$", P(None, None, "tp")),
    (r"blocks/attn/wqkv/s$", P(None, "tp")),
    (r"blocks/attn/bqkv$", P(None, "tp")),
    (r"blocks/attn/wo(/q)?$", P(None, "tp")),
    (r"blocks/attn/wo/s$", P()),
    (r"blocks/mlp/wi(/q)?$", P(None, None, "tp")),
    (r"blocks/mlp/wi/s$", P(None, "tp")),
    (r"blocks/mlp/wo(/q)?$", P(None, "tp")),
    (r"blocks/mlp/wo/s$", P()),
    (r".*", P()),
]

# GPT-2-MoE (models/moe.py): the dense trunk shards like GPT-2; the
# expert stacks [L, E, D, M] shard their EXPERT axis over `ep` — under jit
# the dispatch/combine einsums against ep-sharded weights make XLA place
# each expert's FFN on its shard and insert the all-to-alls, exactly as
# the tp specs imply the Megatron psums. The tiny router is replicated.
MOE_RULES: List[Tuple[str, PartitionSpec]] = [
    (r"blocks/moe/wr$", P()),
    (r"blocks/moe/w[io](/q)?$", P(None, "ep")),
    (r"blocks/moe/w[io]/s$", P(None, "ep")),
    (r"blocks/moe/b[io]$", P(None, "ep")),
] + GPT2_RULES

# Rule set per model-family name (models/registry.py ModelFamily.name).
# (The bucketed engine's KV-cache sharding — [L, B, Hkv, T, Dh]: batch
# over dp, heads over tp — is derived by jit's sharding propagation from
# the param/batch specs. The PAGED engine's slot-state planes are the
# exception: they cross program boundaries as explicit host-held arrays,
# so their shardings are pinned by the plane table below instead of
# re-derived per program.)
RULES_FOR = {
    "gpt2": GPT2_RULES,
    "llama": LLAMA_RULES,
    "bert": BERT_RULES,
    "gpt2_moe": MOE_RULES,
}

# ---------------------------------------------- paged state plane table
#
# The paged engine's per-plane sharding policy, keyed by PLANE NAME (the
# attribute chain past the state/cache root — the same key
# `analysis/absint.collect_plane_puts` derives from producer call sites,
# so the `pspec-flow` lint rule can check every producer against this
# table). ONE semantic sharding per named plane across all producers:
# `_init_state`'s birth puts, `_canon_state`'s dispatch-boundary
# respells, `_fresh_prefill_cache`, and the prefix-cache block
# canonicalization all resolve specs HERE and nowhere else.
#
# KV planes shard their heads axis over tp: slot cache k/v are
# [L, S, Hkv, T, Dh] and the int8-KV scale planes ks/vs are
# [L, S, Hkv, T] — heads is axis 2 in both — so one spec spelling,
# P(None, None, "tp"), serves the pair; the prefix tree's immutable
# KVBlock runs ([L, 1, H, B, Dh] / [L, 1, H, B]) share the layout and
# the spec, making a radix hit splice tp-sharded blocks without a
# gather. Host-state planes (positions, masks, transcripts, staged
# cursors, rng keys) are genuinely replicated and keep the canonical
# `P()` spelling — the PR-2 recompile incident's fix, now per plane.
# MoE expert planes are PARAMS (MOE_RULES shards their expert axis over
# ep above); no slot-state plane carries an expert axis, so `ep` does
# not appear here — state planes replicate over ep exactly like dp.
#
# On a tp=1 mesh P(None, None, "tp") degrades to replication (the
# shard_tree doctrine: axes of size 1 are harmless), so one table
# serves every mesh.
PAGED_PLANE_SPECS: Dict[str, PartitionSpec] = {
    # SlotState.cache planes (engine/paged.SlotState).
    "cache.k": P(None, None, "tp"),
    "cache.v": P(None, None, "tp"),
    "cache.ks": P(None, None, "tp"),
    "cache.vs": P(None, None, "tp"),
    "cache.length": P(),
    # Bare KVCache / prefix KVBlock planes (single-slot prefill caches
    # and the radix tree's block runs share the heads-at-axis-2 layout).
    "k": P(None, None, "tp"),
    "v": P(None, None, "tp"),
    "ks": P(None, None, "tp"),
    "vs": P(None, None, "tp"),
    "length": P(),
    # Host-state planes: replicated, canonical spelling.
    "tok": P(),
    "active": P(),
    "seen": P(),
    "transcript": P(),
    "staged": P(),
    "stage_cursor": P(),
    "stage_len": P(),
    "stage_seq": P(),
    "stage_rng": P(),
}


def supported_tp(num_kv_heads: int) -> List[int]:
    """The tp ways that shard `num_kv_heads` KV heads evenly: the
    ascending divisors. The paged plane table splits the heads axis
    across tp shards, so any other way would leave ragged head shards
    (gpt2-large's 20 heads admit [1, 2, 4, 5, 10, 20] — not 8)."""
    return [d for d in range(1, num_kv_heads + 1) if num_kv_heads % d == 0]


def validate_tp_heads(num_kv_heads: int, tp: int, model: str) -> None:
    """Reject a tp that does not divide the KV head count — loudly, with
    the exact supported divisors, instead of padding heads (a padded
    head's KV would cost real HBM and attention bandwidth on every
    shard, the resource tp exists to split)."""
    if tp > 1 and num_kv_heads % tp:
        raise ValueError(
            f"tp={tp} does not divide {model!r}'s {num_kv_heads} KV "
            f"heads; the paged KV planes shard the heads axis evenly — "
            f"supported tp ways for this model: "
            f"{supported_tp(num_kv_heads)}"
        )


def tree_paths(tree: Any) -> List[str]:
    paths, _ = jax.tree_util.tree_flatten_with_path(tree)
    return ["/".join(_key_str(k) for k in kp) for kp, _ in paths]


def _key_str(k) -> str:
    if hasattr(k, "key"):
        return str(k.key)
    if hasattr(k, "idx"):
        return str(k.idx)
    return str(k)


def match_partition_rules(rules: Rules, tree: Any) -> Any:
    """Return a pytree of PartitionSpec matching `tree`'s structure."""

    def spec_for(path: str, leaf) -> PartitionSpec:
        if getattr(leaf, "ndim", 0) == 0:
            return P()
        for pattern, spec in rules:
            if re.search(pattern, path):
                return spec
        raise ValueError(f"no partition rule matched {path!r}")

    paths_and_leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    specs = [
        spec_for("/".join(_key_str(k) for k in kp), leaf)
        for kp, leaf in paths_and_leaves
    ]
    return jax.tree_util.tree_unflatten(treedef, specs)


def shard_tree(tree: Any, mesh: Mesh, rules: Rules) -> Any:
    """Device-put a pytree with NamedShardings derived from the rules.

    Specs naming axes of size 1 are harmless; on a single-device mesh this
    degrades to replication, so the same code path runs on 1 chip or 256.
    """
    specs = match_partition_rules(rules, tree)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, specs
    )


def shardings_for(tree: Any, mesh: Mesh, rules: Rules) -> Any:
    """Pytree of NamedSharding (for jit in_shardings/out_shardings)."""
    specs = match_partition_rules(rules, tree)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, PartitionSpec))
