"""Partition rules: tree-path regex -> PartitionSpec.

Parameters are plain nested dicts, so sharding assignment is a pure function
of the flattened key path — the idiomatic JAX pattern for 1D/2D weight
sharding (cf. public fmengine/EasyLM-style `match_partition_rules`; pattern
reimplemented here for our stacked-layer layout).

Weight layout reminders (models/gpt2.py, models/bert.py, models/llama.py):
per-layer tensors carry a leading layer axis L, linears are [in, out].
Megatron-style TP: column-parallel QKV/FFN-in (shard the out dim),
row-parallel attn-out/FFN-out (shard the in dim) — one psum per block pair,
inserted automatically by XLA from these specs.
"""

from __future__ import annotations

import re
from typing import Any, Dict, List, Sequence, Tuple

import jax
from jax.sharding import Mesh, NamedSharding, PartitionSpec

P = PartitionSpec

Rules = Sequence[Tuple[str, PartitionSpec]]

# GPT-2 family (stacked blocks; layer axis first, replicated).
#
# Weight-only int8 (models/quant.py) replaces a dense leaf `X` with the pair
# `X/q` (int8, same shape) and `X/s` (f32 scales): `q` shards exactly like
# the dense leaf; `s` is the leaf's shape minus the contracted `in` axis
# (per-out-channel scales) — so column-parallel leaves shard their scales
# over tp and row-parallel leaves replicate them (the scale applies after
# the tp psum). Embedding tables scale per ROW (quantize_embedding), so
# their `s` is [V], vocab-sharded like `q`'s leading axis.
#
# Spelling: trailing Nones are dropped everywhere (P() not P(None, None),
# P(None, "tp") not P(None, "tp", None)) — PartitionSpec pads with None,
# and one canonical spelling per layout keeps spelling-keyed jit caches
# from silently recompiling (the canonical-pspec lint rule enforces this;
# see engine/paged._state_spec for the incident).
GPT2_RULES: List[Tuple[str, PartitionSpec]] = [
    (r"wte(/q)?$", P("tp")),       # vocab-sharded embedding
    (r"wte/s$", P("tp")),
    (r"wpe$", P()),
    (r"blocks/attn/wqkv(/q)?$", P(None, None, "tp")),   # column parallel
    (r"blocks/attn/wqkv/s$", P(None, "tp")),
    (r"blocks/attn/bqkv$", P(None, "tp")),
    (r"blocks/attn/wo(/q)?$", P(None, "tp")),     # row parallel
    (r"blocks/attn/wo/s$", P()),
    (r"blocks/attn/bo$", P()),
    (r"blocks/mlp/wi(/q)?$", P(None, None, "tp")),
    (r"blocks/mlp/wi/s$", P(None, "tp")),
    (r"blocks/mlp/bi$", P(None, "tp")),
    (r"blocks/mlp/wo(/q)?$", P(None, "tp")),
    (r"blocks/mlp/wo/s$", P()),
    (r"blocks/mlp/bo$", P()),
    (r"ln|lnf", P()),                    # norms replicated
    (r".*", P()),
]

# Llama family: Megatron TP like GPT-2; q/k/v/gate/up column-parallel,
# o/down row-parallel; untied vocab-sharded embed + lm_head.
LLAMA_RULES: List[Tuple[str, PartitionSpec]] = [
    (r"embed(/q)?$", P("tp")),
    (r"embed/s$", P("tp")),
    (r"lm_head(/q)?$", P("tp")),
    (r"lm_head/s$", P("tp")),
    (r"blocks/attn/w[qkv](/q)?$", P(None, None, "tp")),
    (r"blocks/attn/w[qkv]/s$", P(None, "tp")),
    (r"blocks/attn/wo(/q)?$", P(None, "tp")),
    (r"blocks/attn/wo/s$", P()),
    (r"blocks/mlp/w[gu](/q)?$", P(None, None, "tp")),
    (r"blocks/mlp/w[gu]/s$", P(None, "tp")),
    (r"blocks/mlp/wd(/q)?$", P(None, "tp")),
    (r"blocks/mlp/wd/s$", P()),
    (r"ln|lnf", P()),
    (r".*", P()),
]

BERT_RULES: List[Tuple[str, PartitionSpec]] = [
    (r"embeddings/word(/q)?$", P("tp")),
    (r"embeddings/word/s$", P("tp")),
    (r"embeddings/(position|token_type)$", P()),
    (r"blocks/attn/wqkv(/q)?$", P(None, None, "tp")),
    (r"blocks/attn/wqkv/s$", P(None, "tp")),
    (r"blocks/attn/bqkv$", P(None, "tp")),
    (r"blocks/attn/wo(/q)?$", P(None, "tp")),
    (r"blocks/attn/wo/s$", P()),
    (r"blocks/mlp/wi(/q)?$", P(None, None, "tp")),
    (r"blocks/mlp/wi/s$", P(None, "tp")),
    (r"blocks/mlp/wo(/q)?$", P(None, "tp")),
    (r"blocks/mlp/wo/s$", P()),
    (r".*", P()),
]

# GPT-2-MoE (models/moe.py): the dense trunk shards like GPT-2; the
# expert stacks [L, E, D, M] shard their EXPERT axis over `ep` — under jit
# the dispatch/combine einsums against ep-sharded weights make XLA place
# each expert's FFN on its shard and insert the all-to-alls, exactly as
# the tp specs imply the Megatron psums. The tiny router is replicated.
MOE_RULES: List[Tuple[str, PartitionSpec]] = [
    (r"blocks/moe/wr$", P()),
    (r"blocks/moe/w[io](/q)?$", P(None, "ep")),
    (r"blocks/moe/w[io]/s$", P(None, "ep")),
    (r"blocks/moe/b[io]$", P(None, "ep")),
] + GPT2_RULES

# Rule set per model-family name (models/registry.py ModelFamily.name).
# (KV-cache sharding — [L, B, Hkv, T, Dh]: batch over dp, heads over tp —
# is derived by jit's sharding propagation from the param/batch specs; no
# hand-placed constant needed.)
RULES_FOR = {
    "gpt2": GPT2_RULES,
    "llama": LLAMA_RULES,
    "bert": BERT_RULES,
    "gpt2_moe": MOE_RULES,
}


def tree_paths(tree: Any) -> List[str]:
    paths, _ = jax.tree_util.tree_flatten_with_path(tree)
    return ["/".join(_key_str(k) for k in kp) for kp, _ in paths]


def _key_str(k) -> str:
    if hasattr(k, "key"):
        return str(k.key)
    if hasattr(k, "idx"):
        return str(k.idx)
    return str(k)


def match_partition_rules(rules: Rules, tree: Any) -> Any:
    """Return a pytree of PartitionSpec matching `tree`'s structure."""

    def spec_for(path: str, leaf) -> PartitionSpec:
        if getattr(leaf, "ndim", 0) == 0:
            return P()
        for pattern, spec in rules:
            if re.search(pattern, path):
                return spec
        raise ValueError(f"no partition rule matched {path!r}")

    paths_and_leaves, treedef = jax.tree_util.tree_flatten_with_path(tree)
    specs = [
        spec_for("/".join(_key_str(k) for k in kp), leaf)
        for kp, leaf in paths_and_leaves
    ]
    return jax.tree_util.tree_unflatten(treedef, specs)


def shard_tree(tree: Any, mesh: Mesh, rules: Rules) -> Any:
    """Device-put a pytree with NamedShardings derived from the rules.

    Specs naming axes of size 1 are harmless; on a single-device mesh this
    degrades to replication, so the same code path runs on 1 chip or 256.
    """
    specs = match_partition_rules(rules, tree)
    return jax.tree.map(
        lambda x, s: jax.device_put(x, NamedSharding(mesh, s)), tree, specs
    )


def shardings_for(tree: Any, mesh: Mesh, rules: Rules) -> Any:
    """Pytree of NamedSharding (for jit in_shardings/out_shardings)."""
    specs = match_partition_rules(rules, tree)
    return jax.tree.map(lambda s: NamedSharding(mesh, s), specs,
                        is_leaf=lambda x: isinstance(x, PartitionSpec))
