"""SPMD parallelism: mesh construction, partition rules, ring attention."""

from .mesh import (  # noqa: F401
    initialize_multihost,
    make_hybrid_mesh,
    make_mesh,
    named_sharding,
    single_device_mesh,
)
from .pipeline import pipeline_trunk  # noqa: F401
from .ring import ring_attention  # noqa: F401
from .partition import (  # noqa: F401
    BERT_RULES,
    GPT2_RULES,
    match_partition_rules,
    shard_tree,
    shardings_for,
)
