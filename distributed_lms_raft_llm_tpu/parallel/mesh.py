"""Device-mesh construction for SPMD sharding.

The reference has no compute parallelism (SURVEY.md §2.2) — its distribution
is Raft replication over gRPC. Here the TPU compute plane scales the JAX way:
a `jax.sharding.Mesh` over the local chips with named axes, `NamedSharding`
partition specs on parameter/cache pytrees, and XLA-inserted collectives over
ICI. Axes used across the framework:

- ``dp`` — data parallel (batch of concurrent student queries)
- ``tp`` — tensor parallel (weight shards; the BASELINE GPT-2-large/8-chip
  and Llama-3-8B/16-chip configs)
- ``sp`` — sequence/context parallel (ring attention for long context)
- ``pp`` — pipeline stages (train-time; optional)
"""

from __future__ import annotations

import math
from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec


def make_mesh(
    axis_sizes: Optional[dict] = None,
    *,
    devices: Optional[Sequence[jax.Device]] = None,
    axis_order: Tuple[str, ...] = ("dp", "pp", "sp", "tp"),
) -> Mesh:
    """Build a mesh over the given (default: all local) devices.

    axis_sizes maps axis name -> size; at most one axis may be -1 (inferred).
    Axes not mentioned get size 1. `tp` is placed innermost (fastest-varying)
    so tensor-parallel collectives ride the shortest ICI hops.

    >>> make_mesh({"dp": 2, "tp": 4})  # 8 devices: 2-way data, 4-way tensor
    """
    devices = list(devices if devices is not None else jax.devices())
    n = len(devices)
    sizes = dict(axis_sizes or {})
    unknown = [a for a in sizes if a not in axis_order]
    if unknown:
        raise ValueError(f"unknown mesh axes {unknown}; expected {axis_order}")
    infer = [a for a, s in sizes.items() if s == -1]
    if len(infer) > 1:
        raise ValueError("at most one axis size may be -1")
    known = math.prod(s for s in sizes.values() if s != -1)
    if infer:
        if n % known:
            raise ValueError(f"{n} devices not divisible by {known}")
        sizes[infer[0]] = n // known
    elif known != n:
        # Default: put the remainder on dp if unset, else require exact fit.
        if "dp" not in sizes and n % known == 0:
            sizes["dp"] = n // known
        else:
            raise ValueError(f"axis sizes {sizes} do not multiply to {n} devices")
    shape = [sizes.get(a, 1) for a in axis_order]
    mesh_devices = np.asarray(devices).reshape(shape)
    return Mesh(mesh_devices, axis_order)


def single_device_mesh() -> Mesh:
    """Trivial mesh (1 chip) — lets the same pjit code path serve everywhere."""
    return make_mesh({})


def named_sharding(mesh: Mesh, *spec) -> NamedSharding:
    return NamedSharding(mesh, PartitionSpec(*spec))
